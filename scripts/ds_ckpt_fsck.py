#!/usr/bin/env python
"""Offline checkpoint validator for the durable-commit protocol.

``deepspeed_tpu/runtime/resilience.py`` writes every checkpoint tag as
tmp-dir → manifest (``ds_manifest.json``) → commit marker (``.ds_commit``)
→ fsync → atomic rename.  This tool audits a checkpoint root the same way
the engine's load-time fallback does, without touching a device or
restoring any state — safe to run on a corrupt directory from any machine.

Usage:
    python scripts/ds_ckpt_fsck.py <checkpoint_root> [--json] [--deep]

Reports, per tag: validation status (committed / no_marker / bad_manifest /
partial / legacy), global step, payload file count + bytes, and whether the
``latest`` pointer resolves to a committed tag.  ``--deep`` re-reads every
manifest-listed payload file to catch unreadable blocks, not just wrong
sizes.  Exit code: 0 when ``latest`` (or the newest tag, if no pointer)
is committed; 1 otherwise; 2 on usage errors.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

from deepspeed_tpu.runtime.resilience import (COMMITTED, LEGACY,  # noqa: E402
                                              TMP_SUFFIX, scan_tags,
                                              validate_tag)


def _deep_check(root, tag, manifest):
    """Re-read every manifest-listed payload file; returns problem list."""
    problems = []
    for rec in (manifest or {}).get("files", []):
        path = os.path.join(root, tag, rec["path"])
        try:
            remaining = rec["bytes"]
            with open(path, "rb") as f:
                while remaining > 0:
                    chunk = f.read(min(remaining, 1 << 20))
                    if not chunk:
                        problems.append(f"{rec['path']}: short read")
                        break
                    remaining -= len(chunk)
        except OSError as e:
            problems.append(f"{rec['path']}: {e}")
    return problems


def fsck(root, deep=False):
    """Audit one checkpoint root.  Returns a report dict (also the --json
    payload): per-tag status plus the resolved ``latest`` pointer."""
    tags = []
    for name, status, manifest in scan_tags(root):
        entry = {
            "tag": name,
            "status": status,
            "global_step": (manifest or {}).get("global_step"),
            "files": len((manifest or {}).get("files", [])),
            "bytes": sum(f["bytes"] for f in
                         (manifest or {}).get("files", [])),
        }
        if deep and status == COMMITTED:
            problems = _deep_check(root, name, manifest)
            if problems:
                entry["status"] = "unreadable"
                entry["problems"] = problems
        tags.append(entry)
    stale_tmp = sorted(
        n for n in (os.listdir(root) if os.path.isdir(root) else [])
        if n.startswith(".") and n.endswith(TMP_SUFFIX))
    latest_tag = None
    latest_path = os.path.join(root, "latest")
    if os.path.exists(latest_path):
        with open(latest_path) as f:
            latest_tag = f.read().strip()
    by_tag = {t["tag"]: t for t in tags}
    if latest_tag is not None:
        latest_status = by_tag.get(latest_tag, {}).get("status",
                                                       "missing")
    else:
        latest_status = tags[0]["status"] if tags else "missing"
    return {
        "root": os.path.abspath(root),
        "tags": tags,
        "stale_tmp_dirs": stale_tmp,
        "latest": latest_tag,
        "latest_status": latest_status,
        "ok": latest_status in (COMMITTED, LEGACY),
    }


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="validate DeepSpeed-TPU checkpoint tags offline")
    parser.add_argument("root", help="checkpoint directory (contains tags)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable report on stdout")
    parser.add_argument("--deep", action="store_true",
                        help="re-read every payload file, not just sizes")
    args = parser.parse_args(argv)
    if not os.path.isdir(args.root):
        print(f"error: {args.root} is not a directory", file=sys.stderr)
        return 2
    report = fsck(args.root, deep=args.deep)
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(f"checkpoint root: {report['root']}")
        for t in report["tags"]:
            step = t["global_step"]
            step_s = f"step {step}" if step is not None else "step ?"
            print(f"  {t['tag']:<32} {t['status']:<13} {step_s:<12} "
                  f"{t['files']} file(s), {t['bytes']} byte(s)")
            for p in t.get("problems", []):
                print(f"      ! {p}")
        for n in report["stale_tmp_dirs"]:
            print(f"  {n:<32} stale-tmp (crashed/aborted save)")
        if report["latest"] is not None:
            print(f"latest -> {report['latest']} ({report['latest_status']})")
        else:
            print("no 'latest' pointer")
        print("OK" if report["ok"] else "NOT OK: newest checkpoint is not "
              "committed — the engine will fall back at load time")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
