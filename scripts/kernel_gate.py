#!/usr/bin/env python
"""Shim: the Mosaic compile-gate lives in the package
(``deepspeed_tpu/ops/kernel_gate.py``) so ``ds_report --kernel-gate``
works from an installed package too; this path is kept for the on-chip
programs' documented invocation."""
import os
import sys

_repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if os.path.isdir(os.path.join(_repo, "deepspeed_tpu")) \
        and _repo not in sys.path:
    sys.path.insert(0, _repo)

from deepspeed_tpu.ops.kernel_gate import main

if __name__ == "__main__":
    raise SystemExit(main())
