#!/usr/bin/env python
"""Probe the TPU tunnel until it answers, then fire the on-chip program.

The tunnel dies for hours at a time (round-2 lost its whole on-chip
window to an outage; this session watched a 30-minute near-OOM compile
wedge it).  This watcher converts recovery into artifacts with no human
in the loop:

    nohup python scripts/tunnel_watcher.py --steps serving,bench &

Each probe is a subprocess with a hard timeout (the axon backend hangs
forever rather than failing).  On the first healthy probe it runs
``scripts/onchip_r05.py --only <steps>`` and exits.
"""

import argparse
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def probe(timeout_s: int) -> bool:
    code = ("import jax; d = jax.devices()[0]; "
            "import jax.numpy as jnp; "
            "x = jnp.ones((128, 128), jnp.bfloat16); "
            "print(float((x @ x).sum()), d.platform)")
    try:
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             timeout=timeout_s, cwd=REPO)
    except subprocess.TimeoutExpired:
        return False
    return out.returncode == 0 and "tpu" in (out.stdout or "").lower()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", default="",
                    help="comma list forwarded to onchip_r05.py --only (empty = all steps, priority order)")
    ap.add_argument("--interval", type=int, default=300)
    ap.add_argument("--probe-timeout", type=int, default=150)
    ap.add_argument("--max-hours", type=float, default=10.0)
    args = ap.parse_args()

    deadline = time.time() + args.max_hours * 3600
    attempt = 0
    while time.time() < deadline:
        attempt += 1
        ok = probe(args.probe_timeout)
        print(f"[watcher] probe {attempt}: {'UP' if ok else 'down'}",
              flush=True)
        if ok:
            rc = subprocess.call(
                [sys.executable, "scripts/onchip_r05.py",
                 "--only", args.steps], cwd=REPO)
            print(f"[watcher] onchip program exited rc={rc}", flush=True)
            return rc
        time.sleep(args.interval)
    print("[watcher] gave up: tunnel never recovered", flush=True)
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
