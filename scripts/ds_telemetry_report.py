#!/usr/bin/env python
"""Aggregate a unified-telemetry JSONL stream into human-readable tables.

Reads the ``events.jsonl`` (plus rotated ``events.jsonl.N`` generations,
oldest first) written by ``deepspeed_tpu/monitor/telemetry.py`` — or, for
a distributed run, every per-rank shard ``events.rank{N}.jsonl`` in the
directory — and prints:

* per-span latency percentiles (count / mean / p50 / p90 / p99 / max),
* comm census per op: traced calls, total bytes, summed duration, and
  achieved bandwidth (timed bytes / timed duration) for timed records,
* gauge last/peak table (HBM bytes-in-use, tokens/s, MFU, loss, ...),
* heartbeat summary (steps seen, median step time) and any stall events,
* with >= 2 rank shards: a per-rank cluster table (steps, median step
  time) and the cross-rank step-time skew.

Usage:
    python scripts/ds_telemetry_report.py <telemetry_dir_or_events.jsonl>
    python scripts/ds_telemetry_report.py --json run/telemetry/MyJob
"""

import argparse
import glob
import json
import os
import sys


def _with_rotations(live):
    """[oldest rotated .N .. live] for one stream file."""
    rotated = sorted(
        glob.glob(live + ".*"),
        key=lambda p: int(p.rsplit(".", 1)[1])
        if p.rsplit(".", 1)[1].isdigit() else 0,
        reverse=True)
    files = [p for p in rotated if p.rsplit(".", 1)[1].isdigit()]
    if os.path.exists(live):
        files.append(live)
    return files


def discover_files(target):
    """Stream files for a path that may be a dir, the live file, or a
    glob; ordered oldest -> newest per stream so replay is in time order.
    A directory holding per-rank shards (``events.rank{N}.jsonl``,
    distributed telemetry) yields every shard; records carry their rank
    stamp so the merged replay keeps attribution."""
    if os.path.isdir(target):
        shards = sorted(
            p for p in glob.glob(os.path.join(target, "events.rank*.jsonl"))
            if p.rsplit("rank", 1)[1].split(".")[0].isdigit())
        if shards:
            files = []
            for live in shards:
                files.extend(_with_rotations(live))
            return files
        live = os.path.join(target, "events.jsonl")
    else:
        live = target
    return _with_rotations(live)


def load_events(files):
    for path in files:
        try:
            f = open(path)
        except OSError as e:
            print(f"WARN: skipping unreadable {path}: {e}",
                  file=sys.stderr)
            continue
        with f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    yield json.loads(line)
                except ValueError:
                    continue  # torn tail line from a live writer


def _pct(sorted_vals, q):
    n = len(sorted_vals)
    return sorted_vals[min(n - 1, max(0, int(round(q / 100.0 * (n - 1)))))]


def aggregate(events):
    spans = {}       # name -> [dur_ms]
    comms = {}       # op -> {calls, bytes, axes}
    gauges = {}      # name -> {last, peak, n}
    heartbeats = []  # step_ms values
    rank_steps = {}  # rank -> {step: step_ms} (distributed shards)
    steps = set()
    stalls = []
    metas = []
    serves = {}      # event name -> {count, reasons: {reason: n}}
    fleets = {}      # fleet event name -> {count, reasons, replicas}
    fleet_roles = {} # replica id -> role (disaggregated fleets)
    requests = []    # reconstructed serve/request/* lifecycle traces
    open_reqs = {}   # req_id -> index into requests (trace not yet closed)
    closed_reqs = {} # req_id -> last closed trace index (attr attaches here)
    compiles = {"sites": {}, "storms": 0, "total_misses": 0}
    tunes = {"trials": {}, "pruned": {}, "overlay": None}
    for ev in events:
        kind = ev.get("kind")
        if kind == "span":
            spans.setdefault(ev["name"], []).append(float(ev["dur_ms"]))
        elif kind == "comm":
            rec = comms.setdefault(ev["name"],
                                   {"calls": 0, "bytes": 0, "axes": set(),
                                    "dur_ms": 0.0, "timed_calls": 0,
                                    "timed_bytes": 0})
            rec["calls"] += 1
            rec["bytes"] += int(ev["bytes"])
            rec["axes"].add(ev.get("axis", "?"))
            # timed records (comm tracing): achieved bandwidth is the
            # summed timed payload over the summed duration
            if ev.get("dur_ms"):
                rec["dur_ms"] += float(ev["dur_ms"])
                rec["timed_calls"] += 1
                rec["timed_bytes"] += int(ev["bytes"])
        elif kind == "gauge":
            g = gauges.setdefault(ev["name"],
                                  {"last": None, "peak": None, "n": 0})
            g["last"] = ev["value"]
            g["peak"] = ev.get("peak", ev["value"])
            g["n"] += 1
        elif kind == "heartbeat":
            steps.add(ev.get("step"))
            if ev.get("step_ms") is not None:
                heartbeats.append(float(ev["step_ms"]))
            # distributed shards stamp each record; single-rank -> rank 0
            rs = rank_steps.setdefault(int(ev.get("rank", 0)), {})
            if ev.get("step") is not None:
                rs[int(ev["step"])] = (ev.get("step_ms")
                                       if ev.get("step_ms") is not None
                                       else rs.get(int(ev["step"])))
        elif kind == "compile":
            # profiling plane (monitor/profiling.py): per-site recompile
            # census + storm count for the compile-tracing table
            if ev.get("name") == "compile/storm":
                compiles["storms"] += 1
            else:
                rec = compiles["sites"].setdefault(
                    ev.get("site", "?"),
                    {"misses": 0, "dur_ms": 0.0, "causes": {}})
                rec["misses"] += 1
                rec["dur_ms"] += float(ev.get("dur_ms") or 0.0)
                cause = ev.get("cause")
                if cause:
                    rec["causes"][cause] = rec["causes"].get(cause, 0) + 1
                compiles["total_misses"] += 1
        elif kind == "stall":
            stalls.append(ev)
        elif kind == "meta":
            metas.append(ev)
        elif kind == "fleet":
            rec = fleets.setdefault(ev["name"], {"count": 0, "reasons": {},
                                                 "replicas": set()})
            rec["count"] += 1
            attrs = ev.get("attrs") or {}
            reason = attrs.get("reason")
            if reason:
                rec["reasons"][reason] = rec["reasons"].get(reason, 0) + 1
            replica = attrs.get("replica")
            if replica:
                rec["replicas"].add(str(replica))
            # disaggregated fleets: spawn/respawn stamp each replica's
            # role; migrate_commit carries the page-transfer ledger and
            # migrate_fault its injector site
            role = attrs.get("role")
            if role and replica:
                fleet_roles[str(replica)] = str(role)
            if ev["name"] == "fleet/migrate_commit":
                for k in ("pages", "skipped", "bytes", "bytes_saved",
                          "quant_bytes_saved"):
                    rec[k] = rec.get(k, 0) + int(attrs.get(k) or 0)
            elif ev["name"] == "fleet/migrate_fault":
                site = attrs.get("site")
                if site:
                    sites = rec.setdefault("sites", {})
                    sites[site] = sites.get(site, 0) + 1
            # transport plane (fleet/retry, breaker transitions,
            # dup_call_dropped): per-op retry counts + elapsed-at-retry
            # samples for the timeout percentiles, breaker open/close
            # per replica, and the dedup drop census by op+kind
            elif ev["name"] == "fleet/retry":
                op = str(attrs.get("op") or "?")
                ops = rec.setdefault("ops", {})
                ops[op] = ops.get(op, 0) + 1
                if attrs.get("elapsed_s") is not None:
                    rec.setdefault("elapsed_s", []).append(
                        float(attrs["elapsed_s"]))
            elif ev["name"] in ("fleet/breaker_open",
                                "fleet/breaker_close"):
                if replica:
                    per = rec.setdefault("per_replica", {})
                    per[str(replica)] = per.get(str(replica), 0) + 1
            elif ev["name"] == "fleet/dup_call_dropped":
                op = str(attrs.get("op") or "?")
                kind_ = str(attrs.get("kind") or "?")
                drops = rec.setdefault("drops", {})
                drops[(op, kind_)] = drops.get((op, kind_), 0) + 1
        elif kind == "tune":
            # closed-loop autotuner stream (frozen tune/* vocabulary):
            # trial_start stamps the knob point, trial_result the
            # snapshot-scored objective, trial_pruned the memory-model
            # verdict, overlay_written the persisted winner
            attrs = ev.get("attrs") or {}
            trial = attrs.get("trial")
            if ev["name"] == "tune/trial_start":
                rec = tunes["trials"].setdefault(trial, {})
                rec["knobs"] = attrs.get("knobs")
            elif ev["name"] == "tune/trial_result":
                rec = tunes["trials"].setdefault(trial, {})
                rec["objective"] = attrs.get("objective")
                rec["snapshot_hash"] = attrs.get("snapshot_hash")
                try:
                    rec["metrics"] = json.loads(attrs.get("metrics")
                                                or "{}")
                except ValueError:
                    rec["metrics"] = {}
            elif ev["name"] == "tune/trial_pruned":
                tunes["pruned"][trial] = {"reason": attrs.get("reason"),
                                          "knobs": attrs.get("knobs")}
            elif ev["name"] == "tune/overlay_written":
                tunes["overlay"] = {"trial": trial,
                                    "path": attrs.get("path"),
                                    "snapshot_hash":
                                        attrs.get("snapshot_hash")}
        elif kind == "serve":
            rec = serves.setdefault(ev["name"], {"count": 0, "reasons": {}})
            rec["count"] += 1
            attrs = ev.get("attrs") or {}
            reason = attrs.get("reason")
            if reason:
                rec["reasons"][reason] = rec["reasons"].get(reason, 0) + 1
            # prefix-cache events carry their numbers in attrs — sum them
            # so the report can print the reuse digest without the engine
            if ev["name"] == "serve/prefix_hit":
                rec["pages_reused"] = rec.get("pages_reused", 0) + \
                    int(attrs.get("pages_reused", 0))
                rec["tokens_reused"] = rec.get("tokens_reused", 0) + \
                    int(attrs.get("tokens_reused", 0))
            elif ev["name"] == "serve/prefix_insert":
                rec["pages"] = rec.get("pages", 0) + \
                    int(attrs.get("pages", 0))
            elif ev["name"] == "serve/backend":
                rec["backend"] = attrs.get("attention_backend", "?")
            # scheduler-plane events: the chunked/speculative policies
            # stamp their work on attrs — sum them here so the report can
            # print chunks-per-prefill / acceptance without the engine
            elif ev["name"] == "serve/sched":
                rec["policy"] = attrs.get("policy", "?")
                rec["attrs"] = dict(attrs)
            elif ev["name"] == "serve/prefill_chunk":
                rec["tokens"] = rec.get("tokens", 0) + \
                    int(attrs.get("tokens", 0))
                by_req = rec.setdefault("by_req", {})
                rid = attrs.get("req_id")
                by_req[rid] = by_req.get(rid, 0) + 1
            elif ev["name"] == "serve/spec_draft":
                rec["slots"] = rec.get("slots", 0) + \
                    int(attrs.get("slots", 0))
            elif ev["name"] == "serve/spec_verify":
                rec["accepted"] = rec.get("accepted", 0) + \
                    int(attrs.get("accepted", 0))
                rec["rejected"] = rec.get("rejected", 0) + \
                    int(attrs.get("rejected", 0))
            elif ev["name"].startswith("serve/request/"):
                # rebuild per-request lifecycle traces from the stream;
                # req_ids may recur across runs in one file, so a fresh
                # "admitted" after a terminal opens a NEW trace
                stage = ev["name"].rsplit("/", 1)[1]
                rid = attrs.get("req_id")
                if stage == "attr":
                    # critical-path attribution (emitted adjacent to the
                    # terminal): total per-stage milliseconds for the
                    # attribution digest and pin the breakdown onto the
                    # just-closed trace
                    for k in ("queue_ms", "prefill_ms", "migrate_ms",
                              "gap_ms", "decode_ms", "e2e_ms"):
                        if attrs.get(k) is not None:
                            rec[k] = rec.get(k, 0.0) + float(attrs[k])
                    rec["migrated"] = rec.get("migrated", 0) + \
                        int(attrs.get("migrated") or 0)
                    idx = closed_reqs.get(rid)
                    if idx is not None:
                        requests[idx]["attr"] = {
                            k: attrs[k] for k in
                            ("queue_ms", "prefill_ms", "migrate_ms",
                             "gap_ms", "decode_ms", "e2e_ms", "path")
                            if attrs.get(k) is not None}
                    continue
                if stage == "admitted":
                    open_reqs[rid] = len(requests)
                    requests.append({"req_id": rid, "t_admit": ev["ts"],
                                     "prompt_tokens":
                                         attrs.get("prompt_tokens"),
                                     "deadline": attrs.get("deadline", 0),
                                     "slo_class": attrs.get("slo_class"),
                                     "terminal": None})
                    continue
                idx = open_reqs.get(rid)
                if idx is None:
                    continue    # trace head rotated away
                trace = requests[idx]
                if stage == "prefill_start":
                    trace["slot"] = attrs.get("slot")
                    trace["queue_wait_ms"] = attrs.get("queue_wait_ms")
                elif stage == "first_token":
                    trace["ttft_ms"] = attrs.get("ttft_ms")
                else:           # finish | shed | deadline | evict
                    trace["terminal"] = stage
                    for k in ("reason", "n_generated", "slot", "slo",
                              "queue_wait_ms", "ttft_ms", "tpot_ms",
                              "e2e_ms"):
                        if attrs.get(k) is not None:
                            trace[k] = attrs[k]
                    closed_reqs[rid] = idx
                    del open_reqs[rid]
    return {"spans": spans, "comms": comms, "gauges": gauges,
            "heartbeats": heartbeats, "rank_steps": rank_steps,
            "steps": steps, "stalls": stalls,
            "metas": metas, "serves": serves, "fleets": fleets,
            "fleet_roles": fleet_roles, "tunes": tunes,
            "requests": requests, "compiles": compiles}


def summarize(agg):
    """JSON-friendly summary of an aggregate()."""
    span_rows = {}
    for name, durs in sorted(agg["spans"].items()):
        vals = sorted(durs)
        span_rows[name] = {
            "count": len(vals),
            "mean_ms": round(sum(vals) / len(vals), 3),
            "p50_ms": round(_pct(vals, 50), 3),
            "p90_ms": round(_pct(vals, 90), 3),
            "p99_ms": round(_pct(vals, 99), 3),
            "max_ms": round(vals[-1], 3),
        }
    comm_rows = {}
    for op, rec in sorted(agg["comms"].items()):
        row = {"calls": rec["calls"], "bytes": rec["bytes"],
               "axes": sorted(rec["axes"]),
               "dur_ms": round(rec.get("dur_ms", 0.0), 3),
               "timed_calls": rec.get("timed_calls", 0)}
        dur, tb = rec.get("dur_ms", 0.0), rec.get("timed_bytes", 0)
        row["achieved_gbps"] = (round(tb / (dur / 1e3) / 1e9, 4)
                                if dur > 0 and tb else None)
        comm_rows[op] = row
    gauge_rows = {
        name: {"last": g["last"], "peak": g["peak"], "samples": g["n"]}
        for name, g in sorted(agg["gauges"].items())}
    hb = sorted(agg["heartbeats"])
    heartbeat = {"steps": len(agg["steps"]),
                 "median_step_ms": round(_pct(hb, 50), 3) if hb else None}
    serve_rows = {
        name: {"count": rec["count"],
               "reasons": dict(sorted(rec["reasons"].items()))}
        for name, rec in sorted(agg.get("serves", {}).items())}
    fleet_rows = {
        name: {"count": rec["count"],
               "reasons": dict(sorted(rec["reasons"].items())),
               "replicas": sorted(rec["replicas"])}
        for name, rec in sorted(agg.get("fleets", {}).items())}
    for name, rec in agg.get("fleets", {}).items():
        # migration ledger columns ride the per-event rows too
        for k in ("pages", "skipped", "bytes", "bytes_saved",
                  "quant_bytes_saved", "sites"):
            if k in rec:
                fleet_rows[name][k] = rec[k]
    return {"spans": span_rows, "comms": comm_rows, "gauges": gauge_rows,
            "heartbeat": heartbeat,
            "profiling": _profiling_summary(agg),
            "attribution": _attribution_summary(agg),
            "overlap": _overlap_summary(agg),
            "tiered": _tiered_summary(agg),
            "cluster": _cluster_summary(agg),
            "input_feed": _input_feed_summary(agg),
            "serving": serve_rows,
            "fleet": fleet_rows,
            "fleet_transport": _transport_summary(agg),
            "fleet_disagg": _disagg_summary(agg),
            "autotuning": _autotuning_summary(agg),
            "serving_attention": _serving_attention_summary(agg),
            "scheduler": _scheduler_summary(agg),
            "prefix_cache": _prefix_cache_summary(agg),
            "request_latency": _request_latency_summary(agg),
            "stalls": [{k: v for k, v in s.items() if k != "kind"}
                       for s in agg["stalls"]]}


def _transport_summary(agg):
    """Fleet wire-layer digest from the frozen transport events
    (``fleet/retry``, ``fleet/breaker_open|close``,
    ``fleet/dup_call_dropped``): retry counts by op with the
    elapsed-at-retry percentiles (a proxy for the call-timeout tail),
    breaker transitions per replica, and the duplicate-call drop census
    by op and kind (``stale_resp`` = late reply discarded by call id,
    ``ikey_replay`` = worker-side idempotency dedup).  None when the
    stream carries no transport events."""
    fleets = agg.get("fleets") or {}
    retry = fleets.get("fleet/retry") or {}
    opens = fleets.get("fleet/breaker_open") or {}
    closes = fleets.get("fleet/breaker_close") or {}
    drops = fleets.get("fleet/dup_call_dropped") or {}
    if not (retry or opens or closes or drops):
        return None
    elapsed = sorted(retry.get("elapsed_s") or [])
    breakers = {}
    for name, rec in (("opens", opens), ("closes", closes)):
        for rid, n in (rec.get("per_replica") or {}).items():
            breakers.setdefault(rid, {"opens": 0, "closes": 0})[name] = n
    return {
        "retries": retry.get("count", 0),
        "retries_by_op": dict(sorted((retry.get("ops") or {}).items())),
        "retry_elapsed_p50_s": (round(_pct(elapsed, 50), 4)
                                if elapsed else None),
        "retry_elapsed_p99_s": (round(_pct(elapsed, 99), 4)
                                if elapsed else None),
        "breaker_opens": opens.get("count", 0),
        "breaker_closes": closes.get("count", 0),
        "breakers": dict(sorted(breakers.items())),
        "dup_calls_dropped": drops.get("count", 0),
        "drops_by_op": {f"{op}:{kind}": n for (op, kind), n in
                        sorted((drops.get("drops") or {}).items())},
    }


def _autotuning_summary(agg):
    """Closed-loop autotuner digest from the frozen ``tune/*`` stream:
    trials run/pruned with their knob points, the snapshot-scored
    objective per trial, the winning overlay's knobs and provenance,
    and the BENCH_LEDGER rows the trial runner appended (one per scored
    metric plus the objective row).  None when the stream carries no
    tune events."""
    tunes = agg.get("tunes") or {}
    trials, pruned = tunes.get("trials") or {}, tunes.get("pruned") or {}
    if not trials and not pruned and not tunes.get("overlay"):
        return None

    def _knobs(raw):
        if isinstance(raw, str):
            try:
                return json.loads(raw)
            except ValueError:
                return raw
        return raw

    rows = []
    for tid, rec in sorted(trials.items(), key=lambda kv: str(kv[0])):
        metrics = rec.get("metrics") or {}
        rows.append({"trial": tid, "knobs": _knobs(rec.get("knobs")),
                     "objective": rec.get("objective"),
                     "snapshot_hash": rec.get("snapshot_hash"),
                     "ledger_rows": len(metrics) + 1 if metrics else 0})
    pruned_rows = [
        {"trial": tid, "reason": rec.get("reason"),
         "knobs": _knobs(rec.get("knobs"))}
        for tid, rec in sorted(pruned.items(), key=lambda kv: str(kv[0]))]
    overlay = tunes.get("overlay")
    winner = None
    if overlay:
        winner = {"trial": overlay.get("trial")}
        for r in rows:
            if r["trial"] == overlay.get("trial"):
                winner.update(knobs=r["knobs"], objective=r["objective"])
    return {"trials_run": len(rows), "trials_pruned": len(pruned_rows),
            "trials": rows, "pruned": pruned_rows, "overlay": overlay,
            "winner": winner,
            "ledger_rows_written": sum(r["ledger_rows"] for r in rows)}


def _disagg_summary(agg):
    """Disaggregated-fleet digest: the per-role replica census (from
    role-stamped spawn/respawn events), per-pool queue-depth gauges, and
    the migration ledger summed from the frozen ``fleet/migrate_*``
    stream.  None when the run never stamped a non-unified role."""
    roles = agg.get("fleet_roles") or {}
    if not (set(roles.values()) - {"unified"}):
        return None
    fleets = agg.get("fleets", {})
    gauges = agg.get("gauges", {})

    def _gauge(name):
        g = gauges.get(name)
        return g["last"] if g else None

    by_role = {}
    for rid, role in sorted(roles.items()):
        by_role.setdefault(role, []).append(rid)
    commit = fleets.get("fleet/migrate_commit", {})
    return {
        "roles": {role: sorted(rids)
                  for role, rids in sorted(by_role.items())},
        "queue_depth": {role: _gauge(f"fleet/{role}_queue_depth")
                        for role in sorted(by_role)},
        "migrations": commit.get("count", 0),
        "migrated_pages": commit.get("pages", 0),
        "dedup_skipped_pages": commit.get("skipped", 0),
        "migrate_bytes": commit.get("bytes", 0),
        "bytes_saved": commit.get("bytes_saved", 0),
        "quant_bytes_saved": commit.get("quant_bytes_saved", 0),
        "faults": dict(sorted(fleets.get("fleet/migrate_fault", {})
                              .get("sites", {}).items())),
        "aborts": dict(sorted(fleets.get("fleet/migrate_abort", {})
                              .get("reasons", {}).items())),
        "local_prefills": fleets.get("fleet/local_prefill",
                                     {}).get("count", 0),
    }


def _profiling_summary(agg):
    """Profiling-plane digest (monitor/profiling.py): the per-site
    recompile census, per-span memory attribution from the
    ``mem/<span>/<metric>`` gauges, and the live roofline fractions from
    ``roofline/<span>/<metric>``.  None when the stream carries no
    profiling records at all (plane off)."""
    comp = agg.get("compiles") or {"sites": {}, "storms": 0,
                                   "total_misses": 0}
    mem, roofline = {}, {}
    for name, g in agg["gauges"].items():
        parts = name.split("/")
        if len(parts) != 3:
            continue
        family = {"mem": mem, "roofline": roofline}.get(parts[0])
        if family is not None:
            family.setdefault(parts[1], {})[parts[2]] = {
                "last": g["last"], "peak": g["peak"]}
    if not (comp["total_misses"] or comp["storms"] or mem or roofline):
        return None
    sites = {site: {"misses": rec["misses"],
                    "dur_ms": round(rec["dur_ms"], 3),
                    "causes": dict(sorted(rec["causes"].items()))}
             for site, rec in sorted(comp["sites"].items())}
    return {"compile": {"total_misses": comp["total_misses"],
                        "storms": comp["storms"], "sites": sites},
            "mem": mem, "roofline": roofline}


def _attribution_summary(agg):
    """Attribution-plane digest (monitor/attribution.py): the training
    step decomposition from the frozen ``step/attr/*`` gauges — the same
    numbers the roofline tables sit next to — and the serving
    critical-path stage totals summed over every ``serve/request/attr``
    event.  None when the stream carries neither."""
    step = {name.rsplit("/", 1)[1]: {"last": g["last"], "peak": g["peak"]}
            for name, g in sorted(agg["gauges"].items())
            if name.startswith("step/attr/")}
    attr = agg.get("serves", {}).get("serve/request/attr", {})
    serving = None
    if attr.get("count"):
        e2e = attr.get("e2e_ms", 0.0)
        stages = {}
        for k in ("queue_ms", "prefill_ms", "migrate_ms", "gap_ms",
                  "decode_ms"):
            ms = attr.get(k, 0.0)
            stages[k] = {"total_ms": round(ms, 3),
                         "frac": round(ms / e2e, 4) if e2e else None}
        serving = {"requests": attr["count"],
                   "migrated": attr.get("migrated", 0),
                   "e2e_ms": round(e2e, 3), "stages": stages}
    if not step and not serving:
        return None
    return {"step": step or None, "serving": serving}


def _overlap_summary(agg):
    """Comm/compute-overlap digest (runtime/zero/stage_plan.py): the
    frozen ``comm/overlap/*`` gauges the engine emits when
    ``zero_optimization.overlap.enabled`` — exposed vs overlapped comm
    time per step, the gather/reduce-scatter bucket census, and the
    configured prefetch depth — plus the exposed-comm fraction the
    overlap is meant to drive down.  None when the run never overlapped."""
    rows = {name.rsplit("/", 1)[1]: {"last": g["last"], "peak": g["peak"]}
            for name, g in sorted(agg["gauges"].items())
            if name.startswith("comm/overlap/")}
    if not rows:
        return None
    frac = agg["gauges"].get("step/attr/exposed_comm_frac")
    return {"gauges": rows,
            "exposed_comm_frac": frac["last"] if frac else None}


def _tiered_summary(agg):
    """Tiered-memory-engine digest (runtime/tiered_store.py): the frozen
    ``tier/*`` gauges — occupancy per tier, prefetch hit rate, transfer
    bandwidths, eviction/writeback counts, int8-tier savings.  None when
    the run never touched a tiered store."""
    rows = {name.split("/", 1)[1]: {"last": g["last"], "peak": g["peak"]}
            for name, g in sorted(agg["gauges"].items())
            if name.startswith("tier/")}
    if not rows:
        return None
    hits = (rows.get("prefetch_hits") or {}).get("last") or 0
    misses = (rows.get("prefetch_misses") or {}).get("last") or 0
    return {"gauges": rows,
            "prefetch_hit_rate": (round(hits / (hits + misses), 4)
                                  if hits + misses else None)}


def _cluster_summary(agg):
    """Cross-rank digest from the rank stamps on heartbeat records: one
    row per rank (steps seen, median step time) plus step-time skew over
    the aligned steps (step numbers every rank reported).  None for
    single-rank streams — the table only means something when >= 2 shards
    were merged."""
    rank_steps = agg.get("rank_steps") or {}
    if len(rank_steps) < 2:
        return None
    ranks = sorted(rank_steps)
    per_rank = {}
    for r in ranks:
        ms = sorted(float(v) for v in rank_steps[r].values()
                    if v is not None)
        per_rank[str(r)] = {
            "steps": len(rank_steps[r]),
            "median_step_ms": round(_pct(ms, 50), 3) if ms else None,
        }
    aligned = sorted(set.intersection(
        *(set(s) for s in rank_steps.values())))
    spreads = []
    for step in aligned:
        ms = [float(rank_steps[r][step]) for r in ranks
              if rank_steps[r].get(step) is not None]
        if len(ms) >= 2:
            spreads.append(max(ms) - min(ms))
    spreads.sort()
    medians = sorted(v["median_step_ms"] for v in per_rank.values()
                     if v["median_step_ms"] is not None)
    return {
        "ranks": len(ranks),
        "aligned_steps": len(aligned),
        "per_rank": per_rank,
        "step_skew_ms": {
            "p50": round(_pct(spreads, 50), 3) if spreads else None,
            "max": round(spreads[-1], 3) if spreads else None,
        },
        # the slowest rank relative to the median-of-medians: the same
        # ratio the live aggregator's straggler verdict thresholds on
        "worst_rel": (round(medians[-1] / _pct(medians, 50), 4)
                      if medians and _pct(medians, 50) else None),
    }


# how many individual request rows the latency table prints (slowest by
# e2e first); the percentile block always covers EVERY reconstructed trace
MAX_REQUEST_ROWS = 20


def _request_latency_summary(agg):
    """Per-request latency digest from the reconstructed
    ``serve/request/*`` traces: terminal counts + trace-completeness
    (orphans = admitted with no terminal — a live engine mid-run, or a
    trace leak), SLO attainment, p50/p90/p99 for every derived latency,
    and the slowest individual requests."""
    traces = agg.get("requests") or []
    if not traces:
        return None
    terminals = {}
    slo = {"ok": 0, "miss": 0}
    dists = {"queue_wait_ms": [], "ttft_ms": [], "tpot_ms": [],
             "e2e_ms": []}
    for t in traces:
        term = t.get("terminal")
        terminals[term or "open"] = terminals.get(term or "open", 0) + 1
        if t.get("slo") in slo:
            slo[t["slo"]] += 1
        for k, vals in dists.items():
            if t.get(k) is not None:
                vals.append(float(t[k]))
    pct_rows = {}
    for k, vals in dists.items():
        if not vals:
            continue
        vals = sorted(vals)
        pct_rows[k] = {"count": len(vals),
                       "p50": round(_pct(vals, 50), 3),
                       "p90": round(_pct(vals, 90), 3),
                       "p99": round(_pct(vals, 99), 3),
                       "max": round(vals[-1], 3)}
    closed = [t for t in traces if t.get("terminal")]
    slowest = sorted(closed, key=lambda t: t.get("e2e_ms") or -1.0,
                     reverse=True)[:MAX_REQUEST_ROWS]
    return {
        "traces": len(traces),
        "terminals": dict(sorted(terminals.items())),
        "orphans": terminals.get("open", 0),
        "slo": slo,
        "latency": pct_rows,
        "slowest": [{k: t.get(k) for k in
                     ("req_id", "terminal", "reason", "slot",
                      "n_generated", "queue_wait_ms", "ttft_ms",
                      "tpot_ms", "e2e_ms", "slo") if t.get(k) is not None}
                    for t in slowest],
    }


def _serving_attention_summary(agg):
    """Attention-backend digest: which kernel path served the stream
    (``serve/backend`` event) and attention's share of serve-step time —
    the ``serve/attn`` spans a bench or instrumented engine wraps the
    attention calls in, sized against the engine's ``serve/step``
    dispatch spans."""
    steps = agg["spans"].get("serve/step")
    attn = agg["spans"].get("serve/attn")
    backend = agg.get("serves", {}).get("serve/backend", {}).get("backend")
    if not steps and not attn and backend is None:
        return None
    total_step = sum(steps) if steps else None
    total_attn = sum(attn) if attn else None
    return {
        "backend": backend,
        "steps": len(steps) if steps else 0,
        "total_step_ms": round(total_step, 3) if total_step else None,
        "attn_spans": len(attn) if attn else 0,
        "total_attn_ms": round(total_attn, 3) if total_attn else None,
        "attn_fraction_of_step": (round(total_attn / total_step, 4)
                                  if total_attn and total_step else None),
    }


def _prefix_cache_summary(agg):
    """Prefix-cache reuse digest from the ``serve/prefix_*`` events, plus
    the frozen ``serve/prefix_hit_rate`` gauge when ``health()`` pushed
    one (the gauge is exact — page-level hit rate over every lookup; the
    event-derived fields count only admitted requests)."""
    serves = agg.get("serves", {})
    hits = serves.get("serve/prefix_hit", {})
    admits = serves.get("serve/admit", {}).get("count", 0)
    if not hits and "serve/prefix_hit_rate" not in agg["gauges"]:
        return None
    rate = agg["gauges"].get("serve/prefix_hit_rate", {}).get("last")
    return {
        "requests_with_hits": hits.get("count", 0),
        "admitted": admits,
        "request_hit_fraction": (round(hits.get("count", 0) / admits, 4)
                                 if admits else None),
        "pages_reused": hits.get("pages_reused", 0),
        "tokens_reused": hits.get("tokens_reused", 0),
        "cow_copies": serves.get("serve/prefix_cow", {}).get("count", 0),
        "pages_inserted": serves.get("serve/prefix_insert",
                                     {}).get("pages", 0),
        "evictions": serves.get("serve/prefix_evict", {}).get("count", 0),
        "page_hit_rate_gauge": rate,
    }


def _scheduler_summary(agg):
    """Scheduler-plane digest from the ``serve/sched`` announcement and
    the chunked policy's ``serve/prefill_chunk`` / ``serve/spec_*``
    events: chunks-per-prefill, the prefill/decode interleave ratio,
    speculative acceptance, and per-SLO-class TTFT/TPOT percentiles from
    the reconstructed request traces.  None when the stream predates the
    scheduler plane (no ``serve/sched`` event and no chunk events)."""
    serves = agg.get("serves", {})
    sched = serves.get("serve/sched", {})
    chunks = serves.get("serve/prefill_chunk", {})
    verify = serves.get("serve/spec_verify", {})
    if not sched and not chunks:
        return None
    by_req = chunks.get("by_req", {})
    n_chunks = chunks.get("count", 0)
    # decode work from the closed traces: every generated token was one
    # decode-step's worth of output for that slot
    traces = agg.get("requests") or []
    decode_tokens = sum(int(t.get("n_generated") or 0) for t in traces
                        if t.get("terminal"))
    accepted = verify.get("accepted", 0)
    rejected = verify.get("rejected", 0)
    by_class = {}
    for t in traces:
        cls = t.get("slo_class")
        if cls is None:
            continue
        rec = by_class.setdefault(cls, {"requests": 0, "ttft_ms": [],
                                        "tpot_ms": []})
        rec["requests"] += 1
        for k in ("ttft_ms", "tpot_ms"):
            if t.get(k) is not None:
                rec[k].append(float(t[k]))
    class_rows = {}
    for cls, rec in sorted(by_class.items()):
        row = {"requests": rec["requests"]}
        for k in ("ttft_ms", "tpot_ms"):
            vals = sorted(rec[k])
            row[k] = ({"p50": round(_pct(vals, 50), 3),
                       "p90": round(_pct(vals, 90), 3),
                       "p99": round(_pct(vals, 99), 3)}
                      if vals else None)
        class_rows[cls] = row
    return {
        "policy": sched.get("policy"),
        "config": sched.get("attrs"),
        "prefill_chunks": n_chunks,
        "prefill_chunk_tokens": chunks.get("tokens", 0),
        "prefills_chunked": len(by_req),
        "chunks_per_prefill": (round(n_chunks / len(by_req), 3)
                               if by_req else None),
        # share of cache-writing dispatches that were prefill chunks —
        # how much decode had to share the step loop with prefill
        "interleave_ratio": (round(n_chunks / (n_chunks + decode_tokens),
                                   4)
                             if n_chunks + decode_tokens else None),
        "spec_windows": serves.get("serve/spec_draft", {}).get("count", 0),
        "spec_accepted": accepted,
        "spec_rejected": rejected,
        "spec_acceptance_rate": (round(accepted / (accepted + rejected), 4)
                                 if accepted + rejected else None),
        "slo_classes": class_rows,
    }


# a warm prefetch queue pops in microseconds — any input wait past this is
# a dispatch stall (the feed couldn't keep ahead of compute)
STALL_WAIT_MS = 1.0


def _input_feed_summary(agg):
    """Input-wait / dispatch-stall digest from the ``engine/input_wait``
    spans (emitted around the prefetched-batch pop when the async pipeline
    is on), sized against total ``engine/train_batch`` time."""
    waits = agg["spans"].get("engine/input_wait")
    if not waits:
        return None
    vals = sorted(waits)
    total_wait = sum(vals)
    total_step = sum(agg["spans"].get("engine/train_batch", [])) or None
    return {
        "waits": len(vals),
        "total_wait_ms": round(total_wait, 3),
        "mean_ms": round(total_wait / len(vals), 3),
        "p50_ms": round(_pct(vals, 50), 3),
        "p99_ms": round(_pct(vals, 99), 3),
        "max_ms": round(vals[-1], 3),
        "stalled_steps": sum(1 for v in vals if v > STALL_WAIT_MS),
        "stall_threshold_ms": STALL_WAIT_MS,
        "wait_fraction_of_step": (round(total_wait / total_step, 4)
                                  if total_step else None),
    }


def _fmt_bytes(n):
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n}B"
        n /= 1024.0
    return f"{n}"


def print_tables(summary, out=sys.stdout):
    w = out.write
    if summary["spans"]:
        w("== span latency (ms) ==\n")
        w(f"{'span':<36}{'count':>7}{'mean':>10}{'p50':>10}"
          f"{'p90':>10}{'p99':>10}{'max':>10}\n")
        for name, r in summary["spans"].items():
            w(f"{name:<36}{r['count']:>7}{r['mean_ms']:>10}{r['p50_ms']:>10}"
              f"{r['p90_ms']:>10}{r['p99_ms']:>10}{r['max_ms']:>10}\n")
        w("\n")
    if summary["comms"]:
        w("== comm census (traced calls) ==\n")
        w(f"{'op':<24}{'calls':>7}{'bytes':>14}{'dur_ms':>12}"
          f"{'GB/s':>9}  axes\n")
        for op, r in summary["comms"].items():
            bw = r.get("achieved_gbps")
            w(f"{op:<24}{r['calls']:>7}{_fmt_bytes(r['bytes']):>14}"
              f"{r.get('dur_ms', 0.0):>12}"
              f"{bw if bw is not None else '-':>9}  "
              f"{','.join(r['axes'])}\n")
        w("\n")
    if summary["gauges"]:
        w("== gauges (last / peak) ==\n")
        w(f"{'gauge':<36}{'last':>16}{'peak':>16}{'samples':>9}\n")
        for name, r in summary["gauges"].items():
            last, peak = r["last"], r["peak"]
            if name.startswith("hbm/"):
                last, peak = _fmt_bytes(last), _fmt_bytes(peak)
            else:
                last = round(last, 4) if isinstance(last, float) else last
                peak = round(peak, 4) if isinstance(peak, float) else peak
            w(f"{name:<36}{last:>16}{peak:>16}{r['samples']:>9}\n")
        w("\n")
    prof = summary.get("profiling")
    if prof:
        comp = prof["compile"]
        w("== profiling: compile tracing ==\n")
        w(f"jit cache misses: {comp['total_misses']}  "
          f"storms: {comp['storms']}\n")
        if comp["sites"]:
            w(f"{'site':<32}{'misses':>7}{'dur_ms':>12}  causes\n")
            for site, r in comp["sites"].items():
                causes = ", ".join(f"{k}={v}"
                                   for k, v in r["causes"].items())
                w(f"{site:<32}{r['misses']:>7}{r['dur_ms']:>12}  "
                  f"{causes}\n")
        w("\n")
        if prof["mem"]:
            w("== profiling: HBM attribution (peak per span) ==\n")
            w(f"{'span':<16}{'live':>12}{'peak':>12}{'frag':>12}\n")
            for span, metrics in sorted(prof["mem"].items()):
                cells = []
                for m in ("live_bytes", "peak_bytes", "frag_bytes"):
                    rec = metrics.get(m)
                    cells.append(_fmt_bytes(rec["peak"]) if rec else "-")
                w(f"{span:<16}{cells[0]:>12}{cells[1]:>12}"
                  f"{cells[2]:>12}\n")
            w("\n")
        if prof["roofline"]:
            w("== profiling: live roofline (fraction of peak) ==\n")
            w(f"{'span':<16}{'compute':>10}{'bandwidth':>11}\n")
            for span, metrics in sorted(prof["roofline"].items()):
                cells = []
                for m in ("compute_frac", "bandwidth_frac"):
                    rec = metrics.get(m)
                    cells.append(f"{rec['last'] * 100:.1f}%"
                                 if rec and isinstance(
                                     rec["last"], (int, float)) else "-")
                w(f"{span:<16}{cells[0]:>10}{cells[1]:>11}\n")
            w("\n")
    at = summary.get("attribution")
    if at:
        w("== attribution ==\n")
        if at.get("step"):
            w("step decomposition (last / peak):\n")
            for name, r in at["step"].items():
                w(f"  {name:<20}{r['last']:>12}{r['peak']:>12}\n")
        sv = at.get("serving")
        if sv:
            w(f"requests attributed: {sv['requests']} "
              f"({sv['migrated']} migrated)  "
              f"e2e total: {sv['e2e_ms']} ms\n")
            w(f"{'stage':<12}{'total_ms':>12}{'share':>8}\n")
            for k, r in sv["stages"].items():
                share = (f"{r['frac'] * 100:.1f}%"
                         if r["frac"] is not None else "-")
                w(f"{k[:-3]:<12}{r['total_ms']:>12}{share:>8}\n")
        w("\n")
    ov = summary.get("overlap")
    if ov:
        w("== comm/compute overlap ==\n")
        w(f"{'gauge':<18}{'last':>12}{'peak':>12}\n")
        for name, r in ov["gauges"].items():
            w(f"{name:<18}{r['last']:>12}{r['peak']:>12}\n")
        if ov["exposed_comm_frac"] is not None:
            w(f"exposed comm fraction (step/attr): "
              f"{ov['exposed_comm_frac']}\n")
        w("\n")
    tiered = summary.get("tiered")
    if tiered:
        w("== tiered memory ==\n")
        w(f"{'gauge':<20}{'last':>14}{'peak':>14}\n")
        for name, r in tiered["gauges"].items():
            last, peak = r["last"], r["peak"]
            if name.endswith("_bytes") or name == "quant_bytes_saved":
                last, peak = _fmt_bytes(last), _fmt_bytes(peak)
            w(f"{name:<20}{last:>14}{peak:>14}\n")
        if tiered["prefetch_hit_rate"] is not None:
            w(f"prefetch hit rate: "
              f"{tiered['prefetch_hit_rate'] * 100:.1f}%\n")
        w("\n")
    feed = summary.get("input_feed")
    if feed:
        w("== input feed (engine/input_wait) ==\n")
        w(f"waits: {feed['waits']}  total: {feed['total_wait_ms']} ms  "
          f"mean: {feed['mean_ms']}  p50: {feed['p50_ms']}  "
          f"p99: {feed['p99_ms']}  max: {feed['max_ms']}\n")
        w(f"dispatch stalls (> {feed['stall_threshold_ms']} ms): "
          f"{feed['stalled_steps']}")
        if feed["wait_fraction_of_step"] is not None:
            w(f"  |  wait fraction of train_batch: "
              f"{feed['wait_fraction_of_step'] * 100:.2f}%")
        w("\n\n")
    serving = summary.get("serving")
    if serving:
        w("== serving events ==\n")
        w(f"{'event':<24}{'count':>7}  reasons\n")
        for name, r in serving.items():
            reasons = ", ".join(f"{k}={v}" for k, v in r["reasons"].items())
            w(f"{name:<24}{r['count']:>7}  {reasons}\n")
        w("\n")
    fleet = summary.get("fleet")
    if fleet:
        w("== fleet events ==\n")
        w(f"{'event':<24}{'count':>7}  replicas | reasons\n")
        for name, r in fleet.items():
            parts = []
            if r["replicas"]:
                parts.append(",".join(r["replicas"]))
            if r["reasons"]:
                parts.append(", ".join(f"{k}={v}"
                                       for k, v in r["reasons"].items()))
            w(f"{name:<24}{r['count']:>7}  {' | '.join(parts)}\n")
        w("\n")
    tp = summary.get("fleet_transport")
    if tp:
        w("== fleet transport ==\n")
        retries = ", ".join(f"{k}={v}" for k, v in
                            tp["retries_by_op"].items()) or "-"
        w(f"retries: {tp['retries']}  by op: {retries}\n")
        if tp["retry_elapsed_p50_s"] is not None:
            w(f"elapsed at retry: p50 {tp['retry_elapsed_p50_s']}s  "
              f"p99 {tp['retry_elapsed_p99_s']}s\n")
        w(f"breaker: {tp['breaker_opens']} open, "
          f"{tp['breaker_closes']} close\n")
        if tp["breakers"]:
            w(f"{'replica':<12}{'opens':>7}{'closes':>8}\n")
            for rid, b in tp["breakers"].items():
                w(f"{rid:<12}{b['opens']:>7}{b['closes']:>8}\n")
        drops = ", ".join(f"{k}={v}" for k, v in
                          tp["drops_by_op"].items()) or "-"
        w(f"duplicate calls dropped: {tp['dup_calls_dropped']}  "
          f"by op: {drops}\n")
        w("\n")
    tune = summary.get("autotuning")
    if tune:
        w("== autotuning ==\n")
        w(f"trials: {tune['trials_run']} run, {tune['trials_pruned']} "
          f"pruned  |  ledger rows written: "
          f"{tune['ledger_rows_written']}\n")
        w(f"{'trial':<12}{'objective':>14}  knobs\n")

        def _kn(raw):
            if isinstance(raw, dict):
                return ", ".join(f"{k}={v}" for k, v in raw.items())
            return str(raw or "")

        for r in tune["trials"]:
            obj = (f"{r['objective']:.3f}"
                   if isinstance(r["objective"], (int, float)) else "-")
            w(f"{str(r['trial']):<12}{obj:>14}  {_kn(r['knobs'])}\n")
        for r in tune["pruned"]:
            w(f"{str(r['trial']):<12}{'pruned':>14}  {_kn(r['knobs'])}"
              f"  [{r['reason']}]\n")
        win = tune.get("winner")
        if win:
            w(f"winner: {win['trial']}  knobs: {_kn(win.get('knobs'))}\n")
        if (tune.get("overlay") or {}).get("path"):
            w(f"overlay: {tune['overlay']['path']}\n")
        w("\n")
    dis = summary.get("fleet_disagg")
    if dis:
        w("== disaggregated fleet ==\n")
        w(f"{'role':<10}{'replicas':<20}{'queue':>6}\n")
        for role, rids in dis["roles"].items():
            q = dis["queue_depth"].get(role)
            w(f"{role:<10}{','.join(rids):<20}"
              f"{q if q is not None else '?':>6}\n")
        quant = (f"  quant bytes saved: {dis['quant_bytes_saved']}"
                 if dis.get("quant_bytes_saved") else "")
        w(f"migrations: {dis['migrations']}  "
          f"pages migrated: {dis['migrated_pages']}  "
          f"dedup skipped: {dis['dedup_skipped_pages']}  "
          f"bytes saved: {dis['bytes_saved']}{quant}\n")
        extras = []
        if dis["faults"]:
            extras.append("faults: " + ", ".join(
                f"{k}={v}" for k, v in dis["faults"].items()))
        if dis["aborts"]:
            extras.append("aborts: " + ", ".join(
                f"{k}={v}" for k, v in dis["aborts"].items()))
        if dis["local_prefills"]:
            extras.append(
                f"local prefills (degraded): {dis['local_prefills']}")
        if extras:
            w("  |  ".join(extras) + "\n")
        w("\n")
    sa = summary.get("serving_attention")
    if sa:
        w("== serving attention ==\n")
        w(f"backend: {sa['backend'] or '?'}  "
          f"steps: {sa['steps']}  "
          f"total step: {sa['total_step_ms']} ms\n")
        w(f"attn spans: {sa['attn_spans']}  "
          f"total attn: {sa['total_attn_ms']} ms")
        if sa["attn_fraction_of_step"] is not None:
            w(f"  |  attention share of serve-step: "
              f"{sa['attn_fraction_of_step'] * 100:.1f}%")
        w("\n\n")
    pc = summary.get("prefix_cache")
    if pc:
        w("== prefix cache ==\n")
        frac = pc["request_hit_fraction"]
        w(f"requests with hits: {pc['requests_with_hits']}"
          f"/{pc['admitted']} admitted"
          + (f" ({frac * 100:.1f}%)" if frac is not None else "") + "\n")
        w(f"pages reused: {pc['pages_reused']}  "
          f"tokens reused: {pc['tokens_reused']}  "
          f"cow copies: {pc['cow_copies']}\n")
        w(f"pages inserted: {pc['pages_inserted']}  "
          f"evictions: {pc['evictions']}")
        if pc["page_hit_rate_gauge"] is not None:
            w(f"  |  page hit rate (gauge): "
              f"{pc['page_hit_rate_gauge'] * 100:.1f}%")
        w("\n\n")
    sc = summary.get("scheduler")
    if sc:
        w("== scheduler ==\n")
        w(f"policy: {sc['policy'] or '?'}")
        cfg = sc.get("config") or {}
        if cfg.get("prefill_chunk_tokens"):
            w(f"  chunk: {cfg['prefill_chunk_tokens']} tok")
        if cfg.get("speculative"):
            w(f"  speculative: gamma={cfg.get('num_draft_tokens', '?')}")
        w("\n")
        if sc["prefill_chunks"]:
            w(f"prefill chunks: {sc['prefill_chunks']} "
              f"({sc['prefill_chunk_tokens']} tok) over "
              f"{sc['prefills_chunked']} prefills")
            if sc["chunks_per_prefill"] is not None:
                w(f"  |  chunks/prefill: {sc['chunks_per_prefill']}")
            if sc["interleave_ratio"] is not None:
                w(f"  |  interleave: "
                  f"{sc['interleave_ratio'] * 100:.1f}%")
            w("\n")
        if sc["spec_accepted"] or sc["spec_rejected"]:
            w(f"speculative: {sc['spec_windows']} windows  "
              f"accepted {sc['spec_accepted']}  "
              f"rejected {sc['spec_rejected']}")
            if sc["spec_acceptance_rate"] is not None:
                w(f"  |  acceptance: "
                  f"{sc['spec_acceptance_rate'] * 100:.1f}%")
            w("\n")
        if sc["slo_classes"]:
            w(f"{'slo class':<14}{'reqs':>6}{'ttft p50':>10}"
              f"{'ttft p90':>10}{'ttft p99':>10}{'tpot p50':>10}"
              f"{'tpot p99':>10}\n")
            for cls, row in sc["slo_classes"].items():
                ttft = row.get("ttft_ms") or {}
                tpot = row.get("tpot_ms") or {}
                w(f"{cls:<14}{row['requests']:>6}"
                  f"{ttft.get('p50', '-'):>10}{ttft.get('p90', '-'):>10}"
                  f"{ttft.get('p99', '-'):>10}{tpot.get('p50', '-'):>10}"
                  f"{tpot.get('p99', '-'):>10}\n")
        w("\n")
    rl = summary.get("request_latency")
    if rl:
        w("== request latency (serve/request/* traces) ==\n")
        terms = ", ".join(f"{k}={v}" for k, v in rl["terminals"].items())
        w(f"traces: {rl['traces']}  terminals: {terms}\n")
        if rl["orphans"]:
            w(f"OPEN TRACES (no terminal yet): {rl['orphans']}\n")
        if rl["slo"]["ok"] or rl["slo"]["miss"]:
            total = rl["slo"]["ok"] + rl["slo"]["miss"]
            w(f"slo: {rl['slo']['ok']}/{total} attained "
              f"({rl['slo']['ok'] / total * 100:.1f}%)\n")
        if rl["latency"]:
            w(f"{'latency (ms)':<20}{'count':>7}{'p50':>10}{'p90':>10}"
              f"{'p99':>10}{'max':>10}\n")
            for name, r in rl["latency"].items():
                w(f"{name:<20}{r['count']:>7}{r['p50']:>10}{r['p90']:>10}"
                  f"{r['p99']:>10}{r['max']:>10}\n")
        if rl["slowest"]:
            w(f"slowest requests (by e2e, top {len(rl['slowest'])}):\n")
            w(f"{'req_id':<12}{'terminal':<10}{'slot':>5}{'gen':>5}"
              f"{'queue':>9}{'ttft':>9}{'tpot':>9}{'e2e':>10}  slo\n")
            for t in rl["slowest"]:
                w(f"{str(t.get('req_id', '?')):<12}"
                  f"{t.get('terminal', '?'):<10}"
                  f"{t.get('slot', '-'):>5}{t.get('n_generated', 0):>5}"
                  f"{t.get('queue_wait_ms', '-'):>9}"
                  f"{t.get('ttft_ms', '-'):>9}{t.get('tpot_ms', '-'):>9}"
                  f"{t.get('e2e_ms', '-'):>10}  {t.get('slo', '-')}\n")
        w("\n")
    cl = summary.get("cluster")
    if cl:
        w(f"== cluster ({cl['ranks']} ranks, "
          f"{cl['aligned_steps']} aligned steps) ==\n")
        w(f"{'rank':<6}{'steps':>7}{'median step ms':>16}\n")
        for r, row in sorted(cl["per_rank"].items(), key=lambda kv:
                             int(kv[0])):
            med = row["median_step_ms"]
            w(f"{r:<6}{row['steps']:>7}"
              f"{med if med is not None else '-':>16}\n")
        skew = cl["step_skew_ms"]
        w(f"step skew: p50 {skew['p50']} ms  max {skew['max']} ms")
        if cl["worst_rel"] is not None:
            w(f"  |  slowest rank vs median: {cl['worst_rel']:.2f}x")
        w("\n\n")
    hb = summary["heartbeat"]
    w(f"== heartbeat ==\nsteps: {hb['steps']}  "
      f"median step: {hb['median_step_ms']} ms\n\n")
    if summary["stalls"]:
        w(f"== stalls ({len(summary['stalls'])}) ==\n")
        for s in summary["stalls"]:
            w(f"  step {s.get('step')}: gap {s.get('gap_s')}s "
              f"(median {s.get('median_step_s')}s, "
              f"threshold {s.get('threshold_s')}s)\n")
    else:
        w("== stalls ==\nnone\n")


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Aggregate a telemetry JSONL stream into tables.")
    ap.add_argument("target",
                    help="telemetry dir (containing events.jsonl) or the "
                         "events.jsonl path itself")
    ap.add_argument("--json", action="store_true",
                    help="emit the summary as JSON instead of tables")
    args = ap.parse_args(argv)
    files = discover_files(args.target)
    if not files:
        print(f"no events.jsonl under {args.target!r}", file=sys.stderr)
        return 1
    events = list(load_events(files))
    if not events and os.path.isdir(args.target):
        # a shard dir holding only torn/empty events.rank*.jsonl files
        # must not take the report down with it: degrade to the
        # single-stream events.jsonl path with a warning
        single = [
            p for p in
            _with_rotations(os.path.join(args.target, "events.jsonl"))
            if p not in files]
        if single:
            print("WARN: shard files held no parseable events; falling "
                  "back to the single-stream events.jsonl",
                  file=sys.stderr)
            events = list(load_events(single))
    summary = summarize(aggregate(events))
    if args.json:
        json.dump(summary, sys.stdout, indent=2)
        print()
    else:
        print_tables(summary)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
