#!/usr/bin/env python
"""Frozen schema for the unified telemetry JSONL event stream.

Every line ``deepspeed_tpu/monitor/telemetry.py`` emits must validate
against the per-kind schema below.  The schema is FROZEN: adding an event
kind or a field means editing this file in the same change, and the tier-1
test (``tests/unit/test_telemetry_schema.py``) diffs ``EVENT_KINDS``
against the telemetry module so the two cannot drift silently.

Usage:
    python scripts/check_telemetry_schema.py <events.jsonl> [more.jsonl ...]
    python scripts/check_telemetry_schema.py --prom <metrics.txt> [...]

The ``--prom`` mode validates a Prometheus text exposition page (the
``monitor/export.py`` /metrics surface) instead: metric-name grammar,
known TYPE declarations, numeric sample values.

Exit code 0 when every event on every file validates; 1 otherwise (each
offending line is reported with its file:lineno).
"""

import json
import re
import sys

# required: field -> allowed types.  optional: same, may be absent.
# Unknown kinds AND unknown fields are rejected — the stream is a contract.
_NUM = (int, float)

SCHEMA = {
    "span": {
        "required": {"ts": _NUM, "kind": str, "name": str, "dur_ms": _NUM},
        "optional": {"step": int, "attrs": dict},
    },
    "gauge": {
        "required": {"ts": _NUM, "kind": str, "name": str, "value": _NUM,
                     "peak": _NUM},
        "optional": {"step": int},
    },
    "counter": {
        "required": {"ts": _NUM, "kind": str, "name": str, "value": _NUM},
        "optional": {"step": int},
    },
    "comm": {
        "required": {"ts": _NUM, "kind": str, "name": str, "bytes": int,
                     "axis": str},
        "optional": {},
    },
    "heartbeat": {
        "required": {"ts": _NUM, "kind": str, "name": str, "step": int},
        "optional": {"step_ms": _NUM},
    },
    "stall": {
        "required": {"ts": _NUM, "kind": str, "name": str, "step": int,
                     "gap_s": _NUM, "median_step_s": _NUM,
                     "threshold_s": _NUM},
        "optional": {},
    },
    "meta": {
        "required": {"ts": _NUM, "kind": str, "name": str},
        "optional": {"attrs": dict, "step": int},
    },
    # fault-tolerance events (runtime/resilience.py): I/O retries
    # ("fault/retry", "fault/dataloader_retry"), checkpoint fallback
    # ("fault/ckpt_fallback"), preemption ("fault/preempt_requested",
    # "fault/preempted"), divergence ("fault/divergence",
    # "fault/auto_restore")
    "fault": {
        "required": {"ts": _NUM, "kind": str, "name": str},
        "optional": {"attrs": dict, "step": int},
    },
    # serving-robustness events (inference/robustness.py): admission
    # ("serve/admit"), typed rejection ("serve/reject"), load shedding
    # ("serve/shed"), deadline cancels ("serve/deadline"), per-slot fault
    # eviction ("serve/evict"), graceful drain ("serve/drain"), normal
    # completion ("serve/finish"), and recovered transient faults
    # ("serve/fault").  Typed reasons ride in attrs["reason"].  The
    # ``name`` field is validated against SERVE_EVENTS below.
    "serve": {
        "required": {"ts": _NUM, "kind": str, "name": str},
        "optional": {"attrs": dict, "step": int},
    },
}

# FROZEN vocabulary of serve-kind event names — must stay byte-identical
# to ``deepspeed_tpu.inference.robustness.SERVE_EVENTS`` (the tier-1 test
# diffs the two).  The prefix_* names belong to the prefix-cache subsystem
# (inference/prefix_cache.py): cached-page attach hits, copy-on-write
# copies, newly indexed pages, and reclaim-tier evictions.
# "serve/backend" records the attention backend an engine was built with
# (attrs: attention_backend / impl / interpret) so the stream's serve/step
# spans are attributable to the kernel path that produced them.
SERVE_EVENTS = (
    "serve/admit", "serve/reject", "serve/shed", "serve/deadline",
    "serve/evict", "serve/drain", "serve/finish", "serve/fault",
    "serve/prefix_hit", "serve/prefix_cow", "serve/prefix_insert",
    "serve/prefix_evict",
    "serve/backend",
    # per-request lifecycle trace (RequestTracer): one event per state
    # transition, each carrying req_id plus the derived latencies so a
    # request's full history is reconstructible from the JSONL stream
    # alone.  The "queued" state is implicit between admitted and
    # prefill_start (queue_wait_ms attr); the "decode" phase is implicit
    # between first_token and the terminal (tpot_ms attr).  Every admitted
    # request reaches EXACTLY ONE of the four terminals — the
    # trace-completeness invariant leak_report() audits.
    "serve/request/admitted", "serve/request/prefill_start",
    "serve/request/first_token",
    "serve/request/finish", "serve/request/shed",
    "serve/request/deadline", "serve/request/evict",
)

EVENT_KINDS = tuple(SCHEMA)


def validate_event(event):
    """Validate one decoded event dict.  Returns a list of problem strings
    (empty = valid)."""
    problems = []
    if not isinstance(event, dict):
        return [f"event is {type(event).__name__}, not an object"]
    kind = event.get("kind")
    if kind not in SCHEMA:
        return [f"unknown kind {kind!r}"]
    spec = SCHEMA[kind]
    for field, types in spec["required"].items():
        if field not in event:
            problems.append(f"{kind}: missing required field {field!r}")
        elif not isinstance(event[field], types) or \
                isinstance(event[field], bool):
            problems.append(
                f"{kind}: field {field!r} has type "
                f"{type(event[field]).__name__}")
    allowed = set(spec["required"]) | set(spec["optional"])
    for field, value in event.items():
        if field not in allowed:
            problems.append(f"{kind}: unknown field {field!r}")
        elif field in spec["optional"] and (
                not isinstance(value, spec["optional"][field])
                or isinstance(value, bool)):
            problems.append(
                f"{kind}: optional field {field!r} has type "
                f"{type(value).__name__}")
    if kind == "serve" and isinstance(event.get("name"), str) and \
            event["name"] not in SERVE_EVENTS:
        problems.append(f"serve: unknown event name {event['name']!r}")
    return problems


def validate_stream(lines):
    """Validate an iterable of JSONL lines.  Yields (lineno, problem)
    pairs; empty/whitespace lines are skipped."""
    for i, line in enumerate(lines, start=1):
        line = line.strip()
        if not line:
            continue
        try:
            event = json.loads(line)
        except ValueError as e:
            yield i, f"not valid JSON: {e}"
            continue
        for p in validate_event(event):
            yield i, p


def validate_file(path):
    with open(path) as f:
        return list(validate_stream(f))


# ----------------------------------------------------------------------
# exporter metric-name validation (monitor/export.py)
# ----------------------------------------------------------------------
# Prometheus text exposition format 0.0.4, the exporter's /metrics
# surface.  Every exported family name must match the metric-name
# grammar, carry a known TYPE, and every sample must belong to a typed
# family (summaries also own their _sum/_count companions).
PROM_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
PROM_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^{}]*\})?\s+(\S+)$")
PROM_TYPES = ("counter", "gauge", "summary", "histogram", "untyped")


def validate_prom_exposition(text):
    """Validate a Prometheus text exposition page (the exporter's
    ``/metrics`` body).  Returns a list of problem strings (empty =
    valid)."""
    problems = []
    typed = set()
    for i, line in enumerate(text.splitlines(), start=1):
        line = line.rstrip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) != 4:
                problems.append(f"line {i}: malformed TYPE line")
                continue
            _, _, name, ptype = parts
            if not PROM_NAME_RE.match(name):
                problems.append(f"line {i}: illegal metric name {name!r}")
            if ptype not in PROM_TYPES:
                problems.append(f"line {i}: unknown type {ptype!r}")
            typed.add(name)
            continue
        if line.startswith("#"):
            continue    # HELP / comments
        m = PROM_SAMPLE_RE.match(line)
        if not m:
            problems.append(f"line {i}: malformed sample line {line!r}")
            continue
        name, _, value = m.group(1), m.group(2), m.group(3)
        try:
            float(value)
        except ValueError:
            if value not in ("+Inf", "-Inf", "NaN"):
                problems.append(
                    f"line {i}: non-numeric sample value {value!r}")
        family = name
        for suffix in ("_sum", "_count", "_bucket"):
            if name.endswith(suffix) and name[:-len(suffix)] in typed:
                family = name[:-len(suffix)]
                break
        if family not in typed:
            problems.append(
                f"line {i}: sample {name!r} has no TYPE declaration")
    return problems


def validate_prom_file(path):
    with open(path) as f:
        return validate_prom_exposition(f.read())


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    if not argv:
        print(__doc__)
        return 2
    if argv[0] == "--prom":
        bad = 0
        for path in argv[1:]:
            for p in validate_prom_file(path):
                print(f"{path}: {p}")
                bad += 1
        if bad:
            print(f"FAIL: {bad} problem(s)")
            return 1
        print("OK: exposition validated")
        return 0
    bad = 0
    total = 0
    for path in argv:
        with open(path) as f:
            for i, line in enumerate(f, start=1):
                if not line.strip():
                    continue
                total += 1
                try:
                    event = json.loads(line)
                    problems = validate_event(event)
                except ValueError as e:
                    problems = [f"not valid JSON: {e}"]
                for p in problems:
                    print(f"{path}:{i}: {p}")
                    bad += 1
    if bad:
        print(f"FAIL: {bad} problem(s) across {total} event(s)")
        return 1
    print(f"OK: {total} event(s) validated")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
