#!/usr/bin/env python
"""Frozen schema for the unified telemetry JSONL event stream.

Every line ``deepspeed_tpu/monitor/telemetry.py`` emits must validate
against the per-kind schema below.  The schema is FROZEN: adding an event
kind or a field means editing this file in the same change, and the tier-1
test (``tests/unit/test_telemetry_schema.py``) diffs ``EVENT_KINDS``
against the telemetry module so the two cannot drift silently.

Usage:
    python scripts/check_telemetry_schema.py <events.jsonl> [more.jsonl ...]
    python scripts/check_telemetry_schema.py --prom <metrics.txt> [...]
    python scripts/check_telemetry_schema.py --shards <shard_dir> [...]
    python scripts/check_telemetry_schema.py --cluster <payload.json> [...]
    python scripts/check_telemetry_schema.py --ledger <BENCH_LEDGER.jsonl>
    python scripts/check_telemetry_schema.py --incidents <bundle_or_dir> [...]
    python scripts/check_telemetry_schema.py --tune <overlay_or_dir> [...]

The ``--incidents`` mode validates incident bundles written by the
incident plane (``monitor/incidents.py``): each bundle directory must
contain a schema-valid ``incident.json`` (trigger kind from the frozen
:data:`INCIDENT_TRIGGERS` vocabulary, registry snapshot, correlation
section) plus ``ring.jsonl`` whose every line validates against the
event schema.  A path may be one bundle or a parent ``incidents/``
directory of bundles.

The ``--ledger`` mode validates a perf-regression ledger
(``bench.py`` appends one row per micro-bench metric; ``scripts/
ds_perf_diff.py`` compares runs against it): every row must carry
``ts``/``run``/``bench``/``metric``/``value`` with an optional
``unit``.

The ``--prom`` mode validates a Prometheus text exposition page (the
``monitor/export.py`` /metrics surface) instead: metric-name grammar,
known TYPE declarations, numeric sample values.

The ``--shards`` mode validates a distributed-telemetry shard directory
(``events.rank{N}.jsonl`` per process, rotated generations included):
every event on every shard must validate AND carry a ``rank`` stamp
matching its filename.  The ``--cluster`` mode validates a saved
``/cluster`` endpoint payload (``monitor/aggregate.py`` snapshot shape).

Exit code 0 when every event on every file validates; 1 otherwise (each
offending line is reported with its file:lineno).
"""

import glob
import json
import os
import re
import sys

# required: field -> allowed types.  optional: same, may be absent.
# Unknown kinds AND unknown fields are rejected — the stream is a contract.
_NUM = (int, float)

SCHEMA = {
    "span": {
        "required": {"ts": _NUM, "kind": str, "name": str, "dur_ms": _NUM},
        "optional": {"step": int, "attrs": dict},
    },
    "gauge": {
        "required": {"ts": _NUM, "kind": str, "name": str, "value": _NUM,
                     "peak": _NUM},
        "optional": {"step": int},
    },
    "counter": {
        "required": {"ts": _NUM, "kind": str, "name": str, "value": _NUM},
        "optional": {"step": int},
    },
    # collective-tracing events (comm/comm.py _traced spans + analytic
    # censuses): payload bytes are dtype-TRUE; timed records add the
    # host-observed duration, participant count, and achieved bus
    # bandwidth against the analytic per-link peak
    # (comm/topology_model.py).  ``name`` is validated against COMM_OPS.
    # Quantized collectives (comm/quantize.py) add ``wire_dtype`` (the
    # on-wire payload dtype, e.g. "int8" — ``bytes`` is then the reduced
    # wire payload) and ``bytes_saved`` (dtype-true baseline minus wire
    # bytes); unquantized records omit both.
    "comm": {
        "required": {"ts": _NUM, "kind": str, "name": str, "bytes": int,
                     "axis": str},
        "optional": {"dtype": str, "dur_ms": _NUM, "world": int,
                     "busbw_gbps": _NUM, "peak_gbps": _NUM,
                     "wire_dtype": str, "bytes_saved": int},
    },
    "heartbeat": {
        "required": {"ts": _NUM, "kind": str, "name": str, "step": int},
        "optional": {"step_ms": _NUM},
    },
    "stall": {
        "required": {"ts": _NUM, "kind": str, "name": str, "step": int,
                     "gap_s": _NUM, "median_step_s": _NUM,
                     "threshold_s": _NUM},
        "optional": {},
    },
    "meta": {
        "required": {"ts": _NUM, "kind": str, "name": str},
        "optional": {"attrs": dict, "step": int},
    },
    # fault-tolerance events (runtime/resilience.py): I/O retries
    # ("fault/retry", "fault/dataloader_retry"), checkpoint fallback
    # ("fault/ckpt_fallback"), preemption ("fault/preempt_requested",
    # "fault/preempted"), divergence ("fault/divergence",
    # "fault/auto_restore")
    "fault": {
        "required": {"ts": _NUM, "kind": str, "name": str},
        "optional": {"attrs": dict, "step": int},
    },
    # serving-robustness events (inference/robustness.py): admission
    # ("serve/admit"), typed rejection ("serve/reject"), load shedding
    # ("serve/shed"), deadline cancels ("serve/deadline"), per-slot fault
    # eviction ("serve/evict"), graceful drain ("serve/drain"), normal
    # completion ("serve/finish"), and recovered transient faults
    # ("serve/fault").  Typed reasons ride in attrs["reason"].  The
    # ``name`` field is validated against SERVE_EVENTS below.
    "serve": {
        "required": {"ts": _NUM, "kind": str, "name": str},
        "optional": {"attrs": dict, "step": int},
    },
    # profiling-plane compile tracing (monitor/profiling.py
    # CompileWatcher): one "compile/miss" record per jit-cache miss with
    # the wrapped site, the observed wall time (compile + first
    # execution), the site's cumulative miss count, and the cause diff vs
    # the previous call signature; one "compile/storm" record per storm
    # onset (site "*", count = misses inside the sliding window).  The
    # ``name`` field is validated against COMPILE_EVENTS, ``cause``
    # against COMPILE_CAUSES.
    "compile": {
        "required": {"ts": _NUM, "kind": str, "name": str, "site": str,
                     "count": int},
        "optional": {"dur_ms": _NUM, "cause": str, "window_s": _NUM,
                     "attrs": dict, "step": int},
    },
    # fleet-routing events (inference/fleet.py FleetRouter): replica
    # spawns/respawns, routed dispatches ("fleet/route"), affinity-miss
    # spills, injected dispatch faults, redispatches after a replica
    # failure, abrupt kills, fencing, graceful drains, fleet-level sheds
    # (redispatch budget, fleet drain), and autoscale decisions.  The
    # ``name`` field is validated against FLEET_EVENTS below.
    "fleet": {
        "required": {"ts": _NUM, "kind": str, "name": str},
        "optional": {"attrs": dict, "step": int},
    },
    # incident-plane events (monitor/incidents.py IncidentManager): one
    # "incident/open" per trigger (id, trigger kind from
    # INCIDENT_TRIGGERS, verdict source + detail) and one
    # "incident/written" once its bundle landed on disk (ring-dump event
    # count + bundle path).  The ``name`` field is validated against
    # INCIDENT_EVENTS, ``trigger`` against INCIDENT_TRIGGERS.
    "incident": {
        "required": {"ts": _NUM, "kind": str, "name": str, "id": str,
                     "trigger": str},
        "optional": {"source": str, "detail": str, "step": int,
                     "events": int, "path": str},
    },
    # autotuning control-plane events (autotuning/controlplane.py
    # ControlPlane): one "tune/trial_start" per launched trial (attrs:
    # trial / knobs), one "tune/trial_result" per scored trial (attrs:
    # trial / objective / metrics / snapshot_hash), one
    # "tune/trial_pruned" per point rejected by the feasibility model
    # before running (attrs: trial / knobs / reason), and one
    # "tune/overlay_written" when the winning overlay lands on disk
    # (attrs: trial / path / snapshot_hash).  The ``name`` field is
    # validated against TUNE_EVENTS below.
    "tune": {
        "required": {"ts": _NUM, "kind": str, "name": str},
        "optional": {"attrs": dict, "step": int},
    },
}

# FROZEN vocabulary of serve-kind event names — must stay byte-identical
# to ``deepspeed_tpu.inference.robustness.SERVE_EVENTS`` (the tier-1 test
# diffs the two).  The prefix_* names belong to the prefix-cache subsystem
# (inference/prefix_cache.py): cached-page attach hits, copy-on-write
# copies, newly indexed pages, and reclaim-tier evictions.
# "serve/backend" records the attention backend an engine was built with
# (attrs: attention_backend / impl / interpret) so the stream's serve/step
# spans are attributable to the kernel path that produced them.
SERVE_EVENTS = (
    "serve/admit", "serve/reject", "serve/shed", "serve/deadline",
    "serve/evict", "serve/drain", "serve/finish", "serve/fault",
    "serve/prefix_hit", "serve/prefix_cow", "serve/prefix_insert",
    "serve/prefix_evict",
    # "serve/compile_storm" fires once per recompile-storm onset seen by
    # the serving engine's CompileWatcher (monitor/profiling.py): shapes
    # are churning faster than the jit cache amortises (attrs: misses).
    "serve/compile_storm",
    "serve/backend",
    # scheduler plane (inference/scheduler.py): the once-per-engine
    # policy meta record ("serve/sched": policy / prefill_chunk_tokens /
    # speculative / num_draft_tokens), one chunked-prefill dispatch
    # ("serve/prefill_chunk": req_id / slot / start / tokens / remaining /
    # slo_class), one draft-model proposal ("serve/spec_draft": slots /
    # window) and its target verification ("serve/spec_verify": slots /
    # window / accepted / rejected)
    "serve/sched", "serve/prefill_chunk",
    "serve/spec_draft", "serve/spec_verify",
    # per-request lifecycle trace (RequestTracer): one event per state
    # transition, each carrying req_id plus the derived latencies so a
    # request's full history is reconstructible from the JSONL stream
    # alone.  The "queued" state is implicit between admitted and
    # prefill_start (queue_wait_ms attr); the "decode" phase is implicit
    # between first_token and the terminal (tpot_ms attr).  Every admitted
    # request reaches EXACTLY ONE of the four terminals — the
    # trace-completeness invariant leak_report() audits.
    "serve/request/admitted", "serve/request/prefill_start",
    "serve/request/first_token",
    "serve/request/finish", "serve/request/shed",
    "serve/request/deadline", "serve/request/evict",
    # critical-path attribution (monitor/attribution.py): one record
    # adjacent to each terminal carrying the ordered stage breakdown
    # (queue/prefill/migrate/gap/decode _ms attrs, summing to e2e_ms by
    # construction), the terminal it pairs with, chunk count, whether
    # the request crossed a prefill->decode migration, and the "path"
    # flow string ds_trace_export renders as arrows
    "serve/request/attr",
)

# FROZEN vocabulary of fleet-kind event names — must stay byte-identical
# to ``deepspeed_tpu.inference.fleet.FLEET_EVENTS`` (the tier-1 test
# diffs the two).  Typed reasons / replica ids / epochs ride in attrs.
FLEET_EVENTS = (
    "fleet/spawn", "fleet/respawn", "fleet/route", "fleet/spill",
    "fleet/dispatch_fault", "fleet/redispatch", "fleet/kill",
    "fleet/fence", "fleet/drain", "fleet/shed",
    "fleet/scale_up", "fleet/scale_down",
    "fleet/migrate_start", "fleet/migrate_commit", "fleet/migrate_fault",
    "fleet/migrate_abort", "fleet/local_prefill",
    "fleet/worker_lost",
    "fleet/retry", "fleet/breaker_open", "fleet/breaker_close",
    "fleet/dup_call_dropped",
)

# FROZEN vocabulary of the fleet gauge family — must stay byte-identical
# to ``deepspeed_tpu.inference.fleet.FLEET_GAUGES`` (the tier-1 test
# diffs the two).  Every gauge event under the ``fleet/`` prefix is
# validated against this tuple; most of the family is registry-only
# (scraped by the exporter) and only the breaker gauges are also
# emitted as gauge EVENTS at transition time.
FLEET_GAUGES = (
    "fleet/replicas", "fleet/healthy", "fleet/pending",
    "fleet/queue_depth", "fleet/redispatches", "fleet/workers_lost",
    "fleet/heartbeat_age_s", "fleet/migrating", "fleet/migrated_pages",
    "fleet/dedup_skipped_pages", "fleet/prefill_queue_depth",
    "fleet/decode_queue_depth", "fleet/breaker_open_replicas",
    "fleet/breaker_opens", "fleet/breaker_closes", "fleet/retries",
    "fleet/dup_calls_dropped",
)

# FROZEN vocabulary of tune-kind event names — must stay byte-identical
# to ``deepspeed_tpu.autotuning.controlplane.TUNE_EVENTS`` (the tier-1
# test diffs the two).  Trial ids / knob dicts / objective scores ride
# in attrs.
TUNE_EVENTS = (
    "tune/trial_start", "tune/trial_result", "tune/trial_pruned",
    "tune/overlay_written",
)

# Distributed (sharded) mode stamps every record with its origin rank so
# merged streams keep attribution; single-rank streams omit it.
for _spec in SCHEMA.values():
    _spec["optional"]["rank"] = int

# FROZEN vocabulary of comm-kind event names — must stay byte-identical
# to ``deepspeed_tpu.comm.comm.COMM_OPS`` (the tier-1 test diffs the
# two).  Covers every traced dist.* verb plus the analytic censuses for
# XLA-inserted reductions (engine grad reduce, param-stream replication).
COMM_OPS = (
    "all_reduce", "all_gather", "reduce_scatter", "all_to_all",
    "broadcast", "scatter", "ppermute", "barrier",
)

# FROZEN vocabulary of the quantized-collective savings gauges — must
# stay byte-identical to ``deepspeed_tpu.comm.quantize.QUANT_GAUGES``
# (the tier-1 test diffs the two).  One gauge per quantizable wire path;
# any gauge event under the ``comm/`` prefix is validated against this
# tuple (the busbw gauges are registry-only and never emitted as gauge
# events).
QUANT_GAUGES = (
    "comm/all_reduce/quant_bytes_saved",
    "comm/reduce_scatter/quant_bytes_saved",
    "comm/kv_migrate/quant_bytes_saved",
)

# FROZEN vocabulary of the comm/compute-overlap gauges — must stay
# byte-identical to ``deepspeed_tpu.runtime.zero.stage_plan.
# OVERLAP_GAUGES`` (the tier-1 test diffs the two).  Emitted per step by
# the engine when ``zero_optimization.overlap.enabled``; every gauge
# event under the ``comm/overlap/`` prefix is validated against this
# tuple (other ``comm/`` gauges stay on the quantization vocabulary).
OVERLAP_GAUGES = (
    "comm/overlap/exposed_ms",
    "comm/overlap/overlapped_ms",
    "comm/overlap/gather_buckets",
    "comm/overlap/rs_buckets",
    "comm/overlap/prefetch_depth",
)

# FROZEN vocabulary of the tiered-memory-engine gauges — must stay
# byte-identical to ``deepspeed_tpu.runtime.tiered_store.TIER_GAUGES``
# (the tier-1 test diffs the two).  Occupancy per tier, prefetch
# hit/miss counters, eviction/writeback counts, achieved bandwidth per
# transfer path, and int8-tier savings; every gauge event under the
# ``tier/`` prefix is validated against this tuple.
TIER_GAUGES = (
    "tier/hbm_bytes",
    "tier/host_bytes",
    "tier/nvme_bytes",
    "tier/prefetch_hits",
    "tier/prefetch_misses",
    "tier/evictions",
    "tier/writebacks",
    "tier/h2d_gbps",
    "tier/d2h_gbps",
    "tier/nvme_read_gbps",
    "tier/nvme_write_gbps",
    "tier/quant_bytes_saved",
)

# FROZEN vocabulary of the cluster aggregation gauges — must stay
# byte-identical to ``deepspeed_tpu.monitor.aggregate.CLUSTER_GAUGES``
# (the tier-1 test diffs the two).
CLUSTER_GAUGES = (
    "cluster/ranks",
    "cluster/missing_ranks",
    "cluster/step_skew_ms",
    "cluster/step_skew_rel",
    "cluster/collective_spread_ms",
    "cluster/straggler_rank",
)

# FROZEN vocabularies of the profiling plane — each must stay
# byte-identical to its twin in ``deepspeed_tpu.monitor.profiling``
# (the tier-1 test diffs every pair).  compile-kind event names; the
# cause labels a compile/miss may carry; the logical top-level spans
# HBM/roofline attribution keys on; and the per-span metric leaves of
# the ``mem/<span>/<metric>`` and ``roofline/<span>/<metric>`` gauge
# families (validated below for every gauge event under those prefixes).
COMPILE_EVENTS = ("compile/miss", "compile/storm")
COMPILE_CAUSES = ("cold", "new_shape", "new_dtype", "new_callable",
                  "new_static")
PROFILE_SPANS = ("fwd", "bwd", "step", "train_batch", "serve_step",
                 "prefill")
MEM_METRICS = ("live_bytes", "peak_bytes", "frag_bytes")
ROOFLINE_METRICS = ("compute_frac", "bandwidth_frac")

# FROZEN vocabularies of the incident plane — each must stay
# byte-identical to its twin in ``deepspeed_tpu.monitor.incidents``
# (the tier-1 test diffs both pairs).  Incident-kind event names, and
# the closed set of trigger kinds (one per verdict source: watchdog
# stall, recompile-storm onset, cluster straggler, non-empty
# leak_report(), fleet replica kill / fence, SLO burn-rate alert).
INCIDENT_EVENTS = ("incident/open", "incident/written")
INCIDENT_TRIGGERS = ("stall", "storm", "straggler", "leak",
                     "replica_kill", "replica_fence", "slo_burn",
                     "worker_lost", "breaker_open")

# FROZEN vocabularies of the time-attribution plane — each must stay
# byte-identical to its twin in ``deepspeed_tpu.monitor.attribution``
# (the tier-1 test diffs both pairs).  STEP_ATTR_GAUGES is the per-step
# decomposition gauge family (every gauge event under the ``step/attr/``
# prefix is validated against it); ATTR_STAGES is the ordered stage
# vocabulary of the ``serve/request/attr`` critical-path record — its
# attrs must carry one ``<stage>_ms`` per entry plus ``e2e_ms`` the
# stages sum to.
STEP_ATTR_GAUGES = (
    "step/attr/compute_ms",
    "step/attr/exposed_comm_ms",
    "step/attr/input_wait_ms",
    "step/attr/host_sync_ms",
    "step/attr/compile_ms",
    "step/attr/exposed_comm_frac",
)
ATTR_STAGES = ("queue", "prefill", "migrate", "gap", "decode")

EVENT_KINDS = tuple(SCHEMA)


def validate_event(event):
    """Validate one decoded event dict.  Returns a list of problem strings
    (empty = valid)."""
    problems = []
    if not isinstance(event, dict):
        return [f"event is {type(event).__name__}, not an object"]
    kind = event.get("kind")
    if kind not in SCHEMA:
        return [f"unknown kind {kind!r}"]
    spec = SCHEMA[kind]
    for field, types in spec["required"].items():
        if field not in event:
            problems.append(f"{kind}: missing required field {field!r}")
        elif not isinstance(event[field], types) or \
                isinstance(event[field], bool):
            problems.append(
                f"{kind}: field {field!r} has type "
                f"{type(event[field]).__name__}")
    allowed = set(spec["required"]) | set(spec["optional"])
    for field, value in event.items():
        if field not in allowed:
            problems.append(f"{kind}: unknown field {field!r}")
        elif field in spec["optional"] and (
                not isinstance(value, spec["optional"][field])
                or isinstance(value, bool)):
            problems.append(
                f"{kind}: optional field {field!r} has type "
                f"{type(value).__name__}")
    if kind == "serve" and isinstance(event.get("name"), str) and \
            event["name"] not in SERVE_EVENTS:
        problems.append(f"serve: unknown event name {event['name']!r}")
    if kind == "serve" and event.get("name") == "serve/request/attr":
        attrs = event.get("attrs")
        if not isinstance(attrs, dict):
            problems.append("serve: serve/request/attr carries no attrs")
        else:
            for key in tuple(f"{s}_ms" for s in ATTR_STAGES) + ("e2e_ms",):
                v = attrs.get(key)
                if not isinstance(v, _NUM) or isinstance(v, bool):
                    problems.append(
                        f"serve: serve/request/attr attr {key!r} is "
                        f"{type(v).__name__}, not a number")
    if kind == "fleet" and isinstance(event.get("name"), str) and \
            event["name"] not in FLEET_EVENTS:
        problems.append(f"fleet: unknown event name {event['name']!r}")
    if kind == "tune" and isinstance(event.get("name"), str) and \
            event["name"] not in TUNE_EVENTS:
        problems.append(f"tune: unknown event name {event['name']!r}")
    if kind == "comm" and isinstance(event.get("name"), str) and \
            event["name"] not in COMM_OPS:
        problems.append(f"comm: unknown collective {event['name']!r}")
    if kind == "gauge" and isinstance(event.get("name"), str) and \
            event["name"].startswith("cluster/") and \
            event["name"] not in CLUSTER_GAUGES:
        problems.append(f"gauge: unknown cluster gauge {event['name']!r}")
    if kind == "gauge" and isinstance(event.get("name"), str) and \
            event["name"].startswith("comm/overlap/") and \
            event["name"] not in OVERLAP_GAUGES:
        problems.append(f"gauge: unknown overlap gauge {event['name']!r}")
    if kind == "gauge" and isinstance(event.get("name"), str) and \
            event["name"].startswith("comm/") and \
            not event["name"].startswith("comm/overlap/") and \
            event["name"] not in QUANT_GAUGES:
        problems.append(f"gauge: unknown comm gauge {event['name']!r}")
    if kind == "gauge" and isinstance(event.get("name"), str) and \
            event["name"].startswith("tier/") and \
            event["name"] not in TIER_GAUGES:
        problems.append(f"gauge: unknown tier gauge {event['name']!r}")
    if kind == "gauge" and isinstance(event.get("name"), str) and \
            event["name"].startswith("step/attr/") and \
            event["name"] not in STEP_ATTR_GAUGES:
        problems.append(
            f"gauge: unknown step/attr gauge {event['name']!r}")
    if kind == "gauge" and isinstance(event.get("name"), str) and \
            event["name"].startswith("fleet/") and \
            event["name"] not in FLEET_GAUGES:
        problems.append(f"gauge: unknown fleet gauge {event['name']!r}")
    if kind == "compile" and isinstance(event.get("name"), str):
        if event["name"] not in COMPILE_EVENTS:
            problems.append(
                f"compile: unknown event name {event['name']!r}")
        cause = event.get("cause")
        if cause is not None and cause not in COMPILE_CAUSES:
            problems.append(f"compile: unknown cause {cause!r}")
    if kind == "incident":
        if isinstance(event.get("name"), str) and \
                event["name"] not in INCIDENT_EVENTS:
            problems.append(
                f"incident: unknown event name {event['name']!r}")
        trigger = event.get("trigger")
        if isinstance(trigger, str) and trigger not in INCIDENT_TRIGGERS:
            problems.append(f"incident: unknown trigger {trigger!r}")
    if kind == "gauge" and isinstance(event.get("name"), str):
        for prefix, metrics in (("mem/", MEM_METRICS),
                                ("roofline/", ROOFLINE_METRICS)):
            if not event["name"].startswith(prefix):
                continue
            parts = event["name"].split("/")
            if len(parts) != 3 or parts[1] not in PROFILE_SPANS or \
                    parts[2] not in metrics:
                problems.append(
                    f"gauge: unknown {prefix}* gauge {event['name']!r}")
    return problems


def validate_stream(lines):
    """Validate an iterable of JSONL lines.  Yields (lineno, problem)
    pairs; empty/whitespace lines are skipped."""
    for i, line in enumerate(lines, start=1):
        line = line.strip()
        if not line:
            continue
        try:
            event = json.loads(line)
        except ValueError as e:
            yield i, f"not valid JSON: {e}"
            continue
        for p in validate_event(event):
            yield i, p


def validate_file(path):
    with open(path) as f:
        return list(validate_stream(f))


# ----------------------------------------------------------------------
# distributed-telemetry shard directories (monitor/aggregate.py)
# ----------------------------------------------------------------------
_SHARD_RE = re.compile(r"events\.rank(\d+)\.jsonl(\.\d+)?$")


def validate_shard_dir(shard_dir):
    """Validate every per-rank shard under ``shard_dir``.  Beyond the
    per-event schema, each record's ``rank`` stamp must match the rank in
    its shard's filename — a mis-stamped shard would silently corrupt the
    cross-rank alignment.  Returns ``(problems, shards_seen)``."""
    problems = []
    paths = sorted(glob.glob(os.path.join(shard_dir, "events.rank*.jsonl")) +
                   glob.glob(os.path.join(shard_dir, "events.rank*.jsonl.*")))
    shards = 0
    for path in paths:
        m = _SHARD_RE.search(path)
        if not m:
            continue
        shards += 1
        want_rank = int(m.group(1))
        with open(path) as f:
            lines = f.readlines()
        for i, line in enumerate(lines, start=1):
            stripped = line.strip()
            if not stripped:
                continue
            try:
                event = json.loads(stripped)
            except ValueError:
                # torn tail of a live writer: tolerated on the final
                # line (aggregation skips and counts it), fatal
                # anywhere else
                if i != len(lines):
                    problems.append(
                        f"{path}:{i}: unparseable non-final line")
                continue
            for p in validate_event(event):
                problems.append(f"{path}:{i}: {p}")
            got = event.get("rank") if isinstance(event, dict) else None
            if got != want_rank:
                problems.append(
                    f"{path}:{i}: rank stamp {got!r} != shard "
                    f"rank {want_rank}")
    if not shards:
        problems.append(f"{shard_dir}: no events.rank*.jsonl shards found")
    return problems, shards


# ----------------------------------------------------------------------
# /cluster endpoint payload (monitor/aggregate.py aggregate_cluster)
# ----------------------------------------------------------------------
def _check(problems, cond, msg):
    if not cond:
        problems.append(msg)


def validate_cluster_payload(obj):
    """Validate a decoded ``/cluster`` snapshot (the aggregate_cluster
    dict).  Returns a list of problem strings (empty = valid)."""
    problems = []
    if not isinstance(obj, dict):
        return [f"payload is {type(obj).__name__}, not an object"]
    for field, types in (("ts", _NUM), ("shard_dir", str), ("ranks", list),
                         ("missing_ranks", list), ("torn_lines", int),
                         ("steps", dict), ("step_skew", dict),
                         ("collectives", dict), ("straggler", dict)):
        if field not in obj:
            problems.append(f"missing required field {field!r}")
        elif not isinstance(obj[field], types):
            problems.append(f"field {field!r} has type "
                            f"{type(obj[field]).__name__}")
    if problems:
        return problems
    _check(problems, all(isinstance(r, int) for r in obj["ranks"]),
           "ranks: non-int rank")
    _check(problems, all(isinstance(r, int) for r in obj["missing_ranks"]),
           "missing_ranks: non-int rank")
    steps = obj["steps"]
    for f in ("count", "aligned"):
        _check(problems, isinstance(steps.get(f), int),
               f"steps.{f}: not an int")
    _check(problems,
           steps.get("median_step_ms") is None or
           isinstance(steps["median_step_ms"], _NUM),
           "steps.median_step_ms: not numeric or null")
    skew = obj["step_skew"]
    _check(problems, isinstance(skew.get("aligned"), int),
           "step_skew.aligned: not an int")
    for f in ("max_spread_ms", "p50_spread_ms", "max_rel"):
        _check(problems,
               skew.get(f) is None or isinstance(skew[f], _NUM),
               f"step_skew.{f}: not numeric or null")
    for op, row in obj["collectives"].items():
        if op not in COMM_OPS:
            problems.append(f"collectives: unknown collective {op!r}")
            continue
        if not isinstance(row, dict):
            problems.append(f"collectives.{op}: not an object")
            continue
        for f in ("calls", "bytes", "timed_calls", "timed_bytes"):
            _check(problems, isinstance(row.get(f), int),
                   f"collectives.{op}.{f}: not an int")
        _check(problems, isinstance(row.get("dur_ms"), _NUM),
               f"collectives.{op}.dur_ms: not numeric")
        for f in ("achieved_gbps", "busbw_gbps", "peak_gbps"):
            _check(problems,
                   row.get(f) is None or isinstance(row[f], _NUM),
                   f"collectives.{op}.{f}: not numeric or null")
        spread = row.get("arrival_spread_ms")
        _check(problems,
               spread is None or (
                   isinstance(spread, dict) and
                   isinstance(spread.get("p50"), _NUM) and
                   isinstance(spread.get("max"), _NUM)),
               f"collectives.{op}.arrival_spread_ms: malformed")
    strag = obj["straggler"]
    _check(problems,
           strag.get("rank") is None or isinstance(strag["rank"], int),
           "straggler.rank: not an int or null")
    _check(problems,
           strag.get("metric") in (None, "step_time", "collective_entry"),
           f"straggler.metric: unknown metric {strag.get('metric')!r}")
    _check(problems, isinstance(strag.get("threshold"), _NUM),
           "straggler.threshold: not numeric")
    _check(problems, isinstance(strag.get("window"), int),
           "straggler.window: not an int")
    per_rank = strag.get("per_rank")
    if not isinstance(per_rank, dict):
        problems.append("straggler.per_rank: not an object")
    else:
        for r, row in per_rank.items():
            ok = (isinstance(row, dict) and
                  isinstance(row.get("steps"), int) and
                  (row.get("median_step_ms") is None or
                   isinstance(row["median_step_ms"], _NUM)) and
                  isinstance(row.get("mean_entry_delay_ms"), _NUM))
            _check(problems, ok,
                   f"straggler.per_rank[{r!r}]: malformed row")
    return problems


def validate_cluster_file(path):
    with open(path) as f:
        try:
            obj = json.load(f)
        except ValueError as e:
            return [f"not valid JSON: {e}"]
    return validate_cluster_payload(obj)


# ----------------------------------------------------------------------
# perf-regression ledger (bench.py appends; scripts/ds_perf_diff.py reads)
# ----------------------------------------------------------------------
# One row per (run, bench, metric): ``run`` groups every metric a single
# bench.py invocation recorded, so ds_perf_diff.py can baseline on prior
# runs and diff the latest against them.
LEDGER_REQUIRED = {"ts": _NUM, "run": str, "bench": str, "metric": str,
                   "value": _NUM}
LEDGER_OPTIONAL = {"unit": str}


def validate_ledger_row(row):
    """Validate one decoded ledger row.  Returns a list of problem
    strings (empty = valid)."""
    problems = []
    if not isinstance(row, dict):
        return [f"row is {type(row).__name__}, not an object"]
    for field, types in LEDGER_REQUIRED.items():
        if field not in row:
            problems.append(f"ledger: missing required field {field!r}")
        elif not isinstance(row[field], types) or \
                isinstance(row[field], bool):
            problems.append(f"ledger: field {field!r} has type "
                            f"{type(row[field]).__name__}")
    allowed = set(LEDGER_REQUIRED) | set(LEDGER_OPTIONAL)
    for field, value in row.items():
        if field not in allowed:
            problems.append(f"ledger: unknown field {field!r}")
        elif field in LEDGER_OPTIONAL and (
                not isinstance(value, LEDGER_OPTIONAL[field])
                or isinstance(value, bool)):
            problems.append(f"ledger: optional field {field!r} has type "
                            f"{type(value).__name__}")
    return problems


def validate_ledger_file(path):
    problems = []
    with open(path) as f:
        for i, line in enumerate(f, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError as e:
                problems.append(f"{path}:{i}: not valid JSON: {e}")
                continue
            for p in validate_ledger_row(row):
                problems.append(f"{path}:{i}: {p}")
    return problems


# ----------------------------------------------------------------------
# autotuning overlays + tune journals (autotuning/controlplane.py)
# ----------------------------------------------------------------------
# A persisted overlay is ``{"overlay": <ds-config fragment>,
# "provenance": {trial, snapshot_hash, objective, ts, knobs}}`` — the
# fragment is deep-merged over the user config at initialize() /
# create_serving_engine() time, and the provenance stamp ties it back to
# the trial + telemetry snapshot that won.
OVERLAY_PROVENANCE = {"trial": str, "snapshot_hash": str,
                      "objective": _NUM, "ts": _NUM, "knobs": dict}


def validate_overlay_payload(obj):
    """Validate one decoded overlay file.  Returns a list of problem
    strings (empty = valid)."""
    problems = []
    if not isinstance(obj, dict):
        return [f"overlay is {type(obj).__name__}, not an object"]
    if not isinstance(obj.get("overlay"), dict):
        problems.append("overlay: missing or non-object 'overlay' fragment")
    prov = obj.get("provenance")
    if not isinstance(prov, dict):
        problems.append("overlay: missing or non-object 'provenance'")
        return problems
    for field, types in OVERLAY_PROVENANCE.items():
        if field not in prov:
            problems.append(
                f"overlay: provenance missing required field {field!r}")
        elif not isinstance(prov[field], types) or \
                isinstance(prov[field], bool):
            problems.append(
                f"overlay: provenance field {field!r} has type "
                f"{type(prov[field]).__name__}")
    return problems


def validate_overlay_file(path):
    with open(path) as f:
        try:
            obj = json.load(f)
        except ValueError as e:
            return [f"{path}: not valid JSON: {e}"]
    return [f"{path}: {p}" for p in validate_overlay_payload(obj)]


def validate_tune_path(path):
    """Validate ``path`` as one overlay JSON file, or as a tune results
    directory (the control plane's ``results_dir``): the overlay (if
    present), every ``events*.jsonl`` tune stream, and every trial
    journal ``*.json``.  Returns ``(problems, artifacts_seen)``."""
    if os.path.isfile(path):
        return validate_overlay_file(path), 1
    problems = []
    seen = 0
    if not os.path.isdir(path):
        return [f"{path}: not a file or directory"], 0
    for stream in sorted(glob.glob(os.path.join(path, "**",
                                                "events*.jsonl"),
                                   recursive=True)):
        seen += 1
        for i, p in validate_file(stream):
            problems.append(f"{stream}:{i}: {p}")
    for jpath in sorted(glob.glob(os.path.join(path, "*.json"))):
        seen += 1
        if os.path.basename(jpath) == "overlay.json":
            problems.extend(validate_overlay_file(jpath))
            continue
        with open(jpath) as f:
            try:
                obj = json.load(f)
            except ValueError as e:
                problems.append(f"{jpath}: not valid JSON: {e}")
                continue
        if not isinstance(obj, dict) or \
                not isinstance(obj.get("ds_config"), dict):
            problems.append(
                f"{jpath}: trial journal missing ds_config object")
    if not seen:
        problems.append(f"{path}: no tune artifacts found")
    return problems, seen


# ----------------------------------------------------------------------
# incident bundles (monitor/incidents.py IncidentManager._write_bundle)
# ----------------------------------------------------------------------
# Each bundle is a directory ``<bundle_dir>/<inc-NNNN-kind>/`` holding
# ``incident.json`` (the typed bundle) + ``ring.jsonl`` (the flight
# recorder's dump, one schema-valid event per line).
INCIDENT_BUNDLE_FILES = ("incident.json", "ring.jsonl")


def validate_incident_bundle(dirpath):
    """Validate one incident bundle directory.  Returns a list of
    problem strings (empty = valid)."""
    problems = []
    inc_path = os.path.join(dirpath, "incident.json")
    ring_path = os.path.join(dirpath, "ring.jsonl")
    if not os.path.isfile(inc_path):
        return [f"{dirpath}: missing incident.json"]
    with open(inc_path) as f:
        try:
            obj = json.load(f)
        except ValueError as e:
            return [f"{inc_path}: not valid JSON: {e}"]
    if not isinstance(obj, dict):
        return [f"{inc_path}: bundle is {type(obj).__name__}, not an object"]
    _check(problems, isinstance(obj.get("id"), str) and obj.get("id"),
           f"{inc_path}: missing or non-string id")
    _check(problems,
           isinstance(obj.get("ts"), _NUM) and
           not isinstance(obj.get("ts"), bool),
           f"{inc_path}: missing or non-numeric ts")
    trig = obj.get("trigger")
    if not isinstance(trig, dict):
        problems.append(f"{inc_path}: trigger is not an object")
    else:
        _check(problems, trig.get("kind") in INCIDENT_TRIGGERS,
               f"{inc_path}: unknown trigger kind {trig.get('kind')!r}")
        _check(problems, isinstance(trig.get("source"), str),
               f"{inc_path}: trigger.source is not a string")
    reg = obj.get("registry")
    if not isinstance(reg, dict):
        problems.append(f"{inc_path}: registry is not an object")
    else:
        for f_ in ("counters", "gauges", "histograms"):
            _check(problems, isinstance(reg.get(f_), dict),
                   f"{inc_path}: registry.{f_} is not an object")
    corr = obj.get("correlation")
    if not isinstance(corr, dict):
        problems.append(f"{inc_path}: correlation is not an object")
    else:
        _check(problems,
               isinstance(corr.get("window_s"), _NUM) and
               not isinstance(corr.get("window_s"), bool),
               f"{inc_path}: correlation.window_s is not numeric")
        _check(problems, isinstance(corr.get("windows"), list),
               f"{inc_path}: correlation.windows is not a list")
        _check(problems, isinstance(corr.get("links"), list),
               f"{inc_path}: correlation.links is not a list")
    ring = obj.get("ring")
    if not isinstance(ring, dict):
        problems.append(f"{inc_path}: ring is not an object")
    else:
        _check(problems,
               isinstance(ring.get("events"), int) and
               not isinstance(ring.get("events"), bool),
               f"{inc_path}: ring.events is not an int")
        _check(problems, isinstance(ring.get("path"), str),
               f"{inc_path}: ring.path is not a string")
    if not os.path.isfile(ring_path):
        problems.append(f"{dirpath}: missing ring.jsonl")
    else:
        for i, p in validate_file(ring_path):
            problems.append(f"{ring_path}:{i}: {p}")
    return problems


def validate_incidents_path(path):
    """Validate ``path`` as one bundle directory, or as a parent
    ``incidents/`` directory of bundles.  Returns ``(problems,
    bundles_seen)``."""
    if os.path.isfile(os.path.join(path, "incident.json")):
        return validate_incident_bundle(path), 1
    problems = []
    bundles = 0
    for entry in sorted(os.listdir(path) if os.path.isdir(path) else []):
        sub = os.path.join(path, entry)
        if os.path.isfile(os.path.join(sub, "incident.json")):
            bundles += 1
            problems.extend(validate_incident_bundle(sub))
    if not bundles:
        problems.append(f"{path}: no incident bundles found")
    return problems, bundles


# ----------------------------------------------------------------------
# exporter metric-name validation (monitor/export.py)
# ----------------------------------------------------------------------
# Prometheus text exposition format 0.0.4, the exporter's /metrics
# surface.  Every exported family name must match the metric-name
# grammar, carry a known TYPE, and every sample must belong to a typed
# family (summaries also own their _sum/_count companions).
PROM_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
PROM_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^{}]*\})?\s+(\S+)$")
PROM_TYPES = ("counter", "gauge", "summary", "histogram", "untyped")


def validate_prom_exposition(text):
    """Validate a Prometheus text exposition page (the exporter's
    ``/metrics`` body).  Returns a list of problem strings (empty =
    valid)."""
    problems = []
    typed = set()
    for i, line in enumerate(text.splitlines(), start=1):
        line = line.rstrip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) != 4:
                problems.append(f"line {i}: malformed TYPE line")
                continue
            _, _, name, ptype = parts
            if not PROM_NAME_RE.match(name):
                problems.append(f"line {i}: illegal metric name {name!r}")
            if ptype not in PROM_TYPES:
                problems.append(f"line {i}: unknown type {ptype!r}")
            typed.add(name)
            continue
        if line.startswith("#"):
            continue    # HELP / comments
        m = PROM_SAMPLE_RE.match(line)
        if not m:
            problems.append(f"line {i}: malformed sample line {line!r}")
            continue
        name, _, value = m.group(1), m.group(2), m.group(3)
        try:
            float(value)
        except ValueError:
            if value not in ("+Inf", "-Inf", "NaN"):
                problems.append(
                    f"line {i}: non-numeric sample value {value!r}")
        family = name
        for suffix in ("_sum", "_count", "_bucket"):
            if name.endswith(suffix) and name[:-len(suffix)] in typed:
                family = name[:-len(suffix)]
                break
        if family not in typed:
            problems.append(
                f"line {i}: sample {name!r} has no TYPE declaration")
    return problems


def validate_prom_file(path):
    with open(path) as f:
        return validate_prom_exposition(f.read())


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    if not argv:
        print(__doc__)
        return 2
    if argv[0] == "--prom":
        bad = 0
        for path in argv[1:]:
            for p in validate_prom_file(path):
                print(f"{path}: {p}")
                bad += 1
        if bad:
            print(f"FAIL: {bad} problem(s)")
            return 1
        print("OK: exposition validated")
        return 0
    if argv[0] == "--shards":
        bad = shards = 0
        for shard_dir in argv[1:]:
            problems, n = validate_shard_dir(shard_dir)
            shards += n
            for p in problems:
                print(p)
                bad += 1
        if bad:
            print(f"FAIL: {bad} problem(s) across {shards} shard(s)")
            return 1
        print(f"OK: {shards} shard(s) validated")
        return 0
    if argv[0] == "--ledger":
        bad = 0
        for path in argv[1:]:
            for p in validate_ledger_file(path):
                print(p)
                bad += 1
        if bad:
            print(f"FAIL: {bad} problem(s)")
            return 1
        print("OK: ledger validated")
        return 0
    if argv[0] == "--cluster":
        bad = 0
        for path in argv[1:]:
            for p in validate_cluster_file(path):
                print(f"{path}: {p}")
                bad += 1
        if bad:
            print(f"FAIL: {bad} problem(s)")
            return 1
        print("OK: cluster payload validated")
        return 0
    if argv[0] == "--tune":
        bad = artifacts = 0
        for path in argv[1:]:
            problems, n = validate_tune_path(path)
            artifacts += n
            for p in problems:
                print(p)
                bad += 1
        if bad:
            print(f"FAIL: {bad} problem(s) across {artifacts} artifact(s)")
            return 1
        print(f"OK: {artifacts} tune artifact(s) validated")
        return 0
    if argv[0] == "--incidents":
        bad = bundles = 0
        for path in argv[1:]:
            problems, n = validate_incidents_path(path)
            bundles += n
            for p in problems:
                print(p)
                bad += 1
        if bad:
            print(f"FAIL: {bad} problem(s) across {bundles} bundle(s)")
            return 1
        print(f"OK: {bundles} bundle(s) validated")
        return 0
    bad = 0
    total = 0
    for path in argv:
        with open(path) as f:
            for i, line in enumerate(f, start=1):
                if not line.strip():
                    continue
                total += 1
                try:
                    event = json.loads(line)
                    problems = validate_event(event)
                except ValueError as e:
                    problems = [f"not valid JSON: {e}"]
                for p in problems:
                    print(f"{path}:{i}: {p}")
                    bad += 1
    if bad:
        print(f"FAIL: {bad} problem(s) across {total} event(s)")
        return 1
    print(f"OK: {total} event(s) validated")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
