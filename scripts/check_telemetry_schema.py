#!/usr/bin/env python
"""Frozen schema for the unified telemetry JSONL event stream.

Every line ``deepspeed_tpu/monitor/telemetry.py`` emits must validate
against the per-kind schema below.  The schema is FROZEN: adding an event
kind or a field means editing this file in the same change, and the tier-1
test (``tests/unit/test_telemetry_schema.py``) diffs ``EVENT_KINDS``
against the telemetry module so the two cannot drift silently.

Usage:
    python scripts/check_telemetry_schema.py <events.jsonl> [more.jsonl ...]

Exit code 0 when every event on every file validates; 1 otherwise (each
offending line is reported with its file:lineno).
"""

import json
import sys

# required: field -> allowed types.  optional: same, may be absent.
# Unknown kinds AND unknown fields are rejected — the stream is a contract.
_NUM = (int, float)

SCHEMA = {
    "span": {
        "required": {"ts": _NUM, "kind": str, "name": str, "dur_ms": _NUM},
        "optional": {"step": int, "attrs": dict},
    },
    "gauge": {
        "required": {"ts": _NUM, "kind": str, "name": str, "value": _NUM,
                     "peak": _NUM},
        "optional": {"step": int},
    },
    "counter": {
        "required": {"ts": _NUM, "kind": str, "name": str, "value": _NUM},
        "optional": {"step": int},
    },
    "comm": {
        "required": {"ts": _NUM, "kind": str, "name": str, "bytes": int,
                     "axis": str},
        "optional": {},
    },
    "heartbeat": {
        "required": {"ts": _NUM, "kind": str, "name": str, "step": int},
        "optional": {"step_ms": _NUM},
    },
    "stall": {
        "required": {"ts": _NUM, "kind": str, "name": str, "step": int,
                     "gap_s": _NUM, "median_step_s": _NUM,
                     "threshold_s": _NUM},
        "optional": {},
    },
    "meta": {
        "required": {"ts": _NUM, "kind": str, "name": str},
        "optional": {"attrs": dict, "step": int},
    },
    # fault-tolerance events (runtime/resilience.py): I/O retries
    # ("fault/retry", "fault/dataloader_retry"), checkpoint fallback
    # ("fault/ckpt_fallback"), preemption ("fault/preempt_requested",
    # "fault/preempted"), divergence ("fault/divergence",
    # "fault/auto_restore")
    "fault": {
        "required": {"ts": _NUM, "kind": str, "name": str},
        "optional": {"attrs": dict, "step": int},
    },
    # serving-robustness events (inference/robustness.py): admission
    # ("serve/admit"), typed rejection ("serve/reject"), load shedding
    # ("serve/shed"), deadline cancels ("serve/deadline"), per-slot fault
    # eviction ("serve/evict"), graceful drain ("serve/drain"), normal
    # completion ("serve/finish"), and recovered transient faults
    # ("serve/fault").  Typed reasons ride in attrs["reason"].  The
    # ``name`` field is validated against SERVE_EVENTS below.
    "serve": {
        "required": {"ts": _NUM, "kind": str, "name": str},
        "optional": {"attrs": dict, "step": int},
    },
}

# FROZEN vocabulary of serve-kind event names — must stay byte-identical
# to ``deepspeed_tpu.inference.robustness.SERVE_EVENTS`` (the tier-1 test
# diffs the two).  The prefix_* names belong to the prefix-cache subsystem
# (inference/prefix_cache.py): cached-page attach hits, copy-on-write
# copies, newly indexed pages, and reclaim-tier evictions.
# "serve/backend" records the attention backend an engine was built with
# (attrs: attention_backend / impl / interpret) so the stream's serve/step
# spans are attributable to the kernel path that produced them.
SERVE_EVENTS = (
    "serve/admit", "serve/reject", "serve/shed", "serve/deadline",
    "serve/evict", "serve/drain", "serve/finish", "serve/fault",
    "serve/prefix_hit", "serve/prefix_cow", "serve/prefix_insert",
    "serve/prefix_evict",
    "serve/backend",
)

EVENT_KINDS = tuple(SCHEMA)


def validate_event(event):
    """Validate one decoded event dict.  Returns a list of problem strings
    (empty = valid)."""
    problems = []
    if not isinstance(event, dict):
        return [f"event is {type(event).__name__}, not an object"]
    kind = event.get("kind")
    if kind not in SCHEMA:
        return [f"unknown kind {kind!r}"]
    spec = SCHEMA[kind]
    for field, types in spec["required"].items():
        if field not in event:
            problems.append(f"{kind}: missing required field {field!r}")
        elif not isinstance(event[field], types) or \
                isinstance(event[field], bool):
            problems.append(
                f"{kind}: field {field!r} has type "
                f"{type(event[field]).__name__}")
    allowed = set(spec["required"]) | set(spec["optional"])
    for field, value in event.items():
        if field not in allowed:
            problems.append(f"{kind}: unknown field {field!r}")
        elif field in spec["optional"] and (
                not isinstance(value, spec["optional"][field])
                or isinstance(value, bool)):
            problems.append(
                f"{kind}: optional field {field!r} has type "
                f"{type(value).__name__}")
    if kind == "serve" and isinstance(event.get("name"), str) and \
            event["name"] not in SERVE_EVENTS:
        problems.append(f"serve: unknown event name {event['name']!r}")
    return problems


def validate_stream(lines):
    """Validate an iterable of JSONL lines.  Yields (lineno, problem)
    pairs; empty/whitespace lines are skipped."""
    for i, line in enumerate(lines, start=1):
        line = line.strip()
        if not line:
            continue
        try:
            event = json.loads(line)
        except ValueError as e:
            yield i, f"not valid JSON: {e}"
            continue
        for p in validate_event(event):
            yield i, p


def validate_file(path):
    with open(path) as f:
        return list(validate_stream(f))


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    if not argv:
        print(__doc__)
        return 2
    bad = 0
    total = 0
    for path in argv:
        with open(path) as f:
            for i, line in enumerate(f, start=1):
                if not line.strip():
                    continue
                total += 1
                try:
                    event = json.loads(line)
                    problems = validate_event(event)
                except ValueError as e:
                    problems = [f"not valid JSON: {e}"]
                for p in problems:
                    print(f"{path}:{i}: {p}")
                    bad += 1
    if bad:
        print(f"FAIL: {bad} problem(s) across {total} event(s)")
        return 1
    print(f"OK: {total} event(s) validated")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
