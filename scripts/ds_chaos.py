#!/usr/bin/env python
"""Chaos campaign runner for the cross-process fleet (gate 10).

Sweeps gray-failure scenarios over a REAL 2-worker subprocess fleet on
the deterministic ``tiny_engine_factory`` spec, with every fault driven
by the seeded :class:`WireFaultInjector` (``serving.fleet.transport.
chaos``) — the whole campaign replays from ``(scenario, seed)`` alone,
no wall-clock races.  Each scenario must end with:

* ZERO lost requests — every submitted id reaches exactly one typed
  tracer terminal (``finished`` xor ``pop_terminated``);
* an empty fleet ``leak_report()``;
* survivors BIT-IDENTICAL to the no-fault in-process reference (a
  request's output depends only on prompt/params/seed, never on which
  replica, retry, or dispatch attempt served it);
* the scenario's own expectations (retries absorbed, breaker opened
  and closed without a respawn, duplicate calls dropped, exactly one
  committed migration, ...);
* a schema-clean telemetry stream (``check_telemetry_schema.py`` over
  the run's events.jsonl).

Scenarios::

    ack_loss      worker admits, the ack frame is dropped — the channel
                  retry replays under the same idempotency key and the
                  worker dedups (one admission, one terminal)
    dup_dispatch  the add_request frame is duplicated on the wire — the
                  worker's call-id cache resends the cached response
                  instead of double-admitting
    slow_worker   consecutive step timeouts trip the per-replica
                  circuit breaker: fenced WITHOUT a kill, half-open
                  probe rejoins, zero respawns
    torn_commit   the commit_import ack is dropped mid-migration — the
                  retried commit converges exactly-once (one committed
                  migration, source unpinned once)
    reorder       a step reply is held back past its call's timeout —
                  the late frame is discarded by call id and the
                  cumulative ack redelivers the work
    flap          a link that fails every Nth call — breaker hysteresis
                  (doubling cooldown inside the flap window) keeps the
                  fleet from respawn-storming

Usage::

    python scripts/ds_chaos.py --scenarios ack_loss,slow_worker
    python scripts/ds_chaos.py --scenarios all --seed 7 -v
"""

import argparse
import importlib.util
import json
import os
import shutil
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

SPEC = {"factory":
        "deepspeed_tpu.inference.fleet_worker:tiny_engine_factory",
        "kwargs": {}}

# Short per-RPC wall budget so an injected drop times out in CI time;
# the heartbeat deadline stays LARGE so the breaker — not heartbeat
# death — owns every gray verdict in these scenarios.
BASE_TRANSPORT = {"mode": "subprocess",
                  "heartbeat_interval_s": 0.2,
                  "heartbeat_deadline_s": 60.0,
                  "call_timeout_s": 30.0}


def _load_checker():
    path = os.path.join(REPO, "scripts", "check_telemetry_schema.py")
    spec = importlib.util.spec_from_file_location(
        "check_telemetry_schema", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _prompts(seed, n=4):
    """Deterministic prompt set sharing a family prefix (exercises the
    prefix cache + migration dedup paths)."""
    import numpy as np

    from deepspeed_tpu.models.transformer import TransformerConfig
    vocab = TransformerConfig.tiny(hidden_size=64, n_heads=4,
                                   n_kv_heads=2).vocab_size
    rng = np.random.default_rng(seed)
    fam = rng.integers(0, vocab, (24,)).tolist()
    return {f"c{i}": fam + rng.integers(0, vocab, (4,)).tolist()
            for i in range(n)}


def _submit_all(router, prompts):
    for rid, p in sorted(prompts.items()):
        router.submit(rid, p, max_new_tokens=6, temperature=0.7, seed=11)


def _drive(router, max_steps=2000, wall_s=180.0, settle=None):
    """Step the fleet until every request resolves (typed terminal or
    finish) AND the optional ``settle`` predicate holds (breaker
    scenarios keep stepping until the half-open probe has decided) —
    bounded by steps AND wall clock so a broken scenario fails loudly
    instead of hanging the gate."""
    deadline = time.monotonic() + wall_s
    for _ in range(max_steps):
        router.step()
        if not router._unresolved() and \
                (settle is None or settle(router)):
            return
        if time.monotonic() > deadline:
            break
    raise AssertionError(
        f"fleet did not converge: {router._unresolved()} unresolved, "
        f"settle={settle is None or settle(router)} "
        f"after {router.steps} steps")


def reference_outputs(prompts, roles=None):
    """No-fault IN-PROCESS reference over the identical factory — the
    bit-identity oracle for every chaos scenario."""
    from deepspeed_tpu.inference.fleet import FleetRouter
    from deepspeed_tpu.inference.fleet_worker import tiny_engine_factory
    fleet = {"replicas": 2, "health_interval": 1000}
    if roles:
        fleet = dict(roles, health_interval=1000)
    router = FleetRouter(tiny_engine_factory, fleet=fleet)
    try:
        _submit_all(router, prompts)
        _drive(router)
        term = router.pop_terminated()
        leaks = router.leak_report()
        assert not term and leaks == {}, \
            f"reference run not clean: term={term} leaks={leaks}"
        return dict(router.finished)
    finally:
        router.close()


def run_scenario(name, seed=0, out_dir=None, verbose=False):
    """Run ONE chaos scenario end to end; returns the result dict
    (stats, events, retry/breaker counters) after asserting the
    zero-loss / exactly-once / bit-identity bar.  Raises
    ``AssertionError`` on any violation."""
    from deepspeed_tpu.inference.fleet import FleetRouter
    from deepspeed_tpu.monitor.telemetry import Telemetry
    from deepspeed_tpu.runtime.config import TelemetryConfig

    scen = SCENARIOS[name]
    prompts = _prompts(seed + 5)
    ref = reference_outputs(prompts, roles=scen.get("roles"))

    transport = dict(BASE_TRANSPORT)
    transport.update(scen.get("transport") or {})
    chaos = {k: dict(v) for k, v in (scen.get("chaos") or {}).items()}
    if chaos:
        chaos["seed"] = seed
    transport["chaos"] = chaos
    fleet = {"replicas": 2, "health_interval": 1000,
             "transport": transport}
    if scen.get("roles"):
        fleet = dict(scen["roles"], health_interval=1000,
                     transport=transport)

    tmp = out_dir or tempfile.mkdtemp(prefix=f"ds_chaos_{name}_")
    tel = Telemetry().configure(TelemetryConfig(
        {"enabled": True, "output_path": tmp, "job_name": name,
         "incidents": {"enabled": True, "cooldown_s": 0.0}}), rank=0)
    t0 = time.monotonic()
    router = FleetRouter(SPEC, fleet=fleet, telemetry=tel)
    try:
        _submit_all(router, prompts)
        _drive(router, settle=scen.get("settle"))
        finished = dict(router.finished)
        term = router.pop_terminated()
        leaks = router.leak_report()
        stats = dict(router.stats)
    finally:
        router.close()
        tel.close()
    elapsed = time.monotonic() - t0

    # -- the campaign bar (every scenario) ----------------------------
    assert leaks == {}, f"{name}: leak_report not empty: {leaks}"
    assert set(finished) | set(term) == set(prompts), \
        f"{name}: lost requests: " \
        f"{set(prompts) - set(finished) - set(term)}"
    assert not (set(finished) & set(term)), \
        f"{name}: double terminal: {set(finished) & set(term)}"
    for rid, toks in finished.items():
        assert toks == ref[rid], \
            f"{name}: {rid} diverged from the no-fault reference"

    # -- schema-clean, expected-event-bearing telemetry ---------------
    events_path = os.path.join(tmp, name, "events.jsonl")
    checker = _load_checker()
    problems = checker.validate_file(events_path)
    assert problems == [], f"{name}: schema problems: {problems[:5]}"
    with open(events_path) as f:
        events = [json.loads(ln) for ln in f if ln.strip()]

    result = {"scenario": name, "seed": seed, "elapsed_s": elapsed,
              "finished": len(finished), "terminated": len(term),
              "stats": stats, "events": events}
    scen["check"](result)
    if out_dir is None:
        shutil.rmtree(tmp, ignore_errors=True)
    if verbose:
        print(f"  stats: retries={stats['retries']} "
              f"rpc_timeouts={stats['rpc_timeouts']} "
              f"breaker={stats['breaker_opens']}/"
              f"{stats['breaker_closes']} "
              f"dup_dropped={stats['dup_calls_dropped']} "
              f"workers_lost={stats['workers_lost']} "
              f"respawns={stats['respawns']}")
    return result


def _count(events, kind, name=None, trigger=None):
    return sum(1 for e in events
               if e.get("kind") == kind
               and (name is None or e.get("name") == name)
               and (trigger is None or e.get("trigger") == trigger))


# -- per-scenario expectations ----------------------------------------
def _check_ack_loss(res):
    st, ev = res["stats"], res["events"]
    assert st["retries"] >= 1, "ack loss never retried"
    assert st["dup_calls_dropped"] >= 1, \
        "worker never deduped the replayed admission"
    assert st["workers_lost"] == 0 and st["respawns"] == 0
    assert _count(ev, "fleet", "fleet/retry") >= 1
    assert _count(ev, "fleet", "fleet/dup_call_dropped") >= 1


def _check_dup_dispatch(res):
    st, ev = res["stats"], res["events"]
    assert st["dup_calls_dropped"] >= 1, \
        "duplicated dispatch was not dropped anywhere"
    assert st["workers_lost"] == 0 and st["respawns"] == 0
    assert _count(ev, "fleet", "fleet/dup_call_dropped") >= 1


def _check_slow_worker(res):
    st, ev = res["stats"], res["events"]
    assert st["breaker_opens"] == 1, \
        f"expected exactly one breaker open, got {st['breaker_opens']}"
    assert st["breaker_closes"] == 1, "breaker never rejoined"
    assert st["workers_lost"] == 0 and st["respawns"] == 0, \
        "a slow worker must NOT be killed or respawned"
    assert _count(ev, "fleet", "fleet/breaker_open") == 1
    assert _count(ev, "fleet", "fleet/breaker_close") == 1
    # breaker/liveness composition: one gray failure, ONE incident
    # bundle — the open fires a breaker_open bundle and heartbeat
    # death stays out of it entirely
    assert _count(ev, "incident", "incident/open",
                  trigger="breaker_open") == 1
    assert _count(ev, "incident", trigger="worker_lost") == 0


def _check_torn_commit(res):
    st, ev = res["stats"], res["events"]
    assert st["migrations"] >= 1, "no migration ever committed"
    assert st["dup_calls_dropped"] >= 1, \
        "torn commit ack was not converged by idempotency-key replay"
    assert st["migrate_commit_faults"] == 0, \
        "channel-level retry should absorb the torn ack before the " \
        "router books a commit fault"
    assert st["workers_lost"] == 0 and st["respawns"] == 0
    # exactly one committed migration per migrated request: commits
    # counted once, and the dup drop proves the retry was a replay
    assert _count(ev, "fleet", "fleet/migrate_commit") == \
        st["migrations"]


def _check_reorder(res):
    st, ev = res["stats"], res["events"]
    assert st["rpc_timeouts"] >= 1, "held frame never timed a call out"
    assert st["dup_calls_dropped"] >= 1, \
        "the late reply should be discarded by call id"
    assert st["workers_lost"] == 0 and st["respawns"] == 0
    assert _count(ev, "fleet", "fleet/dup_call_dropped") >= 1


def _check_flap(res):
    st, ev = res["stats"], res["events"]
    assert st["breaker_opens"] >= 2, \
        f"flapping link should re-trip, got {st['breaker_opens']}"
    assert st["breaker_closes"] >= 1
    assert st["workers_lost"] == 0 and st["respawns"] == 0, \
        "hysteresis must keep a flapping link from respawn-storming"
    opens = [e for e in ev if e.get("kind") == "fleet"
             and e.get("name") == "fleet/breaker_open"]
    cools = [e["attrs"]["cooldown_s"] for e in opens]
    assert cools == sorted(cools) and cools[-1] > cools[0], \
        f"flap cooldowns must escalate, got {cools}"


def _no_open_breakers(router):
    return all(r.state != "breaker_open"
               for r in router.replicas.values())


# Drop scenarios pay one call_timeout_s wall wait per injected drop —
# 8s keeps the campaign fast while staying safely above the worker's
# first-step jit compile (init has its own init_timeout_s budget).
_DROP_TIMEOUT = 8.0

SCENARIOS = {
    # worker admits, ack dropped → channel retry → ikey dedup.  No
    # replica filter: routing affinity may place the first admission on
    # either worker, and the op filter alone is deterministic (the
    # router is single-threaded).
    "ack_loss": {
        "chaos": {"wire_recv": {"drop_at": [0], "ops": ["add_request"]}},
        "transport": {"call_timeout_s": _DROP_TIMEOUT,
                      "retry": {"max_retries": 2, "backoff_s": 0.02,
                                "backoff_max_s": 0.1}},
        "check": _check_ack_loss,
    },
    # request frame duplicated → worker cid-cache resends, router
    # drops the extra reply as stale
    "dup_dispatch": {
        "chaos": {"wire_send": {"dup_at": [0], "ops": ["add_request"]}},
        "transport": {"call_timeout_s": _DROP_TIMEOUT},
        "check": _check_dup_dispatch,
    },
    # two consecutive step timeouts trip the breaker; the half-open
    # ping (not a step — the chaos op filter skips it) rejoins.  The
    # rpc_timeout site fires BEFORE anything is sent, so no wall-clock
    # wait and no counter noise from the other replica's traffic.
    "slow_worker": {
        "chaos": {"rpc_timeout": {"action": "timeout", "times": 2,
                                  "ops": ["step"], "replicas": ["r0"]}},
        "transport": {"retry": {"max_retries": 0},
                      "breaker_failures": 2, "breaker_open_s": 0.2,
                      "breaker_probe_timeout_s": 5.0},
        "settle": lambda r: (r.stats["breaker_closes"] >= 1 and
                             _no_open_breakers(r)),
        "check": _check_slow_worker,
    },
    # disaggregated fleet; the commit_import ACK is dropped — the
    # idempotent retry must converge to exactly one committed
    # migration with the source unpinned exactly once
    "torn_commit": {
        "roles": {"roles": {"enabled": True, "prefill_replicas": 1,
                            "decode_replicas": 1,
                            "page_transfer_budget": 1}},
        "chaos": {"wire_recv": {"drop_at": [0],
                                "ops": ["commit_import"]}},
        "transport": {"call_timeout_s": _DROP_TIMEOUT,
                      "retry": {"max_retries": 2, "backoff_s": 0.02,
                                "backoff_max_s": 0.1}},
        "check": _check_torn_commit,
    },
    # a step reply held past its timeout: the NEXT call's reply
    # releases it and the stale frame is discarded by cid; cumulative
    # acks redeliver the first step's tokens
    "reorder": {
        "chaos": {"wire_recv": {"reorder_at": [0], "ops": ["step"],
                                "replicas": ["r0"]}},
        "transport": {"call_timeout_s": _DROP_TIMEOUT},
        "check": _check_reorder,
    },
    # every 3rd step call to r0 times out: breaker_failures=1 trips
    # instantly, the flap window doubles each cooldown, and the fleet
    # never respawns
    "flap": {
        "chaos": {"rpc_timeout": {"action": "timeout", "every": 3,
                                  "ops": ["step"], "replicas": ["r0"]}},
        "transport": {"retry": {"max_retries": 0},
                      "breaker_failures": 1, "breaker_open_s": 0.05,
                      "breaker_open_max_s": 5.0,
                      "breaker_flap_window_s": 60.0},
        "settle": lambda r: (r.stats["breaker_opens"] >= 2 and
                             _no_open_breakers(r)),
        "check": _check_flap,
    },
}


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="deterministic wire-chaos campaign over the "
                    "2-worker subprocess fleet")
    ap.add_argument("--scenarios", default="all",
                    help="comma-separated scenario names, or 'all' "
                         f"(have: {', '.join(SCENARIOS)})")
    ap.add_argument("--seed", type=int, default=0,
                    help="campaign seed (prompts + injector rng)")
    ap.add_argument("--out", default=None,
                    help="keep per-scenario telemetry under this dir")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)

    names = (list(SCENARIOS) if args.scenarios == "all"
             else [s.strip() for s in args.scenarios.split(",")
                   if s.strip()])
    unknown = [n for n in names if n not in SCENARIOS]
    if unknown:
        ap.error(f"unknown scenarios {unknown} "
                 f"(have: {', '.join(SCENARIOS)})")

    failures = 0
    for name in names:
        print(f"[ds_chaos] {name} (seed {args.seed}) ...", flush=True)
        out_dir = (os.path.join(args.out, name) if args.out else None)
        if out_dir:
            os.makedirs(out_dir, exist_ok=True)
        try:
            res = run_scenario(name, seed=args.seed, out_dir=out_dir,
                               verbose=args.verbose)
        except AssertionError as e:
            failures += 1
            print(f"[ds_chaos] {name}: FAIL — {e}", flush=True)
            continue
        print(f"[ds_chaos] {name}: ok "
              f"({res['finished']} finished, {res['terminated']} "
              f"typed terminals, {res['elapsed_s']:.1f}s)", flush=True)
    if failures:
        print(f"[ds_chaos] {failures}/{len(names)} scenarios FAILED")
        return 1
    print(f"[ds_chaos] campaign green: {len(names)} scenarios, "
          f"zero lost requests, bit-identical survivors")
    return 0


if __name__ == "__main__":
    sys.exit(main())
