#!/usr/bin/env python
"""Round-3 on-chip measurement program — one shot, fully journaled.

The TPU tunnel has been flaky for two rounds; this script exists so that
ANY window of tunnel uptime converts into committed artifacts.  Run it the
moment a probe succeeds:

    python scripts/onchip_r03.py            # everything
    python scripts/onchip_r03.py --only kernels,sweep,bench

Each step runs in a subprocess with its own timeout; failures journal and
the program continues.  Results land in ``ONCHIP_r03/`` (JSON per step +
``journal.jsonl``) — commit that directory.

Steps:
  probe    — device sanity (platform, kind, tiny matmul)
  kernels  — Pallas flash alibi/sliding-window fwd+bwd vs jnp oracle with
             interpret=False (round-2: interpret-green != Mosaic-green)
  sweep    — attn_block_q/k sweep on gpt_350m (the queued round-2 sweep)
  bench    — bench.py (headline; persists BENCH_onchip_latest.json)
  serving  — ds_bench inference (p50/p90/p99) + serving throughput
  big      — gpt2_1_5b ZeRO-3 + host-offload Adam + remat (MFU at >=1B)
  tune     — short on-chip autotune (phase 1+2, tight budget)
"""

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "ONCHIP_r03")
JOURNAL = os.path.join(OUT, "journal.jsonl")


def log(step, **kw):
    os.makedirs(OUT, exist_ok=True)
    rec = {"step": step, "t": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                            time.gmtime()), **kw}
    with open(JOURNAL, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(f"[onchip] {step}: {kw.get('status', '')}", flush=True)


def run(step, cmd, timeout, env=None):
    t0 = time.time()
    try:
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=timeout, cwd=REPO,
                             env={**os.environ, **(env or {})})
    except subprocess.TimeoutExpired:
        log(step, status="timeout", timeout_s=timeout, cmd=" ".join(cmd))
        return None
    dt = time.time() - t0
    tail = (out.stdout or "")[-4000:]
    if out.returncode != 0:
        log(step, status="failed", rc=out.returncode, wall_s=round(dt, 1),
            stdout=tail, stderr=(out.stderr or "")[-2000:])
        return None
    # journal every JSON line the step printed
    jsons = []
    for line in (out.stdout or "").splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                jsons.append(json.loads(line))
            except json.JSONDecodeError:
                pass
    log(step, status="ok", wall_s=round(dt, 1), results=jsons,
        stdout=None if jsons else tail)
    with open(os.path.join(OUT, f"{step}.json"), "w") as f:
        json.dump({"wall_s": round(dt, 1), "results": jsons,
                   "stdout_tail": tail}, f, indent=1)
    return jsons


_KERNEL_CHECK = r'''
import json, time
import jax, jax.numpy as jnp
from deepspeed_tpu.ops.pallas.flash_attention import flash_attention
from deepspeed_tpu.ops.attention import alibi_window_bias, reference_attention
from deepspeed_tpu.models.transformer import alibi_slopes

dev = jax.devices()[0]
assert dev.platform == "tpu", dev
rng = jax.random.PRNGKey(0)
B, H, S, D = 2, 8, 2048, 64
q, k, v = (jax.random.normal(jax.random.fold_in(rng, i), (B, S, H, D),
                             jnp.bfloat16) for i in range(3))

def check(name, slopes=None, window=None):
    bias = alibi_window_bias(S, S, slopes=slopes, window=window)

    def f(q, k, v):
        return flash_attention(q, k, v, causal=True, interpret=False,
                               alibi_slopes=slopes,
                               window=window).astype(jnp.float32).sum()

    def r(q, k, v):
        return reference_attention(q, k, v, causal=True,
                                   bias=bias).astype(jnp.float32).sum()
    t0 = time.time()
    fv, fg = jax.jit(jax.value_and_grad(f, argnums=(0, 1, 2)))(q, k, v)
    jax.block_until_ready(fg)
    rv, rg = jax.jit(jax.value_and_grad(r, argnums=(0, 1, 2)))(q, k, v)
    jax.block_until_ready(rg)
    rel = max(float(jnp.max(jnp.abs(a.astype(jnp.float32) -
                                    b.astype(jnp.float32))) /
                    (jnp.max(jnp.abs(b.astype(jnp.float32))) + 1e-6))
              for a, b in zip(fg, rg))
    out = {"variant": name,
           "val_rel": abs(float(fv - rv)) / (abs(float(rv)) + 1e-6),
           "grad_rel_max": rel, "ok": rel < 0.05,
           "wall_s": round(time.time() - t0, 1)}
    print(json.dumps(out))
    return out["ok"]

oks = [check("causal"),
       check("alibi", slopes=alibi_slopes(H)),
       check("window", window=256)]
print(json.dumps({"all_ok": all(oks)}))
'''


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--only", default="")
    args = ap.parse_args()
    steps = [s for s in args.only.split(",") if s] or [
        "probe", "kernels", "sweep", "bench", "serving", "big",
        "longseq", "tune"]
    py = sys.executable

    if "probe" in steps:
        ok = run("probe", [py, "-c",
                           "import jax; d=jax.devices()[0]; "
                           "import jax.numpy as jnp; "
                           "x=jnp.ones((256,256),jnp.bfloat16); "
                           "print((x@x).sum()); "
                           "import json; "
                           "print(json.dumps({'platform': d.platform, "
                           "'kind': getattr(d,'device_kind','')}))"],
                 timeout=240)
        if ok is None:
            log("abort", status="no device")
            return 1

    if "kernels" in steps:
        run("kernels", [py, "-c", _KERNEL_CHECK], timeout=1200)

    if "sweep" in steps:
        for bq, bk in ((512, 512), (256, 512), (256, 256), (128, 512)):
            run(f"sweep_b{bq}x{bk}",
                [py, "bin/ds_bench", "train", "--model", "gpt_350m",
                 "--batch", "8", "--gas", "4", "--seq", "1024",
                 "--steps", "8", "--attn-block-q", str(bq),
                 "--attn-block-k", str(bk), "--json"], timeout=1500)

    if "bench" in steps:
        run("bench", [py, "bench.py"], timeout=900,
            env={"BENCH_BUDGET_S": "840"})

    if "serving" in steps:
        run("inference_latency",
            [py, "bin/ds_bench", "inference", "--model", "gpt2-125m",
             "--batch", "1", "--prompt-len", "128", "--max-new-tokens",
             "64", "--trials", "10"], timeout=1500)
        run("serving_throughput",
            [py, "bin/ds_bench", "serving", "--model", "gpt2_125m",
             "--requests", "16", "--max-batch", "8", "--prompt-len", "128",
             "--gen", "64"], timeout=1500)

    if "big" in steps:
        # >=1B on one 16 GB chip with NO offload: bf16 Adam moments (SR)
        # + bf16 grad accum shrink the train state to 8 B/param (the
        # host-offload route moves ~6 GB/step over the tunnel and times
        # out — measured, journal big_1_5b_b4).  gas sweep around the
        # measured MFU-0.486 config; the 1.1B shape is known to hit a
        # pathological near-limit XLA scheduling compile (>30 min,
        # journal big_1_1b timeout) so it goes LAST with a short leash.
        for model, batch, gas, leash in (("gpt_1b", 2, 4, 1500),
                                         ("gpt_1b", 2, 8, 1500),
                                         ("gpt_1_1b", 1, 8, 1200)):
            run(f"big_{model}_b{batch}_gas{gas}",
                [py, "bin/ds_bench", "train", "--model", model,
                 "--batch", str(batch), "--gas", str(gas),
                 "--seq", "1024", "--steps", "8",
                 "--moment-dtype", "bfloat16",
                 "--grad-accum-dtype", "bfloat16", "--json"],
                timeout=leash)

    if "longseq" in steps:
        # long-context single-chip evidence: flash fwd+bwd at S=4096
        # (GPT-350M shape) — the training bench path exercises the Pallas
        # flash kernel end-to-end at 4x the usual sequence
        run("longseq_s4096",
            [py, "bin/ds_bench", "train", "--model", "gpt_350m",
             "--batch", "2", "--gas", "4", "--seq", "4096",
             "--steps", "6", "--json"], timeout=1800)

    if "tune" in steps:
        spec = {"kind": "causal_lm",
                "config": dict(vocab_size=50304, hidden_size=1024,
                               n_layers=24, n_heads=16, max_seq_len=1024,
                               activation="gelu", use_rmsnorm=False,
                               use_rope=False, tie_embeddings=True,
                               remat=True)}
        code = (
            "import json\n"
            "from deepspeed_tpu.autotuning.autotuner import Autotuner\n"
            "at = Autotuner({'train_micro_batch_size_per_gpu': 8,\n"
            "  'optimizer': {'type': 'AdamW', 'params': {'lr': 1e-4}},\n"
            "  'bf16': {'enabled': True},\n"
            "  'autotuning': {'enabled': True,\n"
            "    'results_dir': 'ONCHIP_r03/autotuning_results',\n"
            "    'start_profile_step': 2, 'end_profile_step': 5,\n"
            "    'num_tuning_micro_batch_sizes': 2,\n"
            "    'min_train_micro_batch_size_per_gpu': 8}})\n"
            "at.feasible_stages = lambda dp: [3]\n"
            f"best = at.tune(model_spec={spec!r}, seq=1024,\n"
            "               trial_timeout=1200)\n"
            "print(json.dumps({'best': best}))\n")
        run("tune", [py, "-c", code], timeout=7200)

    log("done", status="complete")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
