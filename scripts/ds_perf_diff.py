#!/usr/bin/env python
"""Perf-regression gate over the bench ledger.

``bench.py`` appends one row per micro-bench metric to a JSONL ledger
(``BENCH_LEDGER.jsonl`` by default; schema frozen in
``scripts/check_telemetry_schema.py --ledger``).  This script compares
the LATEST run against the baseline built from every earlier run — the
per-(bench, metric) median, so one noisy historical run cannot shift the
gate — and exits nonzero when any metric regressed beyond tolerance.

Direction is inferred from the metric name: duration/size metrics
(``*_ms``, ``*_s``, ``*_secs``, ``*_bytes``, ``*_time*``) regress by
going UP; throughput metrics (``*per_sec*``, ``*gbps*``, ``*rate*``,
``*frac*``, ``*tokens*``, ``*flops*``) regress by going DOWN.  Unknown
directions are reported but never gate.

Usage:
    python scripts/ds_perf_diff.py [LEDGER] [--tolerance 0.25] [--json]
    python scripts/ds_perf_diff.py --check [LEDGER]

``--check`` is the CI entry point: it behaves identically when a usable
ledger exists (>= 2 runs) but exits 0 — with a note — when the ledger is
missing or still single-run, so the gate can ride in the tier-1 flow
before any baseline has been seeded.

``--check`` additionally audits baseline FRESHNESS: when the newest
on-chip train evidence (the latest ``bench == "train"`` ledger row, or
``BENCH_onchip_latest.json`` next to the ledger) is older than the last
``--stale-runs`` cpu-only bench runs, it prints an explicit
``STALE-BASELINE`` warning — the cpu gate keeps ratcheting while the
on-chip numbers it is meant to stand in for go quietly out of date.
The warning never changes the exit code; it is a prompt to re-run the
on-chip bench, not a gate.

Exit codes: 0 ok / skipped, 1 regression(s), 2 usage or malformed ledger.
"""

import argparse
import importlib.util
import json
import os
import sys

DEFAULT_LEDGER = os.environ.get(
    "BENCH_LEDGER",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "BENCH_LEDGER.jsonl"))

# exact-name direction overrides, checked BEFORE the substring
# heuristics: the attribution plane's exposed-comm fraction is a "frac"
# the heuristics would read as higher-is-better, but exposed collective
# time is pure loss — and every critical-path stage scalar is a
# millisecond cost even where the suffix heuristic can't see it.
_DIRECTION_OVERRIDES = {
    "exposed_comm_frac": "down",
    "exposed_comm_ms": "down",
    "host_sync_ms": "down",
    "input_wait_ms": "down",
    "queue_ms": "down",
    "migrate_ms": "down",
    "gap_ms": "down",
}

# metric-name direction heuristics: substring/suffix -> True when lower
# is better.  Checked in order; first hit wins.
_LOWER_BETTER = ("_ms", "_s", "_secs", "_seconds", "_bytes")
_HIGHER_BETTER = ("per_sec", "gbps", "rate", "frac", "tokens", "flops",
                  "mfu", "hits")


def _load_checker():
    """Sibling-module import of check_telemetry_schema (scripts/ is not a
    package) for the frozen ledger row schema."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "check_telemetry_schema.py")
    spec = importlib.util.spec_from_file_location("_ds_schema", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def metric_direction(metric):
    """'down' when lower is better, 'up' when higher is better, None when
    the name matches neither heuristic (such metrics never gate)."""
    m = metric.lower()
    for name, direction in _DIRECTION_OVERRIDES.items():
        if m == name or m.endswith("_" + name):
            return direction
    for pat in _HIGHER_BETTER:
        if pat in m:
            return "up"
    if "time" in m:
        return "down"
    for pat in _LOWER_BETTER:
        if m.endswith(pat):
            return "down"
    return None


def load_ledger(path):
    """Parse + schema-check the ledger.  Returns (rows, problems)."""
    checker = _load_checker()
    rows, problems = [], []
    with open(path) as f:
        for i, line in enumerate(f, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError as e:
                problems.append(f"{path}:{i}: not valid JSON: {e}")
                continue
            bad = checker.validate_ledger_row(row)
            if bad:
                problems.extend(f"{path}:{i}: {p}" for p in bad)
                continue
            rows.append(row)
    return rows, problems


def _median(vals):
    vals = sorted(vals)
    n = len(vals)
    mid = n // 2
    return vals[mid] if n % 2 else (vals[mid - 1] + vals[mid]) / 2.0


def split_runs(rows):
    """(baseline_rows, current_rows, current_run) — runs ordered by first
    appearance (appends are chronological); the last run is the
    candidate, everything earlier is baseline.  ``tune-*`` runs (the
    autotuner's per-trial rows) are never the candidate: each one
    measures a DIFFERENT knob point, so trial-vs-trial deltas are search
    results, not regressions — they ride as baseline history only and
    the tuned-vs-default verdict gates via the ``cpu_autotune`` summary
    rows of the surrounding bench run instead."""
    order = []
    for row in rows:
        if row["run"] not in order:
            order.append(row["run"])
    candidates = [r for r in order if not r.startswith("tune-")]
    if len(order) < 2 or not candidates:
        return [], [], order[-1] if order else None
    current = candidates[-1]
    return ([r for r in rows if r["run"] != current],
            [r for r in rows if r["run"] == current], current)


def diff(baseline_rows, current_rows, tolerance):
    """Compare the current run against per-(bench, metric) baseline
    medians.  Returns a list of row dicts with verdicts."""
    base = {}
    for row in baseline_rows:
        base.setdefault((row["bench"], row["metric"]), []).append(
            float(row["value"]))
    results = []
    for row in current_rows:
        key = (row["bench"], row["metric"])
        cur = float(row["value"])
        rec = {"bench": row["bench"], "metric": row["metric"],
               "current": cur, "baseline": None, "change": None,
               "direction": metric_direction(row["metric"]),
               "verdict": "no_baseline"}
        if key in base:
            med = _median(base[key])
            rec["baseline"] = med
            if med != 0:
                change = (cur - med) / abs(med)
                rec["change"] = change
                if rec["direction"] == "down" and change > tolerance:
                    rec["verdict"] = "regression"
                elif rec["direction"] == "up" and change < -tolerance:
                    rec["verdict"] = "regression"
                elif rec["direction"] is None:
                    rec["verdict"] = "ungated"
                else:
                    rec["verdict"] = "ok"
            else:
                rec["verdict"] = "ok" if cur == 0 else "ungated"
        results.append(rec)
    return results


def check_stale_baseline(rows, onchip_path, stale_runs):
    """Return a STALE-BASELINE warning string, or None when the on-chip
    evidence is still fresh (or there are not yet ``stale_runs`` cpu-only
    runs to judge against).

    Evidence of an on-chip run is the newest of (a) any ``bench ==
    "train"`` ledger row's ts and (b) ``captured_unix`` inside
    ``onchip_path``.  A run counts as cpu-only when none of its rows is a
    train metric."""
    train_ts = max((float(r["ts"]) for r in rows if r["bench"] == "train"),
                   default=None)
    onchip_ts = None
    if onchip_path and os.path.exists(onchip_path):
        try:
            with open(onchip_path) as f:
                cap = json.load(f).get("captured_unix")
            if isinstance(cap, (int, float)) and not isinstance(cap, bool):
                onchip_ts = float(cap)
        except (ValueError, OSError):
            pass
    evidence = [t for t in (train_ts, onchip_ts) if t is not None]
    evidence_ts = max(evidence) if evidence else None

    order, first_ts, has_train = [], {}, set()
    for row in rows:
        run = row["run"]
        if run not in first_ts:
            order.append(run)
            first_ts[run] = float(row["ts"])
        if row["bench"] == "train":
            has_train.add(run)
    cpu_runs = [r for r in order if r not in has_train]
    recent = cpu_runs[-stale_runs:]
    if len(recent) < stale_runs:
        return None
    if evidence_ts is None:
        return (f"STALE-BASELINE: no on-chip train evidence at all (no "
                f"train ledger rows, no {onchip_path}) behind the last "
                f"{stale_runs} cpu bench run(s) — the cpu gate has "
                f"nothing on-chip to stand in for; re-run the on-chip "
                f"train bench (ROADMAP.md open follow-up: 'Re-measure "
                f"on-chip training' — a fresh on-chip row is still owed)")
    if all(first_ts[r] > evidence_ts for r in recent):
        return (f"STALE-BASELINE: newest on-chip train evidence "
                f"(ts {evidence_ts:.0f}) predates the last {stale_runs} "
                f"cpu bench run(s) (oldest at ts "
                f"{min(first_ts[r] for r in recent):.0f}) — cpu gating "
                f"may have drifted from hardware reality; re-run the "
                f"on-chip train bench (ROADMAP.md open follow-up: "
                f"'Re-measure on-chip training' — a fresh on-chip row "
                f"is still owed)")
    return None


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Gate the latest bench run against the ledger "
                    "baseline.")
    ap.add_argument("ledger", nargs="?", default=DEFAULT_LEDGER,
                    help=f"ledger path (default {DEFAULT_LEDGER})")
    ap.add_argument("--tolerance", type=float, default=0.25,
                    help="allowed fractional change in the bad direction "
                         "(default 0.25)")
    ap.add_argument("--check", action="store_true",
                    help="CI mode: exit 0 when the ledger is missing or "
                         "has no baseline yet")
    ap.add_argument("--stale-runs", type=int, default=3,
                    help="warn STALE-BASELINE when the newest on-chip "
                         "train evidence is older than this many cpu "
                         "runs (default 3; --check only)")
    ap.add_argument("--onchip", default=None,
                    help="on-chip evidence file (default "
                         "BENCH_onchip_latest.json next to the ledger)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full diff as JSON")
    args = ap.parse_args(argv)

    if not os.path.exists(args.ledger):
        if args.check:
            print(f"perf-diff: no ledger at {args.ledger} — skipping "
                  f"(seed one with bench.py)")
            return 0
        print(f"perf-diff: ledger not found: {args.ledger}",
              file=sys.stderr)
        return 2
    rows, problems = load_ledger(args.ledger)
    if problems:
        for p in problems:
            print(p, file=sys.stderr)
        return 2
    if args.check:
        onchip = args.onchip or os.path.join(
            os.path.dirname(os.path.abspath(args.ledger)),
            "BENCH_onchip_latest.json")
        warn = check_stale_baseline(rows, onchip, args.stale_runs)
        if warn:
            print(warn)
    baseline_rows, current_rows, current = split_runs(rows)
    if not current_rows:
        msg = (f"perf-diff: ledger has "
               f"{'one run' if current else 'no runs'} — no baseline to "
               f"compare against")
        if args.check:
            print(msg + " — skipping")
            return 0
        print(msg, file=sys.stderr)
        return 2

    results = diff(baseline_rows, current_rows, args.tolerance)
    regressions = [r for r in results if r["verdict"] == "regression"]
    if args.json:
        json.dump({"run": current, "tolerance": args.tolerance,
                   "results": results,
                   "regressions": len(regressions)},
                  sys.stdout, indent=2)
        print()
    else:
        print(f"perf-diff: run {current!r} vs median of "
              f"{len({r['run'] for r in baseline_rows})} baseline run(s), "
              f"tolerance {args.tolerance:.0%}")
        print(f"{'bench':<26}{'metric':<26}{'baseline':>12}"
              f"{'current':>12}{'change':>9}  verdict")
        for r in sorted(results, key=lambda r: (r["bench"], r["metric"])):
            base = ("-" if r["baseline"] is None
                    else f"{r['baseline']:.4g}")
            change = ("-" if r["change"] is None
                      else f"{r['change']:+.1%}")
            print(f"{r['bench']:<26}{r['metric']:<26}{base:>12}"
                  f"{r['current']:>12.4g}{change:>9}  {r['verdict']}")
    if regressions:
        print(f"FAIL: {len(regressions)} metric(s) regressed beyond "
              f"{args.tolerance:.0%}")
        return 1
    print("OK: no regressions")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
