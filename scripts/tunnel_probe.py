"""Background tunnel probe.

Appends one JSON line per attempt to /root/repo/tunnel_status.jsonl and
creates /root/repo/TUNNEL_UP the moment jax.devices() reports a TPU.
Run under nohup; exits after the first success.
"""
import json
import os
import subprocess
import sys
import time

os.chdir('/root/repo')
while True:
    t0 = time.time()
    try:
        p = subprocess.run(
            [sys.executable, '-c',
             'import jax; d=jax.devices(); print(d[0].platform, len(d))'],
            capture_output=True, text=True, timeout=300)
        rc, out, err = p.returncode, p.stdout.strip()[-200:], p.stderr.strip()[-200:]
    except subprocess.TimeoutExpired:
        rc, out, err = -9, '', 'probe timeout 300s'
    line = {"t": time.strftime('%Y-%m-%dT%H:%M:%S'), "dt": round(time.time() - t0, 1),
            "rc": rc, "out": out, "err": err}
    with open('tunnel_status.jsonl', 'a') as f:
        f.write(json.dumps(line) + '\n')
    if rc == 0 and 'tpu' in out.lower():
        with open('TUNNEL_UP', 'w') as f:
            f.write(line['t'])
        break
    time.sleep(60)
