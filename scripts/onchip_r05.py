#!/usr/bin/env python
"""Round-5 on-chip measurement program — one shot, fully journaled.

Single-command unattended runner for every tunnel-dependent round-5
deliverable (round-4 verdict, next #1-#8).  Run the moment a probe
succeeds — any window of tunnel uptime converts into committed artifacts:

    python scripts/onchip_r05.py                    # everything, priority order
    python scripts/onchip_r05.py --only gate,stream # subset
    python scripts/onchip_r05.py --budget 7200      # stop starting new steps

Steps (PRIORITY order — earlier = more valuable; a dying tunnel should
still land the top of the list):
  probe    — device sanity (platform, kind, tiny matmul)
  gate     — Mosaic compile-gate: lower+compile all 14 Pallas kernel
             variants (verdict #7); journals per-variant status
  stream   — THE flagship: beyond-HBM training via param-stream
             (--offload-param cpu), ascending ladder 5B → 6.7B → 8B → 13B,
             >=8 optimizer steps each; first rung past the analytic 3.4B
             cap is the reference-defining claim (verdict #1, #3)
  bench    — bench.py headline (refreshes BENCH_onchip_latest.json;
             verdict #2's cached-onchip promotion feeds on this)
  boundary — param-stream boundary ablation on chip: pipelined vs serial
             GAS-boundary walk at 2.7B (verdict #4's chip half)
  offload1b— gpt_1b + offload_optimizer=cpu: the streamed-writeback path's
             first complete on-chip step; target >=50% of the 15.8k
             no-offload tok/s (verdict #4)
  mfu      — north-star MFU: llama_1b / llama_3b (GQA+SwiGLU) at seq
             2048/4096 with attention-tile sweep; target >=0.55 (verdict
             #5); gpt_1_1b pathological-compile diagnosis goes LAST
  infer    — >=1B inference campaign: gpt2-1.5b p50/p90/p99 (+int8) +
             chunked serving curve decode_chunk ∈ {1,8,32} (verdict #6)
  tune     — autotuner cold-start rediscovery on the 1B config including
             moment/grad-accum dtype knobs (verdict #8)

Each step runs in a subprocess with its own leash; failures journal and
the program continues.  Results land in ``ONCHIP_r05/`` (JSON per step +
``journal.jsonl``) — commit that directory.  The XLA compile cache
persists across attempts so a retry after a tunnel blip resumes warm.
"""

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "ONCHIP_r05")
JOURNAL = os.path.join(OUT, "journal.jsonl")
CACHE = os.path.expanduser("~/.cache/dstpu_xla_cache")

_T0 = time.time()
_BUDGET = None


def _remaining():
    return (_BUDGET - (time.time() - _T0)) if _BUDGET else float("inf")


def log(step, **kw):
    os.makedirs(OUT, exist_ok=True)
    rec = {"step": step, "t": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                            time.gmtime()), **kw}
    with open(JOURNAL, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(f"[onchip] {step}: {kw.get('status', '')}", flush=True)


def run(step, cmd, timeout, env=None):
    if _remaining() < 60:
        log(step, status="skipped", reason="budget exhausted")
        return None
    timeout = min(timeout, max(60, _remaining() - 30))
    t0 = time.time()
    try:
        out = subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout, cwd=REPO,
            env={**os.environ, "JAX_COMPILATION_CACHE_DIR": CACHE,
                 **(env or {})})
    except subprocess.TimeoutExpired as e:
        # journal the partial stdout: per-step JSON rows emitted before the
        # stall are exactly the artifacts this program exists to capture
        partial = (e.stdout.decode(errors="replace")
                   if isinstance(e.stdout, bytes) else (e.stdout or ""))
        jsons = []
        for line in partial.splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    jsons.append(json.loads(line))
                except json.JSONDecodeError:
                    pass
        log(step, status="timeout", timeout_s=round(timeout),
            cmd=" ".join(cmd), results=jsons or None,
            stdout=None if jsons else partial[-2000:])
        return None
    dt = time.time() - t0
    tail = (out.stdout or "")[-4000:]
    jsons = []
    for line in (out.stdout or "").splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                jsons.append(json.loads(line))
            except json.JSONDecodeError:
                pass
    if out.returncode != 0:
        log(step, status="failed", rc=out.returncode, wall_s=round(dt, 1),
            results=jsons or None, stdout=tail,
            stderr=(out.stderr or "")[-2000:])
        return None
    log(step, status="ok", wall_s=round(dt, 1), results=jsons,
        stdout=None if jsons else tail)
    with open(os.path.join(OUT, f"{step}.json"), "w") as f:
        json.dump({"wall_s": round(dt, 1), "results": jsons,
                   "stdout_tail": tail}, f, indent=1)
    return jsons


def main():
    global _BUDGET
    ap = argparse.ArgumentParser()
    ap.add_argument("--only", default="")
    ap.add_argument("--budget", type=int, default=0,
                    help="stop starting steps after this many seconds")
    args = ap.parse_args()
    if args.budget:
        _BUDGET = args.budget
    steps = [s for s in args.only.split(",") if s] or [
        "probe", "gate", "stream", "bench", "boundary", "offload1b",
        "mfu", "infer", "tune"]
    py = sys.executable

    if "probe" in steps:
        ok = run("probe", [py, "-c",
                           "import jax; d=jax.devices()[0]; "
                           "import jax.numpy as jnp; "
                           "x=jnp.ones((256,256),jnp.bfloat16); "
                           "print((x@x).sum()); "
                           "import json; "
                           "print(json.dumps({'platform': d.platform, "
                           "'kind': getattr(d,'device_kind','')}))"],
                 timeout=240)
        if ok is None:
            log("abort", status="no device")
            return 1

    if "gate" in steps:
        run("kernels_gate",
            [py, "scripts/kernel_gate.py",
             "--json-out", os.path.join(OUT, "kernels_gate.json")],
            timeout=1800)

    if "stream" in steps:
        # beyond-HBM ladder, ascending: the FIRST rung already exceeds the
        # 3.4B analytic cap, so even one surviving attempt lands the claim;
        # later rungs raise max_params_measured.  bf16 grad accumulators
        # halve the D2H stream; buffer_count=2 minimizes HBM so activations
        # get the rest; steps=8 per the verdict's done-criterion.
        # host RAM (133 GB) caps the ladder at ~6.7B (16 B/param host Adam
        # state); 8B would need 128 GB + transient init and the 79 GB free
        # disk can't memmap it either — journaled as the measured ceiling
        best = None
        for model, leash in (("gpt_5b", 3600), ("gpt_6_7b", 3000)):
            res = run(f"stream_{model}",
                      [py, "bin/ds_bench", "train", "--model", model,
                       "--batch", "1", "--gas", "1", "--seq", "1024",
                       "--steps", "8", "--zero-stage", "0",
                       "--offload-param", "cpu", "--buffer-count", "2",
                       "--grad-accum-dtype", "bfloat16", "--json"],
                      timeout=leash)
            if res:
                for r in res:
                    if r.get("n_params"):
                        best = r
            else:
                break      # bigger rungs won't fare better; save budget
        if best:
            with open(os.path.join(OUT, "max_params_measured.json"),
                      "w") as f:
                json.dump({"max_params_single_chip": best["n_params"],
                           "max_params_kind": "measured",
                           "via": "param_stream", "record": best}, f,
                          indent=1)

    if "bench" in steps:
        run("bench", [py, "bench.py"], timeout=960,
            env={"BENCH_BUDGET_S": "900"})

    if "boundary" in steps:
        for mode, flag in (("pipelined", []), ("serial",
                                               ["--serial-boundary"])):
            run(f"boundary_{mode}",
                [py, "bin/ds_bench", "train", "--model", "gpt_2_7b",
                 "--batch", "1", "--gas", "1", "--seq", "1024",
                 "--steps", "4", "--zero-stage", "0",
                 "--offload-param", "cpu", "--buffer-count", "2",
                 "--grad-accum-dtype", "bfloat16", "--json"] + flag,
                timeout=2400)

    if "offload1b" in steps:
        run("offload_1b",
            [py, "bin/ds_bench", "train", "--model", "gpt_1b",
             "--batch", "2", "--gas", "4", "--seq", "1024", "--steps", "6",
             "--offload", "cpu", "--json"], timeout=2400)

    if "mfu" in steps:
        # north-star shape: GQA+SwiGLU at long seq.  llama_1b fits the full
        # train state (bf16 moments) on 16 GB; tile sweep at seq 4096.
        run("mfu_llama1b_s2048",
            [py, "bin/ds_bench", "train", "--model", "llama_1b",
             "--batch", "2", "--gas", "4", "--seq", "2048", "--steps", "8",
             "--moment-dtype", "bfloat16", "--grad-accum-dtype", "bfloat16",
             "--json"], timeout=2400)
        for bq, bk in ((512, 1024), (512, 512), (1024, 512)):
            run(f"mfu_llama1b_s4096_b{bq}x{bk}",
                [py, "bin/ds_bench", "train", "--model", "llama_1b",
                 "--batch", "1", "--gas", "4", "--seq", "4096",
                 "--steps", "6", "--moment-dtype", "bfloat16",
                 "--grad-accum-dtype", "bfloat16",
                 "--attn-block-q", str(bq), "--attn-block-k", str(bk),
                 "--json"], timeout=2400)
        run("mfu_llama3b_s2048_stream",
            [py, "bin/ds_bench", "train", "--model", "llama_3b",
             "--batch", "1", "--gas", "2", "--seq", "2048", "--steps", "6",
             "--zero-stage", "0", "--offload-param", "cpu",
             "--buffer-count", "2", "--resident-layers", "8",
             "--grad-accum-dtype", "bfloat16", "--json"], timeout=3000)
        # the r3 pathological 30-min XLA compile, diagnosed not abandoned:
        # same shape, one knob changed (remat policy) — if it compiles
        # fast, the scheduler blowup is remat-policy-bound; journal either
        # way.  Goes last: worst value/minute in the program.
        run("gpt_1_1b_diag_nothing_saveable",
            [py, "bin/ds_bench", "train", "--model", "gpt_1_1b",
             "--batch", "1", "--gas", "8", "--seq", "1024", "--steps", "4",
             "--moment-dtype", "bfloat16", "--grad-accum-dtype",
             "bfloat16", "--remat-policy", "nothing_saveable", "--json"],
            timeout=1500)

    if "infer" in steps:
        run("infer_1_5b",
            [py, "bin/ds_bench", "inference", "--model", "gpt2-1.5b",
             "--batch", "1", "--prompt-len", "128", "--max-new-tokens",
             "64", "--trials", "10"], timeout=2400)
        run("infer_1_5b_int8",
            [py, "bin/ds_bench", "inference", "--model", "gpt2-1.5b",
             "--batch", "1", "--prompt-len", "128", "--max-new-tokens",
             "64", "--trials", "10", "--int8"], timeout=2400)
        for chunk in (1, 8, 32):
            run(f"serving_1_5b_chunk{chunk}",
                [py, "bin/ds_bench", "serving", "--model", "gpt2_1_5b",
                 "--requests", "16", "--max-batch", "8",
                 "--prompt-len", "128", "--gen", "64",
                 "--decode-chunk", str(chunk)], timeout=2400)
        # beyond-HBM inference: 6.7B llama through ZeRO-Inference weight
        # streaming (host-resident params, per-layer H2D) — the inference
        # twin of the param-stream training claim
        run("infer_7b_zero_stream",
            [py, "bin/ds_bench", "inference", "--model", "llama2-7b",
             "--batch", "1", "--prompt-len", "128", "--max-new-tokens",
             "32", "--trials", "5", "--zero-stream"], timeout=3000)
        # int8 weight streaming halves the per-layer H2D — the streamed-
        # inference bottleneck; compare tokens/s against the bf16 stream
        run("infer_7b_zero_stream_int8",
            [py, "bin/ds_bench", "inference", "--model", "llama2-7b",
             "--batch", "1", "--prompt-len", "128", "--max-new-tokens",
             "32", "--trials", "5", "--zero-stream", "--int8"],
            timeout=3000)

    if "tune" in steps:
        spec = {"kind": "causal_lm",
                "config": dict(vocab_size=50304, hidden_size=2048,
                               n_layers=18, n_heads=16, max_seq_len=1024,
                               activation="gelu", use_rmsnorm=False,
                               use_rope=False, tie_embeddings=True,
                               remat=True)}
        code = (
            "import json\n"
            "from deepspeed_tpu.autotuning.autotuner import Autotuner\n"
            "at = Autotuner({'train_micro_batch_size_per_gpu': 2,\n"
            "  'optimizer': {'type': 'AdamW', 'params': {'lr': 1e-4}},\n"
            "  'bf16': {'enabled': True},\n"
            "  'autotuning': {'enabled': True,\n"
            "    'results_dir': 'ONCHIP_r05/autotuning_results',\n"
            "    'start_profile_step': 1, 'end_profile_step': 4,\n"
            "    'num_tuning_micro_batch_sizes': 2,\n"
            "    'min_train_micro_batch_size_per_gpu': 1}})\n"
            "at.feasible_stages = lambda dp: [3]\n"
            f"best = at.tune(model_spec={spec!r}, seq=1024,\n"
            "               trial_timeout=1500)\n"
            "print(json.dumps({'best': best}))\n")
        run("tune", [py, "-c", code], timeout=7200)

    log("done", status="complete",
        elapsed_s=round(time.time() - _T0, 1))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
