#!/usr/bin/env python
"""Export a telemetry events.jsonl stream as a Chrome trace-event file.

Standalone (stdlib-only, no deepspeed_tpu import): converts the JSONL
event stream written by ``monitor/telemetry.py`` — a single-rank
``events.jsonl`` (plus rotated ``events.jsonl.N`` generations) or a
distributed shard directory of ``events.rank<k>.jsonl`` files — into
Chrome trace-event JSON loadable by Perfetto (https://ui.perfetto.dev)
and chrome://tracing.

Mapping (one rank = one trace process):

* ``span`` events become ``"X"`` complete events.  A span record's
  ``ts`` is stamped at span END, so the slice start is
  ``ts - dur_ms/1000``.
* ``comm`` events with a host-observed ``dur_ms`` become ``"X"``
  slices on a per-rank "collectives" track, joined ACROSS ranks by
  flow events (``"s"``/``"t"``/``"f"``): the k-th timed occurrence of
  each collective op is one flow, so rank skew at collective entry is
  visible as slanted arrows.  Untimed comm censuses become instants.
* ``serve/request/*`` lifecycle events become nestable async events
  (``"b"`` at admitted, ``"n"`` at prefill_start / first_token,
  ``"e"`` at the terminal) keyed by ``req_id`` — each request renders
  as one async track spanning admission to terminal.
* ``serve/request/attr`` critical-path events become contiguous
  per-stage ``"X"`` slices (queue → prefill → migrate → gap → decode)
  ending at the terminal ts on a "critical path" track, chained by
  flow arrows so each request's attribution reads as one arrow
  through its stages.
* ``gauge`` / ``counter`` events become ``"C"`` counter events.
* everything else (stall, compile, fleet, fault, incident, meta,
  heartbeat, remaining serve events) becomes ``"i"`` instants.

Usage:
    python scripts/ds_trace_export.py <events.jsonl | telemetry-dir>
        [-o trace.json] [--check]

``-o`` defaults to ``trace.json`` next to the input.  ``--check``
additionally validates the produced object against the trace-event
format (also used by the tier-1 tests via :func:`validate_trace`) and
exits non-zero on problems.
"""

import glob
import json
import os
import re
import sys

_NUM = (int, float)

_SHARD_RE = re.compile(r"events\.rank(\d+)\.jsonl(\.\d+)?$")

# fixed per-rank thread ids (Perfetto tracks)
TID_SPANS = 1
TID_COMM = 2
TID_INSTANTS = 3
TID_REQUESTS = 4
TID_ATTR = 5

# ordered stage vocabulary of serve/request/attr (mirrors
# monitor/attribution.py ATTR_STAGES — the lockstep schema test pins
# the source tuples; this copy only orders the rendered slices)
_ATTR_STAGES = ("queue", "prefill", "migrate", "gap", "decode")

_ASYNC_BEGIN = ("serve/request/admitted",)
_ASYNC_STEP = ("serve/request/prefill_start", "serve/request/first_token")
_ASYNC_END = ("serve/request/finish", "serve/request/shed",
              "serve/request/deadline", "serve/request/evict")


# ----------------------------------------------------------------------
# input discovery / parsing
# ----------------------------------------------------------------------
def discover_inputs(path):
    """Return ``[(filepath, rank_or_None), ...]`` for ``path``: a single
    JSONL file, or a directory holding ``events.jsonl`` (+ rotations)
    and/or ``events.rank<k>.jsonl`` shards."""
    if os.path.isfile(path):
        m = _SHARD_RE.search(path)
        return [(path, int(m.group(1)) if m else None)]
    inputs = []
    for p in sorted(glob.glob(os.path.join(path, "events.jsonl")) +
                    glob.glob(os.path.join(path, "events.jsonl.*"))):
        inputs.append((p, None))
    for p in sorted(glob.glob(os.path.join(path, "events.rank*.jsonl")) +
                    glob.glob(os.path.join(path, "events.rank*.jsonl.*"))):
        m = _SHARD_RE.search(p)
        if m:
            inputs.append((p, int(m.group(1))))
    return inputs


def load_events(path):
    """Parse every input under ``path`` into a flat event list, each
    stamped with its rank (filename rank for shards, else the record's
    own ``rank`` field, else 0).  Unparseable lines are skipped — a live
    writer's torn tail must not break an export."""
    events = []
    for filepath, file_rank in discover_inputs(path):
        with open(filepath) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(ev, dict) or \
                        not isinstance(ev.get("ts"), _NUM):
                    continue
                rank = file_rank
                if rank is None:
                    rank = ev.get("rank")
                ev["_rank"] = int(rank) if isinstance(rank, int) else 0
                events.append(ev)
    events.sort(key=lambda e: e["ts"])
    return events


# ----------------------------------------------------------------------
# conversion
# ----------------------------------------------------------------------
def _args(ev):
    """Everything informative the event carries, minus the envelope."""
    out = {}
    for k, v in ev.items():
        if k in ("ts", "kind", "name", "rank", "_rank", "attrs"):
            continue
        out[k] = v
    attrs = ev.get("attrs")
    if isinstance(attrs, dict):
        out.update(attrs)
    return out


def convert(events):
    """Convert a loaded event list into a Chrome trace-event object."""
    trace = []
    if not events:
        return {"traceEvents": [], "displayTimeUnit": "ms"}

    # the time origin is the earliest slice START, not the earliest
    # record ts: span/comm records are stamped at END, so their slices
    # begin dur earlier — anchoring on raw ts would go negative
    def _start(ev):
        ts = float(ev["ts"])
        if ev.get("kind") in ("span", "comm") and \
                isinstance(ev.get("dur_ms"), _NUM):
            return ts - max(0.0, float(ev["dur_ms"])) / 1000.0
        if ev.get("kind") == "serve" and \
                ev.get("name") == "serve/request/attr":
            e2e = _args(ev).get("e2e_ms")
            if isinstance(e2e, _NUM):
                return ts - max(0.0, float(e2e)) / 1000.0
        return ts

    t0 = min(_start(e) for e in events)

    def us(ts):
        return round((ts - t0) * 1e6, 1)

    ranks = set()
    tids_used = {}          # (pid, tid) -> track name
    comm_occurrence = {}    # (rank, op) -> timed-occurrence counter
    flow_sites = {}         # (op, k) -> [(rank, start_us), ...]

    for ev in events:
        kind = ev.get("kind")
        name = ev.get("name", "")
        rank = ev["_rank"]
        ranks.add(rank)
        ts_us = us(ev["ts"])

        if kind == "span":
            dur_us = max(0.0, float(ev.get("dur_ms", 0.0)) * 1000.0)
            trace.append({"ph": "X", "name": name, "cat": "span",
                          "pid": rank, "tid": TID_SPANS,
                          "ts": round(ts_us - dur_us, 1),
                          "dur": round(dur_us, 1), "args": _args(ev)})
            tids_used[(rank, TID_SPANS)] = "spans"
        elif kind == "comm":
            if isinstance(ev.get("dur_ms"), _NUM):
                dur_us = max(0.0, float(ev["dur_ms"]) * 1000.0)
                start_us = round(ts_us - dur_us, 1)
                trace.append({"ph": "X", "name": name, "cat": "comm",
                              "pid": rank, "tid": TID_COMM,
                              "ts": start_us, "dur": round(dur_us, 1),
                              "args": _args(ev)})
                tids_used[(rank, TID_COMM)] = "collectives"
                k = comm_occurrence.get((rank, name), 0)
                comm_occurrence[(rank, name)] = k + 1
                flow_sites.setdefault((name, k), []).append(
                    (rank, start_us))
            else:
                trace.append({"ph": "i", "name": name, "cat": "comm",
                              "pid": rank, "tid": TID_INSTANTS,
                              "ts": ts_us, "s": "t", "args": _args(ev)})
                tids_used[(rank, TID_INSTANTS)] = "events"
        elif kind == "serve" and name == "serve/request/attr":
            # critical-path attribution: lay the ordered stage
            # decomposition out as contiguous slices ending at the
            # terminal ts (the stages sum to e2e_ms by construction),
            # then chain them with flow arrows keyed by req_id
            args = _args(ev)
            req_id = str(args.get("req_id", "?"))
            e2e = args.get("e2e_ms")
            e2e_us = max(0.0, float(e2e)) * 1000.0 \
                if isinstance(e2e, _NUM) else 0.0
            # clamp: ts_us is rounded to 0.1us, so the anchor event's
            # reconstructed start can dip fractionally below the origin
            cursor = max(0.0, ts_us - e2e_us)
            stage_starts = []
            for stage in _ATTR_STAGES:
                ms = args.get(f"{stage}_ms")
                if not isinstance(ms, _NUM) or ms <= 0:
                    continue
                dur_us = float(ms) * 1000.0
                trace.append({"ph": "X", "name": f"attr/{stage}",
                              "cat": "attr", "pid": rank,
                              "tid": TID_ATTR,
                              "ts": round(cursor, 1),
                              "dur": round(dur_us, 1),
                              "args": dict(args)})
                stage_starts.append(cursor)
                cursor += dur_us
                tids_used[(rank, TID_ATTR)] = "critical path"
            if len(stage_starts) >= 2:
                flow_id = f"attr:{req_id}"
                last = len(stage_starts) - 1
                for i, start_us in enumerate(stage_starts):
                    ph = "s" if i == 0 else ("f" if i == last else "t")
                    rec = {"ph": ph, "name": "critical-path",
                           "cat": "attr-flow", "id": flow_id,
                           "pid": rank, "tid": TID_ATTR,
                           "ts": round(start_us + 0.1, 1)}
                    if ph == "f":
                        rec["bp"] = "e"
                    trace.append(rec)
        elif kind == "serve" and name.startswith("serve/request/"):
            args = _args(ev)
            req_id = str(args.get("req_id", "?"))
            if name in _ASYNC_BEGIN:
                ph = "b"
            elif name in _ASYNC_END:
                ph = "e"
            else:
                ph = "n"
            trace.append({"ph": ph, "name": "request", "cat": "request",
                          "id": req_id, "pid": rank, "tid": TID_REQUESTS,
                          "ts": ts_us,
                          "args": dict(args, state=name)})
            tids_used[(rank, TID_REQUESTS)] = "requests"
        elif kind in ("gauge", "counter"):
            value = ev.get("value")
            if isinstance(value, _NUM) and not isinstance(value, bool):
                trace.append({"ph": "C", "name": name, "pid": rank,
                              "ts": ts_us, "args": {"value": value}})
        else:
            trace.append({"ph": "i", "name": name, "cat": kind or "event",
                          "pid": rank, "tid": TID_INSTANTS,
                          "ts": ts_us, "s": "t", "args": _args(ev)})
            tids_used[(rank, TID_INSTANTS)] = "events"

    # cross-rank collective flows: the k-th timed occurrence of an op on
    # every rank is one logical collective — arrow from the earliest
    # entrant through every later one (the straggler reads directly off
    # the arrow slant).  Flow ts must land inside the bound slice, so we
    # anchor at slice start + epsilon.
    for (op, k), sites in sorted(flow_sites.items()):
        if len(sites) < 2:
            continue
        sites.sort(key=lambda s: s[1])
        flow_id = f"{op}:{k}"
        for i, (rank, start_us) in enumerate(sites):
            if i == 0:
                ph = "s"
            elif i == len(sites) - 1:
                ph = "f"
            else:
                ph = "t"
            rec = {"ph": ph, "name": op, "cat": "comm-flow",
                   "id": flow_id, "pid": rank, "tid": TID_COMM,
                   "ts": round(start_us + 0.1, 1)}
            if ph == "f":
                rec["bp"] = "e"     # bind finish to enclosing slice
            trace.append(rec)

    meta = []
    for rank in sorted(ranks):
        meta.append({"ph": "M", "name": "process_name", "pid": rank,
                     "args": {"name": f"rank {rank}"}})
    for (rank, tid), label in sorted(tids_used.items()):
        meta.append({"ph": "M", "name": "thread_name", "pid": rank,
                     "tid": tid, "args": {"name": label}})
    return {"traceEvents": meta + trace, "displayTimeUnit": "ms"}


# ----------------------------------------------------------------------
# trace-event format validation
# ----------------------------------------------------------------------
_PHASES = ("X", "B", "E", "i", "I", "C", "b", "n", "e", "s", "t", "f",
           "M")


def validate_trace(obj):
    """Validate ``obj`` against the Chrome trace-event JSON format (the
    subset this exporter emits).  Returns a list of problem strings
    (empty = valid)."""
    problems = []
    if not isinstance(obj, dict):
        return [f"trace is {type(obj).__name__}, not an object"]
    events = obj.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents is missing or not a list"]
    for i, ev in enumerate(events):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            problems.append(f"{where}: not an object")
            continue
        ph = ev.get("ph")
        if ph not in _PHASES:
            problems.append(f"{where}: unknown phase {ph!r}")
            continue
        if "pid" not in ev or isinstance(ev["pid"], bool) or \
                not isinstance(ev["pid"], int):
            problems.append(f"{where}: missing or non-int pid")
        if ph != "M":
            ts = ev.get("ts")
            if not isinstance(ts, _NUM) or isinstance(ts, bool):
                problems.append(f"{where}: missing or non-numeric ts")
            elif ts < 0:
                problems.append(f"{where}: negative ts {ts}")
        if ph in ("X", "C", "M", "b", "n", "e", "i", "I") and \
                not isinstance(ev.get("name"), str):
            problems.append(f"{where}: missing or non-string name")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, _NUM) or isinstance(dur, bool):
                problems.append(f"{where}: X event missing numeric dur")
            elif dur < 0:
                problems.append(f"{where}: negative dur {dur}")
        if ph in ("b", "n", "e", "s", "t", "f"):
            if not isinstance(ev.get("id"), str):
                problems.append(f"{where}: {ph!r} event missing string id")
            if ph in ("b", "n", "e") and \
                    not isinstance(ev.get("cat"), str):
                problems.append(
                    f"{where}: async event missing string cat")
        if ph == "C":
            args = ev.get("args")
            if not isinstance(args, dict) or not args or \
                    not all(isinstance(v, _NUM) and
                            not isinstance(v, bool)
                            for v in args.values()):
                problems.append(
                    f"{where}: counter args must be numeric and "
                    f"non-empty")
        if ph == "M":
            if ev.get("name") not in ("process_name", "thread_name",
                                      "process_labels",
                                      "process_sort_index",
                                      "thread_sort_index"):
                problems.append(
                    f"{where}: unknown metadata name {ev.get('name')!r}")
            elif not isinstance(ev.get("args"), dict):
                problems.append(f"{where}: metadata missing args")
    # every async begin must see a matching end (same cat+id+pid)
    opened = {}
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            continue
        key = (ev.get("cat"), ev.get("id"), ev.get("pid"))
        if ev.get("ph") == "b":
            opened[key] = opened.get(key, 0) + 1
        elif ev.get("ph") == "e":
            if opened.get(key, 0) <= 0:
                problems.append(
                    f"traceEvents[{i}]: async end without begin "
                    f"(cat={key[0]!r} id={key[1]!r})")
            else:
                opened[key] -= 1
    return problems


# ----------------------------------------------------------------------
# CLI
# ----------------------------------------------------------------------
def main(argv=None):
    argv = list(argv if argv is not None else sys.argv[1:])
    check = "--check" in argv
    if check:
        argv.remove("--check")
    out_path = None
    if "-o" in argv:
        i = argv.index("-o")
        try:
            out_path = argv[i + 1]
        except IndexError:
            print("FAIL: -o requires a path")
            return 2
        del argv[i:i + 2]
    if len(argv) != 1:
        print(__doc__)
        return 2
    src = argv[0]
    if not os.path.exists(src):
        print(f"FAIL: no such path {src!r}")
        return 1
    events = load_events(src)
    if not events:
        print(f"FAIL: no telemetry events found under {src!r}")
        return 1
    obj = convert(events)
    if out_path is None:
        base = src if os.path.isdir(src) else os.path.dirname(src) or "."
        out_path = os.path.join(base, "trace.json")
    with open(out_path, "w") as f:
        json.dump(obj, f)
    n = len(obj["traceEvents"])
    print(f"wrote {out_path}: {n} trace event(s) from "
          f"{len(events)} telemetry event(s)")
    if check:
        problems = validate_trace(obj)
        if problems:
            for p in problems:
                print(p)
            print(f"FAIL: {len(problems)} problem(s)")
            return 1
        print("OK: trace validated")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
