#!/usr/bin/env bash
# Chain every offline quality gate in one command:
#
#   scripts/run_gates.sh [TELEMETRY_DIR] [INCIDENTS_DIR] [TUNE_DIR]
#
#   1. check_telemetry_schema.py <events.jsonl...>   frozen event vocab
#   2. check_telemetry_schema.py --ledger            BENCH_LEDGER.jsonl rows
#   3. check_telemetry_schema.py --incidents         incident bundles
#   4. ds_perf_diff.py --check                       perf regression gate
#   5. check_telemetry_schema.py --tune              tune journals/overlay
#
# TELEMETRY_DIR (optional) is searched recursively for events*.jsonl
# streams; INCIDENTS_DIR (optional) holds incident bundles; TUNE_DIR
# (optional, default autotuning_results/ when present) holds the
# autotuner's trial journals, tune/* event stream, and overlay.json.
# Gates whose input is absent are SKIPPED, not failed — the script is
# safe to run on a fresh checkout and in CI alike.  Exit 0 iff every
# gate that ran passed.

set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
PY="${PYTHON:-python}"
TELEMETRY_DIR="${1:-}"
INCIDENTS_DIR="${2:-}"
TUNE_DIR="${3:-}"
LEDGER="${LEDGER:-$REPO/BENCH_LEDGER.jsonl}"
fail=0

run_gate() {
    local name="$1"; shift
    echo "== gate: $name =="
    if "$@"; then
        echo "-- $name: PASS"
    else
        echo "-- $name: FAIL"
        fail=1
    fi
}

# 1. event-stream schema (every events*.jsonl under TELEMETRY_DIR)
if [ -n "$TELEMETRY_DIR" ] && [ -d "$TELEMETRY_DIR" ]; then
    mapfile -t streams < <(find "$TELEMETRY_DIR" -name 'events*.jsonl' \
                                -type f | sort)
    if [ "${#streams[@]}" -gt 0 ]; then
        run_gate "event schema" \
            "$PY" "$REPO/scripts/check_telemetry_schema.py" "${streams[@]}"
    else
        echo "== gate: event schema == SKIP (no events*.jsonl under" \
             "$TELEMETRY_DIR)"
    fi
else
    echo "== gate: event schema == SKIP (no telemetry dir given)"
fi

# 2. bench ledger rows
if [ -f "$LEDGER" ]; then
    run_gate "bench ledger" \
        "$PY" "$REPO/scripts/check_telemetry_schema.py" --ledger "$LEDGER"
else
    echo "== gate: bench ledger == SKIP ($LEDGER missing)"
fi

# 3. incident bundles
if [ -n "$INCIDENTS_DIR" ] && [ -d "$INCIDENTS_DIR" ]; then
    run_gate "incident bundles" \
        "$PY" "$REPO/scripts/check_telemetry_schema.py" --incidents \
        "$INCIDENTS_DIR"
else
    echo "== gate: incident bundles == SKIP (no incidents dir given)"
fi

# 4. perf regression (exits 0 quietly on a missing/single-run ledger)
run_gate "perf diff" "$PY" "$REPO/scripts/ds_perf_diff.py" --check \
    "$LEDGER"

# 5. autotuner artifacts: trial journals, tune/* stream, overlay
# provenance (defaults to the control plane's results_dir when present)
if [ -z "$TUNE_DIR" ] && [ -d "$REPO/autotuning_results" ]; then
    TUNE_DIR="$REPO/autotuning_results"
fi
if [ -n "$TUNE_DIR" ] && [ -e "$TUNE_DIR" ]; then
    run_gate "tune artifacts" \
        "$PY" "$REPO/scripts/check_telemetry_schema.py" --tune "$TUNE_DIR"
else
    echo "== gate: tune artifacts == SKIP (no tune dir given)"
fi

if [ "$fail" -ne 0 ]; then
    echo "GATES: FAIL"
    exit 1
fi
echo "GATES: OK"
