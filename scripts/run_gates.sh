#!/usr/bin/env bash
# Chain every offline quality gate in one command:
#
#   scripts/run_gates.sh [TELEMETRY_DIR] [INCIDENTS_DIR] [TUNE_DIR]
#
#   1. check_telemetry_schema.py <events.jsonl...>   frozen event vocab
#   2. check_telemetry_schema.py --ledger            BENCH_LEDGER.jsonl rows
#   3. check_telemetry_schema.py --incidents         incident bundles
#   4. ds_perf_diff.py --check                       perf regression gate
#   5. check_telemetry_schema.py --tune              tune journals/overlay
#   6. comm-quant smoke                              int8 codec roundtrip
#   7. ds_trace_export.py --check                    Perfetto trace export
#   8. overlap smoke                                 ZeRO-3 comm overlap
#   9. fleet xproc smoke                             kill -9 a worker proc
#  10. chaos smoke                                   seeded wire faults
#
# TELEMETRY_DIR (optional) is searched recursively for events*.jsonl
# streams; INCIDENTS_DIR (optional) holds incident bundles; TUNE_DIR
# (optional, default autotuning_results/ when present) holds the
# autotuner's trial journals, tune/* event stream, and overlay.json.
# Gates whose input is absent are SKIPPED, not failed — the script is
# safe to run on a fresh checkout and in CI alike.  Exit 0 iff every
# gate that ran passed.

set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
PY="${PYTHON:-python}"
TELEMETRY_DIR="${1:-}"
INCIDENTS_DIR="${2:-}"
TUNE_DIR="${3:-}"
LEDGER="${LEDGER:-$REPO/BENCH_LEDGER.jsonl}"
fail=0

run_gate() {
    local name="$1"; shift
    echo "== gate: $name =="
    if "$@"; then
        echo "-- $name: PASS"
    else
        echo "-- $name: FAIL"
        fail=1
    fi
}

# 1. event-stream schema (every events*.jsonl under TELEMETRY_DIR)
if [ -n "$TELEMETRY_DIR" ] && [ -d "$TELEMETRY_DIR" ]; then
    mapfile -t streams < <(find "$TELEMETRY_DIR" -name 'events*.jsonl' \
                                -type f | sort)
    if [ "${#streams[@]}" -gt 0 ]; then
        run_gate "event schema" \
            "$PY" "$REPO/scripts/check_telemetry_schema.py" "${streams[@]}"
    else
        echo "== gate: event schema == SKIP (no events*.jsonl under" \
             "$TELEMETRY_DIR)"
    fi
else
    echo "== gate: event schema == SKIP (no telemetry dir given)"
fi

# 2. bench ledger rows
if [ -f "$LEDGER" ]; then
    run_gate "bench ledger" \
        "$PY" "$REPO/scripts/check_telemetry_schema.py" --ledger "$LEDGER"
else
    echo "== gate: bench ledger == SKIP ($LEDGER missing)"
fi

# 3. incident bundles
if [ -n "$INCIDENTS_DIR" ] && [ -d "$INCIDENTS_DIR" ]; then
    run_gate "incident bundles" \
        "$PY" "$REPO/scripts/check_telemetry_schema.py" --incidents \
        "$INCIDENTS_DIR"
else
    echo "== gate: incident bundles == SKIP (no incidents dir given)"
fi

# 4. perf regression (exits 0 quietly on a missing/single-run ledger)
run_gate "perf diff" "$PY" "$REPO/scripts/ds_perf_diff.py" --check \
    "$LEDGER"

# 5. autotuner artifacts: trial journals, tune/* stream, overlay
# provenance (defaults to the control plane's results_dir when present)
if [ -z "$TUNE_DIR" ] && [ -d "$REPO/autotuning_results" ]; then
    TUNE_DIR="$REPO/autotuning_results"
fi
if [ -n "$TUNE_DIR" ] && [ -e "$TUNE_DIR" ]; then
    run_gate "tune artifacts" \
        "$PY" "$REPO/scripts/check_telemetry_schema.py" --tune "$TUNE_DIR"
else
    echo "== gate: tune artifacts == SKIP (no tune dir given)"
fi

# 6. quantized-collective smoke: the comm.quantization config block must
# parse, activate the int8 codec, shrink the wire, and produce a
# schema-valid annotated census event + frozen quant gauge
run_gate "comm quant smoke" env JAX_PLATFORMS=cpu REPO="$REPO" "$PY" - <<'EOF'
import importlib.util, json, os, sys, tempfile
repo = os.environ["REPO"]
sys.path.insert(0, repo)
import numpy as np
import jax.numpy as jnp
from deepspeed_tpu.comm.quantize import CommQuantizer, quant_bytes_saved
from deepspeed_tpu.runtime.config import DeepSpeedConfig
from deepspeed_tpu.monitor.telemetry import Telemetry
from deepspeed_tpu.runtime.config import TelemetryConfig

cfg = DeepSpeedConfig({"train_batch_size": 4,
                       "comm": {"quantization": {"enabled": True,
                                                 "block_size": 64}}})
q = CommQuantizer.from_config(cfg.comm_quantization)
assert q.active(), "quantization config did not activate the codec"
g = jnp.asarray(np.random.default_rng(0).standard_normal(4096),
                dtype=jnp.float32)
out, saved = q.qdq_tree({"w": g}, "all_reduce")
assert saved == quant_bytes_saved(4096, "float32", 64) > 0
err = float(jnp.linalg.norm(out["w"] - g) / jnp.linalg.norm(g))
assert err < 0.05, f"codec error {err}"
tmp = tempfile.mkdtemp()
tel = Telemetry().configure(TelemetryConfig(
    {"enabled": True, "output_path": tmp, "job_name": "quant_smoke"}),
    rank=0)
tel.collective("all_reduce", g.size * 4 - saved, "fsdp", dtype="float32",
               world=4, wire_dtype="int8", bytes_saved=int(saved))
tel.close()
spec = importlib.util.spec_from_file_location(
    "checker", os.path.join(repo, "scripts",
                            "check_telemetry_schema.py"))
checker = importlib.util.module_from_spec(spec)
spec.loader.exec_module(checker)
events = [json.loads(l) for l in
          open(os.path.join(tmp, "quant_smoke", "events.jsonl"))]
problems = [p for ev in events for p in checker.validate_event(ev)]
assert not problems, problems[:3]
annotated = [ev for ev in events if ev.get("bytes_saved")]
assert annotated, "no bytes_saved-annotated census event emitted"
gauges = [ev for ev in events if ev.get("kind") == "gauge" and
          str(ev.get("name", "")).startswith("comm/")]
assert all(ev["name"] in checker.QUANT_GAUGES for ev in gauges)
print(f"quant smoke: saved {int(saved)} bytes, rel err {err:.4f}, "
      f"{len(events)} schema-valid events")
EOF

# 7. trace export: every telemetry stream found under TELEMETRY_DIR must
# convert to a valid Chrome trace-event file (attribution flow arrows
# included) — the exporter is the debugging path of last resort, so a
# stream it chokes on is a gate failure, not a rendering nit
if [ -n "$TELEMETRY_DIR" ] && [ -d "$TELEMETRY_DIR" ]; then
    mapfile -t trace_dirs < <(find "$TELEMETRY_DIR" -name 'events*.jsonl' \
                                   -type f -exec dirname {} \; |
                              sort -u)
    if [ "${#trace_dirs[@]}" -gt 0 ]; then
        trace_tmp="$(mktemp -d)"
        trap 'rm -rf "$trace_tmp"' EXIT
        i=0
        for d in "${trace_dirs[@]}"; do
            run_gate "trace export ($d)" \
                "$PY" "$REPO/scripts/ds_trace_export.py" "$d" \
                --check -o "$trace_tmp/trace.$i.json"
            i=$((i + 1))
        done
    else
        echo "== gate: trace export == SKIP (no events*.jsonl under" \
             "$TELEMETRY_DIR)"
    fi
else
    echo "== gate: trace export == SKIP (no telemetry dir given)"
fi

# 8. overlap smoke: a ZeRO-3 config with zero_optimization.overlap on
# must run the double-buffered step on the simulated 8-device mesh with
# a bit-identical forward vs the serial oracle (the gather pipeline may
# reorder communication, never math), the trajectory inside ulp
# tolerance, and the frozen comm/overlap/* + step/attr/exposed_comm_frac
# gauges riding a schema-valid stream
run_gate "overlap smoke" env JAX_PLATFORMS=cpu REPO="$REPO" "$PY" - <<'EOF'
import importlib.util, json, os, sys, tempfile
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8").strip()
repo = os.environ["REPO"]
sys.path.insert(0, repo)
import numpy as np
import jax
import jax.numpy as jnp
import deepspeed_tpu
from deepspeed_tpu.parallel import groups
from deepspeed_tpu.runtime.zero.stage_plan import layer_scan

HIDDEN, LAYERS = 16, 4

class Stacked:
    def init(self, key):
        k1, k2 = jax.random.split(key)
        return {"layers": {"w": jax.random.normal(
                               k1, (LAYERS, HIDDEN, HIDDEN)) * 0.1,
                           "b": jnp.zeros((LAYERS, HIDDEN))},
                "out": jax.random.normal(k2, (HIDDEN, HIDDEN)) * 0.1}

    def tp_rules(self):
        from jax.sharding import PartitionSpec as P
        return [(r"\['w'\]$", P("fsdp")), (r"\['b'\]$", P("fsdp"))]

    def apply(self, params, x):
        def body(h, layer):
            return jnp.tanh(h @ layer["w"] + layer["b"]), None
        h, _ = layer_scan(body, x, params["layers"])
        return h @ params["out"]

    def loss(self, params, batch, rng=None):
        x, y = jnp.asarray(batch["x"]), jnp.asarray(batch["y"])
        return jnp.mean(jnp.square(self.apply(params, x) - y))

def batch(i):
    rng = np.random.default_rng(i)
    x = rng.normal(size=(32, HIDDEN)).astype(np.float32)
    return {"x": x, "y": np.roll(x, 1, axis=-1) * 0.5}

def run(zero, tmp=None):
    groups.reset_mesh()
    model = Stacked()
    params = model.init(jax.random.key(0))
    cfg = {"train_micro_batch_size_per_gpu": 4,
           "optimizer": {"type": "Adam",
                         "params": {"lr": 1e-2, "weight_decay": 0.0}},
           "zero_optimization": dict({"stage": 3,
                                      "param_persistence_threshold": 0},
                                     **zero),
           "mesh": {"dp": 2, "fsdp": 4}}
    if tmp:
        cfg["telemetry"] = {"enabled": True, "output_path": tmp,
                            "job_name": "overlap_smoke",
                            "attribution": {"enabled": True}}
    engine, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, config=cfg)
    losses = [float(engine.train_batch(batch=batch(i))) for i in range(3)]
    if tmp:
        engine.flush_telemetry()
    return losses

serial = run({})
tmp = tempfile.mkdtemp()
over = run({"overlap": {"enabled": True, "gather_prefetch_depth": 1,
                        "rs_bucket_bytes": 2048}}, tmp=tmp)
assert serial[0] == over[0], \
    f"forward not bit-identical: {serial[0]} vs {over[0]}"
np.testing.assert_allclose(serial, over, rtol=5e-6, atol=1e-7)
stream = os.path.join(tmp, "overlap_smoke", "events.jsonl")
events = [json.loads(l) for l in open(stream)]
names = {ev.get("name") for ev in events if ev.get("kind") == "gauge"}
assert "step/attr/exposed_comm_frac" in names, sorted(names)
spec = importlib.util.spec_from_file_location(
    "checker", os.path.join(repo, "scripts",
                            "check_telemetry_schema.py"))
checker = importlib.util.module_from_spec(spec)
spec.loader.exec_module(checker)
missing = set(checker.OVERLAP_GAUGES) - names
assert not missing, f"missing overlap gauges: {sorted(missing)}"
problems = [p for ev in events for p in checker.validate_event(ev)]
assert not problems, problems[:3]
print(f"overlap smoke: 3 overlapped steps vs serial — step-0 loss "
      f"bit-identical ({serial[0]:.6f}), trajectory within ulp "
      f"tolerance, {len(checker.OVERLAP_GAUGES)} overlap gauges + "
      f"exposed_comm_frac on a {len(events)}-event schema-valid stream")
EOF

# 9. cross-process fleet smoke: a 2-worker subprocess fleet must serve
# the same tokens as the in-process fleet bit-for-bit, then survive a
# real kill -9 of one worker mid-decode with zero lost requests — every
# id reaches exactly one typed terminal, survivors stay bit-identical,
# and the death is booked as a schema-valid fleet/worker_lost event plus
# a worker_lost incident bundle the checker accepts
run_gate "fleet xproc smoke" env JAX_PLATFORMS=cpu REPO="$REPO" "$PY" - <<'EOF'
import importlib.util, json, os, signal, sys, tempfile
repo = os.environ["REPO"]
sys.path.insert(0, repo)
from deepspeed_tpu.inference.fleet import FleetRouter
from deepspeed_tpu.inference.fleet_worker import tiny_engine_factory
from deepspeed_tpu.monitor.telemetry import Telemetry
from deepspeed_tpu.runtime.config import TelemetryConfig

SPEC = {"factory":
        "deepspeed_tpu.inference.fleet_worker:tiny_engine_factory",
        "kwargs": {}}
XPROC = {"mode": "subprocess", "heartbeat_interval_s": 0.2,
         "heartbeat_deadline_s": 10.0}
PROMPTS = {f"q{i}": [1 + i, 2 + i, 3 + i, 4 + i] for i in range(6)}

def run(factory, fleet, kill_rid=None, telemetry=None):
    router = FleetRouter(factory, fleet=fleet, telemetry=telemetry)
    try:
        for rid, p in sorted(PROMPTS.items()):
            router.submit(rid, p, max_new_tokens=6, temperature=0.7,
                          seed=11)
        killed = False
        for step in range(300):
            if kill_rid and step == 3 and not killed:
                os.kill(router.replicas[kill_rid].handle.proc.pid,
                        signal.SIGKILL)
                killed = True
            router.step()
            if not router._unresolved():
                break
        assert not router._unresolved(), "fleet did not converge"
        return (dict(router.finished), router.pop_terminated(),
                router.leak_report(), dict(router.stats))
    finally:
        router.close()

base = {"replicas": 2, "health_interval": 4}
ref, term, leaks, _ = run(tiny_engine_factory, dict(base))
assert not term and leaks == {}, (term, leaks)

out, term, leaks, _ = run(SPEC, dict(base, transport=dict(XPROC)))
assert not term and leaks == {}, (term, leaks)
assert out == ref, "subprocess fleet not bit-identical to in-process"

tmp = tempfile.mkdtemp()
tel = Telemetry().configure(TelemetryConfig(
    {"enabled": True, "output_path": tmp, "job_name": "xproc_gate",
     "incidents": {"enabled": True, "cooldown_s": 0.0}}), rank=0)
try:
    out, term, leaks, stats = run(SPEC, dict(base, transport=dict(XPROC)),
                                  kill_rid="r0", telemetry=tel)
finally:
    tel.close()
assert leaks == {}, leaks
assert stats["workers_lost"] == 1, stats
assert set(out) | set(term) == set(PROMPTS), (set(out), set(term))
assert not (set(out) & set(term)), "a request reached two terminals"
for rid, toks in out.items():
    assert toks == ref[rid], f"{rid} diverged after kill -9"

spec = importlib.util.spec_from_file_location(
    "checker", os.path.join(repo, "scripts",
                            "check_telemetry_schema.py"))
checker = importlib.util.module_from_spec(spec)
spec.loader.exec_module(checker)
stream = os.path.join(tmp, "xproc_gate", "events.jsonl")
assert checker.validate_file(stream) == [], "event stream schema-invalid"
events = [json.loads(l) for l in open(stream) if l.strip()]
assert any(e.get("kind") == "fleet" and
           e.get("name") == "fleet/worker_lost" for e in events)
assert any(e.get("kind") == "incident" and
           e.get("trigger") == "worker_lost" for e in events)
bundles = os.path.join(tmp, "xproc_gate", "incidents")
problems, n_bundles = checker.validate_incidents_path(bundles)
assert problems == [], problems[:3]
assert n_bundles >= 1, "no incident bundle written"
print(f"fleet xproc smoke: {len(ref)} requests bit-identical across the "
      f"process boundary; kill -9 mid-decode -> {len(out)} finished + "
      f"{len(term)} re-terminated, zero lost, workers_lost="
      f"{stats['workers_lost']}, respawns={stats['respawns']}, "
      f"schema-valid worker_lost event + incident bundle")
EOF

# 10. chaos smoke: deterministic wire-fault campaign over the 2-worker
# subprocess fleet — lost add_request ack (channel retry + ikey dedup),
# slow worker (circuit breaker opens, probes, closes; no respawn), and a
# torn commit_import ack (gray migrate recovers exactly-once). Each
# scenario asserts zero lost requests, one terminal per request, empty
# leak report, bit-identical survivors vs an in-process reference, and
# checker-valid telemetry.
run_gate "chaos smoke" env JAX_PLATFORMS=cpu "$PY" \
    "$REPO/scripts/ds_chaos.py" --scenarios ack_loss,slow_worker,torn_commit

# 11. tiered-store smoke: a memory config block must build a TieredStore
# whose quantized NVMe entries carry their scale sidecars, whose sealed
# directory fscks COMMITTED (and flags a torn payload file as partial),
# and whose frozen tier/* gauges ride a schema-valid stream
run_gate "tiered smoke" env JAX_PLATFORMS=cpu REPO="$REPO" "$PY" - <<'EOF'
import importlib.util, json, os, sys, tempfile
repo = os.environ["REPO"]
sys.path.insert(0, repo)
import numpy as np
from deepspeed_tpu.monitor import telemetry as telmod
from deepspeed_tpu.runtime import resilience
from deepspeed_tpu.runtime.config import DeepSpeedConfig, TelemetryConfig
from deepspeed_tpu.runtime.tiered_store import TieredStore

tmp = tempfile.mkdtemp(prefix="tiered_gate_")
cfg = DeepSpeedConfig({
    "train_batch_size": 1,
    "memory": {"placement_policy": "nvme", "nvme_dir": tmp,
               "quantize_tiers": True, "quant_block": 64},
})
tel = telmod.get_telemetry().configure(TelemetryConfig(
    {"enabled": True, "output_path": tmp, "job_name": "tier_gate"}),
    rank=0)
store = TieredStore.from_config(cfg.memory_config, name="gate")
rng = np.random.default_rng(0)
W = {f"L{i}": rng.standard_normal(256).astype(np.float32)
     for i in range(4)}
for k, v in W.items():
    store.put(k, v)
store.commit()
status, manifest = store.validate()
assert status == resilience.COMMITTED, status
listed = [f["path"] for f in manifest["files"]]
assert any(p.endswith(".scales.bin") for p in listed), listed
for k, v in W.items():
    got = store.fetch(k)
    bound = float(np.max(np.abs(v))) / 127.0
    assert float(np.max(np.abs(got - v))) <= bound
store.publish_gauges()
tel.close()
# torn payload file -> the fsck verdict flips to partial
victim = os.path.join(store.nvme_path,
                      next(p for p in listed if p.endswith(".q.bin")))
with open(victim, "r+b") as f:
    f.truncate(8)
assert store.validate()[0] == resilience.PARTIAL
spec = importlib.util.spec_from_file_location(
    "checker", os.path.join(repo, "scripts",
                            "check_telemetry_schema.py"))
checker = importlib.util.module_from_spec(spec)
spec.loader.exec_module(checker)
stream = os.path.join(tmp, "tier_gate", "events.jsonl")
assert checker.validate_file(stream) == [], "event stream schema-invalid"
events = [json.loads(l) for l in open(stream) if l.strip()]
names = {e["name"] for e in events if e.get("kind") == "gauge"
         and str(e.get("name", "")).startswith("tier/")}
assert "tier/nvme_bytes" in names and "tier/quant_bytes_saved" in names
print(f"tiered smoke: memory config -> {len(W)} int8 NVMe entries with "
      f"manifest-listed scale sidecars, fsck COMMITTED -> torn file "
      f"flagged partial, {len(names)} tier/* gauges schema-valid")
EOF

if [ "$fail" -ne 0 ]; then
    echo "GATES: FAIL"
    exit 1
fi
echo "GATES: OK"
