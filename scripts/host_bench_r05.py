"""Round-5 host-side benchmark campaign -> BENCH_host_r05.json.

Captures, on THIS build host (real hardware, no synthetic SlowHandle):
  * cpu_adam fused C++ vs numpy (now with 2 vCPUs / OpenMP, vs r3's 1)
  * NVMe-swapped optimizer pipeline vs serial (benchmarks.offload)
  * param-stream GAS-boundary threaded pipeline vs serial walk, and the
    streamed writeback vs serial D2H/Adam/upload
    (benchmarks.param_stream_boundary) — round-4 verdict, next #4.

Run:  python scripts/host_bench_r05.py
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "BENCH_host_r05.json")


def _run(mod, args, timeout=1200):
    cmd = [sys.executable, "-m", mod] + args
    try:
        p = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=timeout, cwd=REPO)
        stdout, rc, err = p.stdout, p.returncode, (p.stderr or "")[-500:]
    except subprocess.TimeoutExpired as e:
        # keep what the section printed before stalling; the campaign (and
        # its final artifact write) must survive one slow section
        stdout = (e.stdout.decode() if isinstance(e.stdout, bytes)
                  else (e.stdout or ""))
        rc, err = -9, f"timeout after {timeout}s"
    rows = []
    for line in (stdout or "").splitlines():
        try:
            rows.append(json.loads(line))
        except json.JSONDecodeError:
            continue
    return rows, rc, err


def main():
    nproc = os.cpu_count()
    out = {
        "description": "Host-side benchmark artifact (round-5): cpu_adam "
                       "fused pass, NVMe offload pipeline, param-stream "
                       "boundary pipeline + streamed writeback. All on the "
                       "real build host (no synthetic stores).",
        "captured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "environment": {"nproc": nproc},
    }

    rows, rc, err = _run("deepspeed_tpu.benchmarks.cpu_adam",
                         ["--numel", "50000000", "--reps", "3"])
    out["cpu_adam"] = {"rows": rows, "rc": rc, **({"err": err} if rc else {})}

    rows, rc, err = _run("deepspeed_tpu.benchmarks.offload",
                         ["--numel", "100000000", "--sub-groups", "8",
                          "--reps", "3"])
    out["offload_nvme_pipeline"] = {"rows": rows, "rc": rc,
                                    **({"err": err} if rc else {})}

    boundary = {}
    for label, hidden, layers, vocab in (("137m", "1024", "8", "16384"),
                                         ("956m", "2048", "16", "32768")):
        rows, rc, err = _run(
            "deepspeed_tpu.benchmarks.param_stream_boundary",
            ["--cpu", "--hidden", hidden, "--layers", layers,
             "--vocab", vocab, "--numel", "100000000", "--reps", "3"],
            timeout=2400)
        boundary[label] = {"rows": rows, "rc": rc,
                           **({"err": err} if rc else {})}
    out["param_stream_boundary"] = boundary

    speedups = {}
    wb = {}
    for label, sec in boundary.items():
        for row in sec["rows"]:
            if row.get("section") == "boundary":
                speedups[label] = row.get("speedup_x")
            if row.get("section") == "writeback":
                wb[label] = row.get("speedup_x")
    out["summary"] = {
        "boundary_pipeline_speedup_x": speedups,
        # worst case across sizes: the honest number against the 1.25x bar
        "boundary_min_x": min([s for s in speedups.values() if s],
                              default=None),
        "writeback_speedup_x": wb,
        "note": "boundary >= 1.25x is the round-4 verdict #4 bar. On this "
                "2-core build host every stage is memory-bandwidth-bound, "
                "so the upload-under-Adam overlap is partial and shrinks "
                "as the model grows; the writeback pipeline's win is "
                "chip-side (real H2D/D2H DMA) — on the CPU backend "
                "transfers are host memcpys, so ~1.0x here is expected. "
                "The on-chip program re-measures both on the real chip "
                "(onchip_r05 boundary step).",
    }
    with open(OUT, "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    print(json.dumps(out["summary"]))


if __name__ == "__main__":
    main()
