"""Benchmark: training throughput of the flagship model on the available
chip(s).  Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Baseline anchor: the reference's headline "ZeRO-3 Offload sustains up to
50 TFLOPs/GPU" (BASELINE.md, docs/_posts/2021-03-08-zero3-offload.md:65);
``vs_baseline`` = our achieved model TFLOPs/chip ÷ 50.
"""

import json
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.models.transformer import (CausalTransformerLM,
                                                  TransformerConfig)

    on_tpu = jax.default_backend() not in ("cpu",)
    if on_tpu:
        # batch 16 measured best on v5e (MXU utilisation vs HBM working set)
        cfg = TransformerConfig.gpt2_125m(remat=True)
        batch, seq, steps = 16, 1024, 20
    else:  # CI smoke
        cfg = TransformerConfig.tiny()
        batch, seq, steps = 4, 128, 3

    model = CausalTransformerLM(cfg)
    params = model.init(jax.random.key(0))

    ds_config = {
        "train_micro_batch_size_per_gpu": batch,
        "optimizer": {"type": "AdamW",
                      "params": {"lr": 1e-4, "weight_decay": 0.0}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 0},
    }
    engine, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, config=ds_config)

    rng = np.random.default_rng(0)
    def make_batch():
        return {"input_ids": rng.integers(0, cfg.vocab_size, (batch, seq))}

    # warmup/compile
    engine.train_batch(batch=make_batch())
    jax.block_until_ready(engine.state)

    t0 = time.time()
    for _ in range(steps):
        loss = engine.train_batch(batch=make_batch())
    jax.block_until_ready(loss)
    dt = time.time() - t0

    tokens_per_sec = batch * seq * steps / dt
    # 6ND flops per token for fwd+bwd
    n_params = cfg.num_params()
    tflops = 6.0 * n_params * tokens_per_sec / 1e12
    n_chips = max(1, len(jax.devices()))
    result = {
        "metric": f"train_tokens_per_sec_per_chip_gpt2_125m_bf16_seq{seq}",
        "value": round(tokens_per_sec / n_chips, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(tflops / n_chips / 50.0, 3),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
