"""Benchmark: training throughput of the largest GPT that fits the chip.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}

Design notes (round-2 hardening):

* The TPU backend behind ``jax.devices()`` can hang forever or raise while
  initialising (observed both in round 1).  So the parent process NEVER
  imports jax: every JAX touch happens in a subprocess with a timeout, and
  backend init failure degrades (retry -> CPU fallback -> error JSON) instead
  of crashing.  rc is 0 in all paths.
* Model selection: largest GPT config whose ZeRO-3 + remat footprint fits in
  measured HBM (not a fixed 125M toy).
* Reported: tokens/s/chip (headline), achieved model TFLOPs, MFU vs the
  chip's actual bf16 peak, and a measured max-params-on-one-chip probe with
  host optimizer offload (analytic estimate if the probe can't run).

Baseline anchor: the reference's headline "ZeRO-3 Offload sustains up to
50 TFLOPs/GPU" (BASELINE.md, docs/_posts/2021-03-08-zero3-offload.md:65);
``vs_baseline`` = our achieved model TFLOPs/chip / 50.
"""

import json
import os
import subprocess
import sys
import time

_T0 = time.time()
_BUDGET_S = int(os.environ.get("BENCH_BUDGET_S", "520"))

# Every on-chip result is persisted here (committed to the repo), so a
# tunnel outage at round end degrades to "stale on-chip number, clearly
# dated" instead of "no reviewable on-chip evidence at all" (round-2
# verdict, weak #1).
_ONCHIP_CACHE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BENCH_onchip_latest.json")


def _save_onchip(result):
    try:
        entry = dict(result, captured_unix=int(time.time()),
                     captured_utc=time.strftime(
                         "%Y-%m-%dT%H:%M:%SZ", time.gmtime()))
        with open(_ONCHIP_CACHE, "w") as f:
            json.dump(entry, f, indent=1)
            f.write("\n")
    except OSError:
        pass


# beyond this age the cached record degrades back to the run's own (bad)
# numbers — a months-stale artifact must not read as today's measurement
_MAX_CACHE_AGE_H = float(os.environ.get("BENCH_MAX_CACHE_AGE_H", 24 * 30))


def _promote_cached(this_run):
    """Degraded run (tunnel down / CPU fallback): promote the dated on-chip
    record to the TOP-LEVEL metric, provenance-labeled, so the scoreboard
    reflects the best real TPU evidence regardless of tunnel state (round-4
    verdict, next #2).  The degraded run's own numbers ride along under
    ``this_run`` so nothing is hidden; ``fallback: "cached_onchip"`` plus
    ``cache_age_hours`` make the provenance unambiguous.  Records older
    than ``_MAX_CACHE_AGE_H`` are attached but not promoted."""
    cached = _load_onchip()
    if not cached:
        return this_run
    # an undated record cannot pass the staleness cap: attach, don't promote
    if not cached.get("captured_unix"):
        this_run["last_known_onchip"] = cached
        return this_run
    age_h = round((time.time() - int(cached["captured_unix"])) / 3600.0, 1)
    if age_h > _MAX_CACHE_AGE_H:
        # the age belongs to the cached record, not this run's metrics —
        # nest it, and mark the non-promotion explicitly
        stale = dict(cached)
        stale["cache_age_hours"] = age_h
        this_run["last_known_onchip"] = stale
        this_run["cache_too_stale"] = True
        return this_run
    out = dict(cached)
    out["fallback"] = "cached_onchip"
    out["cache_age_hours"] = age_h
    out["this_run"] = this_run
    return out


def _load_onchip():
    try:
        with open(_ONCHIP_CACHE) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _remaining():
    return _BUDGET_S - (time.time() - _T0)

# ---------------------------------------------------------------------------
# chip tables (bf16 dense peak per jax device, HBM fallback per device)
# ---------------------------------------------------------------------------
_PEAK_TFLOPS = [
    ("v6e", 918.0), ("v6 lite", 918.0), ("v6", 918.0),
    ("v5p", 459.0), ("v5e", 197.0), ("v5 lite", 197.0), ("v5", 459.0),
    ("v4", 275.0), ("v3", 61.5), ("v2", 22.5),
]
_HBM_FALLBACK = [
    ("v6", 32e9), ("v5p", 95e9), ("v5e", 16e9), ("v5 lite", 16e9),
    ("v5", 95e9), ("v4", 32e9), ("v3", 16e9), ("v2", 8e9),
]


def _lookup(table, kind, default):
    k = (kind or "").lower()
    for sub, val in table:
        if sub in k:
            return val
    return default


# Mirrors TransformerConfig.loss_chunk_size's default (the parent process
# must not import jax — see module docstring); pinned by
# tests/unit/test_model.py::test_bench_loss_chunk_matches_config.
LOSS_CHUNK_TOKENS = 4096

# GPT ladder: (name, kwargs for TransformerConfig) — GPT-2/3 family shapes.
_LADDER = [
    ("gpt_6_7b", dict(vocab_size=50304, hidden_size=4096, n_layers=32,
                      n_heads=32, max_seq_len=2048, activation="gelu",
                      use_rmsnorm=False, use_rope=False, tie_embeddings=True)),
    ("gpt_2_7b", dict(vocab_size=50304, hidden_size=2560, n_layers=32,
                      n_heads=32, max_seq_len=2048, activation="gelu",
                      use_rmsnorm=False, use_rope=False, tie_embeddings=True)),
    ("gpt2_1_5b", dict(vocab_size=50304, hidden_size=1600, n_layers=48,
                       n_heads=25, max_seq_len=1024, activation="gelu",
                       use_rmsnorm=False, use_rope=False, tie_embeddings=True)),
    ("gpt_760m", dict(vocab_size=50304, hidden_size=1536, n_layers=24,
                      n_heads=16, max_seq_len=1024, activation="gelu",
                      use_rmsnorm=False, use_rope=False, tie_embeddings=True)),
    ("gpt_350m", dict(vocab_size=50304, hidden_size=1024, n_layers=24,
                      n_heads=16, max_seq_len=1024, activation="gelu",
                      use_rmsnorm=False, use_rope=False, tie_embeddings=True)),
    ("gpt2_125m", dict(vocab_size=50304, hidden_size=768, n_layers=12,
                       n_heads=12, max_seq_len=1024, activation="gelu",
                       use_rmsnorm=False, use_rope=False, tie_embeddings=True)),
]


def _n_params(kw):
    d, v, L = kw["hidden_size"], kw["vocab_size"], kw["n_layers"]
    f = 4 * d
    per_layer = 4 * d * d + 2 * d * f + 2 * d
    return L * per_layer + v * d + d + kw["max_seq_len"] * d


def _footprint(kw, batch, seq, n_chips=1):
    """ZeRO-3 per-chip training footprint: bf16 params + bf16 grads +
    fp32 master + 2x fp32 Adam moments = 18 B/param (all sharded over the
    fsdp axis), plus remat'd activations and the streamed loss chunk.
    The fp32 [B,S,V] logits tensor no longer appears: the model's chunked
    cross-entropy (models/transformer.py chunked_next_token_xent) streams
    logits in fixed-size token chunks under a remat'd scan."""
    n = _n_params(kw)
    states = 18.0 * n / n_chips
    b = max(1.0, batch / n_chips)
    acts = 2.0 * b * seq * kw["hidden_size"] * (kw["n_layers"] + 8)
    loss_chunk = 4.0 * LOSS_CHUNK_TOKENS * kw["vocab_size"] * 2  # + bwd copy
    return states + acts + loss_chunk


# ---------------------------------------------------------------------------
# workers (run in subprocesses; each prints one JSON line on stdout)
# ---------------------------------------------------------------------------

def _worker_probe():
    import jax
    d = jax.devices()[0]
    hbm = 0
    try:
        stats = d.memory_stats() or {}
        hbm = int(stats.get("bytes_limit", 0))
    except Exception:
        pass
    print(json.dumps({
        "platform": d.platform,
        "kind": getattr(d, "device_kind", ""),
        "n_devices": len(jax.devices()),
        "hbm": hbm,
    }))


def _worker_train(spec):
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models.transformer import (CausalTransformerLM,
                                                  TransformerConfig)
    import jax

    cfg = TransformerConfig(**spec["model"], remat=spec["remat"],
                            remat_policy=spec.get("remat_policy",
                                                  "dots_saveable"))
    model = CausalTransformerLM(cfg)
    params = model.init(jax.random.key(0))

    gas = int(spec.get("gas", 1))
    opt_params = {"lr": 1e-4, "weight_decay": 0.0}
    if spec.get("moment_dtype"):
        opt_params["moment_dtype"] = spec["moment_dtype"]
    ds_config = {
        "train_micro_batch_size_per_gpu": spec["batch"],
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": opt_params},
        "bf16": {"enabled": True},
        "zero_optimization": dict(spec.get("zero", {"stage": 3})),
    }
    if spec.get("grad_accum_dtype"):
        ds_config["data_types"] = {
            "grad_accum_dtype": spec["grad_accum_dtype"]}
    engine, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, config=ds_config)
    del params

    rng = np.random.default_rng(0)
    batch, seq, steps = spec["batch"], spec["seq"], spec["steps"]

    def make_batch():
        shape = (gas, batch, seq) if gas > 1 else (batch, seq)
        return {"input_ids": rng.integers(0, cfg.vocab_size, shape)}

    engine.train_batch(batch=make_batch())       # compile + warmup
    jax.block_until_ready(engine.state.params)

    t0 = time.time()
    loss = None
    for _ in range(steps):
        loss = engine.train_batch(batch=make_batch())
    jax.block_until_ready(loss)
    dt = time.time() - t0

    print(json.dumps({
        "tokens_per_sec": gas * batch * seq * steps / dt,
        "n_params": cfg.num_params(),
        "loss": float(loss),
        "dt": dt,
    }))


def _worker_params_probe(spec):
    """One param-stream (training-time parameter offload) train step at the
    requested size; success means the model is trainable on this chip.
    The full tree never enters HBM: init runs on the HOST backend and the
    step streams a double-buffered per-layer working set."""
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models.transformer import (CausalTransformerLM,
                                                  TransformerConfig)
    import jax
    import jax.numpy as jnp

    cfg = TransformerConfig(**spec["model"], remat=True)
    model = CausalTransformerLM(cfg)
    with jax.default_device(jax.devices("cpu")[0]):
        params = model.init(jax.random.key(0), dtype=jnp.bfloat16)
    params = jax.tree_util.tree_map(np.asarray, params)
    engine, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
            "bf16": {"enabled": True},
            "data_types": {"grad_accum_dtype": "bfloat16"},
            "zero_optimization": {
                "stage": 0,
                "offload_param": {"device": "cpu", "buffer_count": 2},
                "offload_optimizer": {"device": "cpu"},
            },
        })
    del params
    rng = np.random.default_rng(0)
    loss = engine.train_batch(
        batch={"input_ids": rng.integers(0, cfg.vocab_size, (1, spec["seq"]))})
    jax.block_until_ready(loss)
    print(json.dumps({"ok": bool(np.isfinite(float(loss))),
                      "n_params": cfg.num_params(),
                      "via": "param_stream"}))


def _dispatch_bench(spec=None):
    """CPU-runnable async-step-pipeline micro-bench (returns a dict so tests
    can call it in-process; the ``dispatch`` worker prints it).

    Measures steps/sec of a small jitted train loop fed by a generator with
    ``feed_delay_ms`` of injected host latency per batch, twice with
    telemetry enabled: (A) the synchronous baseline — inline feed plus a
    per-step metric readback (``sync_interval`` 1), so each step pays
    feed + compute; (B) the async pipeline — prefetch worker + deferred
    readback, so each step pays max(feed, compute).  This is the stall the
    tentpole removes, measurable with no TPU attached."""
    spec = spec or {}
    import copy
    import tempfile

    import numpy as np

    import deepspeed_tpu
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.monitor.telemetry import get_telemetry

    hidden = int(spec.get("hidden", 512))
    batch = int(spec.get("batch", 64))
    steps = int(spec.get("steps", 25))
    warmup = int(spec.get("warmup", 3))
    delay_ms = float(spec.get("feed_delay_ms", 10.0))
    depth = int(spec.get("prefetch_depth", 4))
    interval = int(spec.get("sync_interval", 8))

    def loss_fn(params, b, rng):
        h = b["x"]
        for w in params["w"]:
            h = jnp.tanh(h @ w)
        return jnp.mean((h - b["y"]) ** 2)

    prng = np.random.default_rng(0)
    params0 = {"w": [prng.standard_normal((hidden, hidden))
                     .astype(np.float32) * 0.05 for _ in range(4)]}

    def make_feed(n):
        r = np.random.default_rng(1)
        for _ in range(n):
            time.sleep(delay_ms / 1000.0)
            yield {"x": r.standard_normal((batch, hidden)).astype(np.float32),
                   "y": r.standard_normal((batch, hidden)).astype(np.float32)}

    def run(async_on):
        tmp = tempfile.mkdtemp(prefix="dispatch_bench_")
        cfg = {
            "train_micro_batch_size_per_gpu": batch,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "telemetry": {"enabled": True, "output_path": tmp,
                          "stall_watchdog": False, "hbm_gauges": False},
        }
        if async_on:
            cfg["async_pipeline"] = {"enabled": True,
                                     "prefetch_depth": depth,
                                     "sync_interval": interval}
        engine, *_ = deepspeed_tpu.initialize(
            model=loss_fn, model_parameters=copy.deepcopy(params0),
            config=cfg)
        feed = make_feed(steps + warmup)
        for _ in range(warmup):
            engine.train_batch(data_iter=feed)
        jax.block_until_ready(engine.state.params)
        t0 = time.perf_counter()
        loss = None
        for _ in range(steps):
            loss = engine.train_batch(data_iter=feed)
        jax.block_until_ready(loss)
        dt = time.perf_counter() - t0
        engine.flush_telemetry()
        get_telemetry().close()
        return steps / dt

    sync_sps = run(False)
    prefetch_sps = run(True)
    return {
        "steps_per_sec_sync": round(sync_sps, 2),
        "steps_per_sec_prefetch": round(prefetch_sps, 2),
        "prefetch_speedup": round(prefetch_sps / max(sync_sps, 1e-9), 3),
        "injected_feed_ms": delay_ms,
        "sync_interval": interval,
        "prefetch_depth": depth,
    }


def _worker_dispatch(spec):
    print(json.dumps(_dispatch_bench(spec)))


def _serving_bench(spec=None):
    """CPU-runnable serving-overload micro-bench (returns a dict so tests
    can call it in-process; the ``serving`` worker prints it).

    Drives the continuous-batching engine at an offered load well above
    capacity (``arrivals_per_step`` new requests per decode step against a
    small batch) with a bounded queue and the shed-oldest policy, and
    measures what the hardening layer is FOR: the shed rate under overload
    and the served-step latency tail (p50/p99) — plus a drive-by leak
    audit, which must come back empty."""
    spec = spec or {}
    import tempfile

    import numpy as np

    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.inference.robustness import RequestRejected
    from deepspeed_tpu.inference.serving import ServingEngine
    from deepspeed_tpu.models.transformer import (CausalTransformerLM,
                                                  TransformerConfig)
    from deepspeed_tpu.monitor.telemetry import Telemetry
    from deepspeed_tpu.runtime.config import TelemetryConfig

    n_requests = int(spec.get("requests", 48))
    arrivals = int(spec.get("arrivals_per_step", 3))
    max_new = int(spec.get("max_new_tokens", 8))
    warmup_steps = int(spec.get("warmup_steps", 3))
    policy = spec.get("policy", "shed-oldest")

    cfg = TransformerConfig.tiny(hidden_size=64, n_heads=4, n_kv_heads=2)
    model = CausalTransformerLM(cfg)
    params = model.init(jax.random.key(0))
    tmp = tempfile.mkdtemp(prefix="serving_bench_")
    tel = Telemetry().configure(
        TelemetryConfig({"enabled": True, "output_path": tmp,
                         "job_name": "serving_bench"}), rank=0)
    eng = ServingEngine(
        model, params, max_batch=4, page_size=8, max_seq=64,
        dtype=jnp.float32, telemetry=tel,
        serving={"max_queue": int(spec.get("max_queue", 8)),
                 "overload_policy": policy,
                 "queue_high_watermark": 6, "queue_low_watermark": 2})
    rng = np.random.default_rng(0)
    # prompt lengths 3..7 share one prefill bucket (8), so the latency
    # tail measures scheduling, not a late XLA compile of a new shape
    prompts = [rng.integers(0, cfg.vocab_size, (int(n),)).tolist()
               for n in rng.integers(3, 8, n_requests)]
    rejected = 0
    step_ms = []
    finished = {}
    next_req, si = 0, 0
    while next_req < n_requests or eng.queue or eng.n_active:
        for _ in range(arrivals):
            if next_req >= n_requests:
                break
            try:
                eng.add_request(next_req, prompts[next_req],
                                max_new_tokens=max_new)
            except RequestRejected:
                rejected += 1
            next_req += 1
        t0 = time.perf_counter()
        finished.update(eng.step())
        dt = (time.perf_counter() - t0) * 1000.0
        if si >= warmup_steps:
            step_ms.append(dt)
        si += 1
    health = eng.health()
    tel.close()
    vals = sorted(step_ms) or [0.0]

    def pct(q):
        return vals[min(len(vals) - 1,
                        max(0, int(round(q / 100.0 * (len(vals) - 1)))))]

    shed = eng.stats["shed"]
    return {
        "offered_requests": n_requests,
        "served": eng.stats["finished"],
        "shed": shed,
        "rejected": rejected,
        "shed_rate": round((shed + rejected) / max(1, n_requests), 3),
        "step_p50_ms": round(pct(50), 2),
        "step_p99_ms": round(pct(99), 2),
        "steps": si,
        "policy": policy,
        "leaks": eng.leak_report(),
        "oldest_request_age_s": health["oldest_request_age_s"],
    }


def _worker_serving(spec):
    print(json.dumps(_serving_bench(spec)))


def _serving_prefix_bench(spec=None):
    """CPU-runnable prefix-cache micro-bench: a repeated shared-prompt
    workload (one long system prefix, distinct short suffixes — the agent
    / few-shot serving shape) served twice, cache off then on.  Reports
    the page-level hit rate, fresh pages allocated, and prompt tokens
    actually prefilled under each mode — and asserts the whole point:
    outputs are BIT-IDENTICAL, so the cache is purely a latency/FLOPs
    optimisation, never a quality knob."""
    spec = spec or {}
    import tempfile

    import numpy as np

    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.inference.serving import ServingEngine
    from deepspeed_tpu.models.transformer import (CausalTransformerLM,
                                                  TransformerConfig)
    from deepspeed_tpu.monitor.telemetry import Telemetry
    from deepspeed_tpu.runtime.config import TelemetryConfig

    n_requests = int(spec.get("requests", 12))
    shared_len = int(spec.get("shared_prefix_tokens", 48))
    max_new = int(spec.get("max_new_tokens", 4))

    cfg = TransformerConfig.tiny(hidden_size=64, n_heads=4, n_kv_heads=2)
    model = CausalTransformerLM(cfg)
    params = model.init(jax.random.key(0))
    rng = np.random.default_rng(0)
    shared = rng.integers(0, cfg.vocab_size, (shared_len,)).tolist()
    prompts = [shared + rng.integers(0, cfg.vocab_size, (int(n),)).tolist()
               for n in rng.integers(4, 9, n_requests)]

    def run(enabled):
        tmp = tempfile.mkdtemp(prefix="prefix_bench_")
        tel = Telemetry().configure(
            TelemetryConfig({"enabled": True, "output_path": tmp,
                             "job_name": "prefix_bench"}), rank=0)
        eng = ServingEngine(
            model, params, max_batch=4, page_size=8, max_seq=128,
            dtype=jnp.float32, telemetry=tel,
            serving={"prefix_cache": {"enabled": enabled}})
        t0 = time.perf_counter()
        outs = eng.generate(prompts, max_new_tokens=max_new)
        wall = time.perf_counter() - t0
        eng.health()   # push the serve/prefix_* gauges before close
        leaks = eng.leak_report()
        tel.close()
        prefilled = sum(len(p) for p in prompts)
        snap = {}
        if eng.prefix_cache is not None:
            snap = eng.prefix_cache.snapshot()
            prefilled -= snap["tokens_reused"]
        return {"outs": outs, "wall_s": wall, "leaks": leaks,
                "pages_allocated": eng.alloc.pages_taken,
                "prompt_tokens_prefilled": prefilled, "cache": snap}

    off = run(False)
    on = run(True)
    return {
        "requests": n_requests,
        "shared_prefix_tokens": shared_len,
        "bit_identical": on["outs"] == off["outs"],
        "prefix_hit_rate": on["cache"]["hit_rate"],
        "pages_reused": on["cache"]["pages_reused"],
        "tokens_reused": on["cache"]["tokens_reused"],
        "cow_copies": on["cache"]["cow_copies"],
        "pages_allocated_off": off["pages_allocated"],
        "pages_allocated_on": on["pages_allocated"],
        "prompt_tokens_prefilled_off": off["prompt_tokens_prefilled"],
        "prompt_tokens_prefilled_on": on["prompt_tokens_prefilled"],
        "wall_s_off": round(off["wall_s"], 3),
        "wall_s_on": round(on["wall_s"], 3),
        "leaks_off": off["leaks"],
        "leaks_on": on["leaks"],
    }


def _worker_serving_prefix(spec):
    print(json.dumps(_serving_prefix_bench(spec)))


def _fleet_bench(spec=None):
    """CPU-runnable fleet micro-bench: a shared-prefix workload (several
    prompt families, distinct suffixes) served by one replica and by a
    fleet, then again with a mid-flight injected ``replica_kill``.
    Reports aggregate decode throughput at each replica count (the
    scaling claim), per-replica prefix-cache hit rates (the affinity
    claim — fleet routing must keep them at single-engine levels), and
    the kill run's recovery cost (extra wall/steps over the no-fault
    fleet run) with zero lost requests."""
    spec = spec or {}
    import numpy as np

    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.inference.fleet import FleetRouter
    from deepspeed_tpu.inference.serving import ServingEngine
    from deepspeed_tpu.models.transformer import (CausalTransformerLM,
                                                  TransformerConfig)
    from deepspeed_tpu.runtime.resilience import FaultInjector

    n_replicas = int(spec.get("replicas", 3))
    n_requests = int(spec.get("requests", 18))
    max_new = int(spec.get("max_new_tokens", 6))
    prefix_len = int(spec.get("shared_prefix_tokens", 24))

    cfg = TransformerConfig.tiny(hidden_size=64, n_heads=4, n_kv_heads=2)
    model = CausalTransformerLM(cfg)
    params = model.init(jax.random.key(0))
    rng = np.random.default_rng(0)
    families = [rng.integers(0, cfg.vocab_size, (prefix_len,)).tolist()
                for _ in range(2 * n_replicas)]
    prompts = {
        f"q{i}": families[i % len(families)] +
        rng.integers(0, cfg.vocab_size, (4,)).tolist()
        for i in range(n_requests)}

    def factory(rid, epoch):
        return ServingEngine(
            model, params, max_batch=4, page_size=8, max_seq=128,
            dtype=jnp.float32, replica_epoch=epoch,
            serving={"prefix_cache": {"enabled": True}})

    def run(replicas, injector=None, health_interval=2):
        fleet = FleetRouter(
            factory,
            fleet={"replicas": replicas, "max_replicas": replicas + 1,
                   "health_interval": health_interval},
            injector=injector)
        # warm each engine's jit caches off the clock so the timed phase
        # measures serving, not per-replica compilation
        for rep in fleet.replicas.values():
            rep.engine.generate([prompts["q0"]], max_new_tokens=2)
        t0 = time.perf_counter()
        for rid, p in prompts.items():
            fleet.submit(rid, p, max_new_tokens=max_new)
        done = fleet.join(max_steps=2000)
        wall = time.perf_counter() - t0
        generated = sum(len(toks) - len(prompts[rid])
                        for rid, toks in done.items())
        hit_rates = [
            r["prefix_hit_rate"]
            for r in fleet.health()["replicas"].values()
            if r["prefix_hit_rate"] is not None and r["state"] == "healthy"]
        return {"fleet": fleet, "done": done, "wall_s": wall,
                "generated": generated,
                # replicas are parallel fault domains on real hardware but
                # step serially in this single process, so the scaling
                # claim is tokens per FLEET step (one round across all
                # replicas), not wall-clock
                "tokens_per_step": generated / max(fleet.steps, 1),
                "hit_rates": hit_rates, "steps": fleet.steps,
                "leaks": fleet.leak_report()}

    r1 = run(1)
    rn = run(n_replicas)
    kill = run(n_replicas, injector=FaultInjector(
        {"replica_kill": {"fail_at": [1], "msg": "bench chaos"}}))
    st = kill["fleet"].stats
    lost = st["submitted"] - st["finished"] - st["terminated"]
    return {
        "replicas": n_replicas,
        "requests": n_requests,
        "agg_tokens_per_step_single": round(r1["tokens_per_step"], 3),
        "agg_tokens_per_step_fleet": round(rn["tokens_per_step"], 3),
        "throughput_scale_frac": round(
            rn["tokens_per_step"] / max(r1["tokens_per_step"], 1e-9), 3),
        "prefix_hit_rate_single": r1["hit_rates"][0] if r1["hit_rates"]
        else 0.0,
        "prefix_hit_rate_fleet_min": min(rn["hit_rates"], default=0.0),
        "bit_identical": rn["done"] == r1["done"],
        "kill_bit_identical": kill["done"] == r1["done"],
        "kill_extra_wall_s": round(kill["wall_s"] - rn["wall_s"], 3),
        "kill_recovery_steps": kill["steps"] - rn["steps"],
        "kills": kill["fleet"].stats["kills"],
        "redispatches": kill["fleet"].stats["redispatches"],
        "respawns": kill["fleet"].stats["respawns"],
        "lost_requests": lost,
        "leaks_fleet": rn["leaks"],
        "leaks_kill": kill["leaks"],
    }


def _worker_fleet(spec):
    print(json.dumps(_fleet_bench(spec)))


def _fleet_disagg_bench(spec=None):
    """CPU-runnable disaggregated-fleet micro-bench: a mixed workload of
    long-prefill requests and short shared-prefix chat requests served
    once by a unified fleet and once by a prefill/decode-specialised
    fleet (transactional KV-page migration).  Reports chat TTFT p50/p99
    under each mode — the interference claim: long prefills on a
    dedicated pool must not sit in front of chat first tokens — plus the
    migration ledger (pages moved vs dedup-skipped, bytes saved by the
    content-addressed transport) and the zero-loss/bit-identity checks.
    Replicas step serially in this single process, so TTFT deltas are
    scheduling-order effects, not parallel-hardware speedups; the
    transferable numbers are the page/byte counts and the invariants."""
    spec = spec or {}
    import numpy as np

    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.inference.fleet import FleetRouter
    from deepspeed_tpu.inference.serving import ServingEngine
    from deepspeed_tpu.models.transformer import (CausalTransformerLM,
                                                  TransformerConfig)

    n_chat = int(spec.get("chat_requests", 12))
    n_long = int(spec.get("long_requests", 4))
    max_new = int(spec.get("max_new_tokens", 6))
    n_families = int(spec.get("chat_families", 3))

    cfg = TransformerConfig.tiny(hidden_size=64, n_heads=4, n_kv_heads=2)
    model = CausalTransformerLM(cfg)
    params = model.init(jax.random.key(0))
    rng = np.random.default_rng(7)
    # chat: 3-page shared prefixes so sibling migrations dedup; long: one
    # 96-token prefill that monopolises a step's prefill capacity
    families = [rng.integers(0, cfg.vocab_size, (24,)).tolist()
                for _ in range(n_families)]
    long_prefix = rng.integers(0, cfg.vocab_size, (96,)).tolist()
    prompts, kinds = {}, {}
    for i in range(n_chat):
        prompts[f"c{i}"] = families[i % n_families] + \
            rng.integers(0, cfg.vocab_size, (4,)).tolist()
        kinds[f"c{i}"] = "chat"
    for i in range(n_long):
        prompts[f"l{i}"] = long_prefix + \
            rng.integers(0, cfg.vocab_size, (8,)).tolist()
        kinds[f"l{i}"] = "long"

    def factory(rid, epoch):
        return ServingEngine(
            model, params, max_batch=4, page_size=8, max_seq=128,
            dtype=jnp.float32, replica_epoch=epoch,
            serving={"prefix_cache": {"enabled": True}})

    def run(fleet_cfg):
        fleet = FleetRouter(factory, fleet=dict(fleet_cfg))
        for rep in fleet.replicas.values():
            rep.engine.generate([prompts["c0"]], max_new_tokens=2)
        t_submit = {}
        t0 = time.perf_counter()
        for rid, p in prompts.items():
            # timestamp BEFORE submit: admission prefills inline when a
            # slot is free, so the first token can arrive during the call
            t_submit[rid] = time.monotonic()
            fleet.submit(rid, p, max_new_tokens=max_new,
                         temperature=0.7, seed=13)
        done = fleet.join(max_steps=4000)
        wall = time.perf_counter() - t0
        # fleet-level TTFT: submit instant (recorded above) to the first
        # engine-side first-token instant for that request.  Migrated
        # requests trace on both source and target engines — the min
        # picks the prefill-side sample, the true first token.
        first = {}
        for rep in fleet.replicas.values():
            traces = list(rep.engine.tracer.completed) + \
                list(rep.engine.tracer.open.values())
            for tr in traces:
                rid = str(tr.req_id).split(":", 1)[-1]
                if tr.t_first_token >= 0 and rid in t_submit:
                    prev = first.get(rid)
                    first[rid] = tr.t_first_token if prev is None \
                        else min(prev, tr.t_first_token)
        ttft_ms = {rid: (t - t_submit[rid]) * 1000.0
                   for rid, t in first.items()}
        chat = sorted(v for rid, v in ttft_ms.items()
                      if kinds[rid] == "chat")

        def pct(q):
            if not chat:
                return 0.0
            return chat[min(len(chat) - 1, int(q * (len(chat) - 1) + 0.5))]

        st = fleet.stats
        return {"fleet": fleet, "done": done, "wall_s": wall,
                "chat_ttft_p50_ms": pct(0.50), "chat_ttft_p99_ms": pct(0.99),
                "lost": st["submitted"] - st["finished"] - st["terminated"],
                "leaks": fleet.leak_report()}

    uni = run({"replicas": 3, "max_replicas": 4})
    dis = run({"roles": {"enabled": True, "prefill_replicas": 1,
                         "decode_replicas": 2}})
    st = dis["fleet"].stats
    return {
        "chat_requests": n_chat,
        "long_requests": n_long,
        "chat_ttft_p50_ms_unified": round(uni["chat_ttft_p50_ms"], 3),
        "chat_ttft_p99_ms_unified": round(uni["chat_ttft_p99_ms"], 3),
        "chat_ttft_p50_ms_disagg": round(dis["chat_ttft_p50_ms"], 3),
        "chat_ttft_p99_ms_disagg": round(dis["chat_ttft_p99_ms"], 3),
        "wall_s_unified": round(uni["wall_s"], 3),
        "wall_s_disagg": round(dis["wall_s"], 3),
        "migrations": st["migrations"],
        "migrated_pages": st["migrated_pages"],
        "dedup_skipped_pages": st["dedup_skipped_pages"],
        "migrate_bytes": st["migrate_bytes"],
        "migrate_bytes_saved": st["migrate_bytes_saved"],
        "local_prefills": st["local_prefills"],
        "bit_identical": dis["done"] == uni["done"],
        "lost_requests_unified": uni["lost"],
        "lost_requests_disagg": dis["lost"],
        "leaks_unified": uni["leaks"],
        "leaks_disagg": dis["leaks"],
    }


def _worker_fleet_disagg(spec):
    print(json.dumps(_fleet_disagg_bench(spec)))


def _fleet_xproc_bench(spec=None):
    """CPU-runnable cross-process-fleet micro-bench: the same workload
    served by an in-process fleet and by a fleet of real worker
    processes over the socket transport, then again with a real
    ``kill -9`` of one worker mid-decode.  Reports tokens per fleet
    step on both sides of the process boundary (the transport-overhead
    claim), the kill run's recovery latency (SIGKILL to respawned
    replica), and zero lost requests with survivors bit-identical to
    the no-kill run (the robustness claim)."""
    spec = spec or {}
    import os
    import signal

    from deepspeed_tpu.inference.fleet import FleetRouter
    from deepspeed_tpu.inference.fleet_worker import tiny_engine_factory

    n_replicas = int(spec.get("replicas", 2))
    n_requests = int(spec.get("requests", 8))
    max_new = int(spec.get("max_new_tokens", 6))
    worker_spec = {
        "factory":
        "deepspeed_tpu.inference.fleet_worker:tiny_engine_factory",
        "kwargs": {}}
    xproc = {"mode": "subprocess", "heartbeat_interval_s": 0.2,
             "heartbeat_deadline_s": 10.0}
    prompts = {f"q{i}": [1 + i, 2 + i, 3 + i, 4 + i]
               for i in range(n_requests)}

    def run(factory, transport=None, kill_rid=None):
        fleet_cfg = {"replicas": n_replicas,
                     "max_replicas": n_replicas + 1, "health_interval": 4}
        if transport:
            fleet_cfg["transport"] = dict(transport)
        router = FleetRouter(factory, fleet=fleet_cfg)
        try:
            # warm every engine's jit caches off the clock so the timed
            # phase measures serving + transport, not compilation
            for rep in router.replicas.values():
                rep.handle.generate([prompts["q0"]], max_new_tokens=2)
            t0 = time.perf_counter()
            for rid, p in sorted(prompts.items()):
                router.submit(rid, p, max_new_tokens=max_new,
                              temperature=0.7, seed=11)
            killed_at = recovery_s = None
            respawns0 = router.stats["respawns"]
            for step in range(600):
                if kill_rid and step == 3 and killed_at is None:
                    os.kill(router.replicas[kill_rid].handle.proc.pid,
                            signal.SIGKILL)
                    killed_at = time.perf_counter()
                router.step()
                if killed_at is not None and recovery_s is None and \
                        router.stats["respawns"] > respawns0:
                    recovery_s = time.perf_counter() - killed_at
                if not router._unresolved():
                    break
            wall = time.perf_counter() - t0
            done = dict(router.finished)
            term = router.pop_terminated()
            generated = sum(len(toks) - len(prompts[rid])
                            for rid, toks in done.items())
            st = router.stats
            return {"done": done, "term": term, "wall_s": wall,
                    "tokens_per_step": generated / max(router.steps, 1),
                    "steps": router.steps, "recovery_s": recovery_s,
                    "lost": (st["submitted"] - st["finished"] -
                             st["terminated"]),
                    "workers_lost": st["workers_lost"],
                    "respawns": st["respawns"],
                    "leaks": router.leak_report()}
        finally:
            router.close()

    inp = run(tiny_engine_factory)
    xp = run(worker_spec, transport=xproc)
    kill = run(worker_spec, transport=xproc, kill_rid="r0")
    survivors_identical = all(kill["done"][rid] == inp["done"][rid]
                              for rid in kill["done"])
    return {
        "replicas": n_replicas,
        "requests": n_requests,
        "agg_tokens_per_step_inproc": round(inp["tokens_per_step"], 3),
        "agg_tokens_per_step_xproc": round(xp["tokens_per_step"], 3),
        "transport_wall_overhead_frac": round(
            xp["wall_s"] / max(inp["wall_s"], 1e-9) - 1.0, 3),
        "bit_identical_xproc": xp["done"] == inp["done"],
        "kill_recovery_s": round(kill["recovery_s"] or 0.0, 3),
        "kill_extra_wall_s": round(kill["wall_s"] - xp["wall_s"], 3),
        "kill_extra_steps": kill["steps"] - xp["steps"],
        "workers_lost": kill["workers_lost"],
        "respawns": kill["respawns"],
        "survivors_bit_identical": survivors_identical,
        "lost_requests": (inp["lost"] + xp["lost"] + kill["lost"] +
                          len(xp["term"]) + len(inp["term"])),
        "leaks_xproc": xp["leaks"],
        "leaks_kill": kill["leaks"],
    }


def _worker_fleet_xproc(spec):
    print(json.dumps(_fleet_xproc_bench(spec)))


def _fleet_chaos_bench(spec=None):
    """CPU-runnable chaos-recovery micro-bench: replays the gate-10
    wire-fault scenarios (lost add_request ack, slow worker tripping the
    circuit breaker, torn commit_import ack) over a real 2-worker
    subprocess fleet via scripts/ds_chaos.py and reports per-scenario
    recovery wall time plus the retry / breaker / dedup counters.  Every
    scenario asserts the hard bar before returning: zero lost requests,
    one typed terminal per request, empty leak report, survivors
    bit-identical to a no-fault in-process reference, and checker-valid
    telemetry — so a green number here is also a correctness proof."""
    spec = spec or {}
    import importlib.util
    repo = os.path.dirname(os.path.abspath(__file__))
    sp = importlib.util.spec_from_file_location(
        "ds_chaos", os.path.join(repo, "scripts", "ds_chaos.py"))
    chaos = importlib.util.module_from_spec(sp)
    sp.loader.exec_module(chaos)

    seed = int(spec.get("seed", 0))
    names = list(spec.get("scenarios") or
                 ("ack_loss", "slow_worker", "torn_commit"))
    out = {"seed": seed, "scenarios": len(names), "lost_requests": 0}
    totals = {"retries": 0, "rpc_timeouts": 0, "breaker_opens": 0,
              "breaker_closes": 0, "dup_calls_dropped": 0,
              "workers_lost": 0, "respawns": 0}
    for name in names:
        res = chaos.run_scenario(name, seed=seed)
        st = res["stats"]
        out[f"{name}_elapsed_s"] = round(res["elapsed_s"], 3)
        out["lost_requests"] += (st["submitted"] - st["finished"] -
                                 st["terminated"])
        for k in totals:
            totals[k] += st[k]
        if name == "slow_worker":
            opened = [e for e in res["events"]
                      if e.get("name") == "fleet/breaker_open"]
            closed = [e for e in res["events"]
                      if e.get("name") == "fleet/breaker_close"]
            if opened and closed:
                out["breaker_open_to_close_s"] = round(
                    closed[0]["ts"] - opened[0]["ts"], 3)
    for k, v in totals.items():
        out[f"{k}_total"] = v
    return out


def _worker_fleet_chaos(spec):
    print(json.dumps(_fleet_chaos_bench(spec)))


def _serving_attn_bench(spec=None):
    """CPU-runnable serving-attention micro-bench: the jnp gather path vs
    the fused ragged Pallas kernel (interpret mode) on ONE mixed
    prefill+decode batch over a shared paged pool.

    The gather path is how the engine served before the ragged kernel:
    host-side regrouping into per-prefill rectangular calls plus one
    batched decode call, each materialising a max_pages-padded [Hkv, S, D]
    view per sequence.  The ragged kernel serves the whole mix in one
    launch reading pages in place.  Interpret-mode wall time is NOT a TPU
    number (the interpreter is orders slower) — the transferable outputs
    are the equivalence check and the analytic bytes-moved-per-decoded-
    token roofline (docs/mfu_ceiling.md §5), recorded for the next
    on-chip round.  Also drives a tiny engine + ``serve/attn`` spans
    through one telemetry stream and reports
    ``ds_telemetry_report.serving_attention`` — proving attention's share
    of serve-step time is measurable from the frozen stream."""
    spec = spec or {}
    import importlib.util
    import tempfile

    import numpy as np

    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.inference.serving import ServingEngine
    from deepspeed_tpu.models.transformer import (CausalTransformerLM,
                                                  TransformerConfig)
    from deepspeed_tpu.monitor.telemetry import Telemetry
    from deepspeed_tpu.ops.paged_attention import (PagedAllocator,
                                                   PagedKVCache,
                                                   paged_decode_attention)
    from deepspeed_tpu.ops.pallas.ragged_paged_attention import \
        ragged_paged_attention
    from deepspeed_tpu.runtime.config import TelemetryConfig

    H, HKV, D, PAGE = 4, 2, 16, 16
    NPAGES = 64
    prefill_lens = list(spec.get("prefill_lens", [24, 17]))
    decode_ctx = list(spec.get("decode_ctx", [40, 33]))
    iters = int(spec.get("iters", 5))

    rng = np.random.default_rng(0)
    q_lens = prefill_lens + [1] * len(decode_ctx)
    ctx_lens = prefill_lens + decode_ctx
    alloc = PagedAllocator(NPAGES, PAGE, max_pages_per_seq=8,
                           reserve_scratch=True)
    for s, c in enumerate(ctx_lens):
        alloc.allocate(s, c)
    tables = jnp.asarray(alloc.block_table(list(range(len(ctx_lens)))))
    kp = jnp.asarray(rng.standard_normal((NPAGES, HKV, PAGE, D)),
                     jnp.float32)
    vp = jnp.asarray(rng.standard_normal((NPAGES, HKV, PAGE, D)),
                     jnp.float32)
    cache = PagedKVCache(kp, vp)
    q = jnp.asarray(rng.standard_normal((sum(q_lens), H, D)), jnp.float32)
    ctx = jnp.asarray(ctx_lens, jnp.int32)

    tmp = tempfile.mkdtemp(prefix="serving_attn_bench_")
    tel = Telemetry().configure(
        TelemetryConfig({"enabled": True, "output_path": tmp,
                         "job_name": "serving_attn_bench"}), rank=0)

    def gather_mixed():
        """Pre-kernel serving shape: one rectangular jnp call per prefill
        plus one batched call for the decodes."""
        outs, off = [], 0
        for s, ql in enumerate(prefill_lens):
            outs.append(paged_decode_attention(
                q[off:off + ql][None], cache, tables[s:s + 1],
                ctx[s:s + 1], impl="jnp")[0])
            off += ql
        nd = len(decode_ctx)
        dec = paged_decode_attention(
            q[off:].reshape(nd, 1, H, D), cache,
            tables[len(prefill_lens):], ctx[len(prefill_lens):],
            impl="jnp")
        outs.append(dec.reshape(nd, H, D))
        return jnp.concatenate(outs, axis=0)

    def fused_mixed():
        return ragged_paged_attention(q, kp, vp, tables, ctx, q_lens,
                                      interpret=True)

    def timed(fn, label):
        fn().block_until_ready()   # warmup/compile outside the timing
        best = float("inf")
        for _ in range(iters):
            with tel.span("serve/attn", attrs={"backend": label}):
                t0 = time.perf_counter()
                fn().block_until_ready()
                best = min(best, time.perf_counter() - t0)
        return best * 1000.0

    gather_ms = timed(gather_mixed, "jnp")
    fused_ms = timed(fused_mixed, "pallas-interpret")
    err = float(jnp.max(jnp.abs(gather_mixed() - fused_mixed())))

    # analytic HBM traffic per decoded token (fp32 here; ratio is
    # dtype-free): the gather path materialises the max_pages-padded K
    # and V views and reads them again through the softmax/PV einsums
    # (~3 passes), the fused kernel streams each sequence's true context
    # once.  docs/mfu_ceiling.md §5 carries the decomposition.
    bpe = 4
    S_pad = int(tables.shape[1]) * PAGE
    gather_bytes = 3 * 2 * S_pad * HKV * D * bpe
    mean_ctx = sum(decode_ctx) / len(decode_ctx)
    fused_bytes = 2 * mean_ctx * HKV * D * bpe
    # drive a tiny engine through the same stream so serve/backend +
    # serve/step land next to the serve/attn spans
    cfg = TransformerConfig.tiny(hidden_size=64, n_heads=4, n_kv_heads=2)
    model = CausalTransformerLM(cfg)
    params = model.init(jax.random.key(0))
    eng = ServingEngine(model, params, max_batch=2, page_size=8,
                        max_seq=64, dtype=jnp.float32, telemetry=tel,
                        serving={"attention_backend": "jnp"})
    eng.generate([[1, 2, 3, 4, 5], [7, 8, 9]], max_new_tokens=3)
    leaks = eng.leak_report()
    tel.close()

    # attention's share of serve-step time, read back the way an operator
    # would: through ds_telemetry_report's serving_attention summary
    repo = os.path.dirname(os.path.abspath(__file__))
    rp = os.path.join(repo, "scripts", "ds_telemetry_report.py")
    sp = importlib.util.spec_from_file_location("ds_telemetry_report", rp)
    report = importlib.util.module_from_spec(sp)
    sp.loader.exec_module(report)
    files = report.discover_files(os.path.join(tmp, "serving_attn_bench"))
    summary = report.summarize(report.aggregate(report.load_events(files)))

    return {
        "q_lens": q_lens,
        "ctx_lens": ctx_lens,
        "gather_jnp_ms": round(gather_ms, 3),
        "ragged_interpret_ms": round(fused_ms, 3),
        "max_abs_diff": err,
        "equivalent": err < 2e-5,
        "gather_bytes_per_decoded_token": gather_bytes,
        "fused_bytes_per_decoded_token": int(fused_bytes),
        "analytic_bytes_ratio": round(gather_bytes / fused_bytes, 1),
        "serving_attention_report": summary.get("serving_attention"),
        "leaks": leaks,
        "note": "interpret-mode wall time is not a TPU number; the "
                "equivalence + analytic roofline are the transferable "
                "outputs for the next on-chip round",
    }


def _worker_serving_attn(spec):
    print(json.dumps(_serving_attn_bench(spec)))


def _serving_slo_bench(spec=None):
    """CPU-runnable serving-SLO micro-bench: a mixed short/long-prompt
    workload (interactive vs batch shapes) with per-request deadlines,
    reporting the observability plane's own numbers — TTFT / TPOT / e2e /
    queue-wait p50/p99 from the registry histograms, SLO attainment and
    goodput from the deadline verdicts — plus a live scrape of the
    pull-based exporter (ephemeral port), validated against the
    Prometheus-exposition checker.  Wall-clock numbers are CPU numbers;
    the transferable outputs are the trace-completeness audit and the
    scrape-path proof."""
    spec = spec or {}
    import importlib.util
    import tempfile
    import urllib.request

    import numpy as np

    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.inference.robustness import RequestRejected
    from deepspeed_tpu.inference.serving import ServingEngine
    from deepspeed_tpu.models.transformer import (CausalTransformerLM,
                                                  TransformerConfig)
    from deepspeed_tpu.monitor.telemetry import Telemetry
    from deepspeed_tpu.runtime.config import TelemetryConfig

    n_requests = int(spec.get("requests", 24))
    arrivals = int(spec.get("arrivals_per_step", 2))
    max_new = int(spec.get("max_new_tokens", 6))
    deadline_s = float(spec.get("deadline_s", 60.0))

    cfg = TransformerConfig.tiny(hidden_size=64, n_heads=4, n_kv_heads=2)
    model = CausalTransformerLM(cfg)
    params = model.init(jax.random.key(0))
    tmp = tempfile.mkdtemp(prefix="serving_slo_bench_")
    tel = Telemetry().configure(
        TelemetryConfig({"enabled": True, "output_path": tmp,
                         "job_name": "serving_slo_bench",
                         "export": {"enabled": True, "port": 0}}), rank=0)
    eng = ServingEngine(
        model, params, max_batch=4, page_size=8, max_seq=64,
        dtype=jnp.float32, telemetry=tel,
        serving={"max_queue": int(spec.get("max_queue", 12)),
                 "overload_policy": "shed-oldest"})
    rng = np.random.default_rng(0)
    # interactive (short) vs batch (long) prompt mix; both classes carry
    # a deadline so every terminal yields an SLO verdict
    prompts = []
    for i in range(n_requests):
        n = int(rng.integers(3, 7)) if i % 2 == 0 else \
            int(rng.integers(24, 33))
        prompts.append(rng.integers(0, cfg.vocab_size, (n,)).tolist())
    rejected = 0
    next_req = 0
    while next_req < n_requests or eng.queue or eng.n_active:
        for _ in range(arrivals):
            if next_req >= n_requests:
                break
            try:
                eng.add_request(next_req, prompts[next_req],
                                max_new_tokens=max_new,
                                deadline_s=deadline_s)
            except RequestRejected:
                rejected += 1
            next_req += 1
        eng.step()
    health = eng.health()    # populates the latency section
    leaks = eng.leak_report()

    # live scrape through the exporter, validated with the checker
    host, port = tel.exporter.address
    prom = urllib.request.urlopen(
        f"http://{host}:{port}/metrics", timeout=5).read().decode()
    repo = os.path.dirname(os.path.abspath(__file__))
    cp = os.path.join(repo, "scripts", "check_telemetry_schema.py")
    sp = importlib.util.spec_from_file_location("check_telemetry_schema",
                                                cp)
    checker = importlib.util.module_from_spec(sp)
    sp.loader.exec_module(checker)
    prom_problems = checker.validate_prom_exposition(prom)
    tel.close()

    def pcts(name):
        s = health["latency"][name]
        return {"count": s["count"],
                "p50_ms": round(s["p50"], 3) if s["p50"] is not None
                else None,
                "p99_ms": round(s["p99"], 3) if s["p99"] is not None
                else None}

    slo = health["slo"]
    verdicts = slo["attained"] + slo["missed"]
    return {
        "offered_requests": n_requests,
        "served": eng.stats["finished"],
        "shed": eng.stats["shed"],
        "rejected": rejected,
        "ttft": pcts("serve/ttft_ms"),
        "tpot": pcts("serve/tpot_ms"),
        "e2e": pcts("serve/e2e_ms"),
        "queue_wait": pcts("serve/queue_wait_ms"),
        "slo_attained": slo["attained"],
        "slo_missed": slo["missed"],
        "slo_attainment": (round(slo["attained"] / verdicts, 3)
                           if verdicts else None),
        "goodput_tokens": slo["goodput_tokens"],
        "traces": health["traces"],
        "exporter_scrape_ok": not prom_problems and
        "ds_serve_ttft_ms" in prom,
        "leaks": leaks,
    }


def _worker_serving_slo(spec):
    print(json.dumps(_serving_slo_bench(spec)))


def _serving_sched_bench(spec=None):
    """CPU-runnable scheduler micro-bench: one mixed workload (long
    throughput-class prompts arriving alongside short latency-class chat)
    replayed through the monolithic, chunked, and chunked+speculative
    schedulers on a simulated dispatch clock — every device dispatch
    charges ``overhead + per_token * ids.size`` simulated seconds (the
    draft model at a quarter of the target's per-token rate), so the
    TTFT/interleaving numbers measure the SCHEDULING policy, not CPU
    wall-clock or compile skew.  Reports chat TTFT p99 per policy (the
    head-of-line-blocking number chunking exists to fix), decode
    tokens-per-step (the regression guard), speculative acceptance, and
    the cross-policy bit-identity verdicts — greedy outputs must match
    token-for-token across all three schedulers."""
    spec = spec or {}
    import numpy as np

    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.inference.serving import ServingEngine
    from deepspeed_tpu.models.transformer import (CausalTransformerLM,
                                                  TransformerConfig)

    n_requests = int(spec.get("requests", 18))
    max_new = int(spec.get("max_new_tokens", 16))
    long_len = int(spec.get("long_prompt_tokens", 320))
    chunk = int(spec.get("prefill_chunk_tokens", 64))
    max_chunks = int(spec.get("max_prefill_chunks_per_step", 3))
    gamma = int(spec.get("num_draft_tokens", 3))
    noise = float(spec.get("draft_noise", 3e-3))
    overhead_s = float(spec.get("dispatch_overhead_s", 5e-4))
    per_tok_s = float(spec.get("per_token_s", 5e-5))

    cfg = TransformerConfig.tiny(hidden_size=64, n_heads=4, n_kv_heads=2)
    model = CausalTransformerLM(cfg)
    params = model.init(jax.random.key(0))
    # imperfect-but-correlated proposer: the target's own weights plus
    # seeded noise — acceptance lands strictly between 0 and 1, and the
    # verify/correction path has to earn the bit-identity verdict
    leaves, treedef = jax.tree_util.tree_flatten(params)
    keys = jax.random.split(jax.random.key(1), len(leaves))
    draft_params = jax.tree_util.tree_unflatten(
        treedef, [l + noise * jax.random.normal(k, l.shape, l.dtype)
                  for l, k in zip(leaves, keys)])

    rng = np.random.default_rng(0)
    prompts, classes, arrival = [], [], []
    for i in range(n_requests):
        if i % 3 == 0:      # batch job: long prompt, throughput class
            n, cls = long_len, "throughput"
        else:               # interactive chat: short prompt, latency class
            n, cls = int(rng.integers(4, 9)), "latency"
        prompts.append(rng.integers(0, cfg.vocab_size, (n,)).tolist())
        classes.append(cls)
        arrival.append(i * 3e-3)

    class SimClock:
        def __init__(self):
            self.t = 0.0

        def __call__(self):
            return self.t

    def run(policy, speculative):
        clk = SimClock()
        sched_cfg = {"policy": policy}
        if policy == "chunked":
            sched_cfg["prefill_chunk_tokens"] = chunk
            sched_cfg["max_prefill_chunks_per_step"] = max_chunks
        if speculative:
            sched_cfg["speculative"] = {"enabled": True,
                                        "num_draft_tokens": gamma}
        eng = ServingEngine(
            model, params, max_batch=4, page_size=16, max_seq=512,
            dtype=jnp.float32, clock=clk,
            serving={"scheduler": sched_cfg},
            draft_model=model if speculative else None,
            draft_params=draft_params if speculative else None)
        real_step = eng._run_step

        def charged_step(ids, tables, lengths, phase="decode"):
            clk.t += overhead_s + per_tok_s * float(ids.size)
            return real_step(ids, tables, lengths, phase=phase)

        eng._run_step = charged_step
        if speculative:
            sched = eng.scheduler
            real_draft = sched._run_draft

            def charged_draft(ids, tables, lengths, phase):
                clk.t += overhead_s + per_tok_s / 4.0 * float(ids.size)
                return real_draft(ids, tables, lengths, phase)

            sched._run_draft = charged_draft
            real_propose = sched._propose_fn

            def charged_propose(params, caches, tables, lengths, last):
                clk.t += overhead_s + per_tok_s / 4.0 * \
                    float(last.shape[0] * (gamma + 1))
                return real_propose(params, caches, tables, lengths, last)

            sched._propose_fn = charged_propose

        outputs = {}
        next_req = 0
        while next_req < n_requests or eng.queue or eng.n_active:
            clk.t += 1e-4          # host loop tick: progress when idle
            while next_req < n_requests and \
                    arrival[next_req] <= clk.t:
                eng.add_request(next_req, prompts[next_req],
                                max_new_tokens=max_new,
                                slo_class=classes[next_req])
                next_req += 1
            for rid, toks in eng.step().items():
                outputs.setdefault(rid, []).extend(toks)
        leaks = eng.leak_report()
        stats = dict(eng.scheduler.sched_stats)
        snap = eng.scheduler.snapshot()
        chat_ttfts = sorted(
            t.ttft_ms() for t in eng.tracer.completed
            if classes[t.req_id] == "latency" and t.ttft_ms() is not None)
        return {"outputs": outputs, "leaks": leaks, "stats": stats,
                "snapshot": snap, "sim_s": round(clk.t, 4),
                "chat_ttft_p50_ms": _pct_of(chat_ttfts, 50),
                "chat_ttft_p99_ms": _pct_of(chat_ttfts, 99)}

    mono = run("monolithic", False)
    chunked = run("chunked", False)
    spec_run = run("chunked", True)

    def tok_per_step(r):
        steps = r["stats"].get("decode_steps", 0)
        return round(r["stats"].get("decode_tokens", 0) / steps, 3) \
            if steps else None

    reduction = (round(1.0 - chunked["chat_ttft_p99_ms"] /
                       mono["chat_ttft_p99_ms"], 4)
                 if mono["chat_ttft_p99_ms"] else None)
    out = {
        "requests": n_requests,
        "long_prompt_tokens": long_len,
        "prefill_chunk_tokens": chunk,
        "num_draft_tokens": gamma,
        "monolithic_chat_ttft_p99_ms": mono["chat_ttft_p99_ms"],
        "chunked_chat_ttft_p99_ms": chunked["chat_ttft_p99_ms"],
        "chunked_spec_chat_ttft_p99_ms": spec_run["chat_ttft_p99_ms"],
        "monolithic_chat_ttft_p50_ms": mono["chat_ttft_p50_ms"],
        "chunked_chat_ttft_p50_ms": chunked["chat_ttft_p50_ms"],
        # 1 - chunked/monolithic: >= 0.5 is the ">= 2x reduction" gate
        "chunked_ttft_p99_reduction_frac": reduction,
        "monolithic_decode_tokens_per_step": tok_per_step(mono),
        "chunked_decode_tokens_per_step": tok_per_step(chunked),
        "chunked_spec_decode_tokens_per_step": tok_per_step(spec_run),
        # makespan: total simulated seconds to drain the whole workload —
        # the overall-throughput guard (per-step width alone punishes
        # chunking for starting decode EARLIER, during prefill)
        # decode width under chunking relative to monolithic: prefill
        # chunks hold a slot mid-fill, so a few percent below 1.0 is the
        # expected price; the makespan rows show the overall-throughput
        # story (chunked drains the same workload FASTER)
        "chunked_decode_width_ratio_frac":
            (round(tok_per_step(chunked) / tok_per_step(mono), 4)
             if tok_per_step(mono) else None),
        "monolithic_makespan_s": mono["sim_s"],
        "chunked_makespan_s": chunked["sim_s"],
        "chunked_spec_makespan_s": spec_run["sim_s"],
        "spec_acceptance_rate":
            spec_run["snapshot"].get("spec_acceptance_rate"),
        "prefill_chunks": chunked["stats"].get("prefill_chunks", 0),
        "bit_identical_chunked": chunked["outputs"] == mono["outputs"],
        "bit_identical_spec": spec_run["outputs"] == mono["outputs"],
        "leaks": {"monolithic": mono["leaks"],
                  "chunked": chunked["leaks"],
                  "chunked_spec": spec_run["leaks"]},
        "note": "simulated dispatch clock (overhead + per-token charge); "
                "TTFT ratios and bit-identity are the transferable "
                "outputs, not CPU wall time",
    }
    return out


def _pct_of(sorted_vals, q):
    if not sorted_vals:
        return None
    n = len(sorted_vals)
    idx = min(n - 1, max(0, int(round(q / 100.0 * (n - 1)))))
    return round(sorted_vals[idx], 3)


def _worker_serving_sched(spec):
    print(json.dumps(_serving_sched_bench(spec)))


def _autotune_bench(spec=None):
    """CPU-runnable closed-loop autotuner micro-bench: an end-to-end tune
    over a small serving knob grid (prefill chunk tokens x speculative
    draft length) on the same simulated-dispatch-clock workload as the
    scheduler bench.  The ControlPlane prunes the infeasible corner
    (draft + 1 > page_size), scores every surviving trial from its own
    Telemetry snapshot, ledgers each trial as a ``tune-<id>`` run under
    bench ``autotune``, and persists the winner as a provenance-stamped
    overlay.  The bench then replays the DEFAULT config (chunk=256,
    no draft) and the overlay-merged config through the identical
    harness and asserts the tuned point beats the default on >= 2
    ledgered metrics with zero regressions, that the overlay round-trips
    through ``create_serving_engine``, and that the tune artifacts pass
    ``check_telemetry_schema --tune`` / ``--ledger`` and a rehearsal
    ``ds_perf_diff --check``."""
    spec = spec or {}
    import subprocess as sp
    import tempfile

    import numpy as np

    import jax
    import jax.numpy as jnp
    import deepspeed_tpu
    from deepspeed_tpu.autotuning import (ControlPlane, Knob, KnobSpace,
                                          Objective, apply_overlay,
                                          load_overlay)
    from deepspeed_tpu.inference.serving import ServingEngine
    from deepspeed_tpu.models.transformer import (CausalTransformerLM,
                                                  TransformerConfig)
    from deepspeed_tpu.monitor.telemetry import Telemetry

    n_requests = int(spec.get("requests", 12))
    max_new = int(spec.get("max_new_tokens", 12))
    long_len = int(spec.get("long_prompt_tokens", 192))
    overhead_s = float(spec.get("dispatch_overhead_s", 5e-4))
    per_tok_s = float(spec.get("per_token_s", 5e-5))
    chunk_grid = [int(v) for v in spec.get("chunk_grid", [32, 64])]
    # 16 is the deliberately infeasible corner: draft + 1 > page_size,
    # so the memory-model pruner (not the engine) must reject it
    draft_grid = [int(v) for v in spec.get("draft_grid", [0, 3, 16])]
    page_size = int(spec.get("page_size", 16))

    cfg = TransformerConfig.tiny(hidden_size=64, n_heads=4, n_kv_heads=2)
    model = CausalTransformerLM(cfg)
    params = model.init(jax.random.key(0))
    leaves, treedef = jax.tree_util.tree_flatten(params)
    keys = jax.random.split(jax.random.key(1), len(leaves))
    draft_params = jax.tree_util.tree_unflatten(
        treedef, [l + 3e-3 * jax.random.normal(k, l.shape, l.dtype)
                  for l, k in zip(leaves, keys)])

    rng = np.random.default_rng(0)
    prompts, classes, arrival = [], [], []
    for i in range(n_requests):
        if i % 3 == 0:
            n, cls = long_len, "throughput"
        else:
            n, cls = int(rng.integers(4, 9)), "latency"
        prompts.append(rng.integers(0, cfg.vocab_size, (n,)).tolist())
        classes.append(cls)
        arrival.append(i * 3e-3)

    class SimClock:
        def __init__(self):
            self.t = 0.0

        def __call__(self):
            return self.t

    def run_workload(trial_cfg, tel):
        serving = dict(trial_cfg.get("serving") or {})
        sched_blk = dict(serving.get("scheduler") or {})
        gamma = int(dict(sched_blk.get("speculative") or {})
                    .get("num_draft_tokens", 0))
        clk = SimClock()
        sched_cfg = {"policy": "chunked",
                     "prefill_chunk_tokens":
                         int(sched_blk.get("prefill_chunk_tokens", 256)),
                     "max_prefill_chunks_per_step":
                         int(sched_blk.get("max_prefill_chunks_per_step",
                                           3))}
        if gamma > 0:
            sched_cfg["speculative"] = {"enabled": True,
                                        "num_draft_tokens": gamma}
        eng = ServingEngine(
            model, params, max_batch=4,
            page_size=int(serving.get("page_size", page_size)),
            max_seq=512, dtype=jnp.float32, clock=clk,
            serving={"scheduler": sched_cfg}, telemetry=tel,
            draft_model=model if gamma > 0 else None,
            draft_params=draft_params if gamma > 0 else None)
        real_step = eng._run_step

        def charged_step(ids, tables, lengths, phase="decode"):
            clk.t += overhead_s + per_tok_s * float(ids.size)
            return real_step(ids, tables, lengths, phase=phase)

        eng._run_step = charged_step
        if gamma > 0:
            sch = eng.scheduler
            real_draft = sch._run_draft

            def charged_draft(ids, tables, lengths, phase):
                clk.t += overhead_s + per_tok_s / 4.0 * float(ids.size)
                return real_draft(ids, tables, lengths, phase)

            sch._run_draft = charged_draft
            real_propose = sch._propose_fn

            def charged_propose(params, caches, tables, lengths, last):
                clk.t += overhead_s + per_tok_s / 4.0 * \
                    float(last.shape[0] * (gamma + 1))
                return real_propose(params, caches, tables, lengths, last)

            sch._propose_fn = charged_propose

        total = 0
        next_req = 0
        while next_req < n_requests or eng.queue or eng.n_active:
            clk.t += 1e-4
            while next_req < n_requests and arrival[next_req] <= clk.t:
                eng.add_request(next_req, prompts[next_req],
                                max_new_tokens=max_new,
                                slo_class=classes[next_req])
                next_req += 1
            for toks in eng.step().values():
                total += len(toks)
        # TTFT/TPOT/e2e histograms (simulated ms) land in ``tel`` via the
        # engine; tokens/s over the simulated clock is harness-computed
        return {"tokens_per_sec": round(total / clk.t, 3)
                if clk.t else 0.0}

    base_cfg = {"serving": {"page_size": page_size,
                            "scheduler": {
                                "policy": "chunked",
                                "prefill_chunk_tokens": 256,
                                "max_prefill_chunks_per_step": 3}}}
    space = KnobSpace([
        Knob("prefill_chunk_tokens",
             "serving/scheduler/prefill_chunk_tokens", chunk_grid),
        Knob("num_draft_tokens",
             "serving/scheduler/speculative/num_draft_tokens", draft_grid),
    ])
    objective = Objective({"tokens_per_sec": 1.0,
                           "ttft_p99_ms": -0.05,
                           "tpot_p99_ms": -0.5})

    results_dir = tempfile.mkdtemp(prefix="dstpu_autotune_")
    trial_ledger = os.path.join(results_dir, "trial_ledger.jsonl")
    cp = ControlPlane(base_config=base_cfg, knob_space=space,
                      objective=objective, results_dir=results_dir,
                      ledger_path=trial_ledger, bench="autotune")
    summary = cp.tune(run_workload)
    payload = load_overlay(summary["overlay_path"])
    winner = ((payload or {}).get("provenance") or {}).get("knobs") or {}

    def measure(cfg_d):
        tel = Telemetry()
        tel.enabled = True   # registry-only: accumulate, no event sink
        extra = run_workload(cfg_d, tel)
        return objective.metrics(tel.snapshot(), extra)

    default_vec = measure(base_cfg)
    tuned_vec = measure(apply_overlay(base_cfg, payload))

    directions = {"tokens_per_sec": 1, "ttft_p50_ms": -1,
                  "ttft_p99_ms": -1, "tpot_p50_ms": -1, "tpot_p99_ms": -1,
                  "e2e_p99_ms": -1, "queue_wait_p99_ms": -1}
    improved, regressed = [], []
    for name, sign in directions.items():
        d, t = default_vec.get(name), tuned_vec.get(name)
        if d is None or t is None:
            continue
        delta = sign * (t - d)
        if delta > 0.01 * abs(d):
            improved.append(name)
        elif delta < -0.01 * abs(d):
            regressed.append(name)

    # consumption path: the overlay must round-trip through
    # create_serving_engine (autotuning.overlay_path in the ds config)
    eng = deepspeed_tpu.create_serving_engine(
        model, params,
        config={"max_batch": 4, "max_seq": 512,
                "serving": base_cfg["serving"],
                "autotuning": {"overlay_path": summary["overlay_path"]}},
        dtype=jnp.float32)
    consumed = (getattr(eng, "overlay_provenance", None) is not None and
                getattr(eng.scheduler, "chunk", None) ==
                int(winner.get("prefill_chunk_tokens", -1)))

    scripts_dir = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "scripts")
    real_ledger = os.environ.get(
        "BENCH_LEDGER",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "BENCH_LEDGER.jsonl"))
    with open(trial_ledger) as f:
        trial_rows_text = f.read()
    trial_rows = [ln for ln in trial_rows_text.splitlines() if ln.strip()]
    # perf-diff rehearsal: history + this tune's trial runs + a candidate
    # run carrying the summary metrics the parent will ledger — proves
    # the tune rows never trip the gate before touching the real ledger
    check_ledger = os.path.join(results_dir, "check_ledger.jsonl")
    ts = time.time()
    with open(check_ledger, "w") as f:
        if os.path.exists(real_ledger):
            with open(real_ledger) as src:
                f.write(src.read())
        f.write(trial_rows_text)
        for metric, value in (
                ("tuned_tokens_per_sec", tuned_vec.get("tokens_per_sec")),
                ("tuned_ttft_p99_ms", tuned_vec.get("ttft_p99_ms")),
                ("default_tokens_per_sec",
                 default_vec.get("tokens_per_sec")),
                ("default_ttft_p99_ms", default_vec.get("ttft_p99_ms"))):
            if isinstance(value, (int, float)):
                f.write(json.dumps(
                    {"ts": ts, "run": f"run-tunecheck-{int(ts)}",
                     "bench": "cpu_autotune", "metric": metric,
                     "value": value}) + "\n")

    def _rc(args):
        try:
            return sp.run([sys.executable] + args, capture_output=True,
                          text=True, timeout=120).returncode
        except Exception:
            return -1

    checker = os.path.join(scripts_dir, "check_telemetry_schema.py")
    tune_gate_rc = _rc([checker, "--tune", results_dir])
    ledger_gate_rc = _rc([checker, "--ledger", check_ledger])
    perf_diff_rc = _rc([os.path.join(scripts_dir, "ds_perf_diff.py"),
                        check_ledger, "--check"])

    beats = len(improved) >= 2 and not regressed
    problems = []
    if summary.get("best") is None:
        problems.append("no winning trial")
    if not beats:
        problems.append(
            f"tuned does not beat default: improved={improved} "
            f"regressed={regressed}")
    if not consumed:
        problems.append("overlay not consumed by create_serving_engine")
    if tune_gate_rc != 0:
        problems.append(f"--tune gate rc={tune_gate_rc}")
    if ledger_gate_rc != 0:
        problems.append(f"--ledger gate rc={ledger_gate_rc}")
    if perf_diff_rc != 0:
        problems.append(f"ds_perf_diff --check rc={perf_diff_rc}")
    if problems:
        raise RuntimeError("autotune bench failed: " + "; ".join(problems))

    # trial rows reach the real ledger only after every gate passed — a
    # failed tune must never pollute the perf baseline
    appended = 0
    try:
        with open(real_ledger, "a") as f:
            f.write(trial_rows_text)
        appended = len(trial_rows)
    except OSError:
        pass

    def _r(v):
        return round(v, 3) if isinstance(v, (int, float)) else None

    return {
        "trials": summary["trials"],
        "pruned_trials": summary["pruned"],
        "winner_chunk": int(winner.get("prefill_chunk_tokens", 0)),
        "winner_draft": int(winner.get("num_draft_tokens", 0)),
        "winner_objective": _r((summary.get("best") or {})
                               .get("objective")),
        "default_tokens_per_sec": _r(default_vec.get("tokens_per_sec")),
        "tuned_tokens_per_sec": _r(tuned_vec.get("tokens_per_sec")),
        "default_ttft_p99_ms": _r(default_vec.get("ttft_p99_ms")),
        "tuned_ttft_p99_ms": _r(tuned_vec.get("ttft_p99_ms")),
        "default_tpot_p99_ms": _r(default_vec.get("tpot_p99_ms")),
        "tuned_tpot_p99_ms": _r(tuned_vec.get("tpot_p99_ms")),
        "default_e2e_p99_ms": _r(default_vec.get("e2e_p99_ms")),
        "tuned_e2e_p99_ms": _r(tuned_vec.get("e2e_p99_ms")),
        "improved_metric_count": len(improved),
        "regressed_metric_count": len(regressed),
        "improved": improved,
        "regressed": regressed,
        "tuned_beats_default": beats,
        "overlay_consumed": consumed,
        "tune_gate_rc": tune_gate_rc,
        "ledger_gate_rc": ledger_gate_rc,
        "perf_diff_rc": perf_diff_rc,
        "trial_rows_appended": appended,
        "note": "simulated dispatch clock; tuned-vs-default deltas and "
                "gate rcs are the transferable outputs, not CPU wall "
                "time",
    }


def _worker_autotune(spec):
    print(json.dumps(_autotune_bench(spec)))


def _comm_census_bench(spec=None):
    """CPU-runnable distributed-telemetry micro-bench: a simulated 4-rank
    run (N threads, each owning its own Telemetry configured with a
    distinct rank — the same shard layout N real processes produce) with
    synthetic timed collectives and one deliberately delayed rank.
    Reports the observability plane's own numbers: the aggregator's
    per-collective achieved-bandwidth accounting checked against the
    hand-computed bytes/duration, the cross-rank skew table, the
    straggler verdict, plus schema-checker validation of every shard and
    a live scrape of the rank-0 exporter's rank-labelled /metrics and
    /cluster endpoints.  Durations are synthetic by design — the
    accounting chain, not the wire, is what this bench measures."""
    spec = spec or {}
    import importlib.util
    import tempfile
    import threading
    import urllib.request

    from deepspeed_tpu.monitor.telemetry import Telemetry
    from deepspeed_tpu.runtime.config import TelemetryConfig

    n_ranks = int(spec.get("ranks", 4))
    steps = int(spec.get("steps", 12))
    step_ms = float(spec.get("step_ms", 20.0))
    straggler_ms = 4.0 * step_ms              # 4x median, threshold 2x
    comm_bytes = int(spec.get("comm_bytes", 4 << 20))
    comm_dur_ms = float(spec.get("comm_dur_ms", 2.0))
    tmp = tempfile.mkdtemp(prefix="comm_census_bench_")

    def _cfg():
        return TelemetryConfig(
            {"enabled": True, "output_path": tmp,
             "job_name": "comm_census",
             "export": {"enabled": True, "port": 0},
             "distributed": {"enabled": True, "skew_threshold": 2.0,
                             "straggler_window": steps}})

    tels = [None] * n_ranks

    def _run_rank(rank):
        tel = Telemetry().configure(_cfg(), rank=rank)
        tels[rank] = tel
        for step in range(1, steps + 1):
            ms = straggler_ms if rank == n_ranks - 1 else step_ms
            tel.emit("heartbeat", "engine/heartbeat", step=step,
                     step_ms=ms)
            tel.collective("all_reduce", comm_bytes, "fsdp",
                           dtype="float32", dur_ms=comm_dur_ms,
                           world=n_ranks)
            tel.collective("all_gather", comm_bytes // 4, "fsdp",
                           dtype="bfloat16", dur_ms=comm_dur_ms / 2,
                           world=n_ranks)

    threads = [threading.Thread(target=_run_rank, args=(r,))
               for r in range(n_ranks)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    shard_dir = os.path.join(tmp, "comm_census")
    repo = os.path.dirname(os.path.abspath(__file__))
    sp = importlib.util.spec_from_file_location(
        "check_telemetry_schema",
        os.path.join(repo, "scripts", "check_telemetry_schema.py"))
    checker = importlib.util.module_from_spec(sp)
    sp.loader.exec_module(checker)
    shard_problems, n_shards = checker.validate_shard_dir(shard_dir)

    # rank 0 owns the aggregator and the exporter; scrape both surfaces
    tels[0].cluster.refresh(force=True)
    host, port = tels[0].exporter.address
    prom = urllib.request.urlopen(
        f"http://{host}:{port}/metrics", timeout=5).read().decode()
    snap = json.loads(urllib.request.urlopen(
        f"http://{host}:{port}/cluster", timeout=5).read())
    prom_problems = checker.validate_prom_exposition(prom)
    cluster_problems = checker.validate_cluster_payload(snap)
    for tel in tels:
        tel.close()

    # bandwidth accounting: the aggregated achieved GB/s must reproduce
    # the hand-computed sum(bytes)/sum(duration) of the injected events
    expect = comm_bytes / (comm_dur_ms / 1e3) / 1e9
    row = snap["collectives"]["all_reduce"]
    achieved = row["achieved_gbps"] or 0.0
    skew = snap["step_skew"]
    return {
        "ranks": n_ranks,
        "steps_aligned": snap["steps"]["aligned"],
        "shards_validated": n_shards,
        "shard_problems": len(shard_problems),
        "cluster_payload_ok": not cluster_problems,
        "exporter_scrape_ok": not prom_problems and 'rank="0"' in prom,
        "all_reduce_calls": row["calls"],
        "achieved_gbps": achieved,
        "expected_gbps": round(expect, 4),
        "bandwidth_rel_err": round(abs(achieved - expect) / expect, 6),
        "busbw_gbps": row["busbw_gbps"],
        "step_skew_ms": {"p50": skew["p50_spread_ms"],
                         "max": skew["max_spread_ms"]},
        "straggler_rank": snap["straggler"]["rank"],
        "straggler_metric": snap["straggler"]["metric"],
        "straggler_detected": snap["straggler"]["rank"] == n_ranks - 1,
        "note": "synthetic durations: this bench proves the shard -> "
                "aggregate -> scrape accounting chain, not wire speed",
    }


def _worker_comm_census(spec):
    print(json.dumps(_comm_census_bench(spec)))


def _comm_quant_bench(spec=None):
    """CPU-runnable quantized-collective micro-bench: a simulated 4-rank
    grad reduce (shard_map over forced host devices) comparing the fp32
    baseline against the blockwise-int8 two-phase codec in
    comm/quantize.py.  Reports the wire accounting the comm census books
    (bytes-saved ratio vs the analytic int8+scales model), the codec's
    relative error on both verbs, wire-bandwidth rows computed from the
    REDUCED wire bytes, and schema-checker validation of the annotated
    ``comm`` events + frozen quant gauges.  CPU timings are compute-bound
    by design — the codec's numerics and the accounting chain, not wire
    speed, are what this bench measures."""
    spec = spec or {}
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=4").strip()
    import importlib.util
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from deepspeed_tpu.comm.quantize import (QUANT_GAUGES,
                                             quant_bytes_saved,
                                             quant_payload_bytes,
                                             quantized_all_reduce,
                                             quantized_reduce_scatter)
    from deepspeed_tpu.monitor.telemetry import Telemetry
    from deepspeed_tpu.runtime.config import TelemetryConfig

    world = int(spec.get("ranks", 4))
    numel = int(spec.get("numel", 1 << 20))     # fp32 grad shard, 4 MiB
    block = int(spec.get("block_size", 256))
    iters = int(spec.get("iters", 8))
    assert numel % (world * block) == 0
    devices = jax.devices()[:world]
    assert len(devices) == world, \
        f"need {world} host devices, have {len(devices)}"
    mesh = Mesh(np.array(devices), ("dp",))

    def _smap(f, out_specs):
        try:
            from jax import shard_map as sm
            return sm(f, mesh=mesh, in_specs=(P("dp", None),),
                      out_specs=out_specs, check_vma=False)
        except (ImportError, TypeError):
            from jax.experimental.shard_map import shard_map as sm
            return sm(f, mesh=mesh, in_specs=(P("dp", None),),
                      out_specs=out_specs, check_rep=False)

    rng = np.random.default_rng(0)
    # per-rank grad shards with realistic mixed magnitudes
    x = (rng.standard_normal((world, numel)) *
         rng.choice([1e-3, 1e-1, 1.0], (world, numel))).astype(np.float32)
    x = jax.device_put(
        jnp.asarray(x), NamedSharding(mesh, P("dp", None)))

    fp32_ar = jax.jit(_smap(
        lambda g: jax.lax.psum(g, "dp"), P(None, None)))
    int8_ar = jax.jit(_smap(
        lambda g: quantized_all_reduce(g[0], "dp", block)[None],
        P(None, None)))
    int8_rs = jax.jit(_smap(
        lambda g: quantized_reduce_scatter(g[0], "dp", block)[None],
        P("dp", None)))

    def _time(fn):
        fn(x).block_until_ready()            # compile
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(x).block_until_ready()
        return out, (time.perf_counter() - t0) / iters * 1e3

    exact, fp32_ms = _time(fp32_ar)
    quant, int8_ms = _time(int8_ar)
    scattered, rs_ms = _time(int8_rs)
    exact_np = np.asarray(exact)[0]
    ar_err = float(np.linalg.norm(np.asarray(quant)[0] - exact_np) /
                   np.linalg.norm(exact_np))
    rs_full = np.asarray(scattered).reshape(-1)
    rs_err = float(np.linalg.norm(rs_full - exact_np) /
                   np.linalg.norm(exact_np))

    # wire accounting, census semantics: payload bytes per collective
    raw_bytes = numel * 4
    wire_bytes = quant_payload_bytes(numel, block)
    saved = quant_bytes_saved(numel, "float32", block)
    ratio = raw_bytes / wire_bytes

    # the annotated census chain: emit what the engine wiring emits and
    # schema-check every event, including the frozen quant gauges
    tmp = tempfile.mkdtemp(prefix="comm_quant_bench_")
    tel = Telemetry().configure(TelemetryConfig(
        {"enabled": True, "output_path": tmp,
         "job_name": "comm_quant"}), rank=0)
    tel.collective("all_reduce", raw_bytes, "dp", dtype="float32",
                   dur_ms=fp32_ms, world=world)
    tel.collective("all_reduce", wire_bytes, "dp", dtype="float32",
                   dur_ms=int8_ms, world=world,
                   wire_dtype="int8", bytes_saved=saved)
    tel.collective("reduce_scatter", wire_bytes, "dp", dtype="float32",
                   dur_ms=rs_ms, world=world,
                   wire_dtype="int8", bytes_saved=saved)
    for g in QUANT_GAUGES:
        tel.gauge(g, float(saved), step=1)
    tel.close()

    repo = os.path.dirname(os.path.abspath(__file__))
    sp = importlib.util.spec_from_file_location(
        "check_telemetry_schema",
        os.path.join(repo, "scripts", "check_telemetry_schema.py"))
    checker = importlib.util.module_from_spec(sp)
    sp.loader.exec_module(checker)
    problems, n_events = [], 0
    with open(os.path.join(tmp, "comm_quant", "events.jsonl")) as f:
        for line in f:
            n_events += 1
            problems += checker.validate_event(json.loads(line))

    assert ratio >= 3.0, f"bytes-saved ratio {ratio:.3f} below 3x"
    assert ar_err < 0.05 and rs_err < 0.05, (ar_err, rs_err)
    assert not problems, problems[:3]
    return {
        "ranks": world,
        "numel": numel,
        "block_size": block,
        "raw_bytes": raw_bytes,
        "wire_bytes": wire_bytes,
        "bytes_saved": int(saved),
        "bytes_saved_ratio": round(ratio, 4),
        "analytic_ratio": round(raw_bytes /
                                quant_payload_bytes(numel, block), 4),
        "allreduce_rel_err": round(ar_err, 6),
        "reduce_scatter_rel_err": round(rs_err, 6),
        "fp32_allreduce_ms": round(fp32_ms, 3),
        "int8_allreduce_ms": round(int8_ms, 3),
        "int8_reduce_scatter_ms": round(rs_ms, 3),
        "busbw_gbps_fp32": round(raw_bytes / (fp32_ms / 1e3) / 1e9, 4),
        "busbw_gbps_int8_wire": round(wire_bytes / (int8_ms / 1e3) / 1e9,
                                      4),
        "events_validated": n_events,
        "schema_problems": len(problems),
        "note": "CPU timings are compute-bound; the codec numerics and "
                "the bytes-saved accounting chain are what this bench "
                "measures",
    }


def _worker_comm_quant(spec):
    print(json.dumps(_comm_quant_bench(spec)))


def _compile_churn_bench(spec=None):
    """CPU-runnable profiling-plane micro-bench: a jitted kernel driven
    through a deliberately shape-churned workload so every new shape is a
    jit-cache miss.  Reports the observability plane's own numbers: the
    CompileWatcher's miss census against the known churn count, the
    recompile-storm verdict, schema-checker validation of the emitted
    ``compile/*`` events, the mem/roofline gauge path (allocator stats
    injected — CPU has none), and a live scrape of /metrics + /healthz.
    The churn is synthetic by design — the trace -> verdict -> scrape
    chain, not XLA compile speed, is what this bench measures."""
    spec = spec or {}
    import importlib.util
    import tempfile
    import urllib.request

    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.monitor.telemetry import Telemetry
    from deepspeed_tpu.runtime.config import TelemetryConfig

    n_shapes = int(spec.get("shapes", 6))
    repeat = int(spec.get("repeat", 3))
    shapes = [(1, 8 * (i + 1)) for i in range(n_shapes)]
    tmp = tempfile.mkdtemp(prefix="compile_churn_bench_")
    tel = Telemetry().configure(TelemetryConfig(
        {"enabled": True, "output_path": tmp, "job_name": "compile_churn",
         "export": {"enabled": True, "port": 0},
         "profiling": {"enabled": True, "storm_threshold": 3,
                       "storm_window_s": 60.0}}))
    plane = tel.profiling

    @jax.jit
    def kernel(x):
        return (x * 2.0 + 1.0).sum()

    wrapped = plane.wrap(kernel, "bench/churn")
    t0 = time.perf_counter()
    for _ in range(repeat):
        for shape in shapes:
            wrapped(jnp.ones(shape, jnp.float32))
    churn_wall_s = time.perf_counter() - t0
    # hot-path tax: every fingerprint is now cached, so this pass prices
    # the wrapper's per-call dict lookup
    t0 = time.perf_counter()
    for shape in shapes:
        wrapped(jnp.ones(shape, jnp.float32))
    hot_us = (time.perf_counter() - t0) / n_shapes * 1e6
    snap = plane.compile_snapshot()

    # mem attribution + roofline ride the same stream: CPU has no
    # allocator stats, so inject a growing fake and pin the peaks
    state = {"n": 0}

    def fake_stats():
        state["n"] += 1
        return {"bytes_in_use": (1 << 20) + state["n"] * 4096,
                "peak_bytes_in_use": (1 << 20) + state["n"] * 8192}

    plane.hbm.stats_fn = fake_stats
    with plane.track("serve_step"):
        wrapped(jnp.ones(shapes[0], jnp.float32))
    plane.peak_hbm_gbps = 819.0
    plane.roofline("train_batch", 0.01, flops=1e9, bytes_moved=1e8,
                   peak_flops=1e12, step=1)

    host, port = tel.exporter.address
    prom = urllib.request.urlopen(
        f"http://{host}:{port}/metrics", timeout=5).read().decode()
    health = json.loads(urllib.request.urlopen(
        f"http://{host}:{port}/healthz", timeout=5).read())
    tel.close()

    repo = os.path.dirname(os.path.abspath(__file__))
    sp = importlib.util.spec_from_file_location(
        "check_telemetry_schema",
        os.path.join(repo, "scripts", "check_telemetry_schema.py"))
    checker = importlib.util.module_from_spec(sp)
    sp.loader.exec_module(checker)
    events_path = os.path.join(tmp, "compile_churn", "events.jsonl")
    problems = checker.validate_file(events_path)
    prom_problems = checker.validate_prom_exposition(prom)
    misses = storms = mem_gauges = roofline_gauges = 0
    with open(events_path) as f:
        for line in f:
            try:
                ev = json.loads(line)
            except ValueError:
                continue
            if ev.get("kind") == "compile":
                if ev.get("name") == "compile/storm":
                    storms += 1
                else:
                    misses += 1
            elif ev.get("kind") == "gauge":
                if ev.get("name", "").startswith("mem/"):
                    mem_gauges += 1
                elif ev.get("name", "").startswith("roofline/"):
                    roofline_gauges += 1
    return {
        "recompiles": snap["total_misses"],
        "expected_recompiles": n_shapes,
        "storm_flagged": bool(snap["storm_active"]),
        "storm_events": storms,
        "miss_events": misses,
        "mem_gauge_events": mem_gauges,
        "roofline_gauge_events": roofline_gauges,
        "events_ok": not problems,
        "schema_problems": len(problems),
        "exporter_scrape_ok": (not prom_problems and
                               "ds_compile_misses" in prom),
        "healthz_storm": bool(health.get("recompile_storm")),
        "churn_wall_s": round(churn_wall_s, 4),
        "hot_call_overhead_us": round(hot_us, 2),
        "note": "synthetic shape churn: this bench proves the miss -> "
                "event -> storm -> scrape chain, not XLA compile speed",
    }


def _worker_compile_churn(spec):
    print(json.dumps(_compile_churn_bench(spec)))


def _incident_bench(spec=None):
    """CPU-runnable incident-plane micro-bench: prices the always-on
    flight recorder (ring-buffer record ns/event — the tax every emit
    pays once incidents are enabled), then drives a deadline-missing
    serving workload under an injected recompile storm and proves the
    verdict -> bundle chain: the storm onset and the SLO burn-rate
    alerter each write exactly one incident bundle, both validate
    against the frozen bundle schema, and the /incidents endpoint
    serves them.  The workload is synthetic by design — the trigger ->
    bundle -> scrape chain, not model speed, is what this measures."""
    spec = spec or {}
    import importlib.util
    import tempfile
    import urllib.request

    from deepspeed_tpu.monitor.telemetry import Telemetry
    from deepspeed_tpu.runtime.config import TelemetryConfig

    n_events = int(spec.get("events", 20000))
    tmp = tempfile.mkdtemp(prefix="incident_bench_")
    tel = Telemetry().configure(TelemetryConfig(
        {"enabled": True, "output_path": tmp, "job_name": "incident",
         "export": {"enabled": True, "port": 0},
         "profiling": {"enabled": True, "storm_threshold": 3,
                       "storm_window_s": 60.0},
         "incidents": {"enabled": True, "ring_capacity": 4096,
                       "burn_windows": [[60.0, 0.3]],
                       "burn_min_requests": 4, "cooldown_s": 0.0}}))
    incidents = tel.incidents

    # flight-recorder tax: ring.record() is on every emit path, so its
    # per-event cost is the plane's standing overhead
    ev = {"ts": time.time(), "kind": "counter", "name": "bench/tick",
          "value": 1}
    t0 = time.perf_counter()
    for _ in range(n_events):
        incidents.record(ev)
    ring_record_ns = (time.perf_counter() - t0) / n_events * 1e9

    # deadline workload: admitted requests that miss their SLO, with the
    # lifecycle traces + counters the correlation pass joins on
    base = time.time()
    for i in range(6):
        tel.emit("serve", "serve/request/admitted",
                 attrs={"req_id": f"req-{i}", "deadline": 1})
        tel.emit("serve", "serve/request/deadline",
                 attrs={"req_id": f"req-{i}", "slo": "miss"}, step=i)
        tel.count("serve/slo_missed")
    # injected recompile storm: 4 distinct non-cold-diffable fingerprints
    # (the first miss is "cold" and excluded from the storm window)
    for i in range(4):
        tel.profiling.compiles.note_miss(
            "bench/incident", ("f", ((f"s{i}", "f32"),)), 0.01, step=i)
    # SLO burn: rate over the injected misses trips the single window
    t0 = time.perf_counter()
    burn = incidents.observe_slo(now=base + 1.0)
    trigger_wall_ms = (time.perf_counter() - t0) * 1e3

    host, port = tel.exporter.address
    scraped = json.loads(urllib.request.urlopen(
        f"http://{host}:{port}/incidents", timeout=5).read())
    bundle_dir = incidents.bundle_dir
    snap = incidents.snapshot()
    tel.close()

    repo = os.path.dirname(os.path.abspath(__file__))
    sp = importlib.util.spec_from_file_location(
        "check_telemetry_schema",
        os.path.join(repo, "scripts", "check_telemetry_schema.py"))
    checker = importlib.util.module_from_spec(sp)
    sp.loader.exec_module(checker)
    problems, bundles = checker.validate_incidents_path(bundle_dir)
    stream_problems = checker.validate_file(
        os.path.join(tmp, "incident", "events.jsonl"))
    return {
        "ring_record_ns": round(ring_record_ns, 1),
        "ring_events_recorded": n_events,
        "bundles_written": bundles,
        "expected_bundles": 2,          # storm onset + slo_burn
        "slo_burn_fired": bool(burn),
        "slo_burn_trigger_ms": round(trigger_wall_ms, 3),
        "bundles_ok": not problems,
        "bundle_problems": len(problems),
        "events_ok": not stream_problems,
        "incidents_scrape_ok": (
            len(scraped.get("incidents", [])) == bundles),
        "ring_occupancy": int(snap["ring"]["events"]),
        "note": "synthetic deadline workload + injected storm: this "
                "bench proves the trigger -> bundle -> scrape chain and "
                "prices the always-on ring buffer",
    }


def _worker_incident(spec):
    print(json.dumps(_incident_bench(spec)))


def _step_attr_bench(spec=None):
    """CPU-runnable attribution-plane micro-bench: prices the per-event
    record tap and the interval-algebra close, then pins the algebra to
    an analytically constructed workload — a simulated 4-rank step with
    known compute/collective overlap where the collective's only exposed
    window is the 5 ms gap between forward and backward, so the expected
    exposed fraction is EXACTLY 5/100 regardless of per-rank skew (the
    skew shifts overlap between the two compute spans but never changes
    its total).  The serving half round-trips one migrated request
    through capture_handoff -> import_ctx on a fake clock and checks the
    stage sum equals e2e exactly."""
    spec = spec or {}
    import importlib.util
    import tempfile

    from deepspeed_tpu.monitor.attribution import (RequestAttributor,
                                                   decompose_step)
    from deepspeed_tpu.monitor.telemetry import Telemetry
    from deepspeed_tpu.runtime.config import TelemetryConfig

    ranks = int(spec.get("ranks", 4))
    n_record = int(spec.get("events", 20000))
    tmp = tempfile.mkdtemp(prefix="step_attr_bench_")
    tel = Telemetry().configure(TelemetryConfig(
        {"enabled": True, "output_path": tmp, "job_name": "step_attr",
         "attribution": {"enabled": True}}))
    plane = tel.attribution

    # tap tax: record() sits on every emit path once the plane is on
    ev = {"ts": time.time(), "kind": "span", "name": "engine/forward",
          "dur_ms": 1.0}
    t0 = time.perf_counter()
    for _ in range(n_record):
        plane.record(ev)
    record_ns = (time.perf_counter() - t0) / n_record * 1e9
    plane._compute.clear()      # drop the priming intervals

    # analytic workload: window 100 ms, input_wait [0,10], forward
    # [10,40], backward [45,85], all_reduce [30+k, 60+k] for per-rank
    # skew k in 0..3 ms.  The collective's overlap with compute is
    # (10-k) + (15+k) = 25 ms for every k: exposed = 5 ms, frac = 0.05.
    expected_frac = 0.05
    base = time.time()
    for s in range(ranks):
        w0 = base + s
        skew = 0.001 * s
        for name, end_s, dur_ms in (
                ("engine/input_wait", 0.010, 10.0),
                ("engine/forward", 0.040, 30.0),
                ("engine/backward", 0.085, 40.0)):
            plane.record({"ts": w0 + end_s, "kind": "span",
                          "name": name, "dur_ms": dur_ms})
        plane.record({"ts": w0 + 0.060 + skew, "kind": "comm",
                      "name": "all_reduce", "dur_ms": 30.0})
        plane.record({"ts": w0 + 0.100, "kind": "heartbeat",
                      "name": "engine/step", "step": s,
                      "step_ms": 100.0})
    fracs = [r["exposed_comm_frac"] for r in plane.history]
    rel_err = max(abs(f - expected_frac) / expected_frac for f in fracs) \
        if fracs else 1.0
    assert rel_err < 0.02, \
        f"exposed fraction off by {rel_err:.4f} rel: {fracs}"

    # algebra price: one decompose over the same interval mix
    iters = 2000
    t0 = time.perf_counter()
    for _ in range(iters):
        decompose_step(0.0, 0.1,
                       compute=[(0.010, 0.040), (0.045, 0.085)],
                       comm=[(0.030, 0.060)],
                       input_wait=[(0.000, 0.010)])
    decompose_ns = (time.perf_counter() - t0) / iters * 1e9

    # serving half: one migrated request on a fake clock — the stage sum
    # must equal e2e exactly (the gap stage absorbs the residual)
    clock = [0.0]
    src = RequestAttributor(clock=lambda: clock[0])
    src.admit("req-m")
    clock[0] = 0.040
    src.prefill_start("req-m")
    src.chunk("req-m", 25.0)
    clock[0] = 0.080
    wire = src.capture_handoff("req-m")
    dst = RequestAttributor(clock=lambda: clock[0])
    clock[0] = 0.095
    dst.import_ctx("req-m", wire)
    clock[0] = 0.100
    dst.first_token("req-m")
    clock[0] = 0.200
    attrs = dst.finalize("req-m", "finish")
    stage_sum = sum(attrs[f"{k}_ms"] for k in
                    ("queue", "prefill", "migrate", "gap", "decode"))
    sum_err_ms = abs(stage_sum - attrs["e2e_ms"])
    assert sum_err_ms < 1e-6, f"stage sum {stage_sum} != e2e {attrs}"
    # feed the attr event back through emit: schema-checks the frozen
    # event and lands it in the plane's serve history for /attribution
    tel.emit("serve", "serve/request/attr", attrs=attrs)
    snap = plane.snapshot()
    tel.close()

    repo = os.path.dirname(os.path.abspath(__file__))
    sp = importlib.util.spec_from_file_location(
        "check_telemetry_schema",
        os.path.join(repo, "scripts", "check_telemetry_schema.py"))
    checker = importlib.util.module_from_spec(sp)
    sp.loader.exec_module(checker)
    stream = os.path.join(tmp, "step_attr", "events.jsonl")
    stream_problems = checker.validate_file(stream)
    with open(stream) as f:
        events = [json.loads(line) for line in f if line.strip()]
    attr_gauges = sum(1 for ev in events if ev.get("kind") == "gauge"
                      and str(ev.get("name", "")).startswith("step/attr/"))
    return {
        "record_ns": round(record_ns, 1),
        "decompose_ns": round(decompose_ns, 1),
        "steps_attributed": len(fracs),
        "exposed_comm_frac": round(sum(fracs) / len(fracs), 6),
        "exposed_rel_err": round(rel_err, 6),
        "attr_gauges_emitted": attr_gauges,
        "events_ok": not stream_problems,
        "serve_queue_ms": attrs["queue_ms"],
        "serve_prefill_ms": attrs["prefill_ms"],
        "serve_migrate_ms": attrs["migrate_ms"],
        "serve_gap_ms": attrs["gap_ms"],
        "serve_decode_ms": attrs["decode_ms"],
        "serve_e2e_ms": attrs["e2e_ms"],
        "serve_stage_sum_err_ms": round(sum_err_ms, 9),
        "serve_migrated": attrs["migrated"],
        "serve_paths_snapshotted": len(snap["requests"]),
        "note": "analytic 4-rank step: skewed collective overlaps 25 ms "
                "of compute at every skew, so exposed frac is exactly "
                "0.05; serving half round-trips one migration on a fake "
                "clock",
    }


def _worker_step_attr(spec):
    print(json.dumps(_step_attr_bench(spec)))


def _overlap_bench(spec=None):
    """CPU-runnable comm/compute-overlap micro-bench: a simulated 4-rank
    shard_map ZeRO-3 run (forced host devices) training the same stacked
    MLP with two schedules built from the SAME explicit collectives — a
    serial step (gather layer k, compute layer k, back to back) and an
    overlapped step (layer k+1's all_gather issued before layer k's
    compute, the double-buffered layer_scan schedule).  Because every
    collective is explicitly placed under shard_map, overlap reorders
    communication but never math: the 50-step loss trajectory must be
    BIT-IDENTICAL between the two schedules, asserted elementwise.  The
    backward rides the transposed program, where each tiled all_gather
    becomes an explicit per-layer psum_scatter — the ZeRO-3 grad
    reduce-scatter.  The exposure win is priced analytically
    (CPU executes collectives inline, so wall-clock overlap is
    unmeasurable here): ``simulate_forward_schedule`` emits both
    schedules' comm/compute intervals, the closed forms g/(g+c) vs
    g/(g+L*c) pin them, and ``decompose_step`` (the PR-16 interval
    algebra) must reproduce the simulator's own exposed fraction from
    the raw intervals.  The frozen ``comm/overlap/*`` gauges, the
    ``step/attr/exposed_comm_frac`` gauge, and busbw-carrying census
    rows for the gather/reduce-scatter wire bytes are emitted through
    Telemetry and the stream is schema-checker validated."""
    spec = spec or {}
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=4").strip()
    import importlib.util
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from deepspeed_tpu.monitor.attribution import decompose_step
    from deepspeed_tpu.monitor.telemetry import Telemetry
    from deepspeed_tpu.runtime.config import TelemetryConfig
    from deepspeed_tpu.runtime.zero.stage_plan import (
        OVERLAP_GAUGES, simulate_forward_schedule)

    world = int(spec.get("ranks", 4))
    hidden = int(spec.get("hidden", 16))
    layers = int(spec.get("layers", 4))
    steps = int(spec.get("steps", 50))
    lr = float(spec.get("lr", 0.5))
    batch = int(spec.get("batch", 32))
    assert hidden % world == 0 and batch % world == 0
    devices = jax.devices()[:world]
    assert len(devices) == world, \
        f"need {world} host devices, have {len(devices)}"
    mesh = Mesh(np.array(devices), ("fsdp",))

    def _smap(f, in_specs, out_specs):
        try:
            from jax import shard_map as sm
            return sm(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_vma=False)
        except (ImportError, TypeError):
            from jax.experimental.shard_map import shard_map as sm
            return sm(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)

    def _gather(leaf):
        # tiled all_gather: the explicit ZeRO-3 param gather; its
        # transpose is psum_scatter — the explicit grad reduce-scatter
        return jax.lax.all_gather(leaf, "fsdp", axis=0, tiled=True)

    def fwd_serial(wl, bl, xb, yb):
        h = xb
        for k in range(layers):
            wk, bk = _gather(wl[k]), _gather(bl[k])
            h = jnp.tanh(h @ wk + bk)
        err = h - yb
        return jax.lax.psum(jnp.sum(err * err), "fsdp") / (batch * hidden)

    def fwd_overlap(wl, bl, xb, yb):
        # depth-1 double buffer: layer k+1's gather is ISSUED before
        # layer k's compute — same collectives, same operands, reordered
        h = xb
        nxt = (_gather(wl[0]), _gather(bl[0]))
        for k in range(layers):
            cur = nxt
            if k + 1 < layers:
                nxt = (_gather(wl[k + 1]), _gather(bl[k + 1]))
            wk, bk = cur
            h = jnp.tanh(h @ wk + bk)
        err = h - yb
        return jax.lax.psum(jnp.sum(err * err), "fsdp") / (batch * hidden)

    in_specs = (P(None, "fsdp", None), P(None, "fsdp"),
                P("fsdp", None), P("fsdp", None))

    def make_step(fwd):
        loss_fn = _smap(fwd, in_specs, P())

        def step_fn(wl, bl, xb, yb):
            loss, (gw, gb) = jax.value_and_grad(
                loss_fn, argnums=(0, 1))(wl, bl, xb, yb)
            return wl - lr * gw, bl - lr * gb, loss
        return jax.jit(step_fn)

    rng = np.random.default_rng(0)
    w0 = (rng.standard_normal((layers, hidden, hidden)) /
          np.sqrt(hidden)).astype(np.float32)
    b0 = np.zeros((layers, hidden), np.float32)
    proj = (rng.standard_normal((hidden, hidden)) * 0.5).astype(np.float32)
    X = rng.standard_normal((steps, batch, hidden)).astype(np.float32)
    Y = np.tanh(X @ proj)

    w_sh = NamedSharding(mesh, P(None, "fsdp", None))
    b_sh = NamedSharding(mesh, P(None, "fsdp"))
    x_sh = NamedSharding(mesh, P("fsdp", None))

    def run(fwd):
        step_fn = make_step(fwd)
        wl = jax.device_put(jnp.asarray(w0), w_sh)
        bl = jax.device_put(jnp.asarray(b0), b_sh)
        losses = []
        for i in range(steps):
            xb = jax.device_put(jnp.asarray(X[i]), x_sh)
            yb = jax.device_put(jnp.asarray(Y[i]), x_sh)
            wl, bl, loss = step_fn(wl, bl, xb, yb)
            losses.append(np.asarray(loss, np.float32))
        return np.asarray(losses, np.float32)

    t0 = time.perf_counter()
    ser_losses = run(fwd_serial)
    ovl_losses = run(fwd_overlap)
    train_s = time.perf_counter() - t0
    bit_identical = int(np.sum(ser_losses == ovl_losses))
    assert bit_identical == steps, (
        f"overlap reordered math: {steps - bit_identical}/{steps} steps "
        f"diverge, first at step "
        f"{int(np.argmin(ser_losses == ovl_losses))}")
    assert ser_losses[-1] < 0.7 * ser_losses[0], \
        f"run did not train: {ser_losses[0]} -> {ser_losses[-1]}"

    # analytic exposure: serial vs depth-1, pinned to the closed forms
    # and cross-checked through the interval algebra
    c_ms, g_ms, depth = 3.0, 1.0, 1
    ser = simulate_forward_schedule(layers, c_ms, g_ms, 0)
    ovl = simulate_forward_schedule(layers, c_ms, g_ms, depth)
    expected = {"serial": g_ms / (g_ms + c_ms),
                "overlap": g_ms / (g_ms + layers * c_ms)}
    analytic_rel_err = max(
        abs(ser["exposed_comm_frac"] - expected["serial"])
        / expected["serial"],
        abs(ovl["exposed_comm_frac"] - expected["overlap"])
        / expected["overlap"])
    assert analytic_rel_err < 1e-9, \
        f"schedule off the closed form by {analytic_rel_err}"
    algebra_rel_err = 0.0
    for sched in (ser, ovl):
        dec = decompose_step(0.0, sched["step_ms"] / 1e3,
                             compute=sched["compute"], comm=sched["comm"])
        algebra_rel_err = max(
            algebra_rel_err,
            abs(dec["exposed_comm_frac"] - sched["exposed_comm_frac"])
            / max(sched["exposed_comm_frac"], 1e-12))
    # decompose_step rounds its fraction to 6 decimals, so the algebra
    # agrees to quantization (1/13 carries ~1e-6 rel), not exactly
    assert algebra_rel_err < 1e-5, \
        f"interval algebra disagrees by {algebra_rel_err}"
    frac_drop = ser["exposed_comm_frac"] - ovl["exposed_comm_frac"]
    assert frac_drop > 0, "overlap did not reduce exposed comm"

    # book the run: frozen overlap gauges, the step-attr fraction, and
    # busbw census rows for the explicit gather / reduce-scatter wire
    tmp = tempfile.mkdtemp(prefix="overlap_bench_")
    tel = Telemetry().configure(TelemetryConfig(
        {"enabled": True, "output_path": tmp, "job_name": "overlap"}))
    layer_bytes = (hidden * hidden + hidden) * 4
    gauge_vals = {
        "comm/overlap/exposed_ms": ovl["exposed_comm_ms"],
        "comm/overlap/overlapped_ms":
            ovl["comm_ms"] - ovl["exposed_comm_ms"],
        "comm/overlap/gather_buckets": 2 * layers,
        "comm/overlap/rs_buckets": 2 * layers,
        "comm/overlap/prefetch_depth": depth,
    }
    for name in OVERLAP_GAUGES:
        tel.gauge(name, gauge_vals[name])
    tel.gauge("step/attr/exposed_comm_frac", ovl["exposed_comm_frac"])
    for op in ("all_gather", "reduce_scatter"):
        tel.collective(op, layer_bytes * layers, "fsdp", dtype="float32",
                       dur_ms=g_ms * layers, world=world)
    tel.close()

    repo = os.path.dirname(os.path.abspath(__file__))
    sp = importlib.util.spec_from_file_location(
        "check_telemetry_schema",
        os.path.join(repo, "scripts", "check_telemetry_schema.py"))
    checker = importlib.util.module_from_spec(sp)
    sp.loader.exec_module(checker)
    stream = os.path.join(tmp, "overlap", "events.jsonl")
    stream_problems = checker.validate_file(stream)
    with open(stream) as f:
        events = [json.loads(line) for line in f if line.strip()]
    overlap_gauges = sum(
        1 for ev in events if ev.get("kind") == "gauge"
        and str(ev.get("name", "")).startswith("comm/overlap/"))
    census_rows = sum(1 for ev in events if ev.get("kind") == "comm"
                      and "busbw_gbps" in ev)
    return {
        "ranks": world,
        "layers": layers,
        "trajectory_steps": steps,
        "bit_identical_steps": bit_identical,
        "loss_first": float(ser_losses[0]),
        "loss_last": float(ser_losses[-1]),
        "train_s": round(train_s, 3),
        "serial_exposed_comm_frac": round(ser["exposed_comm_frac"], 6),
        "overlap_exposed_comm_frac": round(ovl["exposed_comm_frac"], 6),
        "exposed_frac_drop": round(frac_drop, 6),
        "analytic_rel_err": round(analytic_rel_err, 12),
        "algebra_rel_err": round(algebra_rel_err, 9),
        "overlap_gauges_emitted": overlap_gauges,
        "census_rows": census_rows,
        "events_ok": not stream_problems,
        "note": "4-rank shard_map ZeRO-3: serial vs depth-1 overlapped "
                "schedule from the same explicit collectives — 50-step "
                "trajectory bit-identical by construction; exposure "
                "priced analytically (serial g/(g+c) vs overlapped "
                "g/(g+L*c)) and cross-checked through decompose_step",
    }


def _worker_overlap(spec):
    print(json.dumps(_overlap_bench(spec)))


def _tiered_bench(spec):
    """Tiered-memory-engine micro-bench (runtime/tiered_store.py): a
    synthetic layer stack LARGER than a simulated HBM budget streams
    through host + NVMe tiers behind the schedule-driven prefetch
    engine.  Asserts the fp32 placement round-trips bit-identical, the
    int8 placement stays inside the codec's absmax/127 block bound while
    shrinking the NVMe tier ~4x, the HBM working set respects the budget
    (evictions fired), the sealed directory fscks COMMITTED, the frozen
    ``tier/*`` gauge stream schema-validates, and the bench's own rows
    rehearse the ledger + ds_perf_diff gates."""
    spec = spec or {}
    import importlib.util
    import subprocess as sp
    import tempfile

    import numpy as np

    from deepspeed_tpu.monitor.telemetry import Telemetry
    from deepspeed_tpu.runtime import resilience
    from deepspeed_tpu.runtime.config import TelemetryConfig
    from deepspeed_tpu.runtime.tiered_store import (PlacementPolicy,
                                                    PrefetchEngine,
                                                    TieredStore)

    layers = int(spec.get("layers", 16))
    hidden = int(spec.get("hidden", 64))
    passes = int(spec.get("passes", 3))
    layer_bytes = hidden * hidden * 4
    # the point of the exercise: the model does NOT fit the device
    hbm_budget = 3 * layer_bytes
    model_bytes = layers * layer_bytes
    assert model_bytes > 4 * hbm_budget

    rng = np.random.default_rng(0)
    W = [(rng.standard_normal((hidden, hidden)) / np.sqrt(hidden))
         .astype(np.float32) for _ in range(layers)]

    tmp = tempfile.mkdtemp(prefix="tiered_bench_")
    tel = Telemetry().configure(TelemetryConfig(
        {"enabled": True, "output_path": tmp, "job_name": "tiered"}))
    # patch the store's process-global telemetry hook onto this bench's
    # sink so publish_gauges lands in our stream
    import deepspeed_tpu.monitor.telemetry as _telmod
    _saved = _telmod._telemetry
    _telmod._telemetry = tel

    def run_store(name, quantize):
        store = TieredStore(
            name=name, nvme_dir=tmp,
            policy=PlacementPolicy(default_tier="nvme",
                                   quantize=quantize),
            hbm_budget_bytes=hbm_budget)
        for i, w in enumerate(W):
            # alternate host/NVMe so both beyond-HBM tiers carry load
            store.put(f"L{i}", w, tier="host" if i % 2 else "nvme")
        store.commit()
        sched = [[f"L{i}"] for i in range(layers)]
        eng = PrefetchEngine(store, sched, depth=1)
        t0 = time.perf_counter()
        for _ in range(passes):
            for i in range(layers):
                eng.access(i, device=True)
        dur = time.perf_counter() - t0
        return store, dur

    fp32_store, fp32_s = run_store("bench_fp32", quantize=False)
    int8_store, int8_s = run_store("bench_int8", quantize=True)

    # fp32: tiers are bit-transparent
    exact = sum(int(np.array_equal(fp32_store.fetch(f"L{i}"), W[i]))
                for i in range(layers))
    assert exact == layers, f"fp32 round trip lost bits: {exact}/{layers}"
    # int8: error bounded by the codec's per-block scale (absmax/127)
    int8_max_err, int8_bound = 0.0, 0.0
    for i, w in enumerate(W):
        got = int8_store.fetch(f"L{i}")
        int8_max_err = max(int8_max_err,
                           float(np.max(np.abs(got - w))))
        int8_bound = max(int8_bound, float(np.max(np.abs(w))) / 127.0)
    assert int8_max_err <= int8_bound, (int8_max_err, int8_bound)

    fp32_stats = fp32_store.stats()
    int8_stats = int8_store.stats()
    quant_ratio = int8_stats["nvme_bytes"] / max(fp32_stats["nvme_bytes"],
                                                 1)
    assert quant_ratio < 0.5, f"int8 tier not smaller: {quant_ratio}"
    assert fp32_stats["hbm_bytes"] <= hbm_budget, fp32_stats
    assert fp32_stats["evictions"] > 0, "budget never forced an eviction"
    assert fp32_stats["prefetch_hits"] > fp32_stats["prefetch_misses"], \
        fp32_stats
    committed = sum(
        int(s.validate()[0] == resilience.COMMITTED)
        for s in (fp32_store, int8_store))
    assert committed == 2, "tier dirs did not fsck COMMITTED"

    fp32_store.publish_gauges()
    int8_store.publish_gauges()
    tel.close()
    _telmod._telemetry = _saved

    repo = os.path.dirname(os.path.abspath(__file__))
    scripts_dir = os.path.join(repo, "scripts")
    sp_ = importlib.util.spec_from_file_location(
        "check_telemetry_schema",
        os.path.join(scripts_dir, "check_telemetry_schema.py"))
    checker = importlib.util.module_from_spec(sp_)
    sp_.loader.exec_module(checker)
    stream = os.path.join(tmp, "tiered", "events.jsonl")
    stream_problems = checker.validate_file(stream)
    with open(stream) as f:
        events = [json.loads(line) for line in f if line.strip()]
    tier_gauges = sum(1 for ev in events if ev.get("kind") == "gauge"
                      and str(ev.get("name", "")).startswith("tier/"))
    assert tier_gauges >= len(checker.TIER_GAUGES), tier_gauges

    # ledger + perf-diff rehearsal on a scratch ledger (two runs so the
    # diff has a median to gate against)
    check_ledger = os.path.join(tmp, "ledger.jsonl")
    with open(check_ledger, "w") as f:
        for run in ("run-a", "run-b"):
            for metric, value in (("fp32_pass_s", fp32_s / passes),
                                  ("int8_pass_s", int8_s / passes),
                                  ("quant_ratio", quant_ratio)):
                f.write(json.dumps(
                    {"ts": time.time(), "run": run, "bench": "cpu_tiered",
                     "metric": metric, "value": value}) + "\n")

    def _rc(argv):
        try:
            return sp.run([sys.executable] + argv, capture_output=True,
                          timeout=60).returncode
        except Exception:
            return -1

    ledger_gate_rc = _rc([os.path.join(scripts_dir,
                                       "check_telemetry_schema.py"),
                          "--ledger", check_ledger])
    perf_diff_rc = _rc([os.path.join(scripts_dir, "ds_perf_diff.py"),
                        check_ledger, "--check"])
    assert ledger_gate_rc == 0, f"--ledger gate rc={ledger_gate_rc}"
    assert perf_diff_rc == 0, f"ds_perf_diff --check rc={perf_diff_rc}"

    return {
        "layers": layers,
        "model_mib": round(model_bytes / 2**20, 3),
        "hbm_budget_mib": round(hbm_budget / 2**20, 3),
        "passes": passes,
        "fp32_pass_s": round(fp32_s / passes, 4),
        "int8_pass_s": round(int8_s / passes, 4),
        "fp32_bit_identical_layers": exact,
        "int8_max_err": round(int8_max_err, 6),
        "int8_err_bound": round(int8_bound, 6),
        "quant_ratio": round(quant_ratio, 4),
        "prefetch_hit_rate": fp32_stats["prefetch_hit_rate"],
        "evictions": fp32_stats["evictions"],
        "manifests_committed": committed,
        "tier_gauges_emitted": tier_gauges,
        "events_ok": not stream_problems,
        "ledger_gate_rc": ledger_gate_rc,
        "perf_diff_rc": perf_diff_rc,
        "note": "16-layer stack 4x over a simulated HBM budget streamed "
                "via host+NVMe tiers with depth-1 prefetch: fp32 "
                "bit-identical, int8 inside the absmax/127 block bound "
                "at ~4x smaller NVMe tier, dirs sealed COMMITTED, "
                "tier/* gauges schema-valid",
    }


def _worker_tiered(spec):
    print(json.dumps(_tiered_bench(spec)))


# ---------------------------------------------------------------------------
# parent orchestration
# ---------------------------------------------------------------------------

def _run_worker(name, spec=None, timeout=600, cpu=False, reserve=45):
    # never let one worker spend past the global budget (the driver kills
    # the whole run at its own deadline — a partial result beats rc=124);
    # with the budget exhausted, don't launch at all: the max(...) floor
    # would otherwise keep granting 30s slices past the deadline.
    # ``reserve``: callers of cheap must-run steps (the CPU fallback probe
    # takes ~3s) pass a small reserve so three exhausted 150s TPU probe
    # attempts can't starve them out of the budget entirely
    if _remaining() < reserve:
        return None, "budget exhausted"
    # never grant a slice that outlives the budget: below 35s remaining the
    # 30s floor would push a hung subprocess past the global deadline
    timeout = min(timeout, max(5, _remaining() - 5))
    if _remaining() >= 35:
        timeout = max(30, timeout)
    cmd = [sys.executable, os.path.abspath(__file__), "--worker", name]
    cmd.append(json.dumps(spec) if spec is not None else "null")
    if cpu:
        # NB: must be the in-process config pin — the JAX_PLATFORMS env var
        # is intercepted by the site's backend hook and can hang.
        cmd.append("--cpu")
    try:
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=timeout)
    except subprocess.TimeoutExpired:
        return None, "timeout"
    if out.returncode != 0:
        return None, (out.stderr or "")[-2000:]
    for line in reversed(out.stdout.strip().splitlines()):
        try:
            return json.loads(line), None
        except json.JSONDecodeError:
            continue
    return None, "no json in worker output"


def _attach_dispatch(out):
    """Attach the async-pipeline micro-bench under the stable key
    ``cpu_dispatch`` (runs on CPU, so the perf trajectory for the step
    pipeline grows even when the TPU tunnel is down).  Budget-gated; a
    failure is recorded in notes, never fatal."""
    if _remaining() < 90:
        return out
    res, err = _run_worker(
        "dispatch", {}, timeout=max(60, min(240, int(_remaining()) - 10)),
        cpu=True, reserve=20)
    if res:
        out["cpu_dispatch"] = res
    else:
        out.setdefault("notes", {})["dispatch"] = (err or "")[:200]
    return out


def _attach_serving(out):
    """Attach the serving-overload micro-bench under the stable key
    ``cpu_serving`` (CPU-runnable like the dispatch bench, so the
    shed-rate / tail-latency trajectory grows even when the TPU tunnel is
    down).  Budget-gated; a failure is recorded in notes, never fatal."""
    if _remaining() < 90:
        return out
    res, err = _run_worker(
        "serving", {}, timeout=max(60, min(240, int(_remaining()) - 10)),
        cpu=True, reserve=20)
    if res:
        out["cpu_serving"] = res
    else:
        out.setdefault("notes", {})["serving"] = (err or "")[:200]
    return out


def _attach_serving_prefix(out):
    """Attach the prefix-cache micro-bench under the stable key
    ``cpu_serving_prefix`` (CPU-runnable; grows the hit-rate / pages-saved
    trajectory even when the TPU tunnel is down).  Budget-gated; a failure
    is recorded in notes, never fatal."""
    if _remaining() < 90:
        return out
    res, err = _run_worker(
        "serving_prefix", {},
        timeout=max(60, min(240, int(_remaining()) - 10)),
        cpu=True, reserve=20)
    if res:
        out["cpu_serving_prefix"] = res
    else:
        out.setdefault("notes", {})["serving_prefix"] = (err or "")[:200]
    return out


def _attach_serving_attn(out):
    """Attach the serving-attention micro-bench under the stable key
    ``cpu_serving_attn`` (CPU-runnable: jnp gather vs interpret-mode
    ragged kernel on a mixed batch, equivalence + analytic roofline).
    Budget-gated; a failure is recorded in notes, never fatal."""
    if _remaining() < 90:
        return out
    res, err = _run_worker(
        "serving_attn", {},
        timeout=max(60, min(240, int(_remaining()) - 10)),
        cpu=True, reserve=20)
    if res:
        out["cpu_serving_attn"] = res
    else:
        out.setdefault("notes", {})["serving_attn"] = (err or "")[:200]
    return out


def _attach_serving_slo(out):
    """Attach the serving-SLO micro-bench under the stable key
    ``cpu_serving_slo`` (CPU-runnable: TTFT/TPOT/e2e percentiles, SLO
    attainment, exporter scrape proof).  Budget-gated; a failure is
    recorded in notes, never fatal."""
    if _remaining() < 90:
        return out
    res, err = _run_worker(
        "serving_slo", {},
        timeout=max(60, min(240, int(_remaining()) - 10)),
        cpu=True, reserve=20)
    if res:
        out["cpu_serving_slo"] = res
    else:
        out.setdefault("notes", {})["serving_slo"] = (err or "")[:200]
    return out


def _attach_serving_sched(out):
    """Attach the scheduler micro-bench under the stable key
    ``cpu_serving_sched`` (CPU-runnable: chat TTFT p99 monolithic vs
    chunked vs chunked+speculative on a simulated dispatch clock, decode
    tokens-per-step, spec acceptance, cross-policy bit-identity).
    Budget-gated; a failure is recorded in notes, never fatal."""
    if _remaining() < 90:
        return out
    res, err = _run_worker(
        "serving_sched", {},
        timeout=max(60, min(300, int(_remaining()) - 10)),
        cpu=True, reserve=20)
    if res:
        out["cpu_serving_sched"] = res
    else:
        out.setdefault("notes", {})["serving_sched"] = (err or "")[:200]
    return out


def _attach_comm_census(out):
    """Attach the distributed-telemetry micro-bench under the stable key
    ``cpu_comm_census`` (CPU-runnable: simulated 4-rank shard run,
    bandwidth accounting vs hand-computed, straggler verdict, checker
    validation).  Budget-gated; a failure is recorded in notes, never
    fatal."""
    if _remaining() < 90:
        return out
    res, err = _run_worker(
        "comm_census", {},
        timeout=max(60, min(240, int(_remaining()) - 10)),
        cpu=True, reserve=20)
    if res:
        out["cpu_comm_census"] = res
    else:
        out.setdefault("notes", {})["comm_census"] = (err or "")[:200]
    return out


def _attach_comm_quant(out):
    """Attach the quantized-collective micro-bench under the stable key
    ``cpu_comm_quant`` (CPU-runnable: 4-rank shard_map grad reduce, fp32
    vs blockwise int8, bytes-saved ratio vs the analytic model, codec
    error bound, checker-validated annotated events).  Budget-gated; a
    failure is recorded in notes, never fatal."""
    if _remaining() < 90:
        return out
    res, err = _run_worker(
        "comm_quant", {},
        timeout=max(60, min(240, int(_remaining()) - 10)),
        cpu=True, reserve=20)
    if res:
        out["cpu_comm_quant"] = res
    else:
        out.setdefault("notes", {})["comm_quant"] = (err or "")[:200]
    return out


def _attach_compile_churn(out):
    """Attach the profiling-plane micro-bench under the stable key
    ``cpu_compile_churn`` (CPU-runnable: shape-churned jit workload,
    compile/* event validation, storm verdict, /metrics + /healthz
    scrape).  Budget-gated; a failure is recorded in notes, never
    fatal."""
    if _remaining() < 90:
        return out
    res, err = _run_worker(
        "compile_churn", {},
        timeout=max(60, min(240, int(_remaining()) - 10)),
        cpu=True, reserve=20)
    if res:
        out["cpu_compile_churn"] = res
    else:
        out.setdefault("notes", {})["compile_churn"] = (err or "")[:200]
    return out


def _attach_fleet(out):
    """Attach the fleet-failover micro-bench under the stable key
    ``cpu_fleet`` (CPU-runnable: aggregate throughput vs replica count,
    per-replica prefix hit rates, and kill-recovery cost).  Budget-gated;
    a failure is recorded in notes, never fatal."""
    if _remaining() < 90:
        return out
    res, err = _run_worker(
        "fleet", {},
        timeout=max(60, min(300, int(_remaining()) - 10)),
        cpu=True, reserve=20)
    if res:
        out["cpu_fleet"] = res
    else:
        out.setdefault("notes", {})["fleet"] = (err or "")[:200]
    return out


def _attach_fleet_disagg(out):
    """Attach the disaggregated-fleet micro-bench under the stable key
    ``cpu_fleet_disagg`` (CPU-runnable: chat TTFT p99 unified vs
    prefill/decode-specialised, migrated vs dedup-skipped page counts,
    zero-loss + bit-identity).  Budget-gated; a failure is recorded in
    notes, never fatal."""
    if _remaining() < 90:
        return out
    res, err = _run_worker(
        "fleet_disagg", {},
        timeout=max(60, min(300, int(_remaining()) - 10)),
        cpu=True, reserve=20)
    if res:
        out["cpu_fleet_disagg"] = res
    else:
        out.setdefault("notes", {})["fleet_disagg"] = (err or "")[:200]
    return out


def _attach_fleet_xproc(out):
    """Attach the cross-process-fleet micro-bench under the stable key
    ``cpu_fleet_xproc`` (CPU-runnable: tokens/fleet-step in-process vs
    real worker processes over the socket transport, kill -9 recovery
    latency, zero-loss + survivors bit-identical).  Budget-gated; a
    failure is recorded in notes, never fatal."""
    if _remaining() < 90:
        return out
    res, err = _run_worker(
        "fleet_xproc", {},
        timeout=max(60, min(300, int(_remaining()) - 10)),
        cpu=True, reserve=20)
    if res:
        out["cpu_fleet_xproc"] = res
    else:
        out.setdefault("notes", {})["fleet_xproc"] = (err or "")[:200]
    return out


def _attach_fleet_chaos(out):
    """Attach the chaos-recovery micro-bench under the stable key
    ``cpu_fleet_chaos`` (CPU-runnable: gate-10 wire-fault scenarios —
    ack loss, slow worker breaker trip, torn commit — per-scenario
    recovery wall time, retry/breaker/dedup counters, zero-loss +
    bit-identity asserted inside each scenario).  Budget-gated; a
    failure is recorded in notes, never fatal."""
    if _remaining() < 120:
        return out
    res, err = _run_worker(
        "fleet_chaos", {},
        timeout=max(90, min(360, int(_remaining()) - 10)),
        cpu=True, reserve=20)
    if res:
        out["cpu_fleet_chaos"] = res
    else:
        out.setdefault("notes", {})["fleet_chaos"] = (err or "")[:200]
    return out


def _attach_incident(out):
    """Attach the incident-plane micro-bench under the stable key
    ``cpu_incident`` (CPU-runnable: ring-buffer record overhead, injected
    storm + deadline workload -> bundle chain, /incidents scrape).
    Budget-gated; a failure is recorded in notes, never fatal."""
    if _remaining() < 90:
        return out
    res, err = _run_worker(
        "incident", {},
        timeout=max(60, min(240, int(_remaining()) - 10)),
        cpu=True, reserve=20)
    if res:
        out["cpu_incident"] = res
    else:
        out.setdefault("notes", {})["incident"] = (err or "")[:200]
    return out


def _attach_step_attr(out):
    """Attach the attribution-plane micro-bench under the stable key
    ``cpu_step_attr`` (CPU-runnable: record-tap/decompose pricing, the
    analytic 4-rank exposed-comm fraction check, and one fake-clock
    migrated request whose stage sum must equal e2e).  Budget-gated; a
    failure is recorded in notes, never fatal."""
    if _remaining() < 90:
        return out
    res, err = _run_worker(
        "step_attr", {},
        timeout=max(60, min(240, int(_remaining()) - 10)),
        cpu=True, reserve=20)
    if res:
        out["cpu_step_attr"] = res
    else:
        out.setdefault("notes", {})["step_attr"] = (err or "")[:200]
    return out


def _attach_overlap(out):
    """Attach the comm/compute-overlap micro-bench under the stable key
    ``cpu_overlap`` (CPU-runnable: simulated 4-rank shard_map ZeRO-3 run,
    serial vs double-buffered schedule with a bit-identical 50-step loss
    trajectory, analytic exposed-comm-fraction drop cross-checked through
    the interval algebra, frozen overlap gauges schema-validated).
    Budget-gated; a failure is recorded in notes, never fatal."""
    if _remaining() < 90:
        return out
    res, err = _run_worker(
        "overlap", {},
        timeout=max(60, min(300, int(_remaining()) - 10)),
        cpu=True, reserve=20)
    if res:
        out["cpu_overlap"] = res
    else:
        out.setdefault("notes", {})["overlap"] = (err or "")[:200]
    return out


def _attach_tiered(out):
    """Attach the tiered-memory micro-bench under the stable key
    ``cpu_tiered`` (CPU-runnable: layer stack 4x over a simulated HBM
    budget streamed through host/NVMe tiers, fp32 bit-identical vs int8
    error-bounded, manifest fsck, tier/* gauges schema-validated, ledger
    + perf-diff rehearsal).  Budget-gated; a failure is recorded in
    notes, never fatal."""
    if _remaining() < 90:
        return out
    res, err = _run_worker(
        "tiered", {},
        timeout=max(60, min(300, int(_remaining()) - 10)),
        cpu=True, reserve=20)
    if res:
        out["cpu_tiered"] = res
    else:
        out.setdefault("notes", {})["tiered"] = (err or "")[:200]
    return out


def _attach_autotune(out):
    """Attach the closed-loop autotuner micro-bench under the stable key
    ``cpu_autotune`` (CPU-runnable: end-to-end tune over a serving knob
    grid on the simulated dispatch clock, tuned-vs-default verdict,
    overlay round-trip, tune/ledger/perf-diff gate rcs).  Budget-gated;
    a failure is recorded in notes, never fatal."""
    if _remaining() < 90:
        return out
    res, err = _run_worker(
        "autotune", {},
        timeout=max(60, min(300, int(_remaining()) - 10)),
        cpu=True, reserve=20)
    if res:
        out["cpu_autotune"] = res
    else:
        out.setdefault("notes", {})["autotune"] = (err or "")[:200]
    return out


def _append_ledger(out):
    """Append this run's numeric bench metrics to the perf-regression
    ledger (``BENCH_LEDGER`` env override; default BENCH_LEDGER.jsonl
    next to this file).  One row per (bench, metric) scalar — the frozen
    row schema lives in scripts/check_telemetry_schema.py (--ledger) and
    scripts/ds_perf_diff.py gates later runs against the medians.  Best
    effort: a read-only checkout must not fail the bench."""
    path = os.environ.get(
        "BENCH_LEDGER",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "BENCH_LEDGER.jsonl"))
    ts = time.time()
    run = f"run-{int(ts)}"
    rows = []

    def _rows_from(bench, rec):
        for metric, value in rec.items():
            if isinstance(value, bool) or not isinstance(value,
                                                         (int, float)):
                continue
            rows.append({"ts": ts, "run": run, "bench": bench,
                         "metric": metric, "value": value})

    # A degraded run promotes the cached on-chip record to the top level
    # (_promote_cached); replaying that stale value here would re-append
    # the same constant on every tunnel-down run, pinning the
    # ds_perf_diff.py baseline median to it and making the perf gate pass
    # vacuously.  Ledger only what this run actually measured: the
    # degraded run's own train metric (a distinct cpu-fallback metric
    # name), or nothing.
    src = out.get("this_run", {}) if out.get("fallback") == "cached_onchip" \
        else out
    if isinstance(src.get("value"), (int, float)) and src.get("metric"):
        rows.append({"ts": ts, "run": run, "bench": "train",
                     "metric": str(src["metric"]),
                     "value": float(src["value"]),
                     "unit": str(src.get("unit", ""))})
    for key, rec in out.items():
        if key.startswith("cpu_") and isinstance(rec, dict):
            _rows_from(key, rec)
    if not rows:
        return out
    try:
        with open(path, "a") as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")
        out["ledger"] = {"path": path, "run": run, "rows": len(rows)}
    except OSError as e:
        out.setdefault("notes", {})["ledger"] = str(e)[:200]
    return out


def main():
    errors = {}

    # 1. backend probe (retry, then CPU fallback).  The axon backend either
    # initialises in ~60-90s or hangs forever — a short leash per attempt
    # leaves budget for the train run when a later attempt succeeds.
    probe = None
    for attempt in range(3):
        # a hung first attempt already diagnoses the tunnel: keep retries
        # short so the CPU train fallback still fits in the budget
        probe, err = _run_worker("probe", timeout=150 if attempt == 0 else 60)
        if probe:
            break
        errors[f"probe_attempt{attempt}"] = err
        time.sleep(10)
    if not probe:
        probe, err = _run_worker("probe", timeout=150, cpu=True, reserve=8)
        if probe:
            probe["fallback"] = "cpu"
        else:
            errors["probe_cpu"] = err
            out = {
                "metric": "train_tokens_per_sec_per_chip",
                "value": 0.0, "unit": "tokens/s/chip", "vs_baseline": 0.0,
                "error": f"backend unavailable: {errors}",
            }
            print(json.dumps(_append_ledger(_attach_tiered(_attach_overlap(_attach_autotune(_attach_step_attr(_attach_incident(_attach_fleet_chaos(_attach_fleet_xproc(_attach_fleet_disagg(_attach_fleet(_attach_compile_churn(_attach_comm_quant(_attach_comm_census(_attach_serving_sched(_attach_serving_slo(_attach_serving_attn(_attach_serving_prefix(_attach_serving(_attach_dispatch(_promote_cached(out))))))))))))))))))))))
            return

    on_tpu = probe["platform"] not in ("cpu",)
    kind = probe.get("kind", "")
    n_chips = max(1, probe.get("n_devices", 1))
    peak = _lookup(_PEAK_TFLOPS, kind, 197.0) if on_tpu else None
    hbm = probe.get("hbm") or (_lookup(_HBM_FALLBACK, kind, 16e9)
                               if on_tpu else 4e9)

    # 2. best-known single-chip config first: gpt_1b (1.01B params) with
    # bf16 Adam moments (SR) + bf16 grad accum — the full >=1B train state
    # fits one 16 GB chip with NO host offload, measured MFU 0.486 /
    # 95.7 TFLOPs on TPU v5 lite (ONCHIP_r03/big_1b.json).  Falls back to
    # the footprint-driven ladder if it OOMs (e.g. smaller-HBM chip).
    train, name, spec = None, None, None
    if on_tpu and hbm >= 15e9 and n_chips == 1:
        name = "gpt_1b"
        kw = dict(vocab_size=50304, hidden_size=2048, n_layers=18,
                  n_heads=16, max_seq_len=1024, activation="gelu",
                  use_rmsnorm=False, use_rope=False, tie_embeddings=True)
        spec = {"model": kw, "batch": 2, "seq": 1024, "steps": 12,
                "remat": True, "gas": 4, "zero": {"stage": 3},
                "moment_dtype": "bfloat16", "grad_accum_dtype": "bfloat16"}
        train, err = _run_worker("train", spec, timeout=1800)
        if not train:
            errors["train_gpt_1b"] = err

    # 2b. footprint-driven ladder --------------------------------------
    if not train:
        if on_tpu:
            seq, steps = 1024, 12
            choice = None
            for lname, kw in _LADDER:
                batch = 8 * n_chips
                while batch >= n_chips and \
                        _footprint(kw, batch, seq, n_chips) > 0.82 * hbm:
                    batch //= 2
                if batch >= n_chips:
                    choice = (lname, kw, batch)
                    break
            if choice is None:
                choice = ("gpt2_125m", dict(_LADDER[-1][1]), 1)
            name, kw, batch = choice
        else:
            name, kw, batch = "gpt2_125m", dict(_LADDER[-1][1]), 4
            seq, steps = 256, 3

        # gas=4 fuses four microbatches into one dispatch (measured +5% on
        # the tunneled chip: the per-step RPC overhead amortizes)
        spec = {"model": kw, "batch": batch, "seq": seq, "steps": steps,
                "remat": True, "gas": 4 if on_tpu else 1,
                "zero": {"stage": 3}}
        train, err = _run_worker("train", spec, timeout=1800, cpu=not on_tpu)
    if not train:
        # record the first attempt's failure NOW: if the budget runs out
        # before any retry, this error would otherwise vanish from the
        # output line (observed: only probe timeouts reported)
        errors["train_tpu" if on_tpu else "train_cpu"] = err
    if not train and on_tpu:
        # one retry, one rung down, shorter leash (a hung backend costs
        # the timeout — don't walk the whole ladder at 1800 s each)
        idx = [n for n, _ in _LADDER].index(name)
        if idx + 1 < len(_LADDER):
            smaller, kw2 = _LADDER[idx + 1]
            train, err = _run_worker("train", dict(spec, model=kw2),
                                     timeout=900)
            if train:
                name = smaller
            else:
                errors[f"train_{smaller}"] = err
    if not train and _remaining() > 120:
        name = "gpt2_125m_cpu_fallback"
        spec = {"model": dict(_LADDER[-1][1]), "batch": 4, "seq": 256,
                "steps": 3, "remat": True, "zero": {"stage": 3}}
        train, err = _run_worker("train", spec, timeout=1800, cpu=True)
        if not train:
            errors["train_fallback"] = err   # the LAST thing that ran
        on_tpu = False
        peak = None
        kind = "cpu"
        n_chips = 1
    if not train:
        out = {
            "metric": "train_tokens_per_sec_per_chip",
            "value": 0.0, "unit": "tokens/s/chip", "vs_baseline": 0.0,
            "error": f"all train attempts failed: {errors}",
        }
        print(json.dumps(_append_ledger(_attach_tiered(_attach_overlap(_attach_autotune(_attach_step_attr(_attach_incident(_attach_fleet_chaos(_attach_fleet_xproc(_attach_fleet_disagg(_attach_fleet(_attach_compile_churn(_attach_serving_sched(_attach_serving_slo(_attach_serving_attn(_attach_serving_prefix(_attach_serving(_attach_dispatch(_promote_cached(out))))))))))))))))))))
        return

    tps = train["tokens_per_sec"]
    n_params = train["n_params"]
    tflops = 6.0 * n_params * tps / 1e12 / n_chips

    # 3. max-params-on-one-chip probe (param-stream) --------------------
    max_params = None
    max_params_kind = None
    if on_tpu:
        # with param-stream the stack lives on the HOST: the binding
        # constraint is host RAM at 16 B/param (fp32 master + 2 fp32
        # moments + bf16 mirror + bf16 grad accum), not HBM
        try:
            host_ram = (os.sysconf("SC_PHYS_PAGES") *
                        os.sysconf("SC_PAGE_SIZE"))
        except (ValueError, OSError):
            host_ram = 64e9
        analytic = int(0.8 * host_ram / 16.0)
        if _remaining() > 150:
            # short seq: the probe establishes the model FITS and steps;
            # long-seq throughput is the training bench's job.  Streaming
            # >4B params through the tunnel is slow, hence the
            # budget-bounded attempts.
            for frac in (0.75, 0.55):
                target = int(analytic * frac)
                # scale a GPT shape to the target count: params ~ 12 L d^2
                d = 4096
                L = max(4, int(target / (12 * d * d)))
                probe_kw = dict(vocab_size=50304, hidden_size=d, n_layers=L,
                                n_heads=32, max_seq_len=1024,
                                activation="gelu", use_rmsnorm=False,
                                use_rope=False, tie_embeddings=True)
                res, err = _run_worker(
                    "params_probe", {"model": probe_kw, "seq": 256},
                    timeout=420)
                if res and res.get("ok"):
                    max_params, max_params_kind = res["n_params"], "measured"
                    break
                errors[f"params_probe_{frac}"] = err
                if _remaining() < 150:
                    break
        if max_params is None:
            # probes couldn't run to completion in budget: report the
            # analytic bound, clearly labeled (never passed off as measured)
            max_params, max_params_kind = analytic, "analytic"

    result = {
        "metric": f"train_tokens_per_sec_per_chip_{name}_bf16_zero3_seq"
                  f"{spec['seq']}",
        "value": round(tps / n_chips, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(tflops / 50.0, 3),
        "model_tflops_per_chip": round(tflops, 1),
        "n_params": n_params,
        "device_kind": kind,
        "n_chips": n_chips,
    }
    for k in ("moment_dtype", "grad_accum_dtype"):
        if spec.get(k):
            result[k] = spec[k]
    if peak:
        result["mfu"] = round(tflops / peak, 4)
        result["peak_tflops_bf16"] = peak
    if max_params is not None:
        result["max_params_single_chip"] = max_params
        result["max_params_kind"] = max_params_kind
    if errors:
        result["notes"] = {k: (v or "")[:200] for k, v in errors.items()}
    if not on_tpu:
        result["fallback_platform"] = "cpu"
        result = _promote_cached(result)
    else:
        _save_onchip(result)   # cpu_dispatch attaches after: cache stays on-chip-only
    print(json.dumps(_append_ledger(_attach_tiered(_attach_overlap(_attach_autotune(_attach_step_attr(_attach_incident(_attach_fleet_chaos(_attach_fleet_xproc(_attach_fleet_disagg(_attach_fleet(_attach_compile_churn(_attach_comm_quant(_attach_comm_census(_attach_serving_sched(_attach_serving_slo(_attach_serving_attn(_attach_serving_prefix(_attach_serving(_attach_dispatch(result)))))))))))))))))))))


if __name__ == "__main__":
    if len(sys.argv) > 2 and sys.argv[1] == "--worker":
        which = sys.argv[2]
        spec = json.loads(sys.argv[3]) if len(sys.argv) > 3 else None
        import jax
        # persistent compile cache: repeat bench runs (and the retry
        # ladder) skip the 20-40s XLA compile of unchanged programs
        jax.config.update("jax_compilation_cache_dir",
                          os.path.expanduser("~/.cache/dstpu_xla_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
        if "--cpu" in sys.argv:
            jax.config.update("jax_platforms", "cpu")
        if which == "probe":
            _worker_probe()
        elif which == "train":
            _worker_train(spec)
        elif which == "params_probe":
            _worker_params_probe(spec)
        elif which == "dispatch":
            _worker_dispatch(spec)
        elif which == "serving":
            _worker_serving(spec)
        elif which == "serving_prefix":
            _worker_serving_prefix(spec)
        elif which == "fleet":
            _worker_fleet(spec)
        elif which == "fleet_disagg":
            _worker_fleet_disagg(spec)
        elif which == "fleet_xproc":
            _worker_fleet_xproc(spec)
        elif which == "fleet_chaos":
            _worker_fleet_chaos(spec)
        elif which == "serving_attn":
            _worker_serving_attn(spec)
        elif which == "serving_slo":
            _worker_serving_slo(spec)
        elif which == "serving_sched":
            _worker_serving_sched(spec)
        elif which == "comm_census":
            _worker_comm_census(spec)
        elif which == "comm_quant":
            _worker_comm_quant(spec)
        elif which == "compile_churn":
            _worker_compile_churn(spec)
        elif which == "incident":
            _worker_incident(spec)
        elif which == "step_attr":
            _worker_step_attr(spec)
        elif which == "autotune":
            _worker_autotune(spec)
        elif which == "overlap":
            _worker_overlap(spec)
        elif which == "tiered":
            _worker_tiered(spec)
        else:
            raise SystemExit(f"unknown worker {which}")
    else:
        main()
