"""Experiment scheduler / resource manager.

Parity: reference ``autotuning/scheduler.py`` (``ResourceManager``: queue of
experiments, per-experiment result JSON under ``autotuning_results/``,
best-tracking).  On a single TPU host experiments run sequentially in
process (the reference schedules across free nodes); the journal format is
kept so results survive crashes and re-runs skip finished experiments.
"""

import json
import os
import time
from typing import Any, Callable, Dict, List, Optional

from deepspeed_tpu.utils.logging import logger


class Experiment:

    def __init__(self, name: str, ds_config: Dict[str, Any],
                 model_overrides: Optional[Dict[str, Any]] = None):
        self.name = name
        self.ds_config = ds_config
        # TransformerConfig knob overrides for this trial (remat policy,
        # attention tile sizes — template model knobs the reference has no
        # analogue for); merged into the trial worker's model spec
        self.model_overrides = dict(model_overrides or {})
        self.result: Optional[Dict[str, Any]] = None

    def done(self) -> bool:
        return self.result is not None


class ResourceManager:

    def __init__(self, results_dir: str = "autotuning_results",
                 metric: str = "throughput", overwrite: bool = True):
        self.results_dir = results_dir
        self.metric = metric
        self.overwrite = overwrite
        self.experiments: List[Experiment] = []
        os.makedirs(results_dir, exist_ok=True)

    def _result_path(self, exp: Experiment) -> str:
        return os.path.join(self.results_dir, f"{exp.name}.json")

    def schedule_experiments(self, exps: List[Experiment]):
        self.experiments.extend(exps)

    def _load_journaled(self, exp: Experiment) -> bool:
        """Try to satisfy ``exp`` from its on-disk journal (crash/resume:
        a re-run skips finished experiments).  Returns True when the
        journal was reused.  A torn trailing journal — the experiment
        whose result write the crash interrupted — is tolerated: the
        unparseable file is treated as absent and the experiment re-runs."""
        path = self._result_path(exp)
        if not os.path.exists(path):
            return False
        try:
            with open(path) as f:
                journaled = json.load(f)
        except ValueError:
            logger.warning(f"autotuning: journal for {exp.name} is torn "
                           "(crash mid-write?); re-running")
            return False
        if not isinstance(journaled, dict):
            return False
        if journaled.get("ds_config") == json.loads(
                json.dumps(exp.ds_config, default=str)) and \
                journaled.get("model_overrides", {}) == json.loads(
                    json.dumps(exp.model_overrides, default=str)):
            exp.result = journaled
            logger.info(f"autotuning: reusing journaled {exp.name}")
            return True
        logger.info(f"autotuning: journaled {exp.name} has a "
                    "different ds_config; re-running")
        return False

    def run_one(self, exp: Experiment,
                run_fn: Callable[[Experiment], Dict[str, Any]]) \
            -> Dict[str, Any]:
        """THE shared trial runner: the legacy :class:`Autotuner` grid
        phases and the closed-loop control plane
        (``autotuning/controlplane.py``) both execute every trial through
        this one body — timing, failure capture, and journaling live in
        exactly one place.  Returns the (journaled) metrics dict."""
        if exp.result is None and not self.overwrite:
            self._load_journaled(exp)
        if exp.result is not None:
            return exp.result
        t0 = time.time()
        try:
            metrics = run_fn(exp)
        except Exception as e:  # infeasible config (e.g. OOM) scores 0
            logger.warning(f"autotuning: {exp.name} failed: {e}")
            metrics = {self.metric: 0.0, "error": str(e)}
        metrics["wall_s"] = time.time() - t0
        metrics["ds_config"] = exp.ds_config
        if exp.model_overrides:
            metrics["model_overrides"] = exp.model_overrides
        exp.result = metrics
        with open(self._result_path(exp), "w") as f:
            json.dump(metrics, f, indent=1, default=str)
        return metrics

    def run(self, run_fn: Callable[[Experiment], Dict[str, Any]]):
        """Run all pending experiments.  With ``overwrite=False``,
        previously-journaled results are reused (reference skip-finished
        behaviour) — but only when the journaled ds_config matches this
        experiment's, so a stale ``autotuning_results/`` dir from a
        different model can't supply wrong measurements under the same
        experiment name."""
        for exp in self.experiments:
            self.run_one(exp, run_fn)

    @staticmethod
    def best_of(exps: List[Experiment],
                metric: str) -> Optional[Experiment]:
        """THE ranking rule (one definition for every phase): failed
        experiments (crash/OOM) never win — a {metric: 0.0} sentinel would
        rank first under minimize metrics like latency."""
        done = [e for e in exps if e.done() and "error" not in e.result]
        if not done:
            return None
        sign = -1 if metric == "latency" else 1
        return max(done, key=lambda e: sign * float(
            e.result.get(metric, 0.0)))

    def best_experiment(self) -> Optional[Experiment]:
        return self.best_of(self.experiments, self.metric)
