"""The declared knob space the control plane sweeps.

Every knob names a slash path into the ds-config (``kind="ds"``) or a
TransformerConfig field (``kind="model"``, surfaced through
``autotuning_model_overrides`` exactly like the legacy template tuner),
plus its candidate values.  The default spaces cover the knobs the
observability planes showed actually move the gauges:

* training — gradient-accumulation steps, the async checkpoint/dataloader
  pipeline's prefetch depth, and the remat policy (a model knob);
* serving — KV page size, the scheduler's prefill chunk tokens and
  speculative draft length, the admission watermarks, and the fleet's
  prefill/decode replica mix.

``KnobSpace.grid()`` enumerates the cartesian product;
``fragment_for(point)`` turns one point into the ds-config fragment that
becomes the trial config (and, for a winner, the persisted overlay).
"""

import itertools
from typing import Any, Dict, Iterator, List, Optional, Sequence

from deepspeed_tpu.autotuning.config_templates import set_ds_path


class Knob:
    """One tunable dimension: ``path`` is a ``/``-separated ds-config path
    (``kind="ds"``) or a TransformerConfig field name (``kind="model"``)."""

    def __init__(self, name: str, path: str, values: Sequence[Any],
                 domain: str = "serving", kind: str = "ds"):
        if domain not in ("training", "serving"):
            raise ValueError(f"knob {name!r}: unknown domain {domain!r}")
        if kind not in ("ds", "model"):
            raise ValueError(f"knob {name!r}: unknown kind {kind!r}")
        if not values:
            raise ValueError(f"knob {name!r}: empty candidate list")
        self.name = name
        self.path = path
        self.values = list(values)
        self.domain = domain
        self.kind = kind

    def to_dict(self) -> Dict[str, Any]:
        return {"path": self.path, "values": self.values,
                "domain": self.domain, "kind": self.kind}

    def __repr__(self):
        return f"Knob({self.name!r}, {self.path!r}, {self.values})"


_COMM_QUANT_BLOCK_CANDIDATES = (64, 128, 256, 512)


def comm_quant_block_knob(pad_multiple: Optional[int] = None) -> Knob:
    """The ``comm.quantization.block_size`` knob, candidates pruned to
    divisors of the grad-bucket padding multiple: a block that does not
    divide the bucket boundary would fold padding zeros into a real
    block's absmax scale, quietly inflating quantization error for that
    tail block.  ``pad_multiple`` defaults to the ZeRO
    ``reduce_bucket_size`` default."""
    if pad_multiple is None:
        from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig
        pad_multiple = int(DeepSpeedZeroConfig.reduce_bucket_size)
    values = [b for b in _COMM_QUANT_BLOCK_CANDIDATES
              if pad_multiple % b == 0]
    return Knob("comm_quant_block_size", "comm/quantization/block_size",
                values or [256], domain="training")


def memory_knobs(nvme_dir: Optional[str] = None) -> List[Knob]:
    """Tiered-memory engine knobs (``runtime/tiered_store.py``): the
    default placement tier and the pinned-host budget.  ``nvme`` only
    enters the placement candidates when the caller declares an
    ``nvme_dir`` — a placement the store cannot realise is pruned here
    rather than burned as a trial (the control plane additionally
    rejects nvme placements whose config carries no dir, and prices
    host/nvme placements into the ZeRO memory model as offloaded
    state)."""
    tiers = ["host", "nvme"] if nvme_dir else ["host"]
    knobs = [
        Knob("mem_placement_policy", "memory/placement_policy", tiers,
             domain="training"),
        Knob("mem_host_budget_bytes", "memory/host_budget_bytes",
             [0, 1 << 30, 4 << 30, 16 << 30], domain="training"),
    ]
    if nvme_dir:
        knobs.append(Knob("mem_nvme_dir", "memory/nvme_dir", [nvme_dir],
                          domain="training"))
    return knobs


def default_training_knobs() -> List[Knob]:
    return [
        Knob("gas", "gradient_accumulation_steps", [1, 2, 4, 8],
             domain="training"),
        Knob("prefetch_depth", "async_pipeline/prefetch_depth", [1, 2, 4],
             domain="training"),
        Knob("remat_policy", "remat_policy",
             ["nothing_saveable", "dots_saveable"],
             domain="training", kind="model"),
        # quantized-collective wire codec (comm/quantize.py): whether the
        # grad reduce rides int8, and at which scale-block granularity
        Knob("comm_quant_enabled", "comm/quantization/enabled",
             [False, True], domain="training"),
        comm_quant_block_knob(),
        # explicit ZeRO-3 comm/compute overlap (stage_plan.layer_scan +
        # the engine's bucketed reduce-scatter): the gather prefetch
        # depth is HBM-priced — depth+1 gathered working sets stay live,
        # so the control plane prunes infeasible depths through
        # gather_buffer_bytes before spending a trial on them;
        # step/attr/exposed_comm_frac (objective weight -100) scores the
        # survivors
        Knob("overlap_enabled", "zero_optimization/overlap/enabled",
             [False, True], domain="training"),
        Knob("gather_prefetch_depth",
             "zero_optimization/overlap/gather_prefetch_depth", [1, 2, 4],
             domain="training"),
        Knob("rs_bucket_bytes",
             "zero_optimization/overlap/rs_bucket_bytes",
             [25_000_000, 50_000_000, 100_000_000], domain="training"),
    ]


def default_serving_knobs() -> List[Knob]:
    return [
        Knob("page_size", "serving/page_size", [8, 16, 32]),
        Knob("prefill_chunk_tokens",
             "serving/scheduler/prefill_chunk_tokens", [32, 64, 128, 256]),
        Knob("num_draft_tokens",
             "serving/scheduler/speculative/num_draft_tokens", [0, 2, 4]),
        Knob("queue_high_watermark", "serving/queue_high_watermark",
             [0.6, 0.8, 0.9]),
        Knob("queue_low_watermark", "serving/queue_low_watermark",
             [0.3, 0.5]),
        Knob("prefill_replicas", "serving/fleet/roles/prefill_replicas",
             [1, 2]),
        Knob("decode_replicas", "serving/fleet/roles/decode_replicas",
             [1, 2, 3]),
    ]


class KnobSpace:

    def __init__(self, knobs: Sequence[Knob]):
        names = [k.name for k in knobs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate knob names in {names}")
        self.knobs = list(knobs)

    def __len__(self):
        return len(self.knobs)

    def size(self) -> int:
        n = 1
        for k in self.knobs:
            n *= len(k.values)
        return n

    def grid(self) -> Iterator[Dict[str, Any]]:
        """Enumerate every point as ``{knob name: value}`` in a stable
        order (first knob varies slowest)."""
        for combo in itertools.product(*(k.values for k in self.knobs)):
            yield dict(zip((k.name for k in self.knobs), combo))

    def fragment_for(self, point: Dict[str, Any]) -> Dict[str, Any]:
        """The ds-config fragment for one grid point.  Model knobs land
        under ``autotuning_model_overrides`` — the key the trial workers
        and ``initialize()`` already surface to model construction."""
        frag: Dict[str, Any] = {}
        by_name = {k.name: k for k in self.knobs}
        for name, value in point.items():
            knob = by_name[name]
            if knob.kind == "model":
                frag = set_ds_path(
                    frag, f"autotuning_model_overrides/{knob.path}", value)
            else:
                frag = set_ds_path(frag, knob.path, value)
        return frag

    @classmethod
    def from_config(cls, spec: Optional[Dict[str, Any]],
                    domain: Optional[str] = None) -> "KnobSpace":
        """Build a space from the ``autotuning.knobs`` config block:
        ``{name: {"path": …, "values": […], "domain": …, "kind": …}}`` or
        ``{name: [values]}`` (path defaults to the name).  With no block,
        the default space for ``domain`` (both domains when None)."""
        if not spec:
            knobs = []
            if domain in (None, "training"):
                knobs += default_training_knobs()
            if domain in (None, "serving"):
                knobs += default_serving_knobs()
            return cls(knobs)
        knobs = []
        for name, v in spec.items():
            if isinstance(v, dict):
                knobs.append(Knob(
                    name, v.get("path", name), v.get("values", []),
                    domain=v.get("domain", domain or "serving"),
                    kind=v.get("kind", "ds")))
            else:
                knobs.append(Knob(name, name, list(v),
                                  domain=domain or "serving"))
        return cls(knobs)
