"""Config overlays: how tuned configurations persist and apply.

The control plane does not write a new config file — a winner persists as
an *overlay*: a ds-config fragment that is deep-merged over the user's
config at ``deepspeed.initialize()`` / ``create_serving_engine()`` time,
provenance-stamped with the winning trial id and a hash of the telemetry
snapshot that scored it.  The user config stays the source of truth; the
overlay is an auditable, revocable diff on top of it, and
``scripts/check_telemetry_schema.py --tune`` validates the persisted file.

Payload shape (frozen — the checker's ``validate_overlay_payload`` is the
twin)::

    {"overlay":    {<ds-config fragment>},
     "provenance": {"trial": "tune-3", "snapshot_hash": "sha256:…",
                    "objective": 12.4, "ts": 1754…, "knobs": {…}}}
"""

import copy
import hashlib
import json
import os
import time
from typing import Any, Dict, Optional, Tuple

from deepspeed_tpu.utils.logging import logger

OVERLAY_BASENAME = "overlay.json"


def deep_merge(base: Dict[str, Any],
               overlay: Dict[str, Any]) -> Dict[str, Any]:
    """Merge ``overlay`` over ``base``: dicts recurse, everything else
    (scalars, lists) is replaced by the overlay value.  Neither input is
    mutated."""
    merged = copy.deepcopy(base)
    for k, v in overlay.items():
        if isinstance(v, dict) and isinstance(merged.get(k), dict):
            merged[k] = deep_merge(merged[k], v)
        else:
            merged[k] = copy.deepcopy(v)
    return merged


def snapshot_hash(snapshot: Dict[str, Any]) -> str:
    """Content hash of a ``Telemetry.snapshot()`` — canonical-JSON sha256,
    so the overlay's provenance pins the exact measurements that won."""
    blob = json.dumps(snapshot, sort_keys=True, default=str)
    return "sha256:" + hashlib.sha256(blob.encode()).hexdigest()


def make_overlay(fragment: Dict[str, Any], trial: str,
                 snapshot: Dict[str, Any], objective: float,
                 knobs: Dict[str, Any]) -> Dict[str, Any]:
    return {
        "overlay": fragment,
        "provenance": {
            "trial": trial,
            "snapshot_hash": snapshot_hash(snapshot),
            "objective": float(objective),
            "ts": round(time.time(), 6),
            "knobs": dict(knobs),
        },
    }


def write_overlay(path: str, payload: Dict[str, Any]) -> str:
    """Atomically persist an overlay payload (tmp + rename, so a reader
    never sees a torn file)."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1, default=str, sort_keys=True)
    os.replace(tmp, path)
    return path


def load_overlay(path: str) -> Optional[Dict[str, Any]]:
    """Load an overlay payload; ``None`` (with a warning) when the file is
    missing or malformed — a broken overlay must never take the job down,
    the user config alone is always a valid fallback."""
    try:
        with open(path) as f:
            payload = json.load(f)
    except FileNotFoundError:
        logger.warning(f"autotuning: overlay {path} not found; "
                       "running with the base config")
        return None
    except ValueError as e:
        logger.warning(f"autotuning: overlay {path} is not valid JSON "
                       f"({e}); running with the base config")
        return None
    if not isinstance(payload, dict) or \
            not isinstance(payload.get("overlay"), dict):
        logger.warning(f"autotuning: overlay {path} has no 'overlay' "
                       "fragment; running with the base config")
        return None
    return payload


def apply_overlay(config: Dict[str, Any],
                  payload: Dict[str, Any]) -> Dict[str, Any]:
    """Deep-merge a loaded overlay payload's fragment over ``config``."""
    return deep_merge(config, payload.get("overlay", {}))


def maybe_apply_overlay(param_dict: Dict[str, Any],
                        overlay_path: Optional[str] = None) \
        -> Tuple[Dict[str, Any], Optional[Dict[str, Any]]]:
    """The initialize()/create_serving_engine() hook: when
    ``autotuning.overlay_path`` names a persisted overlay (or
    ``overlay_path`` is passed explicitly), deep-merge it over
    ``param_dict``.  Returns ``(merged_config, provenance_or_None)``;
    the input dict is never mutated."""
    if overlay_path is None:
        at = param_dict.get("autotuning")
        if isinstance(at, dict):
            overlay_path = at.get("overlay_path")
    if not overlay_path:
        return param_dict, None
    payload = load_overlay(overlay_path)
    if payload is None:
        return param_dict, None
    prov = payload.get("provenance")
    merged = apply_overlay(param_dict, payload)
    if isinstance(prov, dict):
        logger.info(
            f"autotuning: applied overlay {overlay_path} "
            f"(trial={prov.get('trial')}, "
            f"snapshot={str(prov.get('snapshot_hash'))[:19]}…)")
    return merged, prov if isinstance(prov, dict) else None
