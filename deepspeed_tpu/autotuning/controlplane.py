"""Closed-loop autotuner: the observability planes turned into a control
plane.

The legacy :class:`~deepspeed_tpu.autotuning.autotuner.Autotuner`
re-enters the launcher and ranks trials on wall-clock throughput alone.
This driver instead sweeps a declared :class:`~deepspeed_tpu.autotuning
.knobs.KnobSpace`, prunes infeasible points *before* spending a trial
(the ZeRO memory model plus the measured ``mem/<span>/peak_bytes``
gauges), and scores every surviving trial from the
``Telemetry.snapshot()`` taken at trial end — SLO histograms, roofline
fractions, attainment counters — through a weighted
:class:`~deepspeed_tpu.autotuning.objective.Objective`.

Trials execute through the SAME journaled trial runner as the legacy
tuner (``ResourceManager.run_one``), so crash/resume and skip-finished
semantics are shared.  Every trial appends ``{run: "tune-<id>", bench,
metric, value}`` rows to the perf ledger so ``scripts/ds_perf_diff.py``
can gate the tuned config against the untuned baseline, and the winner
persists as a provenance-stamped config overlay
(:mod:`~deepspeed_tpu.autotuning.overlay`) consumed at
``deepspeed.initialize()`` / ``create_serving_engine()`` time.

The control plane speaks a FROZEN ``tune/*`` event vocabulary
(:data:`TUNE_EVENTS`) through the telemetry layer; the schema checker
(``scripts/check_telemetry_schema.py``) carries the byte-identical twin
and a tier-1 test diffs the two.
"""

import json
import os
import time
from typing import Any, Callable, Dict, List, Optional

from deepspeed_tpu.autotuning.autotuner import (gather_buffer_bytes,
                                                model_memory_per_chip)
from deepspeed_tpu.autotuning.knobs import KnobSpace
from deepspeed_tpu.autotuning.objective import Objective
from deepspeed_tpu.autotuning.overlay import (OVERLAY_BASENAME, deep_merge,
                                              snapshot_hash, write_overlay)
from deepspeed_tpu.autotuning.scheduler import Experiment, ResourceManager
from deepspeed_tpu.monitor.telemetry import JsonlEventSink, Telemetry
from deepspeed_tpu.utils.logging import logger

# FROZEN vocabulary of tune-kind event names — must stay byte-identical
# to ``TUNE_EVENTS`` in scripts/check_telemetry_schema.py (the tier-1
# test diffs the two).
TUNE_EVENTS = (
    "tune/trial_start", "tune/trial_result", "tune/trial_pruned",
    "tune/overlay_written",
)


def _fresh_telemetry(out_dir: Optional[str] = None) -> Telemetry:
    """An enabled process-local Telemetry.  With ``out_dir`` it owns a
    JSONL sink there; without, it is registry-only (emit() no-ops) — the
    cheap per-trial measurement surface."""
    tel = Telemetry()
    tel.enabled = True
    if out_dir:
        tel.sink = JsonlEventSink(out_dir)
    return tel


class ControlPlane:
    """Search driver over a declared knob space.

    ``trial_fn(trial_config, telemetry) -> extra_metrics_or_None`` is the
    workload harness: it builds/runs the trial under ``trial_config``
    (the base config deep-merged with the point's fragment), records into
    the *fresh per-trial* ``telemetry`` it is handed, and may return
    directly-measured extras (e.g. ``{"tokens_per_sec": …}``).  Scoring
    happens here, from the snapshot — never inside the harness.
    """

    def __init__(self, base_config: Optional[Dict[str, Any]] = None,
                 knob_space: Optional[KnobSpace] = None,
                 objective: Optional[Objective] = None,
                 results_dir: str = "autotuning_results",
                 telemetry: Optional[Telemetry] = None,
                 hbm_bytes: Optional[int] = None,
                 model_num_params: Optional[int] = None,
                 model_num_layers: Optional[int] = None,
                 baseline_snapshot: Optional[Dict[str, Any]] = None,
                 ledger_path: Optional[str] = None,
                 bench: str = "autotune",
                 overlay_path: Optional[str] = None,
                 overwrite: bool = False,
                 max_trials: Optional[int] = None):
        self.base_config = dict(base_config or {})
        at = self.base_config.get("autotuning") or {}
        self.space = knob_space if knob_space is not None else \
            KnobSpace.from_config(at.get("knobs"), domain=at.get("domain"))
        self.objective = objective if objective is not None else \
            Objective.from_config(at.get("objective"))
        self.results_dir = results_dir
        # the control plane's own event stream (tune/* events) lands
        # under results_dir so the --tune gate can validate it alongside
        # the trial journals and the overlay
        self.telemetry = telemetry if telemetry is not None else \
            _fresh_telemetry(results_dir)
        self.hbm_bytes = hbm_bytes
        self.model_num_params = model_num_params
        self.model_num_layers = model_num_layers
        self.baseline_snapshot = baseline_snapshot
        self.ledger_path = ledger_path
        self.bench = bench
        self.overlay_path = overlay_path or at.get("overlay_path") or \
            os.path.join(results_dir, OVERLAY_BASENAME)
        self.max_trials = max_trials if max_trials is not None else \
            at.get("max_trials")
        # trials rank on the snapshot-scored objective, THROUGH the
        # legacy tuner's journaled runner (shared crash/resume semantics)
        self.rm = ResourceManager(results_dir, metric="objective",
                                  overwrite=overwrite)
        self.trials: List[Dict[str, Any]] = []
        self.pruned: List[Dict[str, Any]] = []
        self.ledger_rows_written = 0

    # -- feasibility pruning -------------------------------------------
    def _observed_peak_bytes(self) -> Optional[float]:
        """Worst measured ``mem/<span>/peak_bytes`` across spans in the
        baseline snapshot — the activation/runtime residual the analytic
        state model can't predict."""
        snap = self.baseline_snapshot
        if not snap:
            return None
        peaks = [g.get("peak", g.get("value"))
                 for name, g in snap.get("gauges", {}).items()
                 if name.startswith("mem/") and
                 name.endswith("/peak_bytes") and isinstance(g, dict)]
        peaks = [p for p in peaks if isinstance(p, (int, float))]
        return max(peaks) if peaks else None

    def prune_reason(self, trial_cfg: Dict[str, Any]) -> Optional[str]:
        """None when the point is feasible; otherwise a short reason.

        * serving: the paged allocator requires ``num_draft_tokens + 1``
          slots per page, so a draft length >= page size can never run;
        * training: analytic ZeRO state bytes
          (:func:`model_memory_per_chip`) plus the baseline snapshot's
          measured ``mem/<span>/peak_bytes`` must fit ``hbm_bytes``;
        * overlap: the gather pipeline's ``prefetch_depth + 1``
          per-layer buffers (:func:`gather_buffer_bytes`) are priced
          into the same budget — a depth whose double-buffered working
          sets don't fit is pruned before execution, like the other
          ZeRO-memory-model knobs (needs ``model_num_layers``).
        """
        serving = trial_cfg.get("serving") or {}
        page = serving.get("page_size")
        spec = (serving.get("scheduler") or {}).get("speculative") or {}
        draft = spec.get("num_draft_tokens")
        if isinstance(page, int) and isinstance(draft, int) and \
                draft + 1 > page:
            return f"draft_exceeds_page (draft={draft}, page={page})"
        mem = trial_cfg.get("memory") or {}
        placement = mem.get("placement_policy")
        if placement == "nvme" and not mem.get("nvme_dir"):
            return ("nvme_placement_no_dir (memory.placement_policy="
                    "'nvme' needs memory.nvme_dir)")
        if placement == "host" and self.model_num_params:
            # tiered host state is fp32 master + 2 Adam moments (16 B per
            # param with grads); a budget it cannot fit needs the NVMe
            # spill tier behind it
            budget = int(mem.get("host_budget_bytes") or 0)
            state_bytes = 16 * int(self.model_num_params)
            if budget and state_bytes > budget and not mem.get("nvme_dir"):
                return (f"host_budget (tiered state {state_bytes} > "
                        f"host budget {budget}, no nvme spill dir)")
        if self.hbm_bytes and self.model_num_params:
            zero = trial_cfg.get("zero_optimization") or {}
            stage = int(zero.get("stage", 0))
            dp = max(1, int(trial_cfg.get("dp", 1)))
            # a host/nvme tier placement moves optimizer state off the
            # chip exactly like offload_optimizer for the HBM model
            offload = bool(zero.get("offload_optimizer")) or \
                placement in ("host", "nvme")
            est = model_memory_per_chip(self.model_num_params, stage, dp,
                                        offload_optimizer=offload)
            observed = self._observed_peak_bytes()
            if observed:
                est += int(observed)
            overlap = zero.get("overlap") or {}
            buffers = 0
            depth = int(overlap.get("gather_prefetch_depth", 1) or 1)
            if overlap.get("enabled") and stage >= 3 and \
                    self.model_num_layers:
                buffers = gather_buffer_bytes(
                    self.model_num_params, self.model_num_layers, depth)
            if est + buffers > self.hbm_bytes:
                if buffers and est <= self.hbm_bytes:
                    return (f"overlap_depth_hbm (gather buffers {buffers} "
                            f"push {est} over hbm {self.hbm_bytes}, "
                            f"depth={depth})")
                return (f"zero_mem_model ({est + buffers} > hbm "
                        f"{self.hbm_bytes}, stage={stage})")
        return None

    # -- ledger --------------------------------------------------------
    def _append_ledger(self, run: str, metrics: Dict[str, float]):
        if not self.ledger_path:
            return
        ts = round(time.time(), 6)
        try:
            with open(self.ledger_path, "a") as f:
                for metric, value in sorted(metrics.items()):
                    f.write(json.dumps(
                        {"ts": ts, "run": run, "bench": self.bench,
                         "metric": metric, "value": float(value)}) + "\n")
                    self.ledger_rows_written += 1
        except OSError as e:  # the ledger is best-effort, never fatal
            logger.warning(f"autotuning: ledger append failed: {e}")

    # -- the sweep -----------------------------------------------------
    def tune(self, trial_fn: Callable[[Dict[str, Any], Telemetry],
                                      Optional[Dict[str, float]]]) \
            -> Dict[str, Any]:
        """Sweep the knob space, score each surviving trial from its
        end-of-trial snapshot, persist the winning overlay.  Returns a
        summary dict (``best``/``overlay_path``/``trials``/``pruned``)."""
        tel = self.telemetry
        experiments: List[Experiment] = []
        points: Dict[str, Dict[str, Any]] = {}
        fragments: Dict[str, Dict[str, Any]] = {}
        n = 0
        for point in self.space.grid():
            if self.max_trials is not None and n >= int(self.max_trials):
                logger.info(
                    f"autotuning: max_trials={self.max_trials} reached; "
                    f"remaining grid points not searched")
                break
            trial_id = f"tune-{n:04d}"
            n += 1
            fragment = self.space.fragment_for(point)
            trial_cfg = deep_merge(self.base_config, fragment)
            trial_cfg.pop("autotuning", None)
            reason = self.prune_reason(trial_cfg)
            if reason is not None:
                self.pruned.append({"trial": trial_id, "knobs": point,
                                    "reason": reason})
                tel.tune("tune/trial_pruned",
                         attrs={"trial": trial_id, "reason": reason,
                                "knobs": json.dumps(point, default=str)})
                continue
            overrides = trial_cfg.pop("autotuning_model_overrides", None)
            exp = Experiment(trial_id, trial_cfg, model_overrides=overrides)
            experiments.append(exp)
            points[trial_id] = point
            fragments[trial_id] = fragment
        self.rm.schedule_experiments(experiments)

        for exp in experiments:
            point = points[exp.name]
            tel.tune("tune/trial_start",
                     attrs={"trial": exp.name,
                            "knobs": json.dumps(point, default=str)})

            def run_fn(e: Experiment) -> Dict[str, Any]:
                trial_tel = _fresh_telemetry()
                cfg = deep_merge(e.ds_config, {} if not e.model_overrides
                                 else {"autotuning_model_overrides":
                                       dict(e.model_overrides)})
                extra = trial_fn(cfg, trial_tel) or {}
                snap = trial_tel.snapshot()
                vec = self.objective.metrics(snap, extra)
                return {"objective": self.objective.score(vec),
                        "metrics": vec,
                        "snapshot_hash": snapshot_hash(snap)}

            result = self.rm.run_one(exp, run_fn)
            vec = result.get("metrics") or {}
            score = float(result.get("objective", 0.0))
            row = {"trial": exp.name, "knobs": point, "objective": score,
                   "metrics": vec, "error": result.get("error"),
                   "wall_s": result.get("wall_s")}
            self.trials.append(row)
            self._append_ledger(exp.name, dict(vec, objective=score))
            tel.tune("tune/trial_result",
                     attrs={"trial": exp.name, "objective": score,
                            "snapshot_hash":
                                result.get("snapshot_hash", ""),
                            "metrics": json.dumps(vec, default=str)})

        best = self.rm.best_experiment()
        summary: Dict[str, Any] = {
            "trials": len(self.trials), "pruned": len(self.pruned),
            "ledger_rows": self.ledger_rows_written, "best": None,
            "overlay_path": None,
        }
        if best is None:
            logger.warning("autotuning: no successful trials; "
                           "no overlay written")
            return summary
        payload = {
            "overlay": fragments[best.name],
            "provenance": {
                "trial": best.name,
                "snapshot_hash": best.result.get("snapshot_hash",
                                                 "sha256:unjournaled"),
                "objective": float(best.result.get("objective", 0.0)),
                "ts": round(time.time(), 6),
                "knobs": dict(points[best.name]),
            },
        }
        write_overlay(self.overlay_path, payload)
        tel.tune("tune/overlay_written",
                 attrs={"trial": best.name, "path": self.overlay_path,
                        "snapshot_hash":
                            payload["provenance"]["snapshot_hash"]})
        summary["best"] = {"trial": best.name, "knobs": points[best.name],
                           "objective": payload["provenance"]["objective"]}
        summary["overlay_path"] = self.overlay_path
        return summary
