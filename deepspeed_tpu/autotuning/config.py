"""Autotuning config.

Parity: reference ``autotuning/config.py`` (``DeepSpeedAutotuningConfig``) —
keys keep reference spellings (enabled, fast, metric, start/end profile
steps, tuner type, early stopping, results/exps dirs).
"""

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel

AUTOTUNING = "autotuning"
AUTOTUNING_METRIC_THROUGHPUT = "throughput"
AUTOTUNING_METRIC_LATENCY = "latency"
AUTOTUNING_METRIC_FLOPS = "flops"

GRIDSEARCH = "gridsearch"
RANDOM = "random"
MODEL_BASED = "model_based"


class AutotuningConfig(DeepSpeedConfigModel):
    enabled = False
    fast = True
    results_dir = "autotuning_results"
    exps_dir = "autotuning_exps"
    overwrite = True
    start_profile_step = 3
    end_profile_step = 5
    metric = AUTOTUNING_METRIC_THROUGHPUT
    model_info = None
    tuner_type = GRIDSEARCH
    tuner_early_stopping = 5
    tuner_num_trials = 50
    arg_mappings = None
    max_train_batch_size = None
    min_train_batch_size = 1
    max_train_micro_batch_size_per_gpu = 1024
    min_train_micro_batch_size_per_gpu = 1
    num_tuning_micro_batch_sizes = 3
    mp_size = 1
    # phase-2 coordinate descent over per-stage template knobs (gas,
    # offload device, remat policy, attention tile sizes — reference
    # config_templates/); False = stage×micro-batch only
    template_tuning = True
    # launcher-driven tuning: a serialisable trial model
    # {"kind": "causal_lm", "config": {...TransformerConfig kwargs}}
    model_spec = None
    # ---- autotuning-v2 (closed-loop control plane) -------------------
    # declared knob space: {name: {"path": "a/b/c", "values": [...],
    # "domain": "training"|"serving", "kind": "ds"|"model"}} or
    # {name: [values]}; None = the built-in default space for `domain`
    knobs = None
    # knob domain the default space covers ("training" | "serving";
    # None = both)
    domain = None
    # objective weights {metric: weight} over the snapshot-scored metric
    # vector (negative = lower is better); None = Objective defaults
    objective = None
    # where the winning overlay persists, and where initialize() /
    # create_serving_engine() look for one to deep-merge over the user
    # config; None = <results_dir>/overlay.json when tuning, no overlay
    # applied when consuming
    overlay_path = None
    # cap on searched grid points (None = the full grid)
    max_trials = None
