"""The autotuner.

Parity: reference ``autotuning/autotuner.py:39`` (``Autotuner``: profile the
model (``:707`` model-info run), prune ZeRO stages by a memory model, tune
micro-batch size per stage from measured short runs, write
``autotuning_results/`` and report the best config; entered from the
launcher ``runner.py:351``).

TPU design: phase 1 searches (zero stage × micro-batch size); phase 2
runs coordinate descent over the winning stage's template knobs
(``config_templates.py``: gradient-accumulation steps, optimizer offload
device, remat policy, Pallas attention tile sizes — the knobs round-2's
hand tuning actually moved).  Memory feasibility uses the ZeRO memory
model (params/grads/optimizer bytes per chip given the fsdp degree)
against the accelerator's reported HBM, seeded by the phase-1 winner's
measured ``n_params`` (the reference's model-info run); each trial builds
a real engine and measures steady-state samples/sec over
``end_profile_step - start_profile_step`` fused steps.
"""

import itertools
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np

from deepspeed_tpu.autotuning.config import (AUTOTUNING,
                                             AUTOTUNING_METRIC_THROUGHPUT,
                                             AutotuningConfig)
from deepspeed_tpu.autotuning.scheduler import Experiment, ResourceManager
from deepspeed_tpu.utils.logging import logger

BYTES_PER_PARAM_BF16 = 2
# Adam: fp32 master + m + v
BYTES_OPTIM_PER_PARAM = 12
BYTES_GRAD_PER_PARAM = 4


def model_memory_per_chip(num_params: int, stage: int, dp: int,
                          offload_optimizer: bool = False) -> int:
    """ZeRO memory model (reference ``autotuner.py`` stage pruning):
    bytes/chip of params + grads + optimizer states."""
    p = num_params * BYTES_PER_PARAM_BF16
    g = num_params * BYTES_GRAD_PER_PARAM
    o = 0 if offload_optimizer else num_params * BYTES_OPTIM_PER_PARAM
    if stage >= 3:
        p //= dp
    if stage >= 2:
        g //= dp
    if stage >= 1:
        o //= dp
    return p + g + o


def gather_buffer_bytes(num_params: int, n_layers: int,
                        prefetch_depth: int) -> int:
    """HBM cost of the ``zero_optimization.overlap`` gather pipeline:
    ``prefetch_depth + 1`` per-layer gathered (UNsharded) working sets
    ride the scan carry, so deeper prefetch buys overlap with layer-sized
    slabs of HBM.  The per-layer size is the stacked model's params
    spread evenly over its layers — the right scale for the transformer
    stacks ``layer_scan`` pipelines."""
    per_layer = (int(num_params) // max(1, int(n_layers))) \
        * BYTES_PER_PARAM_BF16
    return (int(prefetch_depth) + 1) * per_layer


class Autotuner:

    def __init__(self, ds_config: Dict[str, Any],
                 model_num_params: Optional[int] = None,
                 hbm_bytes: Optional[int] = None,
                 active_resources: Optional[Dict[str, Any]] = None):
        if not isinstance(ds_config, dict):
            # launcher entry (runner.py): an argparse Namespace carrying
            # --deepspeed_config; reference Autotuner(args, resource_pool)
            path = getattr(ds_config, "deepspeed_config", None) or \
                getattr(ds_config, "ds_config", None)
            if isinstance(path, str):
                with open(path) as f:
                    ds_config = json.load(f)
            elif isinstance(path, dict):
                ds_config = path
            else:
                raise ValueError(
                    "Autotuner needs a ds_config dict or an args namespace "
                    "with --deepspeed_config")
        self.active_resources = active_resources
        self.base_config = {k: v for k, v in ds_config.items()
                            if k != AUTOTUNING}
        self.at_config = AutotuningConfig(ds_config.get(AUTOTUNING, {}))
        self.model_num_params = model_num_params
        if hbm_bytes is None:
            try:
                from deepspeed_tpu.accelerator import get_accelerator
                hbm_bytes = get_accelerator().total_memory()
            except Exception:
                hbm_bytes = 16 << 30
        self.hbm_bytes = hbm_bytes
        self.rm = ResourceManager(self.at_config.results_dir,
                                  metric=self.at_config.metric,
                                  overwrite=self.at_config.overwrite)
        # set by tune(): path of the persisted best config (reference
        # ds_config_optimal.json; consumed by `deepspeed --autotuning run`)
        self.optimal_config_path: Optional[str] = None

    # ------------------------------------------------------------------
    def feasible_stages(self, dp: int) -> List[int]:
        if self.model_num_params is None:
            return [0, 1, 2, 3]
        stages = [s for s in (0, 1, 2, 3)
                  if model_memory_per_chip(self.model_num_params, s, dp)
                  < self.hbm_bytes * 0.9]
        # always consider the most-sharded stage even if the model says no
        # (offload may rescue it)
        return stages or [3]

    def candidate_micro_batches(self) -> List[int]:
        at = self.at_config
        out, m = [], max(1, at.min_train_micro_batch_size_per_gpu)
        while m <= at.max_train_micro_batch_size_per_gpu and \
                len(out) < at.num_tuning_micro_batch_sizes:
            out.append(m)
            m *= 2
        return out

    def tuning_space(self, dp: int) -> List[Dict[str, Any]]:
        space = []
        for stage, micro in itertools.product(self.feasible_stages(dp),
                                              self.candidate_micro_batches()):
            cfg = dict(self.base_config)
            zo = dict(cfg.get("zero_optimization", {}))
            zo["stage"] = stage
            cfg["zero_optimization"] = zo
            cfg["train_micro_batch_size_per_gpu"] = micro
            cfg.pop("train_batch_size", None)
            space.append(cfg)
        return space

    # ------------------------------------------------------------------
    def _default_runner(self, make_batch: Callable[[int], Any],
                        model, params) -> Callable[[Experiment], Dict]:
        at = self.at_config

        def run(exp: Experiment) -> Dict[str, Any]:
            import deepspeed_tpu
            from deepspeed_tpu.autotuning.trial_worker import timed_trial
            from deepspeed_tpu.parallel import groups
            groups.reset_mesh()
            engine, *_ = deepspeed_tpu.initialize(
                model=model,
                model_parameters=jax.tree_util.tree_map(np.asarray, params),
                config=exp.ds_config)
            gas = engine.gradient_accumulation_steps_

            def batch():
                b = make_batch(engine.train_batch_size())
                if gas > 1:   # fused GAS steps consume [gas, micro*dp, ...]
                    b = jax.tree_util.tree_map(
                        lambda x: np.asarray(x).reshape(
                            (gas, -1) + np.shape(x)[1:]), b)
                return b
            return timed_trial(engine, batch,
                               at.start_profile_step, at.end_profile_step)
        return run

    def _subprocess_runner(self, model_spec: Dict[str, Any], seq: int,
                           timeout: float = 900.0,
                           cpu: bool = False) -> Callable[[Experiment], Dict]:
        """Each experiment as its OWN OS process (reference
        ``autotuning/scheduler.py`` ``ResourceManager.run_job``: trials are
        separate jobs, so one trial's OOM / allocator state / XLA live
        buffers cannot distort the next trial's measurement)."""
        import subprocess
        import sys

        at = self.at_config

        def run(exp: Experiment) -> Dict[str, Any]:
            spec = {"model": model_spec, "ds_config": exp.ds_config,
                    "model_overrides": exp.model_overrides,
                    "seq": seq, "cpu": cpu,
                    "start_profile_step": at.start_profile_step,
                    "end_profile_step": at.end_profile_step}
            out = subprocess.run(
                [sys.executable, "-m",
                 "deepspeed_tpu.autotuning.trial_worker", json.dumps(spec)],
                capture_output=True, text=True, timeout=timeout)
            if out.returncode != 0:
                raise RuntimeError(
                    f"trial {exp.name} failed (rc={out.returncode}): "
                    f"{(out.stderr or '')[-800:]}")
            for line in reversed(out.stdout.strip().splitlines()):
                try:
                    parsed = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(parsed, dict):   # stray scalar prints are
                    return parsed              # not trial results
            raise RuntimeError(f"trial {exp.name}: no JSON in worker output")
        return run

    def tune(self, model=None, params=None,
             make_batch: Optional[Callable[[int], Any]] = None,
             run_fn: Optional[Callable[[Experiment], Dict]] = None,
             model_spec: Optional[Dict[str, Any]] = None,
             seq: int = 256, trial_timeout: float = 900.0,
             trial_cpu: bool = False) -> Dict[str, Any]:
        """Run the search; returns the best ds_config.

        Three trial modes, most isolated first:
        * ``model_spec=`` — each trial in a fresh OS process (the
          reference's separate-job semantics; required for trustworthy
          OOM boundaries);
        * ``model=/params=/make_batch=`` — in-process trials (arbitrary
          non-serialisable models; measurements share one XLA heap);
        * ``run_fn=`` — caller-supplied runner.
        """
        if model_spec is None and run_fn is None and model is None:
            # launcher-driven tuning: the model spec rides in the
            # autotuning config ("model_spec": {"kind": ..., "config": ...}).
            # Resolved FIRST so the dp probe below sees subprocess mode.
            spec_cfg = getattr(self.at_config, "model_spec", None)
            if spec_cfg:
                model_spec = dict(spec_cfg)
        if model_spec is not None and not trial_cpu:
            # do NOT initialise the TPU backend in the parent: libtpu is
            # exclusive per process, and a parent holding the device would
            # starve every trial subprocess.  Probe the count out of line.
            import subprocess
            import sys
            try:
                out = subprocess.run(
                    [sys.executable, "-c",
                     "import jax; print(jax.device_count())"],
                    capture_output=True, text=True, timeout=180)
                dp = max(1, int(out.stdout.strip().splitlines()[-1]))
            except Exception:
                dp = 1
        else:
            dp = max(1, jax.device_count())
        # only the in-process default runner cannot apply model-knob
        # overrides (its model object is fixed); subprocess AND caller
        # run_fn modes both see exp.model_overrides
        model_knobs = True
        if run_fn is None and model_spec is not None:
            run_fn = self._subprocess_runner(model_spec, seq,
                                             timeout=trial_timeout,
                                             cpu=trial_cpu)
        if run_fn is None:
            if model is None or params is None or make_batch is None:
                raise ValueError(
                    "tune() needs model_spec=, model/params/make_batch, "
                    "run_fn=, or an autotuning.model_spec config entry")
            run_fn = self._default_runner(make_batch, model, params)
            model_knobs = False

        # ---- model info (reference autotuner.py:707) -----------------
        # seeds the memory model BEFORE the space is built, so stage
        # pruning can actually prune.  In-process: count the params pytree
        # directly (free).  Subprocess: one profiled trial at the most-
        # sharded stage (the worker reports n_params).  Caller run_fn:
        # skipped — the runner may not know the model at all.
        info_exp = None
        if self.model_num_params is None and params is not None:
            leaves = jax.tree_util.tree_leaves(params)
            self.model_num_params = int(sum(np.size(l) for l in leaves))
        if self.model_num_params is None and model_spec is not None:
            micro = self.candidate_micro_batches()[0]
            cfg = dict(self.base_config)
            cfg["zero_optimization"] = dict(
                cfg.get("zero_optimization", {}), stage=3)
            cfg["train_micro_batch_size_per_gpu"] = micro
            cfg.pop("train_batch_size", None)
            info_exp = Experiment(f"z3_mbs{micro}", cfg)
            self.rm.schedule_experiments([info_exp])
            self.rm.run(run_fn)
            if info_exp.done() and info_exp.result.get("n_params"):
                self.model_num_params = int(info_exp.result["n_params"])

        # ---- phase 1: ZeRO stage × micro-batch ------------------------
        space = self.tuning_space(dp)
        exps = []
        for c in space:
            name = (f"z{c['zero_optimization']['stage']}_"
                    f"mbs{c['train_micro_batch_size_per_gpu']}")
            if info_exp is not None and name == info_exp.name:
                # the model-info run already measured this point: it joins
                # the space instead of re-running.  (Outside the space it
                # stays a profile-only run and does NOT compete for best.)
                exps.append(info_exp)
                continue
            exps.append(Experiment(name, c))
        logger.info(f"autotuning: phase 1 — {len(exps)} experiments "
                    f"(stages×micro-batches), metric={self.at_config.metric}")
        self.rm.schedule_experiments(
            [e for e in exps if e is not info_exp])
        self.rm.run(run_fn)
        best = ResourceManager.best_of(exps, self.at_config.metric)
        assert best is not None, "no experiment finished"

        # ---- phase 2: per-stage template knobs around the winner ------
        # (reference config_templates/template_zero*.json; coordinate
        # descent — one knob at a time — keeps trials linear)
        if self.at_config.template_tuning:
            best = self._tune_templates(best, run_fn,
                                        model_knobs=model_knobs,
                                        model_spec=model_spec)
        logger.info(f"autotuning: best = {best.name} "
                    f"({self.at_config.metric}="
                    f"{best.result.get(self.at_config.metric):.2f})")
        out = dict(best.ds_config)
        if best.model_overrides:
            # surfaced so callers can apply the model-side winners too
            out["autotuning_model_overrides"] = dict(best.model_overrides)
        # persist for --autotuning run (reference ds_config_optimal.json)
        self.optimal_config_path = os.path.join(
            self.at_config.results_dir, "ds_config_optimal.json")
        with open(self.optimal_config_path, "w") as f:
            json.dump(out, f, indent=1)
        return out

    @staticmethod
    def skip_template_knob(path: str, ds_config: Dict) -> bool:
        """A template knob is skipped when every candidate would be a no-op
        re-measurement of the incumbent under a new name: moment_dtype is
        read only by the Adam family, and the param-stream dials only
        exist when the base config actually streams params (the engine
        enables param-stream at ANY stage when offload_param is set)."""
        opt_type = str((ds_config.get("optimizer") or {})
                       .get("type", "adamw")).lower()
        if path == "optimizer/params/moment_dtype" and \
                opt_type not in ("adam", "adamw"):
            return True
        if path.startswith("zero_optimization/offload_param/"):
            ps_device = str(((ds_config.get("zero_optimization") or {})
                             .get("offload_param") or {})
                            .get("device", "none"))
            if ps_device in ("none", "None"):
                return True
        return False

    def _tune_templates(self, best: Experiment, run_fn,
                        model_knobs: bool = True,
                        model_spec=None) -> Experiment:
        """Coordinate descent over the winning stage's template knobs."""
        from deepspeed_tpu.autotuning.config_templates import (
            KNOB_DEFAULTS, TEMPLATES, get_ds_path, model_overrides_for,
            set_ds_path)
        stage = int(best.ds_config.get("zero_optimization", {})
                    .get("stage", 0))
        tmpl = TEMPLATES.get(stage, {"ds": {}, "model": {}})
        spec_cfg = (model_spec or {}).get("config", {})

        def pick(best, exps):
            return ResourceManager.best_of([best] + exps,
                                           self.at_config.metric) or best

        for path, candidates in tmpl["ds"].items():
            if self.skip_template_knob(path, best.ds_config):
                continue
            exps = []
            for v in candidates:
                if v == get_ds_path(best.ds_config, path):
                    continue      # the incumbent value: already measured
                cfg = set_ds_path(best.ds_config, path, v)
                tag = (str(v).replace(" ", "").replace("'", "")
                       .replace("{", "").replace("}", "").replace(":", "-"))
                exps.append(Experiment(
                    f"{best.name}_{path.split('/')[-1]}-{tag}", cfg,
                    model_overrides=best.model_overrides))
            self.rm.schedule_experiments(exps)
            self.rm.run(run_fn)
            best = pick(best, exps)
        if model_knobs and (model_spec is None or
                            model_spec.get("kind", "causal_lm")
                            == "causal_lm"):
            # the template model knobs are TransformerConfig fields; other
            # model kinds (bert, ...) would TypeError in every trial
            for knob, candidates in tmpl["model"].items():
                exps = []
                for v in candidates:
                    delta = model_overrides_for(knob, v)
                    current = {
                        k: best.model_overrides.get(
                            k, spec_cfg.get(
                                k, model_overrides_for(
                                    knob, KNOB_DEFAULTS.get(knob)).get(k)))
                        for k in delta}
                    if delta == current:
                        continue   # effective incumbent: already measured
                    ov = dict(best.model_overrides, **delta)
                    tag = str(v).replace(" ", "").replace("(", "") \
                        .replace(")", "").replace(",", "x")
                    exps.append(Experiment(f"{best.name}_{knob}-{tag}",
                                           best.ds_config,
                                           model_overrides=ov))
                self.rm.schedule_experiments(exps)
                self.rm.run(run_fn)
                best = pick(best, exps)
        return best

    # parity aliases ----------------------------------------------------
    def run_autotuning(self, *a, **kw):
        return self.tune(*a, **kw)
