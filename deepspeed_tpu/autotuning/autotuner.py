"""The autotuner.

Parity: reference ``autotuning/autotuner.py:39`` (``Autotuner``: profile the
model (``:707`` model-info run), prune ZeRO stages by a memory model, tune
micro-batch size per stage from measured short runs, write
``autotuning_results/`` and report the best config; entered from the
launcher ``runner.py:351``).

TPU design: the tuning space is (zero stage × micro-batch size); memory
feasibility uses the ZeRO memory model (params/grads/optimizer bytes per
chip given the fsdp degree) against the accelerator's reported HBM; each
trial builds a real engine and measures steady-state samples/sec over
``end_profile_step - start_profile_step`` fused steps.
"""

import itertools
import json
import time
from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np

from deepspeed_tpu.autotuning.config import (AUTOTUNING,
                                             AUTOTUNING_METRIC_THROUGHPUT,
                                             AutotuningConfig)
from deepspeed_tpu.autotuning.scheduler import Experiment, ResourceManager
from deepspeed_tpu.utils.logging import logger

BYTES_PER_PARAM_BF16 = 2
# Adam: fp32 master + m + v
BYTES_OPTIM_PER_PARAM = 12
BYTES_GRAD_PER_PARAM = 4


def model_memory_per_chip(num_params: int, stage: int, dp: int,
                          offload_optimizer: bool = False) -> int:
    """ZeRO memory model (reference ``autotuner.py`` stage pruning):
    bytes/chip of params + grads + optimizer states."""
    p = num_params * BYTES_PER_PARAM_BF16
    g = num_params * BYTES_GRAD_PER_PARAM
    o = 0 if offload_optimizer else num_params * BYTES_OPTIM_PER_PARAM
    if stage >= 3:
        p //= dp
    if stage >= 2:
        g //= dp
    if stage >= 1:
        o //= dp
    return p + g + o


class Autotuner:

    def __init__(self, ds_config: Dict[str, Any],
                 model_num_params: Optional[int] = None,
                 hbm_bytes: Optional[int] = None):
        self.base_config = {k: v for k, v in ds_config.items()
                            if k != AUTOTUNING}
        self.at_config = AutotuningConfig(ds_config.get(AUTOTUNING, {}))
        self.model_num_params = model_num_params
        if hbm_bytes is None:
            try:
                from deepspeed_tpu.accelerator import get_accelerator
                hbm_bytes = get_accelerator().total_memory()
            except Exception:
                hbm_bytes = 16 << 30
        self.hbm_bytes = hbm_bytes
        self.rm = ResourceManager(self.at_config.results_dir,
                                  metric=self.at_config.metric,
                                  overwrite=self.at_config.overwrite)

    # ------------------------------------------------------------------
    def feasible_stages(self, dp: int) -> List[int]:
        if self.model_num_params is None:
            return [0, 1, 2, 3]
        stages = [s for s in (0, 1, 2, 3)
                  if model_memory_per_chip(self.model_num_params, s, dp)
                  < self.hbm_bytes * 0.9]
        # always consider the most-sharded stage even if the model says no
        # (offload may rescue it)
        return stages or [3]

    def candidate_micro_batches(self) -> List[int]:
        at = self.at_config
        out, m = [], max(1, at.min_train_micro_batch_size_per_gpu)
        while m <= at.max_train_micro_batch_size_per_gpu and \
                len(out) < at.num_tuning_micro_batch_sizes:
            out.append(m)
            m *= 2
        return out

    def tuning_space(self, dp: int) -> List[Dict[str, Any]]:
        space = []
        for stage, micro in itertools.product(self.feasible_stages(dp),
                                              self.candidate_micro_batches()):
            cfg = dict(self.base_config)
            zo = dict(cfg.get("zero_optimization", {}))
            zo["stage"] = stage
            cfg["zero_optimization"] = zo
            cfg["train_micro_batch_size_per_gpu"] = micro
            cfg.pop("train_batch_size", None)
            space.append(cfg)
        return space

    # ------------------------------------------------------------------
    def _default_runner(self, make_batch: Callable[[int], Any],
                        model, params) -> Callable[[Experiment], Dict]:
        at = self.at_config

        def run(exp: Experiment) -> Dict[str, Any]:
            import deepspeed_tpu
            from deepspeed_tpu.autotuning.trial_worker import timed_trial
            from deepspeed_tpu.parallel import groups
            groups.reset_mesh()
            engine, *_ = deepspeed_tpu.initialize(
                model=model,
                model_parameters=jax.tree_util.tree_map(np.asarray, params),
                config=exp.ds_config)
            return timed_trial(
                engine, lambda: make_batch(engine.train_batch_size()),
                at.start_profile_step, at.end_profile_step)
        return run

    def _subprocess_runner(self, model_spec: Dict[str, Any], seq: int,
                           timeout: float = 900.0,
                           cpu: bool = False) -> Callable[[Experiment], Dict]:
        """Each experiment as its OWN OS process (reference
        ``autotuning/scheduler.py`` ``ResourceManager.run_job``: trials are
        separate jobs, so one trial's OOM / allocator state / XLA live
        buffers cannot distort the next trial's measurement)."""
        import subprocess
        import sys

        at = self.at_config

        def run(exp: Experiment) -> Dict[str, Any]:
            spec = {"model": model_spec, "ds_config": exp.ds_config,
                    "seq": seq, "cpu": cpu,
                    "start_profile_step": at.start_profile_step,
                    "end_profile_step": at.end_profile_step}
            out = subprocess.run(
                [sys.executable, "-m",
                 "deepspeed_tpu.autotuning.trial_worker", json.dumps(spec)],
                capture_output=True, text=True, timeout=timeout)
            if out.returncode != 0:
                raise RuntimeError(
                    f"trial {exp.name} failed (rc={out.returncode}): "
                    f"{(out.stderr or '')[-800:]}")
            for line in reversed(out.stdout.strip().splitlines()):
                try:
                    parsed = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(parsed, dict):   # stray scalar prints are
                    return parsed              # not trial results
            raise RuntimeError(f"trial {exp.name}: no JSON in worker output")
        return run

    def tune(self, model=None, params=None,
             make_batch: Optional[Callable[[int], Any]] = None,
             run_fn: Optional[Callable[[Experiment], Dict]] = None,
             model_spec: Optional[Dict[str, Any]] = None,
             seq: int = 256, trial_timeout: float = 900.0,
             trial_cpu: bool = False) -> Dict[str, Any]:
        """Run the search; returns the best ds_config.

        Three trial modes, most isolated first:
        * ``model_spec=`` — each trial in a fresh OS process (the
          reference's separate-job semantics; required for trustworthy
          OOM boundaries);
        * ``model=/params=/make_batch=`` — in-process trials (arbitrary
          non-serialisable models; measurements share one XLA heap);
        * ``run_fn=`` — caller-supplied runner.
        """
        if model_spec is not None and not trial_cpu:
            # do NOT initialise the TPU backend in the parent: libtpu is
            # exclusive per process, and a parent holding the device would
            # starve every trial subprocess.  Probe the count out of line.
            import subprocess
            import sys
            try:
                out = subprocess.run(
                    [sys.executable, "-c",
                     "import jax; print(jax.device_count())"],
                    capture_output=True, text=True, timeout=180)
                dp = max(1, int(out.stdout.strip().splitlines()[-1]))
            except Exception:
                dp = 1
        else:
            dp = max(1, jax.device_count())
        space = self.tuning_space(dp)
        exps = [Experiment(
            f"z{c['zero_optimization']['stage']}_"
            f"mbs{c['train_micro_batch_size_per_gpu']}", c) for c in space]
        logger.info(f"autotuning: {len(exps)} experiments "
                    f"(stages×micro-batches), metric={self.at_config.metric}")
        self.rm.schedule_experiments(exps)
        if run_fn is None and model_spec is not None:
            run_fn = self._subprocess_runner(model_spec, seq,
                                             timeout=trial_timeout,
                                             cpu=trial_cpu)
        if run_fn is None:
            assert model is not None and params is not None and \
                make_batch is not None, \
                "tune() needs model_spec, model/params/make_batch, or run_fn"
            run_fn = self._default_runner(make_batch, model, params)
        self.rm.run(run_fn)
        best = self.rm.best_experiment()
        assert best is not None, "no experiment finished"
        logger.info(f"autotuning: best = {best.name} "
                    f"({self.at_config.metric}="
                    f"{best.result.get(self.at_config.metric):.2f})")
        return best.ds_config

    # parity aliases ----------------------------------------------------
    def run_autotuning(self, *a, **kw):
        return self.tune(*a, **kw)
