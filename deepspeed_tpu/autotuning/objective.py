"""Snapshot-scored objectives: trials are ranked on what the telemetry
registry measured, not on wall-clock alone.

``extract_metrics`` pulls the ledger-able metric vector out of one
``Telemetry.snapshot()`` — latency percentiles from the ``serve/*_ms``
histograms, SLO attainment from the serve counters, roofline fractions
from the profiling gauges, HBM peaks from the ``mem/<span>/peak_bytes``
family.  ``Objective`` then collapses a metric vector to one score as a
weighted sum: positive weight = higher is better (tokens/s, attainment,
compute fraction), negative weight = lower is better (millisecond
percentiles, peak bytes).  Two trials with identical wall-clock but
different SLO histograms therefore score differently — the property the
acceptance test pins.
"""

from typing import Any, Callable, Dict, Optional

_HIST = "histograms"
_CTR = "counters"
_GAUGE = "gauges"


def _hist_pct(name: str, pct: str) -> Callable[[Dict[str, Any]], Any]:
    def get(snap):
        h = snap.get(_HIST, {}).get(name)
        return None if not h or not h.get("count") else h.get(pct)
    return get


def _slo_attainment(snap: Dict[str, Any]) -> Optional[float]:
    ctrs = snap.get(_CTR, {})
    ok = ctrs.get("serve/slo_attained", 0)
    miss = ctrs.get("serve/slo_missed", 0)
    total = ok + miss
    return None if total == 0 else ok / total


def _gauge_family_max(prefix: str, suffix: str, field: str = "value"):
    """Max over the per-span gauge family ``<prefix><span>/<suffix>`` —
    e.g. the worst ``mem/<span>/peak_bytes`` peak across spans."""
    def get(snap):
        vals = [g.get(field) for name, g in snap.get(_GAUGE, {}).items()
                if name.startswith(prefix) and name.endswith("/" + suffix)
                and isinstance(g, dict) and g.get(field) is not None]
        return max(vals) if vals else None
    return get


# The frozen metric vector: every extractor returns None when the
# snapshot has no signal for it (metric simply absent from the trial's
# vector — the objective skips it rather than inventing a zero).
SNAPSHOT_METRICS: Dict[str, Callable[[Dict[str, Any]], Any]] = {
    "ttft_p50_ms": _hist_pct("serve/ttft_ms", "p50"),
    "ttft_p99_ms": _hist_pct("serve/ttft_ms", "p99"),
    "tpot_p50_ms": _hist_pct("serve/tpot_ms", "p50"),
    "tpot_p99_ms": _hist_pct("serve/tpot_ms", "p99"),
    "e2e_p99_ms": _hist_pct("serve/e2e_ms", "p99"),
    "queue_wait_p99_ms": _hist_pct("serve/queue_wait_ms", "p99"),
    "slo_attainment_frac": _slo_attainment,
    "goodput_tokens":
        lambda s: s.get(_CTR, {}).get("serve/goodput_tokens") or None,
    "roofline_compute_frac":
        _gauge_family_max("roofline/", "compute_frac"),
    "roofline_bandwidth_frac":
        _gauge_family_max("roofline/", "bandwidth_frac"),
    "mem_peak_bytes": _gauge_family_max("mem/", "peak_bytes", field="peak"),
    # attribution plane (monitor/attribution.py): the step fraction spent
    # in collectives NOT hidden behind compute — the number overlap work
    # exists to drive down, so trials that trade it away score better
    "exposed_comm_frac":
        lambda s: (s.get(_GAUGE, {})
                   .get("step/attr/exposed_comm_frac") or {}).get("value"),
}


def extract_metrics(snapshot: Dict[str, Any]) -> Dict[str, float]:
    """The ledger-able metric vector present in one registry snapshot."""
    out = {}
    for name, get in SNAPSHOT_METRICS.items():
        v = get(snapshot)
        if v is not None:
            out[name] = float(v)
    return out


class Objective:
    """Weighted scalarization of a metric vector.  ``weights`` maps
    metric name → weight; metrics absent from a trial's vector contribute
    nothing (so a training trial isn't penalized for having no TTFT
    histogram).  The defaults reward throughput and SLO attainment and
    charge for tail latency — per-unit magnitudes chosen so one token/s
    trades against ~10 ms of p99 tail."""

    DEFAULT_WEIGHTS: Dict[str, float] = {
        "tokens_per_sec": 1.0,
        "slo_attainment_frac": 1000.0,
        "ttft_p99_ms": -0.1,
        "tpot_p99_ms": -0.1,
        "roofline_compute_frac": 100.0,
        # exposed comm is pure loss: a fully-overlapped step scores 100
        # points over one that serializes its collectives
        "exposed_comm_frac": -100.0,
    }

    def __init__(self, weights: Optional[Dict[str, float]] = None):
        self.weights = dict(weights if weights is not None
                            else self.DEFAULT_WEIGHTS)

    def metrics(self, snapshot: Dict[str, Any],
                extra: Optional[Dict[str, float]] = None) \
            -> Dict[str, float]:
        """The full metric vector for one trial: everything the snapshot
        carries, plus caller-measured extras (e.g. the trial harness's own
        tokens/s).  Extras win on name collision — they are direct
        measurements."""
        vec = extract_metrics(snapshot)
        for k, v in (extra or {}).items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                vec[k] = float(v)
        return vec

    def score(self, metrics: Dict[str, float]) -> float:
        return float(sum(w * metrics[name]
                         for name, w in self.weights.items()
                         if name in metrics))

    @classmethod
    def from_config(cls, spec: Optional[Dict[str, Any]]) -> "Objective":
        """Build from the ``autotuning.objective`` config block
        (``{metric: weight}``); defaults when absent."""
        if not spec:
            return cls()
        return cls({str(k): float(v) for k, v in spec.items()})
