"""Autotuning (reference ``deepspeed/autotuning/``).

Two drivers share one journaled trial runner (``scheduler.py``):

* :class:`Autotuner` — the reference-parity launcher-driven grid search
  (stage × micro-batch, then template coordinate descent);
* :class:`ControlPlane` — the closed-loop tuner: declared knob space,
  memory-model + gauge feasibility pruning, telemetry-snapshot scoring,
  and a provenance-stamped config overlay as the persisted winner.
"""

from deepspeed_tpu.autotuning.autotuner import (Autotuner,
                                                model_memory_per_chip)
from deepspeed_tpu.autotuning.config import AutotuningConfig
from deepspeed_tpu.autotuning.controlplane import TUNE_EVENTS, ControlPlane
from deepspeed_tpu.autotuning.knobs import Knob, KnobSpace
from deepspeed_tpu.autotuning.objective import Objective, extract_metrics
from deepspeed_tpu.autotuning.overlay import (apply_overlay, deep_merge,
                                              load_overlay,
                                              maybe_apply_overlay,
                                              write_overlay)
from deepspeed_tpu.autotuning.scheduler import Experiment, ResourceManager

__all__ = ["Autotuner", "AutotuningConfig", "ControlPlane", "Experiment",
           "Knob", "KnobSpace", "Objective", "ResourceManager",
           "TUNE_EVENTS", "apply_overlay", "deep_merge", "extract_metrics",
           "load_overlay", "maybe_apply_overlay", "model_memory_per_chip",
           "write_overlay"]
