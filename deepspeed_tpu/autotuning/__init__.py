"""Autotuning (reference ``deepspeed/autotuning/``)."""

from deepspeed_tpu.autotuning.autotuner import (Autotuner,
                                                model_memory_per_chip)
from deepspeed_tpu.autotuning.config import AutotuningConfig
from deepspeed_tpu.autotuning.scheduler import Experiment, ResourceManager

__all__ = ["Autotuner", "AutotuningConfig", "Experiment", "ResourceManager",
           "model_memory_per_chip"]
