"""Per-stage tuning templates (reference ``autotuning/config_templates/``
``template_zero0.json`` … ``template_zero3.json``).

The reference seeds each ZeRO stage's search with a JSON template whose
tunable keys carry candidate lists; the tuner expands them per stage.
Here the templates are Python dicts with two sections:

* ``ds``: ds_config knob → candidate values (merged into the experiment
  config; nested keys use ``/`` paths, e.g. ``zero_optimization/
  offload_optimizer``)
* ``model``: TransformerConfig knob → candidate values (merged into the
  trial worker's model spec — TPU-specific knobs like remat policy and
  Pallas attention tile sizes have no reference analogue but were this
  round's main hand-tuned wins, so the tuner must search them)

Knobs are searched by coordinate descent around the stage×micro-batch
winner (the reference's fast mode tunes one dimension at a time too),
keeping the trial count linear instead of combinatorial.
"""

from typing import Any, Dict, List

# ds-config knobs common to every stage
_COMMON_DS: Dict[str, List[Any]] = {
    "gradient_accumulation_steps": [1, 2, 4, 8],
    # reduced-precision state: the knobs that fit gpt_1b (1.01B params)
    # on one 16 GB chip at MFU 0.486 (ONCHIP_r03/big_1b.json) — the
    # tuner must be able to rediscover that configuration
    "optimizer/params/moment_dtype": ["float32", "bfloat16"],
    "data_types/grad_accum_dtype": [None, "bfloat16"],
    # param-stream dials — in the COMMON set because the engine streams
    # params at ANY stage when offload_param is configured; searched only
    # when the base config streams (Autotuner.skip_template_knob):
    # pinned layers trade HBM for fewer uploads; the window deepens the
    # prefetch pipeline
    "zero_optimization/offload_param/resident_layers": [0, 4, 8],
    "zero_optimization/offload_param/buffer_count": [2, 3, 5],
}

# model-config knobs common to every stage (TPU-native)
_COMMON_MODEL: Dict[str, List[Any]] = {
    "remat_policy": ["nothing_saveable", "dots_saveable"],
    # Pallas flash-attention tile sizes: (block_q, block_k) pairs are a
    # single knob so the two dims move together
    "attn_blocks": [(512, 512), (256, 512), (256, 256), (128, 512)],
}

TEMPLATES: Dict[int, Dict[str, Dict[str, List[Any]]]] = {
    0: {"ds": dict(_COMMON_DS), "model": dict(_COMMON_MODEL)},
    1: {"ds": dict(_COMMON_DS), "model": dict(_COMMON_MODEL)},
    2: {"ds": {**_COMMON_DS,
               "zero_optimization/offload_optimizer": [
                   None, {"device": "cpu"}]},
        "model": dict(_COMMON_MODEL)},
    3: {"ds": {**_COMMON_DS,
               "zero_optimization/offload_optimizer": [
                   None, {"device": "cpu"}]},
        "model": dict(_COMMON_MODEL)},
}


# effective default per knob when the key is absent from the config/spec —
# used for semantic incumbent-skipping (a candidate equal to the current
# effective value must not burn a trial re-measuring the winner)
KNOB_DEFAULTS: Dict[str, Any] = {
    "gradient_accumulation_steps": 1,
    "optimizer/params/moment_dtype": "float32",
    "data_types/grad_accum_dtype": None,
    "zero_optimization/offload_optimizer": None,
    "zero_optimization/offload_param/resident_layers": 0,
    "zero_optimization/offload_param/buffer_count": 2,
    "remat_policy": "nothing_saveable",   # TransformerConfig defaults
    "attn_blocks": (512, 512),
}


def get_ds_path(cfg: Dict[str, Any], path: str) -> Any:
    """Effective value of ``a/b/c`` in ``cfg`` (KNOB_DEFAULTS when absent)."""
    node: Any = cfg
    for k in path.split("/"):
        if not isinstance(node, dict) or k not in node:
            return KNOB_DEFAULTS.get(path)
        node = node[k]
    return node


def set_ds_path(cfg: Dict[str, Any], path: str, value: Any) -> Dict[str, Any]:
    """Return a copy of ``cfg`` with ``a/b/c`` set to ``value`` (None pops)."""
    cfg = dict(cfg)
    keys = path.split("/")
    node = cfg
    for k in keys[:-1]:
        node[k] = dict(node.get(k, {}))
        node = node[k]
    if value is None:
        node.pop(keys[-1], None)
    else:
        node[keys[-1]] = value
    return cfg


def model_overrides_for(knob: str, value: Any) -> Dict[str, Any]:
    """Translate a template model knob into TransformerConfig overrides."""
    if knob == "attn_blocks":
        bq, bk = value
        return {"attn_block_q": bq, "attn_block_k": bk}
    return {knob: value}
