"""Autotuning trial worker — one experiment in a fresh OS process.

Parity: reference ``autotuning/scheduler.py`` launches each experiment as
a separate DeepSpeed job so OOMs and allocator state can't leak between
trials (``ResourceManager.run_job``).  This worker is that job: it builds
the model from a serialisable spec, runs the timed trial, and prints ONE
JSON line for the parent's journal.

Usage (internal): python -m deepspeed_tpu.autotuning.trial_worker '<json>'

Spec format::

    {"model": {"kind": "causal_lm", "config": {...TransformerConfig}},
     "ds_config": {...}, "seq": 256, "seed": 0,
     "start_profile_step": 2, "end_profile_step": 5, "cpu": false}
"""

import json
import sys
import time


def build_model(model_spec, overrides=None):
    kind = model_spec.get("kind", "causal_lm")
    cfg_kw = dict(model_spec["config"])
    cfg_kw.update(overrides or {})   # per-trial template model knobs
    if kind == "causal_lm":
        from deepspeed_tpu.models.transformer import (CausalTransformerLM,
                                                      TransformerConfig)
        cfg = TransformerConfig(**cfg_kw)
        return CausalTransformerLM(cfg), cfg
    if kind == "bert":
        from deepspeed_tpu.models.bert import BertConfig, BertEncoder
        cfg = BertConfig(**cfg_kw)
        return BertEncoder(cfg), cfg
    raise ValueError(f"unknown model kind {kind!r}")


def timed_trial(engine, make_batch, start_profile_step, end_profile_step):
    """The measurement protocol shared by the in-process and subprocess
    runners.  ``make_batch`` is called once per step (warmup + timed,
    DISTINCT batches defeat result-memoising device tunnels) but all
    batches are generated BEFORE the timed region so host-side data
    generation never pollutes the throughput measurement."""
    import jax

    steps = max(1, end_profile_step - start_profile_step)
    batches = [make_batch() for _ in range(start_profile_step + steps)]
    for b in batches[:start_profile_step]:     # warmup + compile
        engine.train_batch(batch=b)
    t0 = time.time()
    loss = None
    for b in batches[start_profile_step:]:
        loss = engine.train_batch(batch=b)
    jax.block_until_ready(loss)
    dt = time.time() - t0
    return {
        "throughput": engine.train_batch_size() * steps / dt,
        "latency": dt / steps,
        "micro_batch": engine.train_micro_batch_size_per_gpu(),
        "zero_stage": engine.zero_stage,
        "loss": float(loss),
    }


def run_trial(spec):
    import jax
    if spec.get("cpu"):
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import deepspeed_tpu

    model, cfg = build_model(spec["model"], spec.get("model_overrides"))
    params = model.init(jax.random.key(spec.get("seed", 0)))
    engine, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, config=spec["ds_config"])

    rng = np.random.default_rng(spec.get("seed", 0))
    seq = spec.get("seq", 256)
    gas = engine.gradient_accumulation_steps_

    def make_batch():
        # gas>1 steps consume [gas, micro*dp, S] stacks (the fused GAS scan)
        micro_total = engine.train_batch_size() // max(1, gas)
        shape = (gas, micro_total, seq) if gas > 1 else (
            engine.train_batch_size(), seq)
        return {"input_ids": rng.integers(0, cfg.vocab_size, shape)}

    out = timed_trial(engine, make_batch,
                      spec.get("start_profile_step", 2),
                      spec.get("end_profile_step", 5))
    if hasattr(cfg, "num_params"):
        # model-info for the stage-feasibility memory model (reference
        # autotuner.py:707 model-info run)
        out["n_params"] = int(cfg.num_params())
    out["gradient_accumulation_steps"] = gas
    return out


def main():
    spec = json.loads(sys.argv[1])
    print(json.dumps(run_trial(spec)))


if __name__ == "__main__":
    main()
