"""Prefix cache: content-hashed KV-page reuse for the serving engine.

Under production traffic with shared system prompts and few-shot
templates, every request re-runs prefill over a prefix thousands of other
requests already computed — prefill dominates time-to-first-token at
production batch sizes (PAPERS.md, Gemma-on-TPU serving comparisons), and
block-level KV reuse on top of the existing page structure is the
standard fix (Ragged Paged Attention; vLLM automatic prefix caching).

Design (layered on the refcounted ``ops/paged_attention.PagedAllocator``):

* **Content-hash chain.**  Every FULL page of a served sequence is indexed
  under a rolling hash: ``key_j = H(key_{j-1} || tokens[j*ps:(j+1)*ps])``
  with the chain seeded by a namespace string (model identity / cache
  dtype / page size), so a page's key commits to the ENTIRE token prefix
  behind it, not just its own tokens — two prompts share page ``j`` iff
  they agree on every token up to ``(j+1)*ps``.  Namespaces make pages
  from a different model/dtype/page-size unreachable by construction.
* **Attach, don't copy.**  A lookup walks the chain and hands back the
  matched pages; the engine attaches them to the new request's block
  table via ``allocate(..., shared=...)`` — refcount bumps, zero prefill
  FLOPs, zero page copies.  Suffix writes start at the page boundary
  after the match, so a shared (full, immutable) page is never written.
* **Copy-on-write for partial pages.**  When the next cached page agrees
  with the request's remaining prompt tokens on a proper prefix, its
  content is device-copied into a fresh page (``cow``) and only the
  divergent tail is prefilled — writes land in the request's own copy, a
  sibling sharing the source page is isolated by construction.
* **LRU reclaim tier.**  A cached page whose last sequence reference
  drops parks in the allocator's reclaimable tier instead of the free
  list, still holding its KV content for future hits.  The allocator
  evicts reclaimable pages (oldest first) back into the free list only
  when an allocation outgrows the free list, calling back here so the
  hash index never points at a recycled page.  Admission watermarks count
  reclaimable pages as available — a full cache never looks like page
  pressure.

The last prompt token is never served from cache (its logits seed
sampling), so every request prefills at least one token.
"""

import hashlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel


class PrefixCacheConfig(DeepSpeedConfigModel):
    """The ``serving.prefix_cache`` config block
    (``docs/config-json.md``)."""

    enabled = False
    max_cached_pages = 0     # cap on indexed pages (0 = bounded by pool)
    min_prefix_tokens = 0    # don't consult/populate below this prompt len

    def _validate(self):
        for k in ("max_cached_pages", "min_prefix_tokens"):
            if int(getattr(self, k)) < 0:
                raise ValueError(f"serving.prefix_cache.{k} must be >= 0")


@dataclass
class PrefixMatch:
    """One lookup's result: ``pages`` are full cached pages to attach
    (refcount-shared, in chain order); ``cow_src`` an optional partial
    match whose first ``cow_tokens`` tokens agree with the prompt (the
    engine copies it into a fresh page before writing)."""
    pages: List[int] = field(default_factory=list)
    cow_src: Optional[int] = None
    cow_tokens: int = 0

    def cached_tokens(self, page_size: int) -> int:
        """Total prompt tokens this match serves from cache."""
        return len(self.pages) * page_size + self.cow_tokens


class PrefixCache:
    """Content-hash index over full KV pages, layered on a refcounted
    :class:`~deepspeed_tpu.ops.paged_attention.PagedAllocator`."""

    def __init__(self, alloc, page_size: int, namespace: str = "",
                 max_cached_pages: int = 0, min_prefix_tokens: int = 0,
                 on_evict=None):
        self.alloc = alloc
        self.page_size = int(page_size)
        self.namespace = str(namespace)
        self.max_cached_pages = int(max_cached_pages)
        self.min_prefix_tokens = int(min_prefix_tokens)
        self._on_evict_cb = on_evict
        self._root = hashlib.blake2b(
            self.namespace.encode(), digest_size=16).digest()
        self.index: Dict[bytes, int] = {}        # chain key -> page id
        self.key_of: Dict[int, bytes] = {}       # page id -> chain key
        self.tokens_of: Dict[int, Tuple[int, ...]] = {}
        self.parent_of: Dict[int, bytes] = {}
        self.children: Dict[bytes, Set[int]] = {}
        self.stats = {"lookups": 0, "hits": 0, "pages_reused": 0,
                      "tokens_reused": 0, "cow_copies": 0, "inserts": 0,
                      "evictions": 0, "pages_needed": 0}
        alloc.evict_hook = self._on_evict

    # -- hashing ---------------------------------------------------------
    def _chain_key(self, parent: bytes, page_tokens) -> bytes:
        h = hashlib.blake2b(parent, digest_size=16)
        h.update(np.asarray(page_tokens, np.int64).tobytes())
        return h.digest()

    # -- lookup ----------------------------------------------------------
    def lookup(self, prompt: List[int]) -> PrefixMatch:
        """Longest cached prefix of ``prompt`` (full pages, then one
        optional partial/COW page), capped at ``len(prompt) - 1`` so the
        last token always prefills.  Pure read — nothing is pinned; the
        engine must attach the pages in the same host step (allocation
        protects them) for the ids to stay valid."""
        ps = self.page_size
        match = PrefixMatch()
        self.stats["lookups"] += 1
        self.stats["pages_needed"] += -(-len(prompt) // ps)
        if len(prompt) < max(self.min_prefix_tokens, 2):
            return match
        usable = len(prompt) - 1
        key, pos = self._root, 0
        while pos + ps <= usable:
            nxt = self._chain_key(key, prompt[pos:pos + ps])
            page = self.index.get(nxt)
            if page is None:
                break
            match.pages.append(page)
            key, pos = nxt, pos + ps
        rem = usable - pos
        if rem > 0:
            best, best_m = None, 0
            for page in self.children.get(key, ()):
                toks = self.tokens_of.get(page)
                if not toks:
                    continue
                m = 0
                while m < rem and toks[m] == prompt[pos + m]:
                    m += 1
                if m > best_m:
                    best, best_m = page, m
            if best is not None:
                match.cow_src, match.cow_tokens = best, best_m
                # the engine copies every COW match it attaches, so the
                # match count IS the copy count
                self.stats["cow_copies"] += 1
        reused = len(match.pages) * ps + match.cow_tokens
        if reused:
            self.stats["hits"] += 1
            self.stats["pages_reused"] += len(match.pages)
            self.stats["tokens_reused"] += reused
        return match

    def resident_prefix(self, tokens: List[int]) -> List[int]:
        """Page ids for the leading FULL pages of ``tokens`` resident in
        this cache, in chain order — the migration-import dedup plan.
        Unlike :meth:`lookup` there is no ``len - 1`` cap and no COW leg:
        a migrated request's first output token rides the handoff, so the
        destination never re-prefills and may attach even a fully
        page-aligned prompt's final page.  Pure read — the importer must
        attach the pages (``allocate(shared=...)``) in the same host step
        for the ids to stay valid."""
        ps = self.page_size
        pages, key, pos = [], self._root, 0
        while pos + ps <= len(tokens):
            nxt = self._chain_key(key, tokens[pos:pos + ps])
            page = self.index.get(nxt)
            if page is None:
                break
            pages.append(page)
            key, pos = nxt, pos + ps
        return pages

    # -- insert ----------------------------------------------------------
    def insert(self, tokens: List[int], pages: List[int]) -> int:
        """Index every FULL page of ``(tokens, pages)`` not yet cached
        (pages beyond the last full boundary hold padding/garbage and are
        skipped).  Chain keys are recomputed from the root so partially
        shared sequences deduplicate onto the already-indexed pages.
        Respects ``max_cached_pages`` by evicting LRU reclaimable pages,
        and stops (skipping the remainder) when nothing is evictable.
        Returns the number of pages newly indexed."""
        ps = self.page_size
        if len(tokens) < max(self.min_prefix_tokens, ps):
            return 0
        added, key = 0, self._root
        for j in range(min(len(pages), len(tokens) // ps)):
            page_tokens = tuple(int(t) for t in tokens[j * ps:(j + 1) * ps])
            nxt = self._chain_key(key, page_tokens)
            page = pages[j]
            if nxt in self.index:
                # prefix already cached (possibly on a different physical
                # page this request didn't attach) — keep the incumbent
                key = nxt
                continue
            if page == 0 or page in self.key_of:
                # never index the scratch page; a page already indexed
                # under another chain can't serve two keys
                key = nxt
                continue
            if self.max_cached_pages and \
                    len(self.key_of) >= self.max_cached_pages:
                if self.alloc.reclaim_to_free() is None:
                    break   # everything cached is live; skip the rest
            self.index[nxt] = page
            self.key_of[page] = nxt
            self.tokens_of[page] = page_tokens
            self.parent_of[page] = key
            self.children.setdefault(key, set()).add(page)
            self.alloc.mark_cached(page)
            self.stats["inserts"] += 1
            added += 1
            key = nxt
        return added

    # -- eviction --------------------------------------------------------
    def _on_evict(self, page: int):
        """Allocator surrendered a reclaimable page: drop every index
        entry so no future lookup can hand out the recycled id."""
        key = self.key_of.pop(page, None)
        if key is None:
            return
        self.index.pop(key, None)
        self.tokens_of.pop(page, None)
        parent = self.parent_of.pop(page, None)
        if parent is not None:
            kids = self.children.get(parent)
            if kids is not None:
                kids.discard(page)
                if not kids:
                    del self.children[parent]
        self.stats["evictions"] += 1
        if self._on_evict_cb is not None:
            self._on_evict_cb(page)

    # -- introspection ---------------------------------------------------
    @property
    def cached_page_count(self) -> int:
        return len(self.key_of)

    @property
    def hit_rate(self) -> float:
        """Fraction of prefill pages served from cache across all lookups
        (full shared pages over total pages the prompts spanned)."""
        needed = self.stats["pages_needed"]
        return (self.stats["pages_reused"] / needed) if needed else 0.0

    def audit(self) -> dict:
        """Index/allocator consistency; {} when clean."""
        problems = {}
        if set(self.index.values()) != set(self.key_of):
            problems["index_mismatch"] = True
        not_marked = set(self.key_of) - self.alloc.cached
        if not_marked:
            problems["unmarked_cached_pages"] = sorted(not_marked)
        stray = self.alloc.cached - set(self.key_of)
        if stray:
            problems["stale_allocator_marks"] = sorted(stray)
        if self.max_cached_pages and \
                len(self.key_of) > self.max_cached_pages:
            problems["over_capacity"] = len(self.key_of)
        return problems

    def snapshot(self) -> dict:
        return {"cached_pages": self.cached_page_count,
                "hit_rate": round(self.hit_rate, 4), **self.stats}
