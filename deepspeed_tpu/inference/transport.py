"""Cross-process fleet transport: framed JSON over a local socket pair.

The fleet grew up in one process — ``FleetRouter`` holding N
``ServingEngine`` objects — so "replica death" was an injected fault.
This module is the real wire between a router and a worker process
(``inference/fleet_worker.py``): length-prefixed JSON frames over an
``AF_UNIX`` socketpair, a value codec that makes ndarrays / bytes /
non-string-keyed maps JSON-safe, versioned envelopes for the KV-page
migration payloads, and the router-side :class:`RpcChannel` that demuxes
synchronous RPC responses from the worker's asynchronous heartbeats.

Wire shape, all frames::

    [4-byte big-endian length][utf-8 JSON object]

Frame kinds: a request frame carries ``op`` (router → worker); the
worker answers every op with exactly one ``kind: "resp"`` or ``kind:
"err"`` frame, and interleaves unsolicited ``kind: "hb"`` heartbeat
frames from its beat thread.  Responses are strictly ordered (one
outstanding call at a time), so the channel needs no correlation ids.

Versioning: every payload-bearing envelope (``PrefillHandoff.to_wire``,
``QuantizedPayload.to_wire``, :func:`payload_to_wire`) carries ``"v":
[major, minor]``.  An unknown MAJOR is rejected with the typed
:class:`WireVersionError` (a router must never guess at a frame it
cannot parse); a newer minor passes — minor bumps may only add fields.

Everything here is stdlib + numpy; jax-adjacent imports (the quantized
payload classes) are deferred into the payload helpers so a worker can
import this module before jax finishes loading.
"""

import base64
import json
import socket
import struct
import time
from collections import deque

import numpy as np

# The transport wire version, stamped into every payload envelope as
# ``[major, minor]``.  Bump MINOR when adding fields (old decoders must
# keep working); bump MAJOR for anything an old decoder would misread.
WIRE_VERSION = (1, 0)

_HEADER = struct.Struct(">I")
# sanity bound on one frame (a full KV-page payload for the tiny test
# engines is ~KBs; real payloads are bounded by the page-transfer
# budget) — a corrupt length prefix must not trigger a giant allocation
MAX_FRAME_BYTES = 1 << 30


class TransportError(RuntimeError):
    """The wire failed mid-conversation: torn connection, EOF inside a
    frame, corrupt framing, or a worker-side error that has no typed
    mapping.  The router treats this exactly like a replica death."""


class WorkerError(RuntimeError):
    """A worker-side op raised (engine exception, bad arguments).  The
    wire itself is fine — deliberately NOT a :class:`TransportError`, so
    the router can tell an engine fault (kill the replica, in-process
    semantics) from a torn connection (worker lost)."""


class WireVersionError(TransportError):
    """Typed rejection of an envelope whose MAJOR version this decoder
    does not speak (satellite: reject-with-typed-error, never guess)."""

    def __init__(self, got, what="payload"):
        self.got = got
        self.what = what
        super().__init__(
            f"{what}: unknown wire version {got!r} "
            f"(this decoder speaks major {WIRE_VERSION[0]})")


def check_wire_version(v, what="payload"):
    """Validate an envelope's ``v`` field: the major must match
    ``WIRE_VERSION[0]``; any minor under that major is accepted."""
    try:
        major = int(v[0])
        int(v[1])
    except (TypeError, ValueError, IndexError, KeyError):
        raise WireVersionError(v, what)
    if major != WIRE_VERSION[0]:
        raise WireVersionError(v, what)


# ----------------------------------------------------------------------
# value codec: JSON + ndarrays / bytes / non-string-keyed maps
# ----------------------------------------------------------------------

# reserved marker keys; a plain dict that happens to contain one is
# escaped through the __map__ form so unpacking stays unambiguous
_MARKERS = ("__nd__", "__b64__", "__map__", "__qleaf__", "__tup__")


def _dtype_of(name):
    """``np.dtype`` from its string name, reaching for ml_dtypes (a jax
    dependency, always present here) for bfloat16-family names plain
    numpy does not know."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # registers bfloat16/float8 with numpy
        return np.dtype(getattr(ml_dtypes, name))


def nd_to_wire(arr):
    """One ndarray as a JSON-safe dict (base64 raw bytes + dtype name +
    shape).  Accepts anything ``np.asarray`` takes — jax arrays device-
    transfer here, which is exactly the wire boundary."""
    arr = np.ascontiguousarray(np.asarray(arr))
    return {"__nd__": base64.b64encode(arr.tobytes()).decode("ascii"),
            "dtype": str(arr.dtype), "shape": list(arr.shape)}


def nd_from_wire(d):
    raw = base64.b64decode(d["__nd__"])
    return np.frombuffer(raw, dtype=_dtype_of(d["dtype"])).reshape(
        d["shape"]).copy()


def pack_value(obj):
    """Recursively rewrite ``obj`` into a JSON-serializable structure:
    ndarrays and numpy scalars, bytes, tuples (marked, so they unpack
    back to tuples — req_ids must stay hashable across the wire), and
    dicts with non-string keys all get stable encodings.  The inverse
    is :func:`unpack_value`."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, (np.bool_,)):
        return bool(obj)
    if isinstance(obj, np.integer):
        return int(obj)
    if isinstance(obj, np.floating):
        return float(obj)
    if isinstance(obj, (bytes, bytearray)):
        return {"__b64__": base64.b64encode(bytes(obj)).decode("ascii")}
    if isinstance(obj, np.ndarray):
        return nd_to_wire(obj)
    if isinstance(obj, tuple):
        return {"__tup__": [pack_value(v) for v in obj]}
    if isinstance(obj, list):
        return [pack_value(v) for v in obj]
    if isinstance(obj, dict):
        if any(k in obj for k in _MARKERS):
            return obj          # already packed — pack is idempotent
        if all(isinstance(k, str) for k in obj):
            return {k: pack_value(v) for k, v in obj.items()}
        return {"__map__": [[pack_value(k), pack_value(v)]
                            for k, v in obj.items()]}
    raise TypeError(f"transport cannot encode {type(obj).__name__}")


def unpack_value(obj):
    """Inverse of :func:`pack_value`."""
    if isinstance(obj, dict):
        if "__b64__" in obj:
            return base64.b64decode(obj["__b64__"])
        if "__nd__" in obj:
            return nd_from_wire(obj)
        if "__tup__" in obj:
            return tuple(unpack_value(v) for v in obj["__tup__"])
        if "__map__" in obj:
            return {_hashable(unpack_value(k)): unpack_value(v)
                    for k, v in obj["__map__"]}
        return {k: unpack_value(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [unpack_value(v) for v in obj]
    return obj


def _hashable(k):
    return tuple(k) if isinstance(k, list) else k


# ----------------------------------------------------------------------
# framing
# ----------------------------------------------------------------------


def send_frame(sock, obj, lock=None):
    """Serialize + length-prefix + sendall one frame.  ``lock`` guards
    the socket when two threads write (the worker's main loop and its
    heartbeat thread); any OS-level failure surfaces as
    :class:`TransportError` — a torn wire, not a crash."""
    data = json.dumps(pack_value(obj), separators=(",", ":")).encode()
    buf = _HEADER.pack(len(data)) + data
    try:
        if lock is not None:
            with lock:
                sock.sendall(buf)
        else:
            sock.sendall(buf)
    except (OSError, ValueError) as e:
        raise TransportError(f"send failed: {e}")


def recv_frame(stream):
    """Read exactly one frame from a blocking file-like stream (the
    worker side uses ``sock.makefile('rb')``).  EOF — clean or mid-frame
    — is a :class:`TransportError`: the peer is gone."""
    head = _read_exact(stream, _HEADER.size)
    (n,) = _HEADER.unpack(head)
    if n > MAX_FRAME_BYTES:
        raise TransportError(f"frame length {n} exceeds cap")
    return json.loads(_read_exact(stream, n).decode())


def _read_exact(stream, n):
    chunks = []
    while n:
        try:
            chunk = stream.read(n)
        except OSError as e:
            raise TransportError(f"recv failed: {e}")
        if not chunk:
            raise TransportError("connection closed by peer")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


# ----------------------------------------------------------------------
# migration payload envelopes (versioned)
# ----------------------------------------------------------------------


def payload_to_wire(payload):
    """Wire envelope for a KV-page migration payload: either the raw
    exported pytree or the source codec's :class:`QuantizedPayload`
    (``comm/quantize.py``).  Quantized leaves stay int8 on the wire —
    the whole point of the codec survives serialization."""
    from deepspeed_tpu.comm.quantize import QuantizedPayload
    if payload is None:
        return None
    if isinstance(payload, QuantizedPayload):
        return {"v": list(WIRE_VERSION), "quant": True,
                "block_size": int(payload.block_size),
                "wire_bytes": int(payload.wire_bytes),
                "raw_bytes": int(payload.raw_bytes),
                "tree": _tree_to_wire(payload.leaves)}
    return {"v": list(WIRE_VERSION), "quant": False,
            "tree": _tree_to_wire(payload)}


def payload_from_wire(d):
    """Inverse of :func:`payload_to_wire`; validates the envelope
    version before touching anything else."""
    from deepspeed_tpu.comm.quantize import QuantizedPayload
    if d is None:
        return None
    check_wire_version(d.get("v"), "QuantizedPayload"
                       if d.get("quant") else "migration payload")
    tree = _tree_from_wire(d["tree"])
    if d.get("quant"):
        return QuantizedPayload(leaves=tree,
                                block_size=int(d["block_size"]),
                                wire_bytes=int(d["wire_bytes"]),
                                raw_bytes=int(d["raw_bytes"]))
    return tree


def _tree_to_wire(tree):
    """Encode an exported-cache pytree (nested dict/list/tuple of
    arrays, with :class:`QuantizedLeaf` at quantized positions)."""
    from deepspeed_tpu.comm.quantize import QuantizedLeaf
    if isinstance(tree, QuantizedLeaf):
        return {"__qleaf__": {
            "codes": nd_to_wire(tree.codes),
            "scales": nd_to_wire(tree.scales),
            "shape": list(tree.shape),
            "dtype": str(np.dtype(tree.dtype)),
            "numel": int(tree.numel)}}
    if isinstance(tree, dict):
        return {"__tree_dict__": {str(k): _tree_to_wire(v)
                                  for k, v in tree.items()}}
    if isinstance(tree, tuple) and hasattr(tree, "_fields"):
        # a namedtuple pytree node (e.g. PagedKVCache): record the
        # import path so the receiver rebuilds the SAME node type —
        # import_pages tree_maps the payload against its own cache
        # pytree, so plain lists would be a structure mismatch.  Both
        # ends run this codebase by construction (the engine factory
        # spec is itself a dotted import path), so import-by-name is
        # the same trust domain the fleet already stands on.
        cls = type(tree)
        return {"__tree_ntup__":
                f"{cls.__module__}:{cls.__qualname__}",
                "fields": [_tree_to_wire(v) for v in tree]}
    if isinstance(tree, tuple):
        return {"__tree_tup__": [_tree_to_wire(v) for v in tree]}
    if isinstance(tree, (list,)):
        return {"__tree_list__": [_tree_to_wire(v) for v in tree]}
    return nd_to_wire(tree)


def _nd(x):
    """ndarray from either wire form: the raw ``__nd__`` dict, or an
    already-decoded array (a frame that passed through
    :class:`RpcChannel`'s value decode on its way here)."""
    return x if isinstance(x, np.ndarray) else nd_from_wire(x)


def _tree_from_wire(node):
    from deepspeed_tpu.comm.quantize import QuantizedLeaf
    if isinstance(node, np.ndarray):
        return node
    if "__qleaf__" in node:
        q = node["__qleaf__"]
        return QuantizedLeaf(codes=_nd(q["codes"]),
                             scales=_nd(q["scales"]),
                             shape=tuple(q["shape"]),
                             dtype=_dtype_of(q["dtype"]),
                             numel=int(q["numel"]))
    if "__tree_dict__" in node:
        return {k: _tree_from_wire(v)
                for k, v in node["__tree_dict__"].items()}
    if "__tree_ntup__" in node:
        import importlib
        mod_name, _, qualname = node["__tree_ntup__"].partition(":")
        cls = importlib.import_module(mod_name)
        for part in qualname.split("."):
            cls = getattr(cls, part)
        return cls(*[_tree_from_wire(v) for v in node["fields"]])
    if "__tree_tup__" in node:
        return tuple(_tree_from_wire(v) for v in node["__tree_tup__"])
    if "__tree_list__" in node:
        return [_tree_from_wire(v) for v in node["__tree_list__"]]
    return nd_from_wire(node)


# ----------------------------------------------------------------------
# router-side channel
# ----------------------------------------------------------------------


class RpcChannel:
    """The router's end of one worker socket.

    Single-threaded by design (the :class:`FleetRouter` owns it); the
    worker interleaves asynchronous heartbeat frames between RPC
    responses, so every read path funnels through the same buffered
    parser: heartbeats update :attr:`last_heartbeat` / :attr:`hb_seq` /
    :attr:`hb_epoch` the moment they are seen, everything else lands in
    the response inbox.  :meth:`pump` drains whatever bytes have already
    arrived without blocking — the router's liveness check calls it each
    step, so a worker that stops beating is noticed even when no RPC is
    in flight.

    ``last_heartbeat`` is stamped with the ROUTER's clock at receipt
    (injectable for tests); it starts at construction time, so a fresh
    worker gets one full deadline to come up before liveness can indict
    it.
    """

    def __init__(self, sock, clock=None):
        self.sock = sock
        self._clock = clock if clock is not None else time.monotonic
        self._buf = bytearray()
        self._inbox = deque()
        self.last_heartbeat = self._clock()
        self.hb_seq = -1
        self.hb_epoch = None
        self.closed = False

    # -- byte plumbing ---------------------------------------------------
    def _parse(self):
        while True:
            if len(self._buf) < _HEADER.size:
                return
            (n,) = _HEADER.unpack(bytes(self._buf[:_HEADER.size]))
            if n > MAX_FRAME_BYTES:
                raise TransportError(f"frame length {n} exceeds cap")
            if len(self._buf) < _HEADER.size + n:
                return
            data = bytes(self._buf[_HEADER.size:_HEADER.size + n])
            del self._buf[:_HEADER.size + n]
            frame = unpack_value(json.loads(data.decode()))
            if isinstance(frame, dict) and frame.get("kind") == "hb":
                seq = int(frame.get("seq", 0))
                # a monotonicity regression means a confused or replaced
                # peer — ignore the beat rather than refresh liveness
                if seq > self.hb_seq:
                    self.hb_seq = seq
                    self.hb_epoch = frame.get("epoch")
                    self.last_heartbeat = self._clock()
            else:
                self._inbox.append(frame)

    def _fill(self, timeout):
        """Read whatever the socket has within ``timeout`` seconds
        (0 = only what is already buffered) into the parse buffer."""
        if self.closed:
            raise TransportError("channel is closed")
        try:
            self.sock.settimeout(timeout)
            chunk = self.sock.recv(1 << 16)
        except (socket.timeout, BlockingIOError):
            return False
        except OSError as e:
            raise TransportError(f"recv failed: {e}")
        if not chunk:
            raise TransportError("worker closed the connection")
        self._buf.extend(chunk)
        return True

    def pump(self):
        """Drain already-arrived frames without blocking (heartbeats
        update liveness state; responses queue).  Raises
        :class:`TransportError` when the worker side is gone."""
        while self._fill(0.0):
            pass
        self._parse()

    # -- calls -----------------------------------------------------------
    def call(self, op, timeout=60.0, **kwargs):
        """One synchronous RPC: send ``{op, **kwargs}``, block (up to
        ``timeout`` wall seconds) for the matching response frame, and
        return its payload dict.  Worker-side typed errors re-raise
        here; anything structural raises :class:`TransportError`."""
        self.pump()
        if self._inbox:     # protocol break: a stale unclaimed response
            raise TransportError(
                f"unexpected frame before call {op!r}: "
                f"{self._inbox.popleft()!r}")
        frame = {"op": op}
        frame.update(kwargs)
        try:
            self.sock.settimeout(timeout)
            send_frame(self.sock, frame)
        except TransportError:
            raise
        deadline = time.monotonic() + timeout
        while not self._inbox:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TransportError(f"call {op!r} timed out "
                                     f"after {timeout}s")
            self._fill(remaining)
            self._parse()
        resp = self._inbox.popleft()
        if not isinstance(resp, dict):
            raise TransportError(f"malformed response to {op!r}")
        if resp.get("kind") == "err":
            self._raise_typed(op, resp)
        return resp

    @staticmethod
    def _raise_typed(op, resp):
        etype = resp.get("etype", "")
        detail = resp.get("detail", "")
        if etype == "RequestRejected":
            from deepspeed_tpu.inference.robustness import RequestRejected
            raise RequestRejected(resp.get("req_id"),
                                  resp.get("reason", ""), detail)
        if etype == "WireVersionError":
            raise WireVersionError(resp.get("got"),
                                   resp.get("what", op))
        raise WorkerError(f"worker error in {op!r}: "
                          f"{etype or 'Exception'}: {detail}")

    def close(self):
        self.closed = True
        try:
            self.sock.close()
        except OSError:
            pass
