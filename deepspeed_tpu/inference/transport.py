"""Cross-process fleet transport: framed JSON over a local socket pair.

The fleet grew up in one process — ``FleetRouter`` holding N
``ServingEngine`` objects — so "replica death" was an injected fault.
This module is the real wire between a router and a worker process
(``inference/fleet_worker.py``): length-prefixed JSON frames over an
``AF_UNIX`` socketpair, a value codec that makes ndarrays / bytes /
non-string-keyed maps JSON-safe, versioned envelopes for the KV-page
migration payloads, and the router-side :class:`RpcChannel` that demuxes
synchronous RPC responses from the worker's asynchronous heartbeats.

Wire shape, all frames::

    [4-byte big-endian length][utf-8 JSON object]

Frame kinds: a request frame carries ``op`` (router → worker); the
worker answers every op with exactly one ``kind: "resp"`` or ``kind:
"err"`` frame, and interleaves unsolicited ``kind: "hb"`` heartbeat
frames from its beat thread.  Responses are strictly ordered (one
outstanding call at a time), so the channel needs no correlation ids.

Versioning: every payload-bearing envelope (``PrefillHandoff.to_wire``,
``QuantizedPayload.to_wire``, :func:`payload_to_wire`) carries ``"v":
[major, minor]``.  An unknown MAJOR is rejected with the typed
:class:`WireVersionError` (a router must never guess at a frame it
cannot parse); a newer minor passes — minor bumps may only add fields.

Everything here is stdlib + numpy; jax-adjacent imports (the quantized
payload classes) are deferred into the payload helpers so a worker can
import this module before jax finishes loading.
"""

import base64
import json
import random
import socket
import struct
import time
from collections import deque

import numpy as np

# The transport wire version, stamped into every payload envelope as
# ``[major, minor]``.  Bump MINOR when adding fields (old decoders must
# keep working); bump MAJOR for anything an old decoder would misread.
WIRE_VERSION = (1, 0)

_HEADER = struct.Struct(">I")
# sanity bound on one frame (a full KV-page payload for the tiny test
# engines is ~KBs; real payloads are bounded by the page-transfer
# budget) — a corrupt length prefix must not trigger a giant allocation
MAX_FRAME_BYTES = 1 << 30


class TransportError(RuntimeError):
    """The wire failed mid-conversation: torn connection, EOF inside a
    frame, corrupt framing, or a worker-side error that has no typed
    mapping.  The router treats this exactly like a replica death."""


class WorkerError(RuntimeError):
    """A worker-side op raised (engine exception, bad arguments).  The
    wire itself is fine — deliberately NOT a :class:`TransportError`, so
    the router can tell an engine fault (kill the replica, in-process
    semantics) from a torn connection (worker lost)."""


class RpcTimeout(TransportError):
    """One call's deadline expired with no matching response.  Subclass
    of :class:`TransportError` so legacy catch sites still treat it as a
    wire problem, but distinct so the router's circuit breaker can tell
    "slow or lossy" (count, maybe retry, maybe open the breaker) from
    "torn" (connection dead — worker lost, no retry can help).  The
    reply may still arrive later; it is discarded by call id, never
    misread as the next call's response."""


class WireVersionError(TransportError):
    """Typed rejection of an envelope whose MAJOR version this decoder
    does not speak (satellite: reject-with-typed-error, never guess)."""

    def __init__(self, got, what="payload"):
        self.got = got
        self.what = what
        super().__init__(
            f"{what}: unknown wire version {got!r} "
            f"(this decoder speaks major {WIRE_VERSION[0]})")


def check_wire_version(v, what="payload"):
    """Validate an envelope's ``v`` field: the major must match
    ``WIRE_VERSION[0]``; any minor under that major is accepted."""
    try:
        major = int(v[0])
        int(v[1])
    except (TypeError, ValueError, IndexError, KeyError):
        raise WireVersionError(v, what)
    if major != WIRE_VERSION[0]:
        raise WireVersionError(v, what)


# ----------------------------------------------------------------------
# value codec: JSON + ndarrays / bytes / non-string-keyed maps
# ----------------------------------------------------------------------

# reserved marker keys; a plain dict that happens to contain one is
# escaped through the __map__ form so unpacking stays unambiguous
_MARKERS = ("__nd__", "__b64__", "__map__", "__qleaf__", "__tup__")


def _dtype_of(name):
    """``np.dtype`` from its string name, reaching for ml_dtypes (a jax
    dependency, always present here) for bfloat16-family names plain
    numpy does not know."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # registers bfloat16/float8 with numpy
        return np.dtype(getattr(ml_dtypes, name))


def nd_to_wire(arr):
    """One ndarray as a JSON-safe dict (base64 raw bytes + dtype name +
    shape).  Accepts anything ``np.asarray`` takes — jax arrays device-
    transfer here, which is exactly the wire boundary."""
    arr = np.ascontiguousarray(np.asarray(arr))
    return {"__nd__": base64.b64encode(arr.tobytes()).decode("ascii"),
            "dtype": str(arr.dtype), "shape": list(arr.shape)}


def nd_from_wire(d):
    raw = base64.b64decode(d["__nd__"])
    return np.frombuffer(raw, dtype=_dtype_of(d["dtype"])).reshape(
        d["shape"]).copy()


def pack_value(obj):
    """Recursively rewrite ``obj`` into a JSON-serializable structure:
    ndarrays and numpy scalars, bytes, tuples (marked, so they unpack
    back to tuples — req_ids must stay hashable across the wire), and
    dicts with non-string keys all get stable encodings.  The inverse
    is :func:`unpack_value`."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, (np.bool_,)):
        return bool(obj)
    if isinstance(obj, np.integer):
        return int(obj)
    if isinstance(obj, np.floating):
        return float(obj)
    if isinstance(obj, (bytes, bytearray)):
        return {"__b64__": base64.b64encode(bytes(obj)).decode("ascii")}
    if isinstance(obj, np.ndarray):
        return nd_to_wire(obj)
    if isinstance(obj, tuple):
        return {"__tup__": [pack_value(v) for v in obj]}
    if isinstance(obj, list):
        return [pack_value(v) for v in obj]
    if isinstance(obj, dict):
        if any(k in obj for k in _MARKERS):
            return obj          # already packed — pack is idempotent
        if all(isinstance(k, str) for k in obj):
            return {k: pack_value(v) for k, v in obj.items()}
        return {"__map__": [[pack_value(k), pack_value(v)]
                            for k, v in obj.items()]}
    raise TypeError(f"transport cannot encode {type(obj).__name__}")


def unpack_value(obj):
    """Inverse of :func:`pack_value`."""
    if isinstance(obj, dict):
        if "__b64__" in obj:
            return base64.b64decode(obj["__b64__"])
        if "__nd__" in obj:
            return nd_from_wire(obj)
        if "__tup__" in obj:
            return tuple(unpack_value(v) for v in obj["__tup__"])
        if "__map__" in obj:
            return {_hashable(unpack_value(k)): unpack_value(v)
                    for k, v in obj["__map__"]}
        return {k: unpack_value(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [unpack_value(v) for v in obj]
    return obj


def _hashable(k):
    return tuple(k) if isinstance(k, list) else k


# ----------------------------------------------------------------------
# framing
# ----------------------------------------------------------------------


def send_frame(sock, obj, lock=None):
    """Serialize + length-prefix + sendall one frame.  ``lock`` guards
    the socket when two threads write (the worker's main loop and its
    heartbeat thread); any OS-level failure surfaces as
    :class:`TransportError` — a torn wire, not a crash."""
    data = json.dumps(pack_value(obj), separators=(",", ":")).encode()
    buf = _HEADER.pack(len(data)) + data
    try:
        if lock is not None:
            with lock:
                sock.sendall(buf)
        else:
            sock.sendall(buf)
    except (OSError, ValueError) as e:
        raise TransportError(f"send failed: {e}")


def recv_frame(stream):
    """Read exactly one frame from a blocking file-like stream (the
    worker side uses ``sock.makefile('rb')``).  EOF — clean or mid-frame
    — is a :class:`TransportError`: the peer is gone."""
    head = _read_exact(stream, _HEADER.size)
    (n,) = _HEADER.unpack(head)
    if n > MAX_FRAME_BYTES:
        raise TransportError(f"frame length {n} exceeds cap")
    body = _read_exact(stream, n)
    try:
        return json.loads(body.decode())
    except (UnicodeDecodeError, ValueError) as e:
        # a torn/overlapping frame desynchronized the stream — there is
        # no way to resync a length-prefixed stream after a bad length,
        # so surface it as a wire death, not a crash
        raise TransportError(f"corrupt frame: {e}")


def _read_exact(stream, n):
    chunks = []
    while n:
        try:
            chunk = stream.read(n)
        except OSError as e:
            raise TransportError(f"recv failed: {e}")
        if not chunk:
            raise TransportError("connection closed by peer")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


# ----------------------------------------------------------------------
# migration payload envelopes (versioned)
# ----------------------------------------------------------------------


def payload_to_wire(payload):
    """Wire envelope for a KV-page migration payload: either the raw
    exported pytree or the source codec's :class:`QuantizedPayload`
    (``comm/quantize.py``).  Quantized leaves stay int8 on the wire —
    the whole point of the codec survives serialization."""
    from deepspeed_tpu.comm.quantize import QuantizedPayload
    if payload is None:
        return None
    if isinstance(payload, QuantizedPayload):
        return {"v": list(WIRE_VERSION), "quant": True,
                "block_size": int(payload.block_size),
                "wire_bytes": int(payload.wire_bytes),
                "raw_bytes": int(payload.raw_bytes),
                "tree": _tree_to_wire(payload.leaves)}
    return {"v": list(WIRE_VERSION), "quant": False,
            "tree": _tree_to_wire(payload)}


def payload_from_wire(d):
    """Inverse of :func:`payload_to_wire`; validates the envelope
    version before touching anything else."""
    from deepspeed_tpu.comm.quantize import QuantizedPayload
    if d is None:
        return None
    check_wire_version(d.get("v"), "QuantizedPayload"
                       if d.get("quant") else "migration payload")
    tree = _tree_from_wire(d["tree"])
    if d.get("quant"):
        return QuantizedPayload(leaves=tree,
                                block_size=int(d["block_size"]),
                                wire_bytes=int(d["wire_bytes"]),
                                raw_bytes=int(d["raw_bytes"]))
    return tree


def _tree_to_wire(tree):
    """Encode an exported-cache pytree (nested dict/list/tuple of
    arrays, with :class:`QuantizedLeaf` at quantized positions)."""
    from deepspeed_tpu.comm.quantize import QuantizedLeaf
    if isinstance(tree, QuantizedLeaf):
        return {"__qleaf__": {
            "codes": nd_to_wire(tree.codes),
            "scales": nd_to_wire(tree.scales),
            "shape": list(tree.shape),
            "dtype": str(np.dtype(tree.dtype)),
            "numel": int(tree.numel)}}
    if isinstance(tree, dict):
        return {"__tree_dict__": {str(k): _tree_to_wire(v)
                                  for k, v in tree.items()}}
    if isinstance(tree, tuple) and hasattr(tree, "_fields"):
        # a namedtuple pytree node (e.g. PagedKVCache): record the
        # import path so the receiver rebuilds the SAME node type —
        # import_pages tree_maps the payload against its own cache
        # pytree, so plain lists would be a structure mismatch.  Both
        # ends run this codebase by construction (the engine factory
        # spec is itself a dotted import path), so import-by-name is
        # the same trust domain the fleet already stands on.
        cls = type(tree)
        return {"__tree_ntup__":
                f"{cls.__module__}:{cls.__qualname__}",
                "fields": [_tree_to_wire(v) for v in tree]}
    if isinstance(tree, tuple):
        return {"__tree_tup__": [_tree_to_wire(v) for v in tree]}
    if isinstance(tree, (list,)):
        return {"__tree_list__": [_tree_to_wire(v) for v in tree]}
    return nd_to_wire(tree)


def _nd(x):
    """ndarray from either wire form: the raw ``__nd__`` dict, or an
    already-decoded array (a frame that passed through
    :class:`RpcChannel`'s value decode on its way here)."""
    return x if isinstance(x, np.ndarray) else nd_from_wire(x)


def _tree_from_wire(node):
    from deepspeed_tpu.comm.quantize import QuantizedLeaf
    if isinstance(node, np.ndarray):
        return node
    if "__qleaf__" in node:
        q = node["__qleaf__"]
        return QuantizedLeaf(codes=_nd(q["codes"]),
                             scales=_nd(q["scales"]),
                             shape=tuple(q["shape"]),
                             dtype=_dtype_of(q["dtype"]),
                             numel=int(q["numel"]))
    if "__tree_dict__" in node:
        return {k: _tree_from_wire(v)
                for k, v in node["__tree_dict__"].items()}
    if "__tree_ntup__" in node:
        import importlib
        mod_name, _, qualname = node["__tree_ntup__"].partition(":")
        cls = importlib.import_module(mod_name)
        for part in qualname.split("."):
            cls = getattr(cls, part)
        return cls(*[_tree_from_wire(v) for v in node["fields"]])
    if "__tree_tup__" in node:
        return tuple(_tree_from_wire(v) for v in node["__tree_tup__"])
    if "__tree_list__" in node:
        return [_tree_from_wire(v) for v in node["__tree_list__"]]
    return nd_from_wire(node)


# ----------------------------------------------------------------------
# deterministic wire-fault injection
# ----------------------------------------------------------------------

# Frame-layer fault sites, mirrored (same names, same order) inside
# ``runtime/resilience.py``'s frozen FAULT_SITES tail — a tier-1 test
# diffs the two, so chaos configs and docs share one vocabulary:
#   wire_send   — one outbound request frame: drop / dup / reorder / tear
#   wire_recv   — one inbound response frame: drop / dup / reorder
#   wire_delay  — injected latency before an outbound frame
#   rpc_timeout — force one call's deadline to expire without sending
WIRE_FAULT_SITES = ("wire_send", "wire_recv", "wire_delay", "rpc_timeout")

# actions a site plan can yield, in the order they are checked
_WIRE_ACTIONS = ("tear", "drop", "dup", "reorder", "delay", "timeout")


class WireFaultInjector:
    """Deterministic, seeded, site-addressable frame-fault injector —
    the ``FaultInjector`` idiom (``runtime/resilience.py``) pushed down
    into the wire.  One injector is shared by every channel in a fleet,
    so site counters are global across replicas and a whole chaos
    scenario replays from ``(spec, seed)`` alone.

    Spec: ``{site: cfg, ...}`` over :data:`WIRE_FAULT_SITES`.  Each cfg
    may carry:

    - ``drop_at`` / ``dup_at`` / ``reorder_at`` / ``tear_at`` /
      ``delay_at`` / ``timeout_at`` — 0-based invocation indices (per
      site, counted AFTER filters) at which that action fires;
    - ``times`` + ``action`` — fire ``action`` on the first N matching
      invocations (``{"times": 2, "action": "drop"}``);
    - ``every`` + ``action`` — fire on every Nth matching invocation;
    - ``rate`` + ``action`` — fire with probability ``rate`` from the
      seeded rng (still replayable: same seed, same plan);
    - ``delay_secs`` — sleep budget used when the action is ``delay``;
    - ``ops`` — only frames for these ops consume an index here;
    - ``replicas`` — only channels whose peer id matches consume an
      index, making per-replica plans independent of how often the
      *other* replicas talk (wall-clock-proof determinism).

    Filtered-out invocations consume nothing, so indices stay stable no
    matter how much unrelated traffic interleaves."""

    def __init__(self, spec=None, seed=0):
        spec = dict(spec or {})
        self.seed = int(spec.pop("seed", seed))
        for site in spec:
            if site not in WIRE_FAULT_SITES:
                raise ValueError(f"unknown wire fault site {site!r} "
                                 f"(have {WIRE_FAULT_SITES})")
        self.spec = {site: dict(cfg) for site, cfg in spec.items()}
        self._rng = random.Random(self.seed)
        self._counts = {site: 0 for site in WIRE_FAULT_SITES}
        self._fired = {site: 0 for site in WIRE_FAULT_SITES}

    @classmethod
    def from_config(cls, spec, seed=0):
        """``None``/empty spec → no injector (zero overhead path)."""
        return cls(spec, seed=seed) if spec else None

    def calls(self, site):
        return self._counts[site]

    def fired(self, site):
        return self._fired[site]

    def delay_secs(self, site):
        cfg = self.spec.get(site) or {}
        return float(cfg.get("delay_secs", 0.01))

    def plan(self, site, op=None, peer=None):
        """Consume one invocation at ``site`` and return the action to
        take (one of ``tear|drop|dup|reorder|delay|timeout``) or
        ``None``.  Filters (``ops``/``replicas``) are checked first and
        do not consume an index."""
        if site not in self._counts:
            raise ValueError(f"unknown wire fault site {site!r}")
        cfg = self.spec.get(site)
        if not cfg:
            return None
        ops = cfg.get("ops")
        if ops is not None and op not in ops:
            return None
        reps = cfg.get("replicas")
        if reps is not None and peer not in reps:
            return None
        idx = self._counts[site]
        self._counts[site] += 1
        action = None
        for act in _WIRE_ACTIONS:
            at = cfg.get(f"{act}_at")
            if at is not None and idx in at:
                action = act
                break
        if action is None and "action" in cfg:
            act = cfg["action"]
            if act not in _WIRE_ACTIONS:
                raise ValueError(f"unknown wire fault action {act!r}")
            if "times" in cfg and idx < int(cfg["times"]):
                action = act
            elif "every" in cfg and (idx + 1) % int(cfg["every"]) == 0:
                action = act
            elif "rate" in cfg and self._rng.random() < float(cfg["rate"]):
                action = act
        if action is not None:
            self._fired[site] += 1
        return action


# ----------------------------------------------------------------------
# router-side channel
# ----------------------------------------------------------------------


class RpcChannel:
    """The router's end of one worker socket.

    Single-threaded by design (the :class:`FleetRouter` owns it); the
    worker interleaves asynchronous heartbeat frames between RPC
    responses, so every read path funnels through the same buffered
    parser: heartbeats update :attr:`last_heartbeat` / :attr:`hb_seq` /
    :attr:`hb_epoch` the moment they are seen, everything else lands in
    the response inbox.  :meth:`pump` drains whatever bytes have already
    arrived without blocking — the router's liveness check calls it each
    step, so a worker that stops beating is noticed even when no RPC is
    in flight.

    ``last_heartbeat`` is stamped with the ROUTER's clock at receipt
    (injectable for tests); it starts at construction time, so a fresh
    worker gets one full deadline to come up before liveness can indict
    it.

    Every request frame is stamped with a monotonically increasing call
    id (``cid``) which the worker echoes on its response, so a reply
    that arrives AFTER its call timed out is discarded by id instead of
    being misread as the next call's response.  Calls flagged
    ``idempotent`` retry on :class:`RpcTimeout` with exponential
    backoff + jitter (``retry`` policy, injectable); mutating ops
    additionally carry an idempotency key the worker dedups, so a retry
    after a dropped ack cannot double-apply.  ``wire`` is an optional
    :class:`WireFaultInjector` — the chaos plane's hook into every
    frame this channel sends or receives (heartbeats excepted: their
    timing is wall-clock noise and faulting them would break replay).
    """

    def __init__(self, sock, clock=None, wire=None, retry=None,
                 peer=None):
        self.sock = sock
        self._clock = clock if clock is not None else time.monotonic
        self._buf = bytearray()
        self._inbox = deque()
        self.last_heartbeat = self._clock()
        self.hb_seq = -1
        self.hb_epoch = None
        self.closed = False
        self.wire = wire            # WireFaultInjector (chaos) or None
        self.retry = retry          # RetryPolicy-shaped object or None
        self.peer = peer            # replica id, for per-replica chaos
        self._call_seq = 0          # monotonically increasing call id
        self._op_in_flight = None
        self._recv_hold = None      # inbound frame held by a reorder
        self._send_hold = None      # outbound frame held by a reorder
        # a call timed out with its reply (or a partial frame) possibly
        # still in flight; cleared when a matching reply next arrives.
        # Length-prefixed framing self-heals the buffer, and cids keep
        # the stale reply from being claimed by the next call.
        self.desynced = False
        self.stale_drops = 0        # late/duplicate replies discarded
        self.retries = 0
        self.on_retry = None        # callback(op, attempt, delay_s, elapsed_s)
        self.on_stale = None        # callback(op, kind)

    # -- byte plumbing ---------------------------------------------------
    def _parse(self):
        while True:
            if len(self._buf) < _HEADER.size:
                return
            (n,) = _HEADER.unpack(bytes(self._buf[:_HEADER.size]))
            if n > MAX_FRAME_BYTES:
                raise TransportError(f"frame length {n} exceeds cap")
            if len(self._buf) < _HEADER.size + n:
                return
            data = bytes(self._buf[_HEADER.size:_HEADER.size + n])
            del self._buf[:_HEADER.size + n]
            try:
                frame = unpack_value(json.loads(data.decode()))
            except (UnicodeDecodeError, ValueError) as e:
                raise TransportError(f"corrupt frame: {e}")
            if isinstance(frame, dict) and frame.get("kind") == "hb":
                seq = int(frame.get("seq", 0))
                # a monotonicity regression means a confused or replaced
                # peer — ignore the beat rather than refresh liveness
                if seq > self.hb_seq:
                    self.hb_seq = seq
                    self.hb_epoch = frame.get("epoch")
                    self.last_heartbeat = self._clock()
            else:
                self._deliver(frame)

    def _deliver(self, frame):
        """Inbound fault point for non-heartbeat frames: the chaos
        plane may drop, duplicate, or reorder one decoded frame before
        it reaches the response inbox."""
        if self.wire is not None:
            act = self.wire.plan("wire_recv", op=self._op_in_flight,
                                 peer=self.peer)
            if act == "drop":
                return
            if act == "dup":
                self._push(frame)
                self._push(frame)
                return
            if act == "reorder":
                self._recv_hold = frame   # delivered after the NEXT one
                return
        self._push(frame)

    def _push(self, frame):
        self._inbox.append(frame)
        if self._recv_hold is not None:
            held, self._recv_hold = self._recv_hold, None
            self._inbox.append(held)

    def _fill(self, timeout):
        """Read whatever the socket has within ``timeout`` seconds
        (0 = only what is already buffered) into the parse buffer."""
        if self.closed:
            raise TransportError("channel is closed")
        try:
            self.sock.settimeout(timeout)
            chunk = self.sock.recv(1 << 16)
        except (socket.timeout, BlockingIOError):
            return False
        except OSError as e:
            raise TransportError(f"recv failed: {e}")
        if not chunk:
            raise TransportError("worker closed the connection")
        self._buf.extend(chunk)
        return True

    def pump(self):
        """Drain already-arrived frames without blocking (heartbeats
        update liveness state; responses queue).  Raises
        :class:`TransportError` when the worker side is gone."""
        while self._fill(0.0):
            pass
        self._parse()

    # -- calls -----------------------------------------------------------
    def call(self, op, timeout=60.0, idempotent=False, ikey=None,
             **kwargs):
        """One synchronous RPC: send ``{op, cid, **kwargs}``, block (up
        to ``timeout`` wall seconds per attempt) for the response whose
        call id matches, and return its payload dict.  Worker-side
        typed errors re-raise here; a missed deadline raises
        :class:`RpcTimeout`, and — for ``idempotent`` calls when a
        retry policy is attached — is retried under a fresh call id
        with exponential backoff + jitter.  ``ikey`` (idempotency key)
        rides every attempt unchanged so the worker can dedup a true
        re-execution after a dropped ack.  Non-idempotent ops never
        retry here: the typed error surfaces to the router, which owns
        that recovery decision (breaker, fence, or kill)."""
        policy = self.retry if idempotent else None
        max_retries = int(policy.max_retries) if policy is not None else 0
        start = time.monotonic()
        attempt = 0
        while True:
            try:
                return self._call_once(op, timeout, ikey, kwargs)
            except RpcTimeout:
                if attempt >= max_retries:
                    raise
                attempt += 1
                delay = policy.delay(attempt)
                self.retries += 1
                if self.on_retry is not None:
                    self.on_retry(op, attempt, delay,
                                  time.monotonic() - start)
                if delay > 0:
                    policy.sleep_fn(delay)

    def _call_once(self, op, timeout, ikey, kwargs):
        self.pump()
        self._drop_stale(op)
        cid = self._call_seq
        self._call_seq += 1
        if self.wire is not None and self.wire.plan(
                "rpc_timeout", op=op, peer=self.peer) == "timeout":
            # deadline forced without sending: the cheap, wall-clock-
            # free way to exercise every timeout consumer (retry,
            # breaker) deterministically
            raise RpcTimeout(f"call {op!r} (cid {cid}): injected timeout")
        frame = {"op": op, "cid": cid}
        if ikey is not None:
            frame["ikey"] = ikey
        frame.update(kwargs)
        self._op_in_flight = op
        try:
            self._send(frame, op, timeout)
            deadline = time.monotonic() + timeout
            while True:
                resp = self._take(cid, op)
                if resp is not None:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    # the reply (possibly a partial frame already in
                    # ``_buf``) may still arrive late; mark the channel
                    # desynchronized — the buffered parser self-heals
                    # and ``_take``/``_drop_stale`` discard the stale
                    # reply by cid instead of corrupting the next call
                    self.desynced = True
                    raise RpcTimeout(f"call {op!r} (cid {cid}) timed "
                                     f"out after {timeout}s")
                self._fill(remaining)
                self._parse()
        finally:
            self._op_in_flight = None
        if resp.get("kind") == "err":
            self._raise_typed(op, resp)
        return resp

    def _take(self, cid, op):
        """Pop the response matching ``cid``; discard (and count) any
        stale frame — a late reply to a call that already timed out, or
        the extra copy of a duplicated delivery."""
        while self._inbox:
            resp = self._inbox.popleft()
            if not isinstance(resp, dict):
                raise TransportError(f"malformed response to {op!r}")
            rcid = resp.get("cid")
            if rcid is None or rcid == cid:
                self.desynced = False   # resynchronized on a live reply
                return resp
            self.stale_drops += 1
            if self.on_stale is not None:
                self.on_stale(op, "stale_resp")
        return None

    def _drop_stale(self, op):
        """Before a new call goes out, anything still in the inbox is a
        late reply to a timed-out predecessor — discard it (counted),
        where the pre-cid protocol had to declare the channel broken."""
        while self._inbox:
            self._inbox.popleft()
            self.stale_drops += 1
            if self.on_stale is not None:
                self.on_stale(op, "stale_resp")

    def _send(self, frame, op, timeout):
        """Outbound fault point: the chaos plane may delay, drop,
        duplicate, reorder, or tear this request frame."""
        wire = self.wire
        try:
            self.sock.settimeout(timeout)
        except OSError as e:
            raise TransportError(f"send failed: {e}")
        if wire is None:
            send_frame(self.sock, frame)
            return
        if wire.plan("wire_delay", op=op, peer=self.peer) == "delay":
            time.sleep(wire.delay_secs("wire_delay"))
        act = wire.plan("wire_send", op=op, peer=self.peer)
        if act == "drop":
            return                       # frame never leaves the host
        if act == "tear":
            # half a frame on the wire: the worker's stream desyncs and
            # dies with a typed corrupt-frame TransportError — a real
            # tear is unrecoverable for a length-prefixed stream
            data = json.dumps(pack_value(frame),
                              separators=(",", ":")).encode()
            buf = _HEADER.pack(len(data)) + data
            try:
                self.sock.sendall(buf[:max(1, len(buf) // 2)])
            except (OSError, ValueError) as e:
                raise TransportError(f"send failed: {e}")
            return
        if act == "reorder":
            self._send_hold = frame      # goes out after the NEXT frame
            return
        send_frame(self.sock, frame)
        if act == "dup":
            send_frame(self.sock, frame)     # exact duplicate delivery
        if self._send_hold is not None:
            held, self._send_hold = self._send_hold, None
            send_frame(self.sock, held)

    @staticmethod
    def _raise_typed(op, resp):
        etype = resp.get("etype", "")
        detail = resp.get("detail", "")
        if etype == "RequestRejected":
            from deepspeed_tpu.inference.robustness import RequestRejected
            raise RequestRejected(resp.get("req_id"),
                                  resp.get("reason", ""), detail)
        if etype == "WireVersionError":
            raise WireVersionError(resp.get("got"),
                                   resp.get("what", op))
        raise WorkerError(f"worker error in {op!r}: "
                          f"{etype or 'Exception'}: {detail}")

    def close(self):
        self.closed = True
        try:
            self.sock.close()
        except OSError:
            pass
