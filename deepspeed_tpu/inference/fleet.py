"""Fleet front-end: prefix-affinity routing over N serving replicas.

Every PR so far hardens ONE :class:`ServingEngine`; the ROADMAP north
star needs N of them.  :class:`FleetRouter` owns multiple engines as
in-process fault domains and gives them a single engine-shaped surface
(``submit`` → ``step`` → ``finished`` / ``pop_terminated`` / ``drain``
/ ``health`` / ``leak_report``), built on three ideas:

* **Prefix-affinity routing.**  The routing key is the same rolling
  blake2b chain the prefix cache uses for content-hashed KV pages
  (``inference/prefix_cache.py``), computed over the first
  ``route_prefix_tokens`` prompt tokens — so requests that share a
  prefix land on the replica that already holds those pages, and
  per-replica hit rates stay at single-engine levels under fleet
  traffic.  Replica choice is rendezvous (highest-random-weight)
  hashing: each replica scores ``blake2b(key ‖ replica_id)`` and the
  highest healthy score wins, so a dead replica remaps ONLY its own
  keys and a respawn (same replica id, new epoch) re-takes its ring
  slot.
* **Supervision.**  A sweep every ``health_interval`` steps consults
  the fault injector (``replica_kill``), each replica's
  ``leak_report()`` (page/trace leaks ⇒ fence) and ``health()``
  (``recompile_storm`` ⇒ fence).  A *fenced* replica is drained
  through the graceful ``drain()`` path — finished work is delivered,
  shed work is redispatched; a *killed* replica is dropped abruptly
  and every request it owned is redispatched from scratch.  Either
  way the replica respawns with a fresh epoch (the
  :class:`RequestTracer` namespace, so a redispatched id re-admitted
  on the new engine cannot read as a double admit).
* **Zero lost requests.**  The fleet keeps its own request table and a
  fleet-level :class:`RequestTracer`: every submitted id ends in
  exactly one of the frozen trace terminals — delivered via
  ``finished``, or typed into ``pop_terminated()`` (deadline, shed,
  redispatch budget exhausted).  ``leak_report()`` audits that
  bookkeeping the same way the engine audits pages.

Dispatch atomicity follows the ``page_alloc`` idiom: the
``route_dispatch`` injector site is consulted BEFORE the routing table
or any engine mutates, so a faulted dispatch leaves the request exactly
where it was (pending) and it retries on the next step.

Scaling rides ``elasticity.ReplicaAutoscaler``: aggregated queue depth,
shed deltas, and the tightest free-page fraction feed hysteretic
one-replica-at-a-time decisions between ``min_replicas`` and
``max_replicas``.

**Disaggregated prefill/decode pools** (``serving.fleet.roles``; default
off = the unified behaviour above, bit-for-bit).  Prefill replicas run
prompts to the first token and capture a ``PrefillHandoff`` (pages
pinned at the source); the router then migrates the KV pages to a
decode replica as a TRANSACTION on the ``page_alloc`` atomicity idiom:
the ``page_migrate`` site is consulted before the transfer and
``migrate_commit`` before the routing table flips, the transfer is
content-addressed so pages already resident in the destination's prefix
cache are skipped (a hot shared prefix migrates once per decode
replica, not once per request), a per-step ``page_transfer_budget``
bounds the router's migration bandwidth, and source pages stay pinned
until the destination commits — a kill of EITHER side mid-migration
leaves the request redispatchable from one consistent copy.  If the
prefill pool drains to zero healthy replicas, dispatch degrades to
local (monolithic) prefill on the decode pool instead of stalling
admissions, and autoscaling becomes per-pool
(``elasticity.RoleAwareAutoscaler``).
"""

import hashlib
import os
import socket
import subprocess
import sys
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import numpy as np

from deepspeed_tpu.comm.quantize import QuantizedPayload
from deepspeed_tpu.elasticity.elastic_agent import (ReplicaAutoscaler,
                                                    RoleAwareAutoscaler)
from deepspeed_tpu.inference.robustness import (
    REJECT_BAD_REQUEST, REJECT_BAD_SAMPLING, REJECT_DRAINING,
    REJECT_DUPLICATE, REJECT_INFEASIBLE, REJECT_OVERSIZED, SHED_DEADLINE,
    SHED_DRAIN, RequestRejected, RequestResult, RequestTracer)
from deepspeed_tpu.inference.transport import (RpcChannel, RpcTimeout,
                                               TransportError,
                                               WireFaultInjector)
from deepspeed_tpu.monitor.telemetry import get_telemetry
from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel
from deepspeed_tpu.runtime.resilience import FaultInjector, RetryPolicy
from deepspeed_tpu.utils.logging import logger

# The frozen fleet/* event vocabulary.  scripts/check_telemetry_schema.py
# duplicates this tuple on purpose (the checker must not import the
# package); tests/unit/test_telemetry_schema.py diffs the two.
FLEET_EVENTS = (
    "fleet/spawn", "fleet/respawn", "fleet/route", "fleet/spill",
    "fleet/dispatch_fault", "fleet/redispatch", "fleet/kill",
    "fleet/fence", "fleet/drain", "fleet/shed",
    "fleet/scale_up", "fleet/scale_down",
    "fleet/migrate_start", "fleet/migrate_commit", "fleet/migrate_fault",
    "fleet/migrate_abort", "fleet/local_prefill",
    "fleet/worker_lost",
    "fleet/retry", "fleet/breaker_open", "fleet/breaker_close",
    "fleet/dup_call_dropped",
)

# The frozen fleet/* GAUGE vocabulary (registry snapshots in health(),
# gauge events at breaker transitions).  Mirrored in the checker like
# FLEET_EVENTS; gauge names are deliberately disjoint from event names.
FLEET_GAUGES = (
    "fleet/replicas", "fleet/healthy", "fleet/pending",
    "fleet/queue_depth", "fleet/redispatches", "fleet/workers_lost",
    "fleet/heartbeat_age_s", "fleet/migrating", "fleet/migrated_pages",
    "fleet/dedup_skipped_pages", "fleet/prefill_queue_depth",
    "fleet/decode_queue_depth",
    "fleet/breaker_open_replicas", "fleet/breaker_opens",
    "fleet/breaker_closes", "fleet/retries", "fleet/dup_calls_dropped",
)

# the closed set of replica supervision states (docs/serving.md);
# "breaker_open" fences routing like "fenced" but keeps the PROCESS
# alive — the circuit breaker's half-open probe decides its fate
REPLICA_STATES = ("healthy", "fenced", "dead", "breaker_open")

# the closed set of replica roles: a roleless fleet is all-"unified";
# a disaggregated fleet (serving.fleet.roles.enabled) splits into a
# prefill pool and a decode pool with KV-page migration between them
REPLICA_ROLES = ("unified", "prefill", "decode")

# typed shed reason: the per-request redispatch budget ran out — the
# request bounced off too many dying/overloaded replicas
SHED_REDISPATCH_BUDGET = "redispatch_budget"

# engine rejection reasons that indict the REQUEST, not the replica —
# spilling these to another replica would just collect the same verdict,
# so the fleet terminates the request instead of retrying forever
_FATAL_REJECTS = (REJECT_BAD_REQUEST, REJECT_BAD_SAMPLING,
                  REJECT_OVERSIZED, REJECT_INFEASIBLE)


class FleetRolesConfig(DeepSpeedConfigModel):
    """The ``serving.fleet.roles`` block (docs/config-json.md):
    disaggregated prefill/decode pools with transactional KV-page
    migration.  Disabled by default — a roleless fleet is bit-for-bit
    the unified :class:`FleetRouter`.  When enabled, the pool sizes here
    REPLACE ``serving.fleet.replicas``/``min_replicas``/``max_replicas``
    (each pool scales independently)."""

    enabled = False
    prefill_replicas = 1            # initial prefill-pool size
    decode_replicas = 2             # initial decode-pool size
    min_prefill_replicas = 1        # per-pool supervision floors /
    max_prefill_replicas = 4        # autoscale ceilings
    min_decode_replicas = 1
    max_decode_replicas = 8
    page_transfer_budget = 0        # pages migrated per fleet step
    #                                 (0 = unlimited; >=1 migration per
    #                                 step always proceeds — no livelock)
    migrate_backoff_steps = 2       # fleet steps a faulted migration
    #                                 waits before retrying

    def _validate(self):
        if not self.enabled:
            return
        for k in ("prefill_replicas", "decode_replicas",
                  "min_prefill_replicas", "max_prefill_replicas",
                  "min_decode_replicas", "max_decode_replicas"):
            if int(getattr(self, k)) < 1:
                raise ValueError(f"serving.fleet.roles.{k} must be >= 1")
        for k in ("page_transfer_budget", "migrate_backoff_steps"):
            if int(getattr(self, k)) < 0:
                raise ValueError(f"serving.fleet.roles.{k} must be >= 0")
        for role in ("prefill", "decode"):
            lo = int(getattr(self, f"min_{role}_replicas"))
            hi = int(getattr(self, f"max_{role}_replicas"))
            n = int(getattr(self, f"{role}_replicas"))
            if hi < lo:
                raise ValueError(
                    f"serving.fleet.roles.max_{role}_replicas must be "
                    f">= min_{role}_replicas")
            if not lo <= n <= hi:
                raise ValueError(
                    f"serving.fleet.roles.{role}_replicas must lie in "
                    f"[min_{role}_replicas, max_{role}_replicas]")


class RpcRetryConfig(DeepSpeedConfigModel):
    """The ``serving.fleet.transport.retry`` block (docs/config-json.md):
    exponential backoff + jitter applied by :class:`RpcChannel` to
    IDEMPOTENT ops after an :class:`RpcTimeout`.  Mutating ops carry
    idempotency keys the worker dedups, so a retry after a dropped ack
    replays the recorded outcome instead of double-applying.  Non-
    idempotent ops (``step``, the pops, ``drain``) never retry here —
    their timeout feeds the router's circuit breaker instead."""

    max_retries = 2                 # attempts AFTER the first (0 = off)
    backoff_s = 0.05                # first-retry backoff
    backoff_max_s = 2.0             # exponential cap
    jitter = 0.25                   # backoff *= 1 + jitter·U[0,1)
    seed = 0xD5                     # jitter rng seed (deterministic)

    def _validate(self):
        if int(self.max_retries) < 0:
            raise ValueError(
                "serving.fleet.transport.retry.max_retries must be >= 0")
        for k in ("backoff_s", "backoff_max_s"):
            if float(getattr(self, k)) < 0:
                raise ValueError(
                    f"serving.fleet.transport.retry.{k} must be >= 0")
        if not (0.0 <= float(self.jitter) <= 1.0):
            raise ValueError(
                "serving.fleet.transport.retry.jitter must be in [0, 1]")


class FleetTransportConfig(DeepSpeedConfigModel):
    """The ``serving.fleet.transport`` block (docs/config-json.md):
    where replicas live.  ``mode="inprocess"`` (the default) keeps the
    fleet bit-for-bit the in-process router; ``mode="subprocess"`` hosts
    one ``ServingEngine`` per OS process (``inference/fleet_worker.py``)
    behind the framed socket transport (``inference/transport.py``) with
    heartbeat-based liveness: a worker that misses
    ``heartbeat_deadline_s`` of heartbeats is declared dead, its process
    killed, its requests redispatched, and its ring slot respawned after
    ``respawn_backoff_s`` (the backoff bounds respawn storms when the
    fault is environmental, not replica-local)."""

    mode = "inprocess"              # "inprocess" | "subprocess"
    heartbeat_interval_s = 1.0      # worker beat period
    heartbeat_deadline_s = 10.0     # missed-beat window before death
    respawn_backoff_s = 0.0         # wait before respawning a lost slot
    call_timeout_s = 120.0          # per-RPC wall budget (steady state)
    init_timeout_s = 120.0          # wall budget for the worker's init
    #                                 RPC alone (engine build + jit
    #                                 warm-up) — chaos scenarios shrink
    #                                 call_timeout_s without breaking
    #                                 worker boot
    retry = {}                      # RpcRetryConfig (idempotent-op retry)
    chaos = {}                      # WireFaultInjector spec + "seed" —
    #                                 deterministic frame faults; empty
    #                                 = no injection (zero overhead)
    # per-replica circuit breaker: consecutive RPC timeouts trip it
    # (closed → open → half-open probe → closed); a tripped breaker
    # fences routing WITHOUT killing a possibly-just-slow worker
    breaker_failures = 3            # consecutive timeouts to open
    #                                 (0 = off: every timeout is a
    #                                 worker-lost, pre-breaker behaviour)
    breaker_open_s = 1.0            # cooldown before the half-open probe
    breaker_open_max_s = 30.0       # cap for the doubling cooldown
    breaker_flap_window_s = 30.0    # re-open this soon after a close ⇒
    #                                 flapping link: cooldown doubles, so
    #                                 a flap cannot probe/respawn-storm
    breaker_probes = 3              # failed half-open probes before the
    #                                 replica is finally declared lost
    breaker_probe_timeout_s = 5.0   # wall budget for one half-open ping

    def _validate(self):
        if self.mode not in ("inprocess", "subprocess"):
            raise ValueError(
                "serving.fleet.transport.mode must be 'inprocess' or "
                f"'subprocess', got {self.mode!r}")
        if not isinstance(self.retry, RpcRetryConfig):
            self.retry = RpcRetryConfig(self.retry or {})
        if self.chaos:
            WireFaultInjector(dict(self.chaos))  # site names validated
        for k in ("heartbeat_interval_s", "heartbeat_deadline_s",
                  "respawn_backoff_s", "call_timeout_s",
                  "init_timeout_s",
                  "breaker_open_s", "breaker_open_max_s",
                  "breaker_flap_window_s", "breaker_probe_timeout_s"):
            if float(getattr(self, k)) < 0:
                raise ValueError(f"serving.fleet.transport.{k} must "
                                 "be >= 0")
        for k in ("breaker_failures", "breaker_probes"):
            if int(getattr(self, k)) < 0:
                raise ValueError(f"serving.fleet.transport.{k} must "
                                 "be >= 0")
        if float(self.breaker_open_max_s) < float(self.breaker_open_s):
            raise ValueError(
                "serving.fleet.transport.breaker_open_max_s must be "
                ">= breaker_open_s")
        if float(self.call_timeout_s) <= 0:
            raise ValueError(
                "serving.fleet.transport.call_timeout_s must be > 0")
        if float(self.heartbeat_deadline_s) < \
                float(self.heartbeat_interval_s):
            raise ValueError(
                "serving.fleet.transport.heartbeat_deadline_s must be "
                ">= heartbeat_interval_s")


class FleetConfig(DeepSpeedConfigModel):
    """The ``serving.fleet`` config block (docs/config-json.md)."""

    replicas = 2                    # initial replica count
    min_replicas = 1                # supervision floor (respawn target)
    max_replicas = 8                # autoscale ceiling
    health_interval = 8             # fleet steps between supervision sweeps
    redispatch_max = 3              # per-request redispatch budget
    route_prefix_tokens = 0         # routing-key prefix len (0 = page_size)
    autoscale = False               # ReplicaAutoscaler on aggregate gauges
    scale_up_queue_per_replica = 8
    scale_down_queue_per_replica = 1
    free_page_low_frac = 0.1
    cooldown_sweeps = 8
    fault_injection = {}            # FaultInjector spec (fleet sites)
    roles = {}                      # FleetRolesConfig (disaggregation)
    transport = {}                  # FleetTransportConfig (process mode)
    # autotuning-v2: path to a persisted autotuner overlay
    # (autotuning/overlay.py).  When set, the autoscaler thresholds above
    # are DEFAULTS only — any threshold the overlay's serving.fleet
    # fragment carries wins, so scale policy comes from measured trials
    # rather than hand-set numbers.
    overlay_path = None

    def _validate(self):
        if not isinstance(self.roles, FleetRolesConfig):
            self.roles = FleetRolesConfig(self.roles or {})
        if not isinstance(self.transport, FleetTransportConfig):
            self.transport = FleetTransportConfig(self.transport or {})
        for k in ("replicas", "min_replicas", "health_interval"):
            if int(getattr(self, k)) < 1:
                raise ValueError(f"serving.fleet.{k} must be >= 1")
        for k in ("redispatch_max", "route_prefix_tokens",
                  "scale_up_queue_per_replica",
                  "scale_down_queue_per_replica", "cooldown_sweeps"):
            if int(getattr(self, k)) < 0:
                raise ValueError(f"serving.fleet.{k} must be >= 0")
        if int(self.max_replicas) < int(self.min_replicas):
            raise ValueError(
                "serving.fleet.max_replicas must be >= min_replicas")
        if not (int(self.min_replicas) <= int(self.replicas)
                <= int(self.max_replicas)):
            raise ValueError("serving.fleet.replicas must lie in "
                             "[min_replicas, max_replicas]")
        if not (0.0 <= float(self.free_page_low_frac) < 1.0):
            raise ValueError(
                "serving.fleet.free_page_low_frac must be in [0, 1)")


def _key(k):
    """Hashable req_id from a wire-decoded value (tuples cross the wire
    as lists)."""
    return tuple(k) if isinstance(k, list) else k


class InProcessReplicaHandle:
    """The default replica handle: a thin shim over a local
    :class:`ServingEngine`.  Every method is direct delegation in the
    exact call order the pre-handle router used, so
    ``transport.mode="inprocess"`` stays bit-for-bit the in-process
    fleet.  ``last_heartbeat`` is None — in-process replicas are exempt
    from heartbeat liveness (they cannot die without the router dying
    with them)."""

    mode = "inprocess"
    last_heartbeat = None

    def __init__(self, engine):
        self.engine = engine

    # -- engine surface --------------------------------------------------
    def add_request(self, req_id, prompt, ikey=None, **kwargs):
        # ikey is the wire transport's dedup token; in-process delivery
        # is exactly-once by construction, so it is ignored here
        self.engine.add_request(req_id, prompt, **kwargs)

    def step(self):
        return self.engine.step()

    def pop_terminated(self):
        return self.engine.pop_terminated()

    def pop_prefilled(self):
        return self.engine.pop_prefilled()

    def release_handoff(self, req_id):
        return self.engine.release_handoff(req_id)

    def resident_prefix(self, prompt):
        cache = self.engine.prefix_cache
        return cache.resident_prefix(prompt) if cache is not None else []

    def export_payload(self, page_ids):
        """Export + wire-encode the non-shared prompt pages.  Returns
        ``(payload, wire_frac)`` — the payload in whatever form this
        transport carries (here the live object) and the quantized
        wire-byte fraction (1.0 when the codec passed it through)."""
        if not page_ids:
            return None, 1.0
        payload = self.engine.comm_quant.encode_payload(
            self.engine.export_pages(page_ids))
        if isinstance(payload, QuantizedPayload):
            return payload, payload.wire_bytes / max(payload.raw_bytes, 1)
        return payload, 1.0

    def import_request(self, handoff, payload=None, shared_pages=(),
                       deadline_s=None, ikey=None):
        return self.engine.import_request(handoff, payload=payload,
                                          shared_pages=shared_pages,
                                          deadline_s=deadline_s)

    def commit_import(self, req_id, ikey=None):
        self.engine.commit_import(req_id)

    def cancel_import(self, req_id):
        return self.engine.cancel_import(req_id)

    def drain(self):
        return self.engine.drain()

    def generate(self, prompts, max_new_tokens=8):
        return self.engine.generate(prompts,
                                    max_new_tokens=max_new_tokens)

    def leak_report(self):
        return self.engine.leak_report()

    def health(self):
        return self.engine.health()

    # -- load surface (the router's spill / autoscale inputs) ------------
    @property
    def queue_depth(self):
        return len(self.engine.queue)

    @property
    def n_active(self):
        return self.engine.n_active

    @property
    def load(self):
        return len(self.engine.queue) + self.engine.n_active

    @property
    def free_pages(self):
        return self.engine.alloc.free_page_count

    @property
    def num_pages(self):
        return self.engine.alloc.num_pages

    @property
    def shed_count(self):
        return self.engine.stats["shed"]

    @property
    def prefix_hit_rate(self):
        cache = self.engine.prefix_cache
        return cache.snapshot()["hit_rate"] if cache is not None else None

    @property
    def page_size(self):
        return self.engine.page_size

    @property
    def kv_page_bytes(self):
        return self.engine.kv_page_bytes

    # -- lifecycle -------------------------------------------------------
    def pump(self):
        """No async frames to drain in-process."""

    def close(self, kill=False):
        """Nothing to tear down — the engine is garbage-collected."""


class SubprocessReplicaHandle:
    """A replica hosted in its own OS process (a REAL fault domain).

    The constructor spawns ``python -m deepspeed_tpu.inference.
    fleet_worker`` over one end of a socketpair and drives it through
    the framed RPC protocol (``inference/transport.py``).  The factory
    ``spec`` is a dotted path + kwargs — a deterministic recipe, so a
    respawn rebuilds the exact same engine.  Load state (queue depth,
    active slots, free pages, prefix hit rate, shed count) piggybacks on
    every RPC response and is read from cache, so the router's
    spill-order sort and autoscale sweep cost no extra round trips.
    Liveness is the worker's asynchronous heartbeat stream, surfaced as
    :attr:`last_heartbeat` (router-clock receipt stamps via the
    channel); a torn connection raises :class:`TransportError` from
    whatever call hits it first, which the router maps to the same
    recovery path as a missed-heartbeat death."""

    mode = "subprocess"

    def __init__(self, spec, replica_id, epoch, transport_cfg,
                 telemetry=None, rank=0, clock=None, wire=None,
                 retry=None, on_retry=None, on_stale=None):
        self.replica_id = replica_id
        self.epoch = epoch
        self.engine = None      # no in-process engine behind this handle
        self._timeout = float(transport_cfg.call_timeout_s)
        self._load = {}
        self._on_stale_cb = on_stale
        self._breaker = None    # attached by the router at spawn
        # cumulative-ack bookkeeping: ids delivered by the last step /
        # pop response, confirmed back to the worker on the NEXT call of
        # the same op so its result buffer can prune (a lost response is
        # simply redelivered — nothing finished can vanish on the wire)
        self._ack_done: List[Any] = []
        self._ack_term: List[Any] = []
        self._ack_hand: List[Any] = []
        parent, child = socket.socketpair()
        # the worker must be able to import this package even when the
        # router's cwd is not the source root — export the package
        # parent on PYTHONPATH
        import deepspeed_tpu
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(deepspeed_tpu.__file__)))
        env = dict(os.environ)
        env["PYTHONPATH"] = pkg_root + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH")
            else "")
        try:
            self.proc = subprocess.Popen(
                [sys.executable, "-m",
                 "deepspeed_tpu.inference.fleet_worker",
                 "--fd", str(child.fileno())],
                pass_fds=(child.fileno(),), env=env)
        finally:
            child.close()
        self.chan = RpcChannel(parent, clock=clock, wire=wire,
                               retry=retry, peer=replica_id)
        if on_retry is not None:
            self.chan.on_retry = \
                lambda op, a, d, el: on_retry(replica_id, op, a, d, el)
        if on_stale is not None:
            self.chan.on_stale = \
                lambda op, kind: on_stale(replica_id, op, kind)
        init_timeout = max(self._timeout,
                           float(transport_cfg.init_timeout_s))
        try:
            init = self.chan.call(
                "init", timeout=init_timeout, rid=replica_id,
                epoch=epoch, spec=spec,
                hb_interval_s=float(transport_cfg.heartbeat_interval_s),
                telemetry=telemetry, rank=int(rank))
        except Exception:
            self.close(kill=True)
            raise
        self.page_size = int(init["page_size"])
        self.kv_page_bytes = int(init["kv_page_bytes"])
        self._load = dict(init.get("load") or {})

    def _call(self, op, _idempotent=False, _ikey=None, **kwargs):
        resp = self.chan.call(op, timeout=self._timeout,
                              idempotent=_idempotent, ikey=_ikey,
                              **kwargs)
        if self._breaker is not None:
            self._breaker.record_success()  # a reply = the wire works
        if resp.get("dup") and self._on_stale_cb is not None:
            # the worker replayed a cached mutation for a retried
            # idempotency key — the first execution's ack was lost
            self._on_stale_cb(self.replica_id, op, "ikey_replay")
        load = resp.get("load")
        if load:
            self._load = load
        return resp

    # -- engine surface --------------------------------------------------
    def add_request(self, req_id, prompt, ikey=None, **kwargs):
        self._call("add_request", _idempotent=True, _ikey=ikey,
                   req_id=req_id,
                   prompt=[int(t) for t in prompt], kwargs=kwargs)

    def step(self):
        done = {_key(rid): toks for rid, toks in
                self._call("step", ack=self._ack_done)["done"]}
        self._ack_done = list(done)
        return done

    def pop_terminated(self):
        out = {}
        for rid, res in self._call("pop_terminated",
                                   ack=self._ack_term)["results"]:
            rid = _key(rid)
            out[rid] = RequestResult(
                rid, res["status"], res["reason"],
                tokens=list(res["tokens"]),
                n_generated=int(res["n_generated"]),
                detail=res.get("detail", ""))
        self._ack_term = list(out)
        return out

    def pop_prefilled(self):
        from deepspeed_tpu.inference.serving import PrefillHandoff
        out = {_key(rid): PrefillHandoff.from_wire(wire)
               for rid, wire in self._call(
                   "pop_prefilled", ack=self._ack_hand)["handoffs"]}
        self._ack_hand = list(out)
        return out

    def release_handoff(self, req_id):
        return bool(self._call("release_handoff", _idempotent=True,
                               req_id=req_id)["ok"])

    def resident_prefix(self, prompt):
        return self._call("resident_prefix", _idempotent=True,
                          prompt=[int(t) for t in prompt])["pages"]

    def export_payload(self, page_ids):
        """See :meth:`InProcessReplicaHandle.export_payload`; here the
        payload stays in WIRE form (the worker already ran the int8
        codec), ready to forward to the destination worker."""
        if not page_ids:
            return None, 1.0
        resp = self._call("export_payload", _idempotent=True,
                          pages=[int(p) for p in page_ids])
        payload = resp["payload"]
        if resp.get("quant") and payload is not None:
            return payload, (int(payload["wire_bytes"]) /
                             max(int(payload["raw_bytes"]), 1))
        return payload, 1.0

    def import_request(self, handoff, payload=None, shared_pages=(),
                       deadline_s=None, ikey=None):
        return bool(self._call(
            "import_request", _idempotent=True, _ikey=ikey,
            handoff=handoff.to_wire(), payload=payload,
            shared_pages=[int(p) for p in shared_pages],
            deadline_s=deadline_s)["ok"])

    def commit_import(self, req_id, ikey=None):
        """The explicit commit ack: raises :class:`TransportError` when
        the connection TEARS before the worker acknowledges — the
        uncommitted import died with the process, so the router rolls
        back exactly like an injected ``migrate_commit`` fault.  A mere
        :class:`RpcTimeout` is different: the commit may have landed
        with only the ack lost, so the call is idempotent-retryable
        under ``ikey`` and the worker replays a committed outcome
        instead of double-committing."""
        self._call("commit_import", _idempotent=True, _ikey=ikey,
                   req_id=req_id)

    def cancel_import(self, req_id):
        return bool(self._call("cancel_import", _idempotent=True,
                               req_id=req_id)["ok"])

    def drain(self):
        resp = self._call("drain")
        return {"finished": {_key(rid): toks
                             for rid, toks in resp["finished"]},
                "shed": [_key(r) for r in resp["shed"]],
                "steps": int(resp["steps"]),
                "health": resp["health"]}

    def leak_report(self):
        return self._call("leak_report", _idempotent=True)["leaks"]

    def health(self):
        return self._call("health", _idempotent=True)["health"]

    def ping(self, timeout=None):
        """Liveness probe (the breaker's half-open check): one round
        trip under its own wall budget, no engine work."""
        self.chan.call("ping",
                       timeout=self._timeout if timeout is None
                       else float(timeout))

    def generate(self, prompts, max_new_tokens=8):
        """Warm-up helper for benches/tests (mirrors the engine API)."""
        return self._call(
            "generate",
            prompts=[[int(t) for t in p] for p in prompts],
            max_new_tokens=int(max_new_tokens))["out"]

    # -- load surface (cached from response piggybacks) ------------------
    @property
    def queue_depth(self):
        return int(self._load.get("queue", 0))

    @property
    def n_active(self):
        return int(self._load.get("active", 0))

    @property
    def load(self):
        return self.queue_depth + self.n_active

    @property
    def free_pages(self):
        return int(self._load.get("free_pages", 0))

    @property
    def num_pages(self):
        return int(self._load.get("num_pages", 1))

    @property
    def shed_count(self):
        return int(self._load.get("shed", 0))

    @property
    def prefix_hit_rate(self):
        return self._load.get("hit_rate")

    # -- liveness / lifecycle --------------------------------------------
    @property
    def last_heartbeat(self):
        return self.chan.last_heartbeat

    def pump(self):
        self.chan.pump()

    def close(self, kill=False):
        """Tear the worker down: graceful (``shutdown`` op, then
        SIGTERM fallback) or abrupt (SIGKILL — the fence vs kill split,
        at the process level).  Always reaps the child."""
        proc = getattr(self, "proc", None)
        if not kill and proc is not None and proc.poll() is None and \
                not self.chan.closed:
            try:
                self.chan.call("shutdown", timeout=5.0)
            except Exception:
                pass
        self.chan.close()
        if proc is not None:
            if proc.poll() is None:
                try:
                    proc.kill() if kill else proc.terminate()
                except OSError:
                    pass
            try:
                proc.wait(timeout=10.0)
            except Exception:
                pass


@dataclass
class _FleetRequest:
    """Fleet-side bookkeeping for one submitted request.  ``state`` walks
    pending → dispatched → (pending …) → finished | terminated; the
    dispatch counter enforces the redispatch budget.  Under a
    role-specialized fleet a prefill-phase request additionally passes
    through ``migrating`` (handoff captured on the source replica —
    ``replica_id`` — and queued for transfer to a decode replica) before
    returning to ``dispatched`` on its decode replica at commit."""
    req_id: Any
    prompt: List[int]
    kwargs: Dict[str, Any]
    route_key: bytes
    deadline: float = 0.0           # absolute fleet-clock time; 0 = none
    state: str = "pending"
    replica_id: Optional[str] = None
    dispatches: int = 0
    handoff: Any = None             # PrefillHandoff while ``migrating``
    migrate_after: int = 0          # earliest fleet step to (re)try


@dataclass
class _Replica:
    replica_id: str
    epoch: str
    engine: Any                 # None for subprocess-backed replicas
    handle: Any = None          # ReplicaHandle (the router's only surface)
    state: str = "healthy"
    role: str = "unified"
    breaker: Any = None         # CircuitBreaker (subprocess mode only)


class CircuitBreaker:
    """Per-replica circuit breaker over RPC timeouts: ``closed`` →
    (``breaker_failures`` consecutive timeouts) → ``open`` →
    (``breaker_open_s`` cooldown) → ``half_open`` probe → ``closed`` on
    success, back to ``open`` with a doubled cooldown on failure, and
    finally worker-lost after ``breaker_probes`` failed probes.

    Distinct from heartbeat death on purpose: a slow or lossy link
    produces timeouts while the worker is perfectly alive — fencing
    routing (and letting the probe decide) preserves the worker's warm
    prefix cache and avoids respawn churn.  Hysteresis: re-opening
    within ``breaker_flap_window_s`` of a close doubles the cooldown
    (capped), so a flapping link backs off instead of probe-storming."""

    def __init__(self, tcfg, clock):
        self.failures_limit = int(tcfg.breaker_failures)
        self.base_cooldown = float(tcfg.breaker_open_s)
        self.max_cooldown = float(tcfg.breaker_open_max_s)
        self.flap_window = float(tcfg.breaker_flap_window_s)
        self.max_probes = int(tcfg.breaker_probes)
        self._clock = clock
        self.state = "closed"
        self.consecutive = 0        # timeout run length while closed
        self.opens = 0
        self.closes = 0
        self.probe_failures = 0     # within the CURRENT open episode
        self.cooldown_s = self.base_cooldown
        self._open_until = 0.0
        self._last_close = None

    @property
    def enabled(self):
        return self.failures_limit > 0

    def record_success(self):
        if self.state == "closed":
            self.consecutive = 0

    def record_failure(self):
        """Count one timeout; True when it should OPEN the breaker."""
        if self.state != "closed" or not self.enabled:
            return False
        self.consecutive += 1
        return self.consecutive >= self.failures_limit

    def open(self):
        """Trip.  Returns the cooldown armed before the half-open
        probe (doubled when re-opening inside the flap window)."""
        now = self._clock()
        cooldown = self.base_cooldown
        if self._last_close is not None and \
                now - self._last_close < self.flap_window:
            cooldown = min(max(self.cooldown_s, self.base_cooldown) * 2,
                           self.max_cooldown)
        self.cooldown_s = cooldown
        self.state = "open"
        self.opens += 1
        self.probe_failures = 0
        self._open_until = now + cooldown
        return cooldown

    def probe_due(self):
        """True once the cooldown has elapsed (enters ``half_open``)."""
        if self.state == "open" and self._clock() >= self._open_until:
            self.state = "half_open"
        return self.state == "half_open"

    def probe_failed(self):
        """Book one failed half-open probe, re-arm a doubled cooldown;
        True when the probe budget is spent (escalate to worker-lost)."""
        self.probe_failures += 1
        self.state = "open"
        self.cooldown_s = min(max(self.cooldown_s,
                                  self.base_cooldown) * 2,
                              self.max_cooldown)
        self._open_until = self._clock() + self.cooldown_s
        return self.probe_failures >= self.max_probes

    def close(self):
        self.state = "closed"
        self.closes += 1
        self.consecutive = 0
        self.probe_failures = 0
        self._last_close = self._clock()

    def snapshot(self):
        return {"state": self.state, "consecutive": self.consecutive,
                "opens": self.opens, "closes": self.closes,
                "probe_failures": self.probe_failures,
                "cooldown_s": round(self.cooldown_s, 3)}


class FleetRouter:
    """N in-process :class:`ServingEngine` fault domains behind one
    engine-shaped front-end.

    ``engine_factory(replica_id, epoch)`` builds one replica; the factory
    MUST pass ``replica_epoch=epoch`` through to the engine so respawned
    replicas book traces under a fresh namespace.  Every engine should be
    built from the same (model, params, config) for bit-identical
    redispatch — a request's output depends only on (prompt, sampling
    params, seed), never on which replica or batch served it.
    """

    def __init__(self, engine_factory, fleet=None, injector=None,
                 telemetry=None, clock=None, worker_telemetry=None):
        cfg = fleet if isinstance(fleet, FleetConfig) \
            else FleetConfig(fleet or {})
        self.fleet = cfg
        self._factory = engine_factory
        self._clock = clock if clock is not None else time.monotonic
        self._telemetry = telemetry
        # subprocess mode: telemetry config dict forwarded to each worker
        # (rank-stamped shard sink — the router stays rank 0)
        self._worker_telemetry = worker_telemetry
        self._worker_seq = 0            # next worker telemetry rank
        self._respawn_after = {}        # rid -> clock time respawn allowed
        self._engine_steps = 0          # replica steps actually executed
        self.injector = injector if injector is not None \
            else FaultInjector.from_config(cfg.fault_injection)
        # the chaos plane: ONE seeded frame-fault injector shared by
        # every replica channel, so a whole campaign replays from
        # (spec, seed) alone — counters are global across the fleet
        self.wire_injector = WireFaultInjector.from_config(
            dict(cfg.transport.chaos) if cfg.transport.chaos else None)
        rcfg = cfg.transport.retry
        self._retry_policy = RetryPolicy(
            max_retries=int(rcfg.max_retries),
            backoff_secs=float(rcfg.backoff_s),
            backoff_max_secs=float(rcfg.backoff_max_s),
            jitter=float(rcfg.jitter),
            seed=int(rcfg.seed)) \
            if int(rcfg.max_retries) > 0 else None
        self.replicas: Dict[str, _Replica] = {}
        self.requests: Dict[Any, _FleetRequest] = {}
        self.pending = deque()          # req_ids awaiting (re)dispatch
        self.finished: Dict[Any, List[int]] = {}
        self.terminated: Dict[Any, RequestResult] = {}
        self.tracer = RequestTracer(clock=self._clock)
        self.draining = False
        self.steps = 0
        self.stats = {"submitted": 0, "finished": 0, "terminated": 0,
                      "shed": 0, "deadline": 0, "redispatches": 0,
                      "spills": 0, "dispatch_faults": 0, "kills": 0,
                      "fences": 0, "respawns": 0, "scale_ups": 0,
                      "scale_downs": 0,
                      "migrations": 0, "migrated_pages": 0,
                      "dedup_skipped_pages": 0, "migrate_bytes": 0,
                      "migrate_bytes_saved": 0,
                      "migrate_quant_bytes_saved": 0, "migrate_faults": 0,
                      "migrate_commit_faults": 0, "migrate_aborts": 0,
                      "local_prefills": 0, "workers_lost": 0,
                      "retries": 0, "rpc_timeouts": 0,
                      "breaker_opens": 0, "breaker_closes": 0,
                      "dup_calls_dropped": 0}
        self._gens: Dict[str, int] = {}     # replica_id -> spawn generation
        self._role_of: Dict[str, str] = {}  # replica_id -> role (sticky
        #                                     across respawns, so a dead
        #                                     ring slot re-takes its pool)
        self._next_rid = 0
        self._next_rids = {"prefill": 0, "decode": 0}
        self._roles_enabled = bool(cfg.roles.enabled)
        self.migrations = deque()       # req_ids in the "migrating" state
        self._last_shed_total = 0
        self._last_shed_by = {"prefill": 0, "decode": 0}
        # autoscaler thresholds: config values are the DEFAULTS; with
        # serving.fleet.overlay_path set, whatever the tuned overlay's
        # serving.fleet fragment carries wins (autotuning-v2 — scale
        # policy from measured trials, not hand-set numbers)
        thresholds = self._autoscaler_thresholds(cfg)
        if self._roles_enabled:
            self._targets = {"prefill": int(cfg.roles.prefill_replicas),
                             "decode": int(cfg.roles.decode_replicas)}
            self._target = sum(self._targets.values())
            self._autoscaler = RoleAwareAutoscaler({
                role: ReplicaAutoscaler(
                    min_replicas=int(
                        getattr(cfg.roles, f"min_{role}_replicas")),
                    max_replicas=int(
                        getattr(cfg.roles, f"max_{role}_replicas")),
                    **thresholds)
                for role in ("prefill", "decode")}) \
                if cfg.autoscale else None
        else:
            self._targets = None
            self._target = int(cfg.replicas)
            self._autoscaler = ReplicaAutoscaler.from_overlay(
                cfg.overlay_path,
                defaults=dict(min_replicas=int(cfg.min_replicas),
                              max_replicas=int(cfg.max_replicas),
                              **thresholds)) \
                if cfg.autoscale else None
        # the routing key hashes the first N prompt tokens; N defaults to
        # one KV page so the key matches exactly the prefix-cache chain
        # key of the request's first page
        self._route_tokens = int(cfg.route_prefix_tokens)
        self._route_root = hashlib.blake2b(
            b"ds:fleet-route", digest_size=16).digest()
        if self._roles_enabled:
            for _ in range(int(cfg.roles.prefill_replicas)):
                self._spawn(role="prefill")
            for _ in range(int(cfg.roles.decode_replicas)):
                self._spawn(role="decode")
        else:
            for _ in range(int(cfg.replicas)):
                self._spawn()
        self.attach_exporter()

    # -- plumbing --------------------------------------------------------
    @staticmethod
    def _autoscaler_thresholds(cfg):
        """The shared scale-decision thresholds, config defaults
        overridden by the tuned overlay's ``serving.fleet`` fragment
        when ``serving.fleet.overlay_path`` names one."""
        thresholds = {
            "scale_up_queue_per_replica":
                int(cfg.scale_up_queue_per_replica),
            "scale_down_queue_per_replica":
                int(cfg.scale_down_queue_per_replica),
            "free_page_low_frac": float(cfg.free_page_low_frac),
            "cooldown_sweeps": int(cfg.cooldown_sweeps),
        }
        if cfg.overlay_path:
            from deepspeed_tpu.autotuning.overlay import load_overlay
            payload = load_overlay(cfg.overlay_path)
            if payload is not None:
                frag = ((payload.get("overlay") or {})
                        .get("serving") or {}).get("fleet") or {}
                for key, cast in (
                        ("scale_up_queue_per_replica", int),
                        ("scale_down_queue_per_replica", int),
                        ("free_page_low_frac", float),
                        ("cooldown_sweeps", int)):
                    if key in frag:
                        thresholds[key] = cast(frag[key])
        return thresholds

    def _tel(self):
        tel = self._telemetry if self._telemetry is not None \
            else get_telemetry()
        return tel if (tel is not None and tel.enabled) else None

    def _fleet_event(self, name, **attrs):
        tel = self._tel()
        if tel is not None:
            tel.fleet(name, step=self.steps,
                      attrs={k: v for k, v in attrs.items()
                             if v is not None} or None)

    def _incident(self, kind, source="", detail=""):
        """Open an incident bundle (monitor/incidents.py) for a fleet
        verdict — replica kills and fences; no-op without the plane."""
        tel = self._tel()
        incidents = getattr(tel, "incidents", None) if tel else None
        if incidents is not None:
            incidents.trigger(kind, source=source, detail=detail,
                              step=self.steps)

    def attach_exporter(self):
        """Bind this router's :meth:`health` behind the telemetry
        exporter's ``GET /fleet`` endpoint (no-op without an exporter),
        and register it as incident-bundle context when the incident
        plane is on."""
        tel = self._telemetry if self._telemetry is not None \
            else get_telemetry()
        exporter = getattr(tel, "exporter", None)
        if exporter is not None:
            exporter.fleet_fn = self.health
        incidents = getattr(tel, "incidents", None)
        if incidents is not None:
            incidents.add_context("fleet_health", self.health)

    # -- replica lifecycle ----------------------------------------------
    def _spawn(self, replica_id=None, respawn=False, role=None):
        rid = replica_id
        if rid is not None and role is None:
            role = self._role_of.get(rid)      # respawn keeps its pool
        if self._roles_enabled and role is None:
            raise ValueError("role-specialized fleet: _spawn needs a role")
        if rid is None:
            if self._roles_enabled:
                prefix = "p" if role == "prefill" else "d"
                rid = f"{prefix}{self._next_rids[role]}"
                self._next_rids[role] += 1
            else:
                rid = f"r{self._next_rid}"
                self._next_rid += 1
        gen = self._gens.get(rid, -1) + 1
        self._gens[rid] = gen
        epoch = f"{rid}g{gen}"
        handle = self._make_handle(rid, epoch)
        rep = _Replica(rid, epoch, handle.engine, handle=handle,
                       role=(role or "unified"))
        if isinstance(handle, SubprocessReplicaHandle):
            # fresh breaker per spawn: a respawned process starts with a
            # clean slate (flap hysteresis lives across open/close
            # cycles of ONE process, not across respawns)
            rep.breaker = CircuitBreaker(self.fleet.transport,
                                         self._clock)
            handle._breaker = rep.breaker
        self.replicas[rid] = rep
        self._role_of[rid] = rep.role
        if self._route_tokens == 0:
            self._route_tokens = int(handle.page_size)
        if respawn:
            self.stats["respawns"] += 1
        self._fleet_event("fleet/respawn" if respawn else "fleet/spawn",
                          replica=rid, epoch=epoch,
                          role=(rep.role if self._roles_enabled else None))
        return rep

    def _make_handle(self, rid, epoch):
        """Build one replica behind the transport-mode handle.  In
        ``subprocess`` mode the factory must be a SPEC (dotted path +
        kwargs dict, or the bare path string) the worker process can
        re-import — a live callable cannot cross a process boundary."""
        tcfg = self.fleet.transport
        if tcfg.mode == "subprocess":
            if callable(self._factory):
                raise TypeError(
                    "transport.mode='subprocess' needs a factory SPEC "
                    "({'factory': 'module:fn', 'kwargs': {...}} or a "
                    "'module:fn' string), not a live callable — the "
                    "worker process rebuilds the engine by import")
            self._worker_seq += 1
            return SubprocessReplicaHandle(
                self._factory, rid, epoch, tcfg,
                telemetry=self._worker_telemetry,
                rank=self._worker_seq, clock=self._clock,
                wire=self.wire_injector, retry=self._retry_policy,
                on_retry=self._on_retry, on_stale=self._on_stale)
        return InProcessReplicaHandle(self._factory(rid, epoch))

    def _healthy(self, role: Optional[str] = None) -> List[_Replica]:
        return [r for r in self.replicas.values()
                if r.state == "healthy" and
                (role is None or r.role == role)]

    def _retire(self, rep: _Replica, kill=False):
        """Drop a replica from the routing ring (engine already drained
        or abandoned) and tear down its handle — in subprocess mode
        that reaps the worker process (SIGKILL when ``kill``); its
        fleet requests must have been re-homed."""
        self.replicas.pop(rep.replica_id, None)
        if rep.handle is not None:
            try:
                rep.handle.close(kill=kill)
            except Exception:
                pass

    def _requeue_owned(self, rep: _Replica) -> List[Any]:
        """Every fleet request dispatched to ``rep`` goes back to pending
        (redispatch-from-scratch) — or to a typed terminal when its
        redispatch budget is spent.  A ``migrating`` request whose
        SOURCE is ``rep`` loses its handoff (the pinned pages died with
        the replica) and re-prefills from scratch — that is the
        mid-migration source-kill recovery path."""
        moved = []
        for fr in self.requests.values():
            if fr.state in ("dispatched", "migrating") and \
                    fr.replica_id == rep.replica_id:
                if fr.state == "migrating":
                    fr.handoff = None
                    self.stats["migrate_aborts"] += 1
                    self._fleet_event("fleet/migrate_abort",
                                      req_id=fr.req_id,
                                      replica=rep.replica_id,
                                      reason="source_lost")
                self._requeue(fr)
                moved.append(fr.req_id)
        return moved

    def _requeue(self, fr: _FleetRequest):
        if fr.dispatches > int(self.fleet.redispatch_max):
            self._shed_terminal(
                fr, SHED_REDISPATCH_BUDGET,
                detail=f"{fr.dispatches} dispatches exhausted the "
                       f"redispatch budget {self.fleet.redispatch_max}")
            return
        fr.state = "pending"
        fr.replica_id = None
        self.pending.append(fr.req_id)
        if fr.dispatches:
            self.stats["redispatches"] += 1
            self._fleet_event("fleet/redispatch", req_id=fr.req_id,
                              dispatches=fr.dispatches)

    def kill_replica(self, replica_id, detail="killed"):
        """Abrupt replica death (the ``replica_kill`` injector path, also
        callable directly from tests/chaos drills): NO drain — the engine
        is dropped mid-flight and every request it owned is redispatched
        from scratch to the surviving ring."""
        rep = self.replicas.get(replica_id)
        if rep is None or rep.state == "dead":
            return
        rep.state = "dead"
        self.stats["kills"] += 1
        moved = self._requeue_owned(rep)
        logger.warning(
            f"fleet: replica {replica_id} ({rep.epoch}) killed: {detail}; "
            f"redispatching {len(moved)} requests")
        self._fleet_event("fleet/kill", replica=replica_id,
                          epoch=rep.epoch, redispatched=len(moved),
                          detail=detail)
        self._incident("replica_kill", source=str(replica_id),
                       detail=f"{detail}; redispatched {len(moved)}")
        self._retire(rep, kill=True)

    def _worker_lost(self, rep: _Replica, detail: str):
        """A subprocess replica's wire died (torn connection or missed
        heartbeats) — the PROCESS-level analogue of ``replica_kill``:
        book the ``fleet/worker_lost`` event + incident, arm the
        respawn backoff for the slot, and fall through to the abrupt
        kill path (redispatch everything the worker owned)."""
        if rep.state == "dead" or rep.replica_id not in self.replicas:
            return
        self.stats["workers_lost"] += 1
        self._fleet_event("fleet/worker_lost", replica=rep.replica_id,
                          epoch=rep.epoch, detail=detail)
        self._incident("worker_lost", source=str(rep.replica_id),
                       detail=detail)
        backoff = float(self.fleet.transport.respawn_backoff_s)
        if backoff > 0:
            self._respawn_after[rep.replica_id] = self._clock() + backoff
        self.kill_replica(rep.replica_id, detail=detail)

    def _respawn_ready(self, rid) -> bool:
        """Consume the slot's respawn-backoff stamp once the clock
        passes it; a storm of worker deaths respawns at most once per
        ``respawn_backoff_s`` per slot."""
        after = self._respawn_after.get(rid)
        if after is not None and self._clock() < after:
            return False
        self._respawn_after.pop(rid, None)
        return True

    # -- circuit breaker -------------------------------------------------
    def _ikey(self, rep: _Replica, fr: _FleetRequest) -> str:
        """Idempotency key for one mutation incarnation: stable across
        the channel's retries of one dispatch (the worker dedups a
        replay after a dropped ack), distinct across redispatches
        (``fr.dispatches``) and respawns (``rep.epoch``) — a NEW
        incarnation must really re-execute."""
        return f"{rep.epoch}:{fr.req_id!r}:{fr.dispatches}"

    def _on_retry(self, rid, op, attempt, delay_s, elapsed_s):
        self.stats["retries"] += 1
        self._fleet_event("fleet/retry", replica=rid, op=op,
                          attempt=int(attempt),
                          delay_s=round(float(delay_s), 4),
                          elapsed_s=round(float(elapsed_s), 4))

    def _on_stale(self, rid, op, kind):
        """A duplicate call's effect was dropped somewhere: a late or
        duplicated response discarded by call id (``stale_resp``) or a
        worker-side idempotency replay (``ikey_replay``)."""
        self.stats["dup_calls_dropped"] += 1
        self._fleet_event("fleet/dup_call_dropped", replica=rid, op=op,
                          kind=kind)

    def _rpc_failed(self, rep: _Replica, what: str, e: Exception):
        """An RPC to ``rep`` TIMED OUT (wire intact as far as anyone
        knows — the worker may just be slow or the frames lost).  The
        breaker counts consecutive timeouts and fences the replica
        without killing the process; with the breaker off this
        degrades to the pre-breaker behaviour: worker lost."""
        self.stats["rpc_timeouts"] += 1
        br = rep.breaker
        if br is None or not br.enabled:
            self._worker_lost(rep, f"{what}: {e}")
            return
        if br.record_failure():
            self._breaker_open(rep, f"{what}: {e}")

    def _breaker_open(self, rep: _Replica, detail: str):
        """Trip the breaker: fence ``rep`` from routing and requeue its
        requests (bookkeeping only — no RPC can block here) WITHOUT
        killing the process.  Exactly one incident bundle; heartbeat
        death is suspended while the breaker owns the verdict, so one
        gray failure cannot be double-counted as two incidents."""
        cooldown = rep.breaker.open()
        rep.state = "breaker_open"
        self.stats["breaker_opens"] += 1
        moved = self._requeue_owned(rep)
        logger.warning(
            f"fleet: replica {rep.replica_id} ({rep.epoch}) breaker "
            f"open: {detail}; redispatching {len(moved)} requests, "
            f"half-open probe in {cooldown:.2f}s")
        self._fleet_event("fleet/breaker_open", replica=rep.replica_id,
                          epoch=rep.epoch, detail=detail,
                          consecutive=rep.breaker.consecutive,
                          cooldown_s=round(cooldown, 3),
                          redispatched=len(moved))
        self._incident("breaker_open", source=str(rep.replica_id),
                       detail=f"{detail}; redispatched {len(moved)}")
        self._breaker_gauges()

    def _breaker_close(self, rep: _Replica):
        rep.breaker.close()
        rep.state = "healthy"
        self.stats["breaker_closes"] += 1
        self._fleet_event("fleet/breaker_close", replica=rep.replica_id,
                          epoch=rep.epoch,
                          probes=rep.breaker.probe_failures + 1)
        self._breaker_gauges()

    def _breaker_gauges(self):
        tel = self._tel()
        if tel is None:
            return
        n_open = sum(1 for r in self.replicas.values()
                     if r.state == "breaker_open")
        tel.gauge("fleet/breaker_open_replicas", float(n_open),
                  step=self.steps)
        tel.gauge("fleet/breaker_opens",
                  float(self.stats["breaker_opens"]), step=self.steps)
        tel.gauge("fleet/breaker_closes",
                  float(self.stats["breaker_closes"]), step=self.steps)

    def _probe_breakers(self):
        """Drive every breaker-open replica: keep its channel pumped
        (heartbeats and late replies still flow), and once the cooldown
        elapses run the half-open probe — a ``ping`` under its own wall
        budget.  Success rejoins the ring (the worker's stale work
        self-resolves through the collect guards; its warm prefix cache
        survives); a timed-out probe re-arms a doubled cooldown until
        the probe budget is spent; a torn wire is a worker-lost."""
        probe_timeout = float(self.fleet.transport.breaker_probe_timeout_s)
        for rep in list(self.replicas.values()):
            if rep.state != "breaker_open":
                continue
            try:
                rep.handle.pump()
            except TransportError as e:
                self._worker_lost(rep, f"breaker-open wire died: {e}")
                continue
            if not rep.breaker.probe_due():
                continue
            try:
                rep.handle.ping(timeout=probe_timeout)
            except RpcTimeout as e:
                if rep.breaker.probe_failed():
                    self._worker_lost(
                        rep, f"breaker half-open probes exhausted "
                             f"({rep.breaker.probe_failures}): {e}")
                continue
            except TransportError as e:
                self._worker_lost(rep, f"breaker probe wire died: {e}")
                continue
            except Exception as e:
                self.kill_replica(rep.replica_id,
                                  detail=f"breaker probe raised: {e}")
                continue
            self._breaker_close(rep)

    def _fence(self, rep: _Replica, why: str):
        """Graceful failover: stop routing to the replica, drain it (its
        finished work is delivered, its shed work redispatched), then
        retire it.  The respawn happens on the next ``step``."""
        rep.state = "fenced"
        self.stats["fences"] += 1
        self._fleet_event("fleet/fence", replica=rep.replica_id,
                          epoch=rep.epoch, reason=why)
        self._incident("replica_fence", source=str(rep.replica_id),
                       detail=why)
        try:
            res = rep.handle.drain()
        except TransportError as e:     # worker died mid-drain
            rep.state = "healthy"       # let _worker_lost see it live
            self._worker_lost(rep, f"worker died while fencing: {e}")
            return
        except Exception as e:   # a broken drain degrades to a kill
            rep.state = "healthy"   # let kill_replica see it live
            self.kill_replica(rep.replica_id,
                              detail=f"drain failed while fencing: {e}")
            return
        self._collect_finished(rep, res["finished"])
        self._collect_terminated(rep)
        self._fleet_event("fleet/drain", replica=rep.replica_id,
                          finished=len(res["finished"]),
                          shed=len(res["shed"]), steps=res["steps"])
        self._requeue_owned(rep)
        self._retire(rep)

    # -- routing ---------------------------------------------------------
    def _route_key(self, prompt: List[int]) -> bytes:
        """Rolling blake2b chain over the first ``route_prefix_tokens``
        prompt tokens — the prefix-cache chain-key idiom, so shared
        prefixes share a routing key."""
        h = hashlib.blake2b(self._route_root, digest_size=16)
        n = self._route_tokens or len(prompt)
        h.update(np.asarray(prompt[:n], np.int64).tobytes())
        return h.digest()

    def _pick(self, key: bytes,
              role: Optional[str] = None) -> Optional[_Replica]:
        """Rendezvous hashing: highest ``blake2b(key ‖ replica_id)``
        among healthy replicas (of ``role``'s pool when given).
        Membership changes only remap keys whose winner died; a respawn
        under the same replica_id re-takes its slot."""
        best, best_score = None, None
        for rep in self._healthy(role):
            h = hashlib.blake2b(key, digest_size=8)
            h.update(rep.replica_id.encode())
            score = (int.from_bytes(h.digest(), "big"), rep.replica_id)
            if best_score is None or score > best_score:
                best, best_score = rep, score
        return best

    def _dispatch(self, fr: _FleetRequest) -> bool:
        """One dispatch attempt.  The injector is consulted BEFORE the
        routing table or any engine mutates (the page_alloc atomicity
        idiom): a fault here leaves the request exactly as it was and it
        retries on the next step.  Returns True when the request left the
        pending state (dispatched OR typed into a terminal).

        Role-specialized fleets dispatch to the PREFILL pool with
        ``prefill_only`` set (the engine hands the KV pages off after the
        first token); with zero healthy prefill replicas, dispatch
        degrades to local monolithic prefill on the decode pool so
        admissions never stall on a dead pool."""
        if self.injector is not None:
            self.injector.check("route_dispatch")
        now = self._clock()
        if fr.deadline and now >= fr.deadline:
            self._deadline_terminal(fr)
            return True
        pool, prefill_only = None, False
        if self._roles_enabled:
            if self._healthy("prefill"):
                pool, prefill_only = "prefill", True
            else:
                pool = "decode"     # degraded: local monolithic prefill
        target = self._pick(fr.route_key, pool)
        if target is None:
            return False                 # no healthy replicas right now
        # affinity target first; spill order by least load
        order = [target] + sorted(
            (r for r in self._healthy(pool) if r is not target),
            key=lambda r: (r.handle.load, r.replica_id))
        rejects = []
        for i, rep in enumerate(order):
            kwargs = dict(fr.kwargs)
            if fr.deadline:
                kwargs["deadline_s"] = fr.deadline - now
            if prefill_only:
                kwargs["prefill_only"] = True
            try:
                rep.handle.add_request(fr.req_id, fr.prompt,
                                       ikey=self._ikey(rep, fr), **kwargs)
            except RequestRejected as e:
                rejects.append(e)
                continue
            except RpcTimeout as e:
                self._rpc_failed(rep, "add_request timed out", e)
                continue
            except TransportError as e:
                self._worker_lost(rep, f"add_request transport "
                                       f"failed: {e}")
                continue
            fr.state = "dispatched"
            fr.replica_id = rep.replica_id
            fr.dispatches += 1
            if i > 0:
                self.stats["spills"] += 1
                self._fleet_event("fleet/spill", req_id=fr.req_id,
                                  replica=rep.replica_id,
                                  affinity=target.replica_id)
            if pool == "decode":
                self.stats["local_prefills"] += 1
                self._fleet_event("fleet/local_prefill", req_id=fr.req_id,
                                  replica=rep.replica_id)
            self._fleet_event("fleet/route", req_id=fr.req_id,
                              replica=rep.replica_id,
                              dispatches=fr.dispatches)
            return True
        # every healthy replica said no — a request-indicting reason
        # terminates (another replica would say the same); overload keeps
        # it pending for the next step
        fatal = next((e for e in rejects if e.reason in _FATAL_REJECTS),
                     None)
        if fatal is not None:
            self._shed_terminal(fr, fatal.reason, detail=fatal.detail)
            return True
        return False

    def _pump_pending(self):
        """Try to place every pending request; whatever cannot be placed
        (injected dispatch fault, fleet-wide overload, no healthy
        replicas) stays pending for the next step."""
        for _ in range(len(self.pending)):
            rid = self.pending.popleft()
            fr = self.requests[rid]
            if fr.state != "pending":
                continue
            try:
                placed = self._dispatch(fr)
            except Exception as e:      # injected route_dispatch fault
                self.stats["dispatch_faults"] += 1
                self._fleet_event("fleet/dispatch_fault", req_id=rid,
                                  error=str(e))
                self.pending.append(rid)
                continue
            if not placed:
                self.pending.append(rid)

    # -- terminals -------------------------------------------------------
    def _finish_fleet(self, fr: _FleetRequest, tokens: List[int]):
        fr.state = "finished"
        self.finished[fr.req_id] = tokens
        self.stats["finished"] += 1
        self.tracer.terminal(
            fr.req_id, "finish",
            n_generated=max(0, len(tokens) - len(fr.prompt)))

    def _shed_terminal(self, fr: _FleetRequest, reason: str,
                       detail: str = ""):
        fr.state = "terminated"
        self.terminated[fr.req_id] = RequestResult(
            fr.req_id, "shed", reason, detail=detail)
        self.stats["terminated"] += 1
        self.stats["shed"] += 1
        self.tracer.terminal(fr.req_id, "shed", reason=reason)
        self._fleet_event("fleet/shed", req_id=fr.req_id, reason=reason)

    def _deadline_terminal(self, fr: _FleetRequest,
                           result: Optional[RequestResult] = None):
        fr.state = "terminated"
        self.terminated[fr.req_id] = result if result is not None else \
            RequestResult(fr.req_id, "deadline", SHED_DEADLINE,
                          detail="expired before dispatch")
        self.stats["terminated"] += 1
        self.stats["deadline"] += 1
        self.tracer.terminal(
            fr.req_id, "deadline",
            n_generated=result.n_generated if result else 0,
            reason=SHED_DEADLINE)

    def _collect_finished(self, rep: _Replica, done: Dict[Any, List[int]]):
        for rid, tokens in done.items():
            fr = self.requests.get(rid)
            if fr is not None and fr.state == "dispatched" and \
                    fr.replica_id == rep.replica_id:
                self._finish_fleet(fr, tokens)

    def _collect_terminated(self, rep: _Replica):
        """Fold one replica's typed terminals into fleet state: deadlines
        are final (the TTL is absolute), everything else — shed, evicted,
        drained — is the REPLICA's fault, so the request redispatches
        while its budget lasts."""
        for rid, result in rep.handle.pop_terminated().items():
            fr = self.requests.get(rid)
            if fr is None or fr.state != "dispatched" or \
                    fr.replica_id != rep.replica_id:
                continue
            if result.status == "deadline":
                self._deadline_terminal(fr, result)
            else:
                self._requeue(fr)

    # -- KV-page migration (prefill -> decode) ---------------------------
    def _collect_handoffs(self, rep: _Replica):
        """Fold a prefill replica's completed prefills into fleet state:
        each request enters ``migrating`` (handoff captured, source
        pages pinned under ``rep``) and joins the migration queue."""
        for rid, handoff in rep.handle.pop_prefilled().items():
            fr = self.requests.get(rid)
            if fr is None or fr.state != "dispatched" or \
                    fr.replica_id != rep.replica_id:
                # stale handoff (the request was re-homed) — unpin now
                rep.handle.release_handoff(rid)
                continue
            fr.state = "migrating"
            fr.handoff = handoff
            fr.migrate_after = self.steps
            self.migrations.append(rid)
            self._fleet_event("fleet/migrate_start", req_id=rid,
                              replica=rep.replica_id,
                              pages=len(handoff.pages))

    def _migrate(self, fr: _FleetRequest, src: _Replica):
        """One migration attempt for ``fr`` (state ``migrating``, handoff
        pinned on ``src``).  Returns ``("committed", pages_sent)`` on
        success, ``("retry", 0)`` when no decode replica can take it
        right now, ``("commit_fault", 0)`` after a rolled-back commit
        (backoff already booked).  Raises on a faulted ``page_migrate``
        transfer — the caller books that fault.  Both injector sites run
        BEFORE the state they guard mutates, so every failure leaves the
        source pin and the fleet routing table untouched."""
        handoff = fr.handoff
        now = self._clock()
        target = self._pick(fr.route_key, "decode")
        if target is None:
            return ("retry", 0)
        order = [target] + sorted(
            (r for r in self._healthy("decode") if r is not target),
            key=lambda r: (r.handle.load, r.replica_id))
        # transfer fault site — consulted before any engine mutates
        if self.injector is not None:
            self.injector.check("page_migrate")
        for rep in order:
            h = rep.handle
            # content-addressed dedup: full prompt pages already resident
            # in the destination's prefix cache (same rolling-blake2b
            # chain) are attached by reference instead of transferred —
            # a hot shared prefix migrates ONCE per decode replica
            try:
                resident = h.resident_prefix(handoff.prompt)
            except RpcTimeout as e:
                self._rpc_failed(rep, "resident_prefix timed out", e)
                continue        # try the next decode replica
            except TransportError as e:
                self._worker_lost(rep, f"resident_prefix transport "
                                       f"failed: {e}")
                continue        # try the next decode replica
            to_send = handoff.pages[len(resident):]
            # wire codec runs AFTER the dedup plan: chain keys are
            # token-addressed, so content dedup is quantization-blind;
            # in subprocess mode export+encode run ON the source worker
            # and the quantized payload is what actually crosses the
            # process boundary (the int8 saving is real wire bytes)
            try:
                payload, wire_frac = src.handle.export_payload(to_send)
            except RpcTimeout as e:
                # the pinned copy is still there; back off via the
                # breaker and retry the whole attempt next pump
                self._rpc_failed(src, "export timed out", e)
                return ("retry", 0)
            except TransportError as e:
                # source wire died holding the pin — the pinned copy is
                # gone; _worker_lost requeues this request for a
                # from-scratch re-prefill
                self._worker_lost(src, f"export transport failed: {e}")
                return ("retry", 0)
            deadline_s = (fr.deadline - now) if fr.deadline else None
            try:
                imported = h.import_request(handoff, payload=payload,
                                            shared_pages=resident,
                                            deadline_s=deadline_s,
                                            ikey=self._ikey(rep, fr))
            except RpcTimeout as e:
                # the import may or may not have staged; either way it
                # is uncommitted and a later retry carries the same
                # ikey, so the worker converges to ONE staged import
                self._rpc_failed(rep, "import timed out", e)
                continue
            except TransportError as e:
                self._worker_lost(rep, f"import transport failed: {e}")
                continue        # uncommitted import died with the worker
            if not imported:
                continue        # full right now; try the next replica
            # commit fault site — consulted before the routing table
            # flips; a fault rolls the import back to NOTHING while the
            # source stays pinned (all-or-nothing)
            if self.injector is not None:
                try:
                    self.injector.check("migrate_commit")
                except Exception as e:
                    h.cancel_import(fr.req_id)
                    self.stats["migrate_commit_faults"] += 1
                    self._fleet_event(
                        "fleet/migrate_fault", req_id=fr.req_id,
                        site="migrate_commit", error=str(e))
                    fr.migrate_after = self.steps + max(
                        1, int(self.fleet.roles.migrate_backoff_steps))
                    return ("commit_fault", 0)
            try:
                h.commit_import(fr.req_id, ikey=self._ikey(rep, fr))
            except RpcTimeout as e:
                # GRAY torn commit: the ack was lost but the commit may
                # have LANDED.  Do not kill the destination — book a
                # commit fault and back off; the retry re-runs the whole
                # transaction and the ikey makes commit_import converge
                # exactly-once (a landed commit replays its cached ok,
                # an unstaged one re-imports from the pinned source).
                self._rpc_failed(rep, "commit ack timed out", e)
                self.stats["migrate_commit_faults"] += 1
                self._fleet_event("fleet/migrate_fault", req_id=fr.req_id,
                                  site="migrate_commit",
                                  error=f"commit ack timed out: {e}")
                fr.migrate_after = self.steps + max(
                    1, int(self.fleet.roles.migrate_backoff_steps))
                return ("commit_fault", 0)
            except TransportError as e:
                # TORN COMMIT ACK: the destination died (or the wire
                # tore) before acknowledging — the uncommitted import
                # died with the process, the source stays pinned, and
                # the transaction rolls back exactly like an injected
                # migrate_commit fault
                self._worker_lost(rep, f"commit ack lost: {e}")
                self.stats["migrate_commit_faults"] += 1
                self._fleet_event("fleet/migrate_fault", req_id=fr.req_id,
                                  site="migrate_commit",
                                  error=f"commit ack lost: {e}")
                fr.migrate_after = self.steps + max(
                    1, int(self.fleet.roles.migrate_backoff_steps))
                return ("commit_fault", 0)
            fr.state = "dispatched"
            fr.replica_id = rep.replica_id
            fr.dispatches += 1
            fr.handoff = None
            try:
                src.handle.release_handoff(fr.req_id)
            except TransportError as e:
                # the commit already landed; a torn unpin just means the
                # source worker died and takes the kill path
                self._worker_lost(src, f"release transport failed: {e}")
            page_bytes = int(h.kv_page_bytes)
            # per-page accounting stays analytic (pad lanes excluded):
            # the quantized wire carries wire_frac of the dtype-true
            # page bytes, the rest is quant saving on top of dedup
            raw_bytes = len(to_send) * page_bytes
            wire_bytes = int(raw_bytes * wire_frac)
            quant_saved = raw_bytes - wire_bytes
            self.stats["migrations"] += 1
            self.stats["migrated_pages"] += len(to_send)
            self.stats["dedup_skipped_pages"] += len(resident)
            self.stats["migrate_bytes"] += wire_bytes
            self.stats["migrate_bytes_saved"] += \
                len(resident) * page_bytes + quant_saved
            self.stats["migrate_quant_bytes_saved"] += quant_saved
            if quant_saved:
                tel = self._tel()
                if tel is not None:
                    tel.gauge("comm/kv_migrate/quant_bytes_saved",
                              float(self.stats["migrate_quant_bytes_saved"]),
                              step=self.steps)
            self._fleet_event("fleet/migrate_commit", req_id=fr.req_id,
                              replica=rep.replica_id,
                              source=src.replica_id,
                              pages=len(to_send), skipped=len(resident),
                              bytes=wire_bytes,
                              bytes_saved=(len(resident) * page_bytes
                                           + quant_saved),
                              quant_bytes_saved=quant_saved or None,
                              wire_dtype="int8" if quant_saved else None)
            return ("committed", len(to_send))
        return ("retry", 0)

    def _pump_migrations(self):
        """Drive every ``migrating`` request one transaction attempt
        forward, under the per-step page-transfer budget (the first
        migration of a step always proceeds, so one large handoff can
        never livelock).  A dead source aborts the migration and the
        request re-prefills from scratch; a faulted transfer or commit
        retries after ``migrate_backoff_steps``; an expired deadline is
        final."""
        if not self.migrations:
            return
        budget = int(self.fleet.roles.page_transfer_budget)
        backoff = max(1, int(self.fleet.roles.migrate_backoff_steps))
        sent, migrated_any = 0, False
        for _ in range(len(self.migrations)):
            rid = self.migrations.popleft()
            fr = self.requests.get(rid)
            if fr is None or fr.state != "migrating":
                continue        # re-homed or already terminal
            if fr.deadline and self._clock() >= fr.deadline:
                src = self.replicas.get(fr.replica_id)
                if src is not None:
                    try:
                        src.handle.release_handoff(rid)
                    except TransportError as e:
                        self._worker_lost(src, f"release transport "
                                               f"failed: {e}")
                fr.handoff = None
                self.stats["migrate_aborts"] += 1
                self._fleet_event("fleet/migrate_abort", req_id=rid,
                                  reason="deadline")
                self._deadline_terminal(fr)
                continue
            src = self.replicas.get(fr.replica_id)
            if src is None or src.state != "healthy":
                # source died between capture and transfer: the pinned
                # copy is gone — re-prefill from scratch
                fr.handoff = None
                self.stats["migrate_aborts"] += 1
                self._fleet_event("fleet/migrate_abort", req_id=rid,
                                  reason="source_lost")
                self._requeue(fr)
                continue
            if self.steps < fr.migrate_after:
                self.migrations.append(rid)     # backing off
                continue
            if budget and migrated_any and \
                    sent + len(fr.handoff.pages) > budget:
                self.migrations.append(rid)     # over budget this step
                continue
            try:
                verdict, moved = self._migrate(fr, src)
            except Exception as e:      # injected page_migrate fault
                self.stats["migrate_faults"] += 1
                self._fleet_event("fleet/migrate_fault", req_id=rid,
                                  site="page_migrate", error=str(e))
                fr.migrate_after = self.steps + backoff
                self.migrations.append(rid)
                continue
            if verdict == "committed":
                sent += moved
                migrated_any = True
            else:
                self.migrations.append(rid)

    # -- public surface --------------------------------------------------
    def submit(self, req_id, prompt_ids, max_new_tokens: int = 32,
               temperature: float = 0.0, seed: int = 0, top_k: int = 0,
               top_p: float = 1.0, deadline_s: Optional[float] = None):
        """Register one request with the fleet and try to place it.
        Raises typed :class:`RequestRejected` only for conditions the
        fleet can see without an engine (duplicate id, draining); every
        other failure mode resolves asynchronously into a typed terminal
        in :meth:`pop_terminated` — nothing is ever silently dropped."""
        if self.draining:
            raise RequestRejected(req_id, REJECT_DRAINING,
                                  "fleet is draining; admission stopped")
        if req_id in self.requests:
            raise RequestRejected(req_id, REJECT_DUPLICATE,
                                  "req_id already submitted to the fleet")
        prompt = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        now = self._clock()
        fr = _FleetRequest(
            req_id, prompt,
            kwargs=dict(max_new_tokens=int(max_new_tokens),
                        temperature=float(temperature), seed=int(seed),
                        top_k=int(top_k), top_p=float(top_p)),
            route_key=self._route_key(prompt),
            deadline=(now + deadline_s) if deadline_s else 0.0)
        self.requests[req_id] = fr
        self.pending.append(req_id)
        self.stats["submitted"] += 1
        self.tracer.admit(req_id, deadline=fr.deadline, now=now)
        self._pump_pending()

    def step(self) -> Dict[Any, List[int]]:
        """Advance the whole fleet: retry pending dispatches, step every
        replica (an engine that raises is killed and its requests
        redispatched), fold terminals, run the supervision sweep on its
        interval, and respawn up to the target replica count.  Returns
        the requests that finished THIS step (req_id → full tokens), like
        ``ServingEngine.step``."""
        self.steps += 1
        self._pump_pending()
        done_now: Dict[Any, List[int]] = {}
        for rep in list(self.replicas.values()):
            if rep.state != "healthy":
                continue
            try:
                done = rep.handle.step()
                self._engine_steps += 1
                before = set(self.finished)
                self._collect_finished(rep, done)
                self._collect_terminated(rep)
                if self._roles_enabled and rep.role == "prefill":
                    self._collect_handoffs(rep)
            except RpcTimeout as e:
                # slow-but-alive ≠ dead: the breaker counts consecutive
                # timeouts and fences WITHOUT killing; its half-open
                # probe decides whether the worker ever comes back
                self._rpc_failed(rep, "step timed out", e)
                continue
            except TransportError as e:
                # torn wire ≠ engine fault: the PROCESS died (or its
                # connection did) — take the worker-lost path, which
                # books the fleet/worker_lost incident before killing
                self._worker_lost(rep, f"step transport failed: {e}")
                continue
            except Exception as e:
                self.kill_replica(rep.replica_id,
                                  detail=f"step raised: {e}")
                continue
            for rid in set(self.finished) - before:
                done_now[rid] = self.finished[rid]
        self._check_liveness()
        if self._roles_enabled:
            self._pump_migrations()
        # the sweep waits until at least one replica has actually
        # stepped — health_interval=1 (or a fleet killed down to zero
        # replicas before its first step) must not fire a supervision
        # verdict on engines that never ran
        if self._engine_steps and \
                self.steps % int(self.fleet.health_interval) == 0:
            self._supervise()
        self._ensure_target()
        return done_now

    def _check_liveness(self):
        """Heartbeat liveness for subprocess replicas: drain each
        channel's async frames (heartbeats stamp ``last_heartbeat``
        with the router's clock on receipt) and declare any replica
        whose last heartbeat is older than ``heartbeat_deadline_s``
        lost.  In-process handles report ``last_heartbeat=None`` and
        are exempt — they cannot die without the router dying too.

        Breaker-open replicas are EXEMPT from heartbeat death (the
        ``!= "healthy"`` skip): the breaker already owns the verdict
        for that gray failure, and its half-open probe — driven by
        ``_probe_breakers`` below — decides between rejoin and
        worker-lost.  One gray failure, one incident."""
        deadline = float(self.fleet.transport.heartbeat_deadline_s)
        now = self._clock()
        for rep in list(self.replicas.values()):
            if rep.state != "healthy":
                continue
            try:
                rep.handle.pump()
            except TransportError as e:
                self._worker_lost(rep, f"heartbeat wire died: {e}")
                continue
            last = rep.handle.last_heartbeat
            if last is None or deadline <= 0:
                continue
            age = now - last
            if age > deadline:
                self._worker_lost(
                    rep, f"missed heartbeats: last seen {age:.1f}s ago "
                         f"(deadline {deadline:.1f}s)")
        self._probe_breakers()

    def pop_terminated(self) -> Dict[Any, RequestResult]:
        """Hand back (and clear) every fleet-level typed terminal since
        the last call (deadline expiries, redispatch-budget sheds,
        drain sheds)."""
        out = self.terminated
        self.terminated = {}
        return out

    def join(self, max_steps: int = 10_000) -> Dict[Any, List[int]]:
        """Step until every submitted request reaches a terminal (or the
        step budget runs out); returns everything finished meanwhile."""
        done: Dict[Any, List[int]] = {}
        for _ in range(max_steps):
            if not self._unresolved():
                break
            done.update(self.step())
        return done

    def _unresolved(self) -> int:
        return sum(1 for fr in self.requests.values()
                   if fr.state in ("pending", "dispatched", "migrating"))

    # -- supervision -----------------------------------------------------
    def _supervise(self):
        for rep in list(self.replicas.values()):
            if rep.state != "healthy":
                continue
            if self.injector is not None:
                try:
                    self.injector.check("replica_kill")
                except Exception as e:
                    self.kill_replica(rep.replica_id, detail=str(e))
                    continue
            try:
                leaks = rep.handle.leak_report()
                storm = bool(rep.handle.health().get("recompile_storm"))
            except RpcTimeout as e:
                self._rpc_failed(rep, "health check timed out", e)
                continue
            except TransportError as e:
                self._worker_lost(rep, f"health check transport "
                                       f"failed: {e}")
                continue
            except Exception as e:
                self.kill_replica(rep.replica_id,
                                  detail=f"health check raised: {e}")
                continue
            if leaks:
                self._fence(rep, f"leak_report: {sorted(leaks)}")
            elif storm:
                self._fence(rep, "recompile_storm")
        self._autoscale()

    def _autoscale(self):
        if self._autoscaler is None:
            return
        if self._roles_enabled:
            self._autoscale_roles()
            return
        healthy = self._healthy()
        queue_depth = len(self.pending) + sum(
            r.handle.queue_depth for r in healthy)
        shed_total = self.stats["shed"] + sum(
            r.handle.shed_count for r in healthy)
        shed_delta = max(0, shed_total - self._last_shed_total)
        self._last_shed_total = shed_total
        fracs = [r.handle.free_pages /
                 max(1, r.handle.num_pages - 1) for r in healthy]
        desired = self._autoscaler.decide(
            max(1, len(healthy)), queue_depth=queue_depth,
            shed_delta=shed_delta,
            free_page_frac=min(fracs) if fracs else 1.0)
        if desired > self._target:
            self.stats["scale_ups"] += 1
            self._fleet_event("fleet/scale_up", replicas=desired,
                              queue_depth=queue_depth)
        elif desired < self._target:
            self.stats["scale_downs"] += 1
            self._fleet_event("fleet/scale_down", replicas=desired,
                              queue_depth=queue_depth)
            # retire the least-loaded healthy replica gracefully
            victim = min(
                self._healthy(),
                key=lambda r: (r.handle.load, r.replica_id),
                default=None)
            if victim is not None:
                self._fence(victim, "scale_down")
        self._target = desired

    def _autoscale_roles(self):
        """Per-pool hysteretic scaling: the prefill pool feels fleet
        admission backlog, the decode pool feels the migration queue on
        top of its own decode queues; each pool grows/sheds ±1 within
        its own min/max band (``RoleAwareAutoscaler``)."""
        n_by, q_by, shed_by, frac_by = {}, {}, {}, {}
        for role in ("prefill", "decode"):
            healthy = self._healthy(role)
            n_by[role] = max(1, len(healthy))
            q_by[role] = sum(r.handle.queue_depth for r in healthy) + (
                len(self.pending) if role == "prefill"
                else len(self.migrations))
            shed_total = sum(r.handle.shed_count for r in healthy)
            if role == "prefill":
                shed_total += self.stats["shed"]    # admission sheds
            shed_by[role] = max(0,
                                shed_total - self._last_shed_by[role])
            self._last_shed_by[role] = shed_total
            fracs = [r.handle.free_pages /
                     max(1, r.handle.num_pages - 1)
                     for r in healthy]
            frac_by[role] = min(fracs) if fracs else 1.0
        desired = self._autoscaler.decide(n_by, queue_by_pool=q_by,
                                          shed_by_pool=shed_by,
                                          free_frac_by_pool=frac_by)
        for role in ("prefill", "decode"):
            if desired[role] > self._targets[role]:
                self.stats["scale_ups"] += 1
                self._fleet_event("fleet/scale_up", role=role,
                                  replicas=desired[role],
                                  queue_depth=q_by[role])
            elif desired[role] < self._targets[role]:
                self.stats["scale_downs"] += 1
                self._fleet_event("fleet/scale_down", role=role,
                                  replicas=desired[role],
                                  queue_depth=q_by[role])
                victim = min(
                    self._healthy(role),
                    key=lambda r: (r.handle.load, r.replica_id),
                    default=None)
                if victim is not None:
                    self._fence(victim, "scale_down")
            self._targets[role] = desired[role]
        self._target = sum(self._targets.values())

    def _ensure_target(self):
        """Respawn (dead ring slots first, so rendezvous affinity is
        restored) until the fleet is back at the target size."""
        if self._roles_enabled:
            for role in ("prefill", "decode"):
                floor = max(
                    int(getattr(self.fleet.roles, f"min_{role}_replicas")),
                    self._targets[role])
                while sum(1 for r in self.replicas.values()
                          if r.role == role) < floor:
                    dead_all = sorted(
                        r for r in set(self._gens) - set(self.replicas)
                        if self._role_of.get(r) == role)
                    dead = [r for r in dead_all if self._respawn_ready(r)]
                    if dead_all and not dead:
                        break       # every dead slot is backing off —
                        #             don't mint NEW rids around them
                    self._spawn(replica_id=dead[0] if dead else None,
                                respawn=bool(dead), role=role)
            return
        floor = max(int(self.fleet.min_replicas), self._target)
        while len(self.replicas) < floor:
            dead_all = sorted(set(self._gens) - set(self.replicas))
            dead = [r for r in dead_all if self._respawn_ready(r)]
            if dead_all and not dead:
                break               # respawn storm bounded by backoff
            self._spawn(replica_id=dead[0] if dead else None,
                        respawn=bool(dead))

    # -- lifecycle / introspection ---------------------------------------
    def drain(self) -> Dict[str, Any]:
        """Quiesce the whole fleet: stop admission, drain every replica
        (delivering what finishes), then shed whatever is still pending
        — every submitted request ends in ``finished`` or a typed
        terminal."""
        self.draining = True
        finished: Dict[Any, List[int]] = {}
        for rep in list(self.replicas.values()):
            if rep.state != "healthy":
                continue
            before = set(self.finished)
            self._fence(rep, "fleet drain")
            for rid in set(self.finished) - before:
                finished[rid] = self.finished[rid]
        shed_ids = []
        for rid in list(self.pending):
            fr = self.requests[rid]
            if fr.state == "pending":
                self._shed_terminal(fr, SHED_DRAIN,
                                    detail="shed by fleet drain()")
                shed_ids.append(rid)
        self.pending.clear()
        return {"finished": finished, "shed": shed_ids,
                "health": self.health()}

    def health(self) -> Dict[str, Any]:
        """Fleet snapshot: per-replica supervision state + condensed
        engine health, aggregate load, counters, and the fleet-level
        trace ledger.  Aggregate gauges are mirrored onto the telemetry
        registry (``fleet/*``) and the whole dict is served by the
        exporter's ``GET /fleet``."""
        per_replica = {}
        queue_depth = len(self.pending)
        now = self._clock()
        subprocess_mode = self.fleet.transport.mode == "subprocess"
        for rep in self.replicas.values():
            h = rep.handle
            entry = {
                "state": rep.state,
                "epoch": rep.epoch,
                "role": rep.role,
                "queue_depth": h.queue_depth,
                "active_slots": h.n_active,
                "free_pages": h.free_pages,
                "prefix_hit_rate": h.prefix_hit_rate,
            }
            if subprocess_mode:
                entry["transport"] = h.mode
                last = h.last_heartbeat
                entry["heartbeat_age_s"] = (
                    round(now - last, 3) if last is not None else None)
                if rep.breaker is not None:
                    entry["breaker"] = rep.breaker.snapshot()
            per_replica[rep.replica_id] = entry
            queue_depth += h.queue_depth
        snap = {
            "replicas": per_replica,
            "n_replicas": len(self.replicas),
            "n_healthy": len(self._healthy()),
            "target_replicas": self._target,
            "pending": len(self.pending),
            "in_flight": self._unresolved(),
            "queue_depth": queue_depth,
            "draining": self.draining,
            "counters": dict(self.stats),
            "traces": {"open": len(self.tracer.open),
                       "admitted": self.tracer.admitted,
                       "closed": self.tracer.closed,
                       "terminals": dict(self.tracer.terminals)},
        }
        if self._roles_enabled:
            pools = {}
            for role in ("prefill", "decode"):
                healthy = self._healthy(role)
                pools[role] = {
                    "n_healthy": len(healthy),
                    "target": self._targets[role],
                    "queue_depth": sum(r.handle.queue_depth
                                       for r in healthy),
                }
            snap["pools"] = pools
            snap["migrating"] = len([
                fr for fr in self.requests.values()
                if fr.state == "migrating"])
        tel = self._tel()
        if tel is not None:
            for gauge, key in (("fleet/replicas", "n_replicas"),
                               ("fleet/healthy", "n_healthy"),
                               ("fleet/pending", "pending"),
                               ("fleet/queue_depth", "queue_depth")):
                tel.registry.gauge(gauge).set(snap[key])
            tel.registry.gauge("fleet/redispatches").set(
                self.stats["redispatches"])
            if subprocess_mode:
                tel.registry.gauge("fleet/workers_lost").set(
                    self.stats["workers_lost"])
                ages = [e["heartbeat_age_s"]
                        for e in per_replica.values()
                        if e.get("heartbeat_age_s") is not None]
                if ages:
                    tel.registry.gauge("fleet/heartbeat_age_s").set(
                        max(ages))
                tel.registry.gauge("fleet/breaker_open_replicas").set(
                    sum(1 for r in self.replicas.values()
                        if r.state == "breaker_open"))
                for gauge, key in (
                        ("fleet/breaker_opens", "breaker_opens"),
                        ("fleet/breaker_closes", "breaker_closes"),
                        ("fleet/retries", "retries"),
                        ("fleet/dup_calls_dropped", "dup_calls_dropped")):
                    tel.registry.gauge(gauge).set(self.stats[key])
            if self._roles_enabled:
                tel.registry.gauge("fleet/migrating").set(
                    snap["migrating"])
                tel.registry.gauge("fleet/migrated_pages").set(
                    self.stats["migrated_pages"])
                tel.registry.gauge("fleet/dedup_skipped_pages").set(
                    self.stats["dedup_skipped_pages"])
                for role, pool in snap["pools"].items():
                    tel.registry.gauge(
                        f"fleet/{role}_queue_depth").set(
                        pool["queue_depth"])
        return snap

    def leak_report(self) -> Dict[str, Any]:
        """Fleet invariant audit, {} when clean: every live replica's own
        ``leak_report()`` (keys prefixed ``<replica_id>:``), the
        fleet-level trace-completeness audit, and the bookkeeping
        identity submitted == finished + terminated + unresolved."""
        leaks: Dict[str, Any] = {}
        for rep in list(self.replicas.values()):
            try:
                report = rep.handle.leak_report()
            except TransportError as e:
                self._worker_lost(rep, f"leak audit transport "
                                       f"failed: {e}")
                continue
            for k, v in report.items():
                leaks[f"{rep.replica_id}:{k}"] = v
        live = [fr.req_id for fr in self.requests.values()
                if fr.state in ("pending", "dispatched", "migrating")]
        leaks.update(self.tracer.audit(live))
        resolved = self.stats["finished"] + self.stats["terminated"]
        if self.stats["submitted"] != resolved + self._unresolved():
            leaks["fleet_count_mismatch"] = {
                "submitted": self.stats["submitted"],
                "finished": self.stats["finished"],
                "terminated": self.stats["terminated"],
                "unresolved": self._unresolved()}
        return leaks

    def close(self):
        """Tear down every replica handle.  In-process: a no-op.
        Subprocess: graceful shutdown of each healthy worker (SIGKILL
        for anything already marked unhealthy) — tests and benches call
        this so no worker processes outlive the router."""
        for rep in list(self.replicas.values()):
            if rep.handle is not None:
                try:
                    rep.handle.close(kill=(rep.state != "healthy"))
                except Exception:
                    pass
        self.replicas.clear()
