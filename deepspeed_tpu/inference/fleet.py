"""Fleet front-end: prefix-affinity routing over N serving replicas.

Every PR so far hardens ONE :class:`ServingEngine`; the ROADMAP north
star needs N of them.  :class:`FleetRouter` owns multiple engines as
in-process fault domains and gives them a single engine-shaped surface
(``submit`` → ``step`` → ``finished`` / ``pop_terminated`` / ``drain``
/ ``health`` / ``leak_report``), built on three ideas:

* **Prefix-affinity routing.**  The routing key is the same rolling
  blake2b chain the prefix cache uses for content-hashed KV pages
  (``inference/prefix_cache.py``), computed over the first
  ``route_prefix_tokens`` prompt tokens — so requests that share a
  prefix land on the replica that already holds those pages, and
  per-replica hit rates stay at single-engine levels under fleet
  traffic.  Replica choice is rendezvous (highest-random-weight)
  hashing: each replica scores ``blake2b(key ‖ replica_id)`` and the
  highest healthy score wins, so a dead replica remaps ONLY its own
  keys and a respawn (same replica id, new epoch) re-takes its ring
  slot.
* **Supervision.**  A sweep every ``health_interval`` steps consults
  the fault injector (``replica_kill``), each replica's
  ``leak_report()`` (page/trace leaks ⇒ fence) and ``health()``
  (``recompile_storm`` ⇒ fence).  A *fenced* replica is drained
  through the graceful ``drain()`` path — finished work is delivered,
  shed work is redispatched; a *killed* replica is dropped abruptly
  and every request it owned is redispatched from scratch.  Either
  way the replica respawns with a fresh epoch (the
  :class:`RequestTracer` namespace, so a redispatched id re-admitted
  on the new engine cannot read as a double admit).
* **Zero lost requests.**  The fleet keeps its own request table and a
  fleet-level :class:`RequestTracer`: every submitted id ends in
  exactly one of the frozen trace terminals — delivered via
  ``finished``, or typed into ``pop_terminated()`` (deadline, shed,
  redispatch budget exhausted).  ``leak_report()`` audits that
  bookkeeping the same way the engine audits pages.

Dispatch atomicity follows the ``page_alloc`` idiom: the
``route_dispatch`` injector site is consulted BEFORE the routing table
or any engine mutates, so a faulted dispatch leaves the request exactly
where it was (pending) and it retries on the next step.

Scaling rides ``elasticity.ReplicaAutoscaler``: aggregated queue depth,
shed deltas, and the tightest free-page fraction feed hysteretic
one-replica-at-a-time decisions between ``min_replicas`` and
``max_replicas``.
"""

import hashlib
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import numpy as np

from deepspeed_tpu.elasticity.elastic_agent import ReplicaAutoscaler
from deepspeed_tpu.inference.robustness import (
    REJECT_BAD_REQUEST, REJECT_BAD_SAMPLING, REJECT_DRAINING,
    REJECT_DUPLICATE, REJECT_INFEASIBLE, REJECT_OVERSIZED, SHED_DEADLINE,
    SHED_DRAIN, RequestRejected, RequestResult, RequestTracer)
from deepspeed_tpu.monitor.telemetry import get_telemetry
from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel
from deepspeed_tpu.runtime.resilience import FaultInjector
from deepspeed_tpu.utils.logging import logger

# The frozen fleet/* event vocabulary.  scripts/check_telemetry_schema.py
# duplicates this tuple on purpose (the checker must not import the
# package); tests/unit/test_telemetry_schema.py diffs the two.
FLEET_EVENTS = (
    "fleet/spawn", "fleet/respawn", "fleet/route", "fleet/spill",
    "fleet/dispatch_fault", "fleet/redispatch", "fleet/kill",
    "fleet/fence", "fleet/drain", "fleet/shed",
    "fleet/scale_up", "fleet/scale_down",
)

# the closed set of replica supervision states (docs/serving.md)
REPLICA_STATES = ("healthy", "fenced", "dead")

# typed shed reason: the per-request redispatch budget ran out — the
# request bounced off too many dying/overloaded replicas
SHED_REDISPATCH_BUDGET = "redispatch_budget"

# engine rejection reasons that indict the REQUEST, not the replica —
# spilling these to another replica would just collect the same verdict,
# so the fleet terminates the request instead of retrying forever
_FATAL_REJECTS = (REJECT_BAD_REQUEST, REJECT_BAD_SAMPLING,
                  REJECT_OVERSIZED, REJECT_INFEASIBLE)


class FleetConfig(DeepSpeedConfigModel):
    """The ``serving.fleet`` config block (docs/config-json.md)."""

    replicas = 2                    # initial replica count
    min_replicas = 1                # supervision floor (respawn target)
    max_replicas = 8                # autoscale ceiling
    health_interval = 8             # fleet steps between supervision sweeps
    redispatch_max = 3              # per-request redispatch budget
    route_prefix_tokens = 0         # routing-key prefix len (0 = page_size)
    autoscale = False               # ReplicaAutoscaler on aggregate gauges
    scale_up_queue_per_replica = 8
    scale_down_queue_per_replica = 1
    free_page_low_frac = 0.1
    cooldown_sweeps = 8
    fault_injection = {}            # FaultInjector spec (fleet sites)

    def _validate(self):
        for k in ("replicas", "min_replicas", "health_interval"):
            if int(getattr(self, k)) < 1:
                raise ValueError(f"serving.fleet.{k} must be >= 1")
        for k in ("redispatch_max", "route_prefix_tokens",
                  "scale_up_queue_per_replica",
                  "scale_down_queue_per_replica", "cooldown_sweeps"):
            if int(getattr(self, k)) < 0:
                raise ValueError(f"serving.fleet.{k} must be >= 0")
        if int(self.max_replicas) < int(self.min_replicas):
            raise ValueError(
                "serving.fleet.max_replicas must be >= min_replicas")
        if not (int(self.min_replicas) <= int(self.replicas)
                <= int(self.max_replicas)):
            raise ValueError("serving.fleet.replicas must lie in "
                             "[min_replicas, max_replicas]")
        if not (0.0 <= float(self.free_page_low_frac) < 1.0):
            raise ValueError(
                "serving.fleet.free_page_low_frac must be in [0, 1)")


@dataclass
class _FleetRequest:
    """Fleet-side bookkeeping for one submitted request.  ``state`` walks
    pending → dispatched → (pending …) → finished | terminated; the
    dispatch counter enforces the redispatch budget."""
    req_id: Any
    prompt: List[int]
    kwargs: Dict[str, Any]
    route_key: bytes
    deadline: float = 0.0           # absolute fleet-clock time; 0 = none
    state: str = "pending"
    replica_id: Optional[str] = None
    dispatches: int = 0


@dataclass
class _Replica:
    replica_id: str
    epoch: str
    engine: Any
    state: str = "healthy"


class FleetRouter:
    """N in-process :class:`ServingEngine` fault domains behind one
    engine-shaped front-end.

    ``engine_factory(replica_id, epoch)`` builds one replica; the factory
    MUST pass ``replica_epoch=epoch`` through to the engine so respawned
    replicas book traces under a fresh namespace.  Every engine should be
    built from the same (model, params, config) for bit-identical
    redispatch — a request's output depends only on (prompt, sampling
    params, seed), never on which replica or batch served it.
    """

    def __init__(self, engine_factory, fleet=None, injector=None,
                 telemetry=None, clock=None):
        cfg = fleet if isinstance(fleet, FleetConfig) \
            else FleetConfig(fleet or {})
        self.fleet = cfg
        self._factory = engine_factory
        self._clock = clock if clock is not None else time.monotonic
        self._telemetry = telemetry
        self.injector = injector if injector is not None \
            else FaultInjector.from_config(cfg.fault_injection)
        self.replicas: Dict[str, _Replica] = {}
        self.requests: Dict[Any, _FleetRequest] = {}
        self.pending = deque()          # req_ids awaiting (re)dispatch
        self.finished: Dict[Any, List[int]] = {}
        self.terminated: Dict[Any, RequestResult] = {}
        self.tracer = RequestTracer(clock=self._clock)
        self.draining = False
        self.steps = 0
        self.stats = {"submitted": 0, "finished": 0, "terminated": 0,
                      "shed": 0, "deadline": 0, "redispatches": 0,
                      "spills": 0, "dispatch_faults": 0, "kills": 0,
                      "fences": 0, "respawns": 0, "scale_ups": 0,
                      "scale_downs": 0}
        self._gens: Dict[str, int] = {}     # replica_id -> spawn generation
        self._next_rid = 0
        self._target = int(cfg.replicas)
        self._last_shed_total = 0
        self._autoscaler = ReplicaAutoscaler(
            min_replicas=int(cfg.min_replicas),
            max_replicas=int(cfg.max_replicas),
            scale_up_queue_per_replica=int(cfg.scale_up_queue_per_replica),
            scale_down_queue_per_replica=int(
                cfg.scale_down_queue_per_replica),
            free_page_low_frac=float(cfg.free_page_low_frac),
            cooldown_sweeps=int(cfg.cooldown_sweeps)) \
            if cfg.autoscale else None
        # the routing key hashes the first N prompt tokens; N defaults to
        # one KV page so the key matches exactly the prefix-cache chain
        # key of the request's first page
        self._route_tokens = int(cfg.route_prefix_tokens)
        self._route_root = hashlib.blake2b(
            b"ds:fleet-route", digest_size=16).digest()
        for _ in range(int(cfg.replicas)):
            self._spawn()
        self.attach_exporter()

    # -- plumbing --------------------------------------------------------
    def _tel(self):
        tel = self._telemetry if self._telemetry is not None \
            else get_telemetry()
        return tel if (tel is not None and tel.enabled) else None

    def _fleet_event(self, name, **attrs):
        tel = self._tel()
        if tel is not None:
            tel.fleet(name, step=self.steps,
                      attrs={k: v for k, v in attrs.items()
                             if v is not None} or None)

    def _incident(self, kind, source="", detail=""):
        """Open an incident bundle (monitor/incidents.py) for a fleet
        verdict — replica kills and fences; no-op without the plane."""
        tel = self._tel()
        incidents = getattr(tel, "incidents", None) if tel else None
        if incidents is not None:
            incidents.trigger(kind, source=source, detail=detail,
                              step=self.steps)

    def attach_exporter(self):
        """Bind this router's :meth:`health` behind the telemetry
        exporter's ``GET /fleet`` endpoint (no-op without an exporter),
        and register it as incident-bundle context when the incident
        plane is on."""
        tel = self._telemetry if self._telemetry is not None \
            else get_telemetry()
        exporter = getattr(tel, "exporter", None)
        if exporter is not None:
            exporter.fleet_fn = self.health
        incidents = getattr(tel, "incidents", None)
        if incidents is not None:
            incidents.add_context("fleet_health", self.health)

    # -- replica lifecycle ----------------------------------------------
    def _spawn(self, replica_id=None, respawn=False):
        rid = replica_id
        if rid is None:
            rid = f"r{self._next_rid}"
            self._next_rid += 1
        gen = self._gens.get(rid, -1) + 1
        self._gens[rid] = gen
        epoch = f"{rid}g{gen}"
        engine = self._factory(rid, epoch)
        rep = _Replica(rid, epoch, engine)
        self.replicas[rid] = rep
        if self._route_tokens == 0:
            self._route_tokens = int(engine.page_size)
        if respawn:
            self.stats["respawns"] += 1
        self._fleet_event("fleet/respawn" if respawn else "fleet/spawn",
                          replica=rid, epoch=epoch)
        return rep

    def _healthy(self) -> List[_Replica]:
        return [r for r in self.replicas.values() if r.state == "healthy"]

    def _retire(self, rep: _Replica):
        """Drop a replica from the routing ring (engine already drained
        or abandoned); its fleet requests must have been re-homed."""
        self.replicas.pop(rep.replica_id, None)

    def _requeue_owned(self, rep: _Replica) -> List[Any]:
        """Every fleet request dispatched to ``rep`` goes back to pending
        (redispatch-from-scratch) — or to a typed terminal when its
        redispatch budget is spent."""
        moved = []
        for fr in self.requests.values():
            if fr.state == "dispatched" and \
                    fr.replica_id == rep.replica_id:
                self._requeue(fr)
                moved.append(fr.req_id)
        return moved

    def _requeue(self, fr: _FleetRequest):
        if fr.dispatches > int(self.fleet.redispatch_max):
            self._shed_terminal(
                fr, SHED_REDISPATCH_BUDGET,
                detail=f"{fr.dispatches} dispatches exhausted the "
                       f"redispatch budget {self.fleet.redispatch_max}")
            return
        fr.state = "pending"
        fr.replica_id = None
        self.pending.append(fr.req_id)
        if fr.dispatches:
            self.stats["redispatches"] += 1
            self._fleet_event("fleet/redispatch", req_id=fr.req_id,
                              dispatches=fr.dispatches)

    def kill_replica(self, replica_id, detail="killed"):
        """Abrupt replica death (the ``replica_kill`` injector path, also
        callable directly from tests/chaos drills): NO drain — the engine
        is dropped mid-flight and every request it owned is redispatched
        from scratch to the surviving ring."""
        rep = self.replicas.get(replica_id)
        if rep is None or rep.state == "dead":
            return
        rep.state = "dead"
        self.stats["kills"] += 1
        moved = self._requeue_owned(rep)
        logger.warning(
            f"fleet: replica {replica_id} ({rep.epoch}) killed: {detail}; "
            f"redispatching {len(moved)} requests")
        self._fleet_event("fleet/kill", replica=replica_id,
                          epoch=rep.epoch, redispatched=len(moved),
                          detail=detail)
        self._incident("replica_kill", source=str(replica_id),
                       detail=f"{detail}; redispatched {len(moved)}")
        self._retire(rep)

    def _fence(self, rep: _Replica, why: str):
        """Graceful failover: stop routing to the replica, drain it (its
        finished work is delivered, its shed work redispatched), then
        retire it.  The respawn happens on the next ``step``."""
        rep.state = "fenced"
        self.stats["fences"] += 1
        self._fleet_event("fleet/fence", replica=rep.replica_id,
                          epoch=rep.epoch, reason=why)
        self._incident("replica_fence", source=str(rep.replica_id),
                       detail=why)
        try:
            res = rep.engine.drain()
        except Exception as e:   # a broken drain degrades to a kill
            rep.state = "healthy"   # let kill_replica see it live
            self.kill_replica(rep.replica_id,
                              detail=f"drain failed while fencing: {e}")
            return
        self._collect_finished(rep, res["finished"])
        self._collect_terminated(rep)
        self._fleet_event("fleet/drain", replica=rep.replica_id,
                          finished=len(res["finished"]),
                          shed=len(res["shed"]), steps=res["steps"])
        self._requeue_owned(rep)
        self._retire(rep)

    # -- routing ---------------------------------------------------------
    def _route_key(self, prompt: List[int]) -> bytes:
        """Rolling blake2b chain over the first ``route_prefix_tokens``
        prompt tokens — the prefix-cache chain-key idiom, so shared
        prefixes share a routing key."""
        h = hashlib.blake2b(self._route_root, digest_size=16)
        n = self._route_tokens or len(prompt)
        h.update(np.asarray(prompt[:n], np.int64).tobytes())
        return h.digest()

    def _pick(self, key: bytes) -> Optional[_Replica]:
        """Rendezvous hashing: highest ``blake2b(key ‖ replica_id)``
        among healthy replicas.  Membership changes only remap keys whose
        winner died; a respawn under the same replica_id re-takes its
        slot."""
        best, best_score = None, None
        for rep in self._healthy():
            h = hashlib.blake2b(key, digest_size=8)
            h.update(rep.replica_id.encode())
            score = (int.from_bytes(h.digest(), "big"), rep.replica_id)
            if best_score is None or score > best_score:
                best, best_score = rep, score
        return best

    def _dispatch(self, fr: _FleetRequest) -> bool:
        """One dispatch attempt.  The injector is consulted BEFORE the
        routing table or any engine mutates (the page_alloc atomicity
        idiom): a fault here leaves the request exactly as it was and it
        retries on the next step.  Returns True when the request left the
        pending state (dispatched OR typed into a terminal)."""
        if self.injector is not None:
            self.injector.check("route_dispatch")
        now = self._clock()
        if fr.deadline and now >= fr.deadline:
            self._deadline_terminal(fr)
            return True
        target = self._pick(fr.route_key)
        if target is None:
            return False                 # no healthy replicas right now
        # affinity target first; spill order by least load
        order = [target] + sorted(
            (r for r in self._healthy() if r is not target),
            key=lambda r: (len(r.engine.queue) + r.engine.n_active,
                           r.replica_id))
        rejects = []
        for i, rep in enumerate(order):
            kwargs = dict(fr.kwargs)
            if fr.deadline:
                kwargs["deadline_s"] = fr.deadline - now
            try:
                rep.engine.add_request(fr.req_id, fr.prompt, **kwargs)
            except RequestRejected as e:
                rejects.append(e)
                continue
            fr.state = "dispatched"
            fr.replica_id = rep.replica_id
            fr.dispatches += 1
            if i > 0:
                self.stats["spills"] += 1
                self._fleet_event("fleet/spill", req_id=fr.req_id,
                                  replica=rep.replica_id,
                                  affinity=target.replica_id)
            self._fleet_event("fleet/route", req_id=fr.req_id,
                              replica=rep.replica_id,
                              dispatches=fr.dispatches)
            return True
        # every healthy replica said no — a request-indicting reason
        # terminates (another replica would say the same); overload keeps
        # it pending for the next step
        fatal = next((e for e in rejects if e.reason in _FATAL_REJECTS),
                     None)
        if fatal is not None:
            self._shed_terminal(fr, fatal.reason, detail=fatal.detail)
            return True
        return False

    def _pump_pending(self):
        """Try to place every pending request; whatever cannot be placed
        (injected dispatch fault, fleet-wide overload, no healthy
        replicas) stays pending for the next step."""
        for _ in range(len(self.pending)):
            rid = self.pending.popleft()
            fr = self.requests[rid]
            if fr.state != "pending":
                continue
            try:
                placed = self._dispatch(fr)
            except Exception as e:      # injected route_dispatch fault
                self.stats["dispatch_faults"] += 1
                self._fleet_event("fleet/dispatch_fault", req_id=rid,
                                  error=str(e))
                self.pending.append(rid)
                continue
            if not placed:
                self.pending.append(rid)

    # -- terminals -------------------------------------------------------
    def _finish_fleet(self, fr: _FleetRequest, tokens: List[int]):
        fr.state = "finished"
        self.finished[fr.req_id] = tokens
        self.stats["finished"] += 1
        self.tracer.terminal(
            fr.req_id, "finish",
            n_generated=max(0, len(tokens) - len(fr.prompt)))

    def _shed_terminal(self, fr: _FleetRequest, reason: str,
                       detail: str = ""):
        fr.state = "terminated"
        self.terminated[fr.req_id] = RequestResult(
            fr.req_id, "shed", reason, detail=detail)
        self.stats["terminated"] += 1
        self.stats["shed"] += 1
        self.tracer.terminal(fr.req_id, "shed", reason=reason)
        self._fleet_event("fleet/shed", req_id=fr.req_id, reason=reason)

    def _deadline_terminal(self, fr: _FleetRequest,
                           result: Optional[RequestResult] = None):
        fr.state = "terminated"
        self.terminated[fr.req_id] = result if result is not None else \
            RequestResult(fr.req_id, "deadline", SHED_DEADLINE,
                          detail="expired before dispatch")
        self.stats["terminated"] += 1
        self.stats["deadline"] += 1
        self.tracer.terminal(
            fr.req_id, "deadline",
            n_generated=result.n_generated if result else 0,
            reason=SHED_DEADLINE)

    def _collect_finished(self, rep: _Replica, done: Dict[Any, List[int]]):
        for rid, tokens in done.items():
            fr = self.requests.get(rid)
            if fr is not None and fr.state == "dispatched" and \
                    fr.replica_id == rep.replica_id:
                self._finish_fleet(fr, tokens)

    def _collect_terminated(self, rep: _Replica):
        """Fold one replica's typed terminals into fleet state: deadlines
        are final (the TTL is absolute), everything else — shed, evicted,
        drained — is the REPLICA's fault, so the request redispatches
        while its budget lasts."""
        for rid, result in rep.engine.pop_terminated().items():
            fr = self.requests.get(rid)
            if fr is None or fr.state != "dispatched" or \
                    fr.replica_id != rep.replica_id:
                continue
            if result.status == "deadline":
                self._deadline_terminal(fr, result)
            else:
                self._requeue(fr)

    # -- public surface --------------------------------------------------
    def submit(self, req_id, prompt_ids, max_new_tokens: int = 32,
               temperature: float = 0.0, seed: int = 0, top_k: int = 0,
               top_p: float = 1.0, deadline_s: Optional[float] = None):
        """Register one request with the fleet and try to place it.
        Raises typed :class:`RequestRejected` only for conditions the
        fleet can see without an engine (duplicate id, draining); every
        other failure mode resolves asynchronously into a typed terminal
        in :meth:`pop_terminated` — nothing is ever silently dropped."""
        if self.draining:
            raise RequestRejected(req_id, REJECT_DRAINING,
                                  "fleet is draining; admission stopped")
        if req_id in self.requests:
            raise RequestRejected(req_id, REJECT_DUPLICATE,
                                  "req_id already submitted to the fleet")
        prompt = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        now = self._clock()
        fr = _FleetRequest(
            req_id, prompt,
            kwargs=dict(max_new_tokens=int(max_new_tokens),
                        temperature=float(temperature), seed=int(seed),
                        top_k=int(top_k), top_p=float(top_p)),
            route_key=self._route_key(prompt),
            deadline=(now + deadline_s) if deadline_s else 0.0)
        self.requests[req_id] = fr
        self.pending.append(req_id)
        self.stats["submitted"] += 1
        self.tracer.admit(req_id, deadline=fr.deadline, now=now)
        self._pump_pending()

    def step(self) -> Dict[Any, List[int]]:
        """Advance the whole fleet: retry pending dispatches, step every
        replica (an engine that raises is killed and its requests
        redispatched), fold terminals, run the supervision sweep on its
        interval, and respawn up to the target replica count.  Returns
        the requests that finished THIS step (req_id → full tokens), like
        ``ServingEngine.step``."""
        self.steps += 1
        self._pump_pending()
        done_now: Dict[Any, List[int]] = {}
        for rep in list(self.replicas.values()):
            if rep.state != "healthy":
                continue
            try:
                done = rep.engine.step()
            except Exception as e:
                self.kill_replica(rep.replica_id,
                                  detail=f"step raised: {e}")
                continue
            before = set(self.finished)
            self._collect_finished(rep, done)
            self._collect_terminated(rep)
            for rid in set(self.finished) - before:
                done_now[rid] = self.finished[rid]
        if self.steps % int(self.fleet.health_interval) == 0:
            self._supervise()
        self._ensure_target()
        return done_now

    def pop_terminated(self) -> Dict[Any, RequestResult]:
        """Hand back (and clear) every fleet-level typed terminal since
        the last call (deadline expiries, redispatch-budget sheds,
        drain sheds)."""
        out = self.terminated
        self.terminated = {}
        return out

    def join(self, max_steps: int = 10_000) -> Dict[Any, List[int]]:
        """Step until every submitted request reaches a terminal (or the
        step budget runs out); returns everything finished meanwhile."""
        done: Dict[Any, List[int]] = {}
        for _ in range(max_steps):
            if not self._unresolved():
                break
            done.update(self.step())
        return done

    def _unresolved(self) -> int:
        return sum(1 for fr in self.requests.values()
                   if fr.state in ("pending", "dispatched"))

    # -- supervision -----------------------------------------------------
    def _supervise(self):
        for rep in list(self.replicas.values()):
            if rep.state != "healthy":
                continue
            if self.injector is not None:
                try:
                    self.injector.check("replica_kill")
                except Exception as e:
                    self.kill_replica(rep.replica_id, detail=str(e))
                    continue
            try:
                leaks = rep.engine.leak_report()
                storm = bool(rep.engine.health().get("recompile_storm"))
            except Exception as e:
                self.kill_replica(rep.replica_id,
                                  detail=f"health check raised: {e}")
                continue
            if leaks:
                self._fence(rep, f"leak_report: {sorted(leaks)}")
            elif storm:
                self._fence(rep, "recompile_storm")
        self._autoscale()

    def _autoscale(self):
        if self._autoscaler is None:
            return
        healthy = self._healthy()
        queue_depth = len(self.pending) + sum(
            len(r.engine.queue) for r in healthy)
        shed_total = self.stats["shed"] + sum(
            r.engine.stats["shed"] for r in healthy)
        shed_delta = max(0, shed_total - self._last_shed_total)
        self._last_shed_total = shed_total
        fracs = [r.engine.alloc.free_page_count /
                 max(1, r.engine.alloc.num_pages - 1) for r in healthy]
        desired = self._autoscaler.decide(
            max(1, len(healthy)), queue_depth=queue_depth,
            shed_delta=shed_delta,
            free_page_frac=min(fracs) if fracs else 1.0)
        if desired > self._target:
            self.stats["scale_ups"] += 1
            self._fleet_event("fleet/scale_up", replicas=desired,
                              queue_depth=queue_depth)
        elif desired < self._target:
            self.stats["scale_downs"] += 1
            self._fleet_event("fleet/scale_down", replicas=desired,
                              queue_depth=queue_depth)
            # retire the least-loaded healthy replica gracefully
            victim = min(
                self._healthy(),
                key=lambda r: (len(r.engine.queue) + r.engine.n_active,
                               r.replica_id),
                default=None)
            if victim is not None:
                self._fence(victim, "scale_down")
        self._target = desired

    def _ensure_target(self):
        """Respawn (dead ring slots first, so rendezvous affinity is
        restored) until the fleet is back at the target size."""
        floor = max(int(self.fleet.min_replicas), self._target)
        while len(self.replicas) < floor:
            dead = sorted(set(self._gens) - set(self.replicas))
            self._spawn(replica_id=dead[0] if dead else None,
                        respawn=bool(dead))

    # -- lifecycle / introspection ---------------------------------------
    def drain(self) -> Dict[str, Any]:
        """Quiesce the whole fleet: stop admission, drain every replica
        (delivering what finishes), then shed whatever is still pending
        — every submitted request ends in ``finished`` or a typed
        terminal."""
        self.draining = True
        finished: Dict[Any, List[int]] = {}
        for rep in list(self.replicas.values()):
            if rep.state != "healthy":
                continue
            before = set(self.finished)
            self._fence(rep, "fleet drain")
            for rid in set(self.finished) - before:
                finished[rid] = self.finished[rid]
        shed_ids = []
        for rid in list(self.pending):
            fr = self.requests[rid]
            if fr.state == "pending":
                self._shed_terminal(fr, SHED_DRAIN,
                                    detail="shed by fleet drain()")
                shed_ids.append(rid)
        self.pending.clear()
        return {"finished": finished, "shed": shed_ids,
                "health": self.health()}

    def health(self) -> Dict[str, Any]:
        """Fleet snapshot: per-replica supervision state + condensed
        engine health, aggregate load, counters, and the fleet-level
        trace ledger.  Aggregate gauges are mirrored onto the telemetry
        registry (``fleet/*``) and the whole dict is served by the
        exporter's ``GET /fleet``."""
        per_replica = {}
        queue_depth = len(self.pending)
        for rep in self.replicas.values():
            eng = rep.engine
            per_replica[rep.replica_id] = {
                "state": rep.state,
                "epoch": rep.epoch,
                "queue_depth": len(eng.queue),
                "active_slots": eng.n_active,
                "free_pages": eng.alloc.free_page_count,
                "prefix_hit_rate": (
                    eng.prefix_cache.snapshot()["hit_rate"]
                    if eng.prefix_cache is not None else None),
            }
            queue_depth += len(eng.queue)
        snap = {
            "replicas": per_replica,
            "n_replicas": len(self.replicas),
            "n_healthy": len(self._healthy()),
            "target_replicas": self._target,
            "pending": len(self.pending),
            "in_flight": self._unresolved(),
            "queue_depth": queue_depth,
            "draining": self.draining,
            "counters": dict(self.stats),
            "traces": {"open": len(self.tracer.open),
                       "admitted": self.tracer.admitted,
                       "closed": self.tracer.closed,
                       "terminals": dict(self.tracer.terminals)},
        }
        tel = self._tel()
        if tel is not None:
            for gauge, key in (("fleet/replicas", "n_replicas"),
                               ("fleet/healthy", "n_healthy"),
                               ("fleet/pending", "pending"),
                               ("fleet/queue_depth", "queue_depth")):
                tel.registry.gauge(gauge).set(snap[key])
            tel.registry.gauge("fleet/redispatches").set(
                self.stats["redispatches"])
        return snap

    def leak_report(self) -> Dict[str, Any]:
        """Fleet invariant audit, {} when clean: every live replica's own
        ``leak_report()`` (keys prefixed ``<replica_id>:``), the
        fleet-level trace-completeness audit, and the bookkeeping
        identity submitted == finished + terminated + unresolved."""
        leaks: Dict[str, Any] = {}
        for rep in self.replicas.values():
            for k, v in rep.engine.leak_report().items():
                leaks[f"{rep.replica_id}:{k}"] = v
        live = [fr.req_id for fr in self.requests.values()
                if fr.state in ("pending", "dispatched")]
        leaks.update(self.tracer.audit(live))
        resolved = self.stats["finished"] + self.stats["terminated"]
        if self.stats["submitted"] != resolved + self._unresolved():
            leaks["fleet_count_mismatch"] = {
                "submitted": self.stats["submitted"],
                "finished": self.stats["finished"],
                "terminated": self.stats["terminated"],
                "unresolved": self._unresolved()}
        return leaks
