"""Inference engine.

Parity: reference ``inference/engine.py:35`` (``InferenceEngine``: dtype
conversion, TP group creation ``_create_model_parallel_group:201``, kernel
injection ``_apply_injection_policy:349``, CUDA-graph capture ``:479``,
``forward:541``, ``_generate:571``).

TPU design: "kernel injection" and "CUDA graphs" collapse into jitting the
decode step — XLA compiles the whole token step into one program (the graph)
with fused kernels.  Auto-TP is a sharding plan: model ``tp_rules`` place the
weights over the ``tp`` axis and XLA inserts the row-parallel all-reduces the
reference performs explicitly after attention/MLP.  The KV cache is a
static-shape ring buffer (``ops/decode_attention.py``) so decode never
retraces.
"""

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu import comm as dist
from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
from deepspeed_tpu.parallel import groups
from deepspeed_tpu.parallel.topology import TP_AXIS, TopologyConfig
from deepspeed_tpu.runtime.zero.stage_plan import ZeroShardingPlan
from deepspeed_tpu.utils.logging import log_dist, logger

DTYPES = {"float32": jnp.float32, "fp32": jnp.float32,
          "float16": jnp.float16, "fp16": jnp.float16, "half": jnp.float16,
          "bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16,
          "int8": jnp.int8}


class InferenceEngine:
    """Wraps a model (our ``CausalTransformerLM`` or any object exposing
    ``apply_with_cache``/``init_caches``) for sharded generation."""

    def __init__(self, model, config: DeepSpeedInferenceConfig, params=None,
                 mesh=None):
        self.module = model
        self._config = config
        self.dtype = DTYPES.get(str(config.dtype), jnp.bfloat16)

        dist.init_distributed()
        # TP mesh (reference _create_model_parallel_group)
        if mesh is None:
            tp = max(1, config.tp_size)
            mesh = groups.initialize_mesh(
                TopologyConfig(tp=tp, fsdp=-1))
        self.mesh = mesh

        self.params = None
        self._streaming = False
        if params is None and config.checkpoint:
            params = self.load_model_with_checkpoint(config.checkpoint)
        if params is not None:
            self.set_params(params)
        elif hasattr(model, "params"):
            self.set_params(model.params)

        self._compiled_prefill = None
        self._compiled_decode = None
        self._compiled_generate = {}
        log_dist(f"InferenceEngine ready: dtype={self.dtype.__name__} "
                 f"tp={config.tp_size} mesh={dict(self.mesh.shape)}", ranks=[0])

    # ------------------------------------------------------------------
    def set_params(self, params):
        """Cast + shard weights (reference dtype convert + weight slicing in
        module_inject; here: device_put with TP/fsdp shardings).

        With int8/quantized configs the weights are stored groupwise int8 +
        scales (reference ``GroupQuantizer``/ZeroQuant weight-only path) and
        dequantised inside the jitted step — XLA fuses the dequant into the
        consuming matmul, so HBM holds 1 byte/weight."""
        tp_rules = (self.module.tp_rules()
                    if hasattr(self.module, "tp_rules") else None)
        # stage-3-style sharding over fsdp for memory, + tp rules: this is
        # ZeRO-Inference (reference engine.py:1581 offload-for-inference)
        plan = ZeroShardingPlan(self.mesh, stage=3, tp_rules=tp_rules,
                                param_persistence_threshold=0)
        self.plan = plan
        # quant policy resolved ONCE, before the offload branch, so the
        # streaming and dense paths cannot disagree (and the int8→bf16
        # compute-dtype fix lands before any np_dtype derivation)
        qc = self._config.quant
        self._quantized = bool(qc.enabled) or str(
            self._config.dtype) in ("int8", "torch.int8")
        if self._quantized:
            self._quant_bits = int(qc.num_bits)
            self._quant_group_size = int(qc.group_size)
            if self.dtype == jnp.int8:      # int8 stores, bf16 computes
                self.dtype = jnp.bfloat16
        offload = dict(self._config.zero or {}).get("offload_param") or {}
        if offload.get("device") in ("cpu", "nvme"):
            return self._set_params_streaming(params, offload)
        if self._quantized:
            cast = self._quantize_tree(params)
        else:
            cast = jax.tree_util.tree_map(
                lambda x: x.astype(self.dtype)
                if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
                else jnp.asarray(x), params)
        with self.mesh:
            self.params = jax.device_put(cast, plan.param_shardings(cast))

    # ---- ZeRO-Inference weight streaming ------------------------------
    def _set_params_streaming(self, params, offload):
        """ZeRO-Inference for models larger than HBM: transformer-layer
        weights live on the host (or NVMe) and stream to the device
        layer-by-layer, double-buffered so the transfer of layer i+1
        overlaps layer i's compute (reference: ZeRO-3 param offload reused
        for inference, docs 2022-09-10-zero-inference.md)."""
        assert hasattr(self.module, "config") and \
            hasattr(self.module, "_layer_cached"), \
            "weight streaming needs a CausalTransformerLM-style module"
        c = self.module.config
        np_dtype = np.dtype(jnp.bfloat16 if self.dtype == jnp.bfloat16
                            else np.float32)
        # int8 weight streaming (quant policy resolved by set_params): the
        # per-layer H2D upload is THE bottleneck of streamed inference —
        # groupwise int8 + scales halves it vs bf16 (reference:
        # ZeRO-Inference composes with ZeroQuant weight quantization for
        # exactly this reason).  int8 composes with NVMe too: the tiered
        # store keeps qv/qs/qz as separate manifest-listed files, so the
        # per-group scale sidecars survive the disk round trip.

        def host_cast(x):
            x = np.asarray(x)
            return x.astype(np_dtype) \
                if jnp.issubdtype(x.dtype, jnp.floating) else x

        def host_leaf(k, x):
            """One layer leaf: quantize matmul weights when int8 streaming
            is on (on the HOST backend), cast the rest."""
            x = np.asarray(x)
            if self._quantized and \
                    jnp.issubdtype(x.dtype, jnp.floating) and \
                    self._is_linear_weight([k], x):
                from deepspeed_tpu.ops.quantizer import quantize
                groups = (x.size // self._quant_group_size
                          if x.size % self._quant_group_size == 0 else 1)
                with jax.default_device(jax.devices("cpu")[0]):
                    qt = quantize(x, groups=max(1, groups),
                                  num_bits=self._quant_bits)
                return {"qv": np.asarray(qt.values),
                        "qs": np.asarray(qt.scale),
                        "qz": np.asarray(qt.zero_point)}
            return host_cast(x)

        layers = params["layers"]
        assert not isinstance(layers, (list, tuple)), \
            "streaming expects the stacked-layer layout"
        self._n_layers = c.n_layers
        host_layers = [
            {k: host_leaf(k, v[i]) for k, v in layers.items()}
            for i in range(c.n_layers)]
        self._tiered = None
        if offload.get("device") == "nvme":
            from deepspeed_tpu.runtime.tiered_store import (PlacementPolicy,
                                                            TieredStore)
            # read-only placement over the tiered store: every layer leaf
            # is one NVMe entry (int8 leaves are {qv,qs,qz} multi-file
            # entries — the scale sidecars land in the manifest), and the
            # store seals the directory with the checkpoint protocol's
            # manifest + marker so ds_ckpt_fsck classifies a torn weight
            # file before it serves garbage tokens
            self._tiered = TieredStore(
                name="zero_inference_params",
                nvme_dir=str(offload.get("nvme_path") or "/tmp"),
                policy=PlacementPolicy(default_tier="nvme", read_only=True),
                aio_config=dict(offload.get("aio") or {}))
            self._layer_keys = [sorted(host_layers[0].keys())] * c.n_layers
            for i, hl in enumerate(host_layers):
                for k, v in hl.items():
                    self._tiered.put(f"L{i}.{k}", v, tier="nvme")
            self._tiered.commit()
            self._host_layers = None
            log_dist(f"ZeRO-Inference: {c.n_layers} layers on NVMe at "
                     f"{self._tiered.nvme_path}", ranks=[0])
        else:
            self._host_layers = host_layers
            log_dist(f"ZeRO-Inference: {c.n_layers} layers in host RAM",
                     ranks=[0])

        rest = {k: v for k, v in params.items() if k != "layers"}
        cast = jax.tree_util.tree_map(
            lambda x: jnp.asarray(x).astype(self.dtype)
            if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
            else jnp.asarray(x), rest)
        with self.mesh:
            self.params = jax.device_put(cast,
                                         self.plan.param_shardings(cast))
        self._streaming = True
        self._jit_layer = None
        self._jit_embed = None
        self._jit_head = None

    def _layer_entry_keys(self, i):
        return [f"L{i}.{k}" for k in self._layer_keys[i]]

    def _issue_layer_reads(self, i):
        """Queue async NVMe reads for layer ``i`` (they run while the
        device crunches earlier layers)."""
        if self._tiered is None or not (0 <= i < self._n_layers):
            return
        self._tiered.prefetch(self._layer_entry_keys(i))

    def _fetch_layer(self, i):
        """Host/NVMe → device.  Host path: device_put returns before the
        transfer completes, so it overlaps compute.  NVMe path: reads were
        issued earlier by ``_issue_layer_reads`` (a cold fetch is a demand
        miss the ``tier/*`` gauges expose) and land here, after the
        previous layer's compute was dispatched."""
        if self._host_layers is not None:
            return jax.device_put(self._host_layers[i])
        keys = self._layer_entry_keys(i)
        self._issue_layer_reads(i)
        host = self._tiered.fetch_group(keys)
        dev = jax.device_put(host)
        for k in keys:
            # drop staging caches so host RAM holds at most the prefetch
            # window, not the model — the NVMe files stay authoritative
            self._tiered.evict(k)
        return dev

    def _streaming_apply_with_cache(self, input_ids, caches):
        """Layer-streamed twin of ``CausalTransformerLM.apply_with_cache``
        (list-of-caches layout; weights fetched per layer)."""
        model, c = self.module, self.module.config
        input_ids = jnp.asarray(input_ids, jnp.int32)
        B, T = input_ids.shape
        start = caches[0].length

        if self._jit_embed is None:
            def embed(rest, ids, start):
                positions = start + jnp.broadcast_to(
                    jnp.arange(ids.shape[1])[None, :], ids.shape)
                x = rest["tok_embed"][ids]
                if not c.use_rope:
                    x = x + rest["pos_embed"][positions].astype(x.dtype)
                return x, positions
            self._jit_embed = jax.jit(embed)

            def layer_step(layer, x, ck, cv, length, positions):
                layer = self._maybe_dequant(layer)   # int8 streams dequant
                return model._layer_cached(x, layer, ck, cv, length,
                                           positions)
            self._jit_layer = jax.jit(layer_step)

            def head(rest, x):
                from deepspeed_tpu.models.transformer import _norm
                x = _norm(x, rest["final_norm"], c.norm_eps, c.use_rmsnorm,
                          rest.get("final_norm_b"))
                hd = (rest["tok_embed"].T if c.tie_embeddings
                      else rest["lm_head"])
                return (x @ hd.astype(x.dtype)).astype(jnp.float32)
            self._jit_head = jax.jit(head)

        x, positions = self._jit_embed(self.params, input_ids, start)
        new_caches = []
        nxt = self._fetch_layer(0)
        self._issue_layer_reads(1)
        for i in range(self._n_layers):
            # dispatch layer i (async on device), THEN wait for layer
            # i+1's host/NVMe transfer — so I/O overlaps compute
            x, cache = self._jit_layer(nxt, x, caches[i].k, caches[i].v,
                                       start, positions)
            new_caches.append(cache)
            if i + 1 < self._n_layers:
                nxt = self._fetch_layer(i + 1)
                self._issue_layer_reads(i + 2)
        if self._tiered is not None:
            self._tiered.publish_gauges()
        return self._jit_head(self.params, x), new_caches

    def _streaming_generate(self, input_ids, max_new_tokens):
        from deepspeed_tpu.ops.decode_attention import init_cache
        c = self.module.config
        input_ids = jnp.asarray(input_ids, jnp.int32)
        B, S = input_ids.shape
        caches = [init_cache(B, S + max_new_tokens, c.kv_heads, c.head_dim,
                             self.dtype) for _ in range(self._n_layers)]
        logits, caches = self._streaming_apply_with_cache(input_ids, caches)
        toks = [jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)]
        for _ in range(max_new_tokens - 1):
            logits, caches = self._streaming_apply_with_cache(
                toks[-1][:, None], caches)
            toks.append(jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32))
        return jnp.concatenate([input_ids] +
                               [t[:, None] for t in toks], axis=1)

    # ---- weight-only quantization ------------------------------------
    @staticmethod
    def _is_qleaf(x):
        return isinstance(x, dict) and "qv" in x and "qs" in x

    @staticmethod
    def _is_linear_weight(path, x):
        """Weight-only quantization targets matmul weights only — the
        reference ZeroQuant path never quantizes norm scales/biases or
        embeddings (doing so needlessly degrades accuracy)."""
        name = str(path[-1]).strip("'[]") if path else ""
        lname = name.lower()
        if "norm" in lname or "embed" in lname or lname.endswith("_b") \
                or "bias" in lname:
            return False
        if lname == "wg":
            # MoE router gate: kept fp32 by the model for routing
            # precision — quantizing it can flip expert assignments
            return False
        # stacked layout: linear weights are [L, in, out] (3-D) or plain
        # [in, out] (2-D, e.g. lm_head / per-layer MoE dicts)
        return x.ndim >= 2

    def _quantize_tree(self, params):
        from deepspeed_tpu.ops.quantizer import quantize

        def q(path, x):
            x = jnp.asarray(x)
            if not jnp.issubdtype(x.dtype, jnp.floating):
                return x
            name = (str(path[-1]).strip("'[]") if path else "").lower()
            if name == "wg":
                return x  # router gate stays in its fp32 compute dtype
            if self._is_linear_weight(path, x):
                groups = (x.size // self._quant_group_size
                          if x.size % self._quant_group_size == 0 else 1)
                qt = quantize(x, groups=max(1, groups),
                              num_bits=self._quant_bits)
                return {"qv": qt.values, "qs": qt.scale, "qz": qt.zero_point}
            return x.astype(self.dtype)
        return jax.tree_util.tree_map_with_path(q, params)

    def _maybe_dequant(self, params):
        """Inside-jit dequant of quantized leaves (fused by XLA)."""
        if not getattr(self, "_quantized", False):
            return params
        from deepspeed_tpu.ops.quantizer import QuantizedTensor, dequantize

        def dq(x):
            if self._is_qleaf(x):
                qt = QuantizedTensor(
                    values=x["qv"], scale=x["qs"], zero_point=x["qz"],
                    num_bits=self._quant_bits, group_shape=x["qv"].shape,
                    symmetric=True)
                return dequantize(qt, dtype=self.dtype)
            return x
        return jax.tree_util.tree_map(dq, params, is_leaf=self._is_qleaf)

    # ------------------------------------------------------------------
    def load_model_with_checkpoint(self, checkpoint: str):
        """Load weights from a training checkpoint dir (orbax layout) or a
        universal-checkpoint dir (reference ``load_model_with_checkpoint:292``
        sharded-checkpoint loading)."""
        import os
        if os.path.exists(os.path.join(checkpoint, "universal_meta.json")):
            from deepspeed_tpu.checkpoint import load_universal_checkpoint
            flat = load_universal_checkpoint(checkpoint)
            log_dist(f"loaded universal checkpoint: {len(flat)} tensors",
                     ranks=[0])
            template = (self.module.init(jax.random.key(0))
                        if hasattr(self.module, "init") else None)
            if template is not None:
                return load_universal_checkpoint(checkpoint,
                                                 template=template)
            return flat
        from deepspeed_tpu.checkpoint import load_checkpoint_tree
        state = load_checkpoint_tree(checkpoint)
        params = state.get("params", state)
        log_dist(f"loaded checkpoint params from {checkpoint}", ranks=[0])
        return params

    # ------------------------------------------------------------------
    def forward(self, input_ids, caches=None):
        """Single forward (prefill if caches empty).  Returns logits."""
        input_ids = jnp.asarray(input_ids)
        if self._streaming:
            if caches is None:
                from deepspeed_tpu.ops.decode_attention import init_cache
                c = self.module.config
                caches = [init_cache(input_ids.shape[0],
                                     self._config.max_out_tokens,
                                     c.kv_heads, c.head_dim, self.dtype)
                          for _ in range(self._n_layers)]
            with self.mesh:
                return self._streaming_apply_with_cache(input_ids, caches)
        if not hasattr(self.module, "apply_with_cache"):
            # encoder-style model (e.g. BertEncoder): plain forward
            if self._compiled_prefill is None:
                def enc(params, ids):
                    return self.module.apply(self._maybe_dequant(params),
                                             ids, train=False)
                self._compiled_prefill = jax.jit(enc)
            with self.mesh:
                return self._compiled_prefill(self.params, input_ids), None
        if caches is None:
            caches = self.module.init_caches(
                input_ids.shape[0], self._config.max_out_tokens, self.dtype)
        if self._compiled_prefill is None:
            def prefill(params, ids, caches):
                return self.module.apply_with_cache(
                    self._maybe_dequant(params), ids, caches)
            self._compiled_prefill = jax.jit(prefill)
        with self.mesh:
            logits, caches = self._compiled_prefill(self.params, input_ids, caches)
        return logits, caches

    __call__ = forward

    # ------------------------------------------------------------------
    def generate(self, input_ids, max_new_tokens=32, temperature=0.0,
                 top_k: Optional[int] = None, seed=0, eos_token_id=None):
        """Greedy/temperature sampling decode loop, fully jitted: prefill once,
        then ``lax.scan`` over decode steps (the XLA analogue of the
        reference's CUDA-graph replay per token)."""
        input_ids = jnp.asarray(input_ids, jnp.int32)
        B, S = input_ids.shape
        if self._streaming:
            assert not temperature, \
                "weight-streaming generate is greedy-only"
            with self.mesh:
                return self._streaming_generate(input_ids, max_new_tokens)
        max_seq = S + max_new_tokens
        key = (max_new_tokens, bool(temperature), top_k, B, S)

        if key not in self._compiled_generate:
            def gen(params, ids, rng):
                params = self._maybe_dequant(params)
                caches = self.module.init_caches(B, max_seq, self.dtype)
                logits, caches = self.module.apply_with_cache(params, ids, caches)
                last = logits[:, -1]

                def sample(logits, rng):
                    if temperature and temperature > 0:
                        l = logits / temperature
                        if top_k:
                            kth = jnp.sort(l, axis=-1)[:, -top_k][:, None]
                            l = jnp.where(l < kth, -1e30, l)
                        return jax.random.categorical(rng, l)
                    return jnp.argmax(logits, axis=-1)

                def step(carry, _):
                    last_logits, caches, rng = carry
                    rng, sub = jax.random.split(rng)
                    tok = sample(last_logits, sub).astype(jnp.int32)
                    logits, caches = self.module.apply_with_cache(
                        params, tok[:, None], caches)
                    return (logits[:, -1], caches, rng), tok

                (_, _, _), toks = jax.lax.scan(
                    step, (last, caches, rng), None, length=max_new_tokens)
                return jnp.swapaxes(toks, 0, 1)  # [B, T_new]
            self._compiled_generate[key] = jax.jit(gen)

        with self.mesh:
            new_tokens = self._compiled_generate[key](
                self.params, input_ids, jax.random.key(seed))
        out = jnp.concatenate([input_ids, new_tokens], axis=1)
        if eos_token_id is not None:
            out = np.asarray(out)
            for b in range(out.shape[0]):
                hits = np.where(out[b, S:] == eos_token_id)[0]
                if hits.size:
                    out[b, S + hits[0] + 1:] = eos_token_id
        return out

    _generate = generate  # parity alias

    # ------------------------------------------------------------------
    def create_serving_engine(self, max_batch: int = 8,
                              page_size: int = 128,
                              num_pages: Optional[int] = None,
                              max_seq: int = 2048,
                              eos_token_id: Optional[Any] = None,
                              decode_chunk: int = 1, **kwargs):
        """Build a continuous-batching ``ServingEngine`` over this
        engine's model/params, wiring the config's ``serving`` hardening
        block (admission control, deadlines, load shedding, fault
        injection).  Not available for weight-streaming or quantized
        engines — the paged decode step consumes raw dense weights."""
        if self._streaming:
            raise NotImplementedError(
                "paged serving does not compose with ZeRO-Inference "
                "weight streaming")
        if getattr(self, "_quantized", False):
            raise NotImplementedError(
                "paged serving expects dense weights; disable weight-only "
                "quantization")
        from deepspeed_tpu.inference.serving import ServingEngine
        kwargs.setdefault("serving", getattr(self._config, "serving", None))
        return ServingEngine(self.module, self.params,
                             max_batch=max_batch, page_size=page_size,
                             num_pages=num_pages, max_seq=max_seq,
                             dtype=self.dtype, eos_token_id=eos_token_id,
                             tp_size=max(1, self._config.tp_size),
                             ep_size=max(1, self._config.ep_size),
                             decode_chunk=decode_chunk, **kwargs)

    # ------------------------------------------------------------------
    def profile_model_time(self, use_cuda_events=False):
        logger.warning("use jax.profiler for per-op timing")

    def destroy(self):
        self._compiled_prefill = None
        self._compiled_generate = {}
