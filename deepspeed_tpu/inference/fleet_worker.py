"""Fleet worker: one ``ServingEngine`` per OS process.

``python -m deepspeed_tpu.inference.fleet_worker --fd N`` is the child
half of the cross-process fleet: the router (``inference/fleet.py``,
``transport.mode = "subprocess"``) creates a ``socketpair``, passes one
end's fd to this entry point, and drives the engine through the framed
RPC protocol in ``inference/transport.py``.  The worker is a real fault
domain — ``kill -9`` takes exactly one replica's state, and the router
recovers from its own request table.

Protocol (router → worker ``op`` frames, one ``resp``/``err`` frame
back each, strictly ordered):

* ``init`` — first frame.  Carries the replica identity (``rid``,
  ``epoch``), the ENGINE FACTORY SPEC, the heartbeat interval, and an
  optional telemetry config.  The factory spec is a dotted path
  ``"module:function"`` plus JSON kwargs — a deterministic recipe, not
  a pickled object, so a respawned worker rebuilds the exact same
  engine (same model init key ⇒ bit-identical outputs, the property
  every fleet acceptance test leans on).
* engine ops — ``add_request`` / ``step`` / ``pop_terminated`` /
  ``pop_prefilled`` / ``release_handoff`` / ``resident_prefix`` /
  ``export_payload`` / ``import_request`` / ``commit_import`` (the
  migration transaction's explicit ack) / ``cancel_import`` / ``drain``
  / ``leak_report`` / ``health`` / ``generate`` / ``ping`` /
  ``shutdown``.  Typed engine rejections (``RequestRejected``) cross
  the wire as typed ``err`` frames; any other engine exception becomes
  a generic ``err`` the router maps to its replica-kill path.

Every response piggybacks a ``load`` stamp (queue depth, active slots,
free pages, prefix hit rate, shed count) so the router's spill-order
and autoscale decisions read cached state instead of paying an RPC per
replica per dispatch.

Liveness: a daemon thread emits ``kind: "hb"`` frames every
``hb_interval_s`` with a monotonically increasing ``seq`` and the
worker's epoch; the router declares the replica dead after a missed-
heartbeat deadline.  Worker telemetry rides the rank-stamped shard sink
(``telemetry.distributed``): each worker writes ``events.rank{N}.jsonl``
in the shared shard dir, so one merged stream keeps per-replica
attribution.

Exactly-once under gray failures: every request frame carries a call id
(``cid``) echoed on the response; a duplicated delivery of the same cid
resends the cached response without re-executing.  Mutating ops
(``add_request`` / ``import_request`` / ``commit_import``) additionally
carry an idempotency key (``ikey``, ``epoch:req_id`` at the router) —
a RETRY under a fresh cid replays the cached outcome (response flagged
``dup: true``) instead of double-admitting or double-committing.  And
because a dropped response must not silently lose completed work, the
lossy result ops (``step`` / ``pop_terminated`` / ``pop_prefilled``)
are cumulative: results stay buffered until the router acks them on its
next call (``ack`` list), so a timed-out response is redelivered whole.
"""

import argparse
import importlib
import socket
import sys
import threading
import time
from collections import OrderedDict

from deepspeed_tpu.inference.transport import (TransportError,
                                               WIRE_VERSION,
                                               pack_value, payload_to_wire,
                                               payload_from_wire,
                                               recv_frame, send_frame,
                                               unpack_value)
from deepspeed_tpu.utils.logging import logger


def resolve_factory(spec):
    """``{"factory": "module:function", "kwargs": {...}}`` (or the bare
    ``"module:function"`` string) → a ``factory(rid, epoch)`` callable.
    The dotted path is the whole point: a deterministic, re-importable
    recipe the router can respawn a dead worker from."""
    if isinstance(spec, str):
        spec = {"factory": spec}
    path = spec["factory"]
    kwargs = dict(spec.get("kwargs") or {})
    mod_name, _, fn_name = path.partition(":")
    if not fn_name:
        raise ValueError(f"factory spec {path!r} is not 'module:function'")
    fn = getattr(importlib.import_module(mod_name), fn_name)
    return lambda rid, epoch: fn(rid, epoch, **kwargs)


def tiny_engine_factory(replica_id, epoch, **overrides):
    """The deterministic tiny-transformer engine used by the xproc
    tests, gate 9, and the ``cpu_fleet_xproc`` bench: same geometry as
    ``tests/unit/test_fleet.py``'s in-process factory, init key 0, so an
    in-process fleet over this factory is the bit-identity oracle for a
    subprocess fleet over the same spec."""
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.inference.serving import ServingEngine
    from deepspeed_tpu.models.transformer import (CausalTransformerLM,
                                                  TransformerConfig)
    cfg = TransformerConfig.tiny(hidden_size=64, n_heads=4, n_kv_heads=2)
    model = CausalTransformerLM(cfg)
    params = model.init(jax.random.key(0))
    kwargs = dict(max_batch=4, page_size=8, max_seq=128,
                  dtype=jnp.float32, replica_epoch=epoch,
                  serving={"prefix_cache": {"enabled": True}})
    kwargs.update(overrides)
    return ServingEngine(model, params, **kwargs)


def _result_to_wire(res):
    """``RequestResult`` → plain dict (fields are already primitives)."""
    return {"req_id": pack_value(res.req_id), "status": res.status,
            "reason": res.reason, "tokens": [int(t) for t in res.tokens],
            "n_generated": int(res.n_generated), "detail": res.detail}


class FleetWorker:
    """Hosts one engine behind the socket; see the module docstring."""

    # dedup cache bounds: cids are dense (a dup arrives right behind the
    # original), ikeys live as long as a retry storm plausibly can
    MAX_CID_CACHE = 32
    MAX_IKEY_CACHE = 4096

    def __init__(self, sock):
        self.sock = sock
        self.stream = sock.makefile("rb")
        self.wlock = threading.Lock()   # main loop vs heartbeat thread
        self.engine = None
        self.rid = None
        self.epoch = None
        self._hb_stop = threading.Event()
        self._resp_by_cid = OrderedDict()   # cid → sent response frame
        self._done_ikeys = OrderedDict()    # (ikey, op) → response core
        self.dup_calls = 0                  # replays served from caches
        # cumulative result buffers, pruned by the router's acks — a
        # dropped response cannot silently lose finished work
        self._done_buf = {}                 # rid → generated tokens
        self._term_buf = {}                 # rid → wire RequestResult
        self._hand_buf = {}                 # rid → wire PrefillHandoff

    # -- liveness --------------------------------------------------------
    def _heartbeat_loop(self, interval_s):
        seq = 0
        while not self._hb_stop.wait(interval_s):
            try:
                send_frame(self.sock,
                           {"kind": "hb", "seq": seq, "rid": self.rid,
                            "epoch": self.epoch,
                            "ts": round(time.monotonic(), 6)},
                           lock=self.wlock)
            except TransportError:
                return          # router is gone; main loop exits too
            seq += 1

    # -- op handlers -----------------------------------------------------
    def _load(self):
        eng = self.engine
        cache = eng.prefix_cache
        return {"queue": len(eng.queue), "active": int(eng.n_active),
                "free_pages": int(eng.alloc.free_page_count),
                "num_pages": int(eng.alloc.num_pages),
                "hit_rate": (cache.snapshot()["hit_rate"]
                             if cache is not None else None),
                "shed": int(eng.stats["shed"])}

    def _op_init(self, frame):
        from deepspeed_tpu.monitor.telemetry import get_telemetry
        self.rid = frame["rid"]
        self.epoch = frame["epoch"]
        tcfg = frame.get("telemetry")
        if tcfg:
            from deepspeed_tpu.runtime.config import TelemetryConfig
            get_telemetry().configure(TelemetryConfig(dict(tcfg)),
                                      rank=int(frame.get("rank", 0)))
        factory = resolve_factory(frame["spec"])
        self.engine = factory(self.rid, self.epoch)
        hb = float(frame.get("hb_interval_s", 1.0))
        if hb > 0:
            threading.Thread(target=self._heartbeat_loop, args=(hb,),
                             daemon=True, name="fleet-hb").start()
        return {"v": list(WIRE_VERSION),
                "page_size": int(self.engine.page_size),
                "kv_page_bytes": int(self.engine.kv_page_bytes)}

    def _op_add_request(self, frame):
        self.engine.add_request(unpack_value(frame["req_id"]),
                                frame["prompt"], **frame["kwargs"])
        return {}

    @staticmethod
    def _ack(frame, buf):
        """Prune a cumulative result buffer by the router's ack list —
        ids the router confirms it has consumed from a prior response."""
        for rid in frame.get("ack") or []:
            buf.pop(rid, None)

    def _op_step(self, frame):
        self._ack(frame, self._done_buf)
        for rid, toks in self.engine.step().items():
            self._done_buf[rid] = [int(t) for t in toks]
        return {"done": [[pack_value(rid), list(toks)]
                         for rid, toks in self._done_buf.items()]}

    def _op_pop_terminated(self, frame):
        self._ack(frame, self._term_buf)
        for rid, res in self.engine.pop_terminated().items():
            self._term_buf[rid] = _result_to_wire(res)
        return {"results": [[pack_value(rid), dict(res)]
                            for rid, res in self._term_buf.items()]}

    def _op_pop_prefilled(self, frame):
        self._ack(frame, self._hand_buf)
        for rid, h in self.engine.pop_prefilled().items():
            self._hand_buf[rid] = h.to_wire()
        return {"handoffs": [[pack_value(rid), dict(h)]
                             for rid, h in self._hand_buf.items()]}

    def _op_release_handoff(self, frame):
        return {"ok": self.engine.release_handoff(
            unpack_value(frame["req_id"]))}

    def _op_resident_prefix(self, frame):
        cache = self.engine.prefix_cache
        pages = (cache.resident_prefix(frame["prompt"])
                 if cache is not None else [])
        return {"pages": [int(p) for p in pages]}

    def _op_export_payload(self, frame):
        """Export + encode in one hop: the int8 wire codec runs HERE, on
        the source worker, so what crosses the process boundary is the
        quantized payload — the codec's byte saving is real wire bytes."""
        from deepspeed_tpu.comm.quantize import QuantizedPayload
        pages = [int(p) for p in frame["pages"]]
        if not pages:
            return {"payload": None, "quant": False}
        payload = self.engine.comm_quant.encode_payload(
            self.engine.export_pages(pages))
        return {"payload": payload_to_wire(payload),
                "quant": isinstance(payload, QuantizedPayload)}

    def _op_import_request(self, frame):
        from deepspeed_tpu.inference.serving import PrefillHandoff
        handoff = PrefillHandoff.from_wire(frame["handoff"])
        payload = payload_from_wire(frame.get("payload"))
        ok = self.engine.import_request(
            handoff, payload=payload,
            shared_pages=[int(p) for p in frame.get("shared_pages") or []],
            deadline_s=frame.get("deadline_s"))
        return {"ok": bool(ok)}

    def _op_commit_import(self, frame):
        self.engine.commit_import(unpack_value(frame["req_id"]))
        return {"ok": True}     # the explicit commit ack

    def _op_cancel_import(self, frame):
        return {"ok": self.engine.cancel_import(
            unpack_value(frame["req_id"]))}

    def _op_drain(self, frame):
        res = self.engine.drain()
        return {"finished": [[pack_value(rid), [int(t) for t in toks]]
                             for rid, toks in res["finished"].items()],
                "shed": [pack_value(r) for r in res["shed"]],
                "steps": int(res["steps"]), "health": res["health"]}

    def _op_leak_report(self, frame):
        return {"leaks": self.engine.leak_report()}

    def _op_health(self, frame):
        return {"health": self.engine.health()}

    def _op_generate(self, frame):
        out = self.engine.generate(frame["prompts"],
                                   max_new_tokens=int(
                                       frame.get("max_new_tokens", 8)))
        return {"out": [[int(t) for t in toks] for toks in out]}

    def _op_ping(self, frame):
        return {}

    # -- main loop -------------------------------------------------------
    def serve(self):
        while True:
            try:
                frame = unpack_value(recv_frame(self.stream))
            except TransportError:
                return          # router closed the socket (or died)
            op = frame.get("op")
            cid = frame.get("cid")
            if cid is not None and cid in self._resp_by_cid:
                # duplicated delivery of the same request frame: resend
                # the cached response verbatim, execute nothing — the
                # router discards the extra copy by cid
                self.dup_calls += 1
                try:
                    send_frame(self.sock, self._resp_by_cid[cid],
                               lock=self.wlock)
                except TransportError:
                    return
                continue
            if op == "shutdown":
                self._hb_stop.set()
                send_frame(self.sock, {"kind": "resp", "cid": cid},
                           lock=self.wlock)
                return
            handler = getattr(self, f"_op_{op}", None)
            ikey = frame.get("ikey")
            try:
                if handler is None:
                    raise ValueError(f"unknown op {op!r}")
                if ikey is not None and (ikey, op) in self._done_ikeys:
                    # retried mutation whose first execution succeeded
                    # but whose ack was lost: replay the outcome, do
                    # not double-admit / double-commit
                    self.dup_calls += 1
                    resp = dict(self._done_ikeys[(ikey, op)])
                    resp["dup"] = True
                else:
                    resp = handler(frame)
                    if ikey is not None:
                        self._done_ikeys[(ikey, op)] = dict(resp)
                        while len(self._done_ikeys) > self.MAX_IKEY_CACHE:
                            self._done_ikeys.popitem(last=False)
                resp["kind"] = "resp"
                if self.engine is not None:
                    resp["load"] = self._load()
            except Exception as e:
                resp = self._err_frame(op, e)
            resp["cid"] = cid
            if cid is not None:
                self._resp_by_cid[cid] = resp
                while len(self._resp_by_cid) > self.MAX_CID_CACHE:
                    self._resp_by_cid.popitem(last=False)
            try:
                send_frame(self.sock, resp, lock=self.wlock)
            except TransportError:
                return

    @staticmethod
    def _err_frame(op, e):
        from deepspeed_tpu.inference.robustness import RequestRejected
        from deepspeed_tpu.inference.transport import WireVersionError
        if isinstance(e, RequestRejected):
            return {"kind": "err", "etype": "RequestRejected",
                    "req_id": pack_value(e.req_id), "reason": e.reason,
                    "detail": e.detail}
        if isinstance(e, WireVersionError):
            return {"kind": "err", "etype": "WireVersionError",
                    "got": pack_value(e.got), "what": e.what}
        logger.warning(f"fleet worker op {op!r} raised: {e}")
        return {"kind": "err", "etype": type(e).__name__,
                "detail": str(e)}


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--fd", type=int, required=True,
                        help="inherited socketpair fd from the router")
    args = parser.parse_args(argv)
    sock = socket.socket(fileno=args.fd)
    FleetWorker(sock).serve()
    return 0


if __name__ == "__main__":
    sys.exit(main())
