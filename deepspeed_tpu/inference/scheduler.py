"""Pluggable serving schedulers: monolithic vs chunked prefill, plus
draft-model speculative decoding.

Parity role: the reference schedules inference as one monolithic
prefill-then-decode loop per batch (``InferenceEngine.forward``); modern
TPU serving (PAPERS.md: Gemma-on-TPU TTFT/throughput comparison, vLLM
chunked prefill) interleaves prefill CHUNKS with the running decode batch
so one long prompt cannot stall every in-flight request.  The ragged
paged-attention kernel (PR 6) already serves mixed prefill+decode
batches with per-request ragged lengths, so a prefill chunk — or a
speculative verify window — is just another ragged dispatch.

The split: :class:`~deepspeed_tpu.inference.serving.ServingEngine` keeps
admission, page reservation, deadlines, tracing, and the device
primitives (``_run_step`` / ``_sample`` / ``_prefill``); the scheduler
owns WHAT each step dispatches:

- ``monolithic`` (default): the whole prompt prefills in one bucketed
  dispatch at admission, decode advances every slot per step — today's
  behaviour bit-for-bit.
- ``chunked``: prefill runs ``prefill_chunk_tokens`` at a time,
  interleaved with decode; per-request SLO classes (``latency`` vs
  ``throughput``) order both queue admission and chunk scheduling, and
  deadlines are checked at every chunk boundary (not just whole steps).
- ``chunked`` + ``speculative``: a draft model proposes
  ``num_draft_tokens`` greedy tokens per slot through its OWN paged
  allocator; the target verifies the whole window in one ragged
  dispatch.  Greedy accept keeps the output bit-identical to the
  non-speculative oracle: every accepted token equals the target's
  argmax given the true prefix, and the first mismatch is replaced by
  that argmax (the "bonus" token).  Rejected draft positions need no
  rollback — stale KV entries beyond ``lengths`` are never read and are
  overwritten by the next sequential write.
"""

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel
from deepspeed_tpu.utils.logging import logger

SCHEDULER_POLICIES = ("monolithic", "chunked")

# SLO classes order admission and chunk scheduling under the chunked
# policy: "latency" requests jump the queue and prefill first.  The
# class rides the frozen serve/request/* events (slo_class attr) so the
# report can split TTFT/TPOT percentiles per class.
SLO_CLASSES = ("latency", "throughput")
_SLO_PRIORITY = {c: i for i, c in enumerate(SLO_CLASSES)}


class SpeculativeConfig(DeepSpeedConfigModel):
    """``serving.scheduler.speculative``: draft-model speculative
    decoding on top of the chunked policy."""

    enabled = False
    # draft tokens proposed (and verified) per decode step; the verify
    # window writes up to num_draft_tokens past the reservation tail, so
    # it must fit the +1 scratch overrun column: num_draft_tokens + 1
    # <= page_size (checked at scheduler construction, where the engine
    # page size is known)
    num_draft_tokens = 4

    def _validate(self):
        n = int(self.num_draft_tokens)
        if n < 0:
            raise ValueError(
                "serving.scheduler.speculative.num_draft_tokens must be "
                ">= 0")
        if n == 0:
            # 0 is the "speculation off" point — the autotuner's
            # draft-length knob sweeps it alongside real draft lengths
            self.enabled = False


class SchedulerConfig(DeepSpeedConfigModel):
    """The ``serving.scheduler`` config block."""

    policy = "monolithic"
    # chunked policy: tokens per prefill chunk (one ragged dispatch each)
    prefill_chunk_tokens = 256
    # prefill chunk dispatches interleaved per engine step, before decode
    max_prefill_chunks_per_step = 1
    # class applied when add_request passes no slo_class
    slo_class_default = "throughput"
    # per-class deadline defaults: {"latency": {"default_deadline_s": 2.0}}
    # — applied when add_request passes no deadline_s, before falling back
    # to serving.default_deadline_s
    slo_classes = {}
    speculative = {}

    def _validate(self):
        if isinstance(self.speculative, dict):
            self.speculative = SpeculativeConfig(self.speculative)
        if self.policy not in SCHEDULER_POLICIES:
            raise ValueError(
                f"serving.scheduler.policy must be one of "
                f"{SCHEDULER_POLICIES}")
        if int(self.prefill_chunk_tokens) < 1:
            raise ValueError(
                "serving.scheduler.prefill_chunk_tokens must be >= 1")
        if int(self.max_prefill_chunks_per_step) < 1:
            raise ValueError(
                "serving.scheduler.max_prefill_chunks_per_step must be "
                ">= 1")
        if self.slo_class_default not in SLO_CLASSES:
            raise ValueError(
                f"serving.scheduler.slo_class_default must be one of "
                f"{SLO_CLASSES}")
        for cls in self.slo_classes:
            if cls not in SLO_CLASSES:
                raise ValueError(
                    f"serving.scheduler.slo_classes key {cls!r} is not "
                    f"one of {SLO_CLASSES}")

    def class_deadline_s(self, slo_class: str) -> Optional[float]:
        """Per-class default TTL, or None when the class has none."""
        spec = self.slo_classes.get(slo_class)
        if not isinstance(spec, dict):
            return None
        ttl = spec.get("default_deadline_s")
        return float(ttl) if ttl else None


class SchedulerBase:
    """Decode machinery shared by every policy.

    The decode dispatches mask NON-READY slots (empty, or still
    prefilling under the chunked policy) by feeding them a zeroed block
    table row and length 0: their writes land on the reserved scratch
    page and the host loop skips their outputs.  Under the monolithic
    policy every active slot is ready, so the masked arrays equal the
    engine's own tables/lengths — bit-for-bit the pre-scheduler step.
    """

    policy = "base"

    def __init__(self, engine, cfg: SchedulerConfig):
        self.engine = engine
        self.cfg = cfg
        self._chunk_fns = {}   # use_filters(bool) -> compiled chunk fn
        self.sched_stats = {"prefill_chunks": 0, "prefills_split": 0,
                            "decode_steps": 0, "decode_tokens": 0}

    # -- admission hooks (called by ServingEngine._admit) ----------------
    def order_queue(self):
        """Reorder the waiting queue before slot filling (policy hook)."""

    def prefill_padded_len(self, suffix_tokens: int) -> int:
        """Padded device length the prefill of ``suffix_tokens`` will
        write — the engine sizes the page reservation from it."""
        raise NotImplementedError

    def fill_slot(self, slot: int, req, cached: int) -> bool:
        """A queued request just landed in ``slot`` (pages reserved,
        COW done).  Returns True when the prefill ran to completion
        here (the engine then trims the reservation and indexes the
        prefix); False when it was deferred to later ``step()`` calls."""
        raise NotImplementedError

    def release_slot(self, slot: int, req):
        """The request in ``slot`` is leaving the engine (finish, evict,
        deadline, drain) — drop any scheduler-held state for it."""

    # -- step hooks ------------------------------------------------------
    def run_step(self) -> Dict[Any, List[int]]:
        raise NotImplementedError

    def pending_prefill_steps(self) -> int:
        """Upper bound on extra step() calls needed to finish every
        in-flight prefill (drain budget sizing)."""
        return 0

    def meta(self) -> Dict[str, Any]:
        """Attrs for the one frozen ``serve/sched`` event per engine."""
        return {"policy": self.policy,
                "prefill_chunk_tokens": int(self.cfg.prefill_chunk_tokens),
                "speculative": 0}

    def snapshot(self) -> Dict[str, Any]:
        return {"policy": self.policy, **self.sched_stats}

    def leak_report(self) -> Dict[str, Any]:
        return {}

    # -- shared decode bodies -------------------------------------------
    def _ready_slots(self) -> List[int]:
        eng = self.engine
        return [s for s, r in enumerate(eng.slots)
                if r is not None and r.last_token is not None
                and self._slot_ready(s, r)]

    def _slot_ready(self, slot: int, req) -> bool:
        return True

    def _decode_once(self, ready: List[int]) -> Dict[Any, List[int]]:
        """One token for every ready slot (the pre-scheduler per-token
        step body, masked to ``ready``)."""
        from deepspeed_tpu.inference.robustness import EVICT_FAULT
        eng = self.engine
        last = np.zeros((eng.max_batch, 1), np.int32)
        tables = np.zeros_like(eng.tables)
        lengths = np.zeros_like(eng.lengths)
        for slot in ready:
            req = eng.slots[slot]
            last[slot, 0] = req.last_token
            tables[slot] = eng.tables[slot]
            lengths[slot] = eng.lengths[slot]
        logits, eng.caches, _ = eng._run_step(
            jnp.asarray(last), jnp.asarray(tables), jnp.asarray(lengths))
        logits_np = np.asarray(logits[:, 0])
        self.sched_stats["decode_steps"] += 1

        # finishing frees slots, which admits (and may prefill) queued
        # requests — defer that until after the loop so a mid-loop
        # admission is never mistaken for a slot this decode step served
        done_slots, fault_slots = [], []
        done_now: Dict[Any, List[int]] = {}
        for slot in ready:
            req = eng.slots[slot]
            # the token we just fed is now part of the sequence
            req.out.append(req.last_token)
            eng.lengths[slot] += 1
            self.sched_stats["decode_tokens"] += 1
            ended = (eng.eos is not None and req.last_token == eng.eos)
            if ended or len(req.out) >= req.max_new_tokens:
                done_slots.append(slot)
            else:
                try:
                    req.last_token = eng._sample(req, logits_np[slot])
                except Exception as e:   # per-slot fault isolation
                    fault_slots.append((slot, str(e)))
        for slot, err in fault_slots:
            rid = eng.slots[slot].req_id
            logger.warning(f"evicting request {rid!r} after sampler "
                           f"fault: {err}")
            eng._evict_slot(slot, "evicted", EVICT_FAULT, detail=err)
            eng.stats["evicted"] += 1
            eng._serve_event("serve/evict", req_id=rid,
                             reason=EVICT_FAULT, error=err)
        if fault_slots:
            eng._admit()
        for slot in done_slots:
            rid = eng.slots[slot].req_id
            eng._finish(slot)
            # hand the result back ONCE: a long-running server must not
            # accumulate every finished token list forever
            done_now[rid] = eng.finished.pop(rid)
        return done_now

    # -- the chunked decode step (K tokens per dispatch) ----------------
    def _build_chunk_fn(self, use_filters: bool):
        eng = self.engine
        K = eng.decode_chunk
        paged_call = eng._paged_call   # backend-bound apply_with_paged_cache

        def chunk(params, caches, tables, lengths, last, temps, seeds,
                  gen_counts, top_ks, top_ps):
            """K decode iterations in one device program.  Emits the K
            sampled tokens per slot; the host truncates past EOS /
            max_new_tokens (overrun writes land on the reserved scratch
            page — admission reserved every page a live request can
            validly reach, vLLM-style multi-step scheduling).  Sampling
            keys on (request seed, tokens generated so far), so a
            request's random stream is independent of slot assignment
            and arrival order — the per-token engine's req.seed contract."""
            def one_sample(key, l, temp, top_k, top_p):
                """One slot's filtered sampler: temperature -> top-k ->
                top-p (nucleus) -> categorical.  Rank-based like the host
                sampler: a single stable descending argsort; exactly
                ``cut`` ranked tokens survive each stage (top_k=0 /
                top_p=1.0 gate their stage off explicitly)."""
                V = l.shape[-1]
                l = l / jnp.maximum(temp, 1e-6)
                order = jnp.argsort(-l, stable=True)
                ranks = jnp.zeros(V, jnp.int32).at[order].set(
                    jnp.arange(V, dtype=jnp.int32))
                k_eff = jnp.where((top_k > 0) & (top_k < V), top_k, V)
                l = jnp.where(ranks < k_eff, l, -1e30)
                p = jax.nn.softmax(l)
                cs = jnp.cumsum(p[order])
                # smallest prefix reaching top_p mass (searchsorted+1)
                cut = jnp.where(top_p < 1.0, jnp.sum(cs < top_p) + 1, V)
                l = jnp.where(ranks < cut, l, -1e30)
                return jax.random.categorical(key, l).astype(jnp.int32)

            def one(carry, t):
                caches, lengths, last = carry
                logits, caches, _ = paged_call(
                    params, last[:, None], caches, tables, lengths)
                lg = logits[:, 0]
                greedy = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                keys = jax.vmap(
                    lambda s, g: jax.random.fold_in(jax.random.key(s),
                                                    g + t))(seeds, gen_counts)
                if use_filters:
                    sampled = jax.vmap(one_sample)(keys, lg, temps,
                                                   top_ks, top_ps)
                else:   # plain temperature: no vocab sorts in the loop
                    sampled = jax.vmap(
                        lambda k, l, tt: jax.random.categorical(
                            k, l / jnp.maximum(tt, 1e-6)))(
                        keys, lg, temps).astype(jnp.int32)
                nxt = jnp.where(temps > 0, sampled, greedy)
                return (caches, lengths + 1, nxt), nxt

            (caches, lengths, last), toks = jax.lax.scan(
                one, (caches, lengths, last), jnp.arange(K))
            return toks.T, caches   # [B, K]

        return jax.jit(chunk, donate_argnums=(1,))

    def _decode_scan(self, ready: List[int]) -> Dict[Any, List[int]]:
        eng = self.engine
        K = eng.decode_chunk
        use_filters = any(eng.slots[s].top_k or eng.slots[s].top_p < 1.0
                          for s in ready)
        if self._chunk_fns.get(use_filters) is None:
            self._chunk_fns[use_filters] = eng._wrap_compiled(
                self._build_chunk_fn(use_filters),
                f"serve/decode_chunk:{int(use_filters)}")
        chunk_fn = self._chunk_fns[use_filters]
        last = np.zeros(eng.max_batch, np.int32)
        temps = np.zeros(eng.max_batch, np.float32)
        seeds = np.zeros(eng.max_batch, np.uint32)
        gen_counts = np.zeros(eng.max_batch, np.int32)
        top_ks = np.zeros(eng.max_batch, np.int32)
        top_ps = np.ones(eng.max_batch, np.float32)
        tables = np.zeros_like(eng.tables)
        lengths = np.zeros_like(eng.lengths)
        for slot in ready:
            req = eng.slots[slot]
            last[slot] = req.last_token
            temps[slot] = max(0.0, req.temperature)
            seeds[slot] = np.uint32(req.seed)
            gen_counts[slot] = len(req.out)
            top_ks[slot] = req.top_k
            top_ps[slot] = req.top_p
            tables[slot] = eng.tables[slot]
            lengths[slot] = eng.lengths[slot]
        args = (eng.params, eng.caches, jnp.asarray(tables),
                jnp.asarray(lengths), jnp.asarray(last),
                jnp.asarray(temps), jnp.asarray(seeds),
                jnp.asarray(gen_counts), jnp.asarray(top_ks),
                jnp.asarray(top_ps))
        with eng.telemetry.span("serve/step",
                                attrs={"backend": eng.attention_backend,
                                       "phase": "decode_chunk",
                                       "batch": int(eng.max_batch),
                                       "tokens": int(K)}), \
                eng._prof_track("serve_step"):
            if eng.mesh is not None:
                with eng.mesh:
                    toks, eng.caches = chunk_fn(*args)
            else:
                toks, eng.caches = chunk_fn(*args)
        toks = np.asarray(toks)
        self.sched_stats["decode_steps"] += 1

        done_slots, done_now = [], {}
        for slot in ready:
            req = eng.slots[slot]
            # tokens appended to the cache this chunk: the pre-chunk last
            # token, then the first K-1 samples; sample K-1 is the next
            # chunk's carry (per-token step() semantics, K times)
            seq = [req.last_token] + toks[slot, :-1].tolist()
            finished = False
            for tok in seq:
                req.out.append(int(tok))
                eng.lengths[slot] += 1
                self.sched_stats["decode_tokens"] += 1
                if (eng.eos is not None and int(tok) == eng.eos) or \
                        len(req.out) >= req.max_new_tokens:
                    finished = True
                    break
            if finished:
                done_slots.append(slot)
            else:
                req.last_token = int(toks[slot, -1])
        for slot in done_slots:
            rid = eng.slots[slot].req_id
            eng._finish(slot)
            done_now[rid] = eng.finished.pop(rid)
        return done_now


class MonolithicScheduler(SchedulerBase):
    """Today's behaviour, bit-for-bit: the whole (uncached) prompt
    prefills in one bucketed dispatch at slot-fill time; every active
    slot decodes every step."""

    policy = "monolithic"

    def prefill_padded_len(self, suffix_tokens: int) -> int:
        eng = self.engine
        return min(eng._bucket(suffix_tokens), eng.max_seq)

    def fill_slot(self, slot: int, req, cached: int) -> bool:
        eng = self.engine
        bucket = self.prefill_padded_len(len(req.prompt) - cached)
        eng._prefill(slot, req, bucket, cached)
        return True

    def run_step(self) -> Dict[Any, List[int]]:
        eng = self.engine
        if eng.n_active == 0:
            return {}
        ready = self._ready_slots()
        if eng.decode_chunk > 1:
            return self._decode_scan(ready)
        return self._decode_once(ready)


class ChunkedScheduler(SchedulerBase):
    """Chunked prefill interleaved with decode, SLO-class ordering, and
    (optionally) draft-model speculative decoding.

    Per engine step: up to ``max_prefill_chunks_per_step`` prefill-chunk
    dispatches run first — ordered (SLO class, submit time) — with a
    deadline sweep after EVERY chunk boundary; then one decode dispatch
    advances the slots whose prefill (target AND draft) is complete.
    """

    policy = "chunked"

    def __init__(self, engine, cfg: SchedulerConfig,
                 draft_model=None, draft_params=None):
        super().__init__(engine, cfg)
        self.chunk = int(cfg.prefill_chunk_tokens)
        self.max_chunks = int(cfg.max_prefill_chunks_per_step)
        self.spec = bool(cfg.speculative.enabled)
        self.sched_stats.update(prefill_chunk_tokens=self.chunk)
        if self.spec:
            self._init_spec(draft_model, draft_params)

    # -- speculative state ----------------------------------------------
    def _init_spec(self, draft_model, draft_params):
        from deepspeed_tpu.ops.paged_attention import PagedAllocator
        eng = self.engine
        if draft_model is None or draft_params is None:
            raise ValueError(
                "serving.scheduler.speculative.enabled needs "
                "ServingEngine(draft_model=..., draft_params=...)")
        if eng.decode_chunk != 1:
            raise ValueError(
                "speculative decoding replaces decode_chunk batching; "
                "use decode_chunk=1")
        if eng.mesh is not None:
            raise ValueError(
                "speculative decoding is single-host only (tp/ep mesh "
                "unsupported)")
        self.gamma = int(self.cfg.speculative.num_draft_tokens)
        if self.gamma + 1 > eng.page_size:
            # the verify window (and the draft's sync write of the same
            # tokens) overruns the reservation tail by up to gamma
            # positions — the +1 scratch column absorbs exactly one page
            raise ValueError(
                f"num_draft_tokens + 1 ({self.gamma + 1}) must fit one "
                f"page (page_size {eng.page_size})")
        self.draft_model = draft_model
        self.draft_params = draft_params
        # the draft runs through its OWN paged allocator/caches/tables —
        # sized so a full batch of max-length reservations can never
        # fail, because there is no draft-side prefix sharing to lean on
        draft_pages = eng.max_batch * eng.max_pages_per_seq + 1
        self.draft_alloc = PagedAllocator(draft_pages, eng.page_size,
                                          eng.max_pages_per_seq,
                                          reserve_scratch=True)
        self.draft_caches = draft_model.init_paged_caches(
            draft_pages, eng.page_size, dtype=eng.cache_dtype)
        self.draft_tables = np.zeros_like(eng.tables)
        self.draft_lengths = np.zeros(eng.max_batch, np.int32)
        self._spec_slots = set()
        import functools
        self._draft_call = functools.partial(
            draft_model.apply_with_paged_cache)
        self._draft_step_fn = eng._wrap_compiled(
            jax.jit(self._draft_call, donate_argnums=(2,)),
            "serve/spec_draft_fn")
        self._propose_fn = eng._wrap_compiled(
            self._build_propose_fn(), "serve/spec_propose")
        self.sched_stats.update(spec_windows=0, spec_proposed=0,
                                spec_accepted=0, spec_rejected=0)

    def _build_propose_fn(self):
        """Greedy draft proposal: a scan of ``gamma + 1`` single-token
        decode iterations.  The extra iteration writes the LAST proposed
        token into the draft cache, so an accept-all verify leaves no
        hole — the draft cache stays valid through every position the
        target may commit, and rejection needs no rollback at all."""
        G = self.gamma
        draft_call = self._draft_call

        def propose(params, caches, tables, lengths, last):
            def one(carry, _):
                caches, lengths, last = carry
                logits, caches, _ = draft_call(
                    params, last[:, None], caches, tables, lengths)
                nxt = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
                return (caches, lengths + 1, nxt), nxt

            (caches, _, _), toks = jax.lax.scan(
                one, (caches, lengths, last), None, length=G + 1)
            return toks.T, caches   # [B, G+1]; only the first G are used

        return jax.jit(propose, donate_argnums=(1,))

    def _run_draft(self, ids, tables, lengths, phase):
        eng = self.engine
        with eng.telemetry.span("serve/step",
                                attrs={"backend": "draft", "phase": phase,
                                       "batch": int(ids.shape[0]),
                                       "tokens": int(ids.shape[1])}), \
                eng._prof_track("serve_step"):
            out, self.draft_caches, _ = self._draft_step_fn(
                self.draft_params, ids, self.draft_caches, tables, lengths)
        return out

    # -- admission hooks -------------------------------------------------
    def order_queue(self):
        # stable: latency-class requests first, FIFO within a class
        self.engine.queue.sort(
            key=lambda r: _SLO_PRIORITY.get(r.slo_class, 1))

    def prefill_padded_len(self, suffix_tokens: int) -> int:
        return -(-max(suffix_tokens, 1) // self.chunk) * self.chunk

    def fill_slot(self, slot: int, req, cached: int) -> bool:
        eng = self.engine
        req.prefilled = cached
        req.draft_filled = 0
        eng.lengths[slot] = cached
        if len(req.prompt) - cached > self.chunk:
            self.sched_stats["prefills_split"] += 1
        if self.spec and req.temperature <= 0.0:
            # full draft reservation up front, like the target's: an
            # admitted spec request can never deadlock on draft pages
            total = len(req.prompt) + req.max_new_tokens
            padded = self.prefill_padded_len(len(req.prompt))
            need = min(max(total, padded),
                       eng.max_pages_per_seq * eng.page_size)
            pages = self.draft_alloc.allocate(req.req_id, need)
            self.draft_tables[slot, :] = 0
            self.draft_tables[slot, :len(pages)] = pages
            self.draft_lengths[slot] = 0
            self._spec_slots.add(slot)
        return False

    def release_slot(self, slot: int, req):
        if self.spec and slot in self._spec_slots:
            self._spec_slots.discard(slot)
            self.draft_alloc.free_sequence(req.req_id)
            self.draft_tables[slot, :] = 0
            self.draft_lengths[slot] = 0

    # -- prefill chunk scheduling ----------------------------------------
    def _prefill_pending(self, slot: int, req) -> bool:
        if req.prefilled < len(req.prompt):
            return True
        return self.spec and slot in self._spec_slots and \
            req.draft_filled < len(req.prompt)

    def _next_prefill_slot(self) -> Optional[int]:
        eng = self.engine
        best, best_key = None, None
        for slot, req in enumerate(eng.slots):
            if req is None or not self._prefill_pending(slot, req):
                continue
            key = (_SLO_PRIORITY.get(req.slo_class, 1), req.submit_time,
                   slot)
            if best_key is None or key < best_key:
                best, best_key = slot, key
        return best

    def _prefill_chunk_unit(self, slot: int, req):
        """One prefill-chunk dispatch for ``slot``: the target prompt
        first, then (spec slots) the draft's own full-prompt prefill.
        The final target chunk samples the first token and completes the
        admission sequence (trim + prefix insert)."""
        eng = self.engine
        P = len(req.prompt)
        if req.prefilled < P:
            start = req.prefilled
            toks = req.prompt[start:start + self.chunk]
            n = len(toks)
            ids = np.zeros((1, self.chunk), np.int32)
            ids[0, :n] = toks
            t0 = eng._clock()
            logits, eng.caches, _ = eng._run_step(
                jnp.asarray(ids), jnp.asarray(eng.tables[slot:slot + 1]),
                jnp.full((1,), start, jnp.int32), phase="prefill")
            # chunk-active wall time feeds the critical path's prefill
            # stage; the wait BETWEEN chunks lands in the gap stage —
            # the split that separates scheduler wins from kernel wins
            eng.attrib.chunk(req.req_id, (eng._clock() - t0) * 1000.0)
            req.prefilled = start + n
            eng.lengths[slot] = req.prefilled
            self.sched_stats["prefill_chunks"] += 1
            eng._serve_event("serve/prefill_chunk", req_id=req.req_id,
                             slot=slot, start=start, tokens=n,
                             remaining=P - req.prefilled,
                             slo_class=req.slo_class)
            if req.prefilled >= P:
                # the last prompt token's logits seed sampling — same
                # contract as the monolithic prefill
                req.last_token = eng._sample(
                    req, np.asarray(logits[0, n - 1]))
                eng._note_first_token(slot, req)
                eng._complete_prefill(slot, req)
            return
        # target done -> catch the draft up on its own cache
        start = req.draft_filled
        toks = req.prompt[start:start + self.chunk]
        n = len(toks)
        ids = np.zeros((1, self.chunk), np.int32)
        ids[0, :n] = toks
        self._run_draft(jnp.asarray(ids),
                        jnp.asarray(self.draft_tables[slot:slot + 1]),
                        jnp.full((1,), start, jnp.int32),
                        phase="spec_prefill")
        req.draft_filled = start + n
        self.draft_lengths[slot] = req.draft_filled
        if req.draft_filled >= P:
            # drop the draft's padding surplus, mirroring the target trim
            total = P + req.max_new_tokens
            self.draft_alloc.shrink(req.req_id, total)
            pages = self.draft_alloc.seq_pages[req.req_id]
            self.draft_tables[slot, :] = 0
            self.draft_tables[slot, :len(pages)] = pages

    def _run_prefill_chunks(self):
        from deepspeed_tpu.inference.robustness import EVICT_FAULT
        eng = self.engine
        for _ in range(self.max_chunks):
            slot = self._next_prefill_slot()
            if slot is None:
                return
            req = eng.slots[slot]
            try:
                self._prefill_chunk_unit(slot, req)
            except Exception as e:   # fault isolation: only THIS request
                logger.warning(f"evicting request {req.req_id!r} after "
                               f"prefill-chunk fault: {e}")
                eng._evict_slot(slot, "evicted", EVICT_FAULT,
                                detail=str(e))
                eng.stats["evicted"] += 1
                eng._serve_event("serve/evict", req_id=req.req_id,
                                 reason=EVICT_FAULT, error=str(e))
                continue
            # deadline/TTL granularity fix: a multi-chunk prefill is no
            # longer one opaque dispatch — every chunk boundary cancels
            # expired requests, queued or mid-flight (including the one
            # that was just prefilling)
            eng._expire_deadlines()

    # -- decode ----------------------------------------------------------
    def _slot_ready(self, slot: int, req) -> bool:
        if req.prefilled < len(req.prompt):
            return False
        if self.spec and slot in self._spec_slots:
            return req.draft_filled >= len(req.prompt)
        return True

    def run_step(self) -> Dict[Any, List[int]]:
        eng = self.engine
        self._run_prefill_chunks()
        ready = self._ready_slots()
        if not ready:
            return {}
        if self.spec:
            return self._spec_decode(ready)
        if eng.decode_chunk > 1:
            return self._decode_scan(ready)
        return self._decode_once(ready)

    def pending_prefill_steps(self) -> int:
        eng = self.engine
        pending = 0
        for slot, req in enumerate(eng.slots):
            if req is None:
                continue
            if req.prefilled < len(req.prompt):
                pending += -(-(len(req.prompt) - req.prefilled)
                             // self.chunk)
            if self.spec and slot in self._spec_slots:
                pending += -(-(len(req.prompt) - req.draft_filled)
                             // self.chunk)
        return pending

    def meta(self) -> Dict[str, Any]:
        m = super().meta()
        m["speculative"] = int(self.spec)
        if self.spec:
            m["num_draft_tokens"] = self.gamma
        return m

    def snapshot(self) -> Dict[str, Any]:
        snap = super().snapshot()
        snap["prefilling_slots"] = sum(
            1 for s, r in enumerate(self.engine.slots)
            if r is not None and self._prefill_pending(s, r))
        if self.spec:
            prop = snap.get("spec_proposed", 0)
            snap["spec_acceptance_rate"] = (
                snap.get("spec_accepted", 0) / prop if prop else 0.0)
        return snap

    def leak_report(self) -> Dict[str, Any]:
        if not self.spec:
            return {}
        eng = self.engine
        leaks: Dict[str, Any] = {}
        active = {r.req_id for r in eng.slots if r is not None}
        stray = sorted(set(self.draft_alloc.seq_pages) - active, key=str)
        if stray:
            leaks["spec_stray_draft_owners"] = stray
        for k, v in self.draft_alloc.audit().items():
            leaks[f"spec_draft_{k}"] = v
        return leaks

    # -- speculative decode ---------------------------------------------
    def _spec_decode(self, ready: List[int]) -> Dict[Any, List[int]]:
        """Draft-propose + single-dispatch verify for every ready slot.

        Greedy slots accept the longest draft prefix matching the
        target's argmaxes, then take the argmax at the first mismatch as
        the bonus token — bit-identical to the per-token greedy oracle
        by construction.  Sampled (temperature > 0) slots and slots with
        a 1-token remaining budget ride the same verify dispatch at
        window 0: position 0 of the ragged window is causally identical
        to a T=1 decode, so their host sampling (and its RNG stream) is
        untouched."""
        from deepspeed_tpu.inference.robustness import EVICT_FAULT
        eng = self.engine
        G = self.gamma
        win = np.zeros(eng.max_batch, np.int32)
        specs = []
        for s in ready:
            req = eng.slots[s]
            if s in self._spec_slots and req.temperature <= 0.0:
                w = min(G, req.max_new_tokens - len(req.out) - 1)
                if w > 0:
                    win[s] = w
                    specs.append(s)
        props = np.zeros((eng.max_batch, G), np.int32)
        if specs:
            dlast = np.zeros(eng.max_batch, np.int32)
            dtables = np.zeros_like(self.draft_tables)
            dlengths = np.zeros(eng.max_batch, np.int32)
            for s in specs:
                dlast[s] = eng.slots[s].last_token
                dtables[s] = self.draft_tables[s]
                dlengths[s] = self.draft_lengths[s]
            with eng.telemetry.span(
                    "serve/step",
                    attrs={"backend": "draft", "phase": "spec_draft",
                           "batch": int(eng.max_batch),
                           "tokens": int(G + 1)}), \
                    eng._prof_track("serve_step"):
                toks, self.draft_caches = self._propose_fn(
                    self.draft_params, self.draft_caches,
                    jnp.asarray(dtables), jnp.asarray(dlengths),
                    jnp.asarray(dlast))
            props[:, :] = np.asarray(toks)[:, :G]
            eng._serve_event("serve/spec_draft", slots=len(specs),
                             window=G)
        ids = np.zeros((eng.max_batch, 1 + G), np.int32)
        tables = np.zeros_like(eng.tables)
        lengths = np.zeros_like(eng.lengths)
        for s in ready:
            ids[s, 0] = eng.slots[s].last_token
            tables[s] = eng.tables[s]
            lengths[s] = eng.lengths[s]
        for s in specs:
            ids[s, 1:1 + win[s]] = props[s, :win[s]]
        logits, eng.caches, _ = eng._run_step(
            jnp.asarray(ids), jnp.asarray(tables), jnp.asarray(lengths),
            phase="spec_verify")
        logits_np = np.asarray(logits)
        self.sched_stats["decode_steps"] += 1

        done_slots, fault_slots = [], []
        done_now: Dict[Any, List[int]] = {}
        accepted_total = rejected_total = 0
        for s in ready:
            req = eng.slots[s]
            if s not in specs:
                # per-token semantics on window position 0
                req.out.append(req.last_token)
                eng.lengths[s] += 1
                self.sched_stats["decode_tokens"] += 1
                ended = (eng.eos is not None and req.last_token == eng.eos)
                if ended or len(req.out) >= req.max_new_tokens:
                    done_slots.append(s)
                else:
                    try:
                        req.last_token = eng._sample(req, logits_np[s, 0])
                    except Exception as e:
                        fault_slots.append((s, str(e)))
                continue
            w = int(win[s])
            g = np.argmax(logits_np[s, :w + 1], axis=-1).astype(np.int32)
            req.out.append(req.last_token)
            eng.lengths[s] += 1
            self.sched_stats["decode_tokens"] += 1
            finished = (eng.eos is not None and req.last_token == eng.eos) \
                or len(req.out) >= req.max_new_tokens
            m = 0
            while not finished and m < w and int(props[s, m]) == int(g[m]):
                tok = int(props[s, m])
                req.out.append(tok)
                eng.lengths[s] += 1
                self.sched_stats["decode_tokens"] += 1
                m += 1
                finished = (eng.eos is not None and tok == eng.eos) or \
                    len(req.out) >= req.max_new_tokens
            accepted_total += m
            rejected_total += w - m
            self.sched_stats["spec_proposed"] += w
            self.sched_stats["spec_accepted"] += m
            self.sched_stats["spec_rejected"] += w - m
            if finished:
                done_slots.append(s)
            else:
                # accept boundary: g[m] is the target's argmax given the
                # accepted prefix — the bonus (m == w) or the correction
                # at the first mismatch (m < w)
                req.last_token = int(g[m])
            # the draft cache holds every committed position (the extra
            # propose iteration wrote the final proposal too): resume it
            # at the target's new length, stale tail entries are simply
            # overwritten by the next sequential writes
            self.draft_lengths[s] = eng.lengths[s]
        if specs:
            self.sched_stats["spec_windows"] += 1
            eng._serve_event("serve/spec_verify", slots=len(specs),
                             window=G, accepted=accepted_total,
                             rejected=rejected_total)
            tel = eng.telemetry
            if tel is not None and tel.enabled:
                if accepted_total:
                    tel.count("serve/spec_accepted_tokens", accepted_total)
                if rejected_total:
                    tel.count("serve/spec_rejected_tokens", rejected_total)
        for slot, err in fault_slots:
            rid = eng.slots[slot].req_id
            logger.warning(f"evicting request {rid!r} after sampler "
                           f"fault: {err}")
            eng._evict_slot(slot, "evicted", EVICT_FAULT, detail=err)
            eng.stats["evicted"] += 1
            eng._serve_event("serve/evict", req_id=rid,
                             reason=EVICT_FAULT, error=err)
        if fault_slots:
            eng._admit()
        for slot in done_slots:
            rid = eng.slots[slot].req_id
            eng._finish(slot)
            done_now[rid] = eng.finished.pop(rid)
        return done_now


def create_scheduler(engine, cfg: SchedulerConfig,
                     draft_model=None, draft_params=None) -> SchedulerBase:
    """Build the policy the ``serving.scheduler`` block selects."""
    if not isinstance(cfg, SchedulerConfig):
        cfg = SchedulerConfig(cfg or {})
    if cfg.policy == "chunked":
        return ChunkedScheduler(engine, cfg, draft_model=draft_model,
                                draft_params=draft_params)
    if cfg.speculative.enabled:
        raise ValueError(
            "serving.scheduler.speculative needs policy='chunked'")
    if draft_model is not None:
        logger.warning("draft_model ignored: scheduler policy is "
                       f"{cfg.policy!r} without speculative decoding")
    return MonolithicScheduler(engine, cfg)
