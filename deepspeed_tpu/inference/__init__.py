"""Inference stack: engine, config, continuous-batching serving."""

from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
from deepspeed_tpu.inference.engine import InferenceEngine
from deepspeed_tpu.inference.fleet import (FleetConfig, FleetRouter,
                                           FLEET_EVENTS)
from deepspeed_tpu.inference.prefix_cache import (PrefixCache,
                                                  PrefixCacheConfig,
                                                  PrefixMatch)
from deepspeed_tpu.inference.robustness import (AdmissionController,
                                                RequestRejected,
                                                RequestResult,
                                                ServingRobustnessConfig,
                                                ServingStalled)
from deepspeed_tpu.inference.scheduler import (SchedulerConfig,
                                               SpeculativeConfig,
                                               SCHEDULER_POLICIES,
                                               SLO_CLASSES)
from deepspeed_tpu.inference.serving import ServingEngine

__all__ = ["DeepSpeedInferenceConfig", "InferenceEngine", "ServingEngine",
           "RequestRejected", "RequestResult", "ServingRobustnessConfig",
           "ServingStalled", "AdmissionController", "PrefixCache",
           "PrefixCacheConfig", "PrefixMatch", "FleetConfig",
           "FleetRouter", "FLEET_EVENTS", "SchedulerConfig",
           "SpeculativeConfig", "SCHEDULER_POLICIES", "SLO_CLASSES"]
