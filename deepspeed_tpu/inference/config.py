"""Inference config.

Parity: reference ``inference/config.py`` (``DeepSpeedInferenceConfig``).
Same key spellings; TP degree comes from ``tensor_parallel.tp_size`` or the
legacy ``mp_size``.
"""

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel


class DeepSpeedTPConfig(DeepSpeedConfigModel):
    enabled = True
    tp_size = 1
    mpu = None
    tp_group = None


class QuantizationConfig(DeepSpeedConfigModel):
    enabled = False
    num_bits = 8
    group_size = 64


class DeepSpeedInferenceConfig(DeepSpeedConfigModel):
    dtype = "bfloat16"
    tensor_parallel = {}
    mp_size = None  # legacy alias of tensor_parallel.tp_size
    max_out_tokens = 1024
    min_out_tokens = 1
    max_tokens = None
    replace_with_kernel_inject = False
    injection_policy = None
    checkpoint = None
    base_dir = ""
    quant = {}
    enable_cuda_graph = False   # accepted for parity; XLA jit IS the graph
    replace_method = "auto"
    moe = False
    moe_experts = 1
    moe_type = "standard"
    training_mp_size = 1
    return_tuple = True
    triangular_masking = True
    ep_size = 1
    # ZeRO-Inference (reference engine.py:1581 offload-for-inference):
    # {"offload_param": {"device": "cpu"|"nvme", "nvme_path": ...}}
    zero = {}
    # serving hardening (inference/robustness.py): admission control,
    # deadlines, load shedding, fault injection for the serving engine
    serving = {}

    def _validate(self):
        if isinstance(self.tensor_parallel, dict):
            self.tensor_parallel = DeepSpeedTPConfig(self.tensor_parallel)
        if self.mp_size is not None:
            self.tensor_parallel.tp_size = self.mp_size
        if isinstance(self.quant, dict):
            self.quant = QuantizationConfig(self.quant)
        if isinstance(self.serving, dict):
            from deepspeed_tpu.inference.robustness import \
                ServingRobustnessConfig
            self.serving = ServingRobustnessConfig(self.serving)

    @property
    def tp_size(self):
        return self.tensor_parallel.tp_size
