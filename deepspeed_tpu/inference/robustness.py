"""Serving hardening layer: typed rejection, admission control, deadlines,
load shedding, fault isolation, and graceful drain for ``ServingEngine``.

Parity rationale: the training side got its fault-tolerance layer in
``runtime/resilience.py`` (durable checkpoints, preemption, deterministic
fault injection); this module applies the same discipline to the inference
path.  The ragged-paged-attention serving design (PAPERS.md "Ragged Paged
Attention") assumes the slot/page bookkeeping survives hostile traffic,
and TPU serving comparisons measure *tail latency under load* — which
requires shedding requests with typed reasons, not crashing the batch.

What lives here (the host-control-flow half; ``inference/serving.py``
wires it into the decode loop):

* :class:`RequestRejected` — structured admission-time rejection (oversized
  prompt, infeasible page reservation, duplicate id, bad sampling params,
  bounded-queue overflow, draining).  One bad request can never take down
  the batch.
* :class:`ServingRobustnessConfig` — the ``serving`` config block: bounded
  wait queue, high/low watermarks on queue depth and free KV pages, the
  overload policy (``reject`` | ``shed-oldest`` | ``block``), default
  deadlines, and the serving fault-injection spec.
* :class:`AdmissionController` — hysteresis watermark tracking: overload
  engages at the high watermark (queue) / low watermark (free pages) and
  releases only once pressure drops past the low/high side, so admission
  doesn't flap at the boundary.
* :class:`RequestResult` — the typed terminal record for every request
  that did NOT finish normally (shed / deadline / evicted / drained),
  carrying partial output.
* :class:`ServingStalled` — the typed ``generate()`` stall error carrying
  every already-completed result plus a diagnostic snapshot, replacing the
  result-destroying ``assert``.

All telemetry from this layer rides the frozen ``serve`` event kind
(``scripts/check_telemetry_schema.py``): ``serve/admit``, ``serve/reject``,
``serve/shed``, ``serve/deadline``, ``serve/evict``, ``serve/drain``,
``serve/finish``, ``serve/fault``.
"""

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel

# ----------------------------------------------------------------------
# typed reasons (frozen vocabulary: telemetry attrs + docs/serving.md)
# ----------------------------------------------------------------------
# admission-time rejections (RequestRejected.reason)
REJECT_OVERSIZED = "oversized_prompt"     # prompt + budget exceeds max_seq
REJECT_INFEASIBLE = "infeasible_pages"    # reservation can never fit pool
REJECT_DUPLICATE = "duplicate_id"         # req_id already queued/active
REJECT_BAD_SAMPLING = "bad_sampling"      # top_k/top_p/temperature invalid
REJECT_BAD_REQUEST = "bad_request"        # empty prompt / non-positive budget
REJECT_QUEUE_FULL = "queue_full"          # bounded queue at hard cap
REJECT_OVERLOADED = "overloaded"          # watermark overload, policy=reject
REJECT_DRAINING = "draining"              # drain() stopped admission

# post-admission terminations (RequestResult.reason)
SHED_OLDEST = "shed_oldest"               # displaced by newer arrival
SHED_DEADLINE = "deadline"                # TTL expired (queued or mid-flight)
SHED_DRAIN = "drain"                      # drain() gave up on it
EVICT_FAULT = "fault"                     # per-slot failure isolated

REJECT_REASONS = (REJECT_OVERSIZED, REJECT_INFEASIBLE, REJECT_DUPLICATE,
                  REJECT_BAD_SAMPLING, REJECT_BAD_REQUEST, REJECT_QUEUE_FULL,
                  REJECT_OVERLOADED, REJECT_DRAINING)
TERMINAL_REASONS = (SHED_OLDEST, SHED_DEADLINE, SHED_DRAIN, EVICT_FAULT)

OVERLOAD_POLICIES = ("reject", "shed-oldest", "block")

# The FROZEN vocabulary of serve-kind event names — every ``serve`` event
# the engine emits must use one of these, and the telemetry schema
# (``scripts/check_telemetry_schema.py``) validates streams against the
# same tuple (a tier-1 test diffs the two).  Adding an event name means
# editing both files in the same change.
SERVE_EVENTS = (
    "serve/admit", "serve/reject", "serve/shed", "serve/deadline",
    "serve/evict", "serve/drain", "serve/finish", "serve/fault",
    # prefix-cache subsystem (inference/prefix_cache.py): a lookup that
    # attached cached pages ("serve/prefix_hit", attrs: pages_reused /
    # tokens_reused / cow), a copy-on-write page copy ("serve/prefix_cow"),
    # pages newly indexed after prefill or finish ("serve/prefix_insert"),
    # and a reclaimable page surrendered back to the free list
    # ("serve/prefix_evict")
    "serve/prefix_hit", "serve/prefix_cow", "serve/prefix_insert",
    "serve/prefix_evict",
    # profiling plane (monitor/profiling.py): rising-edge record that the
    # CompileWatcher flagged a recompile storm on the serving jit entry
    # points (attrs: misses) — shape-bucket churn burning latency on
    # compiles; health()["recompile_storm"] mirrors it live
    "serve/compile_storm",
    # attention-backend record: emitted once at engine construction with
    # attrs attention_backend / impl / interpret, so a telemetry stream's
    # serve/step spans are attributable to the kernel path that ran
    "serve/backend",
    # scheduler plane (inference/scheduler.py).  "serve/sched" is the
    # once-per-engine meta record (attrs: policy / prefill_chunk_tokens /
    # speculative / num_draft_tokens); "serve/prefill_chunk" is one
    # chunked-prefill dispatch (attrs: req_id / slot / start / tokens /
    # remaining / slo_class); "serve/spec_draft" is one draft-model
    # proposal dispatch (attrs: slots / window) and "serve/spec_verify"
    # its target verification (attrs: slots / window / accepted /
    # rejected — the same counts feed the serve/spec_accepted_tokens and
    # serve/spec_rejected_tokens registry counters)
    "serve/sched", "serve/prefill_chunk",
    "serve/spec_draft", "serve/spec_verify",
    # per-request lifecycle trace (RequestTracer): one event per state
    # transition, each carrying req_id plus the derived latencies so a
    # request's full history is reconstructible from the JSONL stream
    # alone.  The "queued" state is implicit between admitted and
    # prefill_start (queue_wait_ms attr); the "decode" phase is implicit
    # between first_token and the terminal (tpot_ms attr).  Every admitted
    # request reaches EXACTLY ONE of the four terminals — the
    # trace-completeness invariant leak_report() audits.
    "serve/request/admitted", "serve/request/prefill_start",
    "serve/request/first_token",
    "serve/request/finish", "serve/request/shed",
    "serve/request/deadline", "serve/request/evict",
    # critical-path attribution (monitor/attribution.py): one record
    # adjacent to each terminal carrying the ordered stage breakdown
    # (queue/prefill/migrate/gap/decode _ms attrs, summing to e2e_ms by
    # construction), the terminal it pairs with, chunk count, whether
    # the request crossed a prefill->decode migration, and the "path"
    # flow string ds_trace_export renders as arrows
    "serve/request/attr",
)

# the closed set of trace terminals (the tail of the serve/request/*
# vocabulary above); RequestResult statuses map onto it via
# ``ServingEngine._TERMINAL_BY_STATUS`` ("drained" folds into "shed")
TRACE_TERMINALS = ("finish", "shed", "deadline", "evict")

# the serving.attention_backend vocabulary (mirrors
# ops/paged_attention.py ATTENTION_BACKENDS; validated at config time so
# a typo fails construction, not the first jitted step)
ATTENTION_BACKENDS = ("auto", "jnp", "pallas", "pallas-interpret")


class RequestRejected(Exception):
    """``add_request`` refused this request — the engine state is untouched
    and every other request keeps serving.  ``reason`` is one of
    :data:`REJECT_REASONS`; ``detail`` is the human-readable specifics."""

    def __init__(self, req_id, reason: str, detail: str = ""):
        self.req_id = req_id
        self.reason = reason
        self.detail = detail
        super().__init__(
            f"request {req_id!r} rejected ({reason})"
            + (f": {detail}" if detail else ""))


class ServingStalled(RuntimeError):
    """``generate()`` (or ``drain``) could not make progress within its
    step budget.  Unlike the assert it replaces, every already-completed
    result survives in ``partial`` and the stuck state is reported."""

    def __init__(self, partial, stuck_req_ids, free_pages, queue_depth,
                 steps):
        self.partial = dict(partial)
        self.stuck_req_ids = list(stuck_req_ids)
        self.free_pages = int(free_pages)
        self.queue_depth = int(queue_depth)
        self.steps = int(steps)
        super().__init__(
            f"serving stalled after {steps} steps: "
            f"{len(self.partial)} finished, stuck={self.stuck_req_ids}, "
            f"free_pages={free_pages}, queue_depth={queue_depth}")


@dataclass
class RequestResult:
    """Terminal record for a request that did not finish normally.
    ``tokens`` is the partial output (prompt + everything generated before
    termination); ``status`` is one of ``shed`` / ``deadline`` /
    ``evicted`` / ``drained``."""
    req_id: Any
    status: str
    reason: str
    tokens: List[int] = field(default_factory=list)
    n_generated: int = 0
    detail: str = ""


class ServingRobustnessConfig(DeepSpeedConfigModel):
    """The ``serving`` config block (``DeepSpeedInferenceConfig.serving``
    or the ``ServingEngine(serving=...)`` kwarg).  Defaults preserve the
    pre-hardening behaviour: unbounded queue, no deadlines, no shedding —
    only the typed validation is always on."""

    max_queue = 0                   # hard queue cap (0 = unbounded)
    queue_high_watermark = 0        # overload engages at this depth (0=off)
    queue_low_watermark = 0         # ...and releases at this depth
    free_page_low_watermark = 0     # overload engages at <= this many free
    overload_policy = "reject"      # "reject" | "shed-oldest" | "block"
    block_max_steps = 256           # policy=block: step budget before reject
    default_deadline_s = 0.0        # TTL applied when add_request has none
    max_prompt_tokens = 0           # extra prompt cap under max_seq (0=off)
    step_fault_limit = 8            # consecutive serve_step faults -> raise
    fault_injection = {}            # FaultInjector spec (serving sites)
    # paged-attention implementation: "auto" (Pallas on TPU, jnp
    # elsewhere) | "jnp" (gather oracle) | "pallas" | "pallas-interpret"
    # (the kernel through the interpreter — CPU CI bit-identity)
    attention_backend = "auto"
    # content-hashed KV-page reuse (inference/prefix_cache.py):
    # {"enabled": bool, "max_cached_pages": int, "min_prefix_tokens": int}
    prefix_cache = {}
    # multi-replica fleet front-end (inference/fleet.py): replicas /
    # min_replicas / max_replicas, health_interval, redispatch_max,
    # autoscale thresholds.  Ignored by a bare ServingEngine.
    fleet = {}
    # step scheduler (inference/scheduler.py): policy ("monolithic" |
    # "chunked"), prefill_chunk_tokens, max_prefill_chunks_per_step,
    # slo_class_default / slo_classes, speculative {enabled,
    # num_draft_tokens}
    scheduler = {}

    def _validate(self):
        if isinstance(self.prefix_cache, dict):
            from deepspeed_tpu.inference.prefix_cache import \
                PrefixCacheConfig
            self.prefix_cache = PrefixCacheConfig(self.prefix_cache)
        if isinstance(self.fleet, dict):
            from deepspeed_tpu.inference.fleet import FleetConfig
            self.fleet = FleetConfig(self.fleet)
        if isinstance(self.scheduler, dict):
            from deepspeed_tpu.inference.scheduler import SchedulerConfig
            self.scheduler = SchedulerConfig(self.scheduler)
        if self.overload_policy not in OVERLOAD_POLICIES:
            raise ValueError(
                f"serving.overload_policy must be one of {OVERLOAD_POLICIES}")
        if self.attention_backend not in ATTENTION_BACKENDS:
            raise ValueError(
                f"serving.attention_backend must be one of "
                f"{ATTENTION_BACKENDS}")
        for k in ("max_queue", "queue_high_watermark", "queue_low_watermark",
                  "free_page_low_watermark", "block_max_steps",
                  "max_prompt_tokens", "step_fault_limit"):
            if int(getattr(self, k)) < 0:
                raise ValueError(f"serving.{k} must be >= 0")
        if float(self.default_deadline_s) < 0:
            raise ValueError("serving.default_deadline_s must be >= 0")
        if self.queue_high_watermark and \
                int(self.queue_low_watermark) > int(self.queue_high_watermark):
            raise ValueError("serving.queue_low_watermark must be <= "
                             "queue_high_watermark")


class AdmissionController:
    """Watermark hysteresis over (queue depth, free KV pages).

    Overload engages when the queue reaches ``queue_high_watermark`` OR
    free pages fall to ``free_page_low_watermark``; it releases only when
    the queue is back at ``queue_low_watermark`` AND free pages are above
    the page watermark — so one request finishing at the boundary doesn't
    flap admission open and shut."""

    def __init__(self, cfg: ServingRobustnessConfig):
        self.cfg = cfg
        self.overloaded = False

    def update(self, queue_depth: int, free_pages: int) -> bool:
        """Re-evaluate and return the overload state."""
        qhi = int(self.cfg.queue_high_watermark)
        qlo = int(self.cfg.queue_low_watermark)
        plo = int(self.cfg.free_page_low_watermark)
        if not self.overloaded:
            if (qhi and queue_depth >= qhi) or (plo and free_pages <= plo):
                self.overloaded = True
        else:
            queue_ok = (not qhi) or queue_depth <= qlo
            pages_ok = (not plo) or free_pages > plo
            if queue_ok and pages_ok:
                self.overloaded = False
        return self.overloaded


# ----------------------------------------------------------------------
# per-request lifecycle tracing
# ----------------------------------------------------------------------
@dataclass
class RequestTrace:
    """One request's lifecycle timestamps (engine-clock seconds) and the
    latencies derived from them.  ``-1.0`` marks a state never reached —
    the derived accessors return ``None`` for those, so a request evicted
    before its first token reports no TTFT rather than a garbage one."""
    req_id: Any
    t_admit: float
    deadline: float = 0.0       # absolute engine-clock deadline (0 = none)
    slot: int = -1              # batch slot once scheduled
    t_prefill_start: float = -1.0
    t_first_token: float = -1.0
    terminal: str = ""          # one of TRACE_TERMINALS once closed
    t_terminal: float = -1.0
    n_generated: int = 0
    reason: str = ""            # typed reason for abnormal terminals

    def queue_wait_ms(self) -> Optional[float]:
        if self.t_prefill_start < 0:
            return None
        return (self.t_prefill_start - self.t_admit) * 1000.0

    def ttft_ms(self) -> Optional[float]:
        if self.t_first_token < 0:
            return None
        return (self.t_first_token - self.t_admit) * 1000.0

    def tpot_ms(self) -> Optional[float]:
        """Mean time per output token AFTER the first (the decode-rate
        half of the TTFT/TPOT split)."""
        if self.t_first_token < 0 or self.t_terminal < 0 or \
                self.n_generated < 2:
            return None
        return (self.t_terminal - self.t_first_token) * 1000.0 / \
            (self.n_generated - 1)

    def e2e_ms(self) -> Optional[float]:
        if self.t_terminal < 0:
            return None
        return (self.t_terminal - self.t_admit) * 1000.0

    def slo(self) -> Optional[str]:
        """SLO attainment for deadline-bearing requests: ``"ok"`` when the
        request finished on time, ``"miss"`` for every other terminal (a
        shed or evicted deadline request did not meet its SLO either).
        ``None`` when no deadline was set or the trace is still open."""
        if not self.deadline or not self.terminal:
            return None
        ok = self.terminal == "finish" and self.t_terminal <= self.deadline
        return "ok" if ok else "miss"


class RequestTracer:
    """Always-on host-side request lifecycle bookkeeping for the serving
    engine.  Transitions are dict updates against an injectable clock —
    cheap enough to leave on with telemetry disabled; the engine pairs
    each transition with a frozen ``serve/request/*`` event when the
    stream is live.

    The contract this class exists to enforce: every admitted request
    reaches EXACTLY ONE terminal (:data:`TRACE_TERMINALS`).  Violations —
    a double admit, a terminal on an unknown/closed request, an open trace
    with no live owner — are recorded and surfaced by :meth:`audit`, which
    ``ServingEngine.leak_report()`` folds in, so trace leaks fail the same
    invariant sweep page leaks do.

    ``epoch`` namespaces every request id: under a fleet front-end the
    same id legitimately reappears on a respawned replica (redispatch
    after a kill), and without the namespace a merged audit would read
    that as a double admit.  Ids in reports keep the ``epoch:id`` form so
    the replica generation stays visible."""

    def __init__(self, clock=None, max_completed=4096, epoch=None):
        self._clock = clock if clock is not None else time.monotonic
        self.epoch = epoch
        self.open: Dict[Any, RequestTrace] = {}
        # bounded retention: a long-running server must not accumulate a
        # trace per request forever — the counters below stay exact
        self.completed = deque(maxlen=max_completed)
        self.admitted = 0
        self.closed = 0
        self.terminals = {t: 0 for t in TRACE_TERMINALS}
        self.errors: List[str] = []

    def _key(self, req_id):
        """The id this tracer books under — ``"epoch:id"`` when the owner
        is an epoch-stamped fleet replica, the raw id otherwise."""
        return req_id if self.epoch is None else f"{self.epoch}:{req_id}"

    def admit(self, req_id, deadline: float = 0.0,
              now: Optional[float] = None) -> RequestTrace:
        now = self._clock() if now is None else now
        key = self._key(req_id)
        if key in self.open:
            self.errors.append(f"double admit for {key!r}")
            return self.open[key]
        tr = RequestTrace(key, t_admit=now, deadline=float(deadline))
        self.open[key] = tr
        self.admitted += 1
        return tr

    def prefill_start(self, req_id, slot: int) -> Optional[RequestTrace]:
        key = self._key(req_id)
        tr = self.open.get(key)
        if tr is None:
            self.errors.append(f"prefill_start for untracked {key!r}")
            return None
        tr.slot = int(slot)
        tr.t_prefill_start = self._clock()
        return tr

    def first_token(self, req_id) -> Optional[RequestTrace]:
        key = self._key(req_id)
        tr = self.open.get(key)
        if tr is None:
            self.errors.append(f"first_token for untracked {key!r}")
            return None
        tr.t_first_token = self._clock()
        return tr

    def terminal(self, req_id, terminal: str, n_generated: int = 0,
                 reason: str = "") -> Optional[RequestTrace]:
        key = self._key(req_id)
        if terminal not in TRACE_TERMINALS:
            self.errors.append(
                f"unknown terminal {terminal!r} for {key!r}")
            return None
        tr = self.open.pop(key, None)
        if tr is None:
            self.errors.append(
                f"terminal {terminal!r} for closed/unknown {key!r}")
            return None
        tr.terminal = terminal
        tr.t_terminal = self._clock()
        tr.n_generated = int(n_generated)
        tr.reason = reason
        self.terminals[terminal] += 1
        self.closed += 1
        self.completed.append(tr)
        return tr

    def snapshot_open(self) -> List[Dict[str, Any]]:
        """JSON-safe dump of every still-open lifecycle trace — the
        in-flight requests an incident bundle freezes at trigger time
        (``monitor/incidents.py`` registers this as a bundle context
        provider)."""
        now = self._clock()
        out = []
        for tr in list(self.open.values()):
            out.append({
                "req_id": str(tr.req_id),
                "slot": tr.slot,
                "age_ms": round((now - tr.t_admit) * 1000.0, 3),
                "deadline": tr.deadline or None,
                "queue_wait_ms": tr.queue_wait_ms(),
                "ttft_ms": tr.ttft_ms(),
                "prefilled": tr.t_prefill_start >= 0,
                "first_token": tr.t_first_token >= 0,
            })
        return out

    def audit(self, live_req_ids) -> Dict[str, Any]:
        """Trace-completeness invariant sweep.  ``live_req_ids`` is every
        request currently queued or active in the engine; returns {} when
        clean, else typed leak entries (the ``leak_report()`` shape)."""
        live = {self._key(r) for r in live_req_ids}
        leaks: Dict[str, Any] = {}
        orphans = sorted(set(self.open) - live, key=str)
        if orphans:
            leaks["trace_open_orphans"] = orphans
        untraced = sorted(live - set(self.open), key=str)
        if untraced:
            leaks["untraced_requests"] = untraced
        if self.errors:
            leaks["trace_errors"] = list(self.errors)
        if self.admitted != self.closed + len(self.open):
            leaks["trace_count_mismatch"] = {
                "admitted": self.admitted, "closed": self.closed,
                "open": len(self.open)}
        return leaks
