"""Continuous-batching serving engine over the paged KV cache.

Parity role: the reference serves decode through a per-request contiguous
KV workspace inside ``InferenceEngine`` (``inference_context.h`` workspace
management) — every request pays max-length allocation and batches must
line up.  The TPU-native upgrade is vLLM-style serving (PAPERS.md ragged
paged attention): fixed-size pages shared across sequences through block
tables, slot-based continuous batching (a finished request's pages free
immediately and the next prompt is admitted mid-flight), and one jitted
decode step for the whole active batch regardless of ragged lengths.

Host/device split: page allocation, admission, sampling bookkeeping are
host control flow (``PagedAllocator``); prefill and the batched decode
step are jitted device programs over ``CausalTransformerLM.
apply_with_paged_cache``.  Prefill lengths are bucketed to powers of two
to bound recompilation.

Hardening (``inference/robustness.py``): ``add_request`` raises typed
:class:`RequestRejected` instead of asserts; a bounded queue with
watermark admission control sheds/rejects/blocks under overload;
per-request deadlines cancel queued and mid-flight work at step
boundaries; a per-slot fault (sampler exception or injected
``serve_sample``) evicts ONE request with its partial output while the
rest of the batch keeps serving; ``drain()`` quiesces the engine and
``health()`` snapshots its state onto the telemetry registry.  Injected
``serve_step`` / ``page_alloc`` faults are retried without mutating any
request state, so recovered requests stay bit-identical.
"""

import contextlib
import functools
import math
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.inference.robustness import (
    EVICT_FAULT, REJECT_BAD_REQUEST, REJECT_BAD_SAMPLING, REJECT_DRAINING,
    REJECT_DUPLICATE, REJECT_INFEASIBLE, REJECT_OVERLOADED,
    REJECT_OVERSIZED, REJECT_QUEUE_FULL, SHED_DEADLINE, SHED_DRAIN,
    SHED_OLDEST, AdmissionController, RequestRejected, RequestResult,
    RequestTracer, ServingRobustnessConfig, ServingStalled)
from deepspeed_tpu.comm.quantize import CommQuantizer
from deepspeed_tpu.inference.prefix_cache import PrefixCache, PrefixMatch
from deepspeed_tpu.inference.scheduler import SLO_CLASSES, create_scheduler
from deepspeed_tpu.monitor.attribution import RequestAttributor
from deepspeed_tpu.monitor.telemetry import get_telemetry
from deepspeed_tpu.ops.paged_attention import (PageAllocationError,
                                               PagedAllocator,
                                               resolve_attention_backend)
from deepspeed_tpu.runtime.resilience import FaultInjector
from deepspeed_tpu.utils.logging import logger


# RequestResult statuses -> lifecycle-trace terminal names (the tail of
# the frozen serve/request/* vocabulary).  "drained" folds into "shed":
# from the request's point of view a drain IS a shed, just engine-initiated.
_TERMINAL_BY_STATUS = {"shed": "shed", "drained": "shed",
                       "deadline": "deadline", "evicted": "evict"}


def _round_ms(v):
    return None if v is None else round(v, 3)


@dataclass
class _Request:
    req_id: Any
    prompt: List[int]
    max_new_tokens: int
    temperature: float = 0.0
    seed: int = 0
    top_k: int = 0              # 0 = off
    top_p: float = 1.0          # 1.0 = off
    out: List[int] = field(default_factory=list)
    last_token: Optional[int] = None
    submit_time: float = 0.0
    deadline: float = 0.0       # absolute clock time; 0.0 = no deadline
    slo_class: str = "throughput"   # scheduler SLO class (SLO_CLASSES)
    # chunked-prefill progress: prompt tokens already written to the
    # target / draft KV cache (the monolithic policy never reads these)
    prefilled: int = 0
    draft_filled: int = 0
    # disaggregated fleets: this replica only prefills — the engine
    # captures a PrefillHandoff at prefill completion instead of decoding
    prefill_only: bool = False


@dataclass
class PrefillHandoff:
    """Everything a decode replica needs to continue a request whose
    prefill ran elsewhere: the sampling recipe, the first token (sampled
    on the source — its logits came off the prefill dispatch), the host
    sampler's RNG stream state, and the SOURCE page ids of the prompt's
    KV pages.  The pages stay pinned under the source allocator (keyed by
    ``req_id``) until :meth:`ServingEngine.release_handoff` — the commit
    acknowledgement — so a kill of either side mid-migration always
    leaves one consistent copy to redispatch from."""
    req_id: Any
    prompt: List[int]
    max_new_tokens: int
    temperature: float
    seed: int
    top_k: int
    top_p: float
    slo_class: str
    last_token: int
    out: List[int]
    rng_state: Optional[dict]
    pages: List[int]
    # wire-serialized TraceContext (monitor/attribution.py): the source
    # leg's timing history rides the handoff as plain primitives, so the
    # decode side's serve/request/attr event reports the FULL critical
    # path — queue and prefill on the source, the migration wait, then
    # decode here — not just the decode leg
    trace_ctx: Optional[dict] = None

    def to_wire(self) -> dict:
        """JSON-safe envelope for the cross-process fleet transport
        (``inference/transport.py``), stamped with the wire version.
        Every field is already plain primitives except ``rng_state``
        (numpy bit-generator state — MT19937 carries an ndarray key)."""
        from deepspeed_tpu.inference.transport import (WIRE_VERSION,
                                                       pack_value)
        return {
            "v": list(WIRE_VERSION),
            "req_id": pack_value(self.req_id),
            "prompt": [int(t) for t in self.prompt],
            "max_new_tokens": int(self.max_new_tokens),
            "temperature": float(self.temperature),
            "seed": int(self.seed),
            "top_k": int(self.top_k),
            "top_p": float(self.top_p),
            "slo_class": str(self.slo_class),
            "last_token": int(self.last_token),
            "out": [int(t) for t in self.out],
            "rng_state": pack_value(self.rng_state),
            "pages": [int(p) for p in self.pages],
            "trace_ctx": pack_value(self.trace_ctx),
        }

    @classmethod
    def from_wire(cls, d: dict) -> "PrefillHandoff":
        """Inverse of :meth:`to_wire`.  Rejects an unknown MAJOR wire
        version with the typed ``WireVersionError`` before reading any
        field — a decode replica must never guess at an envelope from a
        newer incompatible router."""
        from deepspeed_tpu.inference.transport import (check_wire_version,
                                                       unpack_value)
        check_wire_version(d.get("v"), "PrefillHandoff")
        return cls(
            req_id=unpack_value(d["req_id"]),
            prompt=[int(t) for t in d["prompt"]],
            max_new_tokens=int(d["max_new_tokens"]),
            temperature=float(d["temperature"]),
            seed=int(d["seed"]),
            top_k=int(d["top_k"]),
            top_p=float(d["top_p"]),
            slo_class=str(d["slo_class"]),
            last_token=int(d["last_token"]),
            out=[int(t) for t in d["out"]],
            rng_state=unpack_value(d["rng_state"]),
            pages=[int(p) for p in d["pages"]],
            trace_ctx=unpack_value(d.get("trace_ctx")),
        )


class ServingEngine:
    """``add_request`` → ``step`` until ``finished`` — or just
    ``generate(prompts, max_new_tokens)``.

    One decode ``step()`` advances EVERY active slot by one token; slots
    free and refill from the queue as requests finish (continuous
    batching).  Inactive slots point at the reserved scratch page and
    their outputs are ignored.
    """

    def __init__(self, model, params, max_batch: int = 8,
                 page_size: int = 128, num_pages: Optional[int] = None,
                 max_seq: int = 2048, dtype=jnp.bfloat16,
                 eos_token_id: Optional[int] = None, tp_size: int = 1,
                 ep_size: int = 1, decode_chunk: int = 1,
                 serving=None, telemetry=None, injector=None, clock=None,
                 replica_epoch=None, draft_model=None, draft_params=None,
                 comm_quant=None):
        """``serving``: a :class:`ServingRobustnessConfig` or its dict —
        defaults keep pre-hardening behaviour (unbounded queue, no
        deadlines).  ``injector``: a ``FaultInjector`` for the serving
        sites (built from ``serving.fault_injection`` when omitted).
        ``clock``: monotonic-seconds callable, injectable so deadline
        tests don't sleep.  ``telemetry``: explicit Telemetry instance;
        None uses the process singleton at event time.  ``replica_epoch``:
        set by the fleet front-end — namespaces request ids in the tracer
        so a respawned replica re-serving a redispatched id cannot read as
        a double admit in a merged audit.  ``draft_model``/``draft_params``:
        the speculative-decoding proposer (``serving.scheduler.speculative``
        — inference/scheduler.py); ignored unless that block enables it.
        ``comm_quant``: wire codec for KV-page migration payloads — a
        :class:`CommQuantizer`, the ``comm.quantization`` config block,
        or None (off); only the EXPORT side consults it, imports decode
        the self-describing payload regardless."""
        self.model = model
        self.config = model.config
        self.max_batch = max_batch
        self.page_size = page_size
        self.max_pages_per_seq = -(-max_seq // page_size)
        if num_pages is None:
            num_pages = max_batch * self.max_pages_per_seq + 1
        self.mesh = None
        caches = model.init_paged_caches(num_pages, page_size, dtype=dtype)
        if ep_size > 1:
            assert getattr(self.config, "is_moe", False), \
                "ep_size > 1 needs an MoE model"
            assert self.config.moe_num_experts % ep_size == 0, \
                "ep_size must divide the expert count"
        if tp_size > 1 or ep_size > 1:
            # tensor/expert-parallel serving: weights per the model's
            # tp_rules (expert leaves carry the ep axis on their leading
            # [E, ...] dim — reference megatron_gpt_moe EP containers), KV
            # pages sharded over the kv-head dim ([L, P, Hkv, page, D])
            from jax.sharding import NamedSharding, PartitionSpec as P
            from deepspeed_tpu.parallel import groups
            from deepspeed_tpu.parallel.topology import TopologyConfig
            from deepspeed_tpu.runtime.zero.stage_plan import ZeroShardingPlan
            assert self.config.kv_heads % tp_size == 0, \
                "tp_size must divide the kv-head count for paged serving"
            groups.reset_mesh()
            self.mesh = groups.initialize_mesh(
                TopologyConfig(tp=tp_size, ep=ep_size, fsdp=-1))
            plan = ZeroShardingPlan(self.mesh, stage=0,
                                    tp_rules=model.tp_rules())
            with self.mesh:
                params = jax.device_put(
                    params, plan._to_sharding(plan.param_specs(params)))
                caches = jax.device_put(
                    caches, NamedSharding(self.mesh,
                                          P(None, None, "tp", None, None)))
        self.params = params
        self.caches = caches
        self.cache_dtype = dtype
        if isinstance(serving, ServingRobustnessConfig):
            self.serving = serving
        else:
            self.serving = ServingRobustnessConfig(serving or {})
        if injector is None:
            injector = FaultInjector.from_config(
                self.serving.fault_injection)
        self.injector = injector
        self.alloc = PagedAllocator(num_pages, page_size,
                                    self.max_pages_per_seq,
                                    reserve_scratch=True,
                                    injector=injector)
        # content-hashed KV-page reuse (inference/prefix_cache.py): the
        # namespace pins cached pages to this model shape / cache dtype /
        # page size, so a differently-configured engine can never attach
        # a foreign page even through a shared registry
        self.prefix_cache = None
        pc_cfg = self.serving.prefix_cache
        if getattr(pc_cfg, "enabled", False):
            mc = self.config
            ns = (f"{type(model).__name__}/"
                  f"L{getattr(mc, 'n_layers', 0)}"
                  f"h{getattr(mc, 'hidden_size', 0)}"
                  f"q{getattr(mc, 'n_heads', 0)}"
                  f"kv{getattr(mc, 'kv_heads', 0)}"
                  f"v{getattr(mc, 'vocab_size', 0)}/"
                  f"{jnp.dtype(dtype).name}/page{page_size}")
            self.prefix_cache = PrefixCache(
                self.alloc, page_size, namespace=ns,
                max_cached_pages=int(pc_cfg.max_cached_pages),
                min_prefix_tokens=int(pc_cfg.min_prefix_tokens),
                on_evict=self._on_prefix_evict)
        self._copy_page_fn = None   # compiled COW page copy (lazy)
        # KV-page migration plumbing (disaggregated fleets): compiled
        # gather/scatter over page ids (lazy), handed-off prefills whose
        # pages stay pinned here, and imports awaiting their commit
        self._gather_pages_fn = None
        self._scatter_pages_fn = None
        self._kv_page_bytes = None
        self.comm_quant = (comm_quant
                           if isinstance(comm_quant, CommQuantizer)
                           else CommQuantizer.from_config(comm_quant))
        self.handoffs: Dict[Any, PrefillHandoff] = {}
        self._new_handoffs: List[Any] = []
        self._pending_imports: Dict[Any, Any] = {}
        self.eos = eos_token_id
        if not self.config.use_rope and not self.config.use_alibi:
            # learned positions: gathers past the table CLAMP under jit
            # (silent garbage), so bound the serve length up front
            assert max_seq <= self.config.max_seq_len, (
                f"max_seq {max_seq} exceeds the model's position table "
                f"({self.config.max_seq_len})")
        self.max_seq = max_seq

        self.slots: List[Optional[_Request]] = [None] * max_batch
        self.queue: List[_Request] = []
        self.finished: Dict[Any, List[int]] = {}
        # terminal records for requests that did NOT finish normally
        # (shed / deadline / evicted / drained) — the caller's delivery
        # channel for partial outputs; drain with pop_terminated()
        self.terminated: Dict[Any, RequestResult] = {}
        self.lengths = np.zeros(max_batch, np.int32)
        # +1 overrun column, permanently the scratch page (page 0): when a
        # reservation fills the whole table (prompt + max_new == max_seq),
        # the final chunk's last write indexes one page past the
        # reservation — this column catches it ON SCRATCH by construction
        # instead of relying on OOB-gather clamping (which would overwrite
        # the request's own last real page)
        self.tables = np.zeros((max_batch, self.max_pages_per_seq + 1),
                               np.int32)
        # attention backend: "auto" (Pallas kernel on TPU, jnp elsewhere),
        # "jnp" (gather oracle), "pallas", or "pallas-interpret" (the exact
        # kernel path through the interpreter — CPU CI).  Bound as static
        # kwargs BEFORE jit so every compiled shape uses one backend.
        self.attention_backend = self.serving.attention_backend
        attn_impl, attn_interpret = resolve_attention_backend(
            self.attention_backend)
        self._paged_call = functools.partial(
            self.model.apply_with_paged_cache,
            attn_backend=attn_impl, attn_interpret=attn_interpret)
        # one jit serves prefill (B=1, bucketed T) and decode (B=max_batch,
        # T=1) alike: jax.jit caches a compilation per input shape
        self._step_fn = jax.jit(self._paged_call, donate_argnums=(2,))
        self._rng = {}
        # multi-token decode: one device program advances every slot
        # ``decode_chunk`` tokens (sampling included) per host round-trip.
        # Through a tunneled chip the per-dispatch floor (~69 ms measured,
        # ONCHIP_r03/inference_latency.json) dominates single-token decode,
        # so chunking multiplies serving throughput by ~decode_chunk.
        self.decode_chunk = int(decode_chunk)
        assert self.decode_chunk >= 1

        self._clock = clock if clock is not None else time.monotonic
        self._telemetry = telemetry
        # profiling plane (monitor/profiling.py): route the serving jit
        # entry points through the CompileWatcher — shape-bucket churn
        # shows up as compile/* events, and a recompile storm flips
        # health()["recompile_storm"].  Telemetry must be bound first.
        self._storm_flagged = False
        self._step_fn = self._wrap_compiled(self._step_fn, "serve/step_fn")
        self._admission = AdmissionController(self.serving)
        # per-request lifecycle traces on the SAME injectable clock as the
        # deadline machinery — always on (host dict ops), so the
        # trace-completeness invariant in leak_report() holds even with
        # telemetry disabled
        self.replica_epoch = replica_epoch
        self.tracer = RequestTracer(clock=self._clock, epoch=replica_epoch)
        # critical-path attribution on the same clock — always on like
        # the tracer (host dict ops); each terminal pairs with one
        # frozen serve/request/attr event whose stage sum equals the
        # traced e2e by construction
        self.attrib = RequestAttributor(clock=self._clock)
        self._consec_step_faults = 0
        self.draining = False
        self.stats = {"admitted": 0, "rejected": 0, "shed": 0,
                      "deadline": 0, "evicted": 0, "finished": 0,
                      "step_faults": 0, "drains": 0, "prefix_hits": 0,
                      "prefix_cow_copies": 0, "prefix_evictions": 0,
                      "slo_attained": 0, "slo_missed": 0,
                      "goodput_tokens": 0,
                      "prefill_handoffs": 0, "imports": 0}
        # one frozen event per engine records which attention path every
        # serve/step span of this stream ran (ds_telemetry_report keys
        # its serving-attention table off it)
        self._serve_event("serve/backend",
                          attention_backend=self.attention_backend,
                          impl=attn_impl or "auto",
                          interpret=int(attn_interpret))
        # pluggable step scheduler (inference/scheduler.py): the
        # serving.scheduler block picks the policy; "monolithic" keeps
        # the pre-scheduler behaviour bit-for-bit.  One frozen
        # serve/sched event per engine records the policy the stream ran.
        self.scheduler = create_scheduler(self, self.serving.scheduler,
                                          draft_model=draft_model,
                                          draft_params=draft_params)
        self._serve_event("serve/sched", **self.scheduler.meta())
        # incident plane: bundles snapshot this engine's health() and its
        # in-flight request traces alongside the flight-recorder dump
        incidents = getattr(self.telemetry, "incidents", None)
        if incidents is not None:
            incidents.add_context("serving_health", self.health)
            incidents.add_context("inflight_traces",
                                  self.tracer.snapshot_open)

    # -- telemetry -------------------------------------------------------
    @property
    def telemetry(self):
        return self._telemetry if self._telemetry is not None \
            else get_telemetry()

    @property
    def _profiling(self):
        tel = self.telemetry
        return getattr(tel, "profiling", None) if tel is not None else None

    def _wrap_compiled(self, fn, site):
        """Compile-tracing wrapper (no-op without the profiling plane)."""
        prof = self._profiling
        return prof.wrap(fn, site) if prof is not None else fn

    def _prof_track(self, span):
        """HBM attribution context for serve_step/prefill spans."""
        prof = self._profiling
        return prof.track(span) if prof is not None \
            else contextlib.nullcontext()

    def _serve_event(self, name, **attrs):
        tel = self.telemetry
        if tel is None or not tel.enabled:
            return
        clean = {k: (v if isinstance(v, (int, float, str)) else str(v))
                 for k, v in attrs.items() if v is not None and v != ""}
        tel.serve(name, attrs=clean or None)

    def _observe_ms(self, name, ms):
        """Record one latency sample into registry histogram ``name``
        (telemetry-gated; None samples — state never reached — drop)."""
        if ms is None:
            return
        tel = self.telemetry
        if tel is not None and tel.enabled:
            tel.registry.histogram(name).observe(ms)

    def _close_trace(self, req: _Request, terminal: str, reason: str = ""):
        """Close a request's lifecycle trace with its terminal: bump SLO /
        goodput counters from the deadline verdict, land the latency
        histogram samples, and emit the frozen ``serve/request/<terminal>``
        trace event carrying every derived latency."""
        tr = self.tracer.terminal(req.req_id, terminal,
                                  n_generated=len(req.out), reason=reason)
        if tr is None:   # leak_report() will surface the tracer error
            return
        slo = tr.slo()
        if slo == "ok":
            self.stats["slo_attained"] += 1
        elif slo == "miss":
            self.stats["slo_missed"] += 1
        if terminal == "finish":
            self.stats["goodput_tokens"] += len(req.out)
        tel = self.telemetry
        if tel is not None and tel.enabled:
            if slo == "ok":
                tel.count("serve/slo_attained")
            elif slo == "miss":
                tel.count("serve/slo_missed")
            if terminal == "finish":
                # decode-rate and end-to-end distributions track SUCCESSFUL
                # requests; abnormal terminals would skew them downward
                tel.count("serve/goodput_tokens", len(req.out))
                self._observe_ms("serve/tpot_ms", tr.tpot_ms())
                self._observe_ms("serve/e2e_ms", tr.e2e_ms())
        self._serve_event(
            f"serve/request/{terminal}", req_id=req.req_id,
            slot=(tr.slot if tr.slot >= 0 else None),
            reason=reason, n_generated=len(req.out),
            queue_wait_ms=_round_ms(tr.queue_wait_ms()),
            ttft_ms=_round_ms(tr.ttft_ms()),
            tpot_ms=_round_ms(tr.tpot_ms()),
            e2e_ms=_round_ms(tr.e2e_ms()), slo=slo,
            slo_class=req.slo_class)
        # critical-path attribution rides adjacent to the terminal: one
        # frozen serve/request/attr event whose ordered stage breakdown
        # sums to e2e_ms.  Closed at the tracer's terminal timestamp so
        # both events agree on when the request ended.
        attrs = self.attrib.finalize(req.req_id, terminal,
                                     now=tr.t_terminal)
        if attrs is not None:
            self._serve_event("serve/request/attr", **attrs)

    # -- host control flow ---------------------------------------------
    def _reject(self, req_id, reason, detail=""):
        self.stats["rejected"] += 1
        self._serve_event("serve/reject", req_id=req_id, reason=reason,
                          detail=detail)
        raise RequestRejected(req_id, reason, detail)

    def add_request(self, req_id, prompt_ids, max_new_tokens: int = 32,
                    temperature: float = 0.0, seed: int = 0,
                    top_k: int = 0, top_p: float = 1.0,
                    deadline_s: Optional[float] = None,
                    slo_class: Optional[str] = None,
                    prefill_only: bool = False):
        """Validate and enqueue one request.  Raises
        :class:`RequestRejected` (typed reason, engine state untouched)
        instead of asserting; ``deadline_s`` is a TTL from now — the
        request is cancelled at the next step boundary once it expires,
        queued or mid-flight.  ``slo_class`` ("latency" | "throughput",
        default ``serving.scheduler.slo_class_default``) orders admission
        and prefill-chunk scheduling under the chunked policy and picks
        the per-class TTL default when ``deadline_s`` is omitted.
        ``prefill_only`` (disaggregated fleets): validate and reserve
        exactly as a full request — same buckets, same feasibility — but
        capture a :class:`PrefillHandoff` at prefill completion instead
        of decoding; collect with :meth:`pop_prefilled`."""
        cfg = self.serving
        if self.draining:
            self._reject(req_id, REJECT_DRAINING,
                         "engine is draining; admission stopped")
        prompt = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        if not prompt or int(max_new_tokens) <= 0:
            self._reject(req_id, REJECT_BAD_REQUEST,
                         f"prompt len {len(prompt)}, "
                         f"max_new_tokens {max_new_tokens}")
        if len(prompt) + max_new_tokens > self.max_seq:
            self._reject(req_id, REJECT_OVERSIZED,
                         f"prompt {len(prompt)} + budget {max_new_tokens} "
                         f"exceeds max_seq {self.max_seq}")
        if cfg.max_prompt_tokens and len(prompt) > int(cfg.max_prompt_tokens):
            self._reject(req_id, REJECT_OVERSIZED,
                         f"prompt {len(prompt)} exceeds "
                         f"serving.max_prompt_tokens {cfg.max_prompt_tokens}")
        total = len(prompt) + max_new_tokens
        # worst-case reservation (no cached prefix), using the SAME
        # padding the scheduler will request at slot-fill time
        padded = self.scheduler.prefill_padded_len(len(prompt))
        need = -(-min(max(total, padded),
                      self.max_pages_per_seq * self.page_size)
                 // self.page_size)
        usable = self.alloc.num_pages - 1   # minus the scratch page
        if need > usable:
            self._reject(req_id, REJECT_INFEASIBLE,
                         f"needs {need} pages but the pool only has "
                         f"{usable}; it would deadlock the queue "
                         "head-of-line")
        if req_id in self.alloc.seq_pages or req_id in self.finished or \
                any(r.req_id == req_id for r in self.queue):
            self._reject(req_id, REJECT_DUPLICATE,
                         "req_id already queued, active, or undelivered")
        if not (0.0 < top_p <= 1.0) or top_k < 0 or temperature < 0.0:
            self._reject(req_id, REJECT_BAD_SAMPLING,
                         f"top_k={top_k}, top_p={top_p}, "
                         f"temperature={temperature}")
        sched_cfg = cfg.scheduler
        if slo_class is None:
            slo_class = sched_cfg.slo_class_default
        if slo_class not in SLO_CLASSES:
            self._reject(req_id, REJECT_BAD_REQUEST,
                         f"slo_class {slo_class!r} is not one of "
                         f"{SLO_CLASSES}")
        self._apply_admission_policy(req_id)
        now = self._clock()
        # TTL precedence: explicit deadline_s > the SLO class's default
        # (serving.scheduler.slo_classes) > serving.default_deadline_s
        ttl = deadline_s if deadline_s is not None \
            else (sched_cfg.class_deadline_s(slo_class)
                  or float(cfg.default_deadline_s) or None)
        deadline = (now + ttl) if ttl else 0.0
        self.queue.append(_Request(req_id, prompt, max_new_tokens,
                                   temperature, seed, top_k, top_p,
                                   submit_time=now, deadline=deadline,
                                   slo_class=slo_class,
                                   prefill_only=bool(prefill_only)))
        self.stats["admitted"] += 1
        # lifecycle trace opens HERE: admission is the promise leak_report
        # audits — exactly one serve/request/* terminal closes it
        self.tracer.admit(req_id, deadline=deadline, now=now)
        self.attrib.admit(req_id, now=now)
        self._serve_event("serve/admit", req_id=req_id,
                          queue_depth=len(self.queue),
                          free_pages=self.alloc.free_page_count)
        self._serve_event("serve/request/admitted", req_id=req_id,
                          queue_depth=len(self.queue),
                          prompt_tokens=len(prompt),
                          max_new_tokens=int(max_new_tokens),
                          deadline=int(bool(deadline)),
                          slo_class=slo_class)
        self._admit()

    def _admission_pressure(self):
        cfg = self.serving
        hard_full = bool(cfg.max_queue) and \
            len(self.queue) >= int(cfg.max_queue)
        # reclaimable (cached, ref-0) pages are one eviction away from the
        # free list — counting them stops a warm prefix cache from reading
        # as page pressure and shedding admissible traffic
        overloaded = self._admission.update(len(self.queue),
                                            self.alloc.available_page_count)
        return hard_full, overloaded

    def _apply_admission_policy(self, req_id):
        """Admission control for one arrival: no-op until the hard queue
        cap or a watermark trips, then apply ``serving.overload_policy``
        — ``reject`` raises, ``shed-oldest`` displaces the oldest queued
        request, ``block`` synchronously steps the engine until pressure
        clears or ``block_max_steps`` is spent (then rejects)."""
        hard_full, overloaded = self._admission_pressure()
        if not hard_full and not overloaded:
            return
        policy = self.serving.overload_policy
        if policy == "block":
            for _ in range(int(self.serving.block_max_steps)):
                if not (self.queue or self.n_active):
                    break
                # requests finishing while the arrival blocks stay
                # retrievable from ``finished`` — the caller isn't in its
                # step() loop to catch them
                self.finished.update(self.step())
                hard_full, overloaded = self._admission_pressure()
                if not hard_full and not overloaded:
                    return
        elif policy == "shed-oldest" and self.queue:
            # the newcomer displaces the oldest QUEUED request (head of
            # line), so queue depth is unchanged and admission proceeds;
            # pure page-pressure overload with an empty queue still
            # rejects — shedding queued work frees no pages
            victim = self.queue.pop(0)
            self._terminate(victim, "shed", SHED_OLDEST,
                            detail=f"displaced by {req_id!r}")
            self.stats["shed"] += 1
            self._serve_event("serve/shed", req_id=victim.req_id,
                              reason=SHED_OLDEST)
            return
        reason = REJECT_QUEUE_FULL if hard_full else REJECT_OVERLOADED
        self._reject(req_id, reason,
                     f"queue_depth={len(self.queue)}, "
                     f"free_pages={self.alloc.free_page_count}, "
                     f"policy={policy}")

    def _bucket(self, n: int) -> int:
        return 1 << max(3, math.ceil(math.log2(max(n, 1))))

    def _terminate(self, req: _Request, status: str, reason: str,
                   detail: str = ""):
        """Record the typed terminal result for a request leaving the
        engine abnormally; the partial output (prompt + generated) rides
        in the record.  Pages are the caller's job (queued requests own
        none)."""
        self._rng.pop(req.req_id, None)
        self.terminated[req.req_id] = RequestResult(
            req_id=req.req_id, status=status, reason=reason,
            tokens=list(req.prompt) + list(req.out),
            n_generated=len(req.out), detail=detail)
        self._close_trace(req, _TERMINAL_BY_STATUS[status], reason=reason)

    def _evict_slot(self, slot: int, status: str, reason: str,
                    detail: str = ""):
        """Remove ONE active request: free its pages immediately, zero its
        table row and length, record the terminal result.  The rest of the
        batch is untouched."""
        req = self.slots[slot]
        self.scheduler.release_slot(slot, req)
        self.alloc.free_sequence(req.req_id)
        self.slots[slot] = None
        self.lengths[slot] = 0
        self.tables[slot, :] = 0
        self._terminate(req, status, reason, detail)

    def _expire_deadlines(self):
        """Cancel every expired request at this step boundary — queued
        requests are dropped from the queue, mid-flight ones are evicted
        with their pages freed immediately."""
        now = self._clock()
        keep, expired = [], []
        for req in self.queue:
            (expired if req.deadline and now >= req.deadline
             else keep).append(req)
        self.queue = keep
        for req in expired:
            self._terminate(req, "deadline", SHED_DEADLINE,
                            detail="expired while queued")
            self.stats["deadline"] += 1
            self._serve_event("serve/deadline", req_id=req.req_id,
                              reason=SHED_DEADLINE, where="queued")
        evicted = False
        for slot, req in enumerate(self.slots):
            if req is not None and req.deadline and now >= req.deadline:
                rid = req.req_id
                self._evict_slot(slot, "deadline", SHED_DEADLINE,
                                 detail="expired mid-flight")
                self.stats["deadline"] += 1
                self._serve_event("serve/deadline", req_id=rid,
                                  reason=SHED_DEADLINE, where="active")
                evicted = True
        if evicted:
            self._admit()

    def _admit(self):
        # policy hook: the chunked scheduler stable-sorts latency-class
        # requests ahead of throughput-class ones (FIFO within a class)
        self.scheduler.order_queue()
        for slot in range(self.max_batch):
            if not self.queue or self.slots[slot] is not None:
                continue
            req = self.queue[0]
            total = len(req.prompt) + req.max_new_tokens
            # prefix cache: attach every fully-cached prefix page without
            # prefill; a partial next-page match copies on write.  The
            # lookup is a pure read — nothing is pinned until allocate().
            match = (self.prefix_cache.lookup(req.prompt)
                     if self.prefix_cache is not None else PrefixMatch())
            cached = match.cached_tokens(self.page_size)
            # the scheduler owns the prefill shape: the monolithic policy
            # pads the suffix to a power-of-two bucket, the chunked one
            # to a whole number of prefill chunks
            padded = self.scheduler.prefill_padded_len(
                len(req.prompt) - cached)
            # reservation covers the budget AND the padded suffix prefill;
            # the cap keeps an unaligned cached prefix from pushing the
            # padding past the table — padding writes past the reservation
            # land on the sacrificial scratch page (clamped/zero columns)
            need_tokens = min(max(total, cached + padded),
                              self.max_pages_per_seq * self.page_size)
            shared = list(match.pages)
            protect = (match.cow_src,) if match.cow_src is not None else ()
            need_fresh = -(-need_tokens // self.page_size) - len(shared)
            pinned = set(shared) | set(protect)
            evictable = sum(1 for p in self.alloc.reclaimable
                            if p not in pinned)
            if need_fresh > self.alloc.free_page_count + evictable:
                return          # head-of-line: keep FIFO order
            # full reservation (prompt + budget) at admission: an admitted
            # request can NEVER deadlock on pages mid-flight (no vLLM-style
            # preemption/recompute machinery needed); only bucket-padding
            # surplus is returned after prefill.  Allocate BEFORE popping:
            # an injected page_alloc fault leaves nothing mutated — shared
            # refcounts untouched, nothing half-attached — and the request
            # retries from the queue on the next step, unchanged.
            try:
                pages = self.alloc.allocate(req.req_id, need_tokens,
                                            shared=shared, protect=protect)
            except PageAllocationError as e:
                self.stats["step_faults"] += 1
                self._serve_event("serve/fault", req_id=req.req_id,
                                  site="page_alloc", error=str(e))
                return
            if cached:
                self.stats["prefix_hits"] += 1
                self._serve_event("serve/prefix_hit", req_id=req.req_id,
                                  pages_reused=len(shared),
                                  tokens_reused=cached,
                                  cow=int(match.cow_src is not None))
            self.queue.pop(0)
            self.tables[slot, :] = 0
            self.tables[slot, :len(pages)] = pages
            self.lengths[slot] = 0
            self.slots[slot] = req
            tr = self.tracer.prefill_start(req.req_id, slot)
            self.attrib.prefill_start(req.req_id)
            if tr is not None:
                self._observe_ms("serve/queue_wait_ms", tr.queue_wait_ms())
                self._serve_event("serve/request/prefill_start",
                                  req_id=req.req_id, slot=slot,
                                  pages=len(pages), cached_tokens=cached,
                                  queue_wait_ms=_round_ms(
                                      tr.queue_wait_ms()))
            try:
                if match.cow_src is not None:
                    # the request's first owned page inherits the partial
                    # match's content; its divergent tail is overwritten
                    # by the suffix prefill, so the shared source page is
                    # never touched
                    self._copy_page(match.cow_src, pages[len(shared)])
                    self.stats["prefix_cow_copies"] += 1
                    self._serve_event("serve/prefix_cow",
                                      req_id=req.req_id,
                                      src=int(match.cow_src),
                                      dst=int(pages[len(shared)]),
                                      tokens=int(match.cow_tokens))
                complete = self.scheduler.fill_slot(slot, req, cached)
            except Exception as e:   # fault isolation: only THIS request
                logger.warning(f"evicting request {req.req_id!r} after "
                               f"prefill fault: {e}")
                self._evict_slot(slot, "evicted", EVICT_FAULT,
                                 detail=str(e))
                self.stats["evicted"] += 1
                self._serve_event("serve/evict", req_id=req.req_id,
                                  reason=EVICT_FAULT, error=str(e))
                continue
            if complete:
                # monolithic: the whole prefill ran inside fill_slot;
                # chunked defers both the prefill and this completion to
                # later step() calls (_complete_prefill at the last chunk)
                self._complete_prefill(slot, req)

    def _complete_prefill(self, slot: int, req: _Request):
        """Admission tail once the prompt is fully in cache: trim the
        padded reservation to the true need and index the prompt's full
        pages into the prefix cache."""
        self._trim_reservation(slot, req)
        if self.prefix_cache is not None:
            added = self.prefix_cache.insert(
                req.prompt, self.alloc.seq_pages[req.req_id])
            if added:
                self._serve_event("serve/prefix_insert",
                                  req_id=req.req_id, pages=added)
        if req.prefill_only:
            self._capture_handoff(slot, req)

    def _capture_handoff(self, slot: int, req: _Request):
        """Prefill-only admission tail: the prompt is fully in cache and
        the first token is sampled, so capture everything a decode
        replica needs, shrink the reservation to the prompt pages, and
        keep them PINNED under this request id until
        :meth:`release_handoff`.  The slot frees immediately for the next
        prefill — that asymmetry is the whole point of the role split."""
        self.alloc.shrink(req.req_id, len(req.prompt))
        rng = self._rng.pop(req.req_id, None)
        # serialize the timing context BEFORE the trace closes below —
        # finalize pops it; the handoff-capture stamp starts the migrate
        # stage the decode side's import will close
        trace_ctx = self.attrib.capture_handoff(req.req_id)
        self.handoffs[req.req_id] = PrefillHandoff(
            req_id=req.req_id, prompt=list(req.prompt),
            max_new_tokens=req.max_new_tokens,
            temperature=req.temperature, seed=req.seed,
            top_k=req.top_k, top_p=req.top_p, slo_class=req.slo_class,
            last_token=int(req.last_token), out=list(req.out),
            rng_state=(rng.bit_generator.state if rng is not None
                       else None),
            pages=list(self.alloc.seq_pages[req.req_id]),
            trace_ctx=trace_ctx)
        self._new_handoffs.append(req.req_id)
        self.scheduler.release_slot(slot, req)
        self.slots[slot] = None
        self.lengths[slot] = 0
        self.tables[slot, :] = 0
        self.stats["prefill_handoffs"] += 1
        self._close_trace(req, "finish", reason="prefill_handoff")

    # -- KV-page migration (disaggregated fleets) ------------------------
    @property
    def kv_page_bytes(self) -> int:
        """Analytic bytes of ONE KV page across every cache leaf (all
        layers, K and V) — the unit the fleet's page-transfer budget and
        bytes-saved accounting multiply by."""
        if self._kv_page_bytes is None:
            self._kv_page_bytes = sum(
                int(np.prod(leaf.shape[:1] + leaf.shape[2:])) *
                jnp.dtype(leaf.dtype).itemsize
                for leaf in jax.tree_util.tree_leaves(self.caches))
        return self._kv_page_bytes

    @staticmethod
    def _pad_pow2(ids) -> np.ndarray:
        """Page-id vector padded to a power-of-two length with the
        scratch page (0): bounds the gather/scatter jit cache to log2
        distinct shapes, and pad traffic lands on the sacrificial scratch
        page by construction."""
        n = max(1, len(ids))
        out = np.zeros(1 << (n - 1).bit_length(), np.int32)
        out[:len(ids)] = ids
        return out

    def pop_prefilled(self) -> Dict[Any, PrefillHandoff]:
        """Hand back the handoffs captured since the last call (req_id →
        :class:`PrefillHandoff`).  Pages stay pinned under this engine's
        allocator until :meth:`release_handoff` — the fleet releases only
        AFTER the decode side commits, so a kill of either replica
        mid-migration leaves one consistent copy to redispatch from."""
        out = {rid: self.handoffs[rid] for rid in self._new_handoffs
               if rid in self.handoffs}
        self._new_handoffs = []
        return out

    def release_handoff(self, req_id) -> bool:
        """Unpin a handed-off request's prompt pages (the decode side
        acknowledged, or the fleet abandoned the migration).  The full
        prompt pages were indexed into this replica's prefix cache at
        capture, so they park in the reclaimable tier — the hot prefix
        stays warm for the next prefill instead of dissolving."""
        if self.handoffs.pop(req_id, None) is None:
            return False
        self.alloc.free_sequence(req_id)
        return True

    def export_pages(self, page_ids):
        """Device-gather the KV content of ``page_ids`` (every layer,
        every cache leaf) into a standalone payload pytree shaped like
        the cache with P = pow2-padded ``len(page_ids)`` — the migration
        wire format.  Pure read, no donation."""
        padded = self._pad_pow2(page_ids)
        if self._gather_pages_fn is None:
            def gather(caches, ids):
                return jax.tree_util.tree_map(
                    lambda leaf: leaf[:, ids], caches)
            self._gather_pages_fn = self._wrap_compiled(
                jax.jit(gather), "serve/export_pages")
        if self.mesh is not None:
            with self.mesh:
                return self._gather_pages_fn(self.caches,
                                             jnp.asarray(padded))
        return self._gather_pages_fn(self.caches, jnp.asarray(padded))

    def import_pages(self, payload, page_ids):
        """Scatter an exported payload into this engine's ``page_ids``
        (the :meth:`export_pages` counterpart; donation makes it an
        in-place page write).  Payload pad lanes beyond ``len(page_ids)``
        scatter onto the sacrificial scratch page.  Quantized payloads
        (the source replica's ``comm_quant`` wire codec) are
        self-describing and dequantize here — the destination needs no
        matching config."""
        payload = CommQuantizer.decode_payload(payload)
        leaves = jax.tree_util.tree_leaves(payload)
        padded = np.zeros(leaves[0].shape[1], np.int32)
        padded[:len(page_ids)] = page_ids
        if self._scatter_pages_fn is None:
            def scatter(caches, payload, ids):
                return jax.tree_util.tree_map(
                    lambda leaf, pay: leaf.at[:, ids].set(pay),
                    caches, payload)
            self._scatter_pages_fn = self._wrap_compiled(
                jax.jit(scatter, donate_argnums=(0,)),
                "serve/import_pages")
        if self.mesh is not None:
            with self.mesh:
                self.caches = self._scatter_pages_fn(
                    self.caches, payload, jnp.asarray(padded))
        else:
            self.caches = self._scatter_pages_fn(self.caches, payload,
                                                 jnp.asarray(padded))

    def import_request(self, handoff: PrefillHandoff, payload=None,
                       shared_pages=(), deadline_s=None) -> bool:
        """Install a migrated request directly into a decode slot: full
        reservation (prompt + budget) attaching ``shared_pages`` (pages
        already resident here by content — the dedup plan from
        ``prefix_cache.resident_prefix``), scatter ``payload`` (the
        source's exported non-shared prompt pages) into freshly owned
        pages, and restore the sampler stream.  NOTHING observable —
        tracer, events, stats, prefix index — mutates until
        :meth:`commit_import`, and :meth:`cancel_import` rolls the
        installation back to nothing, so the fleet's ``migrate_commit``
        fault site is all-or-nothing.  Returns True when installed, False
        when this engine cannot take it right now (draining, no free
        slot, page pressure, id collision)."""
        if self.draining:
            return False
        slot = next((s for s in range(self.max_batch)
                     if self.slots[s] is None), None)
        if slot is None:
            return False
        rid = handoff.req_id
        if rid in self.alloc.seq_pages or rid in self.finished or \
                any(r.req_id == rid for r in self.queue):
            return False
        total = len(handoff.prompt) + handoff.max_new_tokens
        shared = list(shared_pages)
        try:
            pages = self.alloc.allocate(rid, total, shared=shared)
        except PageAllocationError:
            return False
        try:
            n_import = len(handoff.pages) - len(shared)
            if n_import > 0:
                self.import_pages(
                    payload, pages[len(shared):len(shared) + n_import])
        except Exception:
            self.alloc.free_sequence(rid)
            raise
        req = _Request(rid, list(handoff.prompt),
                       handoff.max_new_tokens, handoff.temperature,
                       handoff.seed, handoff.top_k, handoff.top_p,
                       out=list(handoff.out),
                       last_token=handoff.last_token,
                       submit_time=self._clock(),
                       slo_class=handoff.slo_class,
                       prefilled=len(handoff.prompt))
        if deadline_s is not None:
            req.deadline = self._clock() + float(deadline_s)
        if handoff.rng_state is not None:
            rng = np.random.default_rng(handoff.seed)
            rng.bit_generator.state = handoff.rng_state
            self._rng[rid] = rng
        self.tables[slot, :] = 0
        self.tables[slot, :len(pages)] = pages
        self.lengths[slot] = len(handoff.prompt)
        self.slots[slot] = req
        self._pending_imports[rid] = (slot, handoff, len(shared))
        return True

    def commit_import(self, req_id):
        """Make an installed import observable: open the lifecycle trace
        (admit → prefill_start → first_token; the source already sampled
        the first token), bump counters, and index the prompt pages into
        this replica's prefix cache so the NEXT request sharing the
        prefix skips its transfer entirely (migrate-once-per-replica)."""
        slot, handoff, n_shared = self._pending_imports.pop(req_id)
        req = self.slots[slot]
        self.stats["admitted"] += 1
        self.stats["imports"] += 1
        self.tracer.admit(req_id, deadline=req.deadline,
                          now=self._clock())
        # adopt the migrated timing context: the attr event at this
        # replica's terminal reports the FULL path (source queue +
        # prefill, the migration wait closed by this import, decode here)
        self.attrib.import_ctx(req_id, handoff.trace_ctx)
        self._serve_event("serve/admit", req_id=req_id,
                          queue_depth=len(self.queue),
                          free_pages=self.alloc.free_page_count)
        self._serve_event("serve/request/admitted", req_id=req_id,
                          queue_depth=len(self.queue),
                          prompt_tokens=len(req.prompt),
                          max_new_tokens=int(req.max_new_tokens),
                          deadline=int(bool(req.deadline)),
                          slo_class=req.slo_class)
        tr = self.tracer.prefill_start(req_id, slot)
        if tr is not None:
            self._serve_event("serve/request/prefill_start",
                              req_id=req_id, slot=slot,
                              pages=len(self.alloc.seq_pages[req_id]),
                              cached_tokens=n_shared * self.page_size,
                              queue_wait_ms=_round_ms(tr.queue_wait_ms()))
        self._note_first_token(slot, req)
        if self.prefix_cache is not None:
            added = self.prefix_cache.insert(
                req.prompt, self.alloc.seq_pages[req_id])
            if added:
                self._serve_event("serve/prefix_insert", req_id=req_id,
                                  pages=added, at="import")
        return req

    def cancel_import(self, req_id) -> bool:
        """Roll an installed (uncommitted) import back to nothing: free
        the pages, clear the slot, drop the restored RNG.  No trace was
        opened and no event fired, so a faulted ``migrate_commit`` leaves
        this engine exactly as it was (all-or-nothing)."""
        entry = self._pending_imports.pop(req_id, None)
        if entry is None:
            return False
        slot, _, _ = entry
        self.attrib.discard(req_id)
        self.alloc.free_sequence(req_id)
        self._rng.pop(req_id, None)
        self.slots[slot] = None
        self.lengths[slot] = 0
        self.tables[slot, :] = 0
        return True

    def _trim_reservation(self, slot: int, req: _Request):
        """Trim the slot's reservation to the request's TRUE page need.

        Bucketed prefill over-allocates to the padded suffix length; the
        surplus used to be returned only when ``need_tokens > total``,
        leaving the invariant to the caller.  Trimming unconditionally —
        and asserting the result — is what lets the ragged kernel, the
        block tables, and the allocator all agree on true lengths
        (``leak_report`` audits the same invariant engine-wide)."""
        total = len(req.prompt) + req.max_new_tokens
        self.alloc.shrink(req.req_id, total)
        pages = self.alloc.seq_pages[req.req_id]
        expected = max(1, -(-total // self.page_size))
        assert len(pages) == expected, (
            f"request {req.req_id!r}: {len(pages)} pages held after trim, "
            f"expected {expected} for {total} tokens "
            f"(page_size {self.page_size})")
        self.tables[slot, :] = 0
        self.tables[slot, :len(pages)] = pages

    def _run_step(self, ids, tables, lengths, phase="decode"):
        with self.telemetry.span("serve/step",
                                 attrs={"backend": self.attention_backend,
                                        "phase": phase,
                                        "batch": int(ids.shape[0]),
                                        "tokens": int(ids.shape[1])}), \
                self._prof_track("prefill" if phase == "prefill"
                                 else "serve_step"):
            if self.mesh is not None:
                with self.mesh:
                    return self._step_fn(self.params, ids, self.caches,
                                         tables, lengths)
            return self._step_fn(self.params, ids, self.caches, tables,
                                 lengths)

    # -- prefix-cache plumbing ------------------------------------------
    def _on_prefix_evict(self, page: int):
        """Allocator reclaimed a cached page for a fresh allocation (the
        cache already dropped its index entries)."""
        self.stats["prefix_evictions"] += 1
        self._serve_event("serve/prefix_evict", page=int(page))

    def _copy_page(self, src: int, dst: int):
        """Copy-on-write: device-copy one KV page (every layer, every
        cache leaf) into the request's own fresh page.  Donating the
        cache buffers makes this an in-place page write, not a full-cache
        copy."""
        if self._copy_page_fn is None:
            def copy(caches, src, dst):
                return jax.tree_util.tree_map(
                    lambda leaf: leaf.at[:, dst].set(leaf[:, src]), caches)
            self._copy_page_fn = self._wrap_compiled(
                jax.jit(copy, donate_argnums=(0,)), "serve/copy_page")
        if self.mesh is not None:
            with self.mesh:
                self.caches = self._copy_page_fn(
                    self.caches, jnp.int32(src), jnp.int32(dst))
        else:
            self.caches = self._copy_page_fn(
                self.caches, jnp.int32(src), jnp.int32(dst))

    def _prefill(self, slot: int, req: _Request, bucket: int,
                 cached: int = 0):
        """Prefill the UNCACHED suffix: the first ``cached`` prompt tokens
        already sit in attached (or COW-copied) pages, so the device step
        runs only the remaining tokens at start position ``cached`` —
        causal attention reads the cached pages through the block table,
        so the result is bit-identical to a full prefill.  ``cached`` is
        capped at ``len(prompt) - 1`` upstream: the last prompt token
        always prefills, because its logits seed sampling."""
        suffix = req.prompt[cached:]
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :len(suffix)] = suffix
        t0 = self._clock()
        logits, self.caches, _ = self._run_step(
            jnp.asarray(ids),
            jnp.asarray(self.tables[slot:slot + 1]),
            jnp.full((1,), cached, jnp.int32), phase="prefill")
        # monolithic prefill is one dispatch: fold its active wall time
        # into the critical path's prefill stage (chunked prefills land
        # here per chunk via the scheduler)
        self.attrib.chunk(req.req_id, (self._clock() - t0) * 1000.0)
        self.lengths[slot] = len(req.prompt)
        req.prefilled = len(req.prompt)
        req.last_token = self._sample(
            req, np.asarray(logits[0, len(suffix) - 1]))
        # the first output token exists as of the sample above — a sampler
        # fault raises before this line, so an evicted-at-prefill request
        # correctly reports no TTFT
        self._note_first_token(slot, req)

    def _note_first_token(self, slot: int, req: _Request):
        """TTFT bookkeeping shared by the monolithic prefill and the
        chunked policy's final prefill chunk."""
        tr = self.tracer.first_token(req.req_id)
        self.attrib.first_token(req.req_id)
        if tr is not None:
            self._observe_ms("serve/ttft_ms", tr.ttft_ms())
            self._serve_event("serve/request/first_token",
                              req_id=req.req_id, slot=slot,
                              ttft_ms=_round_ms(tr.ttft_ms()))

    def _sample(self, req: _Request, logits: np.ndarray) -> int:
        if self.injector is not None:
            self.injector.check("serve_sample")
        if req.temperature <= 0.0:
            return int(np.argmax(logits))
        rng = self._rng.setdefault(req.req_id,
                                   np.random.default_rng(req.seed))
        l = logits.astype(np.float64) / req.temperature
        V = len(l)
        if req.top_k or req.top_p < 1.0:
            # rank-based filtering — EXACTLY cut tokens survive, stable
            # tie order, mirroring the device sampler's policy
            order = np.argsort(-l, kind="stable")
            ranks = np.empty(V, np.int64)
            ranks[order] = np.arange(V)
            k_eff = req.top_k if 0 < req.top_k < V else V
            l = np.where(ranks < k_eff, l, -np.inf)
            p = np.exp(l - l.max())
            p = p / p.sum()
            if req.top_p < 1.0:
                cs = np.cumsum(p[order])
                # smallest prefix whose mass reaches top_p
                cut = int(np.searchsorted(cs, req.top_p) + 1)
                p = np.where(ranks < cut, p, 0.0)
                p = p / p.sum()
        else:
            p = np.exp(l - l.max())
            p = p / p.sum()
        return int(rng.choice(V, p=p))

    def _finish(self, slot: int):
        req = self.slots[slot]
        self.finished[req.req_id] = req.prompt + req.out
        if self.prefix_cache is not None:
            # index the finished sequence's full pages (prompt AND
            # generated tokens — an agent turn's output is the next turn's
            # prompt) BEFORE the refcounts drop, so they park in the
            # reclaimable tier instead of dissolving into the free list
            added = self.prefix_cache.insert(
                req.prompt + req.out, self.alloc.seq_pages[req.req_id])
            if added:
                self._serve_event("serve/prefix_insert",
                                  req_id=req.req_id, pages=added,
                                  at="finish")
        self.scheduler.release_slot(slot, req)
        self.alloc.free_sequence(req.req_id)
        self._rng.pop(req.req_id, None)
        self.slots[slot] = None
        self.lengths[slot] = 0
        self.tables[slot, :] = 0
        self.stats["finished"] += 1
        self._serve_event("serve/finish", req_id=req.req_id,
                          n_generated=len(req.out))
        self._close_trace(req, "finish")
        self._admit()

    @property
    def n_active(self) -> int:
        return sum(s is not None for s in self.slots)

    def _check_compile_storm(self):
        """Rising-edge serve event when the CompileWatcher flags a
        recompile storm: serving shape-bucket churn is an operator error
        (bucketing misconfigured), so it lands in the frozen serve/*
        stream next to shed/fault events, not just the compile/* stream."""
        prof = self._profiling
        if prof is None:
            return
        active = bool(prof.storm_active)
        if active and not self._storm_flagged:
            snap = prof.compile_snapshot()
            self._serve_event("serve/compile_storm",
                              misses=int(snap.get("total_misses", 0)))
        self._storm_flagged = active

    # -- the batched decode step ---------------------------------------
    def step(self) -> Dict[Any, List[int]]:
        """Advance the engine by one scheduler step — under the default
        monolithic policy, every active request by one token
        (``decode_chunk`` tokens when configured); under the chunked
        policy, up to ``max_prefill_chunks_per_step`` prefill chunks
        first, then one decode (or speculative draft+verify) dispatch for
        every fully-prefilled slot.  Returns ONLY the requests that
        finished during this step (req_id → full tokens).  Expired
        deadlines are cancelled first; an injected ``serve_step`` fault
        returns {} WITHOUT mutating any request (the retry serves
        identically), and raises only after ``serving.step_fault_limit``
        consecutive faults."""
        self._expire_deadlines()
        if self.injector is not None:
            try:
                self.injector.check("serve_step")
            except Exception as e:
                self._consec_step_faults += 1
                self.stats["step_faults"] += 1
                self._serve_event("serve/fault", site="serve_step",
                                  error=str(e))
                if self._consec_step_faults > \
                        int(self.serving.step_fault_limit):
                    raise
                return {}
            self._consec_step_faults = 0
        self._admit()
        self._check_compile_storm()
        incidents = getattr(self.telemetry, "incidents", None)
        if incidents is not None:
            # SLO burn-rate sweep on the engine's (injectable) clock — a
            # sustained multi-window miss fraction opens one incident
            incidents.observe_slo(now=self._clock())
        return self.scheduler.run_step()

    # -- lifecycle / introspection --------------------------------------
    def pop_terminated(self) -> Dict[Any, RequestResult]:
        """Hand back (and clear) every terminal :class:`RequestResult`
        accumulated since the last call — the shed/deadline/evicted
        counterpart of the per-step finished dict."""
        out = self.terminated
        self.terminated = {}
        return out

    def drain(self, timeout_s: Optional[float] = None,
              max_steps: Optional[int] = None) -> Dict[str, Any]:
        """Gracefully quiesce: stop admission, shed everything still
        queued, then step until in-flight work finishes or the budget
        (``max_steps``, default = the largest remaining token budget;
        ``timeout_s`` wall-clock) runs out — whatever is left is shed
        with its partial output.  Returns
        ``{"finished", "shed", "steps", "health"}``; afterwards the
        engine holds zero active slots and zero allocated pages."""
        self.draining = True
        # handed-off prefills: unpin their pages — the fleet owns those
        # requests' lifecycles and re-homes them after the drain
        for rid in list(self.handoffs):
            self.release_handoff(rid)
        self._new_handoffs = []
        shed_ids = []
        for req in list(self.queue):
            self._terminate(req, "drained", SHED_DRAIN,
                            detail="shed from queue by drain()")
            self.stats["shed"] += 1
            self._serve_event("serve/shed", req_id=req.req_id,
                              reason=SHED_DRAIN)
            shed_ids.append(req.req_id)
        self.queue = []
        if max_steps is None:
            remaining = [r.max_new_tokens - len(r.out)
                         for r in self.slots if r is not None]
            max_steps = (-(-max(remaining) // self.decode_chunk) + 4) \
                if remaining else 0
            # chunked policy: in-flight prefills consume whole steps
            # before any decode happens — budget them in
            max_steps += self.scheduler.pending_prefill_steps()
        start = self._clock()
        finished: Dict[Any, List[int]] = {}
        steps = 0
        while self.n_active and steps < max_steps:
            if timeout_s is not None and \
                    self._clock() - start >= timeout_s:
                break
            finished.update(self.step())
            steps += 1
        for slot, req in enumerate(self.slots):
            if req is not None:
                rid = req.req_id
                self._evict_slot(slot, "drained", SHED_DRAIN,
                                 detail="drain budget exhausted")
                self.stats["shed"] += 1
                self._serve_event("serve/shed", req_id=rid,
                                  reason=SHED_DRAIN)
                shed_ids.append(rid)
        # prefill_only requests that completed DURING the drain steps
        # captured fresh handoffs — unpin those too
        for rid in list(self.handoffs):
            self.release_handoff(rid)
        self._new_handoffs = []
        self.stats["drains"] += 1
        self._serve_event("serve/drain", finished=len(finished),
                          shed=len(shed_ids), steps=steps)
        return {"finished": finished, "shed": shed_ids, "steps": steps,
                "health": self.health()}

    def health(self) -> Dict[str, Any]:
        """Operational snapshot; gauges are mirrored onto the telemetry
        registry (``serving/*``) so scrapers see them without calling
        in."""
        now = self._clock()
        live = list(self.queue) + [r for r in self.slots if r is not None]
        snap = {
            "free_pages": self.alloc.free_page_count,
            # free + reclaimable: what admission actually sees
            "available_pages": self.alloc.available_page_count,
            "total_pages": self.alloc.num_pages - 1,
            "queue_depth": len(self.queue),
            "active_slots": self.n_active,
            "max_batch": self.max_batch,
            "oldest_request_age_s": float(max(
                (now - r.submit_time for r in live), default=0.0)),
            "draining": self.draining,
            "overloaded": self._admission.overloaded,
            "undelivered_terminated": len(self.terminated),
            "handoffs_pinned": len(self.handoffs),
            "counters": dict(self.stats),
            "slo": {"attained": self.stats["slo_attained"],
                    "missed": self.stats["slo_missed"],
                    "goodput_tokens": self.stats["goodput_tokens"]},
            "traces": {"open": len(self.tracer.open),
                       "admitted": self.tracer.admitted,
                       "closed": self.tracer.closed,
                       "terminals": dict(self.tracer.terminals)},
        }
        snap["scheduler"] = self.scheduler.snapshot()
        if self.prefix_cache is not None:
            snap["prefix_cache"] = self.prefix_cache.snapshot()
        prof = self._profiling
        if prof is not None:
            # compile health: a recompile storm means serving latency is
            # going to compile, not tokens — operators page on this flag
            snap["compile"] = prof.compile_snapshot()
            snap["recompile_storm"] = bool(prof.storm_active)
        tel = self.telemetry
        if tel is not None and tel.enabled:
            # windowed latency distributions (ms) with p50/p90/p99 — the
            # same histograms the exporter serves as summary quantiles
            snap["latency"] = {
                name: tel.registry.histogram(name).summary()
                for name in ("serve/queue_wait_ms", "serve/ttft_ms",
                             "serve/tpot_ms", "serve/e2e_ms")}
            for key in ("free_pages", "available_pages", "queue_depth",
                        "active_slots", "oldest_request_age_s"):
                tel.registry.gauge(f"serving/{key}").set(snap[key])
            if self.prefix_cache is not None:
                pc = snap["prefix_cache"]
                # frozen serve/* gauge names (docs/serving.md)
                for gauge, key in (("serve/prefix_hit_rate", "hit_rate"),
                                   ("serve/prefix_tokens_reused",
                                    "tokens_reused"),
                                   ("serve/prefix_cow_copies", "cow_copies"),
                                   ("serve/prefix_evictions", "evictions"),
                                   ("serve/prefix_cached_pages",
                                    "cached_pages")):
                    tel.registry.gauge(gauge).set(pc[key])
            if "spec_acceptance_rate" in snap["scheduler"]:
                tel.registry.gauge("serve/spec_acceptance_rate").set(
                    snap["scheduler"]["spec_acceptance_rate"])
        if tel is not None and getattr(tel, "cluster", None) is not None:
            # distributed telemetry: cross-rank skew/straggler view rides
            # along on the same health surface operators already poll
            snap["cluster"] = tel.cluster.snapshot()
        return snap

    def leak_report(self) -> Dict[str, Any]:
        """Invariant audit: every page, RNG stream, and table row must be
        owned by a live slot, refcounts must match the held multiplicity
        (pages are SHARED under the prefix cache, so naive page counting
        would double-book them), and the prefix-cache index must agree
        with the allocator's cached set.  Returns {} when clean — every
        exit path (finish, shed, deadline, evict, drain) must keep it
        that way."""
        # handed-off prefills own their pinned prompt pages by design —
        # the fleet's migration transaction is their live owner
        active = {r.req_id for r in self.slots if r is not None} | \
            set(self.handoffs)
        leaks: Dict[str, Any] = {}
        stray_pages = sorted(set(self.alloc.seq_pages) - active, key=str)
        if stray_pages:
            leaks["stray_page_owners"] = stray_pages
        stray_rng = sorted(set(self._rng) - active, key=str)
        if stray_rng:
            leaks["stray_rng"] = stray_rng
        leaks.update(self.alloc.audit())
        if self.prefix_cache is not None:
            leaks.update(self.prefix_cache.audit())
        dirty = [s for s in range(self.max_batch)
                 if self.slots[s] is None and
                 (self.lengths[s] != 0 or self.tables[s].any())]
        if dirty:
            leaks["dirty_inactive_slots"] = dirty
        # every active slot's reservation must equal its TRUE page need
        # (prompt + budget) — _trim_reservation's invariant, the lengths
        # the ragged attention kernel and the allocator both work from
        over = {}
        for s, req in enumerate(self.slots):
            if req is None:
                continue
            total = len(req.prompt) + req.max_new_tokens
            expected = max(1, -(-total // self.page_size))
            held = len(self.alloc.seq_pages.get(req.req_id, ()))
            if held != expected:
                over[str(req.req_id)] = {"held": held, "expected": expected}
        if over:
            leaks["over_reserved_slots"] = over
        # scheduler-held state (speculative draft allocator): pages owned
        # by requests no longer active, allocator-internal inconsistencies
        leaks.update(self.scheduler.leak_report())
        # trace completeness: every admitted request is either still live
        # (queued/active) or reached exactly one serve/request/* terminal
        # — a handoff's trace CLOSED at capture, so it is not live here
        live = {r.req_id for r in self.queue} | \
            {r.req_id for r in self.slots if r is not None}
        leaks.update(self.tracer.audit(live))
        # HBM leak detector (profiling plane): monotonic live-byte growth
        # across snapshots — device memory the page allocator can't see
        prof = self._profiling
        if prof is not None:
            leaks.update(prof.leak_report())
        if leaks:
            incidents = getattr(self.telemetry, "incidents", None)
            if incidents is not None:
                # a broken invariant is an incident: one bundle per
                # episode (the manager's per-kind cooldown dedups the
                # supervisor's repeated polls)
                incidents.trigger("leak", source="serving/leak_report",
                                  detail=",".join(sorted(leaks)))
        return leaks

    # -- convenience ----------------------------------------------------
    def generate(self, prompts, max_new_tokens: int = 32,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0) -> List[List[int]]:
        """Serve a list of prompts (continuous batching when
        len(prompts) > max_batch); returns full token lists in order.
        Requests terminated mid-flight (deadline/eviction) contribute
        their partial tokens in place; a genuine stall raises
        :class:`ServingStalled` carrying every already-completed result
        instead of destroying them."""
        for i, p in enumerate(prompts):
            self.add_request(i, p, max_new_tokens, temperature,
                             top_k=top_k, top_p=top_p)
        steps = 0
        results: Dict[Any, List[int]] = {}
        limit = (max(len(p) for p in prompts) + max_new_tokens + 4) * \
            (len(prompts) + 1)
        if self.scheduler.policy == "chunked":
            # prefill chunks (and the draft's own prefill under
            # speculative decoding) consume whole steps before a slot
            # decodes — the monolithic bound already covers one step per
            # prompt token, so 3x covers target + draft chunks with slack
            limit *= 3
        while (self.queue or self.n_active) and steps < limit:
            results.update(self.step())
            steps += 1
        if self.queue or self.n_active:
            stuck = [r.req_id for r in self.queue] + \
                [r.req_id for r in self.slots if r is not None]
            raise ServingStalled(results, stuck,
                                 self.alloc.free_page_count,
                                 len(self.queue), steps)
        out = []
        for i in range(len(prompts)):
            if i in results:
                out.append(results[i])
            elif i in self.finished:   # finished inside a blocked add
                out.append(self.finished.pop(i))
            else:   # terminated mid-flight: partial tokens, in place
                out.append(self.terminated.pop(i).tokens)
        return out


def create_serving_engine(model, params, config=None, overlay_path=None,
                          **kwargs):
    """Build a :class:`ServingEngine` from a ds-style config dict.

    ``config`` is the combined config the autotuner sweeps: engine
    geometry (``max_batch`` / ``page_size`` / ``num_pages`` / ``max_seq``
    / ``decode_chunk`` / ``tp_size`` / ``ep_size``) may sit at top level
    or inside the ``serving`` block; everything else in ``serving``
    (watermarks, scheduler, fleet) passes through as the engine's
    robustness config.  When ``config["autotuning"]["overlay_path"]`` (or
    the explicit ``overlay_path``) names a persisted overlay, the tuned
    fragment is deep-merged over ``config`` first — the serving twin of
    the ``deepspeed.initialize()`` hook.  Explicit ``**kwargs`` win over
    everything (caller overrides).  The applied overlay's provenance is
    exposed as ``engine.overlay_provenance`` (None when no overlay)."""
    from deepspeed_tpu.autotuning.overlay import maybe_apply_overlay
    cfg = dict(config or {})
    cfg, provenance = maybe_apply_overlay(cfg, overlay_path)
    serving = dict(cfg.get("serving") or {})
    geometry = ("max_batch", "page_size", "num_pages", "max_seq",
                "decode_chunk", "tp_size", "ep_size", "eos_token_id")
    eng_kwargs = {}
    for key in geometry:
        if key in cfg:
            eng_kwargs[key] = cfg[key]
        if key in serving:   # the serving block wins over top level
            eng_kwargs[key] = serving.pop(key)
    eng_kwargs["serving"] = serving
    quant_cfg = (cfg.get("comm") or {}).get("quantization")
    if quant_cfg:
        eng_kwargs["comm_quant"] = quant_cfg
    eng_kwargs.update(kwargs)
    engine = ServingEngine(model, params, **eng_kwargs)
    engine.overlay_provenance = provenance
    return engine
