"""Continuous-batching serving engine over the paged KV cache.

Parity role: the reference serves decode through a per-request contiguous
KV workspace inside ``InferenceEngine`` (``inference_context.h`` workspace
management) — every request pays max-length allocation and batches must
line up.  The TPU-native upgrade is vLLM-style serving (PAPERS.md ragged
paged attention): fixed-size pages shared across sequences through block
tables, slot-based continuous batching (a finished request's pages free
immediately and the next prompt is admitted mid-flight), and one jitted
decode step for the whole active batch regardless of ragged lengths.

Host/device split: page allocation, admission, sampling bookkeeping are
host control flow (``PagedAllocator``); prefill and the batched decode
step are jitted device programs over ``CausalTransformerLM.
apply_with_paged_cache``.  Prefill lengths are bucketed to powers of two
to bound recompilation.
"""

import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.paged_attention import PagedAllocator
from deepspeed_tpu.utils.logging import logger


@dataclass
class _Request:
    req_id: Any
    prompt: List[int]
    max_new_tokens: int
    temperature: float = 0.0
    seed: int = 0
    top_k: int = 0              # 0 = off
    top_p: float = 1.0          # 1.0 = off
    out: List[int] = field(default_factory=list)
    last_token: Optional[int] = None


class ServingEngine:
    """``add_request`` → ``step`` until ``finished`` — or just
    ``generate(prompts, max_new_tokens)``.

    One decode ``step()`` advances EVERY active slot by one token; slots
    free and refill from the queue as requests finish (continuous
    batching).  Inactive slots point at the reserved scratch page and
    their outputs are ignored.
    """

    def __init__(self, model, params, max_batch: int = 8,
                 page_size: int = 128, num_pages: Optional[int] = None,
                 max_seq: int = 2048, dtype=jnp.bfloat16,
                 eos_token_id: Optional[int] = None, tp_size: int = 1,
                 ep_size: int = 1, decode_chunk: int = 1):
        self.model = model
        self.config = model.config
        self.max_batch = max_batch
        self.page_size = page_size
        self.max_pages_per_seq = -(-max_seq // page_size)
        if num_pages is None:
            num_pages = max_batch * self.max_pages_per_seq + 1
        self.mesh = None
        caches = model.init_paged_caches(num_pages, page_size, dtype=dtype)
        if ep_size > 1:
            assert getattr(self.config, "is_moe", False), \
                "ep_size > 1 needs an MoE model"
            assert self.config.moe_num_experts % ep_size == 0, \
                "ep_size must divide the expert count"
        if tp_size > 1 or ep_size > 1:
            # tensor/expert-parallel serving: weights per the model's
            # tp_rules (expert leaves carry the ep axis on their leading
            # [E, ...] dim — reference megatron_gpt_moe EP containers), KV
            # pages sharded over the kv-head dim ([L, P, Hkv, page, D])
            from jax.sharding import NamedSharding, PartitionSpec as P
            from deepspeed_tpu.parallel import groups
            from deepspeed_tpu.parallel.topology import TopologyConfig
            from deepspeed_tpu.runtime.zero.stage_plan import ZeroShardingPlan
            assert self.config.kv_heads % tp_size == 0, \
                "tp_size must divide the kv-head count for paged serving"
            groups.reset_mesh()
            self.mesh = groups.initialize_mesh(
                TopologyConfig(tp=tp_size, ep=ep_size, fsdp=-1))
            plan = ZeroShardingPlan(self.mesh, stage=0,
                                    tp_rules=model.tp_rules())
            with self.mesh:
                params = jax.device_put(
                    params, plan._to_sharding(plan.param_specs(params)))
                caches = jax.device_put(
                    caches, NamedSharding(self.mesh,
                                          P(None, None, "tp", None, None)))
        self.params = params
        self.caches = caches
        self.alloc = PagedAllocator(num_pages, page_size,
                                    self.max_pages_per_seq,
                                    reserve_scratch=True)
        self.eos = eos_token_id
        if not self.config.use_rope and not self.config.use_alibi:
            # learned positions: gathers past the table CLAMP under jit
            # (silent garbage), so bound the serve length up front
            assert max_seq <= self.config.max_seq_len, (
                f"max_seq {max_seq} exceeds the model's position table "
                f"({self.config.max_seq_len})")
        self.max_seq = max_seq

        self.slots: List[Optional[_Request]] = [None] * max_batch
        self.queue: List[_Request] = []
        self.finished: Dict[Any, List[int]] = {}
        self.lengths = np.zeros(max_batch, np.int32)
        # +1 overrun column, permanently the scratch page (page 0): when a
        # reservation fills the whole table (prompt + max_new == max_seq),
        # the final chunk's last write indexes one page past the
        # reservation — this column catches it ON SCRATCH by construction
        # instead of relying on OOB-gather clamping (which would overwrite
        # the request's own last real page)
        self.tables = np.zeros((max_batch, self.max_pages_per_seq + 1),
                               np.int32)
        # one jit serves prefill (B=1, bucketed T) and decode (B=max_batch,
        # T=1) alike: jax.jit caches a compilation per input shape
        self._step_fn = jax.jit(self.model.apply_with_paged_cache,
                                donate_argnums=(2,))
        self._rng = {}
        # multi-token decode: one device program advances every slot
        # ``decode_chunk`` tokens (sampling included) per host round-trip.
        # Through a tunneled chip the per-dispatch floor (~69 ms measured,
        # ONCHIP_r03/inference_latency.json) dominates single-token decode,
        # so chunking multiplies serving throughput by ~decode_chunk.
        self.decode_chunk = int(decode_chunk)
        assert self.decode_chunk >= 1
        self._chunk_fns = {}   # use_filters(bool) -> compiled chunk fn

    # -- host control flow ---------------------------------------------
    def add_request(self, req_id, prompt_ids, max_new_tokens: int = 32,
                    temperature: float = 0.0, seed: int = 0,
                    top_k: int = 0, top_p: float = 1.0):
        prompt = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        assert len(prompt) + max_new_tokens <= self.max_seq, \
            f"request {req_id} exceeds max_seq {self.max_seq}"
        total = len(prompt) + max_new_tokens
        bucket = min(self._bucket(len(prompt)), self.max_seq)
        need = -(-max(total, bucket) // self.page_size)
        usable = self.alloc.num_pages - 1   # minus the scratch page
        assert need <= usable, (
            f"request {req_id} needs {need} pages but the pool only has "
            f"{usable}; it would deadlock the queue head-of-line")
        assert req_id not in self.alloc.seq_pages and \
            req_id not in self.finished and \
            all(r.req_id != req_id for r in self.queue), \
            f"duplicate req_id {req_id!r}"
        assert 0.0 < top_p <= 1.0 and top_k >= 0, (top_k, top_p)
        self.queue.append(_Request(req_id, prompt, max_new_tokens,
                                   temperature, seed, top_k, top_p))
        self._admit()

    def _bucket(self, n: int) -> int:
        return 1 << max(3, math.ceil(math.log2(max(n, 1))))

    def _admit(self):
        for slot in range(self.max_batch):
            if not self.queue or self.slots[slot] is not None:
                continue
            req = self.queue[0]
            total = len(req.prompt) + req.max_new_tokens
            bucket = min(self._bucket(len(req.prompt)), self.max_seq)
            need_pages = -(-max(total, bucket) // self.page_size)
            if not self.alloc.can_allocate(need_pages):
                return          # head-of-line: keep FIFO order
            self.queue.pop(0)
            # full reservation (prompt + budget) at admission: an admitted
            # request can NEVER deadlock on pages mid-flight (no vLLM-style
            # preemption/recompute machinery needed); only bucket-padding
            # surplus is returned after prefill
            pages = self.alloc.allocate(req.req_id, max(total, bucket))
            self.tables[slot, :] = 0
            self.tables[slot, :len(pages)] = pages
            self.lengths[slot] = 0
            self.slots[slot] = req
            self._prefill(slot, req, bucket)
            if bucket > total:
                self.alloc.shrink(req.req_id, total)
                pages = self.alloc.seq_pages[req.req_id]
                self.tables[slot, :] = 0
                self.tables[slot, :len(pages)] = pages

    def _run_step(self, ids, tables, lengths):
        if self.mesh is not None:
            with self.mesh:
                return self._step_fn(self.params, ids, self.caches,
                                     tables, lengths)
        return self._step_fn(self.params, ids, self.caches, tables, lengths)

    def _prefill(self, slot: int, req: _Request, bucket: int):
        T = bucket
        ids = np.zeros((1, T), np.int32)
        ids[0, :len(req.prompt)] = req.prompt
        logits, self.caches, _ = self._run_step(
            jnp.asarray(ids),
            jnp.asarray(self.tables[slot:slot + 1]),
            jnp.zeros((1,), jnp.int32))
        self.lengths[slot] = len(req.prompt)
        req.last_token = self._sample(
            req, np.asarray(logits[0, len(req.prompt) - 1]))

    def _sample(self, req: _Request, logits: np.ndarray) -> int:
        if req.temperature <= 0.0:
            return int(np.argmax(logits))
        rng = self._rng.setdefault(req.req_id,
                                   np.random.default_rng(req.seed))
        l = logits.astype(np.float64) / req.temperature
        V = len(l)
        if req.top_k or req.top_p < 1.0:
            # rank-based filtering — EXACTLY cut tokens survive, stable
            # tie order, mirroring the device sampler's policy
            order = np.argsort(-l, kind="stable")
            ranks = np.empty(V, np.int64)
            ranks[order] = np.arange(V)
            k_eff = req.top_k if 0 < req.top_k < V else V
            l = np.where(ranks < k_eff, l, -np.inf)
            p = np.exp(l - l.max())
            p = p / p.sum()
            if req.top_p < 1.0:
                cs = np.cumsum(p[order])
                # smallest prefix whose mass reaches top_p
                cut = int(np.searchsorted(cs, req.top_p) + 1)
                p = np.where(ranks < cut, p, 0.0)
                p = p / p.sum()
        else:
            p = np.exp(l - l.max())
            p = p / p.sum()
        return int(rng.choice(V, p=p))

    def _finish(self, slot: int):
        req = self.slots[slot]
        self.finished[req.req_id] = req.prompt + req.out
        self.alloc.free_sequence(req.req_id)
        self._rng.pop(req.req_id, None)
        self.slots[slot] = None
        self.lengths[slot] = 0
        self.tables[slot, :] = 0
        self._admit()

    @property
    def n_active(self) -> int:
        return sum(s is not None for s in self.slots)

    # -- the chunked decode step (K tokens per dispatch) ----------------
    def _build_chunk_fn(self, use_filters: bool):
        K = self.decode_chunk
        model = self.model

        def chunk(params, caches, tables, lengths, last, temps, seeds,
                  gen_counts, top_ks, top_ps):
            """K decode iterations in one device program.  Emits the K
            sampled tokens per slot; the host truncates past EOS /
            max_new_tokens (overrun writes land on the reserved scratch
            page — admission reserved every page a live request can
            validly reach, vLLM-style multi-step scheduling).  Sampling
            keys on (request seed, tokens generated so far), so a
            request's random stream is independent of slot assignment
            and arrival order — the per-token engine's req.seed contract."""
            def one_sample(key, l, temp, top_k, top_p):
                """One slot's filtered sampler: temperature -> top-k ->
                top-p (nucleus) -> categorical.  Rank-based like the host
                sampler: a single stable descending argsort; exactly
                ``cut`` ranked tokens survive each stage (top_k=0 /
                top_p=1.0 gate their stage off explicitly)."""
                V = l.shape[-1]
                l = l / jnp.maximum(temp, 1e-6)
                order = jnp.argsort(-l, stable=True)
                ranks = jnp.zeros(V, jnp.int32).at[order].set(
                    jnp.arange(V, dtype=jnp.int32))
                k_eff = jnp.where((top_k > 0) & (top_k < V), top_k, V)
                l = jnp.where(ranks < k_eff, l, -1e30)
                p = jax.nn.softmax(l)
                cs = jnp.cumsum(p[order])
                # smallest prefix reaching top_p mass (searchsorted+1)
                cut = jnp.where(top_p < 1.0, jnp.sum(cs < top_p) + 1, V)
                l = jnp.where(ranks < cut, l, -1e30)
                return jax.random.categorical(key, l).astype(jnp.int32)

            def one(carry, t):
                caches, lengths, last = carry
                logits, caches, _ = model.apply_with_paged_cache(
                    params, last[:, None], caches, tables, lengths)
                lg = logits[:, 0]
                greedy = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                keys = jax.vmap(
                    lambda s, g: jax.random.fold_in(jax.random.key(s),
                                                    g + t))(seeds, gen_counts)
                if use_filters:
                    sampled = jax.vmap(one_sample)(keys, lg, temps,
                                                   top_ks, top_ps)
                else:   # plain temperature: no vocab sorts in the loop
                    sampled = jax.vmap(
                        lambda k, l, tt: jax.random.categorical(
                            k, l / jnp.maximum(tt, 1e-6)))(
                        keys, lg, temps).astype(jnp.int32)
                nxt = jnp.where(temps > 0, sampled, greedy)
                return (caches, lengths + 1, nxt), nxt

            (caches, lengths, last), toks = jax.lax.scan(
                one, (caches, lengths, last), jnp.arange(K))
            return toks.T, caches   # [B, K]

        return jax.jit(chunk, donate_argnums=(1,))

    def _step_chunk(self) -> Dict[Any, List[int]]:
        K = self.decode_chunk
        use_filters = any(r is not None and (r.top_k or r.top_p < 1.0)
                          for r in self.slots)
        if self._chunk_fns.get(use_filters) is None:
            self._chunk_fns[use_filters] = self._build_chunk_fn(use_filters)
        chunk_fn = self._chunk_fns[use_filters]
        last = np.zeros(self.max_batch, np.int32)
        temps = np.zeros(self.max_batch, np.float32)
        seeds = np.zeros(self.max_batch, np.uint32)
        gen_counts = np.zeros(self.max_batch, np.int32)
        top_ks = np.zeros(self.max_batch, np.int32)
        top_ps = np.ones(self.max_batch, np.float32)
        for slot, req in enumerate(self.slots):
            if req is not None:
                last[slot] = req.last_token
                temps[slot] = max(0.0, req.temperature)
                seeds[slot] = np.uint32(req.seed)
                gen_counts[slot] = len(req.out)
                top_ks[slot] = req.top_k
                top_ps[slot] = req.top_p
        args = (self.params, self.caches, jnp.asarray(self.tables),
                jnp.asarray(self.lengths), jnp.asarray(last),
                jnp.asarray(temps), jnp.asarray(seeds),
                jnp.asarray(gen_counts), jnp.asarray(top_ks),
                jnp.asarray(top_ps))
        if self.mesh is not None:
            with self.mesh:
                toks, self.caches = chunk_fn(*args)
        else:
            toks, self.caches = chunk_fn(*args)
        toks = np.asarray(toks)

        done_slots, done_now = [], {}
        for slot, req in enumerate(self.slots):
            if req is None:
                continue
            # tokens appended to the cache this chunk: the pre-chunk last
            # token, then the first K-1 samples; sample K-1 is the next
            # chunk's carry (per-token step() semantics, K times)
            seq = [req.last_token] + toks[slot, :-1].tolist()
            finished = False
            for tok in seq:
                req.out.append(int(tok))
                self.lengths[slot] += 1
                if (self.eos is not None and int(tok) == self.eos) or \
                        len(req.out) >= req.max_new_tokens:
                    finished = True
                    break
            if finished:
                done_slots.append(slot)
            else:
                req.last_token = int(toks[slot, -1])
        for slot in done_slots:
            rid = self.slots[slot].req_id
            self._finish(slot)
            done_now[rid] = self.finished.pop(rid)
        return done_now

    # -- the batched decode step ---------------------------------------
    def step(self) -> Dict[Any, List[int]]:
        """Advance every active request by one token (``decode_chunk``
        tokens when configured); returns ONLY the requests that finished
        during this step (req_id → full tokens)."""
        self._admit()
        if self.n_active == 0:
            return {}
        if self.decode_chunk > 1:
            return self._step_chunk()
        last = np.zeros((self.max_batch, 1), np.int32)
        for slot, req in enumerate(self.slots):
            if req is not None:
                last[slot, 0] = req.last_token
        logits, self.caches, _ = self._run_step(
            jnp.asarray(last), jnp.asarray(self.tables),
            jnp.asarray(self.lengths))
        logits_np = np.asarray(logits[:, 0])

        # finishing frees slots, which admits (and PREFILLS) queued
        # requests — defer that until after the loop so a mid-loop
        # admission is never mistaken for a slot this decode step served
        done_slots = []
        done_now = {}
        for slot, req in enumerate(self.slots):
            if req is None:
                continue
            # the token we just fed is now part of the sequence
            req.out.append(req.last_token)
            self.lengths[slot] += 1
            ended = (self.eos is not None and req.last_token == self.eos)
            if ended or len(req.out) >= req.max_new_tokens:
                done_slots.append(slot)
            else:
                req.last_token = self._sample(req, logits_np[slot])
        for slot in done_slots:
            rid = self.slots[slot].req_id
            self._finish(slot)
            # hand the result back ONCE and evict: a long-running server
            # must not accumulate every finished token list forever
            done_now[rid] = self.finished.pop(rid)
        return done_now

    # -- convenience ----------------------------------------------------
    def generate(self, prompts, max_new_tokens: int = 32,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0) -> List[List[int]]:
        """Serve a list of prompts (continuous batching when
        len(prompts) > max_batch); returns full token lists in order."""
        for i, p in enumerate(prompts):
            self.add_request(i, p, max_new_tokens, temperature,
                            top_k=top_k, top_p=top_p)
        steps = 0
        results: Dict[Any, List[int]] = {}
        limit = (max(len(p) for p in prompts) + max_new_tokens + 4) * \
            (len(prompts) + 1)
        while (self.queue or self.n_active) and steps < limit:
            results.update(self.step())
            steps += 1
        assert not self.queue and self.n_active == 0, "serving stalled"
        return [results[i] for i in range(len(prompts))]
