"""Environment/compatibility report — the ``ds_report`` CLI.

Parity: reference ``deepspeed/env_report.py`` (op compat matrix + version
report).  TPU flavor: reports jax/jaxlib/libtpu versions, the device
inventory (platform, chip kind, HBM), and per-op compatibility from the
op-builder registry.
"""

import sys

GREEN = "\033[92m"
RED = "\033[91m"
YELLOW = "\033[93m"
END = "\033[0m"
OKAY = f"{GREEN}[OKAY]{END}"
WARNING = f"{YELLOW}[WARNING]{END}"
NO = f"{RED}[NO]{END}"


def op_report(verbose=False):
    from deepspeed_tpu.ops.op_builder import ALL_OPS
    max_dots = 23
    print("-" * 72)
    print("DeepSpeed-TPU C++/Pallas op report")
    print("-" * 72)
    print("op name" + "." * (max_dots - len("op name")) + "compatible")
    print("-" * 72)
    rows = []
    for name, builder in sorted(ALL_OPS.items()):
        compatible = builder.is_compatible(verbose=verbose)
        status = OKAY if compatible else NO
        print(name + "." * (max_dots - len(name)) + status)
        rows.append((name, compatible))
    return rows


def debug_report():
    import jax
    import jaxlib

    print("-" * 72)
    print("DeepSpeed-TPU general environment info:")
    print("-" * 72)
    rows = [
        ("python version", sys.version.replace("\n", " ")),
        ("jax version", jax.__version__),
        ("jaxlib version", jaxlib.__version__),
    ]
    try:
        rows += [("default backend", jax.default_backend()),
                 ("process count", jax.process_count())]
    except RuntimeError as e:
        rows.append(("backend init failed", str(e).split("\n")[0]))
    try:
        import deepspeed_tpu
        rows.append(("deepspeed_tpu version", deepspeed_tpu.__version__))
    except Exception:
        pass
    try:
        devs = jax.devices()
        rows.append(("device count", len(devs)))
        if devs:
            d = devs[0]
            rows.append(("device kind", getattr(d, "device_kind", "?")))
            stats = {}
            try:
                stats = d.memory_stats() or {}
            except Exception:
                pass
            if "bytes_limit" in stats:
                rows.append(("HBM per device",
                             f"{stats['bytes_limit'] / 2**30:.1f} GiB"))
    except Exception as e:  # pragma: no cover
        rows.append(("device query failed", str(e)))
    for k, v in rows:
        print(f"{k} {'.' * max(1, 40 - len(k))} {v}")
    return rows


def main(verbose=False, kernel_gate=False):
    op_report(verbose=verbose)
    debug_report()
    if kernel_gate:
        # lower+compile every Pallas kernel variant against the current
        # backend (reference: is_compatible probes surfaced by ds_report;
        # our risk is Mosaic lowering, which interpret-mode can't see)
        import subprocess
        print("\nkernel compile-gate (Mosaic):")
        return subprocess.call(
            [sys.executable, "-m", "deepspeed_tpu.ops.kernel_gate"])
    return 0


def cli_main():  # console entry point
    kernel_gate = "--kernel-gate" in sys.argv
    verbose = "-v" in sys.argv or "--verbose" in sys.argv
    sys.exit(main(verbose=verbose, kernel_gate=kernel_gate))


if __name__ == "__main__":
    cli_main()
