"""Host-side (CPU) Adam for offloaded optimizer state.

Parity: reference ``csrc/adam/cpu_adam.cpp`` (AVX256/512 + OpenMP
``adam_update``, the ZeRO-Offload optimizer) and ``csrc/adagrad/cpu_adagrad.cpp``.

TPU design: optimizer state lives in host RAM (numpy), gradients stream
device→host, the update runs on the TPU-VM host cores, and updated params
stream back.  The hot loop is C++ (OpenMP + auto-vectorised; built lazily via
``ops/native.py``) with a numpy fallback — numpy's vectorised ops already use
SIMD, the C++ path mainly wins by fusing the five passes into one.
"""

import ctypes
import os
from typing import NamedTuple

import numpy as np

from deepspeed_tpu.utils.logging import logger

_lib = None
_lib_tried = False

_CPP_SRC = os.path.join(os.path.dirname(__file__), "csrc", "cpu_adam.cpp")


def _load_native():
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    try:
        from deepspeed_tpu.ops.native import load_extension
        lib = load_extension("cpu_adam", [_CPP_SRC])
        lib.adam_update.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_long, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_int]
        _lib = lib
    except Exception as e:
        logger.warning(f"cpu_adam native build unavailable, numpy fallback: {e}")
        _lib = None
    return _lib


class CPUAdamState(NamedTuple):
    m: np.ndarray
    v: np.ndarray
    step: int


def init_state(numel) -> CPUAdamState:
    return CPUAdamState(m=np.zeros(numel, np.float32),
                        v=np.zeros(numel, np.float32), step=0)


def adam_update(params: np.ndarray, grads: np.ndarray, state: CPUAdamState,
                lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8, weight_decay=0.0,
                adamw_mode=True, bias_correction=True) -> CPUAdamState:
    """In-place fused AdamW on host fp32 buffers.  Mirrors
    ``cpu_adam.cpp Adam_Optimizer::Step`` semantics."""
    assert params.dtype == np.float32 and grads.dtype == np.float32
    step = state.step + 1
    lib = _load_native()
    if lib is not None:
        bc1 = 1.0 - beta1 ** step if bias_correction else 1.0
        bc2 = 1.0 - beta2 ** step if bias_correction else 1.0
        fp = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))  # noqa: E731
        lib.adam_update(fp(params), fp(grads), fp(state.m), fp(state.v),
                        ctypes.c_long(params.size), ctypes.c_float(lr),
                        ctypes.c_float(beta1), ctypes.c_float(beta2),
                        ctypes.c_float(eps), ctypes.c_float(weight_decay),
                        ctypes.c_float(bc1), ctypes.c_float(bc2),
                        ctypes.c_int(1 if adamw_mode else 0))
        return CPUAdamState(m=state.m, v=state.v, step=step)

    # numpy fallback
    g = grads
    if not adamw_mode and weight_decay:
        g = g + weight_decay * params
    m, v = state.m, state.v
    np.multiply(m, beta1, out=m)
    m += (1.0 - beta1) * g
    np.multiply(v, beta2, out=v)
    v += (1.0 - beta2) * np.square(g)
    if bias_correction:
        m_hat = m / (1.0 - beta1 ** step)
        v_hat = v / (1.0 - beta2 ** step)
    else:
        m_hat, v_hat = m, v
    update = m_hat / (np.sqrt(v_hat) + eps)
    if adamw_mode and weight_decay:
        update += weight_decay * params
    params -= lr * update
    return CPUAdamState(m=state.m, v=state.v, step=step)


def adagrad_update(params, grads, sq_accum, lr=1e-2, eps=1e-10,
                   weight_decay=0.0):
    """Host Adagrad (reference cpu_adagrad.cpp)."""
    g = grads + weight_decay * params if weight_decay else grads
    sq_accum += np.square(g)
    params -= lr * g / (np.sqrt(sq_accum) + eps)
    return sq_accum


reference_impl = adam_update
