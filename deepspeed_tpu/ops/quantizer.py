"""Groupwise quantization ops.

Parity: reference ``csrc/quantization/`` (``ds_quantize_fp16/32``,
``ds_sr_quantize(_asym)_*`` — groupwise symmetric/asymmetric int8/int4
quantize/dequantize with optional stochastic rounding, used by MoQ and
inference).  jnp implementation (XLA fuses it); a Pallas variant for the
inference weight-dequant hot path can slot in via the same API.
"""

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


class QuantizedTensor(NamedTuple):
    values: jnp.ndarray   # int8 codes; asymmetric codes are offset by
    #                       -2^(bits-1) so the [0, 2^bits-1] range fits int8
    scale: jnp.ndarray    # fp32 per group
    zero_point: jnp.ndarray  # fp32 per group (0 for symmetric)
    num_bits: int
    group_shape: Tuple[int, ...]
    symmetric: bool = True


def _grouped(x, groups):
    n = x.size
    assert n % groups == 0, f"size {n} not divisible into {groups} groups"
    return x.reshape(groups, n // groups)


def quantize(x, groups=1, num_bits=8, symmetric=True, stochastic=False,
             rng=None):
    """Groupwise quantize; returns QuantizedTensor.

    symmetric: scale = max|x| / qmax, zero_point 0 (``ds_quantize``)
    asymmetric: scale = (max-min)/(2^bits-1), zero = min (``_asym`` variants)
    stochastic: stochastic rounding (``ds_sr_quantize``)
    """
    if num_bits > 8:
        raise ValueError(
            f"num_bits={num_bits}: int8 storage holds at most 8 bits; a "
            "wider cast would silently wrap")
    orig_shape = x.shape
    g = _grouped(x.astype(jnp.float32), groups)
    qmax = 2.0 ** (num_bits - 1) - 1
    if symmetric:
        scale = jnp.max(jnp.abs(g), axis=1, keepdims=True) / qmax
        scale = jnp.where(scale == 0, 1.0, scale)
        zero = jnp.zeros_like(scale)
        q = g / scale
        lo, hi = -qmax - 1, qmax
    else:
        mn = jnp.min(g, axis=1, keepdims=True)
        mx = jnp.max(g, axis=1, keepdims=True)
        scale = (mx - mn) / (2.0 ** num_bits - 1)
        scale = jnp.where(scale == 0, 1.0, scale)
        zero = mn
        q = (g - zero) / scale
        lo, hi = 0, 2 ** num_bits - 1
    if stochastic:
        if rng is None:
            rng = jax.random.key(0)
        noise = jax.random.uniform(rng, q.shape) - 0.5
        q = jnp.floor(q + 0.5 + noise)
    else:
        q = jnp.round(q)
    q = jnp.clip(q, lo, hi)
    if not symmetric:
        q = q - 2.0 ** (num_bits - 1)  # recentre into signed int8 range
    q = q.astype(jnp.int8)
    return QuantizedTensor(values=q.reshape(orig_shape),
                           scale=scale[:, 0], zero_point=zero[:, 0],
                           num_bits=num_bits, group_shape=orig_shape,
                           symmetric=symmetric)


def dequantize(qt: QuantizedTensor, dtype=jnp.float32):
    groups = qt.scale.shape[0]
    g = _grouped(qt.values.astype(jnp.float32), groups)
    if not qt.symmetric:
        g = g + 2.0 ** (qt.num_bits - 1)
    out = g * qt.scale[:, None] + qt.zero_point[:, None]
    return out.reshape(qt.group_shape).astype(dtype)


def fake_quantize(x, groups=1, num_bits=8, symmetric=True, stochastic=False,
                  rng=None):
    """quantize→dequantize in one go (reference ``fake_quantizer.cu``, the
    MoQ training path; straight-through estimator applied by caller)."""
    return dequantize(quantize(x, groups, num_bits, symmetric, stochastic, rng),
                      dtype=x.dtype)


reference_impl = fake_quantize

# parity aliases (reference pt_binding.cpp exported names)
ds_quantize = quantize
ds_dequantize = dequantize
ds_sr_quantize = lambda x, groups=1, num_bits=8, rng=None: quantize(  # noqa: E731
    x, groups, num_bits, symmetric=True, stochastic=True, rng=rng)
