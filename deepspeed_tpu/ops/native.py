"""Native (C/C++) extension build support.

Parity role: reference ``op_builder/builder.py`` JIT-compile path (torch
cpp_extension + ninja).  Here: a tiny g++ shared-object builder + ctypes
loader used by the host-side ops (cpu_adam SIMD, async NVMe I/O).  Built
lazily on first use, cached under ``~/.cache/deepspeed_tpu``.
"""

import ctypes
import hashlib
import os
import subprocess

from deepspeed_tpu.utils.logging import logger

CACHE_DIR = os.path.expanduser(os.environ.get(
    "DSTPU_CACHE_DIR", "~/.cache/deepspeed_tpu"))


def build_extension(name, sources, extra_cflags=None, extra_ldflags=None,
                    verbose=False):
    """Compile ``sources`` (C++ files) into a cached .so; returns the path."""
    os.makedirs(CACHE_DIR, exist_ok=True)
    src_blob = "".join(open(s).read() for s in sources)
    tag = hashlib.sha1(
        (src_blob + str(extra_cflags) + str(extra_ldflags)).encode()
    ).hexdigest()[:12]
    so_path = os.path.join(CACHE_DIR, f"{name}-{tag}.so")
    if os.path.exists(so_path):
        return so_path
    cflags = ["-O3", "-shared", "-fPIC", "-std=c++17", "-fopenmp",
              "-march=native"] + (extra_cflags or [])
    # build to a tmp path and rename so concurrent builders (pytest-xdist,
    # multi-process launch) never load a half-written .so
    tmp_path = f"{so_path}.tmp.{os.getpid()}"
    cmd = ["g++"] + cflags + list(sources) + ["-o", tmp_path] + (extra_ldflags or [])
    if verbose:
        logger.info(" ".join(cmd))
    try:
        subprocess.check_call(cmd)
        os.replace(tmp_path, so_path)
    finally:
        if os.path.exists(tmp_path):
            os.unlink(tmp_path)
    return so_path


def load_extension(name, sources, **kwargs):
    so_path = build_extension(name, sources, **kwargs)
    return ctypes.CDLL(so_path)
