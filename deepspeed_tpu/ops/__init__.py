from deepspeed_tpu.ops import op_builder  # noqa: F401
