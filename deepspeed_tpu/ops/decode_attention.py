"""Decode (inference) attention with KV cache.

Parity: reference ``csrc/transformer/inference`` ``softmax_context_fp16`` —
the fused attention-with-KV-cache kernel behind ``DeepSpeedTransformerInference``.

TPU design: the cache is a static-shape ring buffer [B, Hkv, max_seq, D] —
seq on sublanes, head_dim on lanes, the layout Mosaic tiles natively —
updated with ``lax.dynamic_update_slice`` (static shapes keep XLA happy in a
decode loop); attention masks positions ≥ cur_len.  Two compute paths
behind one API: the Pallas online-softmax kernel
(``ops/pallas/decode_attention.py`` — never fetches cache blocks past the
valid length, never materialises [S] logits in HBM) on TPU, and this
module's jnp path as the oracle/fallback.
"""

import math
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


DEFAULT_BLOCK_K = 256


def use_pallas(impl, seq_len=None, block_k=DEFAULT_BLOCK_K):
    """Shared impl-dispatch policy for decode/paged attention.

    ``impl``: "jnp" | "pallas" | None (auto: Pallas on TPU when the cache
    tiles).  ``seq_len=None`` skips the divisibility check (paged caches
    always tile by page)."""
    if impl == "jnp":
        return False
    if impl == "pallas":
        return True
    assert impl is None, f"unknown impl {impl!r}; expected jnp/pallas/None"
    if jax.default_backend() != "tpu":
        return False
    return seq_len is None or seq_len % min(block_k, seq_len) == 0


class KVCache(NamedTuple):
    k: jnp.ndarray  # [B, Hkv, S_max, D]
    v: jnp.ndarray  # [B, Hkv, S_max, D]
    length: jnp.ndarray  # i32 scalar: valid prefix length


def init_cache(batch, max_seq, n_kv_heads, head_dim, dtype=jnp.bfloat16):
    shape = (batch, n_kv_heads, max_seq, head_dim)
    return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype),
                   length=jnp.zeros((), jnp.int32))


def update_cache(cache: KVCache, k_new, v_new) -> KVCache:
    """Append [B, T, Hkv, D] (model layout) at position cache.length —
    only the new tokens are transposed into the cache layout."""
    start = cache.length
    k_new = jnp.swapaxes(k_new, 1, 2)      # -> [B, Hkv, T, D]
    v_new = jnp.swapaxes(v_new, 1, 2)
    k = jax.lax.dynamic_update_slice(cache.k, k_new.astype(cache.k.dtype),
                                     (0, 0, start, 0))
    v = jax.lax.dynamic_update_slice(cache.v, v_new.astype(cache.v.dtype),
                                     (0, 0, start, 0))
    return KVCache(k=k, v=v, length=start + k_new.shape[2])


def decode_attention(q, cache: KVCache, softmax_scale=None, impl=None,
                     block_k=DEFAULT_BLOCK_K, interpret=False, bias=None,
                     logit_softcap=None):
    """q: [B, T, H, D] (T=1 decode or T=prompt prefill, already appended to
    cache); attends over cache[:length].  fp32 softmax.

    ``impl``: None (auto: Pallas kernel on TPU, jnp elsewhere), "pallas",
    or "jnp".  ``bias``: additive logit bias broadcastable to [B, H, T, S]
    (ALiBi / local-window models); forces the jnp path."""
    B, T, H, D = q.shape
    if bias is None and not logit_softcap and \
            use_pallas(impl, cache.k.shape[2], block_k):
        from deepspeed_tpu.ops.pallas.decode_attention import \
            decode_attention_pallas
        lengths = jnp.broadcast_to(jnp.asarray(cache.length, jnp.int32), (B,))
        return decode_attention_pallas(q, cache.k, cache.v, lengths,
                                       softmax_scale=softmax_scale,
                                       block_k=block_k,
                                       interpret=interpret)
    Hkv = cache.k.shape[1]
    k, v = cache.k, cache.v
    if Hkv != H:
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    scale = softmax_scale if softmax_scale is not None else 1.0 / math.sqrt(D)
    logits = jnp.einsum("bqhd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if logit_softcap:
        logits = logit_softcap * jnp.tanh(logits / logit_softcap)
    S = cache.k.shape[2]
    kpos = jnp.arange(S)[None, :]
    qpos = cache.length - T + jnp.arange(T)[:, None]
    if bias is not None:
        logits = logits + bias
    mask = kpos <= qpos  # causal within the valid prefix
    logits = jnp.where(mask[None, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bqhd", probs.astype(v.dtype), v)
    return out.astype(q.dtype)


softmax_context = decode_attention  # parity alias
reference_impl = decode_attention
