"""Mosaic compile-gate: lower + compile EVERY Pallas kernel variant.

Parity: reference ``op_builder/builder.py:112`` (``is_compatible`` probes an
op before use, surfaced by ds_report).  Our equivalent risk is Mosaic
lowering failures on the real TPU backend — interpret-mode green does NOT
imply Mosaic green (round-3 caught ALiBi/window variants only because a
journaled run happened to execute them).  This gate is compile-only (no
numerics, minutes not hours) and journals one JSON line per variant:

    python -m deepspeed_tpu.ops.kernel_gate                # default backend
    python -m deepspeed_tpu.ops.kernel_gate --json-out gate.json
    ds_report --kernel-gate                                # same, via CLI

Run it FIRST in every on-chip program.
"""

import argparse
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np


def _gate(name, fn, *args):
    t0 = time.time()
    try:
        jax.jit(fn).lower(*args).compile()
        out = {"variant": name, "ok": True,
               "wall_s": round(time.time() - t0, 1)}
    except Exception as e:   # noqa: BLE001 — journal every failure mode
        out = {"variant": name, "ok": False, "error": str(e)[-600:],
               "wall_s": round(time.time() - t0, 1)}
    print(json.dumps(out), flush=True)
    return out


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--json-out", default=None)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--interpret", action="store_true",
                    help="Pallas interpreter instead of Mosaic (CPU smoke "
                         "test of the gate's plumbing only — interpret "
                         "green does NOT imply Mosaic green)")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args(argv)
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    interp = bool(args.interpret)

    from deepspeed_tpu.models.transformer import alibi_slopes
    from deepspeed_tpu.ops.pallas.decode_attention import (
        decode_attention_pallas, paged_attention_pallas)
    from deepspeed_tpu.ops.pallas.flash_attention import flash_attention
    from deepspeed_tpu.ops.pallas.fused_adam import fused_adam_pallas
    from deepspeed_tpu.ops.pallas.sparse_attention import \
        sparse_attention_pallas

    B, S, H, D = 2, args.seq, 8, 64
    rng = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(jax.random.fold_in(rng, i), (B, S, H, D),
                                 jnp.bfloat16) for i in range(3))
    kg, vg = (jax.random.normal(jax.random.fold_in(rng, i), (B, S, 2, D),
                                jnp.bfloat16) for i in range(3, 5))
    slopes = alibi_slopes(H)
    rows = []

    def flash_fwd(name, **kw):
        rows.append(_gate(
            f"flash_fwd_{name}",
            lambda q, k, v: flash_attention(q, k, v, interpret=interp, **kw),
            q, k, v))

    def flash_bwd(name, kk=k, vv=v, **kw):
        def f(q, k, v):
            return flash_attention(q, k, v, interpret=interp,
                                   **kw).astype(jnp.float32).sum()
        rows.append(_gate(f"flash_bwd_{name}",
                          jax.value_and_grad(f, argnums=(0, 1, 2)),
                          q, kk, vv))

    flash_fwd("causal", causal=True)
    flash_fwd("full", causal=False)
    flash_fwd("alibi", causal=True, alibi_slopes=slopes)
    flash_fwd("window", causal=True, window=256)
    flash_fwd("alibi_window", causal=True, alibi_slopes=slopes, window=256)
    rows.append(_gate("flash_fwd_gqa",
                      lambda q, k, v: flash_attention(q, k, v, causal=True,
                                                      interpret=interp),
                      q, kg, vg))
    flash_bwd("causal", causal=True)
    flash_bwd("alibi", causal=True, alibi_slopes=slopes)
    flash_bwd("window", causal=True, window=256)
    flash_bwd("gqa", kk=kg, vv=vg, causal=True)

    # decode: contiguous + paged caches (serving path)
    qd = jax.random.normal(rng, (B, 1, H, D), jnp.bfloat16)
    kc = jax.random.normal(rng, (B, 2, S, D), jnp.bfloat16)
    lengths = jnp.full((B,), S // 2, jnp.int32)
    rows.append(_gate("decode_contiguous",
                      lambda q, k, v, ln: decode_attention_pallas(
                          q, k, v, ln, interpret=interp),
                      qd, kc, kc, lengths))
    page, npages = 128, S // 128
    kp = jax.random.normal(rng, (npages * B, 2, page, D), jnp.bfloat16)
    tables = jnp.arange(B * npages, dtype=jnp.int32).reshape(B, npages)
    rows.append(_gate("decode_paged",
                      lambda q, kp, vp, t, ln: paged_attention_pallas(
                          q, kp, vp, t, ln, interpret=interp),
                      qd, kp, kp, tables, lengths))

    # fused ragged paged attention (one kernel, mixed prefill+decode):
    # gate pure-decode, pure-prefill, and mixed ragged shapes over the
    # same page pool — q_lens is host metadata, so it closes over the fn
    from deepspeed_tpu.ops.pallas.ragged_paged_attention import \
        ragged_paged_attention

    def ragged(name, q_lens, ctx_lens):
        qr = jax.random.normal(rng, (sum(q_lens), H, D), jnp.bfloat16)
        ctx = jnp.asarray(ctx_lens, jnp.int32)
        rows.append(_gate(
            f"ragged_{name}",
            lambda q, kp, vp, t, c: ragged_paged_attention(
                q, kp, vp, t, c, q_lens, interpret=interp),
            qr, kp, kp, tables, ctx))

    ragged("decode", [1] * B, [S // 2] * B)
    ragged("prefill", [256] * B, [256] * B)
    ragged("mixed", [256, 1], [256, S // 2])

    # sparse attention (fixed local+global layout)
    block, nb = 128, S // 128
    layout = np.zeros((H, nb, nb), np.int64)
    for i in range(nb):
        layout[:, i, max(0, i - 2):i + 1] = 1
        layout[:, i, 0] = 1
    rows.append(_gate("sparse_fixed",
                      lambda q, k, v: sparse_attention_pallas(
                          q, k, v, layout, block, causal=True,
                          interpret=interp),
                      q, k, v))

    # fused Adam (flat update kernel)
    from deepspeed_tpu.ops.adam import AdamState
    n = 1 << 20
    p = jnp.zeros((n,), jnp.float32)
    st = AdamState(m=jnp.zeros((n,), jnp.float32),
                   v=jnp.zeros((n,), jnp.float32),
                   step=jnp.asarray(0, jnp.int32))
    rows.append(_gate("fused_adam",
                      lambda p, g, st: fused_adam_pallas(
                          p, g, st, interpret=interp),
                      p, p, st))

    summary = {"all_ok": all(r["ok"] for r in rows),
               "n_variants": len(rows),
               "failed": [r["variant"] for r in rows if not r["ok"]],
               "backend": jax.devices()[0].platform,
               "device_kind": getattr(jax.devices()[0], "device_kind", "")}
    print(json.dumps(summary))
    if args.json_out:
        out_dir = os.path.dirname(os.path.abspath(args.json_out))
        os.makedirs(out_dir, exist_ok=True)
        with open(args.json_out, "w") as f:
            json.dump({"rows": rows, "summary": summary}, f, indent=1)
    return 0 if summary["all_ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
