"""Op-builder registry.

Parity: reference ``op_builder/builder.py:112`` (``OpBuilder``/``CUDAOpBuilder``
— per-op subclass with NAME, compat probe, JIT/AOT compile) and
``op_builder/all_ops.py`` (reflection into ``ALL_OPS``).

TPU design: "building" a Pallas op is tracing+compiling it through XLA, so an
OpBuilder here is a *capability probe + loader*: ``is_compatible()`` checks
the backend supports the kernel (TPU generation, dtype support, or — for
native host ops — a compiled C extension), and ``load()`` returns the op
module.  Every Pallas op ships a jnp reference implementation used as the
fallback (and as the test oracle), selected automatically when Pallas is not
available (e.g. CPU CI).
"""

import importlib

from deepspeed_tpu.utils.logging import logger


class OpBuilder:
    BUILD_VAR = "DSTPU_BUILD_OPS"
    NAME = "op"
    MODULE = None  # python module path providing the op

    def __init__(self):
        self.error_log = None

    def is_compatible(self, verbose=True):
        try:
            self.load()
            return True
        except Exception as e:  # pragma: no cover
            self.error_log = str(e)
            if verbose:
                logger.warning(f"op {self.NAME} incompatible: {e}")
            return False

    def load(self, verbose=True):
        assert self.MODULE, f"{self.NAME} has no module"
        return importlib.import_module(self.MODULE)

    def builder(self):
        return self

    @staticmethod
    def pallas_supported():
        try:
            import jax
            return jax.default_backend() in ("tpu", "axon")
        except Exception:
            return False


class PallasOpBuilder(OpBuilder):
    """Ops with a Pallas fast path and a jnp fallback."""

    def jnp_fallback(self):
        mod = self.load()
        return getattr(mod, "reference_impl", None)


class FusedAdamBuilder(PallasOpBuilder):
    NAME = "fused_adam"
    MODULE = "deepspeed_tpu.ops.adam"


class FusedLambBuilder(PallasOpBuilder):
    NAME = "fused_lamb"
    MODULE = "deepspeed_tpu.ops.lamb"


class CPUAdamBuilder(OpBuilder):
    NAME = "cpu_adam"
    MODULE = "deepspeed_tpu.ops.cpu_adam"


class CPUAdagradBuilder(OpBuilder):
    NAME = "cpu_adagrad"
    MODULE = "deepspeed_tpu.ops.cpu_adam"


class TransformerBuilder(PallasOpBuilder):
    NAME = "transformer"
    MODULE = "deepspeed_tpu.ops.attention"


class InferenceBuilder(PallasOpBuilder):
    NAME = "transformer_inference"
    MODULE = "deepspeed_tpu.ops.decode_attention"


class QuantizerBuilder(PallasOpBuilder):
    NAME = "quantizer"
    MODULE = "deepspeed_tpu.ops.quantizer"


class SparseAttnBuilder(PallasOpBuilder):
    NAME = "sparse_attn"
    MODULE = "deepspeed_tpu.ops.attention"


class RandomLTDBuilder(OpBuilder):
    NAME = "random_ltd"
    MODULE = "deepspeed_tpu.ops.random_ltd"


class AsyncIOBuilder(OpBuilder):
    NAME = "async_io"
    MODULE = "deepspeed_tpu.ops.aio"


class UtilsBuilder(OpBuilder):
    NAME = "utils"
    MODULE = "deepspeed_tpu.ops.flatten"


ALL_OPS = {
    b.NAME: b for b in [
        FusedAdamBuilder(), FusedLambBuilder(), CPUAdamBuilder(),
        CPUAdagradBuilder(), TransformerBuilder(), InferenceBuilder(),
        QuantizerBuilder(), SparseAttnBuilder(), RandomLTDBuilder(),
        AsyncIOBuilder(), UtilsBuilder(),
    ]
}
