"""Ring attention over the ``sp`` axis — exact blockwise attention for
sequences too long for any single device.

The reference has no context parallelism (SURVEY §2.4); its long-sequence
story was block-*sparse* attention.  This implements the exact alternative
(Ring Attention with blockwise online softmax): each device keeps its local
Q block resident and K/V blocks rotate around the ``sp`` ring via
``ppermute``; partial results merge with the flash-attention log-sum-exp
recurrence.  XLA overlaps each hop's transfer with the current block's
compute.

Memory: the forward materialises only [S/sp, S/sp] scores per step, and the
backward is a **custom VJP** that re-rotates K/V and recomputes each block
from the saved log-sum-exp — per-device residuals stay O(S/sp), never the
full sequence.  K/V stay at their GQA head count through the ring (the query
group dim is folded into the block einsums), so ppermute traffic is Hkv-sized.

Causal FLOPs: fully-masked future blocks (kv past the device's own
sequence position) are skipped with a per-device ``lax.cond`` — the ring
still rotates every hop (collectives stay outside the branch) but only
n(n+1)/2 of the n^2 block products are computed.  With the default
contiguous layout this is a FLOPs/energy saving, NOT wall-clock: the
lockstep ppermute after each hop synchronizes the ring, and the last
device computes a full block on every hop while earlier devices idle.
``layout="zigzag"`` converts it into step time: tokens are permuted so
device d owns chunks (d, 2n-1-d) — every device holds early AND late
positions, each (device, hop) computes ~2 of its 4 chunk sub-blocks, and
the causal triangle is balanced across the ring (~2x at large sp).
"""

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.ops._shard_map import axis_size, shard_map
from deepspeed_tpu.parallel.topology import BATCH_AXES, SP_AXIS
from deepspeed_tpu.runtime.zero.stage_plan import active_mesh

_NEG = -1e30


def _rotate(x, axis_name, n):
    return jax.lax.ppermute(x, axis_name, [(j, (j + 1) % n) for j in range(n)])


def _block_scores(q5, k, scale, mask):
    """q5: [B, Sq, Hkv, G, D]; k: [B, Sk, Hkv, D] → scores [B, Hkv, G, Sq, Sk]
    in fp32 (GQA group folded into the einsum — K stays at Hkv heads)."""
    s = jnp.einsum("bqhgd,bkhd->bhgqk", q5, k).astype(jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask[None, None, None], s, _NEG)
    return s


def _causal_mask(my_idx, kv_idx, S):
    pos = jnp.arange(S)
    qpos = my_idx * S + pos[:, None]
    kpos = kv_idx * S + pos[None, :]
    return qpos >= kpos


def _ring_fwd_local(q, k, v, axis_name, causal, scale):
    """Returns (out [B,S,H,D], lse [B,Hkv,G,S])."""
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    G = H // Hkv
    q5 = q.reshape(B, S, Hkv, G, D)
    n = axis_size(axis_name)
    # only the causal mask/skip needs this device's ring position; the
    # non-causal path must not touch axis_index (it lowers to PartitionId,
    # which the SPMD partitioner rejects even when the value is dead)
    my_idx = jax.lax.axis_index(axis_name) if causal else 0

    o0 = jnp.zeros((B, S, Hkv, G, D), jnp.float32)
    m0 = jnp.full((B, Hkv, G, S), _NEG, jnp.float32)
    l0 = jnp.zeros((B, Hkv, G, S), jnp.float32)

    def body(i, carry):
        o, m, l, k_cur, v_cur = carry
        kv_idx = (my_idx - i) % n

        def compute(acc):
            o, m, l = acc
            mask = _causal_mask(my_idx, kv_idx, S) if causal else None
            s = _block_scores(q5, k_cur, scale, mask)  # [B,Hkv,G,Sq,Sk]
            bm = jnp.max(s, axis=-1)
            new_m = jnp.maximum(m, bm)
            p = jnp.exp(s - new_m[..., None])
            p = jnp.where(new_m[..., None] <= _NEG / 2, 0.0, p)
            corr = jnp.exp(m - new_m)
            corr = jnp.where(m <= _NEG / 2, 0.0, corr)
            l2 = l * corr + jnp.sum(p, axis=-1)
            bo = jnp.einsum("bhgqk,bkhd->bqhgd", p.astype(v_cur.dtype),
                            v_cur).astype(jnp.float32)
            corr_o = jnp.moveaxis(corr, 3, 1)[..., None]  # [B,Sq,Hkv,G,1]
            return o * corr_o + bo, new_m, l2

        if causal:
            # future blocks (kv_idx > my_idx) are fully masked: their
            # contribution is exactly zero, so SKIP the compute entirely —
            # per-device lax.cond inside shard_map; the ring ppermutes stay
            # outside so every device still participates in every hop.
            # n(n+1)/2 of n^2 blocks computed — a FLOPs/energy saving;
            # wall-clock needs zig-zag placement (module docstring).
            o, m, l = jax.lax.cond(kv_idx <= my_idx, compute,
                                   lambda acc: acc, (o, m, l))
        else:
            o, m, l = compute((o, m, l))
        return o, m, l, _rotate(k_cur, axis_name, n), \
            _rotate(v_cur, axis_name, n)

    o, m, l, _, _ = jax.lax.fori_loop(0, n, body, (o0, m0, l0, k, v))
    l_safe = jnp.maximum(l, 1e-30)
    out = o / jnp.moveaxis(l_safe, 3, 1)[..., None]
    lse = m + jnp.log(l_safe)
    return out.reshape(B, S, H, D).astype(q.dtype), lse


def _ring_bwd_local(q, k, v, out, lse, g, axis_name, causal, scale):
    """Recompute-with-rotation backward: dk/dv accumulators travel with the
    rotating K/V blocks and arrive home after n hops."""
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    G = H // Hkv
    q5 = q.reshape(B, S, Hkv, G, D).astype(jnp.float32)
    g5 = g.reshape(B, S, Hkv, G, D).astype(jnp.float32)
    o5 = out.reshape(B, S, Hkv, G, D).astype(jnp.float32)
    delta = jnp.sum(g5 * o5, axis=-1)                  # [B,S,Hkv,G]
    delta = jnp.moveaxis(delta, 1, 3)                  # [B,Hkv,G,S]
    n = axis_size(axis_name)
    my_idx = jax.lax.axis_index(axis_name) if causal else 0  # see fwd note

    dq0 = jnp.zeros_like(q5)
    dk0 = jnp.zeros((B, S, Hkv, D), jnp.float32)
    dv0 = jnp.zeros((B, S, Hkv, D), jnp.float32)

    def body(i, carry):
        dq, k_cur, v_cur, dk_cur, dv_cur = carry
        kv_idx = (my_idx - i) % n

        def compute(acc):
            dq, dk_c, dv_c = acc
            mask = _causal_mask(my_idx, kv_idx, S) if causal else None
            s = _block_scores(q5, k_cur, scale, mask)
            p = jnp.exp(s - lse[..., None])            # [B,Hkv,G,Sq,Sk]
            dp = jnp.einsum("bqhgd,bkhd->bhgqk", g5,
                            v_cur.astype(jnp.float32))
            ds = p * (dp - delta[..., None]) * scale
            dq = dq + jnp.einsum("bhgqk,bkhd->bqhgd", ds,
                                 k_cur.astype(jnp.float32))
            dk_c = dk_c + jnp.einsum("bhgqk,bqhgd->bkhd", ds, q5)
            dv_c = dv_c + jnp.einsum("bhgqk,bqhgd->bkhd", p, g5)
            return dq, dk_c, dv_c

        if causal:
            # mirror of the forward skip: fully-masked future blocks
            # contribute exact zeros to dq/dk/dv
            dq, dk_cur, dv_cur = jax.lax.cond(
                kv_idx <= my_idx, compute, lambda acc: acc,
                (dq, dk_cur, dv_cur))
        else:
            dq, dk_cur, dv_cur = compute((dq, dk_cur, dv_cur))
        return (dq, _rotate(k_cur, axis_name, n), _rotate(v_cur, axis_name, n),
                _rotate(dk_cur, axis_name, n), _rotate(dv_cur, axis_name, n))

    dq, _, _, dk, dv = jax.lax.fori_loop(0, n, body, (dq0, k, v, dk0, dv0))
    # after n rotations the accumulators are back at the owner of their block
    return (dq.reshape(B, S, H, D).astype(q.dtype),
            dk.astype(k.dtype), dv.astype(v.dtype))


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def ring_attention_local(q, k, v, axis_name=SP_AXIS, causal=True,
                         softmax_scale=None):
    """Per-device body (inside shard_map): q/k/v [B, S_loc, H|Hkv, D] are this
    device's sequence block; returns the local attention output."""
    scale = softmax_scale if softmax_scale is not None else \
        1.0 / math.sqrt(q.shape[-1])
    out, _ = _ring_fwd_local(q, k, v, axis_name, causal, scale)
    return out


def _ring_local_fwd(q, k, v, axis_name, causal, softmax_scale):
    scale = softmax_scale if softmax_scale is not None else \
        1.0 / math.sqrt(q.shape[-1])
    out, lse = _ring_fwd_local(q, k, v, axis_name, causal, scale)
    return out, (q, k, v, out, lse)


def _ring_local_bwd(axis_name, causal, softmax_scale, res, g):
    q, k, v, out, lse = res
    scale = softmax_scale if softmax_scale is not None else \
        1.0 / math.sqrt(q.shape[-1])
    return _ring_bwd_local(q, k, v, out, lse, g, axis_name, causal, scale)


ring_attention_local.defvjp(_ring_local_fwd, _ring_local_bwd)


# ----------------------------------------------------------------------
# Zig-zag layout: device d owns chunks (d, 2n-1-d) of 2n global chunks.
# Every device holds both EARLY and LATE positions, so the causal triangle
# is ~evenly split: each (device, hop) pair computes ~2 of its 4 chunk
# sub-blocks — the wall-clock realisation of the triangle saving the
# contiguous layout can only bank as FLOPs (module docstring).
# ----------------------------------------------------------------------

def zigzag_perm(S: int, n: int):
    """Global token permutation: new order = concat_d [chunk_d,
    chunk_{2n-1-d}] over devices d (2n chunks of S/(2n))."""
    assert S % (2 * n) == 0, f"S={S} must divide into 2*sp={2 * n} chunks"
    c = S // (2 * n)
    import numpy as _onp
    order = []
    for d in range(n):
        order.extend(range(d * c, (d + 1) * c))
        order.extend(range((2 * n - 1 - d) * c, (2 * n - d) * c))
    perm = _onp.asarray(order)
    inv = _onp.empty_like(perm)
    inv[perm] = _onp.arange(S)
    return perm, inv


def _zz_fwd_local(q, k, v, axis_name, scale):
    """Zig-zag causal forward.  Local block = [early chunk | late chunk]
    (each length c); 2x2 chunk sub-blocks per hop, fully-in-future ones
    skipped per device.  Returns (out, lse) like the contiguous kernel."""
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    G = H // Hkv
    c = S // 2
    n = axis_size(axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    q5 = q.reshape(B, S, Hkv, G, D)
    ar = jnp.arange(c)

    def chunk_id(owner, half):
        return jnp.where(half == 0, owner, 2 * n - 1 - owner)

    # per-half accumulators [B, c, Hkv, G, D] / [B, Hkv, G, c]
    o0 = jnp.zeros((B, S, Hkv, G, D), jnp.float32)
    m0 = jnp.full((B, Hkv, G, S), _NEG, jnp.float32)
    l0 = jnp.zeros((B, Hkv, G, S), jnp.float32)

    def body(i, carry):
        o, m, l, k_cur, v_cur = carry
        j = (my_idx - i) % n
        for qh in (0, 1):
            qc_id = chunk_id(my_idx, qh)
            q_half = q5[:, qh * c:(qh + 1) * c]
            o_h = o[:, qh * c:(qh + 1) * c]
            m_h = m[..., qh * c:(qh + 1) * c]
            l_h = l[..., qh * c:(qh + 1) * c]
            for kh in (0, 1):
                kc_id = chunk_id(j, kh)
                k_half = k_cur[:, kh * c:(kh + 1) * c]
                v_half = v_cur[:, kh * c:(kh + 1) * c]

                def compute(acc, q_half=q_half, k_half=k_half,
                            v_half=v_half, qc_id=qc_id, kc_id=kc_id):
                    o_h, m_h, l_h = acc
                    qpos = qc_id * c + ar[:, None]
                    kpos = kc_id * c + ar[None, :]
                    s = _block_scores(q_half, k_half, scale, qpos >= kpos)
                    bm = jnp.max(s, axis=-1)
                    new_m = jnp.maximum(m_h, bm)
                    p = jnp.exp(s - new_m[..., None])
                    p = jnp.where(new_m[..., None] <= _NEG / 2, 0.0, p)
                    corr = jnp.exp(m_h - new_m)
                    corr = jnp.where(m_h <= _NEG / 2, 0.0, corr)
                    l2 = l_h * corr + jnp.sum(p, axis=-1)
                    bo = jnp.einsum("bhgqk,bkhd->bqhgd",
                                    p.astype(v_half.dtype),
                                    v_half).astype(jnp.float32)
                    corr_o = jnp.moveaxis(corr, 3, 1)[..., None]
                    return o_h * corr_o + bo, new_m, l2

                o_h, m_h, l_h = jax.lax.cond(
                    qc_id >= kc_id, compute, lambda a: a, (o_h, m_h, l_h))
            o = jax.lax.dynamic_update_slice_in_dim(o, o_h, qh * c, 1)
            m = jax.lax.dynamic_update_slice_in_dim(m, m_h, qh * c, 3)
            l = jax.lax.dynamic_update_slice_in_dim(l, l_h, qh * c, 3)
        return o, m, l, _rotate(k_cur, axis_name, n), \
            _rotate(v_cur, axis_name, n)

    o, m, l, _, _ = jax.lax.fori_loop(0, n, body, (o0, m0, l0, k, v))
    l_safe = jnp.maximum(l, 1e-30)
    out = o / jnp.moveaxis(l_safe, 3, 1)[..., None]
    lse = m + jnp.log(l_safe)
    return out.reshape(B, S, H, D).astype(q.dtype), lse


def _zz_bwd_local(q, k, v, out, lse, g, axis_name, scale):
    """Zig-zag backward: same sub-block skip; dk/dv accumulators travel
    with the rotating K/V and arrive home after n hops."""
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    G = H // Hkv
    c = S // 2
    n = axis_size(axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    ar = jnp.arange(c)
    q5 = q.reshape(B, S, Hkv, G, D).astype(jnp.float32)
    g5 = g.reshape(B, S, Hkv, G, D).astype(jnp.float32)
    o5 = out.reshape(B, S, Hkv, G, D).astype(jnp.float32)
    delta = jnp.moveaxis(jnp.sum(g5 * o5, axis=-1), 1, 3)   # [B,Hkv,G,S]

    def chunk_id(owner, half):
        return jnp.where(half == 0, owner, 2 * n - 1 - owner)

    dq0 = jnp.zeros_like(q5)
    dk0 = jnp.zeros((B, S, Hkv, D), jnp.float32)
    dv0 = jnp.zeros((B, S, Hkv, D), jnp.float32)

    def body(i, carry):
        dq, k_cur, v_cur, dk_cur, dv_cur = carry
        j = (my_idx - i) % n
        for qh in (0, 1):
            qc_id = chunk_id(my_idx, qh)
            q_half = q5[:, qh * c:(qh + 1) * c]
            g_half = g5[:, qh * c:(qh + 1) * c]
            lse_h = lse[..., qh * c:(qh + 1) * c]
            delta_h = delta[..., qh * c:(qh + 1) * c]
            dq_h = dq[:, qh * c:(qh + 1) * c]
            for kh in (0, 1):
                kc_id = chunk_id(j, kh)
                k_half = k_cur[:, kh * c:(kh + 1) * c]
                v_half = v_cur[:, kh * c:(kh + 1) * c]
                dk_h = jax.lax.dynamic_slice_in_dim(dk_cur, kh * c, c, 1)
                dv_h = jax.lax.dynamic_slice_in_dim(dv_cur, kh * c, c, 1)

                def compute(acc, q_half=q_half, g_half=g_half,
                            k_half=k_half, v_half=v_half, lse_h=lse_h,
                            delta_h=delta_h, qc_id=qc_id, kc_id=kc_id):
                    dq_h, dk_h, dv_h = acc
                    qpos = qc_id * c + ar[:, None]
                    kpos = kc_id * c + ar[None, :]
                    s = _block_scores(q_half, k_half, scale, qpos >= kpos)
                    p = jnp.exp(s - lse_h[..., None])
                    dp = jnp.einsum("bqhgd,bkhd->bhgqk", g_half,
                                    v_half.astype(jnp.float32))
                    ds = p * (dp - delta_h[..., None]) * scale
                    dq_h = dq_h + jnp.einsum("bhgqk,bkhd->bqhgd", ds,
                                             k_half.astype(jnp.float32))
                    dk_h = dk_h + jnp.einsum("bhgqk,bqhgd->bkhd", ds,
                                             q_half)
                    dv_h = dv_h + jnp.einsum("bhgqk,bqhgd->bkhd", p,
                                             g_half)
                    return dq_h, dk_h, dv_h

                dq_h, dk_h, dv_h = jax.lax.cond(
                    qc_id >= kc_id, compute, lambda a: a,
                    (dq_h, dk_h, dv_h))
                dk_cur = jax.lax.dynamic_update_slice_in_dim(
                    dk_cur, dk_h, kh * c, 1)
                dv_cur = jax.lax.dynamic_update_slice_in_dim(
                    dv_cur, dv_h, kh * c, 1)
            dq = jax.lax.dynamic_update_slice_in_dim(dq, dq_h, qh * c, 1)
        return (dq, _rotate(k_cur, axis_name, n),
                _rotate(v_cur, axis_name, n),
                _rotate(dk_cur, axis_name, n),
                _rotate(dv_cur, axis_name, n))

    dq, _, _, dk, dv = jax.lax.fori_loop(0, n, body, (dq0, k, v, dk0, dv0))
    return (dq.reshape(B, S, H, D).astype(q.dtype),
            dk.astype(k.dtype), dv.astype(v.dtype))


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def zigzag_ring_attention_local(q, k, v, axis_name=SP_AXIS,
                                softmax_scale=None):
    scale = softmax_scale if softmax_scale is not None else \
        1.0 / math.sqrt(q.shape[-1])
    out, _ = _zz_fwd_local(q, k, v, axis_name, scale)
    return out


def _zz_local_fwd(q, k, v, axis_name, softmax_scale):
    scale = softmax_scale if softmax_scale is not None else \
        1.0 / math.sqrt(q.shape[-1])
    out, lse = _zz_fwd_local(q, k, v, axis_name, scale)
    return out, (q, k, v, out, lse)


def _zz_local_bwd(axis_name, softmax_scale, res, g):
    q, k, v, out, lse = res
    scale = softmax_scale if softmax_scale is not None else \
        1.0 / math.sqrt(q.shape[-1])
    return _zz_bwd_local(q, k, v, out, lse, g, axis_name, scale)


zigzag_ring_attention_local.defvjp(_zz_local_fwd, _zz_local_bwd)


def ring_attention(q, k, v, causal=True, softmax_scale=None, mesh=None,
                   layout="contiguous"):
    """GSPMD entry: q/k/v global [B, S, H|Hkv, D], sequence-sharded over
    ``sp``.  ``layout="zigzag"`` (causal only) permutes tokens so every
    device owns early AND late positions — balanced causal work, ~2x
    step-time at large sp (the permutation gathers lower to one
    all-to-all per tensor)."""
    mesh = mesh or active_mesh()
    if mesh is None or mesh.shape.get(SP_AXIS, 1) == 1:
        from deepspeed_tpu.ops.attention import reference_attention
        return reference_attention(q, k, v, causal=causal,
                                   softmax_scale=softmax_scale)
    spec = P(tuple(BATCH_AXES), SP_AXIS, None, None)
    if layout == "zigzag":
        assert causal, "zigzag layout only makes sense for causal attention"
        n = mesh.shape[SP_AXIS]
        perm, inv = zigzag_perm(q.shape[1], n)
        qz, kz, vz = (x[:, perm] for x in (q, k, v))
        body = shard_map(
            lambda q, k, v: zigzag_ring_attention_local(
                q, k, v, SP_AXIS, softmax_scale),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
        return body(qz, kz, vz)[:, inv]
    body = shard_map(
        # positional call: custom_vjp nondiff_argnums are positional
        lambda q, k, v: ring_attention_local(q, k, v, SP_AXIS, causal,
                                             softmax_scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return body(q, k, v)
