"""Transformer layer op surface (reference ``deepspeed/ops/transformer/``)."""

from deepspeed_tpu.ops.transformer.transformer import (
    DeepSpeedTransformerConfig, DeepSpeedTransformerLayer)

__all__ = ["DeepSpeedTransformerConfig", "DeepSpeedTransformerLayer"]
