"""Op-level inference surface (reference ``csrc/transformer/inference``
``pt_binding.cpp:1714-1780`` — the ~40 fused ops behind
``DeepSpeedTransformerInference``).

TPU design: each op is a small jnp function with the REFERENCE's exact
math (kernels read from ``gelu.cu``/``pt_binding.cpp``); under ``jit``
XLA fuses the chains the reference fuses by hand, and the genuinely
attention-shaped ops (``softmax_context``) dispatch to the Pallas decode
kernels.  The surface exists so code written against the reference's op
API ports one-import; the hot path in THIS framework is the jitted model
(``models/transformer.py``), not op-by-op calls.

Dtype-suffixed aliases (``*_fp16``/``*_fp32``) map to one dtype-generic
function, as do the int8 variants after ``ops/quantizer`` dequant.
"""

from typing import Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.models.transformer import _norm
# softmax_context: attention over the KV cache — the Pallas-backed path
from deepspeed_tpu.ops.decode_attention import softmax_context  # noqa: F401


# ---------------------------------------------------------------------
# elementwise fusions (gelu.cu)
# ---------------------------------------------------------------------

def bias_add(x, bias):
    return x + bias.astype(x.dtype)


def bias_gelu(x, bias):
    return jax.nn.gelu(x + bias.astype(x.dtype))


def bias_relu(x, bias):
    return jax.nn.relu(x + bias.astype(x.dtype))


def bias_geglu(x, bias):
    """Gated GELU (diffusers FFNs): split the last dim in half,
    ``a * gelu(b)``."""
    y = x + bias.astype(x.dtype)
    a, b = jnp.split(y, 2, axis=-1)
    return a * jax.nn.gelu(b)


def bias_residual(x, residual, bias):
    return x + residual + bias.astype(x.dtype)


def residual_add_bias(hidden_state, residual, attention_output,
                      attention_bias, final_bias, mp_size: int = 1,
                      mlp_after_attn: bool = True, add_bias: bool = True,
                      preln: bool = True):
    """Reference ``residual_add_bias`` (pt_binding.cpp:1580; kernels
    ``fused_bias_residual`` / ``gptj_residual_add``, gelu.cu:120,267):

    * mlp_after_attn and preln:
      ``(residual + attn + final_bias + attn_bias) / mp_size + hidden``
    * mlp_after_attn, not preln: ``residual + hidden + final_bias``
    * parallel block (GPT-J; not mlp_after_attn):
      ``hidden + attn + (residual [+ attn_bias] + final_bias) / mp_size``
    """
    scale = 1.0 / mp_size
    if mlp_after_attn:
        if preln:
            return (residual + attention_output + final_bias +
                    attention_bias) * scale + hidden_state
        return residual + hidden_state + final_bias
    r = residual + attention_bias if add_bias else residual
    return hidden_state + attention_output + (r + final_bias) * scale


def moe_res_matmul(moe_res, coef, mlp_out):
    """Reference ``moe_res_matmul`` (gelu.cu:408): coef packs two [d]
    vectors along the hidden dim; ``mlp_out * coef2 + moe_res * coef1``."""
    d = moe_res.shape[-1]
    coef1, coef2 = coef[..., :d], coef[..., d:2 * d]
    return mlp_out * coef2 + moe_res * coef1


# ---------------------------------------------------------------------
# norms (layer_norm.cu)
# ---------------------------------------------------------------------

def layer_norm(x, gamma, beta, eps: float = 1e-5):
    return _norm(x, gamma, eps, use_rms=False, bias=beta)


def layer_norm_residual(x, bias, residual, gamma, beta, eps: float = 1e-5):
    """``ln(x + bias + residual)`` (reference ``_layer_norm_residual``)."""
    return layer_norm(x + residual + bias.astype(x.dtype), gamma, beta, eps)


def layer_norm_residual_store_pre_ln_res(x, bias, residual, gamma, beta,
                                         eps: float = 1e-5):
    """Same, also returning the pre-LN sum (the next block's residual)."""
    pre = x + residual + bias.astype(x.dtype)
    return layer_norm(pre, gamma, beta, eps), pre


# ---------------------------------------------------------------------
# gemm fusions (pt_binding qkv_gemm / mlp_gemm / ...)
# ---------------------------------------------------------------------

def vector_matmul(x, w):
    return x @ w


def linear_layer(x, w, bias=None):
    out = x @ w
    return out if bias is None else out + bias.astype(out.dtype)


def qkv_gemm(x, weight, bias, gamma, beta, eps: float = 1e-5,
             add_bias: bool = True):
    """Pre-LN fused QKV projection; returns ``(qkv, inp_norm)`` like the
    reference (the normed input feeds the attention residual path)."""
    inp_norm = layer_norm(x, gamma, beta, eps)
    out = inp_norm @ weight
    if add_bias:
        out = out + bias.astype(out.dtype)
    return out, inp_norm


def mlp_gemm(x, residual, input_bias, weight_up, bias_up, weight_down,
             gamma, beta, eps: float = 1e-5, preln: bool = True,
             activation=jax.nn.gelu):
    """Pre-LN MLP block: ``res_add = x + residual + input_bias``;
    ``out = act(ln(res_add) @ W_up + b_up) @ W_down``.  Returns
    ``(out, res_add)`` (reference mlp_gemm returns the residual sum for
    the following residual_add_bias)."""
    res_add = x + residual + input_bias.astype(x.dtype) if preln \
        else layer_norm(x + residual + input_bias.astype(x.dtype),
                        gamma, beta, eps)
    h = layer_norm(res_add, gamma, beta, eps) if preln else res_add
    h = activation(h @ weight_up + bias_up.astype(h.dtype))
    return h @ weight_down, res_add


def fused_gemm_gelu(x, weight_up, bias_up, weight_down):
    return jax.nn.gelu(x @ weight_up + bias_up.astype(x.dtype)) @ weight_down


# ---------------------------------------------------------------------
# rotary (apply_rotary_pos_emb.cu)
# ---------------------------------------------------------------------

def apply_rotary_pos_emb(query, key, rotary_dim: int, offset: int = 0,
                         rotate_every_two: bool = True,
                         theta: float = 10000.0):
    """q/k: [B, S, H, D]; rotates the leading ``rotary_dim`` of each head.
    ``rotate_every_two=True`` is the GPT-J interleaved convention; False is
    the NeoX half-split (reference's ``rotate_half``)."""
    from deepspeed_tpu.models.transformer import _rope

    B, S, H, D = query.shape
    pos = offset + jnp.arange(S)
    if not rotate_every_two:
        # half-split IS the model's RoPE — delegate, don't duplicate
        pos_b = jnp.broadcast_to(pos[None, :], (B, S))
        return (_rope(query, pos_b, theta, rotary_dim),
                _rope(key, pos_b, theta, rotary_dim))

    # interleaved (GPT-J): pair (2j, 2j+1) rotates by freq j.  Tables are
    # shared between query and key.
    half = rotary_dim // 2
    freqs = theta ** (-jnp.arange(half, dtype=jnp.float32) / half)
    ang = pos[:, None].astype(jnp.float32) * freqs[None, :]
    cos = jnp.cos(ang)[None, :, None, :]
    sin = jnp.sin(ang)[None, :, None, :]

    def rot(x):
        r, rest = x[..., :rotary_dim], x[..., rotary_dim:]
        x1, x2 = r[..., 0::2], r[..., 1::2]
        out = jnp.stack([x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                        axis=-1).reshape(r.shape)
        return jnp.concatenate([out.astype(x.dtype), rest], axis=-1)

    return rot(query), rot(key)


# ---------------------------------------------------------------------
# misc (einsum_sec_sm_ecm — the MoE gather einsum)
# ---------------------------------------------------------------------

def einsum_sec_sm_ecm(a, b):
    return jnp.einsum("sec,sm->ecm", a, b)


# dtype-suffixed parity aliases ----------------------------------------
for _name in ("bias_gelu", "bias_add", "bias_relu", "bias_residual",
              "qkv_gemm", "mlp_gemm", "vector_matmul", "linear_layer",
              "fused_gemm_gelu", "residual_add_bias", "einsum_sec_sm_ecm"):
    globals()[f"{_name}_fp32"] = globals()[_name]
    globals()[f"{_name}_fp16"] = globals()[_name]
