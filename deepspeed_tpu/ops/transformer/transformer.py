"""Fused training transformer layer — parity surface.

Parity: reference ``deepspeed/ops/transformer/transformer.py``
(``DeepSpeedTransformerConfig``/``DeepSpeedTransformerLayer`` backed by the
``transformer`` CUDA op: a fully fused fwd+bwd encoder layer; the
``stochastic_transformer`` variant trades determinism for speed).

TPU design: one jitted layer IS the fused kernel — XLA fuses
norm+qkv+attention+mlp, and autodiff supplies the fused backward; the
Pallas flash-attention path covers the attention core.  This class adapts
the reference's layer-level API onto ``CausalTransformerLM``'s single-layer
machinery so code written against DeepSpeedTransformerLayer ports directly.
"""

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.models.transformer import (CausalTransformerLM,
                                              TransformerConfig)


@dataclass
class DeepSpeedTransformerConfig:
    """Reference ctor args (transformer.py DeepSpeedTransformerConfig)."""
    batch_size: int = 1
    hidden_size: int = 768
    intermediate_size: Optional[int] = None
    heads: int = 12
    attn_dropout_ratio: float = 0.0
    hidden_dropout_ratio: float = 0.0
    num_hidden_layers: int = 1
    initializer_range: float = 0.02
    seed: int = 0
    fp16: bool = False
    pre_layer_norm: bool = True
    normalize_invertible: bool = False
    gelu_checkpoint: bool = False
    stochastic_mode: bool = False
    huggingface: bool = False
    training: bool = True

    def to_model_config(self) -> TransformerConfig:
        return TransformerConfig(
            vocab_size=1, hidden_size=self.hidden_size, n_layers=1,
            n_heads=self.heads,
            ffn_hidden_size=self.intermediate_size or 4 * self.hidden_size,
            activation="gelu", use_rmsnorm=False, use_rope=True,
            use_bias=True, norm_bias=True, remat=self.gelu_checkpoint)


class DeepSpeedTransformerLayer:
    """One pre-LN encoder/decoder layer with the reference's call shape:
    ``layer(params, hidden_states)``. Causality follows ``causal=``
    (the reference BERT kernel is bidirectional)."""

    def __init__(self, config: DeepSpeedTransformerConfig, causal=False):
        self.config = config
        self.causal = causal
        mc = config.to_model_config()
        if not causal:
            mc = TransformerConfig(**{**mc.__dict__, "attn_impl": "reference"})
        self.model_config = mc
        self._lm = CausalTransformerLM(mc)
        self._compiled = None

    def init(self, rng, dtype=jnp.float32):
        """Single-layer params (the model's stacked layout with L=1)."""
        full = self._lm.init(rng, dtype=dtype)
        return full["layers"]

    def __call__(self, params, hidden_states, attention_mask=None, rng=None):
        B, S, _ = hidden_states.shape
        positions = jnp.broadcast_to(jnp.arange(S)[None, :], (B, S))
        layer = jax.tree_util.tree_map(lambda x: x[0], params)  # drop L dim
        if self.causal:
            x = self._lm._attn_block(hidden_states, layer, positions)
        else:
            # bidirectional: reference BERT-style full attention
            from deepspeed_tpu.ops.attention import reference_attention
            c = self.model_config
            from deepspeed_tpu.models.transformer import _norm
            h = _norm(hidden_states, layer["attn_norm"], c.norm_eps,
                      c.use_rmsnorm, layer.get("attn_norm_b"))
            q, k, v = self._lm._qkv(h, layer, B, S, positions)
            attn = reference_attention(q, k, v, causal=False)
            x = hidden_states + self._lm._proj(
                attn.reshape(B, S, -1), layer, "wo")
        x, _ = self._lm._mlp_block(x, layer, rng=rng, train=self.config.training)
        return x

    forward = __call__


# stochastic variant: same math on TPU (XLA is deterministic); kept for API
DeepSpeedStochasticTransformerLayer = DeepSpeedTransformerLayer
