"""shard_map / axis introspection across jax versions.

``jax.shard_map`` only became public API after 0.4.x (older releases ship it
as ``jax.experimental.shard_map``), the replication-check kwarg was renamed
``check_rep`` → ``check_vma`` along the way, and ``jax.lax.axis_size``
appeared later still.  Resolve all three at import time so callers use one
spelling.
"""

import inspect

import jax

try:
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

try:
    from jax.lax import axis_size
except ImportError:  # pragma: no cover - older jax
    def axis_size(axis_name):
        # psum of a static python int folds to the axis extent at trace
        # time, so the result stays usable in shape/range computations
        return jax.lax.psum(1, axis_name)

_CHECK_KW = ("check_vma" if "check_vma"
             in inspect.signature(_shard_map).parameters else "check_rep")


def shard_map(f, mesh, in_specs, out_specs, check=False):
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **{_CHECK_KW: check})
