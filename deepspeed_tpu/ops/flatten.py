"""Tensor flatten/unflatten.

Parity: reference ``csrc/utils/flatten_unflatten.cpp`` (apex-style
``flatten``/``unflatten`` used by the engine and ZeRO for contiguous comm
buffers).  On TPU this is ``jax.flatten_util.ravel_pytree`` — XLA keeps the
layout fusion-friendly, so no custom kernel is needed.
"""

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree


def flatten(tensors):
    """Pytree/list of arrays → one flat fp-preserving 1-D buffer."""
    flat, _ = ravel_pytree(tensors)
    return flat


def unflatten(flat, like):
    """Inverse of flatten given a template pytree ``like``."""
    _, unravel = ravel_pytree(like)
    return unravel(flat)


def flatten_dense_tensors_aligned(tensors, alignment):
    """Flatten with padding to ``alignment`` elements (reference
    ``stage_1_and_2.py flatten_dense_tensors_aligned``)."""
    flat = flatten(tensors)
    remainder = flat.size % alignment
    if remainder:
        flat = jnp.pad(flat, (0, alignment - remainder))
    return flat
