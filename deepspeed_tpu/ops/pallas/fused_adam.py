"""Pallas fused AdamW over flat partition buffers.

Parity: reference ``csrc/adam/multi_tensor_adam.cu`` (``multi_tensor_adam``)
— the CUDA multi-tensor AdamW used by ZeRO.  The reference fuses the whole
update into one kernel launch over chunked tensor lists; here the ZeRO
partition layout is already a flat buffer, so one Pallas kernel tiles it
through VMEM and the update never round-trips HBM between its ~10
elementwise ops.  Outputs alias the inputs (in-place, like the CUDA op).

``ops/adam.py:reference_impl`` is the jnp oracle; CPU CI runs this kernel
with ``interpret=True``.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    _HAS_PLTPU = False

_LANES = 128
_BLOCK_ROWS = 512        # 512x128 fp32 x 7 live buffers ≈ 1.8 MB VMEM


def _adam_kernel(scalars_ref, p_ref, g_ref, m_ref, v_ref,
                 out_p_ref, out_m_ref, out_v_ref, *,
                 beta1, beta2, eps, weight_decay, adamw_mode):
    c1 = scalars_ref[0]      # 1 - beta1**step   (1.0 if no bias correction)
    c2 = scalars_ref[1]      # 1 - beta2**step
    lr = scalars_ref[2]
    g = g_ref[...].astype(jnp.float32)
    p = p_ref[...].astype(jnp.float32)
    if not adamw_mode and weight_decay:       # L2-regularised Adam (mode 1)
        g = g + weight_decay * p
    m = beta1 * m_ref[...] + (1.0 - beta1) * g
    v = beta2 * v_ref[...] + (1.0 - beta2) * (g * g)
    update = (m / c1) / (jnp.sqrt(v / c2) + eps)
    if adamw_mode and weight_decay:           # decoupled decay (mode 0)
        update = update + weight_decay * p
    out_p_ref[...] = (p - lr * update).astype(out_p_ref.dtype)
    out_m_ref[...] = m
    out_v_ref[...] = v


def fused_adam_pallas(params, grads, state, lr=1e-3, beta1=0.9, beta2=0.999,
                      eps=1e-8, weight_decay=0.0, adamw_mode=True,
                      bias_correction=True, interpret=False):
    """One fused AdamW step on a flat buffer.  Same contract as
    ``ops/adam.py:reference_impl``: returns (new_params, new_state)."""
    from deepspeed_tpu.ops.adam import AdamState

    n = params.size
    step = state.step + 1
    sf = step.astype(jnp.float32)
    c1 = 1.0 - beta1 ** sf if bias_correction else jnp.float32(1.0)
    c2 = 1.0 - beta2 ** sf if bias_correction else jnp.float32(1.0)
    scalars = jnp.stack([jnp.asarray(c1, jnp.float32),
                         jnp.asarray(c2, jnp.float32),
                         jnp.asarray(lr, jnp.float32)])

    # pad + tile the flat buffer to [rows, 128]
    tile = _BLOCK_ROWS * _LANES
    n_pad = -n % tile
    def shape2d(x):
        x = x.reshape(-1)
        if n_pad:
            x = jnp.pad(x, (0, n_pad))
        return x.reshape(-1, _LANES)

    p2 = shape2d(params)
    g2 = shape2d(grads)
    m2 = shape2d(state.m)
    v2 = shape2d(state.v)
    rows = p2.shape[0]
    grid = (rows // _BLOCK_ROWS,)

    kernel = functools.partial(
        _adam_kernel, beta1=beta1, beta2=beta2, eps=eps,
        weight_decay=weight_decay, adamw_mode=adamw_mode)
    block = pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i, scalars: (i, 0))
    new_p, new_m, new_v = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[block] * 4,
            out_specs=[block] * 3,
        ),
        out_shape=[
            jax.ShapeDtypeStruct(p2.shape, p2.dtype),
            jax.ShapeDtypeStruct(m2.shape, jnp.float32),
            jax.ShapeDtypeStruct(v2.shape, jnp.float32),
        ],
        input_output_aliases={1: 0, 3: 1, 4: 2},
        interpret=interpret,
    )(scalars, p2, g2, m2, v2)

    unpad = lambda x: x.reshape(-1)[:n]
    return unpad(new_p).reshape(params.shape), AdamState(
        m=unpad(new_m).reshape(params.shape),
        v=unpad(new_v).reshape(params.shape), step=step)
