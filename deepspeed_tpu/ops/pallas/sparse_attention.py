"""Pallas block-sparse attention (BigBird / Longformer / Fixed layouts).

Parity: reference ``deepspeed/ops/sparse_attention`` Triton kernels
(``matmul.py:8-14`` block-sparse sddmm/dsd, ``softmax.py``) — compute that
scales with the number of SET blocks of the layout, not O(S²).

TPU design: the layout [H, nb, nb] is static config, so the active-block
structure is precomputed on the host into an index table
``table[H, nQ, max_active]`` + ``counts[H, nQ]`` and shipped as
scalar-prefetch operands.  The grid is (batch·heads, q_blocks,
max_active): the K/V BlockSpec index maps look the k-block id up in the
table (clamping past ``counts`` so the repeated index skips the DMA), and
``pl.when`` skips the compute — both memory traffic and MXU work scale
with set blocks, which is exactly what the Triton sddmm/dsd pair buys the
reference.  Online softmax accumulates in VMEM scratch across the
active-block grid dimension; rows whose blocks are all masked produce
zeros (the reference kernel's empty-row handling).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    _HAS_PLTPU = False

_NEG = -1e30


def layout_tables(layout: np.ndarray, causal: bool):
    """[H, nb, nb] boolean layout → (table [H, nb, max_active] int32,
    counts [H, nb] int32).  With ``causal`` the upper triangle is dropped
    (those blocks would be fully masked anyway)."""
    lay = np.asarray(layout).astype(bool)
    H, nq, nk = lay.shape
    if causal:
        lay = lay & (np.arange(nq)[:, None] >= np.arange(nk)[None, :])
    counts = lay.sum(-1).astype(np.int32)                    # [H, nq]
    max_active = max(int(counts.max()), 1)
    table = np.zeros((H, nq, max_active), np.int32)
    for h in range(H):
        for qi in range(nq):
            idx = np.nonzero(lay[h, qi])[0]
            table[h, qi, :len(idx)] = idx
    return table, counts, max_active


def _sparse_kernel(counts_ref, table_ref, q_ref, k_ref, v_ref, o_ref,
                   acc_ref, m_ref, l_ref, *, scale, causal, block, n_heads):
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    i = pl.program_id(2)
    n_steps = pl.num_programs(2)
    h = bh % n_heads
    count = counts_ref[h, qi]

    @pl.when(i == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(i < count)
    def _compute():
        kb = table_ref[h, qi, i]
        q = q_ref[0].astype(jnp.float32) * scale            # [BLK, D]
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            qpos = qi * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 0)
            kpos = kb * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 1)
            s = jnp.where(qpos >= kpos, s, _NEG)
        m_prev, l_prev = m_ref[...], l_ref[...]
        bm = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, bm)
        p = jnp.exp(s - m_new)
        p = jnp.where(m_new <= _NEG / 2, 0.0, p)
        corr = jnp.exp(m_prev - m_new)
        corr = jnp.where(m_prev <= _NEG / 2, 0.0, corr)
        m_ref[...] = m_new
        l_ref[...] = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(i == n_steps - 1)
    def _finalize():
        # empty rows (count==0 or fully causal-masked) have l==0 and
        # acc==0: 0/eps = 0, matching the oracle's empty-row zeroing
        l_safe = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)


def sparse_attention_pallas(q, k, v, layout, block, causal=False,
                            softmax_scale=None, interpret=False):
    """q/k/v: [B, S, H, D]; layout: [H, nb, nb] (numpy, static).
    Only set blocks are fetched and computed."""
    B, S, H, D = q.shape
    assert S % block == 0, f"S {S} must tile by layout block {block}"
    nb = S // block
    scale = softmax_scale if softmax_scale is not None else D ** -0.5
    table, counts, max_active = layout_tables(
        np.asarray(layout)[:, :nb, :nb], causal)

    qr = jnp.swapaxes(q, 1, 2).reshape(B * H, S, D)
    kr = jnp.swapaxes(k, 1, 2).reshape(B * H, S, D)
    vr = jnp.swapaxes(v, 1, 2).reshape(B * H, S, D)

    def kv_map(bh, qi, i, counts_ref, table_ref):
        h = bh % H
        last = jnp.maximum(counts_ref[h, qi] - 1, 0)
        return (bh, table_ref[h, qi, jnp.minimum(i, last)], 0)

    kernel = functools.partial(
        _sparse_kernel, scale=scale, causal=causal, block=block, n_heads=H)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B * H, nb, max_active),
            in_specs=[
                pl.BlockSpec((1, block, D),
                             lambda bh, qi, i, c, t: (bh, qi, 0)),
                pl.BlockSpec((1, block, D), kv_map),
                pl.BlockSpec((1, block, D), kv_map),
            ],
            out_specs=pl.BlockSpec((1, block, D),
                                   lambda bh, qi, i, c, t: (bh, qi, 0)),
            scratch_shapes=[
                pltpu.VMEM((block, D), jnp.float32),
                pltpu.VMEM((block, 1), jnp.float32),
                pltpu.VMEM((block, 1), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
        interpret=interpret,
    )(jnp.asarray(counts), jnp.asarray(table), qr, kr, vr)
    return jnp.swapaxes(out.reshape(B, H, S, D), 1, 2)


def sparse_flops(layout, block, causal, head_dim):
    """Analytic kernel cost: FLOPs proportional to set blocks (the
    scaling contract the Triton kernels have; used by tests/profilers)."""
    table, counts, _ = layout_tables(np.asarray(layout), causal)
    set_blocks = int(counts.sum())
    return 4 * set_blocks * block * block * head_dim
