"""Pallas flash attention (TPU).

Parity role: the fused attention inside the reference's training transformer
kernel (``csrc/transformer/ds_transformer_cuda.cpp``) and its
softmax/dropout/transform sub-kernels — rebuilt as a tiled online-softmax
kernel that streams K/V blocks through VMEM into the MXU and never
materialises the [S, S] score matrix.

Forward: Pallas kernel, grid (batch·heads, q_blocks); K/V for the head stay
in VMEM (fine to S≈8k at D=128); inner ``fori_loop`` over K blocks carries
(acc, row-max, row-sum) registers.  Causal blocks beyond the diagonal are
skipped via the loop bound, the diagonal block is masked with iota.

Backward: custom VJP using the saved log-sum-exp, as two Pallas kernels —
``_bwd_dq_kernel`` (grid over q blocks; streams K/V) and
``_bwd_dkv_kernel`` (grid over k blocks; streams Q/dO) — O(S) memory,
recomputing the probabilities tile-by-tile instead of materialising the
[B,H,S,S] score matrix.  ``_flash_bwd`` (jnp einsums) is the test oracle
only: non-tiling shapes never reach the custom VJP, because
``flash_attention()`` routes them to ``reference_attention`` (whose
autodiff handles their gradient) before the VJP is involved.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu is only importable on TPU-capable installs
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
_NEG = -1e30


def _tile_positions(q_base, k_base, block_q, block_k):
    qpos = q_base + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    kpos = k_base + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return qpos, kpos


def _mask_bias(s, qpos, kpos, causal, slope, window):
    """Shared score-tile transform: ALiBi bias (``slope * kpos`` — the
    row-constant part cancels in softmax, matching the model's
    ``_attn_bias``) then causal / sliding-window masking.  ``slope`` and
    ``window`` are traced scalars (0 disables)."""
    if slope is not None:
        s = s + slope * kpos.astype(jnp.float32)
    allowed = None
    if causal:
        allowed = qpos >= kpos
    if window is not None:
        in_win = (qpos - kpos < window) | (window <= 0)
        allowed = in_win if allowed is None else (allowed & in_win)
    if allowed is not None:
        s = jnp.where(allowed, s, _NEG)
    return s


def _k_range(qi, block_q, block_k, seq_len, causal, window):
    """[lo, hi) K-block range visible to q-block ``qi``; with a window the
    far-past blocks are skipped (true sliding-window FLOPs)."""
    num_k_blocks = seq_len // block_k
    if causal:
        hi = jax.lax.div((qi + 1) * block_q + block_k - 1, block_k)
        hi = jnp.minimum(hi, num_k_blocks)
    else:
        hi = num_k_blocks
    lo = 0
    if window is not None:
        lo_w = jax.lax.div(qi * block_q - (window - 1), block_k)
        lo = jnp.where(window > 0, jnp.maximum(0, lo_w), 0)
    return lo, hi


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                block_q, block_k, seq_len):
    _fwd_impl(q_ref, k_ref, v_ref, None, None, o_ref, lse_ref, scale=scale,
              causal=causal, block_q=block_q, block_k=block_k,
              seq_len=seq_len)


def _fwd_kernel_biased(q_ref, k_ref, v_ref, slope_ref, window_ref, o_ref,
                       lse_ref, *, scale, causal, block_q, block_k,
                       seq_len, use_slope=True, use_window=True):
    _fwd_impl(q_ref, k_ref, v_ref, slope_ref if use_slope else None,
              window_ref if use_window else None, o_ref, lse_ref,
              scale=scale, causal=causal, block_q=block_q, block_k=block_k,
              seq_len=seq_len)


def _fwd_impl(q_ref, k_ref, v_ref, slope_ref, window_ref, o_ref, lse_ref,
              *, scale, causal, block_q, block_k, seq_len):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale          # [BLK_Q, D]
    d = q.shape[-1]

    bh = pl.program_id(0)
    slope = slope_ref[bh, 0] if slope_ref is not None else None
    window = window_ref[bh, 0] if window_ref is not None else None
    lo, hi = _k_range(qi, block_q, block_k, seq_len, causal, window)

    def body(kb, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = q @ k.T                                    # [BLK_Q, BLK_K]
        if causal or slope is not None or window is not None:
            qpos, kpos = _tile_positions(qi * block_q, kb * block_k,
                                         block_q, block_k)
            s = _mask_bias(s, qpos, kpos, causal, slope, window)
        bm = jnp.max(s, axis=-1, keepdims=True)        # [BLK_Q, 1]
        new_m = jnp.maximum(m, bm)
        p = jnp.exp(s - new_m)
        p = jnp.where(new_m <= _NEG / 2, 0.0, p)
        corr = jnp.exp(m - new_m)
        corr = jnp.where(m <= _NEG / 2, 0.0, corr)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + p @ v
        return acc, new_m, l

    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(lo, hi, body, (acc0, m0, l0))

    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l_safe)


def _scalar_specs(shape):
    """Block specs for the per-(batch·head) bias scalars.

    The scalars ride as FULL ``[B*H, 1]`` arrays — a ``(1, 1)`` VMEM block of
    a ``[B*H, 1]`` array violates Mosaic's last-two-dims tiling rule (must
    tile (8, 128) or equal the array dims).  On TPU they live in SMEM (the
    scalar memory, where dynamic scalar reads are native); kernels index them
    with ``pl.program_id(0)``.
    """
    if _HAS_PLTPU:
        smem = pl.BlockSpec(memory_space=pltpu.SMEM)
        return [smem, smem]
    full = pl.BlockSpec(shape, lambda *_: (0,) * len(shape))
    return [full, full]


def _bias_inputs(alibi_slopes, window, B, H):
    """Per-(batch·head) ALiBi slope and window scalars as [B*H, 1] arrays
    (None, None when the no-bias fast path applies)."""
    if alibi_slopes is None and window is None:
        return None, None
    slopes = (jnp.zeros((H,), jnp.float32) if alibi_slopes is None
              else jnp.asarray(alibi_slopes, jnp.float32))
    slopes_bh = jnp.tile(slopes, B).reshape(B * H, 1)
    w = jnp.asarray(0 if window is None else window).astype(jnp.int32)
    w_bh = jnp.broadcast_to(w, (B * H,)).reshape(B * H, 1)
    return slopes_bh, w_bh


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret=False,
               alibi_slopes=None, window=None):
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    group = H // Hkv
    qr = jnp.swapaxes(q, 1, 2).reshape(B * H, S, D)
    kr = jnp.swapaxes(k, 1, 2).reshape(B * Hkv, S, D)
    vr = jnp.swapaxes(v, 1, 2).reshape(B * Hkv, S, D)

    block_q = min(block_q, S)
    block_k = min(block_k, S)
    grid = (B * H, S // block_q)

    slopes_bh, w_bh = _bias_inputs(alibi_slopes, window, B, H)
    in_specs = [
        pl.BlockSpec((1, block_q, D), lambda bh, qi: (bh, qi, 0)),
        pl.BlockSpec((1, S, D), lambda bh, qi, g=group: (bh // g, 0, 0)),
        pl.BlockSpec((1, S, D), lambda bh, qi, g=group: (bh // g, 0, 0)),
    ]
    args = [qr, kr, vr]
    if slopes_bh is None:
        kernel = functools.partial(
            _fwd_kernel, scale=scale, causal=causal, block_q=block_q,
            block_k=block_k, seq_len=S)
    else:
        kernel = functools.partial(
            _fwd_kernel_biased, scale=scale, causal=causal, block_q=block_q,
            block_k=block_k, seq_len=S,
            use_slope=alibi_slopes is not None,
            use_window=window is not None)
        in_specs += _scalar_specs(slopes_bh.shape)
        args += [slopes_bh, w_bh]

    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, qi: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, S, 1), jnp.float32),
        ],
        interpret=interpret,
    )(*args)

    out = jnp.swapaxes(out.reshape(B, H, S, D), 1, 2)
    return out, lse.reshape(B, H, S)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, scale, causal, block_q, block_k, seq_len):
    _bwd_dq_impl(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, None,
                 None, dq_ref, scale=scale, causal=causal, block_q=block_q,
                 block_k=block_k, seq_len=seq_len)


def _bwd_dq_kernel_biased(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          slope_ref, window_ref, dq_ref, *, scale, causal,
                          block_q, block_k, seq_len, use_slope=True,
                          use_window=True):
    _bwd_dq_impl(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                 slope_ref if use_slope else None,
                 window_ref if use_window else None, dq_ref, scale=scale,
                 causal=causal, block_q=block_q, block_k=block_k,
                 seq_len=seq_len)


def _bwd_dq_impl(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, slope_ref,
                 window_ref, dq_ref, *, scale, causal, block_q, block_k,
                 seq_len):
    """dQ for one (batch·head, q-block): stream K/V blocks, recompute P
    from the saved LSE, accumulate dq = Σ_kb dS @ K."""
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)                   # [BQ, D]
    do = do_ref[0].astype(jnp.float32)                 # [BQ, D]
    lse = lse_ref[0].reshape(block_q, 1)               # [BQ, 1, 1]→[BQ, 1]
    delta = delta_ref[0].reshape(block_q, 1)
    d = q.shape[-1]

    bh = pl.program_id(0)
    slope = slope_ref[bh, 0] if slope_ref is not None else None
    window = window_ref[bh, 0] if window_ref is not None else None
    lo, hi = _k_range(qi, block_q, block_k, seq_len, causal, window)

    def body(kb, dq):
        k = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal or slope is not None or window is not None:
            qpos, kpos = _tile_positions(qi * block_q, kb * block_k,
                                         block_q, block_k)
            s = _mask_bias(s, qpos, kpos, causal, slope, window)
        p = jnp.exp(s - lse)
        p = jnp.where(s <= _NEG / 2, 0.0, p)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        return dq + jnp.dot(ds, k, preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(lo, hi, body,
                           jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, scale, causal, block_q, block_k,
                    seq_len):
    _bwd_dkv_impl(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, None,
                  None, dk_ref, dv_ref, scale=scale, causal=causal,
                  block_q=block_q, block_k=block_k, seq_len=seq_len)


def _bwd_dkv_kernel_biased(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                           slope_ref, window_ref, dk_ref, dv_ref, *, scale,
                           causal, block_q, block_k, seq_len,
                           use_slope=True, use_window=True):
    _bwd_dkv_impl(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                  slope_ref if use_slope else None,
                  window_ref if use_window else None, dk_ref, dv_ref,
                  scale=scale, causal=causal, block_q=block_q,
                  block_k=block_k, seq_len=seq_len)


def _bwd_dkv_impl(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                  slope_ref, window_ref, dk_ref, dv_ref, *, scale, causal,
                  block_q, block_k, seq_len):
    """dK/dV for one (batch·head, k-block): stream Q/dO blocks.
    dv = Σ_qb Pᵀ @ dO;  dk = Σ_qb dSᵀ @ Q."""
    ki = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)                   # [BK, D]
    v = v_ref[0].astype(jnp.float32)
    d = k.shape[-1]

    bh = pl.program_id(0)
    slope = slope_ref[bh, 0] if slope_ref is not None else None
    window = window_ref[bh, 0] if window_ref is not None else None
    num_q_blocks = seq_len // block_q
    lo = (ki * block_k) // block_q if causal else 0
    hi = num_q_blocks
    if window is not None:
        # last q block that can see this k block: qpos < kpos + window
        hi_w = jax.lax.div((ki + 1) * block_k + window - 2, block_q) + 1
        hi = jnp.where(window > 0,
                       jnp.minimum(num_q_blocks, hi_w), num_q_blocks)

    def body(qb, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(qb * block_q, block_q), :].reshape(block_q, 1)
        delta = delta_ref[0, pl.ds(qb * block_q, block_q), :].reshape(
            block_q, 1)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal or slope is not None or window is not None:
            qpos, kpos = _tile_positions(qb * block_q, ki * block_k,
                                         block_q, block_k)
            s = _mask_bias(s, qpos, kpos, causal, slope, window)
        p = jnp.exp(s - lse)
        p = jnp.where(s <= _NEG / 2, 0.0, p)
        dv = dv + jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    dk0 = jnp.zeros((block_k, d), jnp.float32)
    dv0 = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(lo, hi, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd_pallas(scale, causal, res, g, block_q, block_k,
                      interpret=False, alibi_slopes=None, window=None):
    """O(S)-memory flash backward: recompute P per tile from the saved LSE.
    Returns (dq, dk, dv) with GQA group reduction."""
    q, k, v, out, lse = res
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    group = H // Hkv

    qr = jnp.swapaxes(q, 1, 2).reshape(B * H, S, D)
    kr = jnp.swapaxes(k, 1, 2).reshape(B * Hkv, S, D)
    vr = jnp.swapaxes(v, 1, 2).reshape(B * Hkv, S, D)
    gr = jnp.swapaxes(g, 1, 2).reshape(B * H, S, D)
    of = jnp.swapaxes(out, 1, 2).reshape(B * H, S, D)
    # trailing singleton dim: mosaic requires the last two block dims to
    # tile (8, 128) or equal the array dims — (block, 1) blocks of an
    # [..., 1] array are legal where (1, block) blocks of a 2-D one aren't
    lser = lse.reshape(B * H, S, 1)
    # delta_i = Σ_d dO_i · O_i  (the softmax-jacobian row term)
    delta = jnp.sum(gr.astype(jnp.float32) * of.astype(jnp.float32),
                    axis=-1, keepdims=True)

    slopes_bh, w_bh = _bias_inputs(alibi_slopes, window, B, H)
    scalar_specs = ([] if slopes_bh is None
                    else _scalar_specs(slopes_bh.shape))
    scalar_args = [] if slopes_bh is None else [slopes_bh, w_bh]

    kv_spec = pl.BlockSpec((1, S, D), lambda bh, i, g=group: (bh // g, 0, 0))
    dq_kernel = _bwd_dq_kernel if slopes_bh is None else functools.partial(
        _bwd_dq_kernel_biased, use_slope=alibi_slopes is not None,
        use_window=window is not None)
    dq = pl.pallas_call(
        functools.partial(dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_len=S),
        grid=(B * H, S // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi: (bh, qi, 0)),
            kv_spec,
            kv_spec,
            pl.BlockSpec((1, block_q, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, qi: (bh, qi, 0)),
        ] + scalar_specs,
        out_specs=pl.BlockSpec((1, block_q, D), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
        interpret=interpret,
    )(qr, kr, vr, gr, lser, delta, *scalar_args)

    full_spec = pl.BlockSpec((1, S, D), lambda bh, ki: (bh, 0, 0))
    dkv_kernel = (_bwd_dkv_kernel if slopes_bh is None
                  else functools.partial(
                      _bwd_dkv_kernel_biased,
                      use_slope=alibi_slopes is not None,
                      use_window=window is not None))
    dk, dv = pl.pallas_call(
        functools.partial(dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_len=S),
        grid=(B * H, S // block_k),
        in_specs=[
            full_spec,                                     # q
            pl.BlockSpec((1, block_k, D),
                         lambda bh, ki, g=group: (bh // g, ki, 0)),
            pl.BlockSpec((1, block_k, D),
                         lambda bh, ki, g=group: (bh // g, ki, 0)),
            full_spec,                                     # dO
            pl.BlockSpec((1, S, 1), lambda bh, ki: (bh, 0, 0)),  # lse
            pl.BlockSpec((1, S, 1), lambda bh, ki: (bh, 0, 0)),  # delta
        ] + scalar_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, ki: (bh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, S, D), jnp.float32),
            jax.ShapeDtypeStruct((B * H, S, D), jnp.float32),
        ],
        interpret=interpret,
    )(qr, kr, vr, gr, lser, delta, *scalar_args)

    dq = jnp.swapaxes(dq.reshape(B, H, S, D), 1, 2)
    dk = dk.reshape(B, Hkv, group, S, D).sum(axis=2)     # GQA group reduce
    dv = dv.reshape(B, Hkv, group, S, D).sum(axis=2)
    dk = jnp.swapaxes(dk, 1, 2).astype(k.dtype)
    dv = jnp.swapaxes(dv, 1, 2).astype(v.dtype)
    return dq, dk, dv


def _flash_bwd(scale, causal, res, g):
    """Flash backward from saved LSE (jnp einsums; fp32)."""
    q, k, v, out, lse = res
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    if Hkv != H:
        rep = H // Hkv
        k_full = jnp.repeat(k, rep, axis=2)
        v_full = jnp.repeat(v, rep, axis=2)
    else:
        k_full, v_full = k, v

    qf = q.astype(jnp.float32)
    kf = k_full.astype(jnp.float32)
    vf = v_full.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    of = out.astype(jnp.float32)

    s = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * scale
    if causal:
        qpos = jnp.arange(S)[:, None]
        kpos = jnp.arange(S)[None, :]
        s = jnp.where((qpos >= kpos)[None, None], s, _NEG)
    p = jnp.exp(s - lse[..., None])                    # [B,H,S,S]

    dv = jnp.einsum("bhqk,bqhd->bkhd", p, gf)
    dp = jnp.einsum("bqhd,bkhd->bhqk", gf, vf)
    delta = jnp.sum(gf * of, axis=-1)                  # [B,S,H]
    ds = p * (dp - jnp.swapaxes(delta, 1, 2)[..., None]) * scale
    dq = jnp.einsum("bhqk,bkhd->bqhd", ds, kf)
    dk = jnp.einsum("bhqk,bqhd->bkhd", ds, qf)

    if Hkv != H:
        rep = H // Hkv
        dk = dk.reshape(B, S, Hkv, rep, D).sum(axis=3)
        dv = dv.reshape(B, S, Hkv, rep, D).sum(axis=3)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype))


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash_attention(q, k, v, alibi_slopes, window, scale, causal, block_q,
                     block_k, interpret=False):
    out, _ = _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret,
                        alibi_slopes=alibi_slopes, window=window)
    return out


def _flash_attention_fwd(q, k, v, alibi_slopes, window, scale, causal,
                         block_q, block_k, interpret=False):
    out, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k,
                          interpret, alibi_slopes=alibi_slopes,
                          window=window)
    return out, (q, k, v, alibi_slopes, window, out, lse)


def _flash_attention_bwd(scale, causal, block_q, block_k, interpret,
                         res, g):
    # the forward only runs the kernel on tiling shapes, so the tiled
    # backward applies whenever this VJP is reached
    q, k, v, alibi_slopes, window, out, lse = res
    dq, dk, dv = _flash_bwd_pallas(scale, causal, (q, k, v, out, lse), g,
                                   block_q, block_k, interpret,
                                   alibi_slopes=alibi_slopes, window=window)
    dslopes = (None if alibi_slopes is None
               else jnp.zeros_like(jnp.asarray(alibi_slopes, jnp.float32)))
    dwindow = (None if window is None
               else jnp.zeros_like(jnp.asarray(window, jnp.float32)))
    return dq, dk, dv, dslopes, dwindow


_flash_attention.defvjp(_flash_attention_fwd, _flash_attention_bwd)


def flash_attention(q, k, v, causal=True, softmax_scale=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                    interpret=False, alibi_slopes=None, window=None):
    """q: [B, S, H, D]; k/v: [B, S, Hkv, D].  Falls back to the jnp reference
    when the shape doesn't tile (S not divisible by the block size).
    ``interpret=True`` runs the kernel in the Pallas interpreter (CPU CI).

    ``alibi_slopes`` ([H] fp32, treated as CONSTANT — stop_gradient; ALiBi
    slopes are a deterministic function of the head count, never learned)
    adds the Bloom-style per-head bias ``slope * kpos`` in-kernel; ``window`` (traced int scalar, 0/None =
    unlimited) applies a sliding-window mask AND skips K blocks wholly
    outside the window, so GPT-Neo/Mistral local attention gets its
    asymptotics (role of the reference's local-attention inference kernels,
    ``csrc/transformer/inference``)."""
    B, S, H, D = q.shape
    scale = softmax_scale if softmax_scale is not None else 1.0 / math.sqrt(D)
    block_q = min(block_q, S)
    block_k = min(block_k, S)
    if S % block_q or S % block_k or H % k.shape[2]:
        from deepspeed_tpu.ops.attention import (alibi_window_bias,
                                                 reference_attention)
        bias = alibi_window_bias(S, S, slopes=alibi_slopes, window=window)
        return reference_attention(q, k, v, causal=causal,
                                   softmax_scale=softmax_scale, bias=bias)
    window_f = (None if window is None
                else jnp.asarray(window, jnp.float32))
    if alibi_slopes is not None:
        # slopes are a deterministic function of the head count, not a
        # learned parameter: declare them constant so the custom VJP's
        # zero cotangent is stop_gradient semantics, not a silent grad loss
        alibi_slopes = jax.lax.stop_gradient(
            jnp.asarray(alibi_slopes, jnp.float32))
    return _flash_attention(q, k, v, alibi_slopes, window_f, scale, causal,
                            block_q, block_k, interpret)
