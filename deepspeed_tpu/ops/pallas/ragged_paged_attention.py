"""Ragged paged attention: ONE Pallas kernel for mixed prefill+decode.

Parity role: the serving engine's hottest op.  The jnp gather path in
``ops/paged_attention.py`` materialises every sequence's pages as a dense
``[B, Hkv, max_pages*page, D]`` view each step — three HBM passes over
max-length-padded K/V per decoded token.  This kernel (Ragged Paged
Attention, arXiv:2604.15464, cf. PAPERS.md) reads K/V pages IN PLACE
through the block table and serves a whole mixed batch in one launch:

* **Packed ragged queries.**  ``q`` is a flat ``[total_q, H, D]`` row
  stack — a 37-token prefill, three single-token decodes, and a 9-token
  chunked prefill ride in ONE call.  Per-sequence query lengths are host
  metadata (the engine knows them), so there is no per-slot padding to a
  batch max and no host-side regrouping into separate prefill and decode
  dispatches.  Internally each sequence's rows are padded only up to the
  next ``q_tile`` multiple.
* **grid = (q_tiles, kv_heads, pages)**; scalar-prefetched metadata
  (context lengths, query lengths, padded row starts, tile→sequence /
  tile→q-tile maps, block tables) steers the BlockSpec index maps, so the
  K/V index map fetches exactly the owning sequence's pages — shared
  prefix-cache pages and partial last pages read in place; pages past the
  tile's causal frontier are clamped to a repeat index (DMA skipped) and
  their compute is ``pl.when``-predicated off.
* **Online softmax** (running max / sum / fp32 accumulator in VMEM
  scratch persisting across the sequential page grid dim), one
  ``[q_tile·group, D]`` tile per (q-tile, kv-head); GQA comes free by
  folding each kv head's whole query group into the tile rows.

``ragged_paged_attention`` is the packed front-end (tests/bench/gate);
``ragged_paged_attention_rect`` adapts the rectangular ``[B, T, H, D]``
calls the jitted serving path makes (every sequence q_len = T) onto the
same kernel — it is what ``paged_decode_attention(backend="pallas")``
and the deprecated ``paged_attention_pallas`` route through, so there is
one paged-attention kernel surface.  The jnp gather path remains the
oracle; ``interpret=True`` runs this kernel on CPU CI.
"""

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    _HAS_PLTPU = False

_NEG = -1e30

DEFAULT_Q_TILE = 8


def _ragged_kernel(ctx_ref, qlens_ref, qstarts_ref, sot_ref, qot_ref,
                   tables_ref, q_ref, k_ref, v_ref, o_ref,
                   acc_ref, m_ref, l_ref, *, scale, page_size, q_tile,
                   group):
    """One (q-tile, kv-head, page) step of online-softmax attention.

    q_ref: [q_tile, 1, group, D] — ``q_tile`` padded query rows of ONE
    sequence for one kv head's whole group; k_ref/v_ref: [1, 1, page, D]
    (the page the index map resolved through the block table);
    o_ref: [q_tile, 1, group, D]; scratch acc/m/l persist across the
    page grid dim (TPU grids are sequential)."""
    t = pl.program_id(0)
    i = pl.program_id(2)
    n_pages = pl.num_programs(2)
    s = sot_ref[t]
    qt = qot_ref[t]
    ctx = ctx_ref[s]          # tokens in the cache INCLUDING the queries
    qlen = qlens_ref[s]       # this sequence's real (unpadded) query rows
    # keys this q tile may attend (causal): positions < kv_hi
    kv_hi = ctx - qlen + jnp.minimum(qlen, (qt + 1) * q_tile)

    rows = q_tile * group
    d = q_ref.shape[-1]

    @pl.when(i == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(i * page_size < kv_hi)
    def _compute():
        q = q_ref[:, 0].reshape(rows, d).astype(jnp.float32) * scale
        k = k_ref[0, 0].astype(jnp.float32)                # [page, D]
        v = v_ref[0, 0].astype(jnp.float32)
        sc = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # [rows, page]

        # row r is the sequence's local query token qt*q_tile + r//group
        # at absolute position ctx - qlen + local_t; per-sequence padding
        # rows (local_t >= qlen) mask to nothing and finalize to zeros
        local_t = qt * q_tile + jax.lax.broadcasted_iota(
            jnp.int32, (rows, page_size), 0) // group
        kpos = i * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (rows, page_size), 1)
        qpos = ctx - qlen + local_t
        sc = jnp.where((kpos <= qpos) & (local_t < qlen), sc, _NEG)

        m_prev, l_prev = m_ref[...], l_ref[...]
        bm = jnp.max(sc, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, bm)
        p = jnp.exp(sc - m_new)
        p = jnp.where(m_new <= _NEG / 2, 0.0, p)
        corr = jnp.exp(m_prev - m_new)
        corr = jnp.where(m_prev <= _NEG / 2, 0.0, corr)
        m_ref[...] = m_new
        l_ref[...] = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(i == n_pages - 1)
    def _finalize():
        l_safe = jnp.maximum(l_ref[...], 1e-30)
        o_ref[:, 0] = (acc_ref[...] / l_safe).reshape(q_tile, group, d) \
            .astype(o_ref.dtype)


def _ragged_call(qg, k_pages, v_pages, block_tables, ctx_lens, q_lens,
                 q_starts, seq_of_tile, qtile_of_tile, q_tile, scale,
                 interpret):
    """Launch the kernel over a q-tile-padded packed query stack.

    qg: [total_padded, Hkv, group, D] — every sequence's rows start at a
    q_tile multiple (``q_starts``).  ctx_lens/q_lens may be traced;
    q_starts / seq_of_tile / qtile_of_tile are host metadata (they size
    the grid)."""
    total_padded, Hkv, group, D = qg.shape
    page_size = k_pages.shape[2]
    max_pages = block_tables.shape[1]
    n_tiles = len(seq_of_tile)
    ctx_lens = jnp.asarray(ctx_lens, jnp.int32)
    q_lens = jnp.asarray(q_lens, jnp.int32)
    q_starts = jnp.asarray(q_starts, jnp.int32)
    sot = jnp.asarray(seq_of_tile, jnp.int32)
    qot = jnp.asarray(qtile_of_tile, jnp.int32)
    tables = jnp.asarray(block_tables, jnp.int32)

    def q_map(t, h, i, ctx, qls, qst, sot, qot, tbl):
        return (qst[sot[t]] // q_tile + qot[t], h, 0, 0)

    def kv_map(t, h, i, ctx, qls, qst, sot, qot, tbl):
        # fetch only pages under this tile's causal frontier: clamp to the
        # last needed page (repeat index -> DMA skipped)
        s = sot[t]
        kv_hi = ctx[s] - qls[s] + jnp.minimum(qls[s], (qot[t] + 1) * q_tile)
        last = jnp.maximum(pl.cdiv(kv_hi, page_size) - 1, 0)
        return (tbl[s, jnp.minimum(i, last)], h, 0, 0)

    kernel = functools.partial(_ragged_kernel, scale=scale,
                               page_size=page_size, q_tile=q_tile,
                               group=group)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=6,
            grid=(n_tiles, Hkv, max_pages),
            in_specs=[
                pl.BlockSpec((q_tile, 1, group, D), q_map),
                pl.BlockSpec((1, 1, page_size, D), kv_map),
                pl.BlockSpec((1, 1, page_size, D), kv_map),
            ],
            out_specs=pl.BlockSpec((q_tile, 1, group, D), q_map),
            scratch_shapes=[
                pltpu.VMEM((q_tile * group, D), jnp.float32),
                pltpu.VMEM((q_tile * group, 1), jnp.float32),
                pltpu.VMEM((q_tile * group, 1), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct(qg.shape, qg.dtype),
        interpret=interpret,
    )(ctx_lens, q_lens, q_starts, sot, qot, tables,
      qg, k_pages, v_pages)
    return out


def _pack_metadata(q_lens, q_tile):
    """Per-sequence padded row starts and tile maps for a packed stack."""
    starts, seq_of_tile, qtile_of_tile = [], [], []
    off = 0
    for s, ql in enumerate(q_lens):
        starts.append(off)
        n_t = -(-ql // q_tile)
        seq_of_tile.extend([s] * n_t)
        qtile_of_tile.extend(range(n_t))
        off += n_t * q_tile
    return (np.asarray(starts, np.int32),
            np.asarray(seq_of_tile, np.int32),
            np.asarray(qtile_of_tile, np.int32), off)


def ragged_paged_attention(q, k_pages, v_pages, block_tables, ctx_lens,
                           q_lens, softmax_scale=None,
                           q_tile=DEFAULT_Q_TILE, interpret=False):
    """Mixed prefill+decode attention over a packed ragged batch.

    q: [total_q, H, D] — sequence b's rows are
    ``q[sum(q_lens[:b]) : sum(q_lens[:b+1])]`` (its LAST q_lens[b] tokens,
    already appended to the cache); k_pages/v_pages: [P, Hkv, page, D];
    block_tables: [B, max_pages] int32; ctx_lens: [B] int32 tokens stored
    per sequence INCLUDING the query tokens (may be traced); q_lens: [B]
    host ints — the packed layout is host metadata, like the block
    tables' shape.  Returns [total_q, H, D].
    """
    total_q, H, D = q.shape
    Hkv = k_pages.shape[1]
    group = H // Hkv
    q_lens = [int(x) for x in np.asarray(q_lens).reshape(-1)]
    assert q_lens and min(q_lens) >= 1, f"bad q_lens {q_lens}"
    assert sum(q_lens) == total_q, \
        f"q has {total_q} rows but q_lens sums to {sum(q_lens)}"
    scale = softmax_scale if softmax_scale is not None else 1.0 / math.sqrt(D)
    q_tile = int(min(q_tile, max(q_lens)))
    starts, sot, qot, total_padded = _pack_metadata(q_lens, q_tile)

    # scatter each sequence's rows to its q_tile-aligned start (static
    # offsets: this is shape plumbing, not data-dependent control flow)
    qp = jnp.zeros((total_padded, H, D), q.dtype)
    off = 0
    for s, ql in enumerate(q_lens):
        qp = qp.at[int(starts[s]):int(starts[s]) + ql].set(q[off:off + ql])
        off += ql

    out = _ragged_call(qp.reshape(total_padded, Hkv, group, D),
                       k_pages, v_pages, block_tables, ctx_lens, q_lens,
                       starts, sot, qot, q_tile, scale, interpret)
    out = out.reshape(total_padded, H, D)
    return jnp.concatenate(
        [out[int(starts[s]):int(starts[s]) + ql]
         for s, ql in enumerate(q_lens)], axis=0)


def ragged_paged_attention_rect(q, k_pages, v_pages, block_tables, lengths,
                                softmax_scale=None, q_tile=DEFAULT_Q_TILE,
                                interpret=False):
    """Rectangular front-end for the jitted serving path.

    q: [B, T, H, D] — the last T tokens of each sequence (T=1 decode,
    T>1 bucketed/chunked prefill); lengths: [B] int32 valid tokens
    including the T new ones (may be traced — T itself is the static
    shape, so the packed metadata stays host-side).  Same kernel as
    :func:`ragged_paged_attention`; rows past a multiple-of-q_tile pad
    are masked inside the kernel.
    """
    B, T, H, D = q.shape
    Hkv = k_pages.shape[1]
    group = H // Hkv
    scale = softmax_scale if softmax_scale is not None else 1.0 / math.sqrt(D)
    q_tile = int(min(q_tile, T))
    n_qt = -(-T // q_tile)
    Tp = n_qt * q_tile
    if Tp != T:
        q = jnp.pad(q, ((0, 0), (0, Tp - T), (0, 0), (0, 0)))
    starts = np.arange(B, dtype=np.int32) * Tp
    sot = np.repeat(np.arange(B, dtype=np.int32), n_qt)
    qot = np.tile(np.arange(n_qt, dtype=np.int32), B)
    q_lens = jnp.full((B,), T, jnp.int32)
    out = _ragged_call(q.reshape(B * Tp, Hkv, group, D),
                       k_pages, v_pages, block_tables, lengths, q_lens,
                       starts, sot, qot, q_tile, scale, interpret)
    return out.reshape(B, Tp, H, D)[:, :T]
