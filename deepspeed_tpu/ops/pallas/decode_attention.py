"""Pallas decode attention with KV cache (contiguous and paged).

Parity role: the reference's fused inference attention
``softmax_context_fp16`` (``csrc/transformer/inference/csrc/pt_binding.cpp``
~:1720) — attention over a growing KV cache, GQA-aware, without
materialising logits in HBM.

TPU design (one kernel body, two front-ends):

* grid = (batch, kv_heads, key_blocks); the per-sequence valid length is a
  **scalar-prefetch** operand so both the BlockSpec index maps and the
  kernel see it before the body runs;
* key blocks past a sequence's length are never fetched: the index map
  clamps to the last valid block (Pallas skips the DMA when the block index
  repeats) and ``pl.when`` skips their compute;
* online softmax (running max / sum / accumulator in VMEM scratch that
  persists across the key-block grid dimension), fp32 accumulation, one
  [group·T, D] output tile per (batch, kv head);
* GQA comes free: the q tile for one kv head is its whole head group;
* the paged front-end (``paged_attention_pallas``) is now a deprecated
  shim over the fused ragged kernel in
  ``ops/pallas/ragged_paged_attention.py`` — one paged-attention kernel
  surface for decode, prefill, and mixed ragged batches.

The jnp paths in ``ops/decode_attention.py`` / ``ops/paged_attention.py``
remain the test oracles; ``interpret=True`` runs this kernel on CPU CI.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    _HAS_PLTPU = False

_NEG = -1e30


def _decode_kernel(lengths_ref, q_ref, k_ref, v_ref, o_ref,
                   acc_ref, m_ref, l_ref, *, scale, block_k, n_q_tokens,
                   group):
    """One (batch, kv-head, key-block) step of online-softmax attention.

    q_ref: [1, T, 1, group, D]; k_ref/v_ref: [1, 1, block_k, D]
    (cache layout [B, Hkv, S, D] — seq on sublanes, D on lanes);
    o_ref: [1, T, 1, group, D]; scratch acc/m/l persist across the
    key-block grid dim (TPU grids are sequential)."""
    b = pl.program_id(0)
    i = pl.program_id(2)
    n_blocks = pl.num_programs(2)
    length = lengths_ref[b]

    T, G = n_q_tokens, group
    rows = T * G
    d = q_ref.shape[-1]

    @pl.when(i == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(i * block_k < length)
    def _compute():
        q = q_ref[0].reshape(rows, d).astype(jnp.float32) * scale
        k = k_ref[0, 0].astype(jnp.float32)                # [BK, D]
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # [rows, BK]

        # causal-ragged mask: row r is query token t = r // group at
        # absolute position length - T + t; keys at i*block_k + col
        row_t = jax.lax.broadcasted_iota(jnp.int32, (rows, block_k), 0) // G
        kpos = i * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (rows, block_k), 1)
        qpos = length - T + row_t
        s = jnp.where(kpos <= qpos, s, _NEG)

        m_prev, l_prev = m_ref[...], l_ref[...]
        bm = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, bm)
        p = jnp.exp(s - m_new)
        p = jnp.where(m_new <= _NEG / 2, 0.0, p)
        corr = jnp.exp(m_prev - m_new)
        corr = jnp.where(m_prev <= _NEG / 2, 0.0, corr)
        m_ref[...] = m_new
        l_ref[...] = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(i == n_blocks - 1)
    def _finalize():
        l_safe = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0, :, 0] = (acc_ref[...] / l_safe).reshape(T, G, d) \
            .astype(o_ref.dtype)


def decode_attention_pallas(q, k, v, lengths, softmax_scale=None,
                            block_k=256, interpret=False):
    """Ragged decode attention over a contiguous cache.

    q: [B, T, H, D] — the last T tokens of each sequence (T=1 decode,
    T>1 chunked prefill; they are already appended to the cache);
    k/v: [B, Hkv, S_max, D]; lengths: [B] int32 valid prefix lengths.
    """
    B, T, H, D = q.shape
    S = k.shape[2]
    Hkv = k.shape[1]
    group = H // Hkv
    scale = softmax_scale if softmax_scale is not None else 1.0 / math.sqrt(D)
    block_k = min(block_k, S)
    assert S % block_k == 0, f"S_max {S} must tile by block_k {block_k}"
    n_blocks = S // block_k
    lengths = jnp.asarray(lengths, jnp.int32)

    # [B, T, H, D] -> [B, T, Hkv, group, D]: head h of kv-head hk is
    # column hk*group + g, which is exactly how H is laid out for GQA
    qg = q.reshape(B, T, Hkv, group, D)

    def k_map(b, h, i, lens):
        # never fetch blocks past the valid length: clamp to the last
        # block containing valid keys (repeat index -> DMA skipped)
        last = jnp.maximum(pl.cdiv(lens[b], block_k) - 1, 0)
        return (b, h, jnp.minimum(i, last), 0)

    grid = (B, Hkv, n_blocks)
    kernel = functools.partial(
        _decode_kernel, scale=scale, block_k=block_k, n_q_tokens=T,
        group=group)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, T, 1, group, D),
                             lambda b, h, i, lens: (b, 0, h, 0, 0)),
                pl.BlockSpec((1, 1, block_k, D), k_map),
                pl.BlockSpec((1, 1, block_k, D), k_map),
            ],
            out_specs=pl.BlockSpec((1, T, 1, group, D),
                                   lambda b, h, i, lens: (b, 0, h, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((T * group, D), jnp.float32),
                pltpu.VMEM((T * group, 1), jnp.float32),
                pltpu.VMEM((T * group, 1), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, T, Hkv, group, D), q.dtype),
        interpret=interpret,
    )(lengths, qg, k, v)
    return out.reshape(B, T, H, D)


def paged_attention_pallas(q, k_pages, v_pages, block_tables, lengths,
                           softmax_scale=None, interpret=False):
    """DEPRECATED: delegate to the fused ragged kernel.

    The decode-only paged kernel that used to live here is subsumed by
    ``ops/pallas/ragged_paged_attention.py`` (one kernel surface for
    decode, prefill, and mixed ragged batches).  This shim keeps the old
    signature — q: [B, T, H, D]; k_pages/v_pages: [P, Hkv, page_size, D];
    block_tables: [B, max_pages] int32; lengths: [B] int32 — and routes
    through the rectangular front-end, which for T=1 does identical work
    (one q row per sequence, pages resolved through the block table).
    New callers should use ``paged_decode_attention`` in
    ``ops/paged_attention.py`` or the ragged entry points directly.
    """
    from deepspeed_tpu.ops.pallas.ragged_paged_attention import \
        ragged_paged_attention_rect
    return ragged_paged_attention_rect(q, k_pages, v_pages, block_tables,
                                       lengths, softmax_scale=softmax_scale,
                                       interpret=interpret)
