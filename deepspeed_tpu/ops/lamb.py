"""Fused LAMB.

Parity: reference ``csrc/lamb/fused_lamb_cuda.cu`` (``lamb`` — fused LAMB with
trust-ratio reductions).  The trust ratio needs per-tensor norms, so the op
takes a segment map (tensor boundaries within the flat buffer) and computes
segment norms with ``jax.ops.segment_sum`` — the XLA equivalent of the CUDA
kernel's two-pass norm reduction.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp


class LambState(NamedTuple):
    m: jnp.ndarray
    v: jnp.ndarray
    step: jnp.ndarray


def init_state(params_flat):
    return LambState(m=jnp.zeros_like(params_flat, jnp.float32),
                     v=jnp.zeros_like(params_flat, jnp.float32),
                     step=jnp.zeros((), jnp.int32))


def reference_impl(params, grads, state: LambState, segment_ids=None,
                   num_segments=1, lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-6,
                   weight_decay=0.0, max_coeff=10.0, min_coeff=0.01):
    """Fused LAMB on a flat buffer; ``segment_ids`` marks per-tensor segments
    for trust-ratio computation (all-one segment if None)."""
    g = grads.astype(jnp.float32)
    p = params.astype(jnp.float32)
    step = state.step + 1
    m = beta1 * state.m + (1.0 - beta1) * g
    v = beta2 * state.v + (1.0 - beta2) * jnp.square(g)
    sf = jnp.float32(step)
    m_hat = m / (1.0 - beta1 ** sf)
    v_hat = v / (1.0 - beta2 ** sf)
    update = m_hat / (jnp.sqrt(v_hat) + eps) + weight_decay * p

    if segment_ids is None:
        segment_ids = jnp.zeros_like(p, dtype=jnp.int32)
        num_segments = 1
    w_sq = jax.ops.segment_sum(jnp.square(p), segment_ids, num_segments)
    u_sq = jax.ops.segment_sum(jnp.square(update), segment_ids, num_segments)
    w_norm = jnp.sqrt(w_sq)
    u_norm = jnp.sqrt(u_sq)
    ratio = jnp.where((w_norm > 0) & (u_norm > 0),
                      jnp.clip(w_norm / u_norm, min_coeff, max_coeff), 1.0)
    trust = ratio[segment_ids]
    new_p = p - lr * trust * update
    return new_p.astype(params.dtype), LambState(m=m, v=v, step=step)


fused_lamb = reference_impl
