"""Paged (block-table) KV cache + ragged decode attention.

Parity role: reference decode serving is a contiguous per-request KV
workspace (``inference_context.h`` KV-cache workspace management).  The
TPU-native upgrade is a *paged* cache — fixed-size pages shared across
sequences through per-sequence block tables (vLLM/ragged-paged-attention
style, cf. PAPERS.md) — which removes max-length over-allocation and lets
sequences of very different lengths batch together.

Layout:
  k_pages/v_pages: [num_pages, Hkv, page_size, D] — the physical pool
  (seq on sublanes, D on lanes — the layout Mosaic tiles natively)
  block_tables:    [B, max_pages_per_seq] int32 — page ids per sequence
  lengths:         [B] int32 — tokens currently stored per sequence

Two compute paths behind one API: the Pallas kernel
(``ops/pallas/decode_attention.py:paged_attention_pallas`` — the key-block
index map reads the block table so only each sequence's own pages are
DMA'd) on TPU, and this module's jnp gather + masked softmax as the
oracle/fallback.  Page allocation is host-side (``PagedAllocator``)
because it is control flow, not compute.
"""

import math
from typing import List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class PagedKVCache(NamedTuple):
    k_pages: jnp.ndarray   # [P, Hkv, page, D]
    v_pages: jnp.ndarray


def init_paged_cache(num_pages, page_size, n_kv_heads, head_dim,
                     dtype=jnp.bfloat16) -> PagedKVCache:
    shape = (num_pages, n_kv_heads, page_size, head_dim)
    return PagedKVCache(k_pages=jnp.zeros(shape, dtype),
                        v_pages=jnp.zeros(shape, dtype))


def append_paged(cache: PagedKVCache, block_tables, lengths, k_new, v_new
                 ) -> Tuple[PagedKVCache, jnp.ndarray]:
    """Append ONE token per sequence (decode step).

    k_new/v_new: [B, 1, Hkv, D].  Returns (cache, new lengths).  The pages
    written must already be mapped in ``block_tables`` (allocator's job).
    """
    B = k_new.shape[0]
    page_size = cache.k_pages.shape[2]
    page_idx = jnp.take_along_axis(
        block_tables, (lengths // page_size)[:, None], axis=1)[:, 0]
    offset = lengths % page_size
    k = cache.k_pages.at[page_idx, :, offset].set(
        k_new[:, 0].astype(cache.k_pages.dtype))
    v = cache.v_pages.at[page_idx, :, offset].set(
        v_new[:, 0].astype(cache.v_pages.dtype))
    return PagedKVCache(k_pages=k, v_pages=v), lengths + 1


def prefill_paged(cache: PagedKVCache, block_tables, lengths, k_new, v_new
                  ) -> Tuple[PagedKVCache, jnp.ndarray]:
    """Write a whole prompt [B, T, Hkv, D] starting at ``lengths`` (which is
    typically zero)."""
    B, T = k_new.shape[:2]
    page_size = cache.k_pages.shape[2]
    pos = lengths[:, None] + jnp.arange(T)[None, :]          # [B, T]
    page_idx = jnp.take_along_axis(block_tables, pos // page_size, axis=1)
    offset = pos % page_size
    # advanced indices (page_idx, offset) around the ':' slice put their
    # broadcast dims first: the set value is [B, T, Hkv, D] = k_new's layout
    k = cache.k_pages.at[page_idx, :, offset].set(
        k_new.astype(cache.k_pages.dtype))
    v = cache.v_pages.at[page_idx, :, offset].set(
        v_new.astype(cache.v_pages.dtype))
    return PagedKVCache(k_pages=k, v_pages=v), lengths + T


def paged_decode_attention(q, cache: PagedKVCache, block_tables, lengths,
                           softmax_scale: Optional[float] = None,
                           impl: Optional[str] = None,
                           interpret: bool = False,
                           logit_softcap: Optional[float] = None):
    """q: [B, T, H, D] — the last T tokens of each sequence (T=1 decode).

    ``impl``: None (auto: Pallas kernel on TPU, jnp elsewhere), "pallas",
    or "jnp".  The jnp path gathers each sequence's pages into its logical
    view and runs masked attention over the valid ragged prefix."""
    from deepspeed_tpu.ops.decode_attention import use_pallas
    if use_pallas(impl) and not logit_softcap:
        from deepspeed_tpu.ops.pallas.decode_attention import \
            paged_attention_pallas
        return paged_attention_pallas(q, cache.k_pages, cache.v_pages,
                                      block_tables, lengths,
                                      softmax_scale=softmax_scale,
                                      interpret=interpret)
    B, T, H, D = q.shape
    Hkv = cache.k_pages.shape[1]
    page_size = cache.k_pages.shape[2]
    max_pages = block_tables.shape[1]
    S = max_pages * page_size

    # [B, max_pages, Hkv, page, D] → [B, Hkv, S, D]
    k = jnp.swapaxes(cache.k_pages[block_tables], 1, 2) \
        .reshape(B, Hkv, S, D)
    v = jnp.swapaxes(cache.v_pages[block_tables], 1, 2) \
        .reshape(B, Hkv, S, D)
    if Hkv != H:
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    scale = softmax_scale if softmax_scale is not None else 1.0 / math.sqrt(D)
    logits = jnp.einsum("bqhd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if logit_softcap:
        logits = logit_softcap * jnp.tanh(logits / logit_softcap)
    kpos = jnp.arange(S)[None, None, :]                       # [1, 1, S]
    qpos = (lengths[:, None] - T + jnp.arange(T)[None, :])[..., None]
    mask = kpos <= qpos                                       # [B, T, S]
    logits = jnp.where(mask[:, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bqhd", probs.astype(v.dtype), v)
    return out.astype(q.dtype)   # impl-independent output dtype


class PageAllocationError(RuntimeError):
    """Typed allocator failure (pool exhausted, per-sequence cap exceeded,
    or an injected ``page_alloc`` fault): callers turn it into a structured
    rejection / retry instead of an engine-killing assert."""


class PagedAllocator:
    """Host-side page bookkeeping (the control-flow half of vLLM's block
    manager): per-sequence page lists over a fixed pool, with free-list
    reuse."""

    def __init__(self, num_pages: int, page_size: int,
                 max_pages_per_seq: int, reserve_scratch: bool = False,
                 injector=None):
        """``reserve_scratch``: keep page 0 out of the pool — serving
        engines point INACTIVE batch slots' tables at page 0 so their
        dummy-token writes land in a sacrificial page.  ``injector``: a
        ``runtime.resilience.FaultInjector`` consulted at the ``page_alloc``
        site before any page leaves the free list (so an injected fault
        never half-allocates)."""
        self.num_pages = num_pages
        self.page_size = page_size
        self.max_pages_per_seq = max_pages_per_seq
        self.free: List[int] = list(range(1 if reserve_scratch else 0,
                                          num_pages))
        self.seq_pages = {}
        self.injector = injector

    def can_allocate(self, n_pages: int) -> bool:
        return len(self.free) >= n_pages

    @property
    def free_page_count(self) -> int:
        return len(self.free)

    def allocate(self, seq_id, n_tokens: int) -> List[int]:
        need = -(-n_tokens // self.page_size)
        if need > self.max_pages_per_seq:
            raise PageAllocationError(
                f"{n_tokens} tokens exceed max_pages_per_seq "
                f"({self.max_pages_per_seq})")
        if not self.can_allocate(need):
            raise PageAllocationError(
                f"out of KV pages: need {need}, free {len(self.free)}")
        if self.injector is not None:
            try:
                self.injector.check("page_alloc")
            except Exception as e:
                raise PageAllocationError(
                    f"injected page_alloc fault: {e}") from e
        pages = [self.free.pop() for _ in range(need)]
        self.seq_pages[seq_id] = pages
        return pages

    def extend(self, seq_id, total_tokens: int) -> List[int]:
        """Ensure ``seq_id`` has pages for ``total_tokens``; allocates new
        pages as it crosses page boundaries."""
        pages = self.seq_pages[seq_id]
        need = -(-total_tokens // self.page_size)
        if need > self.max_pages_per_seq:
            raise PageAllocationError(
                f"{total_tokens} tokens exceed max_pages_per_seq "
                f"({self.max_pages_per_seq})")
        if len(pages) < need:
            if not self.can_allocate(need - len(pages)):
                raise PageAllocationError(
                    f"out of KV pages: need {need - len(pages)} more, "
                    f"free {len(self.free)}")
            if self.injector is not None:
                try:
                    self.injector.check("page_alloc")
                except Exception as e:
                    raise PageAllocationError(
                        f"injected page_alloc fault: {e}") from e
            while len(pages) < need:
                pages.append(self.free.pop())
        return pages

    def shrink(self, seq_id, total_tokens: int):
        """Release pages beyond what ``total_tokens`` needs (a bucketed
        prefill over-allocates to the padded length, then trims)."""
        pages = self.seq_pages[seq_id]
        need = max(1, -(-total_tokens // self.page_size))
        while len(pages) > need:
            self.free.append(pages.pop())

    def free_sequence(self, seq_id):
        self.free.extend(self.seq_pages.pop(seq_id, []))

    def block_table(self, seq_ids) -> np.ndarray:
        """[B, max_pages_per_seq] table (0-padded) for the given batch."""
        out = np.zeros((len(seq_ids), self.max_pages_per_seq), np.int32)
        for b, sid in enumerate(seq_ids):
            pages = self.seq_pages[sid]
            out[b, :len(pages)] = pages
        return out
